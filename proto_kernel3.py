"""True device-time kernel measurement: K calls inside one jitted program."""
import functools, sys, time
import jax, jax.numpy as jnp, numpy as np
from proto_kernel2 import hist_var
from h2o3_tpu.ops.hist_pallas import hist_pallas

K_CALLS = 20


def timeit(label, make_fn, *args):
    f = jax.jit(make_fn)
    r = f(*args); jax.block_until_ready(r)
    t0 = time.time()
    r = f(*args)
    jax.block_until_ready(r)
    dt = time.time() - t0
    print(f"{label}: {dt/K_CALLS*1000:7.2f} ms/call  ({dt*1000:.0f} ms total)",
          file=sys.stderr)
    return dt / K_CALLS


def main():
    rng = np.random.default_rng(0)
    ROWS = 122 * 8192  # 999424
    F = 32
    codes_t = jnp.asarray(rng.integers(0, 254, size=(F, ROWS), dtype=np.int32))
    ghw = jnp.asarray(rng.normal(size=(3, ROWS)).astype(np.float32))
    N = 8
    nid0 = jnp.asarray(rng.integers(0, N, size=(ROWS,), dtype=np.int32))

    def many(kernel_fn):
        def prog(ct, ni, gh):
            acc = 0.0
            for i in range(K_CALLS):
                nid_i = ((ni + i) % N)[None, :]
                acc = acc + jnp.sum(kernel_fn(ct, nid_i, gh))
            return acc
        return prog

    timeit("v1 full    t2048 f8  N8", many(lambda ct, ni, gh: hist_pallas(ct, ni, gh, N, 255)), codes_t, nid0, ghw)
    for variant in ("full", "nocompare", "nomatmul"):
        timeit(f"v2 {variant:9s} t2048 f8  N8",
               many(lambda ct, ni, gh, v=variant: hist_var(ct, ni, gh, N, 255, v)),
               codes_t, nid0, ghw)
    for tile, fblk in [(2048, 32), (4096, 16), (8192, 8), (8192, 32)]:
        timeit(f"v2 full      t{tile} f{fblk} N8",
               many(lambda ct, ni, gh, t=tile, fb=fblk: hist_var(ct, ni, gh, N, 255, "full", t, fb)),
               codes_t, nid0, ghw)
    for N2 in (1, 16):
        timeit(f"v2 full      t2048 f8  N{N2}",
               many(lambda ct, ni, gh, n=N2: hist_var(ct, ni, gh, n, 255)),
               codes_t, nid0, ghw)


if __name__ == "__main__":
    main()
