"""Root conftest: force an 8-device virtual CPU mesh for the test suite,
and gate the heavy tier behind ``-m`` markers so the default run stays
under the 5-minute bar (VERDICT r4 task 8): tests marked ``slow``
(multi-minute AutoML/sharded-parity/client-explain runs) are skipped
unless ``--runslow`` (or ``-m slow``) is given — the driver's full pass
runs them separately.

Mirrors the reference's "fake multi-node" strategy (4 JVMs on loopback,
see SURVEY.md §4.1 / multiNodeUtils.sh) with JAX's
--xla_force_host_platform_device_count. The axon sitecustomize pins
JAX_PLATFORMS=axon (one real TPU chip); tests override to CPU so sharding
semantics are exercised on 8 virtual devices.

Set H2O3_TPU_TEST_PLATFORM=tpu to run the suite on the real chip instead.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

def _xla_flags_supported(flags: str) -> bool:
    """XLA abort()s the whole process on an unknown flag in XLA_FLAGS, so
    optional flags must be probed in a throwaway subprocess first. jaxlib
    builds differ across driver hosts (the collective-timeout flags below
    exist on some but not this image's 0.4.37) — cache the verdict per
    jaxlib version so the ~5s probe runs once per environment."""
    import hashlib
    import subprocess
    import tempfile
    try:
        import jaxlib
        ver = getattr(jaxlib, "__version__", "?")
    except ImportError:
        ver = "?"
    key = hashlib.sha1(f"{ver}|{flags}".encode()).hexdigest()[:12]
    marker = os.path.join(tempfile.gettempdir(), f"h2o3_xlaflags_{key}")
    try:
        with open(marker) as f:
            return f.read().strip() == "1"
    except OSError:
        pass
    env = dict(os.environ, XLA_FLAGS=flags, JAX_PLATFORMS="cpu")
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            env=env, capture_output=True, timeout=300)
        ok = r.returncode == 0
    except (OSError, subprocess.SubprocessError):
        # transient (timeout under load, spawn failure): do NOT cache a
        # permanent negative — skip the flags this run, re-probe next
        return False
    try:
        with open(marker, "w") as f:
            f.write("1" if ok else "0")
    except OSError:
        pass
    return ok


if os.environ.get("H2O3_TPU_TEST_PLATFORM", "cpu") == "cpu":
    _flags = (os.environ.get("XLA_FLAGS", "")
              + " --xla_force_host_platform_device_count=8")
    # the 8-participant collective rendezvous can stall >40s on this
    # 1-core host under load (all participants share one thread
    # pool); XLA's default 40s terminate timeout then abort()s the
    # whole process ("only 7 of them arrived on time") — observed
    # intermittently on the wide sharded tests. The stall resolves;
    # give it room instead of dying. The flags only exist on newer
    # jaxlib builds — probe before adding (unknown flags are fatal).
    _timeout_flags = (
        " --xla_cpu_collective_call_warn_stuck_timeout_seconds=120"
        " --xla_cpu_collective_call_terminate_timeout_seconds=900")
    if _xla_flags_supported(_flags + _timeout_flags):
        _flags += _timeout_flags
    os.environ["XLA_FLAGS"] = _flags
    import jax

    jax.config.update("jax_platforms", "cpu")
    # persistent compilation cache: the suite's wall time is dominated by
    # re-compiling the same sharded train steps (a cold full run spends
    # ~80% of its time in XLA); cached executables make repeat runs and
    # re-runs of single files start warm (water/MRTask has no compile
    # step to cache — this cost is TPU-stack-specific, so the fix is too)
    cache_dir = os.environ.get("H2O3_TEST_JAX_CACHE",
                               "/tmp/h2o3_jax_cache")
    # key the cache by host-CPU fingerprint: XLA:CPU AOT results encode
    # machine features (prefer-no-scatter etc.), and loading an entry
    # compiled on a different host warns "could lead to SIGILL" — which
    # manifested as intermittent worker abort()s when this repo's cache
    # outlived a driver-host change
    try:
        import hashlib
        with open("/proc/cpuinfo") as _f:
            flags = next((ln for ln in _f if ln.startswith("flags")), "")
        cache_dir += "_" + hashlib.sha1(flags.encode()).hexdigest()[:8]
    except OSError:
        pass
    # per-xdist-worker cache dir: concurrent processes racing on the
    # same cache files have produced aborted workers ("node down")
    worker = os.environ.get("PYTEST_XDIST_WORKER")
    if worker:
        cache_dir = f"{cache_dir}_{worker}"
    # single-writer lock: two concurrent pytest INVOCATIONS sharing the
    # dir have produced torn cache entries that abort() every later run
    # at deserialize time (observed as SIGABRT inside a jnp.where
    # compile, reproducible until the dir was wiped). The second
    # concurrent run gets a private cold dir instead.
    try:
        import atexit
        import fcntl
        os.makedirs(cache_dir, exist_ok=True)
        _cache_lock_fd = open(os.path.join(cache_dir, ".writer_lock"),
                              "w")
        try:
            fcntl.flock(_cache_lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            import shutil
            cache_dir = f"{cache_dir}_p{os.getpid()}"
            atexit.register(shutil.rmtree, cache_dir,
                            ignore_errors=True)
    except OSError:
        pass
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # concurrent XLA dispatch from CV/grid build threads can abort() the
    # oversubscribed CPU backend under xdist ("gw node down"); pin build
    # pools to one thread for the suite — the dedicated concurrency
    # tests (tests/test_parallel_build.py) raise the cap back.
    os.environ.setdefault("H2O3_MAX_BUILD_THREADS", "1")


def pytest_addoption(parser):
    parser.addoption("--runslow", action="store_true", default=False,
                     help="run tests marked slow (the heavy tier)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-minute tests (AutoML plans, sharded "
        "parity, client explain) — skipped unless --runslow")


def pytest_collection_modifyitems(config, items):
    import pytest as _pytest
    if config.getoption("--runslow") or \
            "slow" in (config.getoption("markexpr", "") or ""):
        return
    # an explicitly named test (node id with '::') means the developer
    # asked for exactly that test — don't skip-trap them into a
    # misleading '1 skipped'
    if any("::" in a for a in config.args):
        return
    skip = _pytest.mark.skip(reason="slow tier: pass --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
