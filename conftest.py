"""Root conftest: force an 8-device virtual CPU mesh for the test suite.

Mirrors the reference's "fake multi-node" strategy (4 JVMs on loopback,
see SURVEY.md §4.1 / multiNodeUtils.sh) with JAX's
--xla_force_host_platform_device_count. The axon sitecustomize pins
JAX_PLATFORMS=axon (one real TPU chip); tests override to CPU so sharding
semantics are exercised on 8 virtual devices.

Set H2O3_TPU_TEST_PLATFORM=tpu to run the suite on the real chip instead.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

if os.environ.get("H2O3_TPU_TEST_PLATFORM", "cpu") == "cpu":
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    # persistent compilation cache: the suite's wall time is dominated by
    # re-compiling the same sharded train steps (a cold full run spends
    # ~80% of its time in XLA); cached executables make repeat runs and
    # re-runs of single files start warm (water/MRTask has no compile
    # step to cache — this cost is TPU-stack-specific, so the fix is too)
    cache_dir = os.environ.get("H2O3_TEST_JAX_CACHE",
                               "/tmp/h2o3_jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
