"""Isolate the pallas hist kernel bottleneck: compare / matmul / grid overhead."""
import functools, sys, time
import jax, jax.numpy as jnp, numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def make_kernel(variant, n_nodes, n_bins_p, tile, n_row_tiles, mxu_dtype, fblk):
    def kern(codes_ref, nid_ref, ghw_ref, out_ref, acc_ref):
        r = pl.program_id(1)

        @pl.when(r == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        nid = nid_ref[0, :]
        nodes_t = jax.lax.broadcasted_iota(jnp.int32, (n_nodes, tile), 0)
        node_oh_t = (nodes_t == nid[None, :]).astype(mxu_dtype)
        R_t = jnp.concatenate(
            [node_oh_t * ghw_ref[k, :][None, :].astype(mxu_dtype)
             for k in range(3)], axis=0)                       # [3N, tile]
        bins = jax.lax.broadcasted_iota(jnp.int32, (tile, n_bins_p), 1)
        for fi in range(fblk):
            c = codes_ref[fi, :]
            if variant == "nocompare":
                bin_oh = (bins + c[:, None]).astype(mxu_dtype)
            else:
                bin_oh = (bins == c[:, None]).astype(mxu_dtype)
            if variant == "nomatmul":
                acc_ref[fi, 0, :] += jnp.sum(bin_oh, axis=0)
            else:
                acc_ref[fi] += jax.lax.dot_general(
                    R_t, bin_oh, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)

        @pl.when(r == n_row_tiles - 1)
        def _flush():
            out_ref[...] = acc_ref[...]
    return kern


def hist_var(codes_t, nid, ghw, n_nodes, n_bins1, variant="full",
             tile=2048, fblk=8, mxu_dtype=jnp.bfloat16):
    F, rows = codes_t.shape
    assert rows % tile == 0 and F % fblk == 0, (rows, tile, F, fblk)
    n_row_tiles = rows // tile
    n_bins_p = int(np.ceil(n_bins1 / 128) * 128)
    kern = make_kernel(variant, n_nodes, n_bins_p, tile, n_row_tiles,
                       mxu_dtype, fblk)
    out = pl.pallas_call(
        kern,
        grid=(F // fblk, n_row_tiles),
        in_specs=[
            pl.BlockSpec((fblk, tile), lambda f, r: (f, r)),
            pl.BlockSpec((1, tile), lambda f, r: (0, r)),
            pl.BlockSpec((3, tile), lambda f, r: (0, r)),
        ],
        out_specs=pl.BlockSpec((fblk, 3 * n_nodes, n_bins_p),
                               lambda f, r: (f, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, 3 * n_nodes, n_bins_p), jnp.float32),
        scratch_shapes=[pltpu.VMEM((fblk, 3 * n_nodes, n_bins_p), jnp.float32)],
    )(codes_t, nid, ghw)
    return out


def bench(label, fn, *args):
    f = jax.jit(fn)
    r = f(*args); jax.block_until_ready(r)
    t0 = time.time()
    for _ in range(5):
        r = f(*args)
    jax.block_until_ready(r)
    print(f"{label}: {(time.time()-t0)/5*1000:7.2f} ms", file=sys.stderr)


def main():
    rng = np.random.default_rng(0)
    ROWS = 61 * 16384  # 999424, divisible by 2048/4096/8192/16384
    F = 32
    codes_t = jnp.asarray(rng.integers(0, 254, size=(F, ROWS), dtype=np.int32))
    ghw = jnp.asarray(rng.normal(size=(3, ROWS)).astype(np.float32))
    N = 8
    nid = jnp.asarray(rng.integers(0, N, size=(1, ROWS), dtype=np.int32))

    for variant in ("full", "nocompare", "nomatmul"):
        bench(f"{variant:10s} t2048 f8 ",
              lambda ct, ni, gh, v=variant: hist_var(ct, ni, gh, N, 255, v), codes_t, nid, ghw)
    for tile, fblk in [(2048, 32), (4096, 8), (8192, 8), (8192, 32)]:
        bench(f"full       t{tile} f{fblk}",
              lambda ct, ni, gh, t=tile, fb=fblk: hist_var(ct, ni, gh, N, 255, "full", t, fb),
              codes_t, nid, ghw)


if __name__ == "__main__":
    main()
