"""h2o-py fleet scoring client: key affinity + zero-hop dispatch.

The client-facing surface of the router tier (ISSUE 20). An
:class:`H2OFleetClient` fetches the fleet's consistent-hash ring from
``GET /3/Fleet/ring``, hashes routing keys client-side with the SAME
blake2b scheme the routers use, and POSTs scoring requests straight to
the home replica's ``/3/Predictions`` surface — the proxy hop is
skipped entirely on the steady-state path. On epoch mismatch (a
response's ``X-H2O3-Fleet-Epoch`` header disagrees with the pinned
ring) or connect failure, the request falls back to any configured
router and the ring is refreshed.

Usage::

    from h2o_bindings.fleet_client import H2OFleetClient
    c = H2OFleetClient(["http://router-a:54321", "http://router-b:54321"])
    preds = c.predict_rows("my_gbm", [{"x1": 0.3, "x2": 1.0}])
    cols  = c.predict_rows("my_gbm", rows, fmt="columnar")
    c.zero_hop_ratio()   # fraction of requests that skipped the proxy

``lane`` tags the request's deadline class (``interactive`` > ``bulk``
> ``background``; ``X-H2O3-Lane`` on the wire) — bulk scoring floods
are shed at the front door instead of riding the interactive queue.
"""
from h2o3_tpu.fleet.affinity import AffinityClient as _AffinityClient
from h2o3_tpu.fleet.affinity import RingView  # noqa: F401 — re-export

__all__ = ["H2OFleetClient", "RingView"]


class H2OFleetClient(_AffinityClient):
    """The h2o-py spelling of the affinity client (see module doc).
    ``predict_rows(model, rows, key=..., fmt=..., lane=...)`` returns
    the replica's response body: the ``predictions`` list for
    ``fmt="rows"``, the columns dict for ``fmt="columnar"``, the raw
    NDJSON text for ``fmt="stream"``."""

    def predict_rows(self, model, rows, *, key=None, timeout_ms=None,
                     fmt="rows", lane=None):
        out = super().predict_rows(model, rows, key=key,
                                   timeout_ms=timeout_ms, fmt=fmt,
                                   lane=lane)
        if isinstance(out, dict):
            if fmt == "rows" and "predictions" in out:
                return out["predictions"]
            if fmt == "columnar" and "columns" in out:
                return out["columns"]
        return out
