"""Prototype: fused route+bin+histogram pallas kernel with per-node
adaptive uniform bins (H2O DHistogram UniformAdaptive semantics).

Per level d, one kernel call over row tiles:
  1. route: nid' = child(nid) using the PREVIOUS level's split tables
     (feat/thr/na_left/can per node) — table lookups via one-hot matmul,
     split-feature select via compare-accumulate over F lanes;
  2. bin: b = isnan(x) ? W-1 : clip((x - lo[n,f]) * inv[n,f], 0, W-2)
     with per-(node, feature) ranges — lookups again via one-hot matmul;
  3. hist: acc[(k,n), (f,b)] += ghw[k] via node-onehot × bin-onehot MXU
     contraction.

Outputs: histogram triple + updated nid. No precomputed codes, no
transposed copy, no per-level XLA routing pass.
"""
import functools, sys, time
import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

W = 64          # per-feature histogram lanes: bins 0..W-2 real, W-1 = NA
TILE = 2048


def _kernel(x_ref, nid_ref, ghw_ref, feat_ref, thr_ref, nal_ref, can_ref,
            lo_ref, inv_ref, nid_out, hist_out, acc_ref, *,
            n_prev: int, n_nodes: int, F: int, tile: int, n_row_tiles: int,
            level_base: int, mxu_dtype=jnp.bfloat16):
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                                   # [tile, F] f32
    nid = nid_ref[0, :]                              # [tile] i32 (global ids)
    # ---- route through the previous level's splits -------------------
    prev_base = level_base - n_prev if n_prev > 0 else 0
    if n_prev > 0:
        lid_p = nid - prev_base                      # local id in prev level
        onp = (jax.lax.broadcasted_iota(jnp.int32, (n_prev, tile), 0)
               == lid_p[None, :]).astype(jnp.float32)   # [n_prev, tile]
        # per-row split data via one-hot matmul (exact for ints < 2^24)
        def lut(tbl_ref):
            # HIGHEST precision: a bf16-rounded threshold flips routing for
            # rows near the split boundary
            t = tbl_ref[0, :n_prev].astype(jnp.float32)  # [n_prev]
            return jax.lax.dot_general(
                t[None, :], onp, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)[0]  # [tile]
        f_r = lut(feat_ref)                          # split feature (f32)
        t_r = lut(thr_ref)                           # raw threshold
        nl_r = lut(nal_ref)                          # NA-left flag
        cn_r = lut(can_ref)                          # is-split flag
        # x[r, feat_r]: compare-accumulate over F (no dynamic gather);
        # f_r is an exact int-valued float (one-hot matmul of ints < 2^24)
        fi = jax.lax.broadcasted_iota(jnp.int32, (tile, F), 1)
        f_i = f_r.astype(jnp.int32)
        xsel = jnp.sum(jnp.where(fi == f_i[:, None], x, 0.0), axis=1)
        # all-float select (bool-branch select_n lowers to an i8→i1
        # truncation Mosaic rejects)
        is_na = jnp.isnan(xsel)
        gr_f = jnp.where(is_na, 1.0 - nl_r,
                         (xsel >= t_r).astype(jnp.float32))
        in_prev = (lid_p >= 0) & (lid_p < n_prev)
        child = 2 * nid + 1 + gr_f.astype(jnp.int32)
        nid = jnp.where(in_prev & (cn_r > 0.5), child, nid)
    nid_out[0, :] = nid
    # ---- per-(node, feature) ranges ----------------------------------
    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidc = jnp.where(in_lvl, lid, 0)
    onh = (jax.lax.broadcasted_iota(jnp.int32, (n_nodes, tile), 0)
           == lidc[None, :])
    onh_f = onh.astype(jnp.float32) * in_lvl.astype(jnp.float32)[None, :]
    # lo/inv per row: [tile, F] = onh^T @ lo (contraction over n; exact f32
    # so bin boundaries match the host/split-side threshold arithmetic)
    lo_r = jax.lax.dot_general(onh_f, lo_ref[...], (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=jax.lax.Precision.HIGHEST)
    inv_r = jax.lax.dot_general(onh_f, inv_ref[...], (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=jax.lax.Precision.HIGHEST)
    bin_f = jnp.clip((x - lo_r) * inv_r, 0.0, float(W - 2))
    bin_i = jnp.where(jnp.isnan(x), W - 1, bin_f.astype(jnp.int32))  # [tile,F]
    # ---- one-hot over W lanes per feature, contract on MXU -----------
    b_all = jnp.concatenate(
        [jnp.broadcast_to(bin_i[:, fi:fi + 1], (tile, W)) for fi in range(F)],
        axis=1)                                               # [tile, F*W]
    lane = jax.lax.broadcasted_iota(jnp.int32, (tile, F * W), 1)
    oh = ((lane % W) == b_all).astype(mxu_dtype)
    ghw = ghw_ref[...]                        # [3, tile]
    left = jnp.concatenate(
        [onh_f.astype(mxu_dtype) * ghw[k, :][None, :].astype(mxu_dtype)
         for k in range(3)], axis=0)          # [3N, tile]
    acc_ref[...] += jax.lax.dot_general(
        left, oh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=(jax.lax.Precision.HIGHEST if mxu_dtype == jnp.float32
                   else jax.lax.Precision.DEFAULT))   # [3N, F*W]

    @pl.when(r == n_row_tiles - 1)
    def _flush():
        hist_out[...] = acc_ref[...]


def level_kernel(x, nid, ghw, tables_prev, lo, inv, n_prev, n_nodes,
                 level_base, tile=TILE, interpret=False,
                 mxu_dtype=jnp.bfloat16):
    """x [rows, F] f32 (NaN=NA), nid [rows] i32, ghw [3, rows] f32,
    tables_prev = (feat, thr, nal, can) each [n_prev] f32/i32,
    lo/inv [n_nodes, F] f32 → (nid', hist [3N, F*W])."""
    rows, F = x.shape
    assert rows % tile == 0
    n_row_tiles = rows // tile
    feat, thr, nal, can = tables_prev
    np1 = max(n_prev, 1)
    kern = functools.partial(_kernel, n_prev=n_prev, n_nodes=n_nodes, F=F,
                             tile=tile, n_row_tiles=n_row_tiles,
                             level_base=level_base, mxu_dtype=mxu_dtype)
    nid2, hist = pl.pallas_call(
        kern,
        grid=(n_row_tiles,),
        in_specs=[
            pl.BlockSpec((tile, F), lambda r: (r, 0)),       # x
            pl.BlockSpec((1, tile), lambda r: (0, r)),       # nid
            pl.BlockSpec((3, tile), lambda r: (0, r)),       # ghw
            pl.BlockSpec((1, np1), lambda r: (0, 0)),        # feat
            pl.BlockSpec((1, np1), lambda r: (0, 0)),        # thr
            pl.BlockSpec((1, np1), lambda r: (0, 0)),        # nal
            pl.BlockSpec((1, np1), lambda r: (0, 0)),        # can
            pl.BlockSpec((n_nodes, F), lambda r: (0, 0)),    # lo
            pl.BlockSpec((n_nodes, F), lambda r: (0, 0)),    # inv
        ],
        out_specs=[
            pl.BlockSpec((1, tile), lambda r: (0, r)),               # nid'
            pl.BlockSpec((3 * n_nodes, F * W), lambda r: (0, 0)),    # hist
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows), jnp.int32),
            jax.ShapeDtypeStruct((3 * n_nodes, F * W), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((3 * n_nodes, F * W), jnp.float32)],
        interpret=interpret,
    )(x, nid[None, :], ghw, feat[None, :], thr[None, :], nal[None, :],
      can[None, :], lo, inv)
    return nid2[0], hist.reshape(3, n_nodes, F, W)


def ref_level(x, nid, ghw, tables_prev, lo, inv, n_prev, n_nodes, level_base):
    """Numpy reference of the same level."""
    x = np.asarray(x); nid = np.asarray(nid).copy(); ghw = np.asarray(ghw)
    feat, thr, nal, can = [np.asarray(t) for t in tables_prev]
    rows, F = x.shape
    if n_prev > 0:
        prev_base = level_base - n_prev
        lid_p = nid - prev_base
        inp = (lid_p >= 0) & (lid_p < n_prev)
        for r in range(rows):
            if not inp[r] or can[lid_p[r]] < 0.5:
                continue
            f = int(feat[lid_p[r]])
            xv = x[r, f]
            if np.isnan(xv):
                gr = nal[lid_p[r]] < 0.5
            else:
                gr = xv >= thr[lid_p[r]]
            nid[r] = 2 * nid[r] + 1 + int(gr)
    hist = np.zeros((3, n_nodes, F, W), np.float32)
    lid = nid - level_base
    inl = (lid >= 0) & (lid < n_nodes)
    for r in range(rows):
        if not inl[r]:
            continue
        n = lid[r]
        for f in range(F):
            xv = x[r, f]
            if np.isnan(xv):
                b = W - 1
            else:
                b = int(np.clip((xv - lo[n, f]) * inv[n, f], 0, W - 2))
            hist[:, n, f, b] += ghw[:, r]
    return nid, hist


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    mode = sys.argv[2] if len(sys.argv) > 2 else "check"
    F = 32
    rng = np.random.default_rng(0)
    x = rng.normal(size=(rows, F)).astype(np.float32)
    x[rng.random((rows, F)) < 0.05] = np.nan
    ghw = rng.normal(size=(3, rows)).astype(np.float32)
    if mode == "check":
        # level 2: n_prev=2, n_nodes=4, with some dead rows
        n_prev, n_nodes, base = 2, 4, 3
        nid = rng.integers(0, 3, rows).astype(np.int32)  # ids 0..2 (some dead)
        nid[nid == 0] = 1
        feat = rng.integers(0, F, 2).astype(np.int32)
        thr = rng.normal(size=2).astype(np.float32)
        nal = (rng.random(2) < 0.5).astype(np.float32)
        can = np.array([1.0, 1.0], np.float32)
        lo = (rng.normal(size=(n_nodes, F)) * 0.1 - 1.0).astype(np.float32)
        inv = np.full((n_nodes, F), (W - 2) / 2.0, np.float32)
        tabs = (jnp.asarray(feat, jnp.float32), jnp.asarray(thr),
                jnp.asarray(nal), jnp.asarray(can))
        nid2, hist = level_kernel(jnp.asarray(x), jnp.asarray(nid),
                                  jnp.asarray(ghw), tabs, jnp.asarray(lo),
                                  jnp.asarray(inv), n_prev, n_nodes, base,
                                  tile=256, interpret=True,
                                  mxu_dtype=jnp.float32)
        rn, rh = ref_level(x, nid, ghw, (feat, thr, nal, can), lo, inv,
                           n_prev, n_nodes, base)
        np.testing.assert_array_equal(np.asarray(nid2), rn)
        np.testing.assert_allclose(np.asarray(hist), rh, rtol=1e-5, atol=1e-4)
        print("parity OK (f32 exact)")
    else:
        REP = 10
        xs = jnp.asarray(x)
        nid = jnp.zeros(rows, jnp.int32)
        ghws = jnp.asarray(ghw)
        for n_nodes, n_prev, base in ((1, 0, 0), (8, 4, 7), (32, 16, 31)):
            feat = jnp.zeros(max(n_prev, 1), jnp.float32)
            thr = jnp.zeros(max(n_prev, 1), jnp.float32)
            nal = jnp.zeros(max(n_prev, 1), jnp.float32)
            can = jnp.zeros(max(n_prev, 1), jnp.float32)
            lo = jnp.full((n_nodes, F), -3.0)
            inv = jnp.full((n_nodes, F), (W - 2) / 6.0)
            nz = jnp.zeros(rows, jnp.int32) + (base if base else 0)

            @jax.jit
            def run(x, nid, ghw, lo, inv, f, t, a, c):
                def it(i, acc):
                    nid2, h = level_kernel(x, nid + i * 0, ghw,
                                           (f, t, a, c), lo, inv,
                                           n_prev, n_nodes, base)
                    return acc + h[0, 0, 0, 0] + nid2[0].astype(jnp.float32)
                return jax.lax.fori_loop(0, REP, it, jnp.float32(0))

            s = float(run(xs, nz, ghws, lo, inv, feat, thr, nal, can))
            t0 = time.time()
            s = float(run(xs, nz, ghws, lo, inv, feat, thr, nal, can))
            dt = (time.time() - t0) / REP
            gb = rows * F * 4 / 1e9
            print(f"N={n_nodes:3d}: {dt*1e3:8.2f} ms/level "
                  f"({gb/dt:.0f} GB/s eff)")


if __name__ == "__main__":
    main()
