"""Decompose grow_tree per-tree cost at 1M rows (serial-dep timing)."""
import sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
from h2o3_tpu.models.tree import TreeConfig, grow_tree, _find_splits
from h2o3_tpu.ops.binning import CodesView
from h2o3_tpu.ops.histogram import build_histograms

rng = np.random.default_rng(0)
ROWS = 489 * 2048
F = 28
Fp = 32
cfg = TreeConfig(max_depth=6, n_bins=255, n_features=F, min_rows=1.0)

rm = jnp.asarray(rng.integers(0, 254, size=(ROWS, F), dtype=np.int32).astype(np.uint8))
ct = jnp.asarray(
    np.pad(rng.integers(0, 254, size=(F, ROWS), dtype=np.int32), ((0, Fp - F), (0, 0))))
codes = CodesView(rm=rm, t=ct)
g0 = np.ascontiguousarray(rng.normal(size=ROWS).astype(np.float32))
h0 = np.abs(rng.normal(size=ROWS)).astype(np.float32)
w0 = np.ones(ROWS, np.float32)
col_mask = jnp.ones(F, bool)


def timeit(label, prog, *args, K=None):
    f = jax.jit(prog)
    x = f(*args); jax.block_until_ready(x)
    ts = []
    for t in range(2):
        a2 = (jnp.asarray(g0 + np.float32(t + 1)),) + args[1:]
        t0 = time.time(); x = f(*a2); jax.block_until_ready(x)
        ts.append(time.time() - t0)
    print(f"{label}: {min(ts)*1000:8.1f} ms", file=sys.stderr)


gj, hj, wj = jnp.asarray(g0), jnp.asarray(h0), jnp.asarray(w0)

# (a) full grow_tree x10
def full10(g, h, w):
    acc = jnp.float32(0)
    for i in range(10):
        tree, nid = grow_tree(codes, g + acc * 1e-20, h, w, cfg, col_mask)
        acc = acc + tree["value"].sum() + nid.sum() * 1e-9
    return acc
timeit("grow_tree x10           ", full10, gj, hj, wj)

# (b) hists only: 6 levels (sibling pattern N=1,1,2,4,8,16) x10
def hists10(g, h, w):
    acc = jnp.float32(0)
    nid = (jnp.arange(ROWS) % 64).astype(jnp.int32)
    for i in range(10):
        for N in (1, 1, 2, 4, 8, 16):
            hist = build_histograms(codes, nid % N, g + acc * 1e-20, h, w, N, 256)
            acc = acc + hist.sum()
    return acc
timeit("hist 6 levels x10       ", hists10, gj, hj, wj)

# (c) routing only: 6 levels of the gather+update x10
def route10(g, h, w):
    acc = jnp.float32(0)
    for i in range(10):
        nid = jnp.zeros(ROWS, jnp.int32)
        for d in range(6):
            N = 2 ** d
            word = (jnp.arange(N, dtype=jnp.int32) % F) | (128 << 14) | (1 << 29)
            rw = word[jnp.clip(nid - (N - 1), 0, N - 1)]
            node_feat = rw & ((1 << 14) - 1)
            node_bin = (rw >> 14) & ((1 << 14) - 1)
            c = jnp.take_along_axis(rm, node_feat[:, None], axis=1)[:, 0].astype(jnp.int32)
            go_right = (c >= node_bin) | (g + acc * 1e-20 > 1e30)
            nid = 2 * nid + 1 + go_right.astype(jnp.int32)
        acc = acc + nid.sum() * 1e-9
    return acc
timeit("routing 6 levels x10    ", route10, gj, hj, wj)

# (d) split finding on hists x10 (levels N=1..32)
def splits10(g, h, w):
    acc = jnp.float32(0)
    for i in range(10):
        for N in (1, 2, 4, 8, 16, 32):
            hist = jnp.ones((N, F, 256, 3), jnp.float32) * (1 + acc * 1e-20)
            bg, bf, bb, bnl, gt, ht, wt = _find_splits(hist, cfg, col_mask)
            acc = acc + bg.sum() + gt.sum()
    return acc
timeit("find_splits 6 levels x10", splits10, gj, hj, wj)

# (e) the where-masking of g/h/w per level x10
def mask10(g, h, w):
    acc = jnp.float32(0)
    nid = (jnp.arange(ROWS) % 64).astype(jnp.int32)
    for i in range(10):
        for d in range(6):
            N = 2 ** d
            local = nid - (N - 1)
            in_level = (local >= 0) & (local < N)
            lw = jnp.where(in_level, w, 0.0)
            lg = jnp.where(in_level, g + acc * 1e-20, 0.0)
            lh = jnp.where(in_level, h, 0.0)
            acc = acc + lg.sum() + lh.sum() + lw.sum()
    return acc
timeit("mask ghw 6 levels x10   ", mask10, gj, hj, wj)
