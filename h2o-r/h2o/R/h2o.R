# R client for the TPU-native H2O-3 rebuild.
#
# Reference surface: /root/reference/h2o-r/h2o-package/R (connection.R,
# frame.R, models.R) — the subset implemented here covers the workflow
# verbs: init/connect, importFile, frame accessors, the major trainers,
# predict, performance. The wire contract is identical to what the
# unmodified h2o-py client exercises in tests/test_h2opy_client.py.
#
# NOT RUN UNDER R IN THIS BUILD IMAGE (no R interpreter available);
# written against the REST contract verified via the Python client and
# curl (tests/test_h2opy_client*.py, tests/test_rest*.py).

.h2o.env <- new.env(parent = emptyenv())

.h2o.url <- function(path) {
  paste0(get("base", envir = .h2o.env), path)
}

.h2o.get <- function(path, params = list()) {
  u <- .h2o.url(path)
  if (length(params)) {
    q <- paste(mapply(function(k, v) {
      paste0(curl::curl_escape(k), "=", curl::curl_escape(as.character(v)))
    }, names(params), params), collapse = "&")
    u <- paste0(u, "?", q)
  }
  jsonlite::fromJSON(rawToChar(curl::curl_fetch_memory(u)$content),
                     simplifyVector = FALSE)
}

.h2o.serialize <- function(v) {
  # vector-valued params (hidden, base_models, alpha, ...) go over the
  # wire in the server's bracket syntax "[a,b]" (api/server.py
  # _bracket_list); scalars as plain strings
  if (length(v) > 1)
    paste0("[", paste(vapply(v, function(x)
      if (is.character(x)) paste0("\"", x, "\"") else as.character(x),
      character(1)), collapse = ","), "]")
  else as.character(v)
}

.h2o.post <- function(path, params = list()) {
  h <- curl::new_handle()
  fields <- paste(mapply(function(k, v) {
    paste0(curl::curl_escape(k), "=", curl::curl_escape(.h2o.serialize(v)))
  }, names(params), params), collapse = "&")
  curl::handle_setopt(h, postfields = fields)
  curl::handle_setheaders(h,
    "Content-Type" = "application/x-www-form-urlencoded")
  r <- curl::curl_fetch_memory(.h2o.url(path), handle = h)
  jsonlite::fromJSON(rawToChar(r$content), simplifyVector = FALSE)
}

#' Connect to a running cluster (the reference's h2o.init connects or
#' launches a jar; this client connects only).
h2o.init <- function(ip = "127.0.0.1", port = 54321, url = NULL) {
  assign("base",
         if (is.null(url)) sprintf("http://%s:%d", ip, port) else url,
         envir = .h2o.env)
  cl <- .h2o.get("/3/Cloud")
  message(sprintf("Connected to %s (version %s)",
                  get("base", envir = .h2o.env), cl$version))
  invisible(cl)
}

h2o.clusterStatus <- function() .h2o.get("/3/Cloud")

.h2o.poll <- function(job_key, interval = 0.3) {
  repeat {
    j <- .h2o.get(paste0("/3/Jobs/",
                         utils::URLencode(job_key, reserved = TRUE)))
    st <- j$jobs[[1]]$status
    if (st != "RUNNING") {
      if (st == "FAILED")
        stop("job failed: ", j$jobs[[1]]$exception)
      return(j$jobs[[1]])
    }
    Sys.sleep(interval)
  }
}

#' Import + parse a file into a Frame; returns an H2OFrame handle.
h2o.importFile <- function(path, destination_frame = NULL) {
  imp <- .h2o.post("/3/ImportFiles", list(path = path))
  src <- as.character(jsonlite::toJSON(unlist(imp$destination_frames)))
  setup <- .h2o.post("/3/ParseSetup", list(source_frames = src))
  dest <- if (is.null(destination_frame)) setup$destination_frame
          else destination_frame
  parse <- .h2o.post("/3/Parse", list(
    source_frames = src, destination_frame = dest,
    separator = setup$separator, check_header = setup$check_header))
  .h2o.poll(parse$job$key$name)
  structure(list(key = dest), class = "H2OFrame")
}

h2o.getFrame <- function(key) {
  structure(list(key = key), class = "H2OFrame")
}

h2o.ls <- function() .h2o.get("/3/Frames")

h2o.describe <- function(frame) {
  .h2o.get(paste0("/3/Frames/",
                  utils::URLencode(frame$key, reserved = TRUE)))
}

h2o.nrow <- function(frame) h2o.describe(frame)$frames[[1]]$rows

.h2o.train <- function(algo, y, training_frame, params = list()) {
  body <- c(list(training_frame = training_frame$key), params)
  if (!is.null(y)) body$response_column <- y
  r <- .h2o.post(paste0("/3/ModelBuilders/", algo), body)
  job <- .h2o.poll(r$job$key$name)
  structure(list(key = job$dest$name, algo = algo), class = "H2OModel")
}

# per-algo estimator functions (h2o.gbm, h2o.glm, ...) live in
# estimators_gen.R — generated from the live /3/ModelBuilders metadata
# by tools/gen_R.py with the full parameter surface.

h2o.getModel <- function(key) {
  .h2o.get(paste0("/3/Models/",
                  utils::URLencode(key, reserved = TRUE)))
}

h2o.performance <- function(model, newdata = NULL) {
  if (is.null(newdata)) {
    m <- h2o.getModel(model$key)
    return(m$models[[1]]$output$training_metrics)
  }
  r <- .h2o.post(sprintf("/3/ModelMetrics/models/%s/frames/%s",
                         model$key, newdata$key), list())
  r$model_metrics[[1]]
}

h2o.predict <- function(model, newdata) {
  r <- .h2o.post(sprintf("/3/Predictions/models/%s/frames/%s",
                         model$key, newdata$key), list())
  structure(list(key = r$predictions_frame$name), class = "H2OFrame")
}

h2o.auc <- function(perf) perf$AUC

h2o.automl <- function(y, training_frame, max_models = 10,
                       project_name = NULL) {
  # /99/AutoMLBuilder takes the NESTED spec the reference clients post:
  # {build_control, input_spec, build_models} (h2o-py _estimator.py:668;
  # server.py _automl_build reads exactly these keys)
  spec <- list(
    build_control = list(
      project_name = project_name,
      stopping_criteria = list(max_models = max_models)),
    input_spec = list(
      training_frame = training_frame$key,
      response_column = y),
    build_models = list())
  r <- .h2o.post("/99/AutoMLBuilder", list(
    build_control = as.character(jsonlite::toJSON(
      spec$build_control, auto_unbox = TRUE, null = "null")),
    input_spec = as.character(jsonlite::toJSON(
      spec$input_spec, auto_unbox = TRUE)),
    build_models = "{}"))
  .h2o.poll(r$job$key$name)
  structure(list(project = r$build_control$project_name),
            class = "H2OAutoML")
}
