"""Kernel variant shootout for the adaptive histogram level kernel.

Times the deepest level (N=32, the dominant cost) for several kernel
variants at 10M rows to find what to change in ops/hist_adaptive.py.
"""
import sys, os, time, functools
sys.path.insert(0, '/root/repo')

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from h2o3_tpu.ops.pallas_compat import CompilerParams as _CompilerParams

ROWS = 10_002_432
F, W = 28, 32
N = 32
TILE = int(os.environ.get("TILE", 4096))
REPS = 10
_VMEM_LIMIT = 100 * 1024 * 1024
HI = jax.lax.Precision.HIGHEST


def _route(x, nid, tabs_ref, n_prev, level_base, tile, F):
    prev_base = level_base - n_prev
    lid_p = nid - prev_base
    onp = (jax.lax.broadcasted_iota(jnp.int32, (n_prev, tile), 0)
           == lid_p[None, :]).astype(jnp.float32)
    t4 = tabs_ref[:, :n_prev]
    lut = jax.lax.dot_general(t4, onp, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32,
                              precision=HI)
    f_r, t_r, nl_r, cn_r = lut[0], lut[1], lut[2], lut[3]
    fi = jax.lax.broadcasted_iota(jnp.int32, (tile, F), 1)
    xsel = jnp.sum(jnp.where(fi == f_r.astype(jnp.int32)[:, None], x, 0.0),
                   axis=1)
    gr_f = jnp.where(jnp.isnan(xsel), 1.0 - nl_r,
                     (xsel >= t_r).astype(jnp.float32))
    in_prev = (lid_p >= 0) & (lid_p < n_prev)
    child = 2 * nid + 1 + gr_f.astype(jnp.int32)
    return jnp.where(in_prev & (cn_r > 0.5), child, nid)


def _kernel(x_ref, nid_ref, ghw_ref, tabs_ref, loinv_ref, nid_out, hist_out,
            acc_ref, *, n_prev, n_nodes, F, W, tile, n_row_tiles, level_base,
            mxu_dtype, variant):
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    nid = nid_ref[0, :]
    if n_prev > 0 and variant != "noroute":
        nid = _route(x, nid, tabs_ref, n_prev, level_base, tile, F)
    nid_out[0, :] = nid

    lid = nid - level_base
    in_lvl = (lid >= 0) & (lid < n_nodes)
    lidc = jnp.where(in_lvl, lid, 0)
    onh = (jax.lax.broadcasted_iota(jnp.int32, (n_nodes, tile), 0)
           == lidc[None, :])
    onh_f = onh.astype(jnp.float32) * in_lvl.astype(jnp.float32)[None, :]
    if variant == "noloinv":
        lo_r = jnp.full((tile, F), -4.0, jnp.float32)
        inv_r = jnp.full((tile, F), (W - 2) / 8.0, jnp.float32)
    else:
        loinv_r = jax.lax.dot_general(onh_f, loinv_ref[...],
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32,
                                      precision=HI)
        lo_r = loinv_r[:, :F]
        inv_r = loinv_r[:, F:]
    bin_f = jnp.floor(jnp.clip((x - lo_r) * inv_r, 0.0, float(W - 2)))
    bin_v = jnp.where(jnp.isnan(x), float(W - 1), bin_f)
    lane = jax.lax.broadcasted_iota(jnp.int32, (tile, F * W), 1)
    if variant in ("base", "noroute", "noloinv", "nohist"):
        sel = (jax.lax.broadcasted_iota(jnp.int32, (F, F * W), 1) // W
               == jax.lax.broadcasted_iota(jnp.int32, (F, F * W), 0)
               ).astype(jnp.float32)
        b_all = jax.lax.dot_general(bin_v, sel, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
    elif variant == "bf16sel":
        sel = (jax.lax.broadcasted_iota(jnp.int32, (F, F * W), 1) // W
               == jax.lax.broadcasted_iota(jnp.int32, (F, F * W), 0)
               ).astype(jnp.bfloat16)
        b_all = jax.lax.dot_general(bin_v.astype(jnp.bfloat16), sel,
                                    (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
    elif variant == "bcast":
        b_all = jnp.broadcast_to(bin_v[:, :, None], (tile, F, W)
                                 ).reshape(tile, F * W)
    elif variant == "repeat":
        b_all = jnp.repeat(bin_v, W, axis=1)
    oh = ((lane % W) == b_all.astype(jnp.int32)).astype(mxu_dtype)
    ghw = ghw_ref[...]
    left = jnp.concatenate(
        [onh_f.astype(mxu_dtype) * ghw[k, :][None, :].astype(mxu_dtype)
         for k in range(3)], axis=0)
    if variant == "nohist":
        acc_ref[...] += jnp.broadcast_to(
            jnp.sum(oh.astype(jnp.float32), axis=0, keepdims=True)[:, :acc_ref.shape[1]],
            acc_ref.shape) * left[0, 0]
    else:
        acc_ref[...] += jax.lax.dot_general(
            left, oh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=(HI if mxu_dtype == jnp.float32
                       else jax.lax.Precision.DEFAULT))

    @pl.when(r == n_row_tiles - 1)
    def _flush():
        hist_out[...] = acc_ref[...]


def level(x, nid, ghw, tables, lo, inv, n_prev, n_nodes, level_base, W,
          tile, variant, mxu_dtype=jnp.bfloat16):
    rows, F = x.shape
    n_row_tiles = rows // tile
    tabs = jnp.stack(tables, axis=0)
    np1 = tabs.shape[1]
    loinv = jnp.concatenate([lo, inv], axis=1)
    kern = functools.partial(_kernel, n_prev=n_prev, n_nodes=n_nodes, F=F,
                             W=W, tile=tile, n_row_tiles=n_row_tiles,
                             level_base=level_base, mxu_dtype=mxu_dtype,
                             variant=variant)
    nid2, hist = pl.pallas_call(
        kern,
        grid=(n_row_tiles,),
        in_specs=[
            pl.BlockSpec((tile, F), lambda r: (r, 0)),
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3, tile), lambda r: (0, r)),
            pl.BlockSpec((4, np1), lambda r: (0, 0)),
            pl.BlockSpec((n_nodes, 2 * F), lambda r: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile), lambda r: (0, r)),
            pl.BlockSpec((3 * n_nodes, F * W), lambda r: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows), jnp.int32),
            jax.ShapeDtypeStruct((3 * n_nodes, F * W), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((3 * n_nodes, F * W), jnp.float32)],
        compiler_params=_CompilerParams(vmem_limit_bytes=_VMEM_LIMIT),
    )(x, nid[None, :], ghw, tabs, loinv)
    return nid2[0], hist


def main():
    rows = ROWS - (ROWS % TILE)
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(rows, F)).astype(np.float32))
    ghw = jnp.stack([jnp.asarray(rng.normal(size=rows).astype(np.float32)),
                     jnp.ones(rows, jnp.float32), jnp.ones(rows, jnp.float32)])
    # realistic nids: uniformly in the previous level
    n_prev = N // 2
    base = N - 1
    prev_base = base - n_prev
    nid = jnp.asarray(prev_base
                      + rng.integers(0, n_prev, rows).astype(np.int32))
    tables = (jnp.asarray(rng.integers(0, F, n_prev).astype(np.float32)),
              jnp.zeros(n_prev, jnp.float32), jnp.zeros(n_prev, jnp.float32),
              jnp.ones(n_prev, jnp.float32))
    lo = jnp.full((N, F), -4.0, jnp.float32)
    inv = jnp.full((N, F), (W - 2) / 8.0, jnp.float32)
    jax.device_get(jnp.sum(X[0]))

    ref_hist = None
    variants = os.environ.get(
        "VARIANTS", "base,bf16sel,bcast,repeat,noroute,noloinv").split(",")
    for variant in variants:
        try:
            def loop(X, nid, ghw, tables, lo, inv, variant=variant):
                def body(i, carry):
                    nid_c, acc = carry
                    nid2, hist = level(X, nid_c, ghw, tables, lo, inv,
                                       n_prev, N, base, W, TILE, variant)
                    return (jnp.where(nid2 > 0, nid_c, nid_c),
                            acc + hist[0, :8].sum())
                return jax.lax.fori_loop(0, REPS, body, (nid, 0.0))

            f = jax.jit(loop)
            out = f(X, nid, ghw, tables, lo, inv)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            out = f(X, nid, ghw, tables, lo, inv)
            jax.block_until_ready(out)
            t = (time.perf_counter() - t0) / REPS
            # correctness vs base (single call, full hist)
            nid2, hist = jax.jit(functools.partial(
                level, n_prev=n_prev, n_nodes=N, level_base=base, W=W,
                tile=TILE, variant=variant))(X, nid, ghw, tables, lo, inv)
            hs = np.asarray(jax.device_get(hist))
            if variant == "base":
                ref_hist = hs
                match = "ref"
            else:
                match = ("OK" if ref_hist is not None and
                         np.allclose(hs, ref_hist, rtol=2e-2, atol=1.0)
                         else "DIFF")
            print(f"{variant:10s}: {t*1000:7.2f} ms/level  [{match}]",
                  flush=True)
        except Exception as e:
            print(f"{variant:10s}: FAILED {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)


if __name__ == "__main__":
    main()
