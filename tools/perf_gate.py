"""Bench-trajectory regression gate: BENCH_r*.json may only get better.

The h2o3-lint baseline ratchet (PR 10) machine-checks that the lint
finding count shrinks monotonically; this is the same shape for the
PERF record: every checked-in ``BENCH_r{NN}.json`` round is compared
against the best earlier round per headline metric, and a round that
regresses beyond the metric's noise band FAILS the gate — "the bench
only ever gets faster" stops being an eyeballed convention.

Semantics per metric (direction + noise band in ``METRIC_SPECS``):

- higher-is-better (rows/sec, MFU, scaling efficiency): round ``i``
  fails when ``value < best_so_far * (1 - band)``;
- lower-is-better (latency, time-to-first-model): fails when
  ``value > best_so_far * (1 + band)``.

A metric is only checked from the first round that reports it (early
rounds predate serve/MFU fields), and a metric with fewer than two data
points is skipped. Fewer than two round files = clean skip (a fresh
repo must not fail its own gate). Noise bands are deliberately wider
for latency metrics (scheduler noise) than for throughput.

INFORMATIONAL rounds: a round recorded off-TPU carries
``"informational": true`` (bench.py stamps it from the backend). Every
headline metric here is hardware-bound — comparing a CPU smoke round
against TPU history is meaningless in BOTH directions (a fake
regression AND a fake best) — so informational rounds are excluded
from the ratchet entirely and listed in the report instead. (The
per-point ``train.perf_informational`` flag is NOT used: it also fires
on real TPUs missing from the peak table.)

Stdlib-only by design — tier-1 runs it (tests/test_perf_accounting.py)
without paying the jax import.

Usage:
    python tools/perf_gate.py [--dir REPO] [--json] [--band X]
Exit 1 when any round regressed beyond its band; 0 otherwise.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

# (dotted metric path, direction, relative noise band). Paths resolve a
# FLAT key first (bench emits "train.mfu" literally), then dotted
# descent ("serve.p50_ms" -> record["serve"]["p50_ms"]).
METRIC_SPECS: Tuple[Tuple[str, str, float], ...] = (
    ("value", "higher", 0.10),                 # rows/sec/chip headline
    ("vs_baseline", "higher", 0.10),
    ("train.mfu", "higher", 0.10),
    ("time_to_first_model_s", "lower", 0.35),  # compile-cache sensitive
    ("loop_s", "lower", 0.15),
    ("ingest_rows_per_sec", "higher", 0.15),
    # parse throughput ratchets up; any byte range re-parsed through
    # the Python tokenizer ratchets DOWN from a best of zero (band 0 on
    # a 0 best: one fallback range fails the gate — ISSUE 14)
    ("ingest.mb_per_sec", "higher", 0.15),
    ("ingest.fallback_ranges", "lower", 0.0),
    # nogil native encode + member-parallel compressed ingest (ISSUE
    # 16): both throughputs may only ratchet up
    ("ingest.encode_mb_per_sec", "higher", 0.15),
    ("ingest.compressed_mb_per_sec", "higher", 0.15),
    # multi-level fused dispatch (ISSUE 17): level-pass throughput
    # (rows x trees x depth / loop_s) may only ratchet up — the fused
    # window's win is fewer host round-trips at identical per-level
    # math, so this moves while train.hot_loop_bytes_per_row stays flat
    ("train.level_loop_rows_per_sec", "higher", 0.15),
    ("serve.rows_per_sec", "higher", 0.20),
    ("serve.mfu", "higher", 0.25),
    ("serve.p50_ms", "lower", 0.35),
    ("serve.p99_ms", "lower", 0.50),
    ("multichip.scaling_efficiency_8", "higher", 0.15),
    # fleet round (ISSUE 13): multi-replica routed throughput may only
    # grow; membership shed latency (kill -> out of the routed set) may
    # only shrink — wide band, it is heartbeat-quantized
    ("fleet.rows_per_sec", "higher", 0.20),
    ("fleet.shed_ms", "lower", 0.60),
    # router tier (ISSUE 20): the zero-hop dispatch ratio is a
    # steady-state invariant (>= 0.9 acceptance, tight band); the
    # affinity path's p50 is loopback-HTTP-quantized — wide band. The
    # interactive-under-bulk p99 is the lane-isolation ratchet: it may
    # only shrink toward the solo band
    ("fleet.zero_hop_ratio", "higher", 0.05),
    ("fleet.routed_p50_ms", "lower", 0.50),
    ("serve.interactive_p99_under_bulk_ms", "lower", 0.60),
    # training scheduler (ISSUE 15): completions under oversubscription
    # and the preempt/resume bit-identity verdict (1/0) may never
    # regress (band 0); queue wait is train-duration-quantized — the
    # widest band in the table
    ("sched.oversub_completed", "higher", 0.0),
    ("sched.preempt_resume_ok", "higher", 0.0),
    ("sched.queue_wait_p50_ms", "lower", 0.60),
    # fleet scheduler (ISSUE 18): the evict-requeue and migrate counts
    # are correctness floors (band 0 — a round that stops resuming or
    # migrating is a regression, not noise); cross-replica queue wait
    # is heartbeat- and hand-off-quantized like sched's
    ("fleetsched.queue_wait_p50_ms", "lower", 0.60),
    ("fleetsched.migrations", "higher", 0.0),
    ("fleetsched.resumed_after_evict", "higher", 0.0),
    # flight recorder (ISSUE 19): enabled-path append cost — the 2µs
    # budget leaves headroom, but the append is one struct.pack + one
    # mmap splice, so scheduler jitter dominates; wide band
    ("blackbox.ns_per_event", "lower", 0.60),
)

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def load_rounds(bench_dir: str) -> List[Tuple[int, str, Dict]]:
    """Checked-in bench rounds sorted by round number. Each record is
    the driver wrapper's ``parsed`` dict when present (the bench's own
    JSON line), else the file's top level."""
    out = []
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            print(f"perf_gate: unreadable {path}: {e}", file=sys.stderr)
            continue
        rec = data.get("parsed") if isinstance(
            data.get("parsed"), dict) else data
        out.append((int(m.group(1)), os.path.basename(path), rec))
    return sorted(out)


def metric_value(rec: Dict, path: str) -> Optional[float]:
    if path in rec:
        v = rec[path]
    else:
        v = rec
        for part in path.split("."):
            if not isinstance(v, dict) or part not in v:
                return None
            v = v[part]
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def check_trajectory(rounds: List[Tuple[int, str, Dict]],
                     specs=METRIC_SPECS,
                     band_override: Optional[float] = None) -> Dict:
    """The ratchet: walk rounds in order per metric, tracking the best
    value seen; any round beyond its band off the best is a violation."""
    metrics: Dict[str, Dict] = {}
    violations: List[Dict] = []
    for path, direction, band in specs:
        band = band_override if band_override is not None else band
        points = [(n, name, metric_value(rec, path))
                  for n, name, rec in rounds]
        points = [(n, name, v) for n, name, v in points if v is not None]
        if len(points) < 2:
            metrics[path] = {"checked": False, "points": len(points)}
            continue
        best = points[0][2]
        best_round = points[0][0]
        viols = []
        for n, name, v in points[1:]:
            if direction == "higher":
                limit = best * (1.0 - band)
                bad = v < limit
                better = v > best
            else:
                limit = best * (1.0 + band)
                bad = v > limit
                better = v < best
            if bad:
                viols.append({
                    "metric": path, "round": n, "file": name,
                    "value": v, "best": best, "best_round": best_round,
                    "limit": round(limit, 6), "band": band,
                    "direction": direction})
            if better:
                best, best_round = v, n
        metrics[path] = {"checked": True, "points": len(points),
                         "direction": direction, "band": band,
                         "best": best, "best_round": best_round,
                         "latest": points[-1][2],
                         "violations": len(viols)}
        violations.extend(viols)
    return {"ok": not violations,
            "rounds": [name for _, name, _ in rounds],
            "metrics": metrics,
            "violations": violations}


def is_informational(rec: Dict) -> bool:
    """Off-TPU round: the top-level flag ONLY (bench.py stamps it from
    the backend). Deliberately NOT the per-point
    ``train.perf_informational`` flag — that one also fires on REAL
    TPU hardware whose device kind is missing from the peak table
    (nominal-peak provenance), and excluding such rounds would let
    genuine throughput regressions slip the ratchet."""
    return bool(rec.get("informational"))


def run(bench_dir: str, band_override: Optional[float] = None) -> Dict:
    rounds = load_rounds(bench_dir)
    informational = [name for _, name, rec in rounds
                     if is_informational(rec)]
    rounds = [(n, name, rec) for n, name, rec in rounds
              if not is_informational(rec)]
    if len(rounds) < 2:
        return {"ok": True, "skipped": True,
                "reason": f"{len(rounds)} hardware bench round(s) in "
                          f"{bench_dir} — need 2 to ratchet",
                "rounds": [name for _, name, _ in rounds],
                "informational_rounds": informational}
    report = check_trajectory(rounds, band_override=band_override)
    report["skipped"] = False
    report["informational_rounds"] = informational
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="bench-trajectory regression gate (shrink-only "
                    "ratchet over BENCH_r*.json)")
    ap.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--band", type=float, default=None,
                    help="override every metric's noise band")
    args = ap.parse_args(argv)
    report = run(args.dir, band_override=args.band)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        if report.get("skipped"):
            print(f"perf_gate: SKIP — {report['reason']}")
        else:
            for v in report["violations"]:
                print(f"perf_gate: REGRESSION {v['metric']} in "
                      f"{v['file']}: {v['value']} vs best {v['best']} "
                      f"(r{v['best_round']:02d}), limit {v['limit']} "
                      f"[{v['direction']}, band {v['band']:.0%}]")
            checked = {k: m for k, m in report["metrics"].items()
                       if m.get("checked")}
            info = report.get("informational_rounds") or []
            print(f"perf_gate: {'OK' if report['ok'] else 'FAIL'} — "
                  f"{len(report['rounds'])} rounds, "
                  f"{len(checked)} metrics checked, "
                  f"{len(report['violations'])} violation(s)"
                  + (f", {len(info)} informational round(s) excluded "
                     f"({', '.join(info)})" if info else ""))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
