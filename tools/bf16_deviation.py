"""Quantify the bf16 histogram-contraction deviation (task: document a
bound, not a comment). Trains deep GBMs twice — histogram_precision
bfloat16 vs float32 — on adversarial near-tie data and reports split
disagreement and AUC delta. Run on the real TPU chip.
"""
import os, sys, time
sys.path.insert(0, '/root/repo')

import numpy as np

ROWS = int(os.environ.get("ROWS", 2_000_000))
DEPTH = int(os.environ.get("DEPTH", 8))
TREES = int(os.environ.get("TREES", 10))


def main():
    import jax
    import h2o3_tpu as h2o
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    rng = np.random.default_rng(11)
    F = 12
    X = rng.normal(size=(ROWS, F)).astype(np.float32)
    # near-tie structure: pairs of nearly identical features so split
    # gains between them differ only in low-order bits
    for j in range(0, F, 2):
        X[:, j + 1] = X[:, j] + 1e-4 * rng.normal(size=ROWS).astype(np.float32)
    logit = (X[:, 0] - X[:, 2] + 0.5 * X[:, 4] * X[:, 6]
             + 0.3 * np.sin(2 * X[:, 8]))
    y = (rng.random(ROWS) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    cols = {f"f{i}": X[:, i] for i in range(F)}
    cols["y"] = y
    fr = h2o.Frame.from_numpy(cols)

    models = {}
    for prec in ("bfloat16", "float32"):
        t0 = time.time()
        est = H2OGradientBoostingEstimator(
            ntrees=TREES, max_depth=DEPTH, learn_rate=0.1, nbins=30,
            distribution="bernoulli", seed=3, score_tree_interval=0,
            stopping_rounds=0, min_rows=1.0, histogram_precision=prec)
        est.train(y="y", training_frame=fr)
        m = est.model
        models[prec] = m
        print(f"{prec}: train {time.time()-t0:.1f}s "
              f"loop {m.output['training_loop_seconds']:.2f}s "
              f"AUC {m.training_metrics.auc:.6f}", flush=True)

    mb, mf = models["bfloat16"], models["float32"]
    fb = np.asarray(mb._feat); ff = np.asarray(mf._feat)
    sb = np.asarray(mb._is_split); sf = np.asarray(mf._is_split)
    tb = np.asarray(mb._thr); tf = np.asarray(mf._thr)
    both = sb & sf
    n_splits = int(both.sum())
    feat_diff = int((fb[both] != ff[both]).sum())
    thr_diff = int(((fb[both] == ff[both])
                    & (tb[both] != tf[both])).sum())
    auc_d = abs(mb.training_metrics.auc - mf.training_metrics.auc)
    print(f"splits compared: {n_splits}")
    print(f"feature disagreements: {feat_diff} "
          f"({100*feat_diff/max(n_splits,1):.3f}%)")
    print(f"threshold-only disagreements: {thr_diff} "
          f"({100*thr_diff/max(n_splits,1):.3f}%)")
    print(f"AUC delta: {auc_d:.6f}")
    # leaf value agreement (deepest level uses exact f32 totals in both)
    vb = np.asarray(mb._value); vf = np.asarray(mf._value)
    same_struct = (fb == ff).all(axis=1)
    if same_struct.any():
        rel = np.abs(vb[same_struct] - vf[same_struct])
        print(f"leaf |Δvalue| max over same-structure trees: {rel.max():.2e}")
    return {
        "rows": ROWS, "depth": DEPTH, "trees": TREES,
        "splits_compared": n_splits,
        "feature_disagreements": feat_diff,
        "feature_disagreement_pct": round(100 * feat_diff
                                          / max(n_splits, 1), 3),
        "threshold_only_disagreements": thr_diff,
        "auc_bf16": round(float(mb.training_metrics.auc), 6),
        "auc_f32": round(float(mf.training_metrics.auc), 6),
        "auc_delta": round(float(auc_d), 7),
        # which hot path the guard measured: with packed_codes auto the
        # default TPU run exercises the PACKED binned kernel (ISSUE 12)
        # — the record must say so or a path switch would silently
        # reinterpret the history
        "packed_codes": mf.output.get("packed_codes"),
        # guard threshold: a kernel-numerics regression shows up as an
        # AUC gap far above the measured near-tie noise floor (~3e-5)
        "auc_delta_threshold": 1e-3,
        "pass": bool(auc_d < 1e-3),
    }


if __name__ == "__main__":
    res = main()
    if "--json" in sys.argv:
        import json
        idx = sys.argv.index("--json")
        if idx + 1 >= len(sys.argv):
            sys.exit("--json requires an output path")
        with open(sys.argv[idx + 1], "w") as f:
            json.dump(res, f, indent=1)
    sys.exit(0 if res["pass"] else 1)
