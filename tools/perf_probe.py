"""Per-level timing probe for the adaptive histogram kernel on real TPU.

The axon tunnel adds ~100ms per dispatch, so each level is looped REPS
times inside ONE jitted program (lax.fori_loop) and the per-iteration
time is (total - overhead) / REPS.
"""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.ops.hist_adaptive import adaptive_level_tpu, leaf_totals_tpu

ROWS = int(os.environ.get("ROWS", 10_000_000))
F = int(os.environ.get("F", 28))
W = int(os.environ.get("W", 32))
DEPTH = 6
TILE = int(os.environ.get("TILE", 4096))
REPS = int(os.environ.get("REPS", 20))


def _sync(out):
    # axon-tunnel block_until_ready is a no-op; device_get truly syncs
    leaf = jax.tree_util.tree_leaves(out)[-1]
    np.asarray(jax.device_get(leaf))


def timed(fn, *args):
    out = fn(*args)
    _sync(out)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn(*args)
        _sync(out)
        ts.append(time.perf_counter() - t0)
    return min(ts), out


def main():
    print(f"backend: {jax.default_backend()} rows={ROWS} F={F} W={W} "
          f"tile={TILE} reps={REPS}")
    rows = ROWS - (ROWS % TILE) if ROWS % TILE else ROWS
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(rows, F)).astype(np.float32))
    g = jnp.asarray(rng.normal(size=rows).astype(np.float32))
    ghw = jnp.stack([g, jnp.ones(rows, jnp.float32),
                     jnp.ones(rows, jnp.float32)])
    nid0 = jnp.zeros(rows, jnp.int32)
    jax.block_until_ready(X)

    # measure dispatch overhead with a trivial program
    triv = jax.jit(lambda a: a + 1)
    t_over, _ = timed(triv, nid0)
    print(f"dispatch overhead (trivial jit): {t_over*1000:.1f} ms")

    total = 0.0
    nid = nid0
    for d in range(DEPTH):
        N = 2 ** d
        base = N - 1
        n_prev = N // 2 if d else 0
        npv = max(n_prev, 1)
        if d:
            tables = (jnp.asarray(rng.integers(0, F, npv).astype(np.float32)),
                      jnp.zeros(npv, jnp.float32), jnp.zeros(npv, jnp.float32),
                      jnp.ones(npv, jnp.float32))
        else:
            tables = (jnp.zeros(1, jnp.float32),) * 4
        lo = jnp.full((N, F), -4.0, jnp.float32)
        inv = jnp.full((N, F), (W - 2) / 8.0, jnp.float32)

        def level_loop(X, nid, ghw, tables, lo, inv, n_prev=n_prev, N=N,
                       base=base):
            def body(i, carry):
                nid_c, acc = carry
                nid2, hist = adaptive_level_tpu(X, nid_c, ghw, tables, lo,
                                                inv, n_prev, N, base, W,
                                                tile=TILE)
                # feed nid2 back (real dependence, defeats loop hoisting);
                # compute is shape-dependent only, so timing stays valid
                return nid2 % (2 * N), acc + hist[0, 0, 0, 0]
            return jax.lax.fori_loop(0, REPS, body, (nid, 0.0))

        f = jax.jit(level_loop)
        t, out = timed(f, X, nid, ghw, tables, lo, inv)
        per = (t - t_over) / REPS
        total += per
        print(f"level d={d} N={N:3d}: {per*1000:8.2f} ms/iter")
        # advance nid realistically for next level
        nid2, _ = jax.jit(lambda X, nid, ghw, tables, lo, inv:
                          adaptive_level_tpu(X, nid, ghw, tables, lo, inv,
                                             n_prev, N, base, W, tile=TILE)
                          )(X, nid, ghw, tables, lo, inv)
        nid = jnp.where(jnp.asarray(rng.random(rows) < 0.5), 2 * nid + 1,
                        2 * nid + 2) if d == 0 else nid2

    npv = 2 ** (DEPTH - 1)
    tables = (jnp.asarray(rng.integers(0, F, npv).astype(np.float32)),
              jnp.zeros(npv, jnp.float32), jnp.zeros(npv, jnp.float32),
              jnp.ones(npv, jnp.float32))
    ND = 2 ** DEPTH

    def leaf_loop(X, nid, ghw, tables):
        def body(i, carry):
            nid_c, acc = carry
            nid2, tot = leaf_totals_tpu(X, nid_c, ghw, tables, ND // 2, ND,
                                        ND - 1, tile=TILE)
            return nid2 % ND, acc + tot[0, 0]
        return jax.lax.fori_loop(0, REPS, body, (nid, 0.0))

    t, _ = timed(jax.jit(leaf_loop), X, nid, ghw, tables)
    per = (t - t_over) / REPS
    total += per
    print(f"leaf_totals ND={ND}: {per*1000:8.2f} ms/iter")
    print(f"TOTAL per tree: {total*1000:.1f} ms  "
          f"({rows/total/1e6:.1f}M rows/s/tree-pass)")


if __name__ == "__main__":
    main()
