#!/usr/bin/env python
"""blackbox-read CLI — decode flight-recorder rings offline (ISSUE 19).

The post-mortem half of the cluster flight recorder: a SIGKILLed
replica's mmap ring under the shared blackbox dir is still ordinary
bytes on disk, and this tool reads it without importing jax or
joining any fleet.

Usage:
    python tools/blackbox_read.py RING.bbx               # whole ring
    python tools/blackbox_read.py RING.bbx --last 20     # death window
    python tools/blackbox_read.py --dir /shared/blackbox # every ring
    python tools/blackbox_read.py --dir D --trace tr-abc # follow one
                                                         # trace id
                                                         # across rings
    ... --json                                           # machine out

Exit codes: 0 = decoded something, 1 = no events matched,
2 = usage / unreadable ring.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Importing ``h2o3_tpu.telemetry.blackbox`` through the real package
# initializers would pull jax in (seconds of startup a post-mortem
# reader on a rescue box doesn't need, and may not have). Pre-register
# bare package shells so the submodule imports resolve without running
# either __init__ — the h2o3_lint trick. (When the real package is
# already imported this is a no-op.)
if "h2o3_tpu" not in sys.modules:
    _pkg = types.ModuleType("h2o3_tpu")
    _pkg.__path__ = [os.path.join(_REPO, "h2o3_tpu")]
    sys.modules["h2o3_tpu"] = _pkg
if "h2o3_tpu.telemetry" not in sys.modules:
    _sub = types.ModuleType("h2o3_tpu.telemetry")
    _sub.__path__ = [os.path.join(_REPO, "h2o3_tpu", "telemetry")]
    sys.modules["h2o3_tpu.telemetry"] = _sub

from h2o3_tpu.telemetry.blackbox import follow_trace, read_ring  # noqa: E402


def _fmt(ev: dict) -> str:
    t = time.strftime("%H:%M:%S", time.localtime(ev["t_wall"]))
    frac = f"{ev['t_wall'] % 1:.3f}"[1:]
    trace = f" trace={ev['trace_id']}" if ev.get("trace_id") else ""
    ring = f" [{ev['member_ring']}]" if ev.get("member_ring") else ""
    return (f"{t}{frac} e{ev['epoch']:<3d} #{ev['seq']:<6d}"
            f" {ev['kind']:<22s} {ev['member']:<28s}"
            f" {ev['payload']}{trace}{ring}")


def _collect(args) -> list:
    paths = list(args.rings)
    if args.dir:
        try:
            paths += sorted(
                os.path.join(args.dir, n) for n in os.listdir(args.dir)
                if n.endswith(".bbx"))
        except OSError as e:
            print(f"blackbox-read: {args.dir}: {e}", file=sys.stderr)
            sys.exit(2)
    if not paths:
        print("blackbox-read: no ring files (pass RING.bbx or --dir)",
              file=sys.stderr)
        sys.exit(2)
    rings = []
    for p in paths:
        try:
            rings.append(read_ring(p, last=args.last))
        except (OSError, ValueError) as e:
            print(f"blackbox-read: skipping {p}: {e}", file=sys.stderr)
    if not rings:
        sys.exit(2)
    return rings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("rings", nargs="*", help="ring files (*.bbx)")
    ap.add_argument("--dir", default=None,
                    help="decode every *.bbx ring in this directory")
    ap.add_argument("--last", type=int, default=None, metavar="N",
                    help="only the last N events per ring (the "
                         "last-moments-before-death view)")
    ap.add_argument("--trace", default=None, metavar="TRACE_ID",
                    help="follow one trace id across all given rings, "
                         "merged in causal (epoch, wall, seq) order")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable JSON")
    args = ap.parse_args(argv)

    rings = _collect(args)
    if args.trace:
        evs = follow_trace(args.trace, rings)
        if args.json:
            print(json.dumps({"trace_id": args.trace, "events": evs},
                             indent=2))
        else:
            for ev in evs:
                print(_fmt(ev))
        return 0 if evs else 1

    if args.json:
        print(json.dumps({"rings": rings}, indent=2))
        return 0 if any(r["events"] for r in rings) else 1
    total = 0
    for rg in rings:
        print(f"== {rg['path']}  member={rg['member_id']}  "
              f"seq={rg['seq']}  capacity={rg['capacity']}  "
              f"showing={len(rg['events'])}")
        for ev in rg["events"]:
            print(_fmt(ev))
        total += len(rg["events"])
    return 0 if total else 1


if __name__ == "__main__":
    sys.exit(main())
