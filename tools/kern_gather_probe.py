"""Probe: does Mosaic lower a dynamic LANE gather (jnp.take along axis=1
of a [S, N<=128] table with [tile] per-lane indices) inside a pallas
kernel — and how fast vs the bf16-split one-hot-matmul lookup?

If supported, both the route-table and range-table lookups can become
exact f32 gathers, dropping 2 lookup matmuls + 2 three-term recombines
per level.
"""
import sys, os, time, functools
sys.path.insert(0, '/root/repo')

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROWS = 2_500_608
F, N = 28, 32
TILE = int(os.environ.get("TILE", 8192))
REPS = 40


def kern_gather(tab_ref, idx_ref, out_ref):
    tab = tab_ref[...]                       # [2F, N] f32 (N in lanes)
    idx = idx_ref[0, :]                      # [TILE] i32 in [0, N)
    # lane gather via take_along_axis with a padded-to-TILE table:
    # out[s, t] = tab[s, idx[t]]
    tabp = jnp.pad(tab, ((0, 0), (0, TILE - N)))
    idx2 = jnp.broadcast_to(idx[None, :], (2 * F, TILE))
    out_ref[...] = jnp.take_along_axis(tabp, idx2, axis=1)


def kern_matmul(tab_ref, idx_ref, out_ref):
    tab = tab_ref[...]                       # [2F, N]
    idx = idx_ref[0, :]
    onh = (jax.lax.broadcasted_iota(jnp.int32, (N, TILE), 0)
           == idx[None, :]).astype(jnp.bfloat16)
    out_ref[...] = jax.lax.dot_general(
        tab.astype(jnp.bfloat16), onh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def run(kern, name):
    call = pl.pallas_call(
        kern,
        grid=(ROWS // TILE,),
        in_specs=[
            pl.BlockSpec((2 * F, N), lambda r: (0, 0)),
            pl.BlockSpec((1, TILE), lambda r: (0, r)),
        ],
        out_specs=pl.BlockSpec((2 * F, TILE), lambda r: (0, r)),
        out_shape=jax.ShapeDtypeStruct((2 * F, ROWS), jnp.float32),
    )
    rng = np.random.default_rng(0)
    tab = jnp.asarray(rng.normal(size=(2 * F, N)).astype(np.float32))
    idx = jnp.asarray(rng.integers(0, N, ROWS).astype(np.int32))

    @jax.jit
    def loop(tab, idx):
        def body(i, carry):
            s, idx = carry
            out = call(tab, idx[None, :])
            idx = (idx + out[0, :].astype(jnp.int32) % 2) % N
            return s + out[1, 0], idx
        return jax.lax.fori_loop(0, REPS, body, (0.0, idx))

    try:
        out = loop(tab, idx)
        _ = float(jax.device_get(out[0]))
    except Exception as e:
        print(f"{name}: FAILED — {str(e)[:300]}")
        return
    t0 = time.time()
    out2 = loop(tab, out[1])
    _ = float(jax.device_get(out2[0]))
    dt = (time.time() - t0) / REPS
    print(f"{name}: {dt*1000:.3f} ms ({ROWS/dt/1e6:.0f} M rows/s)",
          flush=True)


if __name__ == "__main__":
    run(kern_gather, "lane-gather")
    run(kern_matmul, "onehot-matmul")
