"""Multi-host SPMD worker (one PROCESS of the cloud).

Usage: python tools/multihost_worker.py <process_id> <num_processes> <port>

Each process owns 4 virtual CPU devices; jax.distributed.initialize forms
the process group (the Paxos cloud-formation analog, SURVEY §7.3), the
mesh spans all processes, and ONE shard_mapped adaptive tree build runs
with its histogram psums crossing the process boundary. Tree outputs are
replicated, so every process prints the same digest — the test asserts
it.
"""
import os
import sys

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
port = sys.argv[3]

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import h2o3_tpu as h2o

h2o.init(distributed=True, coordinator_address=f"localhost:{port}",
         num_processes=nproc, process_id=pid)
assert jax.process_count() == nproc
assert len(jax.devices()) == 4 * nproc, len(jax.devices())

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from h2o3_tpu.models.tree import TreeConfig, grow_tree_adaptive
from h2o3_tpu.parallel.mesh import DATA_AXIS, current_mesh, partitioner

mesh = current_mesh()
rows_global, F = 4096, 6
rows_local = rows_global // nproc
rng = np.random.default_rng(100 + pid)      # DIFFERENT rows per process
Xl = rng.normal(size=(rows_local, F)).astype(np.float32)
gl = rng.normal(size=rows_local).astype(np.float32)

# the product partitioner's multi-process branch: global sharded arrays
# assembled from process-local rows (the same layer frame/vec.py
# placement rides in a multi-host cluster)
part = partitioner(mesh)
X = part.shard_rows(Xl, rows_global)
g = part.shard_rows(gl, rows_global)
ones = part.shard_rows(np.ones(rows_local, np.float32), rows_global)

cfg = TreeConfig(max_depth=4, n_bins=30, n_features=F, min_rows=1.0)
root_lo = jnp.full(F, -4.0, jnp.float32)
root_hi = jnp.full(F, 4.0, jnp.float32)
col_mask = jnp.ones(F, bool)


def step(X, g, h, w):
    tree, nid = grow_tree_adaptive(X, g, h, w, cfg, col_mask, root_lo,
                                   root_hi, axis_name=DATA_AXIS)
    return tree


fn = jax.jit(jax.shard_map(
    step, mesh=mesh,
    in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
    out_specs=P(), check_vma=False))
tree = fn(X, g, ones, ones)
feat = np.asarray(jax.device_get(tree["feat"]))
val = np.asarray(jax.device_get(tree["value"]))
digest = f"{feat.sum()}:{np.round(float(np.abs(val).sum()), 4)}"
print(f"proc {pid}/{nproc} coordinator={h2o.is_coordinator()} "
      f"digest={digest}", flush=True)
print(f"DIGEST {digest}", flush=True)
