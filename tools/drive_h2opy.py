"""Drive the UNMODIFIED h2o-py client against the live REST server.

The north-star integration check (SURVEY.md §1 L13, §7.1.6): the real
client package from /root/reference/h2o-py, over real HTTP, end to end:
connect -> import_file -> parse -> frame ops (Rapids) -> GBM + GLM train
-> predict -> model_performance -> save/load. Run standalone for fast
iteration; tests/test_h2opy_client.py wraps the same flow in pytest.
"""
import faulthandler
import os
import sys

faulthandler.dump_traceback_later(240, repeat=True)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests"))

import h2opy_shim

STEP = os.environ.get("STEP", "all")


def main():
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu.api import start_server
    srv = start_server(port=0)
    print(f"server on {srv.port}", flush=True)

    h2o = h2opy_shim.import_h2o()
    h2o.connect(url=f"http://127.0.0.1:{srv.port}", verbose=False)
    print("STEP connect OK", flush=True)

    data = os.path.join(h2opy_shim.H2O_PY_PATH, "h2o", "h2o_data",
                        "prostate.csv")
    fr = h2o.import_file(data)
    print("STEP import_file OK:", fr.dim, flush=True)
    assert fr.dim == [380, 9], fr.dim

    # frame ops -> Rapids
    print("names:", fr.names, flush=True)
    desc = fr.describe()
    print("STEP describe OK", flush=True)
    m = fr["AGE"].mean()
    print("STEP mean OK:", m, flush=True)
    sub = fr[fr["AGE"] > 65, :]
    print("STEP filter OK:", sub.nrow, flush=True)
    fr["CAPSULE"] = fr["CAPSULE"].asfactor()
    print("STEP asfactor OK:", fr["CAPSULE"].isfactor(), flush=True)

    from h2o.estimators import (H2OGradientBoostingEstimator,
                                H2OGeneralizedLinearEstimator)
    gbm = H2OGradientBoostingEstimator(ntrees=10, max_depth=3, seed=42)
    gbm.train(y="CAPSULE", x=["AGE", "RACE", "PSA", "GLEASON"],
              training_frame=fr)
    print("STEP gbm train OK", flush=True)
    perf = gbm.model_performance(fr)
    print("STEP gbm perf OK auc=", perf.auc(), flush=True)
    assert perf.auc() > 0.7

    pred = gbm.predict(fr)
    print("STEP gbm predict OK:", pred.dim, pred.names, flush=True)

    glm = H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.0)
    glm.train(y="CAPSULE", x=["AGE", "RACE", "PSA", "GLEASON"],
              training_frame=fr)
    print("STEP glm train OK", flush=True)
    co = glm.coef()
    print("STEP glm coef OK:", co, flush=True)

    # save / load round trip over REST
    path = h2o.save_model(gbm, path="/tmp/h2opy_models", force=True)
    print("STEP save_model OK:", path, flush=True)
    loaded = h2o.load_model(path)
    print("STEP load_model OK:", loaded.model_id, flush=True)

    lb = h2o.ls()
    print("STEP ls OK:", len(lb), flush=True)

    h2o.remove(fr)
    print("STEP remove OK", flush=True)
    srv.stop()
    print("ALL STEPS PASSED", flush=True)


if __name__ == "__main__":
    main()
