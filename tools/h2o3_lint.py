#!/usr/bin/env python
"""h2o3-lint CLI — the repo-native static-analysis pass.

Usage:
    python tools/h2o3_lint.py h2o3_tpu                # human output
    python tools/h2o3_lint.py h2o3_tpu --json         # machine-readable
    python tools/h2o3_lint.py h2o3_tpu --write-baseline
    python tools/h2o3_lint.py --rules                 # rule catalog

Exit codes: 0 = clean (no new findings, no stale baseline entries),
1 = new findings and/or stale baseline entries, 2 = usage error.

The JSON report mirrors the bench/chaos verdict convention: tooling
asserts ``.ok`` / ``counts.new == 0`` the same way it asserts transfer
budgets. Pure-stdlib imports only — the linter must not pay (or
require) a JAX import to run.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# The analysis package is pure stdlib, but ``import h2o3_tpu.analysis``
# would execute h2o3_tpu/__init__.py — which imports jax (seconds of
# startup the linter doesn't need, and a hard dependency CI lint jobs
# shouldn't have). Pre-register a bare package shell so the submodule
# import resolves without running the package initializer. (Test runs
# import the real package first, in which case this is a no-op.)
if "h2o3_tpu" not in sys.modules:
    _pkg = types.ModuleType("h2o3_tpu")
    _pkg.__path__ = [os.path.join(_REPO, "h2o3_tpu")]
    sys.modules["h2o3_tpu"] = _pkg

from h2o3_tpu.analysis.core import (default_baseline_path, load_baseline,  # noqa: E402
                                    run_lint, save_baseline)
from h2o3_tpu.analysis.rules import all_rules  # noqa: E402


def _print_rules() -> None:
    for rule in all_rules():
        doc = (rule.__doc__ or "").strip().splitlines()
        head = doc[0] if doc else ""
        print(f"{rule.name}  [{rule.severity}]")
        print(f"    {head}")
        for line in doc[1:]:
            print(f"    {line.strip()}" if line.strip() else "")
        print()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help="files/dirs to lint")
    ap.add_argument("--json", action="store_true",
                    help="emit a machine-readable JSON report")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: "
                         "h2o3_tpu/analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings as the new "
                         "baseline (after reviewing them!)")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.rules:
        _print_rules()
        return 0
    if not args.paths:
        ap.print_usage()
        return 2

    rules = all_rules()
    baseline_path = args.baseline or default_baseline_path()
    baseline = {} if (args.no_baseline or args.write_baseline) \
        else load_baseline(baseline_path)
    report = run_lint(args.paths, rules, baseline=baseline)

    if args.write_baseline:
        path = save_baseline(report.new, path=baseline_path)
        print(f"wrote {len(report.new)} finding(s) to {path}")
        return 0

    if args.json:
        json.dump(report.to_dict(), sys.stdout, indent=1)
        print()
    else:
        for f in report.new:
            print(f.render())
        for ent in report.stale:
            print(f"{ent['path']}: [STALE baseline] {ent['rule']} x"
                  f"{ent['count']}: {ent['code']!r} — the finding is "
                  f"gone; remove the entry (or --write-baseline)")
        print(f"h2o3-lint: {report.files} files, "
              f"{len(report.rules)} rules, "
              f"{len(report.new)} new finding(s), "
              f"{len(report.baselined)} baselined, "
              f"{len(report.suppressed)} suppressed, "
              f"{len(report.stale)} stale baseline entr(ies)")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
