"""Ablation timing of the adaptive level kernel (deepest level, N=32).

Feeds nid2 back between fori_loop iterations so XLA can't hoist/CSE.
Each ablation removes one phase; the delta vs base is that phase's cost.

Multi-level mode (``LEVELS=1,2,4``): times the PACKED-code level body
(_kernel_bt shape: int8 codes, one-hot off the sublane repeat, ghw
contraction) chained L levels inside ONE jitted dispatch — the fused
window the streamed grower issues when H2O3_LEVELS_PER_PASS > 1. Per L
it reports ms/level plus the phase split from ablations: the one-hot
build share, the MXU contraction share (vs everything-else = VPU), the
routing share, and — comparing per-level time across L — the
dispatch-overhead share the fusion amortizes away. Runs under
H2O3_PALLAS_INTERPRET=1 at reduced ROWS for CPU smoke checks.
"""
import sys, os, time, functools
sys.path.insert(0, '/root/repo')

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from h2o3_tpu.ops.pallas_compat import CompilerParams as _CompilerParams

ROWS = 10_002_432
F, W, N = 28, 32, 32
TILE = 4096
REPS = 10
_VM = 100 * 1024 * 1024


def make_kernel(ablate):
    def kern(x_ref, nid_ref, ghw_ref, tabs_ref, loinv_ref, nid_out, hist_out,
             acc_ref):
        r = pl.program_id(0)

        @pl.when(r == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        x = x_ref[...]
        nid = nid_ref[0, :]
        n_prev = N // 2
        base = N - 1
        if ablate != "route":
            prev_base = base - n_prev
            lid_p = nid - prev_base
            onp = (jax.lax.broadcasted_iota(jnp.int32, (n_prev, TILE), 0)
                   == lid_p[None, :]).astype(jnp.bfloat16)
            lut3 = jax.lax.dot_general(tabs_ref[:, :n_prev], onp,
                                       (((1,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)
            lut = lut3[0:4] + lut3[4:8] * (1/256.) + lut3[8:12] * (1/65536.)
            f_r, t_r, nl_r, cn_r = lut[0], lut[1], lut[2], lut[3]
            fi = jax.lax.broadcasted_iota(jnp.int32, (TILE, F), 1)
            xsel = jnp.sum(jnp.where(fi == f_r.astype(jnp.int32)[:, None],
                                     x, 0.0), axis=1)
            gr_f = jnp.where(jnp.isnan(xsel), 1.0 - nl_r,
                             (xsel >= t_r).astype(jnp.float32))
            in_prev = (lid_p >= 0) & (lid_p < n_prev)
            child = 2 * nid + 1 + gr_f.astype(jnp.int32)
            nid = jnp.where(in_prev & (cn_r > 0.5), child, nid)
        nid_out[0, :] = nid

        lid = nid - base
        in_lvl = (lid >= 0) & (lid < N)
        lidc = jnp.where(in_lvl, lid, 0)
        onh = (jax.lax.broadcasted_iota(jnp.int32, (N, TILE), 0)
               == lidc[None, :])
        onh_f = onh.astype(jnp.float32) * in_lvl.astype(jnp.float32)[None, :]
        if ablate == "loinv":
            lo_r = jnp.full((TILE, F), -4.0, jnp.float32)
            inv_r = jnp.full((TILE, F), (W - 2) / 8.0, jnp.float32)
        else:
            onh_b = onh_f.astype(jnp.bfloat16)
            lr3 = jax.lax.dot_general(onh_b, loinv_ref[...],
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            lr = lr3[:, :2*F] + lr3[:, 2*F:4*F] * (1/256.) + lr3[:, 4*F:] * (1/65536.)
            lo_r = lr[:, :F]
            inv_r = lr[:, F:]
        bin_f = jnp.floor(jnp.clip((x - lo_r) * inv_r, 0.0, float(W - 2)))
        bin_v = jnp.where(jnp.isnan(x), float(W - 1), bin_f)
        if ablate == "sel":
            # skip the selector matmul: bogus b_all from a cheap broadcast
            b_all = jnp.broadcast_to(bin_v[:, :1], (TILE, F * W))
        else:
            sel = (jax.lax.broadcasted_iota(jnp.int32, (F, F * W), 1) // W
                   == jax.lax.broadcasted_iota(jnp.int32, (F, F * W), 0)
                   ).astype(jnp.bfloat16)
            b_all = jax.lax.dot_general(bin_v.astype(jnp.bfloat16), sel,
                                        (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)
        lane = jax.lax.broadcasted_iota(jnp.int32, (TILE, F * W), 1)
        if ablate == "onehot":
            oh = b_all.astype(jnp.bfloat16)  # skip compare, keep shape
        else:
            oh = ((lane % W).astype(jnp.float32) == b_all
                  ).astype(jnp.bfloat16)
        ghw = ghw_ref[...]
        if ablate == "left":
            left = jnp.broadcast_to(ghw[0, :].astype(jnp.bfloat16)[None, :],
                                    (3 * N, TILE))
        else:
            left = jnp.concatenate(
                [onh_f.astype(jnp.bfloat16) * ghw[k, :][None, :
                 ].astype(jnp.bfloat16) for k in range(3)], axis=0)
        if ablate == "matmul":
            acc_ref[...] += jnp.broadcast_to(
                oh[:1, :acc_ref.shape[1]] + left[0, 0], acc_ref.shape)
        else:
            acc_ref[...] += jax.lax.dot_general(
                left, oh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(r == ROWS // TILE - 1)
        def _flush():
            hist_out[...] = acc_ref[...]
    return kern


def run(ablate, X, nid0, ghw, tabs, loinv):
    kern = make_kernel(ablate)
    n_tiles = X.shape[0] // TILE

    def level(X, nid, ghw, tabs, loinv):
        nid2, hist = pl.pallas_call(
            kern,
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((TILE, F), lambda r: (r, 0)),
                pl.BlockSpec((1, TILE), lambda r: (0, r)),
                pl.BlockSpec((3, TILE), lambda r: (0, r)),
                pl.BlockSpec((12, N // 2), lambda r: (0, 0)),
                pl.BlockSpec((N, 6 * F), lambda r: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, TILE), lambda r: (0, r)),
                pl.BlockSpec((3 * N, F * W), lambda r: (0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((1, X.shape[0]), jnp.int32),
                jax.ShapeDtypeStruct((3 * N, F * W), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((3 * N, F * W), jnp.float32)],
            cost_estimate=(pl.CostEstimate(
                flops=2 * 3 * N * F * W * X.shape[0],
                bytes_accessed=X.shape[0] * F * 4 + X.shape[0] * 16,
                transcendentals=0) if os.environ.get("COST") else None),
            compiler_params=_CompilerParams(vmem_limit_bytes=_VM),
        )(X, nid[None, :], ghw, tabs, loinv)
        return nid2[0], hist

    def loop(X, nid, ghw, tabs, loinv):
        def body(i, carry):
            nid_c, acc = carry
            nid2, hist = level(X, nid_c, ghw, tabs, loinv)
            return (jnp.abs(nid2) % (2 * N - 1) + (N - 1) - N // 2,
                    acc + hist[0, 0])
        return jax.lax.fori_loop(0, REPS, body, (nid0, 0.0))

    f = jax.jit(loop)
    out = f(X, nid0, ghw, tabs, loinv)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = f(X, nid0, ghw, tabs, loinv)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / REPS


# ------------------------------------------------------------- levels
# Multi-level fused ablation (packed codes): the production streamed
# grower's window shape — L binned levels traced into one executable,
# nid carried on device between them.

LN, LF, LW = 32, 28, 16          # deepest level, features, packed bins


def make_packed_kernel(ablate, tile, n_tiles, mxu_dtype=jnp.bfloat16):
    from h2o3_tpu.ops.hist_adaptive import _route_bt

    def kern(c_ref, nid_ref, ghw_ref, tabs_ref, nid_out, hist_out, acc_ref):
        r = pl.program_id(0)

        @pl.when(r == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        cf = c_ref[...].astype(jnp.int32).astype(jnp.float32)  # [F, tile]
        nid = nid_ref[0, :]
        if ablate != "route":
            nid = _route_bt(cf, nid, tabs_ref, LN // 2, LN - 1, tile,
                            LF, LW)
        nid_out[0, :] = nid
        lid = nid - (LN - 1)
        in_lvl = (lid >= 0) & (lid < LN)
        lidm = jnp.where(in_lvl, lid, -1)
        onh_m = (jax.lax.broadcasted_iota(jnp.int32, (LN, tile), 0)
                 == lidm[None, :]).astype(mxu_dtype)
        b_all = jnp.repeat(cf, LW, axis=0)                 # [F*W, tile]
        if ablate == "onehot":
            oh_t = b_all.astype(mxu_dtype)   # keep repeat, skip compare
        else:
            brow = jax.lax.broadcasted_iota(jnp.int32, (LF * LW, tile), 0)
            oh_t = ((brow % LW).astype(jnp.float32) == b_all
                    ).astype(mxu_dtype)
        ghw_m = ghw_ref[...].astype(mxu_dtype)
        left = jnp.concatenate(
            [onh_m * ghw_m[k, :][None, :] for k in range(3)], axis=0)
        if ablate == "matmul":
            acc_ref[...] += jnp.broadcast_to(oh_t[0, 0] + left[0, 0],
                                             acc_ref.shape)
        else:
            acc_ref[...] += jax.lax.dot_general(
                left, oh_t, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(r == n_tiles - 1)
        def _flush():
            hist_out[...] = acc_ref[...]
    return kern


def run_levels(L, ablate, ct, nid0, ghw, tabs, tile, interp):
    rows = ct.shape[1]
    n_tiles = rows // tile
    kern = make_packed_kernel(ablate, tile, n_tiles)
    np1 = tabs.shape[1]

    def level(ct, nid, ghw, tabs):
        nid2, hist = pl.pallas_call(
            kern,
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((LF, tile), lambda r: (0, r)),
                pl.BlockSpec((1, tile), lambda r: (0, r)),
                pl.BlockSpec((3, tile), lambda r: (0, r)),
                pl.BlockSpec((12, np1), lambda r: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, tile), lambda r: (0, r)),
                pl.BlockSpec((3 * LN, LF * LW), lambda r: (0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((1, rows), jnp.int32),
                jax.ShapeDtypeStruct((3 * LN, LF * LW), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((3 * LN, LF * LW), jnp.float32)],
            compiler_params=_CompilerParams(vmem_limit_bytes=_VM),
            interpret=interp,
        )(ct, nid[None, :], ghw, tabs)
        return nid2[0], hist

    def window(ct, nid, ghw, tabs):
        # L levels, ONE dispatch: nid feeds forward (renormalized into
        # the parent band so routing stays live and XLA can't CSE)
        hist = None
        for _ in range(L):
            nid2, hist = level(ct, nid, ghw, tabs)
            nid = (jnp.abs(nid2) % (2 * LN - 1)
                   + (LN - 1) - LN // 2)
        return nid, hist[0, 0]

    f = jax.jit(window)
    reps = max(1, REPS // L)
    nid, s = f(ct, nid0, ghw, tabs)
    jax.block_until_ready((nid, s))
    t0 = time.perf_counter()
    for _ in range(reps):
        nid, s = f(ct, nid, ghw, tabs)   # one host dispatch per window
    jax.block_until_ready((nid, s))
    return (time.perf_counter() - t0) / (reps * L)


def main_levels(levels):
    from h2o3_tpu.ops.hist_adaptive import _pack_tables, pallas_interpret
    interp = pallas_interpret()
    tile = int(os.environ.get("LTILE", 512 if interp else 8192))
    rows_d = 8 * tile if interp else 2_502_656
    rows = int(os.environ.get("LROWS", rows_d))
    rows -= rows % tile
    rng = np.random.default_rng(0)
    ct = jnp.asarray(rng.integers(0, LW - 1, size=(LF, rows)).astype(np.int8))
    ghw = jnp.stack([jnp.asarray(rng.normal(size=rows).astype(np.float32)),
                     jnp.ones(rows, jnp.float32),
                     jnp.ones(rows, jnp.float32)])
    n_prev = LN // 2
    nid0 = jnp.asarray((LN - 1 - n_prev
                        + rng.integers(0, n_prev, rows)).astype(np.int32))
    tabs = _pack_tables((
        jnp.asarray(rng.integers(0, LF, n_prev).astype(np.float32)),
        jnp.asarray(rng.integers(1, LW - 1, n_prev).astype(np.float32)),
        jnp.asarray((rng.random(n_prev) < 0.5).astype(np.float32)),
        jnp.ones(n_prev, jnp.float32)))
    per_l1 = None
    for L in levels:
        t = {}
        for ab in ("none", "route", "onehot", "matmul"):
            t[ab] = run_levels(L, ab, ct, nid0, ghw, tabs, tile, interp)
        base = t["none"]
        mxu = max(0.0, 1 - t["matmul"] / base)
        oneh = max(0.0, 1 - t["onehot"] / base)
        rout = max(0.0, 1 - t["route"] / base)
        extra = ""
        if L == 1:
            per_l1 = base
        elif per_l1:
            extra = (f"  dispatch-overhead saved vs L=1: "
                     f"{max(0.0, 1 - base / per_l1) * 100:5.1f}%")
        print(f"L={L}: {base*1000:8.3f} ms/level  "
              f"mxu {mxu:.2f} / vpu {1-mxu:.2f}  "
              f"onehot {oneh:.2f}  route {rout:.2f}{extra}", flush=True)


def main():
    from h2o3_tpu.ops.hist_adaptive import _split3_bf16
    rows = ROWS - (ROWS % TILE)
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(rows, F)).astype(np.float32))
    ghw = jnp.stack([jnp.asarray(rng.normal(size=rows).astype(np.float32)),
                     jnp.ones(rows, jnp.float32), jnp.ones(rows, jnp.float32)])
    n_prev = N // 2
    nid0 = jnp.asarray((N - 1 - n_prev
                        + rng.integers(0, n_prev, rows)).astype(np.int32))
    t4 = jnp.asarray(np.stack([
        rng.integers(0, F, n_prev).astype(np.float32),
        rng.normal(size=n_prev).astype(np.float32),
        (rng.random(n_prev) < 0.5).astype(np.float32),
        np.ones(n_prev, np.float32)]))
    tabs = _split3_bf16(t4, axis=0)
    lo = np.full((N, F), -4.0, np.float32)
    inv = np.full((N, F), (W - 2) / 8.0, np.float32)
    loinv = _split3_bf16(jnp.asarray(np.concatenate([lo, inv], 1)), axis=1)
    jax.device_get(jnp.sum(X[0]))
    base = None
    for ab in os.environ.get(
            "ABLATE", "none,route,loinv,sel,onehot,left,matmul").split(","):
        try:
            t = run(ab, X, nid0, ghw, tabs, loinv)
            if ab == "none":
                base = t
            delta = f"  (saves {1000*(base-t):6.2f} ms)" if base and ab != "none" else ""
            print(f"{ab:8s}: {t*1000:7.2f} ms/level{delta}", flush=True)
        except Exception as e:
            print(f"{ab:8s}: FAILED {type(e).__name__} {str(e)[:150]}",
                  flush=True)


if __name__ == "__main__":
    lv = os.environ.get("LEVELS")
    if lv:
        main_levels([max(1, int(x)) for x in lv.split(",")])
    else:
        main()
