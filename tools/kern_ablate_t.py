"""Ablation timing of the TRANSPOSED adaptive level kernel (deepest
level, N=32) — where does a level's time go?

Variants knock out one phase each; delta vs full = that phase's cost.
Feeds nid2 back between fori_loop reps so XLA can't hoist/CSE (memory:
axon microbench pitfalls).  Run: python tools/kern_ablate_t.py
"""
import sys, os, time, functools
sys.path.insert(0, '/root/repo')

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from h2o3_tpu.ops.pallas_compat import CompilerParams as _CompilerParams

ROWS = int(__import__("os").environ.get("ROWS", 2_500_608))
F, W, N = 28, 32, int(os.environ.get("N", 32))
TILE = int(os.environ.get("TILE", 8192))
REPS = int(os.environ.get("REPS", 40))
_VM = 100 * 1024 * 1024


def _unsplit3(p_hi, p_mid, p_lo):
    return p_hi + (p_mid * (1 / 256.) + p_lo * (1 / 65536.))


def make_kernel(ablate):
    n_prev = max(N // 2, 1)
    base = N - 1

    def kern(x_ref, nid_ref, ghw_ref, tabs_ref, loinv_ref, nid_out,
             hist_out, acc_ref):
        r = pl.program_id(0)

        @pl.when(r == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        xt = x_ref[...]                              # [F, TILE]
        nid = nid_ref[0, :]
        if ablate != "route":
            prev_base = base - n_prev
            lid_p = nid - prev_base
            onp = (jax.lax.broadcasted_iota(jnp.int32, (n_prev, TILE), 0)
                   == lid_p[None, :]).astype(jnp.bfloat16)
            lut3 = jax.lax.dot_general(tabs_ref[:, :n_prev], onp,
                                       (((1,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)
            lut = _unsplit3(lut3[0:4], lut3[4:8], lut3[8:12])
            f_r, t_r, nl_r, cn_r = lut[0], lut[1], lut[2], lut[3]
            fi = jax.lax.broadcasted_iota(jnp.int32, (F, TILE), 0)
            xsel = jnp.sum(jnp.where(fi == f_r.astype(jnp.int32)[None, :],
                                     xt, 0.0), axis=0)
            gr_f = jnp.where(jnp.isnan(xsel), 1.0 - nl_r,
                             (xsel >= t_r).astype(jnp.float32))
            in_prev = (lid_p >= 0) & (lid_p < n_prev)
            child = 2 * nid + 1 + gr_f.astype(jnp.int32)
            nid = jnp.where(in_prev & (cn_r > 0.5), child, nid)
        nid_out[0, :] = nid

        lid = nid - base
        in_lvl = (lid >= 0) & (lid < N)
        lidc = jnp.where(in_lvl, lid, 0)
        onh = (jax.lax.broadcasted_iota(jnp.int32, (N, TILE), 0)
               == lidc[None, :])
        onh_f = onh.astype(jnp.float32) * in_lvl.astype(jnp.float32)[None, :]
        onh_b = onh_f.astype(jnp.bfloat16)
        if ablate != "ranges":
            lr3 = jax.lax.dot_general(loinv_ref[...], onh_b,
                                      (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            lr = _unsplit3(lr3[:2 * F], lr3[2 * F:4 * F], lr3[4 * F:])
            lo_r = lr[:F]
            inv_r = lr[F:]
        else:
            lo_r = jnp.zeros((F, TILE), jnp.float32) - 4.0
            inv_r = jnp.zeros((F, TILE), jnp.float32) + 3.75
        bin_f = jnp.floor(jnp.clip((xt - lo_r) * inv_r, 0.0, float(W - 2)))
        bin_v = jnp.where(jnp.isnan(xt), float(W - 1), bin_f)
        if ablate == "onehot":
            # skip the [F*W, TILE] build: reuse a cheap broadcast of bin row
            oh_t = jnp.broadcast_to(bin_v[:1, :], (F * W, TILE)
                                    ).astype(jnp.bfloat16)
        elif ablate == "repeat":
            # keep compare, skip sublane repeat (compare vs single row)
            brow = jax.lax.broadcasted_iota(jnp.int32, (F * W, TILE), 0)
            oh_t = ((brow % W).astype(jnp.float32)
                    == jnp.broadcast_to(bin_v[:1, :], (F * W, TILE))
                    ).astype(jnp.bfloat16)
        else:
            b_all = jnp.repeat(bin_v, W, axis=0)
            brow = jax.lax.broadcasted_iota(jnp.int32, (F * W, TILE), 0)
            oh_t = ((brow % W).astype(jnp.float32) == b_all
                    ).astype(jnp.bfloat16)
        ghw = ghw_ref[...]
        left = jnp.concatenate(
            [onh_f.astype(jnp.bfloat16) * ghw[k, :][None, :
             ].astype(jnp.bfloat16) for k in range(3)], axis=0)
        if ablate != "matmul":
            acc_ref[...] += jax.lax.dot_general(
                left, oh_t, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            acc_ref[...] += (jnp.sum(left, axis=1, keepdims=True)
                             + jnp.sum(oh_t.astype(jnp.float32)))

        @pl.when(r == REPS * 0 + (ROWS // TILE) - 1)
        def _flush():
            hist_out[...] = acc_ref[...]

    return kern


def run(ablate):
    n_tiles = ROWS // TILE
    kern = make_kernel(ablate)
    call = pl.pallas_call(
        kern,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((F, TILE), lambda r: (0, r)),
            pl.BlockSpec((1, TILE), lambda r: (0, r)),
            pl.BlockSpec((3, TILE), lambda r: (0, r)),
            pl.BlockSpec((12, N // 2), lambda r: (0, 0)),
            pl.BlockSpec((6 * F, N), lambda r: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, TILE), lambda r: (0, r)),
            pl.BlockSpec((3 * N, F * W), lambda r: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, ROWS), jnp.int32),
            jax.ShapeDtypeStruct((3 * N, F * W), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((3 * N, F * W), jnp.float32)],
        compiler_params=_CompilerParams(vmem_limit_bytes=_VM),
    )

    rng = np.random.default_rng(0)
    import time as _t; _t0=_t.time()
    xt = jnp.asarray(rng.normal(size=(F, ROWS)).astype(np.float32))
    jax.block_until_ready(xt); print(f"  xfer {ROWS*F*4/1e6:.0f}MB in {_t.time()-_t0:.1f}s", flush=True)
    prev_base = (N - 1) - max(N // 2, 1)
    nid0 = jnp.asarray(rng.integers(prev_base, prev_base + max(N // 2, 1),
                                    ROWS).astype(np.int32))
    ghw = jnp.asarray(rng.normal(size=(3, ROWS)).astype(np.float32))
    tabs = jnp.asarray(rng.normal(size=(12, N // 2)).astype(np.float32)
                       ).astype(jnp.bfloat16)
    loinv = jnp.asarray(rng.normal(size=(6 * F, N)).astype(np.float32)
                        ).astype(jnp.bfloat16)

    @jax.jit
    def loop(xt, nid, ghw, tabs, loinv):
        # arrays ride as ARGUMENTS: closing over them embeds 280MB of
        # constants in the program, which the axon remote-compile
        # endpoint rejects with HTTP 413
        def body(i, carry):
            nid, acc = carry
            nid2, hist = call(xt, nid[None, :], ghw, tabs, loinv)
            # feed nid back (mod to keep in prev-level range) so no CSE
            n_prev = max(N // 2, 1)
            pb = (N - 1) - n_prev
            nid = jnp.clip(nid2[0] % n_prev + pb, pb, pb + n_prev - 1)
            return nid, acc + hist[0, 0]
        return jax.lax.fori_loop(0, REPS, body, (nid, 0.0))

    tw = time.time()
    out = loop(xt, nid0, ghw, tabs, loinv)
    _ = float(jax.device_get(out[1]))      # force full execution round-trip
    print(f"  warm(compile+run) {time.time()-tw:.1f}s", flush=True)
    # time with DIFFERENT inputs (the warmup's output nid) — identical
    # repeat requests can be served from a cache layer on axon
    nid1 = out[0]
    t0 = time.time()
    out2 = loop(xt, nid1, ghw, tabs, loinv)
    _ = float(jax.device_get(out2[1]))
    dt = (time.time() - t0) / REPS
    return dt


if __name__ == "__main__":
    names = ["full", "route", "ranges", "repeat", "onehot", "matmul"]
    if len(sys.argv) > 1:
        names = sys.argv[1:]
    base = None
    for n in names:
        dt = run(n)
        if n == "full":
            base = dt
        extra = (f"  delta={1000*(base-dt):+.2f}ms"
                 if base is not None and n != "full" else "")
        print(f"{n:8s}: {dt*1000:7.2f} ms/level "
              f"({ROWS/dt/1e6:7.1f} M rows/s){extra}", flush=True)
