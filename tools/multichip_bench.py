"""Multi-chip GBM scaling bench — rows/s/chip at n_devices ∈ {1, 4, 8}.

The SPMD default path (ISSUE 7) claims near-linear rows/s scaling across
the mesh; this round ASSERTS it instead of eyeballing: the same
HIGGS-shaped train runs on meshes carved from 1, 4 and 8 devices, each
frame rebuilt under its mesh (Frame.resharded), and the verdict compares
rows/s/chip at 8 devices against the single-device number
(``scaling_efficiency_8 >= 0.7`` is the acceptance bar).

On a host without 8 accelerator devices the tool forces 8 VIRTUAL CPU
devices (``--xla_force_host_platform_device_count=8``) so the sharded
code path still runs end-to-end — but virtual devices share one host's
cores, so aggregate throughput physically cannot scale; the verdict is
then reported as ``informational`` (basis=cpu-virtual-devices) rather
than a fake pass/fail. On a real TPU mesh the verdict is enforced.

Runs standalone (``python tools/multichip_bench.py``) or as the
``multichip`` round bench.py spawns. Prints ONE JSON line on stdout.

Env knobs: H2O3_MC_ROWS (default 1M TPU / 120k CPU), H2O3_MC_TREES (10),
H2O3_MC_DEPTH (6), H2O3_MC_NBINS (14), H2O3_MC_MIN_EFF (0.7).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# force the virtual 8-device CPU mesh BEFORE jax import when the host
# has no accelerator fleet (the parent bench may run single-chip)
if os.environ.get("JAX_PLATFORMS", "") in ("", "cpu") and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    import jax
    import h2o3_tpu as h2o
    from h2o3_tpu.cluster_boot import setup_compilation_cache
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.parallel.mesh import current_mesh, make_mesh, set_mesh

    setup_compilation_cache()
    backend = jax.default_backend()
    n_dev = len(jax.devices())
    rows = int(os.environ.get(
        "H2O3_MC_ROWS", 1_000_000 if backend == "tpu" else 120_000))
    trees = int(os.environ.get("H2O3_MC_TREES", 10))
    depth = int(os.environ.get("H2O3_MC_DEPTH", 6))
    nbins = int(os.environ.get("H2O3_MC_NBINS", 14))
    min_eff = float(os.environ.get("H2O3_MC_MIN_EFF", 0.7))
    log(f"backend={backend} devices={n_dev} rows={rows} trees={trees}")

    rng = np.random.default_rng(42)
    F = 28
    X = rng.normal(size=(rows, F)).astype(np.float32)
    logit = (X[:, 0] * 1.5 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
             + 0.3 * np.sin(3 * X[:, 4]))
    y = (rng.random(rows) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float32)
    cols = {f"f{i}": X[:, i] for i in range(F)}
    cols["label"] = y
    base_fr = h2o.Frame.from_numpy(cols)

    params = dict(ntrees=trees, max_depth=depth, nbins=nbins,
                  learn_rate=0.1, distribution="bernoulli", seed=7,
                  min_rows=1.0, score_tree_interval=0, stopping_rounds=0,
                  histogram_type="random")
    points = []
    old_mesh = current_mesh()
    try:
        for n in (1, 4, 8):
            if n > n_dev:
                log(f"n_devices={n}: skipped (only {n_dev} devices)")
                continue
            mesh = make_mesh(n_data=n, n_model=1,
                             devices=jax.devices()[:n])
            set_mesh(mesh)
            fr = base_fr.resharded(mesh)
            # warm the executables at this mesh's shapes, then measure
            warm = H2OGradientBoostingEstimator(**params)
            warm.train(y="label", training_frame=fr)
            gbm = H2OGradientBoostingEstimator(**params)
            t0 = time.time()
            gbm.train(y="label", training_frame=fr)
            total = time.time() - t0
            m = gbm.model
            assert m.output["spmd"]["n_data"] == n, m.output["spmd"]
            loop_s = m.output["training_loop_seconds"]
            rps = rows * m.ntrees_built / loop_s
            # collective/straggler attribution (ISSUE 8): when the
            # scaling verdict fails, these say whether the loss is a
            # straggling shard or barrier wait — per device count
            coll = m.output["spmd"].get("collective") or {}
            points.append({
                "n_devices": n, "loop_s": round(loop_s, 3),
                "warm_train_s": round(total, 3),
                "rows_per_sec": round(rps, 1),
                "rows_per_sec_per_chip": round(rps / n, 1),
                "auc": round(float(m.training_metrics.auc), 4),
                "straggler_ratio": coll.get("straggler_ratio"),
                "collective_wait_share": coll.get("collective_wait_share"),
                "collective_wait_ms": coll.get("collective_wait_ms")})
            log(f"n={n}: loop={loop_s:.2f}s rows/s={rps:,.0f} "
                f"({rps / n:,.0f}/chip) AUC={points[-1]['auc']} "
                f"straggler={coll.get('straggler_ratio')} "
                f"wait_share={coll.get('collective_wait_share')}")
    finally:
        set_mesh(old_mesh)

    out = {"metric": "multichip_gbm_scaling", "backend": backend,
           "rows": rows, "trees": trees, "depth": depth, "nbins": nbins,
           "points": points, "min_efficiency": min_eff}
    # headline attribution from the WIDEST measured mesh — so a scaling
    # regression is explainable from the BENCH/MULTICHIP JSON alone
    widest = max((p for p in points
                  if p.get("straggler_ratio") is not None),
                 key=lambda p: p["n_devices"], default=None)
    if widest is not None:
        out["straggler_ratio"] = widest["straggler_ratio"]
        out["collective_wait_share"] = widest["collective_wait_share"]
    per_chip = {p["n_devices"]: p["rows_per_sec_per_chip"] for p in points}
    if 1 in per_chip and 8 in per_chip:
        eff = per_chip[8] / per_chip[1]
        out["scaling_efficiency_8"] = round(eff, 4)
        if backend == "tpu":
            out["verdict"] = "pass" if eff >= min_eff else "fail"
        else:
            # 8 virtual CPU devices share one host's cores: aggregate
            # throughput cannot scale, so an efficiency number here is
            # a code-path check, not a hardware claim — never a fake
            # pass (or fail) against the >=70% bar
            out["verdict"] = "informational"
            out["basis"] = "cpu-virtual-devices"
    else:
        out["verdict"] = "skipped"
        out["basis"] = f"only {n_dev} devices"
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
