#!/usr/bin/env python
"""Chaos sweep — run train + serve under injected faults and report
recovery metrics.

The robustness analog of the transfer-budget guard: instead of
eyeballing "retries work", a chaos round drives the real pipelines
through the deterministic fault layer (h2o3_tpu/faults.py) and emits::

    resilience.recovered_total    retries that ended in success
    resilience.recovery_p50_ms    median first-failure → recovery time
    resilience.degraded_trains    dense→streamed OOM degradations
    resilience.circuit_opens      serve circuit-open transitions
    resilience.faults_injected    total faults the layer raised
    resilience.ckpt_resume_ok     mid-train kill → checkpoint resume
                                  produced the bit-identical model

Usage::

    JAX_PLATFORMS=cpu python tools/chaos_sweep.py           # standalone
    # bench.py runs the same round via run_chaos_round() unless
    # H2O3_BENCH_CHAOS=0

The sweep sizes itself small (seconds, not minutes): it guards the
RECOVERY machinery, not throughput — BENCH_*.json keeps the speed
story.
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _counter(reg, name, labels=None):
    return reg.value(name, labels)


def _recovery_p50_ms(reg):
    """Median recovery latency across every site's h2o3_recovery_ms
    histogram (bucket-interpolated — good enough for a guard)."""
    samples = []
    for s in reg.samples():
        if s["name"] != "h2o3_recovery_ms" or s.get("kind") != "histogram":
            continue
        prev_le, prev_cum = 0.0, 0
        for le, cum in s["buckets"]:
            fresh = cum - prev_cum
            if fresh > 0:
                mid = prev_le + (min(le, prev_le * 2 + 10) - prev_le) / 2 \
                    if le != float("inf") else prev_le
                samples.extend([mid] * fresh)
            prev_le, prev_cum = le, cum
    return round(float(np.median(samples)), 2) if samples else None


def run_chaos_round(rows: int = 2000, log=print) -> dict:
    """Run the sweep with a hard guarantee that fault injection is
    DISARMED on every exit path — bench.py swallows chaos-round
    exceptions, and a leaked spec would corrupt everything the process
    runs afterwards while looking organic."""
    from h2o3_tpu import faults
    try:
        return _chaos_round(rows, log)
    finally:
        faults.configure(None)


def _chaos_round(rows: int, log) -> dict:
    import jax

    import h2o3_tpu as h2o
    from h2o3_tpu import dkv, faults, serve, telemetry
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator as GBM

    reg = telemetry.registry()

    def retries_total():
        return sum(s["value"] for s in reg.samples()
                   if s["name"] == "h2o3_retry_total")

    def injected_total():
        return sum(s["value"] for s in reg.samples()
                   if s["name"] == "h2o3_fault_injected_total")

    def circuit_opens():
        return sum(s["value"] for s in reg.samples()
                   if s["name"] == "h2o3_circuit_open_total")

    r0, i0, c0 = retries_total(), injected_total(), circuit_opens()
    d0 = _counter(reg, "h2o3_degrade_total", {"algo": "gbm"})

    rng = np.random.default_rng(42)
    cols = {f"f{i}": rng.normal(size=rows) for i in range(6)}
    cols["y"] = (cols["f0"] * 2 - cols["f1"]
                 + rng.normal(size=rows) * 0.1)
    fr = h2o.Frame.from_numpy(cols)
    kw = dict(ntrees=10, max_depth=3, seed=13, learn_rate=0.2)

    # reference run (fault-free) for the bit-parity verdicts
    ref = GBM(**kw)
    ref.train(y="y", training_frame=fr)

    def trees_equal(a, b):
        for k in ("_feat", "_thr", "_value"):
            ea = np.asarray(jax.device_get(getattr(a, k)))
            eb = np.asarray(jax.device_get(getattr(b, k)))
            if ea.shape != eb.shape or not (ea == eb).all():
                return False
        return True

    # 1) transient h2d + execute faults: an ingest under h2d faults
    #    parses correctly, a train under execute faults completes via
    #    retries, bit-identical to the reference
    faults.configure("h2d:every=2:times=2:exc=Unavailable,"
                     "execute@train:every=1:times=2:exc=Internal")
    fr2 = h2o.Frame.from_numpy(
        {"a": rng.normal(size=256), "b": rng.normal(size=256)})
    ingest_ok = bool(np.isfinite(fr2.vec("a").to_numpy()).all())
    t_train = GBM(**kw)
    t_train.train(y="y", training_frame=fr)
    transient_ok = ingest_ok and trees_equal(ref.model, t_train.model)
    faults.configure(None)

    # 2) mid-train kill → checkpoint resume, bit-identical
    ckdir = tempfile.mkdtemp(prefix="chaos_ckpt_")
    faults.configure("execute@train:every=1:after=1:times=1:exc=Fatal")
    killed = GBM(in_training_checkpoints_dir=ckdir,
                 in_training_checkpoints_tree_interval=3, **kw)
    resume_ok = False
    try:
        killed.train(y="y", training_frame=fr)
    except RuntimeError:
        pass
    faults.configure(None)
    ckpts = sorted(os.listdir(ckdir))
    if ckpts:
        resumed = GBM(checkpoint=os.path.join(ckdir, ckpts[-1]), **kw)
        resumed.train(y="y", training_frame=fr)
        resume_ok = trees_equal(ref.model, resumed.model)

    # 3) synthetic OOM → dense degrades to the streamed path
    faults.configure("execute@train:every=1:times=1:exc=ResourceExhausted")
    degraded = GBM(**kw)
    degraded.train(y="y", training_frame=fr)
    faults.configure(None)
    degraded_ok = bool(degraded.model.output.get("streamed"))

    # 4) serve: persistently failing deployment trips the breaker and
    #    recovers once the fault clears
    dkv.put("chaos_model", "model", ref.model)
    dep = serve.deploy("chaos_model", circuit_failures=2,
                       circuit_open_ms=150, max_delay_ms=1.0)
    row = {f"f{i}": 0.1 * i for i in range(6)}
    faults.configure("execute@serve:key=chaos_model:every=1:exc=Internal")
    circuit_opened = False
    for _ in range(6):
        try:
            dep.predict_rows([row], timeout_ms=500)
        except serve.ServeCircuitOpenError:
            circuit_opened = True
            break
        except Exception:   # noqa: BLE001 — injected device errors
            pass
    faults.configure(None)
    time.sleep(0.2)
    served_after = None
    try:
        served_after = dep.predict_rows([row])[0]
    except Exception:   # noqa: BLE001
        pass
    serve.undeploy("chaos_model")
    dkv.remove("chaos_model")

    out = {
        "recovered_total": round(retries_total() - r0),
        "recovery_p50_ms": _recovery_p50_ms(reg),
        "degraded_trains": round(
            _counter(reg, "h2o3_degrade_total", {"algo": "gbm"}) - d0),
        "circuit_opens": round(circuit_opens() - c0),
        "faults_injected": round(injected_total() - i0),
        "transient_train_bit_identical": transient_ok,
        "ckpt_resume_ok": resume_ok,
        "oom_degrade_ok": degraded_ok,
        "circuit_lifecycle_ok": bool(circuit_opened
                                     and served_after is not None),
    }
    ok = all(out[k] for k in ("transient_train_bit_identical",
                              "ckpt_resume_ok", "oom_degrade_ok",
                              "circuit_lifecycle_ok"))
    out["ok"] = ok
    log(f"chaos sweep: {'PASS' if ok else 'FAIL'} {out}")
    return out


def main():
    out = {"resilience": run_chaos_round(
        log=lambda *a: print(*a, file=sys.stderr))}
    print(json.dumps(out, indent=2))
    sys.exit(0 if out["resilience"]["ok"] else 1)


if __name__ == "__main__":
    main()
