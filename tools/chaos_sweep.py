#!/usr/bin/env python
"""Chaos sweep — run train + serve under injected faults and report
recovery metrics.

The robustness analog of the transfer-budget guard: instead of
eyeballing "retries work", a chaos round drives the real pipelines
through the deterministic fault layer (h2o3_tpu/faults.py) and emits::

    resilience.recovered_total    retries that ended in success
    resilience.recovery_p50_ms    median first-failure → recovery time
    resilience.degraded_trains    dense→streamed OOM degradations
    resilience.circuit_opens      serve circuit-open transitions
    resilience.faults_injected    total faults the layer raised
    resilience.ckpt_resume_ok     mid-train kill → checkpoint resume
                                  produced the bit-identical model
    resilience.recovered_after_restart
                                  kill -9 of the WORKER PROCESS mid-
                                  train → fresh-process boot recovery
                                  resumed a bit-identical model
                                  (ISSUE 9; --kill-process /
                                  H2O3_BENCH_CHAOS_KILL)
    resilience.restart_recovery_s boot-scan → resumed-model wall time

Usage::

    JAX_PLATFORMS=cpu python tools/chaos_sweep.py           # standalone
    JAX_PLATFORMS=cpu python tools/chaos_sweep.py --kill-process
    # bench.py runs the same round via run_chaos_round() unless
    # H2O3_BENCH_CHAOS=0; the process-kill round rides along unless
    # H2O3_BENCH_CHAOS_KILL=0

The sweep sizes itself small (seconds, not minutes): it guards the
RECOVERY machinery, not throughput — BENCH_*.json keeps the speed
story. (The process-kill round pays one extra interpreter+jax start.)
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import textwrap
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the process-kill probe's train: constants shared by the killed child
# and the parent's uninterrupted reference so bit-parity is well-defined
_KILL_MODEL_KEY = "chaos_restart_gbm"
_KILL_PARAMS = dict(ntrees=40, max_depth=3, seed=13, learn_rate=0.2,
                    in_training_checkpoints_tree_interval=2)


def _counter(reg, name, labels=None):
    return reg.value(name, labels)


def _recovery_p50_ms(reg):
    """Median recovery latency across every site's h2o3_recovery_ms
    histogram (bucket-interpolated — good enough for a guard)."""
    samples = []
    for s in reg.samples():
        if s["name"] != "h2o3_recovery_ms" or s.get("kind") != "histogram":
            continue
        prev_le, prev_cum = 0.0, 0
        for le, cum in s["buckets"]:
            fresh = cum - prev_cum
            if fresh > 0:
                mid = prev_le + (min(le, prev_le * 2 + 10) - prev_le) / 2 \
                    if le != float("inf") else prev_le
                samples.extend([mid] * fresh)
            prev_le, prev_cum = le, cum
    return round(float(np.median(samples)), 2) if samples else None


def _trees_equal(a, b) -> bool:
    import jax
    for k in ("_feat", "_thr", "_value"):
        ea = np.asarray(jax.device_get(getattr(a, k)))
        eb = np.asarray(jax.device_get(getattr(b, k)))
        if ea.shape != eb.shape or not (ea == eb).all():
            return False
    return True


def run_kill_process_round(rows: int = 2000, log=print,
                           kill_deadline_s: float = 300.0) -> dict:
    """The restart-recovery probe (ISSUE 9): SIGKILL a WORKER PROCESS
    mid-train, then run the boot-time recovery scan in this (fresh,
    relative to the dead worker) process and assert the resumed model
    is bit-identical to an uninterrupted train on the same data.

    The child is forced onto the SAME virtual-device count as this
    process: the sharded histogram psum's accumulation order is part of
    the bit-parity contract, so the killed train's committed prefix
    must have been built under the mesh the resume continues on.
    ``ran`` in the result says whether the probe actually exercised
    recovery — a benign skip (child finished before the first
    checkpoint, or this process is on a real accelerator the child
    cannot share, so its CPU-built tree prefix would not be
    bit-comparable) must not read as a recovery failure."""
    import jax
    out = {"ran": False, "recovered_after_restart": False,
           "restart_recovery_s": None}
    if jax.default_backend() != "cpu":
        log("kill-process round: skipped — the child runs on CPU and "
            f"this process is on {jax.default_backend()}; cross-backend "
            "tree prefixes are not bit-comparable")
        return out
    base = tempfile.mkdtemp(prefix="chaos_restart_")
    recdir = os.path.join(base, "recovery")
    ckdir = os.path.join(base, "ckpts")
    os.makedirs(ckdir, exist_ok=True)
    env = dict(os.environ, H2O3_RECOVERY_DIR=recdir, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_"
                            f"count={len(jax.devices())}").strip()
    child_src = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {_REPO!r})
        import numpy as np
        import h2o3_tpu as h2o
        from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
        rng = np.random.default_rng(42)
        rows = {rows}
        cols = {{f"f{{i}}": rng.normal(size=rows) for i in range(6)}}
        cols["y"] = (cols["f0"] * 2 - cols["f1"]
                     + rng.normal(size=rows) * 0.1)
        fr = h2o.Frame.from_numpy(cols)
        est = H2OGradientBoostingEstimator(
            model_id={_KILL_MODEL_KEY!r},
            in_training_checkpoints_dir={ckdir!r}, **{_KILL_PARAMS!r})
        est.train(y="y", training_frame=fr)
        print("CHILD_DONE", flush=True)
    """)
    proc = subprocess.Popen([sys.executable, "-c", child_src], env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    killed = False
    deadline = time.time() + kill_deadline_s
    try:
        while time.time() < deadline:
            if any(fn.endswith(".zip") for fn in os.listdir(ckdir)):
                os.kill(proc.pid, signal.SIGKILL)   # no cleanup, no flush
                killed = True
                break
            if proc.poll() is not None:
                break
            time.sleep(0.2)
    finally:
        if proc.poll() is None and not killed:
            proc.kill()
        proc.wait()
    if not killed:
        log("kill-process round: child finished or died before the "
            "first checkpoint — nothing to recover")
        return out
    prev = os.environ.get("H2O3_RECOVERY_DIR")
    os.environ["H2O3_RECOVERY_DIR"] = recdir
    try:
        from h2o3_tpu import dkv, recovery
        from h2o3_tpu.persist import load_frame
        entries, _corrupt = recovery.scan()
        if not entries:
            # the kill can land AFTER the child's train completed
            # (manifest already dropped deliberately) — a benign race,
            # not a recovery failure; ran stays False
            log("kill-process round: no manifest survived the kill "
                "(train likely completed first) — nothing to recover")
            return out
        out["ran"] = True
        frame_path = entries[0]["frame_path"]
        t0 = time.time()
        rep = recovery.recover_at_boot(wait=True)
        out["restart_recovery_s"] = round(time.time() - t0, 3)
        if not rep["resumed"]:
            log(f"kill-process round: resume failed: {rep['failed']}")
            return out
        resumed = dkv.get(_KILL_MODEL_KEY, "model")
        from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
        ref = H2OGradientBoostingEstimator(**_KILL_PARAMS)
        ref.train(y="y", training_frame=load_frame(frame_path))
        out["recovered_after_restart"] = _trees_equal(ref.model, resumed)
        out["resumed_from_trees"] = rep["resumed"][0].get("ckpt_trees")
        dkv.remove(_KILL_MODEL_KEY)
    finally:
        if prev is None:
            os.environ.pop("H2O3_RECOVERY_DIR", None)
        else:
            os.environ["H2O3_RECOVERY_DIR"] = prev
    log(f"kill-process round: "
        f"{'PASS' if out['recovered_after_restart'] else 'FAIL'} {out}")
    return out


# ------------------------------------------------- kill-replica round
#
# The fleet front door's chaos probe (ISSUE 13): N real serve-replica
# PROCESSES join the parent's router over REST, traffic flows through
# consistent-hash routing, and one replica is SIGKILLed mid-traffic.
# Asserted: the router sheds the dead replica within ~one heartbeat
# interval, rebalances onto the survivors, and no request started
# after the shed window fails (single failover absorbs the in-flight
# casualties). Recorded in bench.py as
# fleet.{replicas,rows_per_sec,shed_ms,rebalance_ok}.

_FLEET_MODEL_KEY = "chaos_fleet_gbm"
_FLEET_PARAMS = dict(ntrees=8, max_depth=3, seed=17, learn_rate=0.2,
                     min_rows=1.0)
_FLEET_ROWS = 1500


def _fleet_child_src(repo: str, router_port: int) -> str:
    """One serve replica: train the deterministic model, deploy, start
    a REST surface, join the fleet via the agent (seeds env), park."""
    return textwrap.dedent(f"""
        import sys, threading
        sys.path.insert(0, {repo!r})
        import numpy as np
        import h2o3_tpu as h2o
        from h2o3_tpu import dkv, serve
        from h2o3_tpu.api.server import H2OApiServer
        from h2o3_tpu.fleet import FleetAgent
        from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
        rng = np.random.default_rng(21)
        n = {_FLEET_ROWS}
        a = rng.normal(size=n).astype(np.float32)
        b = rng.uniform(-2, 2, size=n).astype(np.float32)
        y = rng.random(n) < 1 / (1 + np.exp(-(a * 1.2 - b)))
        fr = h2o.Frame.from_numpy(dict(
            a=a, b=b, cls=np.where(y, "YES", "NO")))
        est = H2OGradientBoostingEstimator(**{_FLEET_PARAMS!r})
        est.train(y="cls", training_frame=fr)
        est.model.key = {_FLEET_MODEL_KEY!r}
        dkv.put(est.model.key, "model", est.model)
        serve.deploy(est.model.key, max_delay_ms=1.0, queue_limit=65536)
        srv = H2OApiServer(port=0).start()
        agent = FleetAgent(f"http://127.0.0.1:{{srv.port}}",
                           router_url="http://127.0.0.1:{router_port}")
        agent.start()
        print("REPLICA_READY", srv.port, flush=True)
        threading.Event().wait()
    """)


def run_kill_replica_round(replicas: int = 3, traffic_secs: float = 6.0,
                           clients: int = 6, log=print,
                           spawn_deadline_s: float = 300.0) -> dict:
    """SIGKILL one of N replica processes mid-traffic and measure the
    membership shed + router rebalance. ``ran=False`` results are
    benign skips (non-CPU parent — child tree bits would not be
    comparable), same contract as the kill-process round."""
    import queue as _q
    import threading

    import jax

    out = {"ran": False, "replicas": replicas, "rows_per_sec": None,
           "single_rows_per_sec": None, "speedup": None,
           "shed_ms": None, "shed_within_beat": None,
           "rebalance_ok": False, "failed_after_shed": None,
           "parity_ok": None, "ok": False}
    if jax.default_backend() != "cpu":
        log("kill-replica round: skipped — replica children run on CPU "
            f"and this process is on {jax.default_backend()}")
        out["ok"] = True          # a skip is not a failure
        return out
    import h2o3_tpu as h2o
    from h2o3_tpu import dkv, fleet, serve
    from h2o3_tpu.api.server import H2OApiServer
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    hb_ms = float(os.environ.get("H2O3_FLEET_BENCH_HB_MS", "500") or 500)
    prev_hb = os.environ.get("H2O3_FLEET_HEARTBEAT_MS")
    os.environ["H2O3_FLEET_HEARTBEAT_MS"] = str(hb_ms)
    fleet.reset()
    srv = H2OApiServer(port=0).start()
    router = fleet.router()
    procs = []
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   H2O3_FLEET_SEEDS=f"127.0.0.1:{srv.port}",
                   XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                              .replace("--xla_force_host_platform_"
                                       "device_count=8", "")).strip())
        src = _fleet_child_src(_REPO, srv.port)
        for _ in range(replicas):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", src], env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        # the parent's parity reference: the SAME deterministic train
        rng = np.random.default_rng(21)
        n = _FLEET_ROWS
        a = rng.normal(size=n).astype(np.float32)
        b = rng.uniform(-2, 2, size=n).astype(np.float32)
        yv = rng.random(n) < 1 / (1 + np.exp(-(a * 1.2 - b)))
        fr = h2o.Frame.from_numpy(dict(
            a=a, b=b, cls=np.where(yv, "YES", "NO")))
        est = H2OGradientBoostingEstimator(**_FLEET_PARAMS)
        est.train(y="cls", training_frame=fr)
        est.model.key = _FLEET_MODEL_KEY
        dkv.put(est.model.key, "model", est.model)
        dep = serve.deploy(est.model.key, max_delay_ms=1.0)
        rows = [{"a": float(a[i]), "b": float(b[i])} for i in range(64)]
        direct = dep.predict_rows(rows)
        # wait for every replica to join routable
        deadline = time.monotonic() + spawn_deadline_s
        while time.monotonic() < deadline:
            if len(router.table.live_members()) >= replicas:
                break
            if any(p.poll() is not None for p in procs):
                log("kill-replica round: a replica died during spawn")
                return out
            time.sleep(0.25)
        live = router.table.live_members()
        if len(live) < replicas:
            log(f"kill-replica round: only {len(live)}/{replicas} "
                f"replicas joined before the deadline — skipping")
            return out
        out["ran"] = True

        # parity probe: routed scoring == the parent's direct predict
        probe = router.predict_rows(_FLEET_MODEL_KEY, rows, key="p0")
        out["parity_ok"] = all(
            rr["label"] == dd["label"]
            and rr["classProbabilities"] == dd["classProbabilities"]
            for rr, dd in zip(probe["predictions"], direct))

        # single-replica baseline: same client count, one member pinned
        one = live[0]
        single_scored = [0] * clients
        stop_single = time.monotonic() + max(traffic_secs / 3, 1.5)

        def single_client(ci):
            i = 0
            while time.monotonic() < stop_single:
                try:
                    got = router._dispatch(one, _FLEET_MODEL_KEY, rows,
                                           time.monotonic() + 10.0)
                    single_scored[ci] += len(got["predictions"])
                except Exception:   # noqa: BLE001 — baseline best-effort
                    pass
                i += 1

        t0 = time.monotonic()
        ths = [threading.Thread(target=single_client, args=(ci,))
               for ci in range(clients)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        single_rps = sum(single_scored) / max(time.monotonic() - t0, 1e-9)
        out["single_rows_per_sec"] = round(single_rps, 1)

        # routed traffic across the fleet, with a mid-traffic SIGKILL
        import socket as _socket
        results: "_q.Queue" = _q.Queue()
        stop_at = time.monotonic() + traffic_secs
        kill_at = time.monotonic() + traffic_secs / 2
        victim = procs[1]
        victim_member = f"{victim.pid}@{_socket.gethostname()}"
        killed = {"t": None}
        shed = {"t": None}
        kill_mu = threading.Lock()

        def shed_monitor():
            """Stamp the instant the victim leaves the routed set —
            DURING traffic, so shed latency is measured, not the poll
            that happens to notice it afterwards."""
            while killed["t"] is None:
                if time.monotonic() > stop_at + 30:
                    return
                time.sleep(hb_ms / 1000.0 / 20)
            probe_deadline = killed["t"] + 30.0
            while time.monotonic() < probe_deadline:
                ids = {m.member_id for m in router.table.live_members()}
                if victim_member not in ids:
                    shed["t"] = time.monotonic()
                    return
                time.sleep(hb_ms / 1000.0 / 20)

        mon = threading.Thread(target=shed_monitor, daemon=True)
        mon.start()

        def client(ci):
            i = 0
            while time.monotonic() < stop_at:
                with kill_mu:
                    if killed["t"] is None and \
                            time.monotonic() >= kill_at:
                        os.kill(victim.pid, signal.SIGKILL)
                        killed["t"] = time.monotonic()
                t_start = time.monotonic()
                try:
                    got = router.predict_rows(
                        _FLEET_MODEL_KEY, rows, key=f"c{ci}-{i}",
                        timeout_ms=10_000)
                    results.put((t_start, len(got["predictions"]),
                                 got["_fleet"]["member"], None))
                except Exception as e:   # noqa: BLE001 — counted below
                    results.put((t_start, 0, None, repr(e)))
                i += 1

        t0 = time.monotonic()
        ths = [threading.Thread(target=client, args=(ci,))
               for ci in range(clients)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        elapsed = time.monotonic() - t0
        mon.join(timeout=35)
        t_kill = killed["t"] or time.monotonic()
        t_shed = shed["t"] if shed["t"] is not None \
            else time.monotonic()
        out["shed_ms"] = round((t_shed - t_kill) * 1e3, 1)
        out["shed_within_beat"] = bool(
            shed["t"] is not None
            and out["shed_ms"] <= 2.0 * hb_ms)  # 1 beat + detector slack
        recs = []
        while not results.empty():
            recs.append(results.get())
        scored = sum(r[1] for r in recs)
        out["rows_per_sec"] = round(scored / max(elapsed, 1e-9), 1)
        out["speedup"] = round(
            out["rows_per_sec"] / max(single_rps, 1e-9), 2)
        fails = [r for r in recs if r[3] is not None]
        # failures are only tolerated in the in-flight window
        # [kill, shed]: those requests raced the death; everything
        # after the shed must succeed (failover + rebalance)
        late = [r for r in fails if r[0] > t_shed]
        out["failed_total"] = len(fails)
        out["failed_after_shed"] = len(late)
        survivors = {r[2] for r in recs
                     if r[3] is None and r[0] > t_shed}
        out["rebalance_ok"] = bool(
            len(router.table.live_members()) == replicas - 1
            and scored > 0 and survivors
            and victim_member not in survivors)
        out["heartbeat_ms"] = hb_ms
        out["ok"] = bool(out["parity_ok"] and out["rebalance_ok"]
                         and out["failed_after_shed"] == 0
                         and out["shed_within_beat"])
        log(f"kill-replica round: {'PASS' if out['ok'] else 'FAIL'} "
            f"{out}")
        return out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:   # noqa: BLE001 — cleanup best-effort
                pass
        try:
            serve.undeploy(_FLEET_MODEL_KEY)
            dkv.remove(_FLEET_MODEL_KEY)
        except Exception:   # noqa: BLE001
            pass
        fleet.reset()
        srv.stop()
        if prev_hb is None:
            os.environ.pop("H2O3_FLEET_HEARTBEAT_MS", None)
        else:
            os.environ["H2O3_FLEET_HEARTBEAT_MS"] = prev_hb


# -------------------------------------------------- kill-router round
#
# The router TIER's chaos probe (ISSUE 20): two real router PROCESSES
# gossip one member table, replica processes join through the seeds
# list, and one router is SIGKILLed mid-traffic. Asserted: clients
# fail over to the surviving router with zero failures after the shed
# window, routed/direct predictions stay bit-identical, and the
# bounced router comes back WARM — its first routed request after the
# REST surface answers routes from the peer-absorbed table (no
# empty-table 503 window).

_TIER_MODEL_KEY = _FLEET_MODEL_KEY   # same deterministic train


def _free_ports(n: int):
    import socket
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _router_child_src(repo: str, port: int) -> str:
    """One router-tier process: warm-boot the member table from any
    answering peer BEFORE the REST surface starts answering, then
    serve + gossip. Seeds arrive via H2O3_FLEET_SEEDS."""
    return textwrap.dedent(f"""
        import sys, threading
        sys.path.insert(0, {repo!r})
        from h2o3_tpu import fleet
        from h2o3_tpu.api.server import H2OApiServer
        # warm boot runs before bind: by the time a client can reach
        # this router, the peer's table + registry are already absorbed
        tier = fleet.start_router_tier("http://127.0.0.1:{port}")
        srv = H2OApiServer(port={port}).start()
        print("ROUTER_READY", srv.port, flush=True)
        threading.Event().wait()
    """)


def _tier_replica_src(repo: str) -> str:
    """A serve replica that discovers routers purely through the seeds
    list (no pinned router url): its beat stream rotates to a peer
    router on connect failure, carrying the SAME incarnation."""
    return textwrap.dedent(f"""
        import sys, threading
        sys.path.insert(0, {repo!r})
        import numpy as np
        import h2o3_tpu as h2o
        from h2o3_tpu import dkv, serve
        from h2o3_tpu.api.server import H2OApiServer
        from h2o3_tpu.fleet import FleetAgent
        from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
        rng = np.random.default_rng(21)
        n = {_FLEET_ROWS}
        a = rng.normal(size=n).astype(np.float32)
        b = rng.uniform(-2, 2, size=n).astype(np.float32)
        y = rng.random(n) < 1 / (1 + np.exp(-(a * 1.2 - b)))
        fr = h2o.Frame.from_numpy(dict(
            a=a, b=b, cls=np.where(y, "YES", "NO")))
        est = H2OGradientBoostingEstimator(**{_FLEET_PARAMS!r})
        est.train(y="cls", training_frame=fr)
        est.model.key = {_TIER_MODEL_KEY!r}
        dkv.put(est.model.key, "model", est.model)
        serve.deploy(est.model.key, max_delay_ms=1.0, queue_limit=65536)
        srv = H2OApiServer(port=0).start()
        agent = FleetAgent(f"http://127.0.0.1:{{srv.port}}")
        agent.start()
        print("REPLICA_READY", srv.port, flush=True)
        threading.Event().wait()
    """)


def _rest_post(url: str, payload: dict, timeout_s: float = 10.0):
    import urllib.request
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        return json.loads(r.read().decode())


def _rest_get(url: str, timeout_s: float = 5.0):
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return json.loads(r.read().decode())


def run_kill_router_round(replicas: int = 2, traffic_secs: float = 6.0,
                          clients: int = 4, log=print,
                          spawn_deadline_s: float = 300.0) -> dict:
    """SIGKILL one of two router processes mid-traffic, then bounce it
    back. Same skip contract as the other process rounds (CPU parent
    only)."""
    import queue as _q
    import threading

    import jax

    out = {"ran": False, "routers": 2, "replicas": replicas,
           "gossip_converged": None, "parity_ok": None,
           "failed_total": None, "failed_after_shed": None,
           "warm_reboot_ok": None, "warm_reboot_first_request_ok": None,
           "ok": False}
    if jax.default_backend() != "cpu":
        log("kill-router round: skipped — children run on CPU and "
            f"this process is on {jax.default_backend()}")
        out["ok"] = True
        return out
    import h2o3_tpu as h2o
    from h2o3_tpu import dkv, serve
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    hb_ms = float(os.environ.get("H2O3_FLEET_BENCH_HB_MS", "500") or 500)
    p0, p1 = _free_ports(2)
    urls = [f"http://127.0.0.1:{p0}", f"http://127.0.0.1:{p1}"]
    seeds = f"127.0.0.1:{p0},127.0.0.1:{p1}"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               H2O3_FLEET_SEEDS=seeds,
               H2O3_FLEET_HEARTBEAT_MS=str(hb_ms),
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          .replace("--xla_force_host_platform_"
                                   "device_count=8", "")).strip())
    procs = []
    router_a = None
    try:
        router_a = subprocess.Popen(
            [sys.executable, "-c", _router_child_src(_REPO, p0)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        router_b = subprocess.Popen(
            [sys.executable, "-c", _router_child_src(_REPO, p1)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        procs = [router_b]
        src = _tier_replica_src(_REPO)
        for _ in range(replicas):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", src], env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        # the parity reference: the SAME deterministic train, scored
        # locally (never through the fleet)
        rng = np.random.default_rng(21)
        n = _FLEET_ROWS
        a = rng.normal(size=n).astype(np.float32)
        b = rng.uniform(-2, 2, size=n).astype(np.float32)
        yv = rng.random(n) < 1 / (1 + np.exp(-(a * 1.2 - b)))
        fr = h2o.Frame.from_numpy(dict(
            a=a, b=b, cls=np.where(yv, "YES", "NO")))
        est = H2OGradientBoostingEstimator(**_FLEET_PARAMS)
        est.train(y="cls", training_frame=fr)
        est.model.key = _TIER_MODEL_KEY
        dkv.put(est.model.key, "model", est.model)
        dep = serve.deploy(est.model.key, max_delay_ms=1.0)
        rows = [{"a": float(a[i]), "b": float(b[i])} for i in range(64)]
        direct = dep.predict_rows(rows)

        def ring_members(url):
            try:
                ring = _rest_get(f"{url}/3/Fleet/ring", timeout_s=2.0)
                return {m["member_id"] for m in ring.get("members", [])}
            except Exception:   # noqa: BLE001 — not up yet
                return set()

        # replicas join ONE router (seed order); the OTHER must learn
        # them via gossip — both rings listing all replicas IS the
        # 2-router convergence assertion
        deadline = time.monotonic() + spawn_deadline_s
        while time.monotonic() < deadline:
            if all(len(ring_members(u)) >= replicas for u in urls):
                break
            if any(p.poll() is not None for p in procs) \
                    or router_a.poll() is not None:
                log("kill-router round: a child died during spawn")
                return out
            time.sleep(0.25)
        converged = all(len(ring_members(u)) >= replicas for u in urls)
        out["gossip_converged"] = converged
        if not converged:
            log("kill-router round: rings never converged — skipping")
            return out
        out["ran"] = True

        def routed(url, key, timeout_s=10.0):
            return _rest_post(
                f"{url}/3/Fleet/models/{_TIER_MODEL_KEY}/rows",
                {"rows": rows, "key": key}, timeout_s=timeout_s)

        # parity: ANY router answers any key, bit-identically
        pa = routed(urls[0], "probe")["predictions"]
        pb = routed(urls[1], "probe")["predictions"]
        out["parity_ok"] = (pa == pb) and (
            direct is None or all(
                rr["label"] == dd["label"]
                and rr["classProbabilities"] == dd["classProbabilities"]
                for rr, dd in zip(pa, direct)))

        # traffic with a mid-flight router SIGKILL; each client fails
        # over to the other router on connect failure (the affinity
        # client's routed-fallback rotation, spelled out)
        results: "_q.Queue" = _q.Queue()
        stop_at = time.monotonic() + traffic_secs
        kill_at = time.monotonic() + traffic_secs / 2
        killed = {"t": None}
        kill_mu = threading.Lock()

        def client(ci):
            idx, i = 0, 0
            while time.monotonic() < stop_at:
                with kill_mu:
                    if killed["t"] is None \
                            and time.monotonic() >= kill_at:
                        os.kill(router_a.pid, signal.SIGKILL)
                        killed["t"] = time.monotonic()
                t_start = time.monotonic()
                err = None
                for attempt in range(2 * len(urls)):
                    try:
                        got = routed(urls[idx % len(urls)],
                                     f"c{ci}-{i}")
                        err = None
                        results.put((t_start,
                                     len(got["predictions"]), None))
                        break
                    except Exception as e:  # noqa: BLE001 — rotate
                        err = RuntimeError(
                            f"{e!r} @ {urls[idx % len(urls)]}")
                        idx += 1
                        if attempt >= len(urls) - 1:
                            # every router refused once: transient
                            # (accept-queue pressure) — brief backoff
                            # before the second rotation, the same
                            # retry a real client performs
                            time.sleep(0.05)
                if err is not None:
                    results.put((t_start, 0, repr(err)))
                i += 1

        ths = [threading.Thread(target=client, args=(ci,))
               for ci in range(clients)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        t_kill = killed["t"] or time.monotonic()
        recs = []
        while not results.empty():
            recs.append(results.get())
        fails = [r for r in recs if r[2] is not None]
        # in-flight casualties may land inside [kill, kill + one beat
        # + detector slack]; after that the surviving router must
        # absorb EVERYTHING
        shed_window_s = 2.0 * hb_ms / 1000.0
        late = [r for r in fails if r[0] > t_kill + shed_window_s]
        out["failed_total"] = len(fails)
        out["failed_after_shed"] = len(late)
        out["requests_total"] = len(recs)
        if fails:
            out["fail_sample"] = sorted(
                {r[2][:120] for r in (late or fails)})[:3]

        # bounce the dead router: same port, fresh process. Its warm
        # boot runs BEFORE its REST surface binds, so the first routed
        # request it can physically receive must route (the pre-fix
        # behavior was a 503 window until replica beats rebuilt the
        # table)
        router_a.wait(timeout=10)
        router_a = subprocess.Popen(
            [sys.executable, "-c", _router_child_src(_REPO, p0)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        reboot_deadline = time.monotonic() + spawn_deadline_s
        first = None
        while time.monotonic() < reboot_deadline:
            try:
                first = routed(urls[0], "rebooted", timeout_s=5.0)
                break
            except Exception:   # noqa: BLE001 — still booting
                if router_a.poll() is not None:
                    log("kill-router round: rebooted router died")
                    return out
                time.sleep(0.25)
        out["warm_reboot_first_request_ok"] = bool(
            first is not None and first.get("predictions") == pa)
        out["warm_reboot_ok"] = bool(
            out["warm_reboot_first_request_ok"]
            and len(ring_members(urls[0])) >= replicas)
        out["heartbeat_ms"] = hb_ms
        out["ok"] = bool(out["parity_ok"] and converged
                         and out["failed_after_shed"] == 0
                         and out["warm_reboot_ok"])
        log(f"kill-router round: {'PASS' if out['ok'] else 'FAIL'} "
            f"{out}")
        return out
    finally:
        for p in procs + ([router_a] if router_a is not None else []):
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:   # noqa: BLE001 — cleanup best-effort
                pass
        try:
            serve.undeploy(_TIER_MODEL_KEY)
            dkv.remove(_TIER_MODEL_KEY)
        except Exception:   # noqa: BLE001
            pass


# -------------------------------------------------- router-tier round
#
# Steady-state affinity economics (ISSUE 20): one process hosts the
# router REST surface AND a deployed replica; an AffinityClient hashes
# keys client-side and posts straight to /3/Predictions (zero hop),
# while the reference load posts through /3/Fleet (the proxy hop).
# Emits fleet.zero_hop_ratio (>= 0.9 acceptance) and
# fleet.routed_p50_ms (the affinity path's p50 — strictly below the
# proxy path's p50, both measured over identical request shapes).

_TIER_BENCH_KEY = "chaos_tier_gbm"


def run_router_tier_round(requests: int = 200, rows_per_req: int = 8,
                          log=print) -> dict:
    import socket as _socket

    import h2o3_tpu as h2o
    from h2o3_tpu import dkv, fleet, serve
    from h2o3_tpu.api.server import H2OApiServer
    from h2o3_tpu.fleet.affinity import AffinityClient
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    out = {"ran": False, "zero_hop_ratio": None, "routed_p50_ms": None,
           "proxy_p50_ms": None, "ok": False}
    fleet.reset()
    srv = None
    try:
        rng = np.random.default_rng(21)
        n = 1200
        a = rng.normal(size=n).astype(np.float32)
        b = rng.uniform(-2, 2, size=n).astype(np.float32)
        yv = rng.random(n) < 1 / (1 + np.exp(-(a * 1.2 - b)))
        fr = h2o.Frame.from_numpy(dict(
            a=a, b=b, cls=np.where(yv, "YES", "NO")))
        est = H2OGradientBoostingEstimator(**_FLEET_PARAMS)
        est.train(y="cls", training_frame=fr)
        est.model.key = _TIER_BENCH_KEY
        dkv.put(est.model.key, "model", est.model)
        serve.deploy(est.model.key, max_delay_ms=1.0, max_batch=256,
                     buckets=[rows_per_req, 256])
        srv = H2OApiServer(port=0).start()
        base = f"http://127.0.0.1:{srv.port}"
        router = fleet.router()
        mid = f"{os.getpid()}@{_socket.gethostname()}"
        m = router.table.join(mid, base, heartbeat_s=60.0,
                              deployments=(_TIER_BENCH_KEY,))
        router.table.heartbeat(mid, m.incarnation, routable=True,
                               deployments=(_TIER_BENCH_KEY,))
        rows = [{"a": float(a[i]), "b": float(b[i])}
                for i in range(rows_per_req)]
        client = AffinityClient([base])
        for i in range(5):       # warm both paths out of the timing
            client.predict_rows(_TIER_BENCH_KEY, rows, key=f"w{i}")
            _rest_post(f"{base}/3/Fleet/models/{_TIER_BENCH_KEY}/rows",
                       {"rows": rows, "key": f"w{i}"})
        client.zero_hop = client.routed = 0
        aff_ms, proxy_ms = [], []
        for i in range(requests):
            t0 = time.perf_counter()
            client.predict_rows(_TIER_BENCH_KEY, rows, key=f"k{i}")
            aff_ms.append((time.perf_counter() - t0) * 1e3)
        for i in range(requests):
            t0 = time.perf_counter()
            _rest_post(f"{base}/3/Fleet/models/{_TIER_BENCH_KEY}/rows",
                       {"rows": rows, "key": f"k{i}"})
            proxy_ms.append((time.perf_counter() - t0) * 1e3)
        out["ran"] = True
        out["zero_hop_ratio"] = round(client.zero_hop_ratio(), 4)
        out["routed_p50_ms"] = round(
            float(np.percentile(aff_ms, 50)), 3)
        out["proxy_p50_ms"] = round(
            float(np.percentile(proxy_ms, 50)), 3)
        out["requests"] = requests
        out["ok"] = bool(out["zero_hop_ratio"] >= 0.9
                         and out["routed_p50_ms"]
                         < out["proxy_p50_ms"])
        log(f"router-tier round: {'PASS' if out['ok'] else 'FAIL'} "
            f"zero_hop_ratio={out['zero_hop_ratio']} "
            f"affinity_p50={out['routed_p50_ms']}ms "
            f"proxy_p50={out['proxy_p50_ms']}ms")
        return out
    finally:
        try:
            serve.undeploy(_TIER_BENCH_KEY)
            dkv.remove(_TIER_BENCH_KEY)
        except Exception:   # noqa: BLE001
            pass
        fleet.reset()
        if srv is not None:
            srv.stop()


# --------------------------------------------------------- lane round
#
# Deadline-class isolation under load (ISSUE 20): a saturating bulk
# scoring flood against a real deployment (sheds expected — that IS
# the mechanism) while sequential interactive requests measure their
# p99. Emits serve.interactive_p99_under_bulk_ms with the solo band it
# is judged against (<= 2x solo is the acceptance bar).

_LANE_MODEL_KEY = "chaos_lane_gbm"


def run_lane_round(log=print, interactive_requests: int = 150,
                   flood_threads: int = 4) -> dict:
    import threading

    import h2o3_tpu as h2o
    from h2o3_tpu import dkv, serve
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    from h2o3_tpu.serve.batcher import ServeLaneShedError

    out = {"ran": False, "interactive_p99_solo_ms": None,
           "interactive_p99_under_bulk_ms": None, "bulk_shed": None,
           "ok": False}
    rng = np.random.default_rng(21)
    n = 1200
    a = rng.normal(size=n).astype(np.float32)
    b = rng.uniform(-2, 2, size=n).astype(np.float32)
    yv = rng.random(n) < 1 / (1 + np.exp(-(a * 1.2 - b)))
    fr = h2o.Frame.from_numpy(dict(
        a=a, b=b, cls=np.where(yv, "YES", "NO")))
    est = H2OGradientBoostingEstimator(**_FLEET_PARAMS)
    est.train(y="cls", training_frame=fr)
    est.model.key = _LANE_MODEL_KEY
    dkv.put(est.model.key, "model", est.model)
    one = [{"a": float(a[0]), "b": float(b[0])}]
    bulk = [{"a": float(a[i]), "b": float(b[i])} for i in range(64)]

    def phase(flood: bool):
        """Fresh deployment per phase: the lane percentile reservoir
        must not mix solo samples into the under-flood verdict."""
        dep = serve.deploy(_LANE_MODEL_KEY, max_delay_ms=1.0,
                           max_batch=64, queue_limit=256,
                           buckets=[1, 64])
        stop = threading.Event()
        shed = [0]

        def hammer():
            while not stop.is_set():
                try:
                    dep.predict_rows(bulk, timeout_ms=2_000,
                                     lane="bulk")
                except ServeLaneShedError:
                    shed[0] += 1
                    time.sleep(0.001)
                except Exception:   # noqa: BLE001 — flood best-effort
                    pass

        ths = [threading.Thread(target=hammer)
               for _ in range(flood_threads if flood else 0)]
        for t in ths:
            t.start()
        try:
            time.sleep(0.05 if flood else 0.0)
            for _ in range(interactive_requests):
                dep.predict_rows(one, timeout_ms=10_000,
                                 lane="interactive")
        finally:
            stop.set()
            for t in ths:
                t.join(5)
        (p99,) = dep.stats.lane_percentiles_ms("interactive", [99])
        serve.undeploy(_LANE_MODEL_KEY)
        return p99, shed[0]

    try:
        solo_p99, _ = phase(flood=False)
        under_p99, sheds = phase(flood=True)
        out["ran"] = True
        out["interactive_p99_solo_ms"] = round(solo_p99, 2)
        out["interactive_p99_under_bulk_ms"] = round(under_p99, 2)
        out["bulk_shed"] = sheds
        out["ok"] = bool(sheds > 0 and under_p99
                         <= max(2.0 * solo_p99, solo_p99 + 25.0))
        log(f"lane round: {'PASS' if out['ok'] else 'FAIL'} "
            f"interactive_p99 solo={out['interactive_p99_solo_ms']}ms "
            f"under_bulk={out['interactive_p99_under_bulk_ms']}ms "
            f"(bulk sheds={sheds})")
        return out
    finally:
        try:
            serve.undeploy(_LANE_MODEL_KEY)
            dkv.remove(_LANE_MODEL_KEY)
        except Exception:   # noqa: BLE001
            pass


def run_oversubscribe_round(log=print, rows: int = 3000) -> dict:
    """Training-scheduler chaos (ISSUE 15, --oversubscribe): a memman
    budget sized for ONE resident train, four concurrent bulk GBM
    submissions, plus one interactive train submitted once the first
    bulk victim holds the device. Proves the acceptance shape: every
    submission completes DENSE (queued, never OOM-degraded), admission
    never overlaps two trains, the interactive train preempts the
    running bulk victim at a checkpoint commit, and every preempted
    train's final tree arrays are bit-identical to an unpreempted twin.
    Restores the process memman budget + scheduler on every exit."""
    import numpy as np

    import h2o3_tpu as h2o
    from h2o3_tpu import jobs, memman, sched
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator as GBM

    rng = np.random.default_rng(5)
    F = 6
    X = rng.normal(size=(rows, F)).astype(np.float32)
    logit = X[:, 0] - 0.5 * X[:, 1]
    cols = {f"x{i}": X[:, i] for i in range(F)}
    cols["y"] = np.where(rng.random(rows) < 1 / (1 + np.exp(-logit)),
                         "a", "b")
    fr = h2o.Frame.from_numpy(cols)
    kw = dict(ntrees=24, max_depth=3, min_rows=1.0, seed=7,
              score_tree_interval=2, stopping_rounds=0)
    twin = GBM(**kw)
    twin.train(y="y", training_frame=fr)     # unpreempted reference
    out = {"ran": True, "submissions": 5}
    try:
        memman.reset(budget=500_000)
        s = sched.reset()
        bulk = [GBM(model_id=f"oversub_bulk_{i}", **kw)
                for i in range(4)]
        with sched.submit_context(priority="bulk", share="oversub"):
            for est in bulk:
                est.train(y="y", training_frame=fr, background=True)
        # submit the interactive train the moment a bulk victim holds
        # the device — it cannot admit, so it must preempt
        t0 = time.monotonic()
        while all(e.job.status == jobs.QUEUED for e in bulk) \
                and time.monotonic() - t0 < 60:
            time.sleep(0.005)
        hi = GBM(ntrees=3, max_depth=3, min_rows=1.0, seed=1)
        hi.train(y="y", training_frame=fr, background=True)
        for est in bulk + [hi]:
            est.job.join(600)
        jobs_all = [e.job for e in bulk + [hi]]
        completed = sum(j.status == jobs.DONE for j in jobs_all)
        models = [e.job.result for e in bulk]
        preempted = [e for e in bulk if e.job.preempt_count > 0]
        resume_ok = None
        if preempted:
            # a preempted job that produced NO model is a resume
            # FAILURE, not a vacuous pass — the ratcheted
            # preempt_resume_ok metric must never read 1 by default
            results = [e.job.result for e in preempted]
            resume_ok = (all(r is not None for r in results)
                         and all(_trees_equal(twin.model, r)
                                 for r in results))
        waits = sorted(j.queue_wait_s or 0.0 for j in jobs_all)
        out.update({
            "oversub_completed": completed,
            "degraded": sum(bool((m.output or {}).get("streamed"))
                            for m in models if m is not None),
            "peak_concurrent": s.peak_running,
            "preempted": len(preempted),
            "preempt_resume_ok": ((1 if resume_ok else 0)
                                  if resume_ok is not None else None),
            "queue_wait_p50_ms": round(
                waits[len(waits) // 2] * 1000.0, 2),
            "counters": s.snapshot()["counters"],
        })
        out["ok"] = bool(completed == 5 and out["degraded"] == 0
                         and s.peak_running == 1
                         and len(preempted) >= 1 and resume_ok)
    finally:
        memman.reset()
        sched.reset()
    log(f"oversubscribe round: {out}")
    return out


# --------------------------------------- kill-replica-training round
#
# The fleet SCHEDULER's chaos probe (ISSUE 18): two real replica
# processes join the parent's router over REST with a SHARED recovery
# dir; a checkpointing train submitted to replica A is SIGKILLed
# mid-train and must complete on replica B bit-identically (evict →
# fleet-wide requeue from the last chunk commit); then a local bulk
# train preempted by an interactive one migrates its checkpoint to the
# surviving replica and resumes bit-identically. Recorded in bench.py
# as fleetsched.{queue_wait_p50_ms,migrations,resumed_after_evict}.

_FTS_EVICT_PARAMS = dict(ntrees=40, max_depth=3, seed=11, min_rows=1.0,
                         learn_rate=0.2, score_tree_interval=0,
                         stopping_rounds=0)
_FTS_MIG_PARAMS = dict(ntrees=18, max_depth=3, seed=7, min_rows=1.0,
                       score_tree_interval=2, stopping_rounds=0)


def _fts_replica_src(router_port: int) -> str:
    """An idle fleet replica: REST surface + agent; everything it
    trains arrives via /3/FleetSched/submit."""
    return textwrap.dedent(f"""
        import sys, threading
        sys.path.insert(0, {_REPO!r})
        from h2o3_tpu.api.server import H2OApiServer
        from h2o3_tpu.fleet import FleetAgent
        srv = H2OApiServer(port=0).start()
        agent = FleetAgent(f"http://127.0.0.1:{{srv.port}}",
                           router_url="http://127.0.0.1:{router_port}")
        agent.start()
        print("REPLICA_READY", srv.port, flush=True)
        threading.Event().wait()
    """)


def _victim_last_events(recdir, member_id, log, n=20):
    """Decode a dead member's flight-recorder ring from the shared root
    (ISSUE 19): the last-N-before-death view. Attached to the round
    report AND logged, so a failing round carries the victim's own
    account of its final control-plane decisions — the post-mortem
    ``tools/blackbox_read.py`` would print, inline."""
    try:
        from h2o3_tpu.telemetry import blackbox
        path = os.path.join(recdir, "blackbox",
                            blackbox._sanitize(str(member_id)) + ".bbx")
        if not os.path.exists(path):
            log(f"kill-replica-training round: no victim ring at {path}")
            return []
        rg = blackbox.read_ring(path, last=n)
        evs = rg["events"]
        log(f"kill-replica-training round: victim flight recorder "
            f"({member_id}, seq={rg['seq']}) — last {len(evs)} events:")
        for ev in evs:
            log(f"  e{ev['epoch']} #{ev['seq']} {ev['kind']} "
                f"{ev['member']} {ev['payload']}"
                + (f" trace={ev['trace_id']}" if ev["trace_id"] else ""))
        return evs
    except Exception as e:   # noqa: BLE001 — post-mortem is advisory
        log(f"kill-replica-training round: victim ring decode "
            f"failed: {e!r}")
        return []


def _survivor_cluster_timeline(base_url, log, trace_id="tr-chaos-fts"):
    """GET the survivor's fleet-wide causal timeline (ISSUE 19) and
    extract the chaos train's trace: the round report shows the whole
    submit→evict→requeue→resume story as one causally ordered list,
    with the dead victim's ring merged from the shared root."""
    import urllib.request
    out = {"cluster_timeline_members": None,
           "cluster_trace_events": None, "cluster_trace_kinds": None,
           "cluster_trace_ordered": None}
    try:
        with urllib.request.urlopen(
                f"{base_url}/3/Timeline?scope=cluster&n=512",
                timeout=30) as r:
            tl = json.loads(r.read().decode())
        evs = [e for e in tl.get("events", [])
               if e.get("trace_id") == trace_id]
        keys = [(e["epoch"], e["t_corrected"], e["member_ring"],
                 e["seq"]) for e in evs]
        out["cluster_timeline_members"] = {
            mid: {"dead": m.get("dead"),
                  "skew_flagged": m.get("skew_flagged")}
            for mid, m in (tl.get("members") or {}).items()}
        out["cluster_trace_events"] = len(evs)
        out["cluster_trace_kinds"] = [e["kind"] for e in evs]
        out["cluster_trace_ordered"] = keys == sorted(keys)
    except Exception as e:   # noqa: BLE001 — timeline is advisory
        log(f"kill-replica-training round: cluster timeline fetch "
            f"failed: {e!r}")
    return out


def run_kill_replica_training_round(log=print, rows: int = 2000,
                                    spawn_deadline_s: float = 300.0
                                    ) -> dict:
    """SIGKILL a replica mid-TRAIN (not mid-traffic — that is the
    --kill-replica round) and prove the fleet scheduler's two recovery
    paths: evict-requeue onto the survivor and preempt-migrate onto a
    member with headroom, both bit-identical. ``ran=False`` results
    are benign skips (non-CPU parent — child tree bits would not be
    comparable), same contract as the other process rounds."""
    import urllib.request

    import jax

    out = {"ran": False, "replicas": 2, "resumed_after_evict": None,
           "evict_resume_ok": None, "migrations": None,
           "migrate_resume_ok": None, "queue_wait_p50_ms": None,
           "ok": False}
    if jax.default_backend() != "cpu":
        log("kill-replica-training round: skipped — replica children "
            f"run on CPU and this process is on {jax.default_backend()}")
        out["ok"] = True          # a skip is not a failure
        return out
    import h2o3_tpu as h2o
    from h2o3_tpu import dkv, fleet, jobs, memman, sched
    from h2o3_tpu.api.server import H2OApiServer
    from h2o3_tpu.fleet import sched as fleet_sched
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator as GBM
    from h2o3_tpu.persist import load_model

    recdir = tempfile.mkdtemp(prefix="chaos_fleetsched_")
    ckdir = os.path.join(recdir, "victim_ck")
    hb_ms = float(os.environ.get("H2O3_FLEET_BENCH_HB_MS", "300")
                  or 300)
    saved = {k: os.environ.get(k)
             for k in ("H2O3_FLEET_HEARTBEAT_MS", "H2O3_RECOVERY_DIR")}
    os.environ["H2O3_FLEET_HEARTBEAT_MS"] = str(hb_ms)
    os.environ["H2O3_RECOVERY_DIR"] = recdir

    # deterministic frame + uninterrupted references, trained BEFORE
    # the fleet exists so the placer cannot hand them off
    rng = np.random.default_rng(23)
    F = 6
    X = rng.normal(size=(rows, F)).astype(np.float32)
    logit = X[:, 0] - 0.5 * X[:, 1]
    cols = {f"x{i}": X[:, i] for i in range(F)}
    cols["y"] = np.where(rng.random(rows) < 1 / (1 + np.exp(-logit)),
                         "a", "b")
    fr = h2o.Frame.from_numpy(cols)
    fr.key = "chaos_fts_frame"
    ref_evict = GBM(**_FTS_EVICT_PARAMS)
    ref_evict.train(y="y", training_frame=fr)
    twin_mig = GBM(**_FTS_MIG_PARAMS)
    twin_mig.train(y="y", training_frame=fr)

    fleet.reset()
    srv = H2OApiServer(port=0).start()
    router = fleet.router()
    procs = []
    try:
        exported = fleet_sched._export_frame(fr)
        if exported is None:
            log("kill-replica-training round: frame export failed")
            return out
        frame_path, frame_key = exported
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   H2O3_FLEET_SEEDS=f"127.0.0.1:{srv.port}")
        src = _fts_replica_src(srv.port)
        for _ in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", src], env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        deadline = time.monotonic() + spawn_deadline_s
        while time.monotonic() < deadline:
            if len(router.table.live_members()) >= 2:
                break
            if any(p.poll() is not None for p in procs):
                log("kill-replica-training round: a replica died "
                    "during spawn")
                return out
            time.sleep(0.25)
        live = router.table.live_members()
        if len(live) < 2:
            log(f"kill-replica-training round: only {len(live)}/2 "
                f"replicas joined before the deadline — skipping")
            return out
        out["ran"] = True

        # ---- phase 1: SIGKILL replica A mid-train, B finishes it
        victim = live[0]
        payload = {
            "schema_version": 1, "algo": "gbm",
            "params": dict(_FTS_EVICT_PARAMS,
                           model_id="chaos_fts_evict_gbm",
                           in_training_checkpoints_dir=ckdir,
                           in_training_checkpoints_tree_interval=5),
            "y": "y", "x": None,
            "frame_path": frame_path, "frame_key": frame_key,
            "priority": "bulk", "share": "chaos",
            "trace_id": "tr-chaos-fts",
            "model_key": "chaos_fts_evict_gbm",
            "result_path": fleet_sched._result_path(
                "chaos_fts_evict_gbm"),
            "resuming": False, "submitter": "chaos@parent"}
        req = urllib.request.Request(
            f"{victim.base_url}/3/FleetSched/submit",
            data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            sub = json.loads(r.read().decode())
        if not sub.get("ok"):
            log(f"kill-replica-training round: submit rejected {sub}")
            return out
        # the kill lands at the FIRST durable chunk commit
        deadline = time.monotonic() + 300
        while not (os.path.isdir(ckdir) and any(
                f.startswith("chaos_fts_evict_gbm_t")
                for f in os.listdir(ckdir))):
            if time.monotonic() > deadline:
                log("kill-replica-training round: no checkpoint landed")
                return out
            time.sleep(0.05)
        victim_proc = procs[0]     # spawn order == join order is NOT
        # guaranteed: find the child whose agent owns the victim id
        victim_pid = int(str(victim.member_id).split("@", 1)[0])
        for p in procs:
            if p.pid == victim_pid:
                victim_proc = p
                break
        os.kill(victim_proc.pid, signal.SIGKILL)
        victim_proc.wait(timeout=30)
        # flight recorder (ISSUE 19): the victim is gone — its mmap
        # ring under the shared root is the only witness to its last
        # control-plane decisions. Decode it BEFORE the survivor
        # verdict so even a failing round reports the death window.
        out["victim_last_events"] = _victim_last_events(
            recdir, victim.member_id, log)
        # eviction → fleet-wide requeue → the SURVIVOR resumes from the
        # last chunk commit and exports the result artifact
        rp = payload["result_path"]
        deadline = time.monotonic() + 600
        while not os.path.exists(rp):
            if time.monotonic() > deadline:
                log("kill-replica-training round: evicted train never "
                    "completed on the survivor")
                return out
            time.sleep(0.1)
        time.sleep(0.5)            # let the artifact writer close
        resumed = load_model(rp)
        out["resumed_after_evict"] = fleet_sched.counters()[
            "evict_requeues"]
        out["evict_resume_ok"] = bool(
            getattr(resumed, "ntrees_built", 0)
            == _FTS_EVICT_PARAMS["ntrees"]
            and _trees_equal(ref_evict.model, resumed))
        # the survivor's cluster timeline must tell the same story
        # causally — its own events plus the dead victim's merged ring
        out.update(_survivor_cluster_timeline(live[1].base_url, log))

        # ---- phase 2: preempt-MIGRATE onto the survivor
        memman.reset(budget=500_000)
        sched.reset()
        mig = GBM(model_id="chaos_fts_mig_gbm", **_FTS_MIG_PARAMS)
        with sched.submit_context(priority="bulk"):
            mig.train(y="y", training_frame=fr, background=True)
        t0 = time.monotonic()
        while mig.job.status == jobs.QUEUED \
                and time.monotonic() - t0 < 120:
            time.sleep(0.005)
        # the interactive preemptor carries a validation frame, so it
        # is NOT placement-eligible: it preempts locally by design
        vfr = h2o.Frame.from_numpy(
            {f"x{i}": X[:400, i] for i in range(F)}
            | {"y": cols["y"][:400]})
        hi = GBM(ntrees=3, max_depth=3, seed=1, min_rows=1.0)
        hi.train(y="y", training_frame=fr, validation_frame=vfr,
                 background=True)
        hi.job.join(300)
        mig.job.join(600)
        out["migrations"] = fleet_sched.counters()["migrations"]
        mig_ok = (mig.job.status == jobs.DONE
                  and mig.job.result is not None
                  and out["migrations"] >= 1
                  and _trees_equal(twin_mig.model, mig.job.result))
        out["migrate_resume_ok"] = bool(mig_ok)
        waits = sorted(j.queue_wait_s or 0.0
                       for j in (mig.job, hi.job))
        out["queue_wait_p50_ms"] = round(
            waits[len(waits) // 2] * 1000.0, 2)
        out["heartbeat_ms"] = hb_ms
        out["ok"] = bool(out["evict_resume_ok"]
                         and out["migrate_resume_ok"]
                         and (out["resumed_after_evict"] or 0) >= 1)
        log(f"kill-replica-training round: "
            f"{'PASS' if out['ok'] else 'FAIL'} {out}")
        return out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:   # noqa: BLE001 — cleanup best-effort
                pass
        try:
            dkv.remove("chaos_fts_evict_gbm")
        except Exception:   # noqa: BLE001
            pass
        fleet.reset()
        srv.stop()
        memman.reset()
        sched.reset()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_chaos_round(rows: int = 2000, log=print,
                    kill_process=None) -> dict:
    """Run the sweep with a hard guarantee that fault injection is
    DISARMED on every exit path — bench.py swallows chaos-round
    exceptions, and a leaked spec would corrupt everything the process
    runs afterwards while looking organic. ``kill_process=None``
    defaults from H2O3_BENCH_CHAOS_KILL (on unless '0')."""
    from h2o3_tpu import faults
    try:
        out = _chaos_round(rows, log)
    finally:
        faults.configure(None)
    if kill_process is None:
        kill_process = os.environ.get("H2O3_BENCH_CHAOS_KILL",
                                      "1") not in ("0", "false", "")
    if kill_process:
        try:
            probe = run_kill_process_round(rows, log)
        except Exception as e:   # noqa: BLE001 — probe must not sink bench
            log(f"kill-process round FAILED to run: {e!r}")
            probe = {"ran": True, "recovered_after_restart": False,
                     "restart_recovery_s": None}
        out.update(probe)
        if probe.get("ran"):
            # only a probe that actually exercised recovery can fail
            # the sweep — a benign skip (wrong backend, child finished
            # before the first checkpoint) is not a recovery failure
            out["ok"] = bool(out["ok"]
                             and out.get("recovered_after_restart"))
    return out


def _chaos_round(rows: int, log) -> dict:
    import jax

    import h2o3_tpu as h2o
    from h2o3_tpu import dkv, faults, serve, telemetry
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator as GBM

    reg = telemetry.registry()

    def retries_total():
        return sum(s["value"] for s in reg.samples()
                   if s["name"] == "h2o3_retry_total")

    def injected_total():
        return sum(s["value"] for s in reg.samples()
                   if s["name"] == "h2o3_fault_injected_total")

    def circuit_opens():
        return sum(s["value"] for s in reg.samples()
                   if s["name"] == "h2o3_circuit_open_total")

    r0, i0, c0 = retries_total(), injected_total(), circuit_opens()
    d0 = _counter(reg, "h2o3_degrade_total", {"algo": "gbm"})

    rng = np.random.default_rng(42)
    cols = {f"f{i}": rng.normal(size=rows) for i in range(6)}
    cols["y"] = (cols["f0"] * 2 - cols["f1"]
                 + rng.normal(size=rows) * 0.1)
    fr = h2o.Frame.from_numpy(cols)
    kw = dict(ntrees=10, max_depth=3, seed=13, learn_rate=0.2)

    # reference run (fault-free) for the bit-parity verdicts
    ref = GBM(**kw)
    ref.train(y="y", training_frame=fr)

    trees_equal = _trees_equal

    # 1) transient h2d + execute faults: an ingest under h2d faults
    #    parses correctly, a train under execute faults completes via
    #    retries, bit-identical to the reference
    faults.configure("h2d:every=2:times=2:exc=Unavailable,"
                     "execute@train:every=1:times=2:exc=Internal")
    fr2 = h2o.Frame.from_numpy(
        {"a": rng.normal(size=256), "b": rng.normal(size=256)})
    ingest_ok = bool(np.isfinite(fr2.vec("a").to_numpy()).all())
    t_train = GBM(**kw)
    t_train.train(y="y", training_frame=fr)
    transient_ok = ingest_ok and trees_equal(ref.model, t_train.model)
    faults.configure(None)

    # 2) mid-train kill → checkpoint resume, bit-identical
    ckdir = tempfile.mkdtemp(prefix="chaos_ckpt_")
    faults.configure("execute@train:every=1:after=1:times=1:exc=Fatal")
    killed = GBM(in_training_checkpoints_dir=ckdir,
                 in_training_checkpoints_tree_interval=3, **kw)
    resume_ok = False
    try:
        killed.train(y="y", training_frame=fr)
    except RuntimeError:
        pass
    faults.configure(None)
    ckpts = sorted(os.listdir(ckdir))
    if ckpts:
        resumed = GBM(checkpoint=os.path.join(ckdir, ckpts[-1]), **kw)
        resumed.train(y="y", training_frame=fr)
        resume_ok = trees_equal(ref.model, resumed.model)

    # 3) synthetic OOM → dense degrades to the streamed path
    faults.configure("execute@train:every=1:times=1:exc=ResourceExhausted")
    degraded = GBM(**kw)
    degraded.train(y="y", training_frame=fr)
    faults.configure(None)
    degraded_ok = bool(degraded.model.output.get("streamed"))

    # 4) serve: persistently failing deployment trips the breaker and
    #    recovers once the fault clears
    dkv.put("chaos_model", "model", ref.model)
    dep = serve.deploy("chaos_model", circuit_failures=2,
                       circuit_open_ms=150, max_delay_ms=1.0)
    row = {f"f{i}": 0.1 * i for i in range(6)}
    faults.configure("execute@serve:key=chaos_model:every=1:exc=Internal")
    circuit_opened = False
    for _ in range(6):
        try:
            dep.predict_rows([row], timeout_ms=500)
        except serve.ServeCircuitOpenError:
            circuit_opened = True
            break
        except Exception:   # noqa: BLE001 — injected device errors
            pass
    faults.configure(None)
    time.sleep(0.2)
    served_after = None
    try:
        served_after = dep.predict_rows([row])[0]
    except Exception:   # noqa: BLE001
        pass
    serve.undeploy("chaos_model")
    dkv.remove("chaos_model")

    out = {
        "recovered_total": round(retries_total() - r0),
        "recovery_p50_ms": _recovery_p50_ms(reg),
        "degraded_trains": round(
            _counter(reg, "h2o3_degrade_total", {"algo": "gbm"}) - d0),
        "circuit_opens": round(circuit_opens() - c0),
        "faults_injected": round(injected_total() - i0),
        "transient_train_bit_identical": transient_ok,
        "ckpt_resume_ok": resume_ok,
        "oom_degrade_ok": degraded_ok,
        "circuit_lifecycle_ok": bool(circuit_opened
                                     and served_after is not None),
    }
    ok = all(out[k] for k in ("transient_train_bit_identical",
                              "ckpt_resume_ok", "oom_degrade_ok",
                              "circuit_lifecycle_ok"))
    out["ok"] = ok
    log(f"chaos sweep: {'PASS' if ok else 'FAIL'} {out}")
    return out


def main():
    log = lambda *a: print(*a, file=sys.stderr)  # noqa: E731
    if "--kill-replica" in sys.argv[1:]:
        # fleet chaos only (ISSUE 13): SIGKILL one of N serve-replica
        # processes mid-traffic; shed + rebalance + zero late failures
        out = {"fleet": run_kill_replica_round(log=log)}
        print(json.dumps(out, indent=2))
        sys.exit(0 if out["fleet"]["ok"] else 1)
    if "--kill-router" in sys.argv[1:]:
        # router-tier chaos only (ISSUE 20): SIGKILL one of two
        # gossiping routers mid-traffic — zero failures after the shed
        # window, routed/direct bit-parity, and the bounced router
        # rejoins WARM (first routed request, no empty-table 503)
        out = {"fleet_tier": run_kill_router_round(log=log)}
        print(json.dumps(out, indent=2))
        sys.exit(0 if out["fleet_tier"]["ok"] else 1)
    if "--router-tier" in sys.argv[1:]:
        # steady-state affinity economics (ISSUE 20): zero-hop ratio
        # and client-path p50 vs the proxy hop
        out = {"fleet_affinity": run_router_tier_round(log=log)}
        print(json.dumps(out, indent=2))
        sys.exit(0 if out["fleet_affinity"]["ok"] else 1)
    if "--lanes" in sys.argv[1:]:
        # deadline-class isolation (ISSUE 20): interactive p99 under a
        # saturating bulk flood vs its solo band
        out = {"serve_lanes": run_lane_round(log=log)}
        print(json.dumps(out, indent=2))
        sys.exit(0 if out["serve_lanes"]["ok"] else 1)
    if "--kill-replica-training" in sys.argv[1:]:
        # fleet-scheduler chaos only (ISSUE 18): SIGKILL a replica
        # mid-TRAIN — evict-requeue onto the survivor + preempt-migrate
        # both finish bit-identical
        out = {"fleetsched": run_kill_replica_training_round(log=log)}
        print(json.dumps(out, indent=2))
        sys.exit(0 if out["fleetsched"]["ok"] else 1)
    if "--oversubscribe" in sys.argv[1:]:
        # training-scheduler chaos only (ISSUE 15): tight budget, 4
        # concurrent bulk trains + 1 interactive preemptor — queued not
        # degraded, bit-identical preempt/resume
        out = {"sched": run_oversubscribe_round(log=log)}
        print(json.dumps(out, indent=2))
        sys.exit(0 if out["sched"]["ok"] else 1)
    # --kill-process forces the restart-recovery round even when
    # H2O3_BENCH_CHAOS_KILL=0; without it the env default applies
    kill = True if "--kill-process" in sys.argv[1:] else None
    out = {"resilience": run_chaos_round(log=log, kill_process=kill)}
    print(json.dumps(out, indent=2))
    sys.exit(0 if out["resilience"]["ok"] else 1)


if __name__ == "__main__":
    main()
