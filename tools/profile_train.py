"""Train-path stage profiler — attribute GBM train time to its stages.

Mirrors tools/profile_ingest.py for the training side of the pipeline:
synthesizes a HIGGS-shaped frame (or ingests CSV= / reuses the bench
shape), trains once COLD (spec + compile) and once WARM, and prints ONE
JSON line attributing the warm run to its stages:

  spec_s      frame → dense TrainingSpec (as_matrix, weights, domains)
  bin_s       global-sketch binning / adaptive range setup
  loop_s      the device boosting loop (chunked lax.scan dispatches)
  score_s     host time blocked materializing interval score scalars
  finalize_s  tree device_get + threshold conversion + final metrics
  warm_total_s / warm_over_loop   the headline ratio — ISSUE 2's
              acceptance bar is warm_total <= 2.5x loop at bench shape

plus ``cold_total_s`` (time-to-first-model net of ingest) so compile-
cache regressions are attributable. Stage numbers are read from the
telemetry spans the training driver itself records (h2o3_tpu.telemetry
``train.*`` spans — the same data ``GET /metrics`` and /3/Telemetry
export, so the tool- and REST-reported splits cannot disagree); the
profiler adds no timers of its own around device work, so there is no
double-dispatch skew. The warm run's XLA compile count (the production
``h2o3_xla_compiles_total`` counter) is reported alongside — 0 is the
PR-2 zero-recompile contract.

Env knobs: ROWS (default 2M), NCOL (default 28 features), TREES (20),
DEPTH (6), NBINS (14), HIST (histogram_type, default 'random' like the
bench; set 'quantiles_global' to profile the sketch-binned path),
CSV= (profile a real file through the ingest path instead).

``--xprof-trace [DIR]`` (or XPROF_TRACE_DIR=) wraps the WARM train in a
``jax.profiler.trace`` capture for kernel-level attribution of the
psum/histogram loop — open the dump with xprof/tensorboard
(``python -m xprof.server DIR`` or ``tensorboard --logdir DIR``) to see
per-level fused-histogram kernels and the ICI all-reduce on the
device timeline (the SNIPPETS profiling-harness pattern).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

ROWS = int(os.environ.get("ROWS", 2_000_000))
NCOL = int(os.environ.get("NCOL", 28))
TREES = int(os.environ.get("TREES", 20))
DEPTH = int(os.environ.get("DEPTH", 6))
NBINS = int(os.environ.get("NBINS", 14))
HIST = os.environ.get("HIST", "random")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _frame():
    import h2o3_tpu as h2o
    csv = os.environ.get("CSV")
    if csv:
        from h2o3_tpu.ingest.parse import parse, parse_setup
        fr = parse([csv], parse_setup([csv]))
        return fr, fr.names[-1]
    rng = np.random.default_rng(42)
    X = rng.normal(size=(ROWS, NCOL)).astype(np.float32)
    logit = (X[:, 0] * 1.5 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
             + 0.3 * np.sin(3 * X[:, 4]))
    y = (rng.random(ROWS) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float32)
    cols = {f"f{i}": X[:, i] for i in range(NCOL)}
    cols["label"] = y
    return h2o.Frame.from_numpy(cols), "label"


def _train(fr, yname):
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
    gbm = H2OGradientBoostingEstimator(
        ntrees=TREES, max_depth=DEPTH, nbins=NBINS, learn_rate=0.1,
        distribution="bernoulli", seed=7, min_rows=1.0,
        histogram_type=HIST, score_tree_interval=0, stopping_rounds=0)
    t0 = time.time()
    gbm.train(y=yname, training_frame=fr)
    return gbm.model, time.time() - t0


def _level_split(rows, F, nbins, depth):
    """Standalone per-level timing of the hot kernel, packed binned vs
    f32 adaptive at the profiled shape — attributes the level cost so
    the NEXT 2x is visible per depth, and quantifies the packed-vs-f32
    bytes/row drop at the representation level. Uses the same 'auto'
    dispatch as training (pallas on TPU / interpret escape, scatter on
    CPU); rows are capped off-TPU to keep the probe cheap."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    from h2o3_tpu.ops.hist_adaptive import (adaptive_level, binned_level,
                                            pick_W)
    if jax.default_backend() != "tpu":
        rows = min(rows, 1 << 18)
    rng = np.random.default_rng(0)
    W = pick_W(max(nbins, 2))
    dt = np.int8 if W <= 128 else np.int16
    Xh = rng.normal(size=(rows, F)).astype(np.float32)
    X = jnp.asarray(Xh)
    Xt = jnp.asarray(np.ascontiguousarray(Xh.T))
    codes_h = rng.integers(0, max(nbins, 2), size=(rows, F)).astype(dt)
    codes = jnp.asarray(codes_h)
    ct = jnp.asarray(np.ascontiguousarray(codes_h.T))
    ghw = jnp.ones((3, rows), jnp.float32)
    levels = []

    def timeit(fn, *args, reps=3, **kw):
        r = fn(*args, **kw)
        jax.block_until_ready(r)        # warmup/compile
        t0 = time.perf_counter()
        for _ in range(reps):
            r = fn(*args, **kw)
            jax.block_until_ready(r)
        return (time.perf_counter() - t0) / reps * 1e3

    for d in range(depth):
        N = 2 ** d
        base = N - 1
        n_prev = N // 2
        np1 = max(n_prev, 1)
        nid = jnp.asarray(
            (base - n_prev + rng.integers(0, max(n_prev, 1), rows))
            .astype(np.int32)) if d else jnp.zeros(rows, jnp.int32)
        tables = (jnp.asarray(rng.integers(0, F, np1).astype(np.float32)),
                  jnp.asarray(rng.integers(1, max(nbins - 1, 2), np1)
                              .astype(np.float32)),
                  jnp.zeros(np1, jnp.float32),
                  jnp.ones(np1, jnp.float32))
        lo = jnp.full((N, F), -3.0, jnp.float32)
        inv = jnp.full((N, F), nbins / 6.0, jnp.float32)
        f32_ms = timeit(partial(adaptive_level, n_prev=n_prev, n_nodes=N,
                                level_base=base, W=W), X, nid, ghw,
                        tables, lo, inv, xt=Xt)
        packed_ms = timeit(partial(binned_level, n_prev=n_prev, n_nodes=N,
                                   level_base=base, W=W), codes, nid,
                           ghw, tables, ct=ct)
        levels.append({"level": d, "n_nodes": N,
                       "f32_ms": round(f32_ms, 3),
                       "packed_ms": round(packed_ms, 3)})
    return {"rows": rows, "W": W, "levels": levels,
            "bytes_per_row": {"f32": F * 4,
                              "packed": F * int(np.dtype(dt).itemsize)}}


def _fused_pass(rows, F, nbins, depth):
    """Fused-pass view (multi-level streamed windows, ISSUE 17): times a
    full tree grown as windows of L packed binned levels — each window
    ONE jitted dispatch chaining kernel + device split-select, records
    fetched once at the window boundary — at L in {1, 2, 4} (clamped to
    depth). The per-window stage split attributes device loop time vs
    the boundary record fetch, and the per-level delta vs L=1 is the
    dispatch/sync overhead the fusion amortizes (the
    H2O3_LEVELS_PER_PASS lever). Select is a gain-proxy stub shaped
    like _binned_split_level (cumsum + argmax per node), so the window
    executable carries the same level->select->level dependency chain
    as the production window."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    from h2o3_tpu.models.tree import levels_per_pass
    from h2o3_tpu.ops.hist_adaptive import binned_level, pick_W
    if jax.default_backend() != "tpu":
        rows = min(rows, 1 << 18)
    W = pick_W(max(nbins, 2))
    dt = np.int8 if W <= 128 else np.int16
    rng = np.random.default_rng(0)
    codes_h = rng.integers(0, max(nbins, 2), size=(rows, F)).astype(dt)
    codes = jnp.asarray(codes_h)
    ct = jnp.asarray(np.ascontiguousarray(codes_h.T))
    ghw = jnp.ones((3, rows), jnp.float32)

    def select_tables(hist, N):
        g, h, _w = hist[0], hist[1], hist[2]          # [N, F, W]
        gl = jnp.cumsum(g, axis=2)
        hl = jnp.cumsum(h, axis=2)
        gt, ht = gl[:, :, -1:], hl[:, :, -1:]
        gain = (gl ** 2 / (hl + 1e-6)
                + (gt - gl) ** 2 / (ht - hl + 1e-6)).reshape(N, -1)
        best = jnp.argmax(gain, axis=1)
        return ((best // W).astype(jnp.float32),
                (best % W).astype(jnp.float32),
                jnp.zeros(N, jnp.float32), jnp.ones(N, jnp.float32))

    def window(codes, ct, nid, ghw, tables, *, d0, Lw):
        recs = []
        for j in range(Lw):
            d = d0 + j
            N = 2 ** d
            nid, hist = binned_level(codes, nid, ghw, tables,
                                     N // 2 if d else 0, N, N - 1, W,
                                     ct=ct)
            tables = select_tables(hist, N)
            recs.append(tables[0])
        return nid, tables, recs

    def tree(L):
        nid = jnp.zeros(rows, jnp.int32)
        tables = (jnp.zeros(1, jnp.float32), jnp.ones(1, jnp.float32),
                  jnp.zeros(1, jnp.float32), jnp.zeros(1, jnp.float32))
        loop_s = fetch_s = 0.0
        d = 0
        while d < depth:
            Lw = min(L, depth - d)
            t0 = time.perf_counter()
            nid, tables, recs = wins[(L, d, Lw)](codes, ct, nid, ghw,
                                                 tables)
            jax.block_until_ready(nid)
            t1 = time.perf_counter()
            jax.device_get(recs)           # boundary record fetch
            t2 = time.perf_counter()
            loop_s += t1 - t0
            fetch_s += t2 - t1
            d += Lw
        return loop_s, fetch_s

    out = {"rows": rows, "W": W,
           "auto_levels_per_pass": levels_per_pass(depth, F, W),
           "windows": []}
    base_ms = None
    for L in sorted({1, 2, 4}):
        L = min(L, depth)
        wins = {}
        d = 0
        while d < depth:
            Lw = min(L, depth - d)
            wins[(L, d, Lw)] = jax.jit(partial(window, d0=d, Lw=Lw))
            d += Lw
        tree(L)                            # warm: compile every window
        reps = 3
        loop_s = fetch_s = 0.0
        for _ in range(reps):
            ls, fs = tree(L)
            loop_s += ls
            fetch_s += fs
        loop_ms = loop_s / reps * 1e3
        fetch_ms = fetch_s / reps * 1e3
        per_level = (loop_ms + fetch_ms) / depth
        if L == 1:
            base_ms = per_level
        rec = {"L": L, "windows_per_tree": -(-depth // L),
               "loop_ms": round(loop_ms, 3),
               "boundary_fetch_ms": round(fetch_ms, 3),
               "ms_per_level": round(per_level, 3)}
        if base_ms and L > 1:
            rec["dispatch_overhead_saved"] = round(
                max(0.0, 1 - per_level / base_ms), 3)
        out["windows"].append(rec)
        if L == depth or L >= depth:
            break
    return out


def main():
    import jax
    from h2o3_tpu import telemetry
    from h2o3_tpu.cluster_boot import setup_compilation_cache
    cache = setup_compilation_cache()       # also installs telemetry
    if not telemetry.enabled():
        log("H2O3_TELEMETRY=0: stage/compile attribution unavailable — "
            "those fields will be null/0 (re-run with telemetry enabled)")
    log(f"backend={jax.default_backend()} devices={len(jax.devices())} "
        f"compile_cache={cache}")
    fr, yname = _frame()
    log(f"frame: {fr.nrow}x{fr.ncol} hist={HIST}")

    model, cold_total = _train(fr, yname)
    log(f"cold train {cold_total:.2f}s "
        f"stages={telemetry.stage_seconds('train.')}")
    # stage counters are cumulative: snapshot before the warm run and
    # report the delta — the warm run's own span durations
    stages0 = telemetry.stage_seconds("train.")
    compiles0 = telemetry.registry().value("h2o3_xla_compiles_total")
    h2d0 = telemetry.registry().value("h2o3_h2d_bytes_total")
    # kernel-level attribution of the WARM loop (shared xprof helper,
    # telemetry/profiling.py — the capture holds the per-level histogram
    # kernels and, on a multi-shard mesh, the psum all-reduce on the
    # device timeline); no-op unless --xprof-trace / XPROF_TRACE_DIR
    from h2o3_tpu.telemetry.profiling import last_trace_dir, profile
    with profile("warm_train", log=log):
        model, warm_total = _train(fr, yname)
    trace_dir = last_trace_dir()
    warm_compiles = telemetry.registry().value(
        "h2o3_xla_compiles_total") - compiles0
    warm_h2d = telemetry.registry().value("h2o3_h2d_bytes_total") - h2d0

    # per-phase roofline table (ISSUE 11): the same run that captured
    # the xprof trace carries the chunk executables' cost_analysis —
    # kernel timeline AND FLOP/byte attribution from ONE flag
    perf = model.output.get("perf") or {}
    for pname, pt in (perf.get("phases") or {}).items():
        log(f"roofline[{pname}]: "
            f"{pt['achieved_flops'] / 1e9:.2f} GFLOP/s "
            f"({pt['flops_total'] / 1e9:.2f} GFLOP / "
            f"{pt['device_seconds']:.3f}s)  "
            f"{pt['achieved_bytes_per_s'] / 1e9:.2f} GB/s  "
            f"AI={pt['arith_intensity']} flop/B "
            f"(ridge {pt['ridge_intensity']})  "
            f"mfu={pt['mfu']}  {pt['roofline_regime']}  "
            f"peaks={pt['peak_source']}"
            + (" [informational]" if pt.get("informational") else ""))

    # ONE scrape for every stage read (each samples() pass runs the
    # collector views, incl. an O(live arrays) device-memory walk)
    stages1 = telemetry.stage_seconds(
        "train.", samples=telemetry.registry().samples())

    def stage(name):
        tot = stages1.get(name, {})
        pre = stages0.get(name, {})
        d = tot.get("seconds", 0.0) - pre.get("seconds", 0.0)
        return round(d, 4) if d else None

    loop_s = stage("train.loop") \
        or model.output.get("training_loop_seconds", 0)
    out = {
        "rows": fr.nrow, "ncol": fr.ncol, "trees": model.ntrees_built,
        "depth": DEPTH, "histogram_type": HIST,
        "cold_total_s": round(cold_total, 3),
        "warm_total_s": round(warm_total, 3),
        # stage split from the driver's telemetry spans (same data the
        # REST telemetry endpoints export for this run)
        "spec_s": stage("train.spec"),
        "bin_s": stage("train.bin"),
        "loop_s": round(loop_s, 3),
        "score_s": stage("train.score"),
        "finalize_s": stage("train.finalize"),
        "warm_compiles": int(warm_compiles),
        "warm_over_loop": round(warm_total / max(loop_s, 1e-9), 2),
        "rows_per_sec_warm": round(fr.nrow * model.ntrees_built
                                   / max(loop_s, 1e-9), 1),
        # transfer budget per tree (registry counter delta over the warm
        # train): the dense device-resident path should sit near zero;
        # the streamed path's once-per-tree contract shows up here and
        # in model.output["stream_profile"]
        "h2d_bytes_warm_train": round(warm_h2d),
        "h2d_bytes_per_tree": round(
            warm_h2d / max(model.ntrees_built, 1)),
        "stream_profile": model.output.get("stream_profile"),
        "spmd": model.output.get("spmd"),
        # hot-loop representation (ISSUE 12): what the level kernel
        # streamed — the packed int8/int16 path vs f32, with the
        # cost-analysis-grounded bytes per (row x tree). The xprof
        # capture above names the kernel itself (`_kernel_bt` for the
        # binned path, `_kernel_t` for the f32 adaptive path) on the
        # device timeline.
        "packed_codes": model.output.get("packed_codes"),
        # multi-level fusion (ISSUE 17): how many tree levels each
        # device dispatch covered — max_depth on the dense path (the
        # whole grower traces into one executable), H2O3_LEVELS_PER_PASS
        # on the streamed single-chunk path, 1 per-level otherwise
        "levels_per_dispatch": model.output.get("levels_per_dispatch"),
        "hot_kernel": ((model.output.get("packed_codes") or {})
                       .get("kernel") or "adaptive_level"),
        "hot_loop_bytes_per_row_tree": (
            round(perf.get("train", {}).get("bytes_total", 0)
                  / max(fr.nrow * model.ntrees_built, 1), 2)
            if (perf.get("train") or {}).get("bytes_total") else None),
        # per-phase roofline points (ISSUE 11): cost_analysis-grounded
        # achieved flops/bytes, MFU and regime for the warm train —
        # recorded in the same run as the xprof capture above
        "perf": perf or None,
        "xprof_trace_dir": trace_dir,
    }
    # per-level kernel split (ISSUE 12): standalone binned-vs-f32 level
    # timings at this shape so the roofline table says WHERE the next
    # 2x lives (H2O3_PROFILE_LEVEL_SPLIT=0 skips the probe)
    if os.environ.get("H2O3_PROFILE_LEVEL_SPLIT", "1") not in (
            "0", "false", ""):
        try:
            out["level_split"] = _level_split(fr.nrow, fr.ncol - 1,
                                              NBINS, DEPTH)
            for lv in out["level_split"]["levels"]:
                log(f"level[{lv['level']}] n_nodes={lv['n_nodes']}: "
                    f"f32 {lv['f32_ms']}ms  packed {lv['packed_ms']}ms")
        except Exception as e:  # probe must never sink the profile
            log(f"level-split probe FAILED: {e!r}")
        # fused-pass view (ISSUE 17): per-window stage split at
        # L in {1, 2, 4} — device loop vs boundary fetch, and the
        # dispatch overhead multi-level fusion removes
        try:
            out["fused_pass"] = _fused_pass(fr.nrow, fr.ncol - 1,
                                            NBINS, DEPTH)
            for wv in out["fused_pass"]["windows"]:
                log(f"fused[L={wv['L']}]: {wv['ms_per_level']}ms/level "
                    f"(loop {wv['loop_ms']}ms + fetch "
                    f"{wv['boundary_fetch_ms']}ms / tree)"
                    + (f"  overhead saved "
                       f"{wv['dispatch_overhead_saved']:.0%}"
                       if "dispatch_overhead_saved" in wv else ""))
        except Exception as e:
            log(f"fused-pass probe FAILED: {e!r}")
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
