"""Does a [M, K]x[K, N] Mosaic matmul with M << 128 cost the same as
M=128 (systolic-array row waste)? Times the bare hist-shaped contraction
at several M.  K=8192 (tile), N=896 (F*W).

``L=4`` chains L DEPENDENT contractions per fori step (each left
operand perturbed by the previous output, like the fused multi-level
window feeds nid forward) — per-contraction time vs L=1 shows whether
back-to-back MXU issue at the hist shape keeps the array busy, i.e.
how much of the multi-level win is dispatch/sync amortization vs
in-kernel pipelining. ``FW=448`` probes the W=16 packed geometry."""
import sys, os, time, functools
sys.path.insert(0, '/root/repo')

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from h2o3_tpu.ops.pallas_compat import CompilerParams as _CompilerParams

ROWS = int(os.environ.get("ROWS", 2_500_608))
TILE = int(os.environ.get("TILE", 8192))
FW = int(os.environ.get("FW", 896))
REPS = int(os.environ.get("REPS", 40))
LCHAIN = max(1, int(os.environ.get("L", 1)))


def run(M):
    def kern(l_ref, r_ref, out_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        acc = jax.lax.dot_general(
            l_ref[...], r_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=(jnp.int32 if l_ref.dtype == jnp.int8
                                    else jnp.float32))
        acc_ref[...] += acc.astype(acc_ref.dtype)

        @pl.when(i == ROWS // TILE - 1)
        def _f():
            out_ref[...] = acc_ref[...]

    call = pl.pallas_call(
        kern,
        grid=(ROWS // TILE,),
        in_specs=[pl.BlockSpec((M, TILE), lambda r: (0, 0)),
                  pl.BlockSpec((FW, TILE), lambda r: (0, 0))],
        out_specs=pl.BlockSpec((M, FW), lambda r: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((M, FW), jnp.float32),
        scratch_shapes=[pltpu.VMEM((M, FW), jnp.float32)],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 2 ** 20),
        interpret=os.environ.get("H2O3_PALLAS_INTERPRET", "") == "1",
    )
    rng = np.random.default_rng(0)
    DT = jnp.int8 if os.environ.get("DT") == "i8" else jnp.bfloat16
    if DT == jnp.int8:
        L = jnp.asarray(rng.integers(-127, 127, size=(M, TILE)).astype(np.int8))
        R = jnp.asarray(rng.integers(0, 2, size=(FW, TILE)).astype(np.int8))
    else:
        L = jnp.asarray(rng.normal(size=(M, TILE)).astype(np.float32)).astype(DT)
        R = jnp.asarray(rng.normal(size=(FW, TILE)).astype(np.float32)).astype(DT)

    @jax.jit
    def loop(L, R, s0):
        def body(i, carry):
            s, L = carry
            # LCHAIN dependent contractions back-to-back (the fused
            # multi-level window's issue pattern): each left operand
            # perturbed by the previous output so Mosaic can't CSE
            for _ in range(LCHAIN):
                out = call(L, R)
                L = (L + (out[0, 0] * 1e-20).astype(L.dtype)
                     if L.dtype != jnp.int8 else
                     L ^ (out[0, 0].astype(jnp.int32) % 2).astype(jnp.int8))
            return s + out[0, 0], L
        return jax.lax.fori_loop(0, REPS, body, (s0, L))

    out = loop(L, R, 0.0)
    _ = float(jax.device_get(out[0]))
    t0 = time.time()
    out2 = loop(L, R, 1e-7)
    _ = float(jax.device_get(out2[0]))
    dt = (time.time() - t0) / (REPS * LCHAIN)
    flops = 2 * M * FW * ROWS
    tag = f" L={LCHAIN}" if LCHAIN > 1 else ""
    print(f"M={M:4d}:{tag} {dt*1000:7.3f} ms/contraction  "
          f"({flops/dt/1e12:6.1f} TFLOP/s)", flush=True)


if __name__ == "__main__":
    for M in (map(int, sys.argv[1:]) if len(sys.argv) > 1
              else (6, 24, 96, 128, 256)):
        run(M)
