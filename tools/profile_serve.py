"""Serving-path stage profiler: where does a scored row's time go?

Trains a small GBM, deploys it (h2o3_tpu.serve), drives a mixed
single-row + batched load through the micro-batcher, and prints the
stage attribution the batcher records per batch:

  encode  — dict rows → padded float32 matrix (RowCodec / rows_to_matrix)
  queue   — first-enqueue → batch pick-up (the micro-batching tick)
  device  — dispatch + device execution + result fetch
  decode  — host scores → per-row prediction dicts

plus deploy-time warm-compile cost per batch bucket. Stage numbers come
from the telemetry registry (ISSUE 4): ServeStats is a view over the
process-wide metrics the REST endpoints export, and the per-batch
``serve.*`` spans land in the same registry — so this tool, GET
/3/Serve/stats and GET /metrics can never disagree. The warm-path XLA
compile count (production ``h2o3_xla_compiles_total``) is asserted-by-
reporting: it must be 0 after deploy. One JSON line on stdout (same
contract as tools/profile_train.py / profile_ingest.py).

Knobs: H2O3_SERVE_PROF_ROWS (train rows, default 50k),
H2O3_SERVE_PROF_REQUESTS (single-row requests, default 500),
H2O3_SERVE_PROF_BATCH (batched request size, default 512).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    import h2o3_tpu as h2o
    from h2o3_tpu import serve, telemetry
    from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator

    telemetry.install()
    if not telemetry.enabled():
        log("H2O3_TELEMETRY=0: span/compile attribution unavailable — "
            "those fields will be empty (stats still report)")

    rows_n = int(os.environ.get("H2O3_SERVE_PROF_ROWS", 50_000))
    n_req = int(os.environ.get("H2O3_SERVE_PROF_REQUESTS", 500))
    bsz = int(os.environ.get("H2O3_SERVE_PROF_BATCH", 512))
    rng = np.random.default_rng(7)
    F = 12
    X = rng.normal(size=(rows_n, F)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] + 0.3 * rng.normal(size=rows_n) > 0)
    cols = {f"f{i}": X[:, i] for i in range(F)}
    cols["label"] = np.where(y, "YES", "NO")
    fr = h2o.Frame.from_numpy(cols)

    gbm = H2OGradientBoostingEstimator(ntrees=20, max_depth=5, seed=1)
    t0 = time.time()
    gbm.train(y="label", training_frame=fr)
    log(f"trained in {time.time() - t0:.1f}s")
    model = gbm.model
    model.key = "profile_serve_gbm"

    t0 = time.time()
    dep = serve.deploy(model.key, model=model, max_batch=4096,
                       max_delay_ms=1.0, queue_limit=65536)
    deploy_s = time.time() - t0
    log(f"deployed in {deploy_s:.2f}s; per-bucket warm compile: "
        f"{ {b: round(s, 3) for b, s in dep.scorer.warm_seconds.items()} }")

    names = [f"f{i}" for i in range(F)]
    pool = [{n: float(X[i, j]) for j, n in enumerate(names)}
            for i in range(min(rows_n, 8192))]

    # warm-path compile guard: everything after deploy must compile 0
    # XLA modules — tracked by the PRODUCTION counter, not a test shim
    compiles0 = telemetry.registry().value("h2o3_xla_compiles_total")

    # phase 1: sequential single-row requests (latency path)
    for i in range(n_req):
        dep.predict_rows([pool[i % len(pool)]])
    single = dep.stats.snapshot()

    # phase 2: batched requests (throughput path) — fresh stage counters
    # come from the delta against phase 1's snapshot. Optionally under
    # an xprof capture (shared helper, --xprof-trace / XPROF_TRACE_DIR)
    # for kernel-level attribution of the scoring dispatches
    from h2o3_tpu.telemetry.profiling import last_trace_dir, profile
    n_batches = 32
    with profile("serve_batched", log=log):
        # timed INSIDE the capture: start/stop_trace (trace
        # serialization is hundreds of ms) must not skew the verdict
        t0 = time.time()
        for i in range(n_batches):
            dep.predict_rows(pool[:bsz])
        batch_wall = time.time() - t0
    total = dep.stats.snapshot()

    # phase 3: SAME load through the columnar response path — one
    # vectorized decode per batch instead of per-row dicts (the decode
    # stage delta shows the win; values bit-match the row path)
    t0 = time.time()
    for i in range(n_batches):
        dep.predict_columnar(pool[:bsz])
    col_wall = time.time() - t0
    col_total = dep.stats.snapshot()

    def stage_split(snap, rows):
        ms = snap["stage_ms"]
        tot = sum(ms.values()) or 1.0
        return {s: {"ms_total": round(v, 2),
                    "share": round(v / tot, 4),
                    "us_per_row": round(1e3 * v / max(rows, 1), 2)}
                for s, v in ms.items()}

    batch_stage = {s: total["stage_ms"][s] - single["stage_ms"][s]
                   for s in total["stage_ms"]}
    batch_rows = total["rows"] - single["rows"]
    col_stage = {s: col_total["stage_ms"][s] - total["stage_ms"][s]
                 for s in col_total["stage_ms"]}
    col_rows = col_total["rows"] - total["rows"]
    # per-deployment roofline (ISSUE 11): warm-bucket executable cost x
    # dispatched batches over the measured device stage — printed next
    # to the stage split, captured in the same run as the xprof trace
    perf = dep.perf_snapshot()
    if perf:
        log(f"roofline[serve]: "
            f"{perf['achieved_flops'] / 1e9:.3f} GFLOP/s  "
            f"{perf['achieved_bytes_per_s'] / 1e9:.3f} GB/s  "
            f"AI={perf['arith_intensity']} flop/B "
            f"(ridge {perf['ridge_intensity']})  "
            f"mfu={perf['mfu']}  {perf['roofline_regime']}  "
            f"peaks={perf['peak_source']}"
            + (" [informational]" if perf.get("informational") else ""))

    out = {
        "metric": "serve_stage_profile",
        "deploy_seconds": round(deploy_s, 3),
        "warm_compile_seconds": {
            str(b): round(s, 3)
            for b, s in dep.scorer.warm_seconds.items()},
        "single_row": {
            "requests": n_req,
            "p50_ms": single["p50_ms"], "p99_ms": single["p99_ms"],
            "stages": stage_split(single, single["rows"]),
        },
        "batched": {
            "batch_size": bsz, "batches": n_batches,
            "rows_per_sec": round(batch_rows / max(batch_wall, 1e-9), 1),
            "stages": {s: round(v, 2) for s, v in batch_stage.items()},
            "us_per_row": {s: round(1e3 * v / max(batch_rows, 1), 2)
                           for s, v in batch_stage.items()},
        },
        # columnar response path (?format=columnar / predict_columnar):
        # identical encode/device work, vectorized decode — compare
        # decode us_per_row and rows_per_sec against "batched" above
        "batched_columnar": {
            "batch_size": bsz, "batches": n_batches,
            "rows_per_sec": round(col_rows / max(col_wall, 1e-9), 1),
            "us_per_row": {s: round(1e3 * v / max(col_rows, 1), 2)
                           for s, v in col_stage.items()},
            "decode_speedup": round(
                max(batch_stage.get("decode", 0.0), 1e-9)
                / max(col_stage.get("decode", 1e-9), 1e-9), 2),
        },
        "bucket_fill": total["bucket_fill"],
        "warm_compiles": int(telemetry.registry().value(
            "h2o3_xla_compiles_total") - compiles0),
        # span-level view of the same run (counts prove every batch got
        # stage spans; seconds match the stage_ms sums above)
        "spans": telemetry.stage_seconds("serve."),
        "perf": perf,
        "xprof_trace_dir": last_trace_dir(),
    }
    serve.undeploy(model.key)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
