"""Ingest stage profiler — attribute parse time to its pipeline stages.

Writes a synthetic mixed-type CSV (numeric, enum, time columns with NA
sentinels), runs the REAL end-to-end ``parse()`` (mmap byte-range
fan-out), and reads the stage attribution from the telemetry spans the
pipeline itself records (h2o3_tpu.telemetry): scan (mmap + quote-safe
range discovery), tokenize_encode (native C scan + chunk-local typed
encode, split into tokenize/encode CPU-seconds by the worker stats),
domain_union (enum merge + LUT remap) and device_put (pack + host→device
transfer), plus the h2d transfer-byte counter. The tool keeps NO timers
of its own around pipeline stages — the numbers here are the SAME ones
``GET /metrics`` and ``GET /3/Telemetry`` export, so the tool-reported
and REST-reported splits cannot disagree (ISSUE 4).

Prints ONE JSON line (plus a human per-stage MB/s table on stderr) so a
future ingest regression is attributable to a stage, not just "parse
got slower" — the table is the "where does the next 2x live" artifact
ISSUE 14 asks for. Any byte range that fell back to the Python
tokenizer is listed with its reason; a healthy run shows
``fallback_ranges: 0``.

Args / env knobs: ``--rows N --cols K`` (numeric column count; enum and
time columns ride along via NCOL_ENUM / NCOL_TIME) synthesize the CSV
without a fixture file, so the >=2x claim reproduces anywhere; ``--csv
PATH`` (or CSV env) reuses an existing file; ROWS / NCOL_NUM env still
work for the older driver scripts.
"""
import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _synth_csv(path, rows, ncol_num, ncol_enum, ncol_time):
    rng = np.random.default_rng(11)
    cities = np.array(["ames", "berlin", "cairo", "delhi", "el-paso",
                       "fargo", "galway", "hanoi"])
    header = ([f"n{i}" for i in range(ncol_num)]
              + [f"e{i}" for i in range(ncol_enum)]
              + [f"t{i}" for i in range(ncol_time)])
    log(f"writing {path} ({rows} rows x {len(header)} cols) ...")
    t0 = time.time()
    tmp = path + ".part"
    with open(tmp, "w") as f:
        f.write(",".join(header) + "\n")
        chunk = 200_000
        for s in range(0, rows, chunk):
            e = min(s + chunk, rows)
            cols = []
            for i in range(ncol_num):
                v = np.char.mod("%.6g", rng.normal(size=e - s))
                v[rng.random(e - s) < 0.01] = "NA"
                cols.append(v)
            for i in range(ncol_enum):
                cols.append(cities[rng.integers(0, len(cities), e - s)])
            for i in range(ncol_time):
                days = rng.integers(0, 3650, e - s)
                d = (np.datetime64("2015-01-01") + days).astype(str)
                cols.append(d)
            mat = np.stack(cols, axis=1)
            block = [",".join(row) for row in mat]
            f.write("\n".join(block) + "\n")
    os.replace(tmp, path)
    log(f"csv written in {time.time() - t0:.1f}s")


def _profile_once(path, setup):
    """Run ONE measured parse of ``path`` and return the stage-split
    dict (the JSON-line payload). Factored out so the ``--workers``
    sweep reruns the identical measurement under each pool size."""
    from h2o3_tpu import telemetry
    from h2o3_tpu.ingest.parse import LAST_PROFILE, parse

    # counters are cumulative — diff against the pre-run snapshot
    h2d0 = telemetry.registry().value("h2o3_h2d_bytes_total")
    stages0 = telemetry.stage_seconds("ingest.")

    # optional xprof capture of the parse (shared helper, SNIPPETS [1]
    # shape): --xprof-trace [DIR] / XPROF_TRACE_DIR, else a no-op
    from h2o3_tpu.telemetry.profiling import last_trace_dir, profile
    with profile("ingest_parse", log=log):
        # timed INSIDE the capture: start/stop_trace (trace
        # serialization is hundreds of ms) must not skew the verdict
        t0 = time.perf_counter()
        fr = parse([path], setup)
        wall = time.perf_counter() - t0

    # ONE scrape for every stage read (each samples() pass runs the
    # collector views, incl. an O(live arrays) device-memory walk)
    stages1 = telemetry.stage_seconds(
        "ingest.", samples=telemetry.registry().samples())

    def stage(name):
        tot = stages1.get(name, {})
        pre = stages0.get(name, {})
        # no new span observations (telemetry off) → null, never a fake
        # "0.0s stage" datapoint
        if tot.get("count", 0) == pre.get("count", 0):
            return None
        return round(tot.get("seconds", 0.0) - pre.get("seconds", 0.0), 4)

    nbytes = os.path.getsize(path)
    out = {"rows": fr.nrow, "ncol": fr.ncol,
           "bytes": nbytes,
           "native": LAST_PROFILE.get("native"),
           "chunks": LAST_PROFILE.get("chunks"),
           "streamed": LAST_PROFILE.get("streamed"),
           # range-scoped fallback visibility (ISSUE 14): a healthy run
           # parses every range natively
           "fallback_ranges": LAST_PROFILE.get("fallback_ranges"),
           "fallback_reasons": LAST_PROFILE.get("fallback_reasons"),
           # stage split read from the pipeline's OWN telemetry spans —
           # identical to what GET /metrics exports for the same run
           "scan_s": stage("ingest.scan"),
           "tokenize_encode_s": stage("ingest.tokenize_encode"),
           "domain_union_s": stage("ingest.domain_union"),
           "device_put_s": stage("ingest.device_put"),
           # worker-pool CPU-second split of tokenize_encode (summed
           # across threads, so they exceed the wall split above under
           # fan-out — they answer "which half is the CPU spent in")
           "tokenize_cpu_s": LAST_PROFILE.get("tokenize_cpu_s"),
           "encode_cpu_s": LAST_PROFILE.get("encode_cpu_s"),
           # per-chunk streamed transfer: share of device_put wall time
           # hidden under tokenize (same number the pipeline exports as
           # the h2o3_ingest_h2d_overlap_ratio gauge)
           "h2d_overlap_ratio": LAST_PROFILE.get("h2d_overlap_ratio"),
           "h2d_bytes": round(
               telemetry.registry().value("h2o3_h2d_bytes_total") - h2d0),
           "parse_wall_s": round(wall, 4),
           "parse_rows_per_s": round(fr.nrow / wall, 1),
           "parse_mb_per_s": round(nbytes / 1e6 / wall, 1),
           "xprof_trace_dir": last_trace_dir()}
    return out


def _gil_wait_estimate(out, workers):
    """Estimated thread-seconds the tokenize_encode pool spent NOT
    running Python/C work: ``workers`` threads were nominally live for
    the stage's wall time, and the worker stats say how many CPU-seconds
    they actually burned — the gap is GIL contention + pool idle. A
    nogil-healthy encode keeps this near zero as workers grow; a
    GIL-bound one grows it linearly."""
    te = out.get("tokenize_encode_s")
    cpu = (out.get("tokenize_cpu_s") or 0.0) + (out.get("encode_cpu_s")
                                                or 0.0)
    if te is None or cpu <= 0.0:
        return None
    return round(max(0.0, workers * te - cpu), 4)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="profile the ingest parse pipeline per stage")
    ap.add_argument("--rows", type=int,
                    default=int(os.environ.get("ROWS", 2_000_000)))
    ap.add_argument("--cols", type=int,
                    default=int(os.environ.get("NCOL_NUM", 6)),
                    help="numeric column count of the synthetic CSV")
    ap.add_argument("--enum-cols", type=int,
                    default=int(os.environ.get("NCOL_ENUM", 2)))
    ap.add_argument("--time-cols", type=int,
                    default=int(os.environ.get("NCOL_TIME", 1)))
    ap.add_argument("--csv", default=os.environ.get("CSV"),
                    help="reuse an existing CSV instead of synthesizing")
    ap.add_argument("--workers", default=os.environ.get("WORKERS"),
                    help="comma list of pool sizes (e.g. 1,4,8,16): "
                         "rerun the parse per size and report the "
                         "scaling + GIL-wait table")
    args = ap.parse_args(argv)

    from h2o3_tpu import telemetry
    from h2o3_tpu.ingest.parse import parse_setup

    telemetry.install()
    if not telemetry.enabled():
        log("H2O3_TELEMETRY=0: stage attribution unavailable — stage "
            "fields will be null (re-run with telemetry enabled)")
    path = args.csv or os.path.join(
        tempfile.gettempdir(),
        f"h2o3_profile_ingest_{args.rows}x{args.cols}"
        f"_{args.enum_cols}_{args.time_cols}.csv")
    if not os.path.exists(path):
        _synth_csv(path, args.rows, args.cols, args.enum_cols,
                   args.time_cols)
    setup = parse_setup(path)
    nbytes = os.path.getsize(path)

    if args.workers:
        # worker-scaling sweep: same file, same setup, pool size forced
        # per run via the env knob parse() reads. The per-size GIL-wait
        # estimate is the nogil-encode scaling artifact ISSUE 16 asks
        # for: flat ≈0 means the native encode really released the GIL.
        sizes = [int(w) for w in str(args.workers).split(",") if w]
        prev = os.environ.get("H2O3_INGEST_WORKERS")
        sweep = []
        try:
            for w in sizes:
                os.environ["H2O3_INGEST_WORKERS"] = str(w)
                r = _profile_once(path, setup)
                sweep.append({
                    "workers": w,
                    "parse_mb_per_s": r["parse_mb_per_s"],
                    "tokenize_encode_s": r.get("tokenize_encode_s"),
                    "tokenize_cpu_s": r.get("tokenize_cpu_s"),
                    "encode_cpu_s": r.get("encode_cpu_s"),
                    "gil_wait_est_s": _gil_wait_estimate(r, w),
                    "fallback_ranges": r.get("fallback_ranges")})
        finally:
            if prev is None:
                os.environ.pop("H2O3_INGEST_WORKERS", None)
            else:
                os.environ["H2O3_INGEST_WORKERS"] = prev
        log(f"\n  workers   MB/s   tok+enc wall   cpu-s   GIL-wait est")
        for s in sweep:
            te = s["tokenize_encode_s"]
            cpu = (s["tokenize_cpu_s"] or 0) + (s["encode_cpu_s"] or 0)
            gw = s["gil_wait_est_s"]
            log(f"  {s['workers']:>7} {s['parse_mb_per_s']:>6.1f}"
                f"   {te if te is not None else float('nan'):>12.3f}"
                f"   {cpu:>5.2f}"
                f"   {gw if gw is not None else float('nan'):>12.3f}")
        out = {"bytes": nbytes, "csv": path, "worker_sweep": sweep}
        print(json.dumps(out))
        return out

    out = _profile_once(path, setup)
    wall = out["parse_wall_s"]

    # the "where does the next 2x live" table: per-stage seconds and
    # effective MB/s over the file's bytes (wall stages are additive;
    # the cpu-second rows attribute the tokenize_encode wall)
    log(f"\n  stage               seconds   MB/s (of {nbytes / 1e6:.0f} MB)")
    for label, key, kind in (
            ("scan (ranges)", "scan_s", "wall"),
            ("tokenize_encode", "tokenize_encode_s", "wall"),
            ("  tokenize (cpu)", "tokenize_cpu_s", "cpu"),
            ("  encode   (cpu)", "encode_cpu_s", "cpu"),
            ("domain_union", "domain_union_s", "wall"),
            ("device_put", "device_put_s", "wall")):
        v = out.get(key)
        if v is None:
            log(f"  {label:<19} {'-':>7}")
            continue
        rate = nbytes / 1e6 / v if v > 0 else float("inf")
        log(f"  {label:<19} {v:>7.3f}   {rate:,.0f}")
    log(f"  {'TOTAL parse wall':<19} {wall:>7.3f}   "
        f"{out['parse_mb_per_s']:,.1f}")
    if out.get("fallback_ranges"):
        log(f"  fallback ranges: {out['fallback_ranges']} "
            f"({out['fallback_reasons']})")

    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
