"""Ingest stage profiler — attribute parse time to its pipeline stages.

Writes a synthetic mixed-type CSV (numeric, enum, time columns with NA
sentinels), then times the four stages of the streaming parse pipeline
separately on one chunk — tokenize (native C scan, fast_csv.cpp),
encode (chunk-local typed columns + enum dictionaries, ingest/chunk.py),
domain-union merge, and the batched host→device transfer — plus the real
end-to-end ``parse()`` (byte-range fan-out) for the wall-clock number.
Prints ONE JSON line so a future ingest regression is attributable to a
stage, not just "parse got slower".

Env knobs: ROWS (default 2M), NCOL_NUM / NCOL_ENUM / NCOL_TIME,
CSV (reuse an existing file instead of synthesizing).
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

ROWS = int(os.environ.get("ROWS", 2_000_000))
NCOL_NUM = int(os.environ.get("NCOL_NUM", 6))
NCOL_ENUM = int(os.environ.get("NCOL_ENUM", 2))
NCOL_TIME = int(os.environ.get("NCOL_TIME", 1))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _synth_csv(path):
    rng = np.random.default_rng(11)
    cities = np.array(["ames", "berlin", "cairo", "delhi", "el-paso",
                       "fargo", "galway", "hanoi"])
    header = ([f"n{i}" for i in range(NCOL_NUM)]
              + [f"e{i}" for i in range(NCOL_ENUM)]
              + [f"t{i}" for i in range(NCOL_TIME)])
    log(f"writing {path} ({ROWS} rows x {len(header)} cols) ...")
    t0 = time.time()
    with open(path, "w") as f:
        f.write(",".join(header) + "\n")
        chunk = 200_000
        for s in range(0, ROWS, chunk):
            e = min(s + chunk, ROWS)
            cols = []
            for i in range(NCOL_NUM):
                v = np.char.mod("%.6g", rng.normal(size=e - s))
                v[rng.random(e - s) < 0.01] = "NA"
                cols.append(v)
            for i in range(NCOL_ENUM):
                cols.append(cities[rng.integers(0, len(cities), e - s)])
            for i in range(NCOL_TIME):
                days = rng.integers(0, 3650, e - s)
                d = (np.datetime64("2015-01-01") + days).astype(str)
                cols.append(d)
            mat = np.stack(cols, axis=1)
            block = [",".join(row) for row in mat]
            f.write("\n".join(block) + "\n")
    log(f"csv written in {time.time() - t0:.1f}s")


def main():
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.ingest.chunk import encode_chunk_native, merge_columns
    from h2o3_tpu.ingest.parse import LAST_PROFILE, parse, parse_setup
    from h2o3_tpu.native import parse_bytes

    path = os.environ.get("CSV") or os.path.join(
        tempfile.gettempdir(), f"h2o3_profile_ingest_{ROWS}.csv")
    if not os.path.exists(path):
        _synth_csv(path)
    setup = parse_setup(path)
    with open(path, "rb") as f:
        data = f.read()

    # actual row count, not the ROWS knob — CSV= may point at any file
    nrow = (data.count(b"\n")
            + (0 if (not data or data.endswith(b"\n")) else 1)
            - (1 if setup.header else 0))
    out = {"rows": nrow, "ncol": len(setup.column_names),
           "bytes": len(data)}

    # stage 1: tokenize — the native C scan alone (offsets + doubles)
    t0 = time.perf_counter()
    tok = parse_bytes(data, setup.separator)
    t1 = time.perf_counter()
    if tok is None:
        out["tokenize_s"] = None
        log("native tokenizer unavailable/declined; stage split skipped")
    else:
        out["tokenize_s"] = round(t1 - t0, 4)
        # stage 2: encode — typed columns + chunk-local enum dictionaries
        # (encode_chunk_native re-tokenizes; its own time minus stage 1
        # is the encode share)
        t2 = time.perf_counter()
        cols = encode_chunk_native(data, setup, setup.header)
        t3 = time.perf_counter()
        out["encode_s"] = round((t3 - t2) - (t1 - t0), 4)
        # stage 3: domain union + LUT remap across (here: one) chunks
        t4 = time.perf_counter()
        merged = merge_columns([cols], setup.column_types)
        t5 = time.perf_counter()
        out["domain_union_s"] = round(t5 - t4, 4)
        # stage 4: batched host→device transfer (one DMA per dtype group)
        t6 = time.perf_counter()
        fr = Frame.from_typed_columns(setup.column_names, merged)
        for v in fr.vecs:
            if v.data is not None:
                v.data.block_until_ready()
        t7 = time.perf_counter()
        out["device_put_s"] = round(t7 - t6, 4)

    # end-to-end: the real parallel parse (fan-out + overlap), wall clock
    t8 = time.perf_counter()
    fr = parse([path], setup)
    t9 = time.perf_counter()
    out["parse_wall_s"] = round(t9 - t8, 4)
    out["parse_rows_per_s"] = round(fr.nrow / (t9 - t8), 1)
    out["parallel_profile"] = dict(LAST_PROFILE)
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
