"""Ingest stage profiler — attribute parse time to its pipeline stages.

Writes a synthetic mixed-type CSV (numeric, enum, time columns with NA
sentinels), runs the REAL end-to-end ``parse()`` (byte-range fan-out),
and reads the stage attribution from the telemetry spans the pipeline
itself records (h2o3_tpu.telemetry): tokenize_encode (native C scan +
chunk-local typed encode), domain_union (enum merge + LUT remap) and
device_put (batched host→device transfer), plus the h2d transfer-byte
counter at the ``batch_device_put`` choke point. The tool keeps NO
timers of its own around pipeline stages — the numbers here are the
SAME ones ``GET /metrics`` and ``GET /3/Telemetry`` export, so the
tool-reported and REST-reported splits cannot disagree (ISSUE 4).

Prints ONE JSON line so a future ingest regression is attributable to a
stage, not just "parse got slower".

Env knobs: ROWS (default 2M), NCOL_NUM / NCOL_ENUM / NCOL_TIME,
CSV (reuse an existing file instead of synthesizing).
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

ROWS = int(os.environ.get("ROWS", 2_000_000))
NCOL_NUM = int(os.environ.get("NCOL_NUM", 6))
NCOL_ENUM = int(os.environ.get("NCOL_ENUM", 2))
NCOL_TIME = int(os.environ.get("NCOL_TIME", 1))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _synth_csv(path):
    rng = np.random.default_rng(11)
    cities = np.array(["ames", "berlin", "cairo", "delhi", "el-paso",
                       "fargo", "galway", "hanoi"])
    header = ([f"n{i}" for i in range(NCOL_NUM)]
              + [f"e{i}" for i in range(NCOL_ENUM)]
              + [f"t{i}" for i in range(NCOL_TIME)])
    log(f"writing {path} ({ROWS} rows x {len(header)} cols) ...")
    t0 = time.time()
    with open(path, "w") as f:
        f.write(",".join(header) + "\n")
        chunk = 200_000
        for s in range(0, ROWS, chunk):
            e = min(s + chunk, ROWS)
            cols = []
            for i in range(NCOL_NUM):
                v = np.char.mod("%.6g", rng.normal(size=e - s))
                v[rng.random(e - s) < 0.01] = "NA"
                cols.append(v)
            for i in range(NCOL_ENUM):
                cols.append(cities[rng.integers(0, len(cities), e - s)])
            for i in range(NCOL_TIME):
                days = rng.integers(0, 3650, e - s)
                d = (np.datetime64("2015-01-01") + days).astype(str)
                cols.append(d)
            mat = np.stack(cols, axis=1)
            block = [",".join(row) for row in mat]
            f.write("\n".join(block) + "\n")
    log(f"csv written in {time.time() - t0:.1f}s")


def main():
    from h2o3_tpu import telemetry
    from h2o3_tpu.ingest.parse import LAST_PROFILE, parse, parse_setup

    telemetry.install()
    if not telemetry.enabled():
        log("H2O3_TELEMETRY=0: stage attribution unavailable — stage "
            "fields will be null (re-run with telemetry enabled)")
    path = os.environ.get("CSV") or os.path.join(
        tempfile.gettempdir(), f"h2o3_profile_ingest_{ROWS}.csv")
    if not os.path.exists(path):
        _synth_csv(path)
    setup = parse_setup(path)

    # counters are cumulative — diff against the pre-run snapshot
    h2d0 = telemetry.registry().value("h2o3_h2d_bytes_total")
    stages0 = telemetry.stage_seconds("ingest.")

    # optional xprof capture of the parse (shared helper, SNIPPETS [1]
    # shape): --xprof-trace [DIR] / XPROF_TRACE_DIR, else a no-op
    from h2o3_tpu.telemetry.profiling import last_trace_dir, profile
    with profile("ingest_parse", log=log):
        # timed INSIDE the capture: start/stop_trace (trace
        # serialization is hundreds of ms) must not skew the verdict
        t0 = time.perf_counter()
        fr = parse([path], setup)
        wall = time.perf_counter() - t0

    # ONE scrape for every stage read (each samples() pass runs the
    # collector views, incl. an O(live arrays) device-memory walk)
    stages1 = telemetry.stage_seconds(
        "ingest.", samples=telemetry.registry().samples())

    def stage(name):
        tot = stages1.get(name, {})
        pre = stages0.get(name, {})
        # no new span observations (telemetry off) → null, never a fake
        # "0.0s stage" datapoint
        if tot.get("count", 0) == pre.get("count", 0):
            return None
        return round(tot.get("seconds", 0.0) - pre.get("seconds", 0.0), 4)

    out = {"rows": fr.nrow, "ncol": fr.ncol,
           "bytes": os.path.getsize(path),
           "native": LAST_PROFILE.get("native"),
           "chunks": LAST_PROFILE.get("chunks"),
           "streamed": LAST_PROFILE.get("streamed"),
           # stage split read from the pipeline's OWN telemetry spans —
           # identical to what GET /metrics exports for the same run
           "tokenize_encode_s": stage("ingest.tokenize_encode"),
           "domain_union_s": stage("ingest.domain_union"),
           "device_put_s": stage("ingest.device_put"),
           # per-chunk streamed transfer: share of device_put wall time
           # hidden under tokenize (same number the pipeline exports as
           # the h2o3_ingest_h2d_overlap_ratio gauge)
           "h2d_overlap_ratio": LAST_PROFILE.get("h2d_overlap_ratio"),
           "h2d_bytes": round(
               telemetry.registry().value("h2o3_h2d_bytes_total") - h2d0),
           "parse_wall_s": round(wall, 4),
           "parse_rows_per_s": round(fr.nrow / wall, 1),
           "xprof_trace_dir": last_trace_dir()}
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
