"""Estimator namespace mirroring h2o-py's ``h2o.estimators`` imports
(h2o-py/h2o/estimators/__init__.py — generated there by h2o-bindings;
hand-maintained here)."""
from h2o3_tpu.models.aggregator import H2OAggregatorEstimator
from h2o3_tpu.models.anovaglm import H2OANOVAGLMEstimator
from h2o3_tpu.models.coxph import H2OCoxProportionalHazardsEstimator
from h2o3_tpu.models.infogram import H2OInfogram
from h2o3_tpu.models.misc_models import (H2OGenericEstimator,
                                         H2OGrepEstimator)
from h2o3_tpu.models.targetencoder import H2OTargetEncoderEstimator
from h2o3_tpu.models.psvm import H2OSupportVectorMachineEstimator
from h2o3_tpu.models.uplift import H2OUpliftRandomForestEstimator
from h2o3_tpu.models.word2vec import H2OWord2vecEstimator
from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
from h2o3_tpu.models.gam import H2OGeneralizedAdditiveEstimator
from h2o3_tpu.models.glrm import H2OGeneralizedLowRankEstimator
from h2o3_tpu.models.modelselection import H2OModelSelectionEstimator
from h2o3_tpu.models.rulefit import H2ORuleFitEstimator
from h2o3_tpu.models.drf import H2ORandomForestEstimator
from h2o3_tpu.models.ensemble import H2OStackedEnsembleEstimator
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
from h2o3_tpu.models.isoforest import H2OIsolationForestEstimator
from h2o3_tpu.models.isoforextended import \
    H2OExtendedIsolationForestEstimator
from h2o3_tpu.models.isotonic import H2OIsotonicRegressionEstimator
from h2o3_tpu.models.kmeans import H2OKMeansEstimator
from h2o3_tpu.models.naivebayes import H2ONaiveBayesEstimator
from h2o3_tpu.models.pca import H2OPrincipalComponentAnalysisEstimator
from h2o3_tpu.models.svd import H2OSingularValueDecompositionEstimator
from h2o3_tpu.models.xgboost import H2OXGBoostEstimator

__all__ = [
    "H2OAggregatorEstimator", "H2OANOVAGLMEstimator",
    "H2OCoxProportionalHazardsEstimator", "H2OInfogram",
    "H2OGenericEstimator", "H2OGrepEstimator",
    "H2OTargetEncoderEstimator",
    "H2OSupportVectorMachineEstimator",
    "H2OUpliftRandomForestEstimator", "H2OWord2vecEstimator",
    "H2OGeneralizedAdditiveEstimator", "H2OGeneralizedLowRankEstimator",
    "H2OModelSelectionEstimator",
    "H2ORuleFitEstimator", "H2ODeepLearningEstimator",
    "H2ORandomForestEstimator", "H2OStackedEnsembleEstimator",
    "H2OGradientBoostingEstimator", "H2OGeneralizedLinearEstimator",
    "H2OIsolationForestEstimator", "H2OExtendedIsolationForestEstimator",
    "H2OIsotonicRegressionEstimator", "H2OKMeansEstimator",
    "H2ONaiveBayesEstimator", "H2OPrincipalComponentAnalysisEstimator",
    "H2OSingularValueDecompositionEstimator", "H2OXGBoostEstimator",
]
