"""Per-deployment serving telemetry: latency percentiles, stage
attribution, queue/batch occupancy and request/error counters.

The reference has no online-serving telemetry to mirror (h2o-3 scores
frames, not request streams); the shape here follows what
`/3/Serve/stats` needs to answer: is the path keeping its latency SLO
(p50/p99), where does a request's time go (encode/queue/device/decode),
and is the batcher actually coalescing (mean batch occupancy).

Lock discipline: one mutex per ServeStats, every mutation is a single
short critical section — this sits on the request hot path.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

# ring-buffer length for the latency reservoir: enough for stable p99
# estimates over the recent window without unbounded growth
_RESERVOIR = 4096

STAGES = ("encode", "queue", "device", "decode")


class ServeStats:
    def __init__(self):
        self._mu = threading.Lock()
        self._lat_ms = np.zeros(_RESERVOIR, np.float64)
        self._lat_n = 0            # total recorded (ring index = n % size)
        self.requests = 0          # client-visible request count
        self.rows = 0              # rows scored
        self.batches = 0           # device batches dispatched
        self.batch_rows = 0        # live rows across those batches
        self.padded_rows = 0       # bucket-padded rows across them
        self.errors = 0            # scoring failures surfaced to clients
        self.timeouts = 0          # per-request deadline expiries
        self.rejected = 0          # admission-control rejections (503)
        self.stage_ms: Dict[str, float] = {s: 0.0 for s in STAGES}
        self.queue_depth = 0       # rows currently admitted, not resolved

    # -- mutation (hot path) -------------------------------------------

    def record_request(self, latency_ms: float, rows: int):
        with self._mu:
            self._lat_ms[self._lat_n % _RESERVOIR] = latency_ms
            self._lat_n += 1
            self.requests += 1
            self.rows += rows

    def record_batch(self, live_rows: int, padded_rows: int,
                     stage_ms: Dict[str, float]):
        with self._mu:
            self.batches += 1
            self.batch_rows += live_rows
            self.padded_rows += padded_rows
            for s, v in stage_ms.items():
                self.stage_ms[s] = self.stage_ms.get(s, 0.0) + v

    def record_error(self):
        with self._mu:
            self.errors += 1

    def record_timeout(self):
        with self._mu:
            self.timeouts += 1

    def record_rejected(self):
        with self._mu:
            self.rejected += 1

    def queue_delta(self, rows: int):
        with self._mu:
            self.queue_depth += rows

    # -- snapshot -------------------------------------------------------

    def percentiles_ms(self, qs: List[float]) -> List[Optional[float]]:
        """All requested quantiles from ONE copy of the latency ring —
        separate calls would sample different windows under concurrent
        recording (a snapshot could then report p99 < p50)."""
        with self._mu:
            n = min(self._lat_n, _RESERVOIR)
            window = self._lat_ms[:n].copy() if n else None
        if window is None:
            return [None] * len(qs)
        return [float(np.percentile(window, q)) for q in qs]

    def percentile_ms(self, q: float) -> Optional[float]:
        return self.percentiles_ms([q])[0]

    def snapshot(self) -> Dict:
        p50, p99 = self.percentiles_ms([50, 99])
        with self._mu:
            occ = (self.batch_rows / self.batches) if self.batches else 0.0
            pad_eff = (self.batch_rows / self.padded_rows) \
                if self.padded_rows else 1.0
            return {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "errors": self.errors,
                "timeouts": self.timeouts,
                "rejected": self.rejected,
                "queue_depth": self.queue_depth,
                "mean_batch_occupancy": round(occ, 3),
                "bucket_fill": round(pad_eff, 4),
                "p50_ms": None if p50 is None else round(p50, 3),
                "p99_ms": None if p99 is None else round(p99, 3),
                "stage_ms": {s: round(v, 3)
                             for s, v in self.stage_ms.items()},
            }


def merge_snapshots(snaps: List[Dict]) -> Dict:
    """Cluster-level rollup for /3/Serve/stats: counters sum; the
    percentile fields do NOT aggregate across models (quantiles don't
    add) and are left to the per-model entries."""
    out = {"requests": 0, "rows": 0, "batches": 0, "errors": 0,
           "timeouts": 0, "rejected": 0, "queue_depth": 0,
           "stage_ms": {s: 0.0 for s in STAGES}}
    for s in snaps:
        for k in ("requests", "rows", "batches", "errors", "timeouts",
                  "rejected", "queue_depth"):
            out[k] += s.get(k, 0)
        for st, v in (s.get("stage_ms") or {}).items():
            out["stage_ms"][st] = out["stage_ms"].get(st, 0.0) + v
    out["stage_ms"] = {s: round(v, 3) for s, v in out["stage_ms"].items()}
    return out
