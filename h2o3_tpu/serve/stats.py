"""Per-deployment serving telemetry — registry views (ISSUE 4).

The PR-3 version kept a private mutex-guarded counter set per
deployment; those counters are now *views over the process-wide
telemetry registry* (h2o3_tpu.telemetry): every mutation lands in
lock-striped registry metrics labeled ``{model=<key>}``, so the same
numbers surface identically at ``/3/Serve/stats``, ``GET /metrics``
(Prometheus) and ``GET /3/Telemetry`` — one producer, three exports.

The latency reservoir (exact p50/p99 over the recent window) stays
local: quantiles don't reconstruct from fixed histogram buckets at the
precision the SLO view needs. The registry additionally gets a bucketed
``h2o3_serve_latency_ms`` histogram for Prometheus-side aggregation.

When the global registry is disabled (``H2O3_TELEMETRY=0``) a
deployment falls back to a PRIVATE always-on registry: /3/Serve/stats
keeps answering (the bench's serve round depends on it) while nothing
reaches the exported surface — and the disabled global registry costs
the serve path nothing.

Lock discipline: registry metrics use the striped locks; only the
reservoir keeps a per-deployment mutex, with every critical section a
couple of array writes.
"""
from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from h2o3_tpu.telemetry import registry as _global_registry
from h2o3_tpu.telemetry.registry import Registry

# ring-buffer length for the latency reservoir: enough for stable p99
# estimates over the recent window without unbounded growth
_RESERVOIR = 4096

# per-lane latency reservoirs (ISSUE 20) are smaller: three of them per
# deployment, and the lane-isolation verdict only needs a stable p99
# over the recent window, not deep history
_LANE_RESERVOIR = 2048

# slow-request exemplars kept per deployment: the top-k requests by
# latency, each carrying its trace id — /3/Serve/stats exposes them so a
# p99 spike resolves to concrete trace ids chaseable through
# /3/Timeline (ISSUE 8)
_SLOW_K = 10

# exemplar generations also rotate on wall clock, not just reservoir
# wrap: at low QPS 4096 requests can take DAYS, and a cold-start
# compile-era top-k would mask every later spike until then (their
# trace ids pointing at spans long evicted from the ring)
_SLOW_WINDOW_S = 900.0

STAGES = ("encode", "queue", "device", "decode")

# serve latency histogram bounds in ms (sub-ms micro-batch ticks up to
# deadline-scale)
_LAT_BOUNDS_MS = (0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0,
                  250.0, 1000.0, 10_000.0)


_ANON = [0]
_ANON_LOCK = threading.Lock()


class ServeStats:
    def __init__(self, model: str = ""):
        if not model:
            # anonymous stats (embedded/unit-test use) get a unique
            # label — sharing one "?" series across instances would
            # break the fresh-counters-per-instance contract
            with _ANON_LOCK:
                _ANON[0] += 1
                model = f"_anon_{_ANON[0]}"
        self.model = model
        reg = _global_registry()
        if not reg.enabled:
            # private always-on registry: the serve stats surface must
            # not go dark when exported telemetry is off
            reg = Registry(enabled=True)
        self._reg = reg
        lab = {"model": self.model}
        self._requests = reg.counter(
            "h2o3_serve_requests_total", lab,
            help="client-visible serve requests")
        self._rows = reg.counter(
            "h2o3_serve_rows_total", lab, help="rows scored")
        self._batches = reg.counter(
            "h2o3_serve_batches_total", lab,
            help="device batches dispatched")
        self._batch_rows = reg.counter(
            "h2o3_serve_batch_rows_total", lab,
            help="live rows across dispatched batches")
        self._padded_rows = reg.counter(
            "h2o3_serve_padded_rows_total", lab,
            help="bucket-padded rows across dispatched batches")
        self._errors = reg.counter(
            "h2o3_serve_errors_total", lab,
            help="scoring failures surfaced to clients")
        self._timeouts = reg.counter(
            "h2o3_serve_timeouts_total", lab,
            help="per-request deadline expiries")
        self._rejected = reg.counter(
            "h2o3_serve_rejected_total", lab,
            help="admission-control rejections (503)")
        self._retries = reg.counter(
            "h2o3_serve_retries_total", lab,
            help="single-retry recoveries of transient device-stage "
                 "failures")
        self._queue_depth = reg.gauge(
            "h2o3_serve_queue_depth", lab,
            help="rows admitted but not yet resolved")
        self._stage_ms = {s: reg.counter(
            "h2o3_serve_stage_ms_total", {**lab, "stage": s},
            help="cumulative per-stage milliseconds") for s in STAGES}
        self._latency = reg.histogram(
            "h2o3_serve_latency_ms", lab,
            help="request latency milliseconds", bounds=_LAT_BOUNDS_MS)
        self._mu = threading.Lock()
        self._lat_ms = np.zeros(_RESERVOIR, np.float64)
        self._lat_n = 0            # total recorded (ring index = n % size)
        # deadline-class lanes (ISSUE 20): per-lane latency reservoirs
        # (created on first use — a deployment that never sees a lane
        # pays nothing for it) + shed counters, so the lane-isolation
        # contract (interactive p99 under a bulk flood) is measurable
        # from /3/Serve/stats alone
        self._lane_lat: Dict[str, np.ndarray] = {}
        self._lane_n: Dict[str, int] = {}
        self._lane_shed: Dict[str, object] = {}
        self._lane_shed_base: Dict[str, float] = {}
        # top-k slow-request exemplars: a min-heap of
        # (latency_ms, seq, info) — seq breaks latency ties so the heap
        # never compares the info dicts. Two generations: the previous
        # reservoir window's heap is kept until the next wrap, so a
        # spike stays scrapeable for at least one full window even at
        # high QPS (an instant clear would wipe it before any poll)
        self._slow: List[tuple] = []
        self._slow_prev: List[tuple] = []
        self._slow_seq = 0
        self._slow_t0 = time.monotonic()   # current generation's start
        # queue depth is an INSTANTANEOUS property of this deployment's
        # batcher, not a monotonic series: keep the authoritative value
        # per instance (fresh at redeploy, immune to a drained old
        # deployment's late decrements) and mirror it to the gauge for
        # the Prometheus export
        self._qd = 0
        # redeploying a key reuses the registry series (Prometheus
        # counters are monotonic per model) — but THIS deployment's view
        # starts fresh: snapshot/compat properties report deltas against
        # the construction-time baseline, preserving PR-3 semantics
        self._base = {c: c.value for c in
                      (self._requests, self._rows, self._batches,
                       self._batch_rows, self._padded_rows, self._errors,
                       self._timeouts, self._rejected, self._retries,
                       *self._stage_ms.values())}

    def _delta(self, c) -> float:
        return c.value - self._base.get(c, 0.0)

    # -- mutation (hot path) -------------------------------------------

    def record_request(self, latency_ms: float, rows: int,
                       trace_id: Optional[str] = None,
                       lane: Optional[str] = None):
        # reservoir honors the same enabled flag as the counters: a
        # runtime set_enabled(False) freezes the WHOLE stats surface
        # consistently instead of a moving p50 over frozen counters
        if self._reg.enabled:
            with self._mu:
                self._lat_ms[self._lat_n % _RESERVOIR] = latency_ms
                self._lat_n += 1
                self._note_slow_locked(self._lat_n % _RESERVOIR == 0,
                                       latency_ms, rows, trace_id)
                if lane is not None:
                    ring = self._lane_lat.get(lane)
                    if ring is None:
                        ring = self._lane_lat[lane] = np.zeros(
                            _LANE_RESERVOIR, np.float64)
                        self._lane_n[lane] = 0
                    ring[self._lane_n[lane] % _LANE_RESERVOIR] = \
                        latency_ms
                    self._lane_n[lane] += 1
        self._requests.inc()
        self._rows.inc(rows)
        self._latency.observe(latency_ms)

    def record_lane_shed(self, lane: str):
        """A non-interactive lane's queue budget shed a request
        (ISSUE 20) — counted per lane so a bulk flood's shed rate is
        distinguishable from genuine whole-queue overload."""
        c = self._lane_shed.get(lane)
        if c is None:
            c = self._lane_shed[lane] = self._reg.counter(
                "h2o3_serve_lane_shed_total",
                {"model": self.model, "lane": lane},
                help="requests shed by a lane's queue budget")
            self._lane_shed_base[lane] = c.value
        c.inc()

    def record_failed_exemplar(self, latency_ms: float, rows: int,
                               trace_id: Optional[str],
                               error: str):
        """Failed requests (deadline blowouts, device errors) are by
        construction among the slowest responses — exactly the ones a
        latency investigation chases — so they enter the slow-request
        exemplars (flagged ``error=``) WITHOUT touching the
        success-only latency reservoir, percentile estimates or
        request counters (those keep PR-3 semantics; failures are
        counted by record_error/record_timeout)."""
        if self._reg.enabled:
            with self._mu:
                self._note_slow_locked(False, latency_ms, rows,
                                       trace_id, error)

    def _note_slow_locked(self, wrapped: bool, latency_ms: float,
                          rows: int, trace_id: Optional[str],
                          error: Optional[str] = None):
        if wrapped or \
                time.monotonic() - self._slow_t0 >= _SLOW_WINDOW_S:
            # age the exemplars with the reservoir window OR the wall
            # clock, whichever wraps first: an all-time top-k would let
            # cold-start compile latencies mask every later spike (and
            # their trace ids point at spans long evicted from the
            # ring). The wrap trigger is passed in by record_request
            # (tied to reservoir advancement) so failure-only traffic
            # cannot spuriously rotate at _lat_n == 0.
            self._slow_prev = self._slow
            self._slow = []
            self._slow_t0 = time.monotonic()
        # steady-state fast path: beyond the one monotonic read for
        # generation aging above, requests that cannot enter the top-k
        # allocate nothing
        if len(self._slow) < _SLOW_K or latency_ms > self._slow[0][0]:
            self._slow_seq += 1
            info = {"trace_id": trace_id,
                    "latency_ms": round(float(latency_ms), 3),
                    "rows": int(rows), "time": time.time()}
            if error is not None:
                info["error"] = error
            entry = (float(latency_ms), self._slow_seq, info)
            if len(self._slow) < _SLOW_K:
                heapq.heappush(self._slow, entry)
            else:
                heapq.heapreplace(self._slow, entry)

    def record_batch(self, live_rows: int, padded_rows: int,
                     stage_ms: Dict[str, float]):
        self._batches.inc()
        self._batch_rows.inc(live_rows)
        self._padded_rows.inc(padded_rows)
        for s, v in stage_ms.items():
            c = self._stage_ms.get(s)
            if c is None:
                c = self._stage_ms[s] = self._reg.counter(
                    "h2o3_serve_stage_ms_total",
                    {"model": self.model, "stage": s})
                self._base.setdefault(c, c.value)
            c.inc(v)

    def record_error(self):
        self._errors.inc()

    def record_timeout(self):
        self._timeouts.inc()

    def record_rejected(self):
        self._rejected.inc()

    def record_retry(self):
        self._retries.inc()

    def queue_delta(self, rows: int):
        with self._mu:
            self._qd += rows
            qd = self._qd
        self._queue_depth.set(qd)

    # -- compat properties (tests and callers read these as ints) ------

    @property
    def requests(self) -> int:
        return int(self._delta(self._requests))

    @property
    def rows(self) -> int:
        return int(self._delta(self._rows))

    @property
    def batches(self) -> int:
        return int(self._delta(self._batches))

    @property
    def batch_rows(self) -> int:
        return int(self._delta(self._batch_rows))

    @property
    def padded_rows(self) -> int:
        return int(self._delta(self._padded_rows))

    @property
    def errors(self) -> int:
        return int(self._delta(self._errors))

    @property
    def timeouts(self) -> int:
        return int(self._delta(self._timeouts))

    @property
    def rejected(self) -> int:
        return int(self._delta(self._rejected))

    @property
    def retries(self) -> int:
        return int(self._delta(self._retries))

    @property
    def queue_depth(self) -> int:
        with self._mu:
            return self._qd

    @property
    def stage_ms(self) -> Dict[str, float]:
        return {s: self._delta(c) for s, c in self._stage_ms.items()}

    # -- snapshot -------------------------------------------------------

    def percentiles_ms(self, qs: List[float]) -> List[Optional[float]]:
        """All requested quantiles from ONE copy of the latency ring —
        separate calls would sample different windows under concurrent
        recording (a snapshot could then report p99 < p50)."""
        with self._mu:
            n = min(self._lat_n, _RESERVOIR)
            window = self._lat_ms[:n].copy() if n else None
        if window is None:
            return [None] * len(qs)
        return [float(np.percentile(window, q)) for q in qs]

    def percentile_ms(self, q: float) -> Optional[float]:
        return self.percentiles_ms([q])[0]

    def lane_percentiles_ms(self, lane: str,
                            qs: List[float]) -> List[Optional[float]]:
        """Per-lane quantiles (ISSUE 20), one copy of the lane ring —
        same single-window discipline as percentiles_ms."""
        with self._mu:
            n = min(self._lane_n.get(lane, 0), _LANE_RESERVOIR)
            ring = self._lane_lat.get(lane)
            window = ring[:n].copy() if (ring is not None and n) else None
        if window is None:
            return [None] * len(qs)
        return [float(np.percentile(window, q)) for q in qs]

    def slow_requests(self) -> List[Dict]:
        """The top-k slowest requests (latency desc), each with its
        trace id — the exemplars /3/Serve/stats exposes so a latency
        spike resolves to concrete /3/Timeline spans."""
        with self._mu:
            entries = [e[2] for e in self._slow] + \
                      [e[2] for e in self._slow_prev]
        return sorted(entries,
                      key=lambda e: -e["latency_ms"])[:_SLOW_K]

    def snapshot(self) -> Dict:
        p50, p99 = self.percentiles_ms([50, 99])
        # striped-lock counters have no cross-counter atomic read (the
        # price of losing the PR-3 per-instance mutex); bound the skew
        # instead: numerators read FIRST, denominators last, so a
        # concurrent record_batch can only make the ratios dip, never
        # report occupancy/fill above the truth (fill > 1.0 clamped)
        batch_rows = self.batch_rows
        padded = self.padded_rows
        batches = self.batches
        occ = (batch_rows / batches) if batches else 0.0
        pad_eff = min((batch_rows / padded) if padded else 1.0, 1.0)
        return {
            "requests": self.requests,
            "rows": self.rows,
            "batches": batches,
            "errors": self.errors,
            "timeouts": self.timeouts,
            "rejected": self.rejected,
            "retries": self.retries,
            "queue_depth": self.queue_depth,
            "mean_batch_occupancy": round(occ, 3),
            "bucket_fill": round(pad_eff, 4),
            "p50_ms": None if p50 is None else round(p50, 3),
            "p99_ms": None if p99 is None else round(p99, 3),
            "stage_ms": {s: round(v, 3)
                         for s, v in self.stage_ms.items()},
            "slow_requests": self.slow_requests(),
            "lanes": self._lane_snapshot(),
        }

    def _lane_snapshot(self) -> Dict[str, Dict]:
        with self._mu:
            lanes = sorted(set(self._lane_n) | set(self._lane_shed))
        out: Dict[str, Dict] = {}
        for ln in lanes:
            p50, p99 = self.lane_percentiles_ms(ln, [50, 99])
            shed_c = self._lane_shed.get(ln)
            shed = 0 if shed_c is None else \
                int(shed_c.value - self._lane_shed_base.get(ln, 0.0))
            out[ln] = {
                "requests": int(self._lane_n.get(ln, 0)),
                "shed": shed,
                "p50_ms": None if p50 is None else round(p50, 3),
                "p99_ms": None if p99 is None else round(p99, 3),
            }
        return out


def merge_snapshots(snaps: List[Dict]) -> Dict:
    """Cluster-level rollup for /3/Serve/stats: counters sum; the
    percentile fields do NOT aggregate across models (quantiles don't
    add) and are left to the per-model entries."""
    out = {"requests": 0, "rows": 0, "batches": 0, "errors": 0,
           "timeouts": 0, "rejected": 0, "retries": 0, "queue_depth": 0,
           "stage_ms": {s: 0.0 for s in STAGES}}
    for s in snaps:
        for k in ("requests", "rows", "batches", "errors", "timeouts",
                  "rejected", "retries", "queue_depth"):
            out[k] += s.get(k, 0)
        for st, v in (s.get("stage_ms") or {}).items():
            out["stage_ms"][st] = out["stage_ms"].get(st, 0.0) + v
    out["stage_ms"] = {s: round(v, 3) for s, v in out["stage_ms"].items()}
    return out
