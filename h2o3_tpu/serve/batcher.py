"""Micro-batching queue: concurrent row requests → padded device batches.

Concurrent clients submit small row lists; a batcher thread coalesces
everything that arrives within one tick (max_delay_ms, or until
max_batch rows are waiting) into ONE padded device batch, and a
collector thread fetches results + resolves the waiting clients. Two
threads — not one — because JAX dispatch is asynchronous: the batcher
encodes and dispatches batch k+1 while the collector is still blocked
on batch k's device fetch (the pipeline analog of PR 2's speculative
chunk dispatch). The in-flight queue is bounded (pipeline depth 2) so
a slow device backpressures encoding instead of buffering unboundedly.

Admission control (water/Job has no analog; this is standard serving
hygiene): the pending queue is bounded in ROWS — beyond it submit()
fails fast with ServeOverloadedError (HTTP 503) instead of growing
latency without bound; each request carries a deadline — expired
requests are dropped at pick-up time (never dispatched) or abandoned
at resolve time, surfacing ServeDeadlineError.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu import telemetry
from h2o3_tpu.telemetry import trace as teletrace
from h2o3_tpu.serve import lanes as lanes_mod
from h2o3_tpu.serve.stats import ServeStats


class ServeError(RuntimeError):
    """Base class; http_status picked up by the REST layer."""
    http_status = 500


class ServeOverloadedError(ServeError):
    http_status = 503


class ServeLaneShedError(ServeOverloadedError):
    """A non-interactive lane exhausted its queue budget (ISSUE 20):
    the request sheds fast with 503 + ``Retry-After`` while interactive
    admission — and the rows already queued in every lane — proceed
    untouched. Mirrors the scheduler's priority semantics: bulk load
    degrades bulk, never interactive p99."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class ServeBadRequestError(ServeError):
    """A request's rows failed to encode (e.g. a non-numeric string in
    a numeric column) — the client's fault, not the service's."""
    http_status = 400


class ServeDeadlineError(ServeError):
    http_status = 503


class ServeCircuitOpenError(ServeError):
    """The deployment's circuit breaker is open: its device stage is
    failing consecutively, so requests fail FAST instead of queueing
    into certain timeouts. ``retry_after_s`` feeds the HTTP
    ``Retry-After`` header."""
    http_status = 503

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class ServeClosedError(ServeError):
    http_status = 410


class _Request:
    __slots__ = ("rows", "n", "t_enqueue", "t_wall", "deadline", "event",
                 "results", "error", "abandoned", "columnar", "trace_id",
                 "lane")

    def __init__(self, rows: Sequence[Dict[str, Any]], deadline: float,
                 columnar: bool = False,
                 lane: str = lanes_mod.DEFAULT_LANE):
        self.lane = lane
        self.rows = rows
        self.n = len(rows)
        self.t_enqueue = time.perf_counter()
        self.t_wall = time.time()
        self.deadline = deadline
        self.event = threading.Event()
        self.results = None      # [dict, ...] rows or {col: [...]} columnar
        self.error: Optional[BaseException] = None
        self.abandoned = False
        self.columnar = columnar
        # trace linkage (ISSUE 8): the submitting thread's bound trace
        # (the REST handler set it from the traceparent header). Stays
        # None for embedded callers — minting an id per request would
        # put an os.urandom syscall on the µs-budget submit path for an
        # id nothing downstream could have propagated anyway
        self.trace_id = teletrace.current_trace_id()


class MicroBatcher:
    def __init__(self, encode: Callable, dispatch: Callable,
                 decode: Callable, stats: ServeStats, *,
                 bucket_for: Callable[[int], int],
                 max_batch: int = 512, max_delay_ms: float = 2.0,
                 queue_limit: int = 8192,
                 default_timeout_ms: float = 10_000.0,
                 pipeline_depth: int = 2, breaker=None,
                 fleet_check: Optional[Callable] = None,
                 perf_hook: Optional[Callable] = None):
        import queue as _q
        self.breaker = breaker         # serve/circuit.py CircuitBreaker
        # performance accounting (ISSUE 11): (padded_rows, device_s) per
        # completed batch -> the deployment's costmodel accumulator;
        # None when telemetry is off (checked no-op)
        self._perf_hook = perf_hook
        # fleet gossip verdict (serve/fleet.py reject_for): an open
        # circuit on a PEER replica sheds load here too; None = healthy
        self._fleet_check = fleet_check
        self._encode = encode          # (rows, pad_to) -> np [pad, F]
        self._dispatch = dispatch      # (X, n_active) -> device array
        self._decode = decode          # (host scores, n) -> DecodedBatch
        self._bucket_for = bucket_for
        self.stats = stats
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.queue_limit = int(queue_limit)
        self.default_timeout_s = float(default_timeout_ms) / 1000.0
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        # one FIFO per deadline-class lane (ISSUE 20): pickup drains
        # lanes in priority order, admission budgets rows per lane
        self._pending: Dict[str, deque] = {ln: deque()
                                           for ln in lanes_mod.LANES}
        self._lane_rows: Dict[str, int] = {ln: 0
                                           for ln in lanes_mod.LANES}
        self._pending_rows = 0
        self._closed = False
        self._inflight: "_q.Queue" = _q.Queue(maxsize=max(pipeline_depth, 1))
        self._batch_thread = threading.Thread(
            target=self._batch_loop, daemon=True, name="serve-batcher")
        self._collect_thread = threading.Thread(
            target=self._collect_loop, daemon=True, name="serve-collector")
        self._batch_thread.start()
        self._collect_thread.start()

    # -- client side ----------------------------------------------------

    def submit(self, rows: Sequence[Dict[str, Any]],
               timeout_ms: Optional[float] = None,
               columnar: bool = False,
               lane: Optional[str] = None):
        """Blocking scoring call for one client request. Raises
        ServeOverloadedError when the queue is full, ServeDeadlineError
        when the deadline expires first. ``columnar=True`` returns
        ``{column: [values...]}`` from the batch's vectorized decode
        instead of per-row dicts (requests of both shapes coalesce into
        the same device batch). ``lane`` is the deadline class
        (interactive > bulk > background): non-interactive lanes are
        budgeted to a fraction of the queue and shed fast
        (ServeLaneShedError, 503 + Retry-After) beyond it, so a bulk
        flood cannot ride interactive's admission headroom."""
        if not rows:
            return {} if columnar else []
        lane = lanes_mod.normalize(lane)
        if len(rows) > self.max_batch:
            raise ValueError(
                f"submit() takes at most max_batch={self.max_batch} rows "
                f"per request (got {len(rows)}); split the request")
        if self.breaker is not None:
            # fail FAST while the circuit is open: the device stage is
            # known-broken, queueing would only convert this request
            # into a slow timeout and delay coalesced innocents
            retry_after = self.breaker.allow_request()
            if retry_after is not None:
                self.stats.record_rejected()
                raise ServeCircuitOpenError(
                    f"circuit open for '{self.stats.model}' (device "
                    f"stage failing) — retry in {retry_after:.2f}s",
                    retry_after_s=retry_after)
        if self._fleet_check is not None:
            # the LOCAL breaker ruled first (local state wins); only a
            # peer's open circuit that local evidence cannot contradict
            # sheds here — same fast-503 contract as the local breaker
            hit = self._fleet_check()
            if hit is not None:
                retry_after, src = hit
                self.stats.record_rejected()
                raise ServeCircuitOpenError(
                    f"circuit open for '{self.stats.model}' on fleet "
                    f"peer {src} — shedding load, retry in "
                    f"{retry_after:.2f}s", retry_after_s=retry_after)
        timeout_s = (float(timeout_ms) / 1000.0 if timeout_ms is not None
                     else self.default_timeout_s)
        deadline = time.perf_counter() + timeout_s
        req = _Request(rows, deadline, columnar=columnar, lane=lane)
        with self._cv:
            if self._closed:
                raise ServeClosedError("deployment is shut down")
            if lane != lanes_mod.DEFAULT_LANE:
                # per-lane budget (ISSUE 20): bulk/background may only
                # occupy their fraction of the queue — beyond it THIS
                # lane sheds while interactive admission is untouched
                cap = int(self.queue_limit
                          * lanes_mod.budget_fraction(lane))
                if self._lane_rows[lane] + req.n > cap:
                    self.stats.record_rejected()
                    self.stats.record_lane_shed(lane)
                    retry_s = max(self.max_delay_s * 4, 0.05)
                    try:
                        from h2o3_tpu.telemetry import blackbox
                        blackbox.record(
                            "lane_shed", member=self.stats.model,
                            payload=f"lane={lane} "
                                    f"pending={self._lane_rows[lane]} "
                                    f"cap={cap} at=batcher")
                    except Exception:  # noqa: BLE001 — recorder is advisory
                        pass
                    raise ServeLaneShedError(
                        f"'{lane}' lane budget full "
                        f"({self._lane_rows[lane]} rows pending, lane "
                        f"cap {cap} of {self.queue_limit}) — retry in "
                        f"{retry_s:.2f}s", retry_after_s=retry_s)
            if self._pending_rows + req.n > self.queue_limit:
                self.stats.record_rejected()
                raise ServeOverloadedError(
                    f"serving queue full ({self._pending_rows} rows "
                    f"pending, limit {self.queue_limit}) — retry later")
            self._pending[lane].append(req)
            self._lane_rows[lane] += req.n
            self._pending_rows += req.n
            self._cv.notify_all()
        self.stats.queue_delta(req.n)
        resolved = req.event.wait(max(deadline - time.perf_counter(), 0.0))
        if not resolved:
            # the batcher may be timing this request out concurrently
            # (_take_batch's expired-in-queue branch runs under _mu and
            # records the timeout itself) — claim under the same lock so
            # the counter advances exactly once
            with self._mu:
                req.abandoned = True
                already_counted = req.error is not None
            if not already_counted:
                self.stats.record_timeout()
            self.stats.queue_delta(-req.n)
            # a deadline blowout is slower than every successful
            # request — without an exemplar the slow-request list would
            # show only benign latencies during the exact stall the
            # operator is investigating
            self.stats.record_failed_exemplar(
                (time.perf_counter() - req.t_enqueue) * 1e3, req.n,
                req.trace_id, "deadline")
            raise ServeDeadlineError(
                f"request deadline ({timeout_s * 1e3:.0f} ms) expired "
                f"before results were ready")
        self.stats.queue_delta(-req.n)
        if req.error is not None:
            self.stats.record_failed_exemplar(
                (time.perf_counter() - req.t_enqueue) * 1e3, req.n,
                req.trace_id, type(req.error).__name__)
            raise req.error
        lat_s = time.perf_counter() - req.t_enqueue
        self.stats.record_request(lat_s * 1e3, req.n,
                                  trace_id=req.trace_id, lane=req.lane)
        # root span per client request (submit→resolve wall time),
        # bound to the request's trace so the /3/Timeline entry, the
        # stats slow-request exemplar and the client's traceparent
        # response header all carry the SAME id
        with teletrace.trace_context(req.trace_id):
            telemetry.record_span("serve.request", req.t_wall, lat_s,
                                  model=self.stats.model, rows=req.n)
        return req.results

    def _resolve_error(self, reqs: List[_Request], err: BaseException):
        """Resolve requests with ``err`` under the queue lock. submit()'s
        timeout path claims a request under ``_mu`` and reads
        ``req.error`` to decide which side records the failure — a bare
        write here races that claim and can double-count one request as
        both timeout and error (the PR-8 review-notes race class,
        now machine-checked by h2o3-lint's lock-discipline rule).
        Abandoned requests are skipped: their waiter is gone."""
        with self._mu:
            for r in reqs:
                if not r.abandoned:
                    r.error = err
                r.event.set()

    # -- batcher thread -------------------------------------------------

    def _pop_next_locked(self, rows: int) -> Optional[_Request]:
        """Next request that fits the batch, drained in LANE PRIORITY
        order (interactive > bulk > background) — the serving mirror of
        the scheduler's priority dispatch: an interactive row admitted
        behind a bulk backlog boards the next tick's batch instead of
        riding the whole backlog out."""
        for ln in lanes_mod.LANES:
            q = self._pending[ln]
            if q and rows + q[0].n <= self.max_batch:
                r = q.popleft()
                self._lane_rows[ln] -= r.n
                self._pending_rows -= r.n  # h2o3-lint: allow[lock-discipline] every caller holds self._cv (the _locked suffix contract)
                return r
        return None

    def _has_pending_locked(self) -> bool:
        return any(self._pending.values())

    def _take_batch(self) -> List[_Request]:
        """Collect requests for one tick: first arrival opens a window
        of max_delay_ms; the batch closes when the window ends or
        max_batch rows are in hand."""
        batch: List[_Request] = []
        rows = 0
        window_end = None
        with self._cv:
            while True:
                while True:
                    r = self._pop_next_locked(rows)
                    if r is None:
                        break
                    now = time.perf_counter()
                    if r.abandoned or now > r.deadline:
                        # expired in queue: never dispatch it
                        if not r.abandoned:
                            r.error = ServeDeadlineError(
                                "request expired in the serving queue")
                            self.stats.record_timeout()
                            r.event.set()
                        continue
                    batch.append(r)
                    rows += r.n
                if self._closed and not batch \
                        and not self._has_pending_locked():
                    return []
                if rows >= self.max_batch:
                    return batch
                now = time.perf_counter()
                if batch and window_end is None:
                    window_end = now + self.max_delay_s
                if window_end is not None:
                    if now >= window_end:
                        return batch
                    self._cv.wait(window_end - now)
                else:
                    if self._closed:
                        return []
                    self._cv.wait(0.05)

    def _encode_batch(self, batch: List[_Request]):
        """Encode a coalesced batch. A row that refuses to encode (bad
        client input) must fail ONLY its own request — innocent
        requests sharing the tick are re-encoded without it and still
        dispatched; the offender resolves with a 400-mappable
        ServeBadRequestError instead of poisoning the whole batch."""
        rows: List[Dict[str, Any]] = []
        for r in batch:
            rows.extend(r.rows)
        n = sum(r.n for r in batch)
        try:
            return self._encode(rows, self._bucket_for(n)), batch, n
        except Exception:
            pass                     # isolate per request below
        good: List[_Request] = []
        for r in batch:
            try:
                self._encode(r.rows, r.n)
                good.append(r)
            except Exception as e:   # noqa: BLE001 — client's bad row
                self._resolve_error([r], e if isinstance(e, ServeError)
                                    else ServeBadRequestError(
                                        f"row encoding failed: {e}"))
                self.stats.record_error()
        if not good:
            return None, [], 0
        rows = []
        for r in good:
            rows.extend(r.rows)
        n = sum(r.n for r in good)
        try:
            return self._encode(rows, self._bucket_for(n)), good, n
        except BaseException as e:  # noqa: BLE001 — must not kill the loop
            self._resolve_error(good, e)
            self.stats.record_error()
            return None, [], 0

    def _batch_loop(self):
        while True:
            batch = self._take_batch()
            if not batch:
                if self._closed:
                    self._inflight.put(None)    # collector sentinel
                    return
                continue
            t0 = time.perf_counter()
            t0_wall = time.time()
            # per-batch root span: opened on THIS thread, finished by the
            # collector — the explicit-parent handoff the span API exists
            # for (thread-local nesting cannot cross the pipeline)
            sp_batch = telemetry.open_span("serve.batch",
                                           model=self.stats.model,
                                           rows=sum(r.n for r in batch))
            if sp_batch is not None:
                # the coalesced requests' trace ids ride ON the batch
                # span (bounded — a 512-request tick must not grow an
                # unbounded attr), and the first one becomes the span's
                # own trace link
                tids = [r.trace_id for r in batch if r.trace_id]
                if tids:
                    sp_batch.trace_id = tids[0]
                    sp_batch.attrs["trace_ids"] = ",".join(tids[:16])
                    if len(tids) > 16:
                        sp_batch.attrs["trace_ids"] += \
                            f",+{len(tids) - 16}"
            X, batch, n = self._encode_batch(batch)
            if not batch:
                # every request failed to encode: the batch still shows
                # in the trace (with error=True) so failed bursts don't
                # vanish from /3/Timeline while stats count the errors
                if sp_batch is not None:
                    sp_batch.attrs["error"] = True
                    sp_batch.finish()
                continue
            t1 = time.perf_counter()
            try:
                out = self._dispatch_resilient(X, n, batch)
                t2 = time.perf_counter()
            except BaseException as e:  # noqa: BLE001 — resolve waiters
                self._resolve_error(batch, e)
                self.stats.record_error()
                if self.breaker is not None:
                    self.breaker.record_failure()
                if sp_batch is not None:
                    sp_batch.attrs["error"] = True
                    sp_batch.finish()
                continue
            queue_ms = (t0 - min(r.t_enqueue for r in batch)) * 1e3
            telemetry.record_span("serve.queue",
                                  min(r.t_wall for r in batch),
                                  queue_ms / 1e3, parent=sp_batch)
            telemetry.record_span("serve.encode", t0_wall, t1 - t0,
                                  parent=sp_batch)
            self._inflight.put(
                (out, batch, n, X,
                 {"queue": queue_ms, "encode": (t1 - t0) * 1e3,
                  "dispatch": (t2 - t1) * 1e3},
                 (sp_batch, time.time() - (t2 - t1))))  # h2o3-lint: allow[monotonic-durations] wall START anchor reconstructed from a perf_counter duration, for span reporting

    def _deadline_allows_retry(self, batch: List[_Request]) -> bool:
        """A retry only makes sense if every coalesced request can
        still meet its deadline afterwards (a conservative one-tick
        margin)."""
        margin = self.max_delay_s + 0.001
        return time.perf_counter() + margin < min(r.deadline
                                                  for r in batch)

    def _dispatch_resilient(self, X, n: int, batch: List[_Request]):
        """Device dispatch behind the fault seam with ONE transient
        retry — a single hiccup (preempted device, transient transfer
        error) recovers in-place; a persistent failure propagates to
        the breaker. The retry respects the coalesced requests'
        deadlines: if any would expire, fail now instead of burning
        its remaining budget."""
        from h2o3_tpu import faults
        from h2o3_tpu.resilience import is_transient

        def _once():
            if faults.ACTIVE:
                faults.check("execute", pipeline="serve",
                             key=self.stats.model)
            return self._dispatch(X, n)

        try:
            return _once()
        except BaseException as e:  # noqa: BLE001 — classified below
            if not is_transient(e) or not self._deadline_allows_retry(
                    batch):
                raise
            self.stats.record_retry()
            return _once()

    # -- collector thread -----------------------------------------------

    def _collect_loop(self):
        from h2o3_tpu.resilience import is_transient
        while True:
            item = self._inflight.get()
            if item is None:
                return
            out, batch, n, X, tms, (sp_batch, disp_wall) = item
            padded = X.shape[0]
            t0 = time.perf_counter()
            # DEVICE stage (the breaker's jurisdiction): fetch, with
            # the same single-transient-retry policy as dispatch (the
            # batch is re-dispatched from its still-live encoded
            # matrix). Only failures HERE count against device health.
            try:
                try:
                    host = np.asarray(out)      # blocks until ready
                except BaseException as e:  # noqa: BLE001
                    if not is_transient(e) \
                            or not self._deadline_allows_retry(batch):
                        raise
                    self.stats.record_retry()
                    host = np.asarray(self._dispatch_resilient(
                        X, n, batch))
            except BaseException as e:  # noqa: BLE001
                self._resolve_error(batch, e)
                self.stats.record_error()
                if self.breaker is not None:
                    self.breaker.record_failure()
                if sp_batch is not None:
                    sp_batch.attrs["error"] = True
                    sp_batch.finish()
                continue
            if self.breaker is not None:
                # the device answered: close a half-open circuit /
                # reset the counter BEFORE decode — a host-side codec
                # bug below must not read as device sickness (the
                # breaker contract: client/host failures never count)
                self.breaker.record_success()
            # HOST decode stage: failures resolve the requests with the
            # error but leave the circuit alone
            try:
                t1 = time.perf_counter()
                decoded = self._decode(host, n)
                # per-request views over the batch-wide vectorized
                # decode: row dicts only materialize for row-format
                # requests (columnar requests slice arrays). Built
                # INSIDE the decode-stage window so the stats attribute
                # the dict cost honestly.
                off = 0
                for r in batch:
                    r.results = (decoded.columns(off, r.n) if r.columnar
                                 else decoded.rows(off, r.n))
                    off += r.n
                t2 = time.perf_counter()
            except BaseException as e:  # noqa: BLE001
                self._resolve_error(batch, e)
                self.stats.record_error()
                if sp_batch is not None:
                    sp_batch.attrs["error"] = True
                    sp_batch.finish()
                continue
            for r in batch:
                r.event.set()
            device_s = tms["dispatch"] / 1e3 + (t1 - t0)
            # children recorded on the COLLECTOR thread against the
            # batcher thread's root — explicit parent handoff
            telemetry.record_span("serve.device", disp_wall, device_s,
                                  parent=sp_batch)
            telemetry.record_span(
                "serve.decode", time.time() - (t2 - t1),  # h2o3-lint: allow[monotonic-durations] wall START anchor reconstructed from a perf_counter duration, for span reporting
                t2 - t1, parent=sp_batch)
            if sp_batch is not None:
                sp_batch.finish()
            self.stats.record_batch(
                n, padded,
                {"queue": tms["queue"],
                 "encode": tms["encode"],
                 "device": tms["dispatch"] + (t1 - t0) * 1e3,
                 "decode": (t2 - t1) * 1e3})
            if self._perf_hook is not None:
                try:
                    self._perf_hook(padded, device_s)
                except Exception:   # accounting must never sink serving
                    pass

    # -- lifecycle ------------------------------------------------------

    @property
    def pending_rows(self) -> int:
        with self._mu:
            return self._pending_rows

    @property
    def load_factor(self) -> float:
        """Queue fill fraction (0.0 empty → 1.0 at the admission
        limit) — the load signal a fleet heartbeat carries so the
        router's least-loaded fallback and can't-absorb-load 503 see
        the same number admission control enforces."""
        with self._mu:
            return self._pending_rows / max(self.queue_limit, 1)

    def close(self, timeout: float = 5.0):
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._batch_thread.join(timeout)
        self._collect_thread.join(timeout)
        # resolve anything still queued
        with self._cv:
            for ln in lanes_mod.LANES:
                q = self._pending[ln]
                while q:
                    r = q.popleft()
                    self._lane_rows[ln] -= r.n
                    self._pending_rows -= r.n
                    r.error = ServeClosedError("deployment shut down")
                    r.event.set()
