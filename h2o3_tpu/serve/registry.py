"""Compile-cached device scoring: one jitted predict per batch bucket.

The train path (PR 2) buys zero-recompile warm training with round-up
chunk buckets + traced scalars; serving needs the same property on the
REQUEST axis: every micro-batch pads to one of a fixed set of batch-size
buckets (1/8/64/512/4096 by default) with the live-row count riding as a
TRACED ``n_active`` scalar masking the tail — so the steady-state serve
path compiles ZERO XLA modules no matter how request sizes mix, and
deploy() pays the whole compile bill up front (per process; the
persistent compile cache, cluster_boot.setup_compilation_cache, carries
it across processes).

Scoring dispatch is ASYNC: score() returns the un-fetched device array,
so the batcher can encode batch k+1 while batch k runs on device; the
collector thread blocks on the fetch.

Models whose _predict_matrix does not trace (host-side numpy scorers)
fall back to an unjitted batched call — same results, no compile cache.
"""
from __future__ import annotations

import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 8, 64, 512, 4096)


class CompiledScorer:
    def __init__(self, model, buckets: Sequence[int] = DEFAULT_BUCKETS,
                 warm: bool = True):
        import jax
        import jax.numpy as jnp
        self.model = model
        self.buckets = tuple(sorted({int(b) for b in buckets if int(b) > 0}))
        if not self.buckets:
            raise ValueError("at least one batch bucket is required")
        self.n_features = len(model.feature_names)
        self.nclasses = int(getattr(model, "nclasses", 1) or 1)
        self.jitted = True
        self.warm_seconds: Dict[int, float] = {}
        # per-bucket executable cost (ISSUE 11): captured at warm time
        # from the lowered program, so the batcher can attribute flops
        # to every dispatched batch without touching the hot path
        self.bucket_costs: Dict[int, object] = {}
        # output contract probed at warm time (deploy-time validation):
        # ndim and, for 2-D outputs, the class-axis width
        self.out_ndim: Optional[int] = None
        self.out_k: Optional[int] = None

        def _predict(X, n_active):
            out = jnp.asarray(model._predict_matrix(X))
            mask = jnp.arange(X.shape[0]) < n_active
            # pad rows are all-NA: their (garbage) scores are zeroed so
            # nothing non-finite ever crosses the wire by accident
            if out.ndim == 2:
                return jnp.where(mask[:, None], out, 0.0)
            return jnp.where(mask, out, 0.0)

        self._fn = jax.jit(_predict)
        if warm:
            self.warm_all()

    # -- warmup ---------------------------------------------------------

    def warm_all(self) -> Dict[int, float]:
        """Compile every bucket executable now (deploy-time cost); falls
        back to the unjitted path if the model's predict does not
        trace."""
        import jax
        for b in self.buckets:
            if b in self.warm_seconds:
                continue
            X = np.full((b, self.n_features), np.nan, np.float32)
            t0 = time.perf_counter()
            try:
                out = jax.block_until_ready(self._fn(X, 0))  # h2o3-lint: allow[transfer-seam] deploy-time warmup barrier: warm_seconds must measure the full compile
            except Exception:   # noqa: BLE001 — non-traceable model
                self.jitted = False
                model = self.model
                self._fn = lambda X, n: np.asarray(
                    model._predict_matrix(X))
                self.warm_seconds = {bb: 0.0 for bb in self.buckets}
                self._probe_output()
                break
            self.warm_seconds[b] = time.perf_counter() - t0
            self._record_output_shape(out)
            try:
                from h2o3_tpu.telemetry import costmodel
                cost = costmodel.lowered_cost(
                    lambda X=X: self._fn.lower(X, 0))
                if cost is not None:
                    self.bucket_costs[b] = cost
            except Exception:   # accounting must never sink a deploy
                pass
        return self.warm_seconds

    def _record_output_shape(self, out) -> None:
        self.out_ndim = int(getattr(out, "ndim", 0) or 0)
        self.out_k = int(out.shape[1]) if self.out_ndim == 2 else None

    def _probe_output(self) -> None:
        """One unjitted probe row so deploy can still validate the
        output contract on the fallback path."""
        try:
            out = np.asarray(self._fn(
                np.full((1, self.n_features), np.nan, np.float32), 0))
        except Exception:   # noqa: BLE001 — leave unknown; decode guards
            return
        self._record_output_shape(out)

    # -- scoring --------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest warm bucket >= n (the batcher caps batches at
        max(buckets), so every batch has one)."""
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"batch of {n} rows exceeds the largest bucket "
                         f"{self.buckets[-1]}")

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def score(self, X: np.ndarray, n_active: int):
        """Dispatch one padded batch; returns the (possibly still
        in-flight) result array — callers fetch with np.asarray."""
        if X.shape[0] not in self.buckets and self.jitted:
            raise ValueError(
                f"batch shape {X.shape[0]} is not a warm bucket "
                f"{self.buckets} — encode with pad_to=bucket_for(n)")
        return self._fn(X, n_active)
