"""h2o3_tpu.serve — low-latency model serving.

Micro-batched, compile-cached scoring: deploy() warms one predict
executable per batch-size bucket so steady-state serving compiles zero
XLA modules; a micro-batching queue coalesces concurrent row requests
into padded device batches with admission control and per-request
deadlines. REST surface: POST /3/Predictions/models/{m}/rows,
/3/Serve/models, /3/Serve/stats (api/server.py).
"""
from h2o3_tpu.serve.batcher import (ServeBadRequestError,
                                    ServeCircuitOpenError,
                                    ServeClosedError,
                                    ServeDeadlineError, ServeError,
                                    ServeLaneShedError,
                                    ServeOverloadedError)
from h2o3_tpu.serve import lanes
from h2o3_tpu.serve.circuit import CircuitBreaker
from h2o3_tpu.serve.codec import RowCodec
from h2o3_tpu.serve.registry import DEFAULT_BUCKETS, CompiledScorer
from h2o3_tpu.serve.service import (Deployment, circuit_states, deploy,
                                    deployment, deployments, fleet,
                                    predict_columnar,
                                    predict_rows, prewarm_from_snapshot,
                                    registry_snapshot, shutdown_all,
                                    stats, undeploy)
from h2o3_tpu.serve.stats import ServeStats

__all__ = [
    "CircuitBreaker", "CompiledScorer", "DEFAULT_BUCKETS", "Deployment",
    "RowCodec",
    "ServeBadRequestError", "ServeCircuitOpenError", "ServeClosedError",
    "ServeDeadlineError",
    "ServeError", "ServeLaneShedError", "ServeOverloadedError",
    "ServeStats",
    "circuit_states", "deploy",
    "deployment", "deployments", "fleet", "lanes", "predict_columnar",
    "predict_rows", "prewarm_from_snapshot", "registry_snapshot",
    "shutdown_all", "stats",
    "undeploy",
]
