"""Per-deployment circuit breaker for the serving device stage.

Standard serving hygiene (no h2o-3 analog — its only online path is
frame-batch predict): when a deployment's DEVICE stage fails
consecutively (a bad executable, a sick accelerator, a poisoned model),
continuing to queue and dispatch traffic at it burns the batcher tick,
delays coalesced innocents and converts every request into a slow
timeout. The breaker converts that into FAST failure:

- ``closed``     — healthy; device failures increment a consecutive
                   counter (any success resets it).
- ``open``       — ``failure_threshold`` consecutive device failures
                   trip it: ``submit()`` fails immediately with a
                   503-mapped ``ServeCircuitOpenError`` carrying
                   ``retry_after_s`` (the REST layer emits the
                   ``Retry-After`` header), so clients back off and
                   OTHER deployments keep their latency.
- ``half_open``  — after ``open_secs`` the next request is admitted as
                   a PROBE batch: its success closes the circuit, its
                   failure re-opens (with a fresh cooldown).

State transitions surface on ``h2o3_circuit_state{model=...}``
(0=closed, 1=half_open, 2=open), ``h2o3_circuit_open_total`` and in
``/3/Serve/stats``; encode failures (the CLIENT's bad rows) never count
against the device's health.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

_BB_KINDS = {CLOSED: "circuit_close", HALF_OPEN: "circuit_half_open",
             OPEN: "circuit_open"}


def _bb(model: str, state: str, payload: str = "") -> None:
    """Flight-recorder append (ISSUE 19): local circuit transitions are
    control-plane decisions — a chaos post-mortem needs to see WHEN a
    deployment started failing fast. Advisory."""
    try:
        from h2o3_tpu.telemetry import blackbox
        blackbox.record(_BB_KINDS.get(state, "circuit_open"),
                        member=model or "_anon", payload=payload)
    except Exception:   # noqa: BLE001 — flight recorder is advisory
        pass


class CircuitBreaker:
    def __init__(self, model: str = "", failure_threshold: int = 5,
                 open_secs: float = 1.0, stats=None):
        self.model = model
        self.failure_threshold = max(int(failure_threshold), 1)
        self.open_secs = float(open_secs)
        self._mu = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._open_count = 0
        self._probe_inflight = False
        self._probe_at = 0.0
        # wall-clock of the last device SUCCESS: the fleet gossip layer
        # (serve/fleet.py) lets first-hand local health newer than a
        # peer's open report win over the gossip
        self._last_success_wall = 0.0
        # state gauge lives in the deployment's stats registry so it
        # follows the H2O3_TELEMETRY fallback behavior of every other
        # serve metric
        reg = stats._reg if stats is not None else None
        if reg is None:
            from h2o3_tpu.telemetry import registry
            reg = registry()
        self._gauge = reg.gauge(
            "h2o3_circuit_state", {"model": model or "_anon"},
            help="serve circuit state (0=closed, 1=half_open, 2=open)")
        self._open_ctr = reg.counter(
            "h2o3_circuit_open_total", {"model": model or "_anon"},
            help="circuit-open transitions")

    # -- admission ------------------------------------------------------

    def allow_request(self) -> Optional[float]:
        """None = admit. A float = reject, with the suggested
        Retry-After seconds. In ``open``, the cooldown expiry admits
        ONE request (transitioning to ``half_open``); while the probe
        is in flight further requests stay rejected."""
        with self._mu:
            if self._state == CLOSED:
                return None
            now = time.monotonic()
            if self._state == OPEN:
                remaining = self.open_secs - (now - self._opened_at)
                if remaining > 0:
                    return max(remaining, 0.001)
                self._state = HALF_OPEN
                self._probe_inflight = False
                self._set_gauge()
                _bb(self.model, HALF_OPEN, "cooldown expired; probing")
            # HALF_OPEN: admit a single probe; reject the rest until
            # its verdict lands. A probe can die before EVER reaching
            # the device stage (queue-full rejection, expired in queue,
            # encode failure) and those paths report no verdict — so a
            # stale probe claim expires after a cooldown-sized window
            # and the next request becomes the probe, instead of the
            # deployment wedging in half-open 503s forever.
            if self._probe_inflight \
                    and now - self._probe_at <= max(self.open_secs, 1.0):
                return max(self.open_secs, 0.001)
            self._probe_inflight = True
            self._probe_at = now
            return None

    # -- verdicts (device stage only) -----------------------------------

    def record_success(self) -> None:
        with self._mu:
            self._consecutive_failures = 0
            self._probe_inflight = False
            self._last_success_wall = time.time()
            if self._state != CLOSED:
                self._state = CLOSED
                self._set_gauge()
                _bb(self.model, CLOSED, "probe succeeded")
                from h2o3_tpu.log import info
                info("serve circuit for '%s' closed (probe succeeded)",
                     self.model)

    def record_failure(self) -> None:
        with self._mu:
            self._consecutive_failures += 1
            tripped = (self._state == HALF_OPEN
                       or self._consecutive_failures
                       >= self.failure_threshold)
            if tripped and self._state != OPEN:
                self._state = OPEN
                self._opened_at = time.monotonic()
                self._open_count += 1
                self._probe_inflight = False
                self._open_ctr.inc()
                self._set_gauge()
                _bb(self.model, OPEN,
                    f"failures={self._consecutive_failures}")
                from h2o3_tpu.log import warn
                warn("serve circuit for '%s' OPEN after %d consecutive "
                     "device failures — failing fast for %.2fs",
                     self.model, self._consecutive_failures,
                     self.open_secs)
            elif tripped:
                # already open (e.g. a straggler in-flight batch): push
                # the cooldown out from the latest failure
                self._opened_at = time.monotonic()

    def _set_gauge(self) -> None:
        self._gauge.set(_STATE_CODE[self._state])

    # -- introspection --------------------------------------------------

    @property
    def state(self) -> str:
        with self._mu:
            return self._state

    @property
    def last_success_time(self) -> float:
        with self._mu:
            return self._last_success_wall

    def publish(self) -> Dict[str, object]:
        """Gossip-shaped state for the telemetry snapshot's ``circuit``
        payload (ISSUE 9): what a PEER needs to shed load — the state,
        a Retry-After suggestion (remaining cooldown; a whole window
        when the cooldown already lapsed and the probe is pending) and
        the report's wall time so receivers can age it."""
        with self._mu:
            retry = 0.0
            if self._state == OPEN:
                remaining = self.open_secs - (time.monotonic()
                                              - self._opened_at)
                retry = (max(remaining, 0.05) if remaining > 0
                         else max(self.open_secs, 0.05))
            elif self._state == HALF_OPEN:
                retry = max(self.open_secs, 0.05)
            return {"model": self.model, "state": self._state,
                    "retry_after_s": round(retry, 3),
                    "open_count": self._open_count,
                    "consecutive_failures": self._consecutive_failures,
                    "time": time.time()}

    def snapshot(self) -> Dict[str, object]:
        with self._mu:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "open_count": self._open_count,
                "failure_threshold": self.failure_threshold,
                "open_secs": self.open_secs,
                "seconds_in_state": (
                    round(time.monotonic() - self._opened_at, 3)
                    if self._state == OPEN else None),
            }
