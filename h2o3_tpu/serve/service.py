"""Model serving registry: deploy/undeploy/score/stats.

deploy(key) pins the model in the DKV (shared read-lock, so DELETE
/3/Models of a deployed model 409s instead of yanking weights out from
under live traffic), pre-builds the row codec's enum LUTs, and warms
one compiled predict executable per batch bucket — after deploy()
returns, the steady-state scoring path compiles nothing.

One Deployment per model key; re-deploying an already-deployed key with
new knobs drains and replaces the old pipeline.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from h2o3_tpu.serve.batcher import (MicroBatcher, ServeBadRequestError,
                                    ServeCircuitOpenError,
                                    ServeClosedError, ServeDeadlineError,
                                    ServeError, ServeOverloadedError)
from h2o3_tpu.serve import fleet
from h2o3_tpu.serve.circuit import CircuitBreaker
from h2o3_tpu.serve.codec import RowCodec
from h2o3_tpu.serve.registry import DEFAULT_BUCKETS, CompiledScorer
from h2o3_tpu.serve.stats import ServeStats, merge_snapshots

__all__ = ["deploy", "undeploy", "deployment", "deployments",
           "predict_rows", "predict_columnar", "stats", "shutdown_all",
           "circuit_states", "fleet",
           "registry_snapshot", "prewarm_from_snapshot",
           "Deployment",
           "ServeError", "ServeOverloadedError", "ServeDeadlineError",
           "ServeBadRequestError", "ServeClosedError",
           "ServeCircuitOpenError"]

_DEPLOYMENTS: Dict[str, "Deployment"] = {}
_LOCK = threading.Lock()


class Deployment:
    def __init__(self, key: str, model, *, max_batch: int = 512,
                 max_delay_ms: float = 2.0, queue_limit: int = 8192,
                 timeout_ms: float = 10_000.0,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 warm: bool = True, pinned: bool = False,
                 circuit_failures: int = 5,
                 circuit_open_ms: float = 1000.0):
        if not hasattr(model, "_predict_matrix"):
            raise ValueError(
                f"model '{key}' has no batch predict path "
                f"(_predict_matrix) — only trained h2o3_tpu models "
                f"can be deployed")
        if model.params.get("offset_column"):
            raise ValueError(
                "offset-trained models cannot be deployed for row "
                "serving: rows carry no offset column")
        buckets = tuple(sorted({int(b) for b in buckets}))
        if max_batch > max(buckets):
            raise ValueError(f"max_batch={max_batch} exceeds the largest "
                             f"bucket {max(buckets)}")
        # prune buckets bucket_for can never pick: batches cap at
        # max_batch rows, so anything past the smallest bucket >=
        # max_batch would only add dead warm-compile time + memory
        cap = min(b for b in buckets if b >= max_batch)
        buckets = tuple(b for b in buckets if b <= cap)
        self.key = key
        self.model = model
        self.pinned = pinned                  # holds a DKV read-lock
        self.created = time.time()
        self.config = dict(max_batch=int(max_batch),
                           max_delay_ms=float(max_delay_ms),
                           queue_limit=int(queue_limit),
                           timeout_ms=float(timeout_ms),
                           buckets=list(buckets),
                           circuit_failures=int(circuit_failures),
                           circuit_open_ms=float(circuit_open_ms))
        self.codec = RowCodec(model)
        t0 = time.perf_counter()
        self.scorer = CompiledScorer(model, buckets=buckets, warm=warm)
        self.warm_seconds = time.perf_counter() - t0
        # output-contract validation (warm probes recorded the shape):
        # a classifier whose _predict_matrix yields a 1-D margin (its
        # predict() override is the only valid scoring path, e.g.
        # uplift) would crash decode on EVERY request — reject at
        # deploy instead of 500ing live traffic
        if warm and self.codec.nclasses > 1 \
                and self.scorer.out_ndim is not None:
            if self.scorer.out_ndim != 2 \
                    or self.scorer.out_k != self.codec.nclasses:
                raise ValueError(
                    f"model '{key}' ({getattr(model, 'algo', '?')}) "
                    f"declares {self.codec.nclasses} classes but its "
                    f"batch predict returns "
                    f"{self.scorer.out_ndim}-D/"
                    f"{self.scorer.out_k}-wide output — this algo's "
                    f"predict() override is not row-servable")
        self.stats = ServeStats(model=key)
        # per-deployment circuit breaker: N consecutive device-stage
        # failures → open (fast 503 + Retry-After) → half-open probe
        self.breaker = CircuitBreaker(
            model=key, failure_threshold=circuit_failures,
            open_secs=float(circuit_open_ms) / 1000.0, stats=self.stats)
        # performance accounting (ISSUE 11): per-deployment MFU from the
        # warm buckets' executable costs x dispatched batches over the
        # measured device stage (None when telemetry is off)
        from h2o3_tpu.telemetry import costmodel
        self.perf = costmodel.accumulator("serve")
        self.batcher = MicroBatcher(
            encode=self.codec.encode, dispatch=self.scorer.score,
            decode=self.codec.decode_batch, stats=self.stats,
            bucket_for=self.scorer.bucket_for, max_batch=max_batch,
            max_delay_ms=max_delay_ms, queue_limit=queue_limit,
            default_timeout_ms=timeout_ms, breaker=self.breaker,
            fleet_check=self._fleet_check,
            # hook whenever accounting is on — bucket costs may arrive
            # AFTER construction (warm=False deploys warm lazily), and
            # _perf_hook tolerates a bucket with no captured cost
            perf_hook=(self._perf_hook if self.perf is not None
                       else None))

    def _perf_hook(self, padded_rows: int, device_s: float):
        """Collector-thread accounting seam: the dispatched bucket's
        warm-time executable cost + the batch's measured device stage."""
        cost = self.scorer.bucket_costs.get(padded_rows)
        if cost is not None:
            self.perf.add(cost)
        self.perf.add_device_seconds(device_s)

    def perf_snapshot(self):
        """Roofline point for this deployment's cumulative serve work
        (None when telemetry is off or nothing was dispatched yet) —
        the ``perf`` block in ``/3/Serve/stats``."""
        if self.perf is None:
            return None
        return self.perf.point()

    def _fleet_check(self):
        """Peer-circuit gossip verdict for this deployment: a peer
        replica's OPEN circuit sheds load here (fast 503 + Retry-After)
        unless the local breaker has fresher first-hand evidence of
        health (serve/fleet.py 'local state wins' contract)."""
        return fleet.reject_for(
            self.key, local_healthy_since=self.breaker.last_success_time)

    def predict_rows(self, rows: Sequence[Dict[str, Any]],
                     timeout_ms: Optional[float] = None,
                     lane: Optional[str] = None
                     ) -> List[Dict[str, Any]]:
        """Score a list of row dicts through the micro-batcher. Requests
        larger than max_batch are split — the slices pipeline through
        consecutive ticks. ``lane`` is the deadline class (ISSUE 20)."""
        mb = self.batcher.max_batch
        if len(rows) <= mb:
            return self.batcher.submit(rows, timeout_ms=timeout_ms,
                                       lane=lane)
        out: List[Dict[str, Any]] = []
        for s in range(0, len(rows), mb):
            out.extend(self.batcher.submit(rows[s: s + mb],
                                           timeout_ms=timeout_ms,
                                           lane=lane))
        return out

    def predict_columnar(self, rows: Sequence[Dict[str, Any]],
                         timeout_ms: Optional[float] = None,
                         lane: Optional[str] = None
                         ) -> Dict[str, List]:
        """Score rows and return COLUMN arrays (``{"predict": [...],
        "p<label>": [...]}`` — the H2O predictions-frame shape) from the
        batch's one vectorized decode instead of per-row dicts. Values
        bit-match ``predict_rows`` on the same rows; the per-row dict
        build (~30% of the batched path) is skipped."""
        mb = self.batcher.max_batch
        if len(rows) <= mb:
            return self.batcher.submit(rows, timeout_ms=timeout_ms,
                                       columnar=True, lane=lane)
        out: Dict[str, List] = {}
        for s in range(0, len(rows), mb):
            part = self.batcher.submit(rows[s: s + mb],
                                       timeout_ms=timeout_ms,
                                       columnar=True, lane=lane)
            if not out:
                out = part
            else:
                for c, vals in part.items():
                    out[c].extend(vals)
        return out

    def info(self) -> Dict[str, Any]:
        return {"model": self.key,
                "algo": getattr(self.model, "algo", "?"),
                "nclasses": self.codec.nclasses,
                "n_features": self.codec.n_features,
                "compiled_buckets": list(self.scorer.buckets),
                "jitted": self.scorer.jitted,
                "warm_seconds": round(self.warm_seconds, 3),
                "created": self.created,
                **self.config}

    def close(self):
        self.batcher.close()


def _pin_key(key: str) -> str:
    return f"$serve_{key}"


def deploy(model_key: str, model=None, **config) -> Deployment:
    """Deploy a model for row serving. ``model`` may be passed directly
    (embedded use: bench/tools); the DKV pin (shared read-lock blocking
    DELETE /3/Models) is taken whenever the key is store-resident —
    via lookup OR when the passed object IS the stored one (the
    Model.deploy() Python path) — so the 409-until-undeploy contract
    holds on every deploy spelling."""
    from h2o3_tpu import dkv
    # a live pinned deployment shares the $serve_<key> reader entry; a
    # FAILED re-deploy must then leave the pin in place for it
    existing = deployment(model_key)
    already_pinned = existing is not None and existing.pinned
    pinned = False
    if model is None:
        model = dkv.get_and_read_lock(model_key, "model", _pin_key(model_key))
        pinned = True
    else:
        ent = dkv.get_opt(model_key)
        if ent is not None and ent[0] == "model" and ent[1] is model:
            dkv.read_lock(model_key, _pin_key(model_key))
            pinned = True
    try:
        dep = Deployment(model_key, model, pinned=pinned, **config)
    except BaseException:
        if pinned and not already_pinned:
            dkv.unlock(model_key, _pin_key(model_key))
        raise
    with _LOCK:
        old = _DEPLOYMENTS.pop(model_key, None)
        _DEPLOYMENTS[model_key] = dep
    if old is not None:
        old.close()
        # both pinned: the shared read-lock entry is keyed by the same
        # $serve_<key> job, so the new deployment simply inherits it
        if old.pinned and not pinned:
            dkv.unlock(model_key, _pin_key(model_key))
    return dep


def undeploy(model_key: str) -> bool:
    from h2o3_tpu import dkv
    with _LOCK:
        dep = _DEPLOYMENTS.pop(model_key, None)
    if dep is None:
        return False
    dep.close()
    if dep.pinned:
        dkv.unlock(model_key, _pin_key(model_key))
    return True


def deployment(model_key: str) -> Optional[Deployment]:
    with _LOCK:
        return _DEPLOYMENTS.get(model_key)


def deployments() -> List[Deployment]:
    with _LOCK:
        return list(_DEPLOYMENTS.values())


def predict_rows(model_key: str, rows: Sequence[Dict[str, Any]],
                 timeout_ms: Optional[float] = None,
                 lane: Optional[str] = None) -> List[Dict[str, Any]]:
    dep = deployment(model_key)
    if dep is None:
        raise KeyError(f"model '{model_key}' is not deployed — POST "
                       f"/3/Serve/models/{model_key} first")
    return dep.predict_rows(rows, timeout_ms=timeout_ms, lane=lane)


def predict_columnar(model_key: str, rows: Sequence[Dict[str, Any]],
                     timeout_ms: Optional[float] = None,
                     lane: Optional[str] = None) -> Dict[str, List]:
    dep = deployment(model_key)
    if dep is None:
        raise KeyError(f"model '{model_key}' is not deployed — POST "
                       f"/3/Serve/models/{model_key} first")
    return dep.predict_columnar(rows, timeout_ms=timeout_ms, lane=lane)


def circuit_states() -> List[Dict[str, Any]]:
    """Every deployment's circuit-breaker state in gossip shape — the
    ``circuit`` payload of this process's /3/Telemetry/snapshot body
    (peers ingest it via serve/fleet.py)."""
    return [dep.breaker.publish() for dep in deployments()]


def registry_snapshot() -> Dict[str, Any]:
    """Warm cold-start export (ISSUE 13): what a JOINING replica needs
    to pre-warm before taking routed traffic — every deployment's model
    key and deploy config. The model BITS are not shipped: replicas
    resolve the key from their own DKV (identical training, restart
    recovery, or a shared store); the shared persistent compile cache
    turns the warm compiles into cache reads. Served at
    ``GET /3/Fleet/registry`` and piggybacked on the join response."""
    return {"version": 1,
            "deployments": [{"model": dep.key,
                             "algo": getattr(dep.model, "algo", "?"),
                             "config": dict(dep.config)}
                            for dep in deployments()]}


def prewarm_from_snapshot(snapshot: Dict[str, Any]) -> Dict[str, Any]:
    """Deploy (compile-warm) every model in a fleet registry snapshot
    that THIS process can resolve from its DKV. Returns
    ``{"deployed": [...], "skipped": [{"model", "reason"}, ...]}`` —
    an unresolvable model is reported, never fatal: the router learns
    what this replica actually serves from its heartbeat's deployment
    list, so a partial prewarm degrades routing, not correctness."""
    from h2o3_tpu import dkv
    deployed: List[str] = []
    skipped: List[Dict[str, str]] = []
    for ent in (snapshot or {}).get("deployments") or []:
        key = ent.get("model")
        if not key:
            continue
        if deployment(key) is not None:
            deployed.append(key)
            continue
        stored = dkv.get_opt(key)
        if stored is None or stored[0] != "model":
            skipped.append({"model": key,
                            "reason": "model not resolvable in this "
                                      "process's store"})
            continue
        cfg = {k: v for k, v in (ent.get("config") or {}).items()
               if k in ("max_batch", "max_delay_ms", "queue_limit",
                        "timeout_ms", "buckets", "circuit_failures",
                        "circuit_open_ms")}
        try:
            deploy(key, **cfg)
            deployed.append(key)
        except Exception as e:   # noqa: BLE001 — warmup is best-effort
            skipped.append({"model": key, "reason": repr(e)})
    return {"deployed": deployed, "skipped": skipped}


def stats() -> Dict[str, Any]:
    per_model = {}
    for dep in deployments():
        per_model[dep.key] = {**dep.stats.snapshot(),
                              "pending_rows": dep.batcher.pending_rows,
                              "circuit": dep.breaker.snapshot(),
                              # per-deployment MFU/roofline (ISSUE 11)
                              "perf": dep.perf_snapshot()}
    return {"models": per_model,
            "total": merge_snapshots(list(per_model.values())),
            # fleet view (ISSUE 9): local circuit states + live peer
            # open reports — "which replicas are shedding what"
            "fleet_circuit": fleet.fleet_snapshot(local=circuit_states())}


def shutdown_all():
    """Undeploy everything (test/interpreter teardown)."""
    for dep in deployments():
        undeploy(dep.key)
    fleet.reset()
