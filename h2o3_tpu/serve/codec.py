"""Row codec: dict rows ⇄ padded device batches for the scoring service.

Encode is the vectorized form of EasyPredictModelWrapper's RowData
contract (genmodel.rows_to_matrix does the per-column work: enum-label
LUTs, unknown-level→NA policy, missing→NA), writing straight into a
bucket-padded float32 buffer so the batcher hands XLA one of the warm
batch shapes. Decode mirrors Model.predict's output schema per row:
regression → {"value"}, classification → {"label",
"classProbabilities"} over the training response domain, with the same
balance_classes probability un-correction the frame path applies —
micro-batched predictions must be bit-identical to model.predict on the
same rows.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.genmodel import build_domain_luts, rows_to_matrix


class RowCodec:
    def __init__(self, model, convert_unknown_categorical_levels_to_na:
                 bool = True):
        self.columns = list(model.feature_names)
        self.cat_domains = {k: tuple(v) for k, v in
                            (model.cat_domains or {}).items()}
        self.response_domain = list(model.response_domain or [])
        self.nclasses = int(getattr(model, "nclasses", 1) or 1)
        self.convert_unknown = bool(convert_unknown_categorical_levels_to_na)
        self._luts = build_domain_luts(self.columns, self.cat_domains)
        self.unknown_categorical_levels_seen: Dict[str, int] = {}
        self._model = model

    @property
    def n_features(self) -> int:
        return len(self.columns)

    # -- encode ---------------------------------------------------------

    def encode(self, rows: Sequence[Dict[str, Any]],
               pad_to: Optional[int] = None) -> np.ndarray:
        """[n rows] dicts → [pad_to or n, F] float32, NaN=NA. Pad rows
        (beyond n) stay NaN — the scorer masks them by n_active."""
        n = len(rows)
        pad = int(pad_to or n)
        if pad < n:
            raise ValueError(f"pad_to={pad} < {n} rows")
        out = np.full((pad, self.n_features), np.nan, np.float32)
        rows_to_matrix(
            rows, self.columns, self.cat_domains,
            convert_unknown_categorical_levels_to_na=self.convert_unknown,
            unknown_seen=self.unknown_categorical_levels_seen,
            luts=self._luts, out=out)
        return out

    # -- decode ---------------------------------------------------------

    def decode_batch(self, scores: np.ndarray, n: int) -> "DecodedBatch":
        """ONE vectorized pass over the batch's device output: slice off
        the pad tail, un-correct probabilities (balance_classes) and
        argmax labels for the WHOLE batch — per-request row dicts or
        column arrays are then cheap views (``DecodedBatch.rows`` /
        ``.columns``). The per-row Python dict build used to be ~30% of
        the batched path; columnar responses skip it entirely."""
        scores = np.asarray(scores)[:n]
        if self.nclasses <= 1:
            return DecodedBatch(self, values=scores.reshape(-1)[:n])
        # identical post-processing to Model.predict: probability
        # un-correction for balance_classes, then argmax labels
        probs = self._model._correct_probabilities(scores)
        return DecodedBatch(self, probs=probs,
                            labels=np.argmax(probs, axis=1))

    def decode(self, scores: np.ndarray, n: int) -> List[Dict[str, Any]]:
        """[padded(, K)] device output → n per-row prediction dicts
        (EasyPredict AbstractPrediction shape)."""
        return self.decode_batch(scores, n).rows(0, n)


class DecodedBatch:
    """Vectorized decode result shared by every request in one batch:
    row-shaped and columnar views over the same arrays, so mixed-format
    requests coalesced into one tick pay ONE probability pass."""
    __slots__ = ("codec", "values", "probs", "labels", "_dom")

    def __init__(self, codec: RowCodec, values: Optional[np.ndarray] = None,
                 probs: Optional[np.ndarray] = None,
                 labels: Optional[np.ndarray] = None):
        self.codec = codec
        self.values = values
        self.probs = probs
        self.labels = labels
        self._dom = [str(d) for d in
                     (codec.response_domain
                      or [str(k) for k in range(codec.nclasses)])]

    def rows(self, off: int, k: int) -> List[Dict[str, Any]]:
        """Per-row prediction dicts for rows [off, off+k) — bit-identical
        to the pre-columnar decode path."""
        if self.values is not None:
            return [{"value": float(v)} for v in self.values[off:off + k]]
        dom = self._dom
        K = len(dom)
        probs = self.probs
        labels = self.labels
        return [{
            "label": dom[int(labels[i])],
            "classProbabilities": {dom[c]: float(probs[i, c])
                                   for c in range(K)},
        } for i in range(off, off + k)]

    def columns(self, off: int, k: int) -> Dict[str, List]:
        """Columnar view for rows [off, off+k): ``predict`` plus one
        ``p<label>`` column per class (the H2O predictions-frame column
        convention) — built from array slices, no per-row dicts."""
        if self.values is not None:
            return {"predict": [float(v)
                                for v in self.values[off:off + k]]}
        dom = self._dom
        lab = self.labels[off:off + k]
        cols: Dict[str, List] = {
            "predict": [dom[int(i)] for i in lab]}
        pr = self.probs[off:off + k]
        for c, d in enumerate(dom):
            cols[f"p{d}"] = pr[:, c].astype(float).tolist()
        return cols
