"""Fleet-shared circuit state — sick replicas shed load cluster-wide.

PR 6's circuit breaker is per-process: replica A's device stage failing
consecutively opens A's circuit, but replicas B..N keep queueing traffic
at the same deployment (same poisoned model, same sick accelerator
class) and burn their own ticks discovering it independently. The
reference's answer is cloud membership — every node hears about a sick
member on the heartbeat (SURVEY L1/L2). Since ISSUE 13 that is
literally the vehicle: circuit state is PUSH gossip piggybacked on the
fleet heartbeat, with the telemetry scrape as the pull fallback:

- **push (primary)**: every fleet heartbeat carries this replica's
  circuit states (``circuit_states()``) to the router; the heartbeat
  RESPONSE piggybacks every peer's states back
  (fleet/agent.py ``beat_once`` → ``observe_peer_states``), so an open
  circuit anywhere sheds load on every member within two beats —
  sub-second at the default 500ms beat, vs the multi-second scrape.
- **pull (fallback)**: processes outside the fleet (static
  ``H2O3_TELEMETRY_PEERS`` deployments, tests) still propagate through
  the cluster scrape — each snapshot's ``circuit`` payload feeds this
  store via ``PEER_SNAPSHOT_CONSUMERS`` exactly as PR 9 built it.
- the serve admission path (``MicroBatcher.submit`` via the
  deployment's ``fleet_check``) consults ``reject_for``: an open PEER
  circuit for this deployment → fast 503 + ``Retry-After``, exactly the
  local breaker's client contract.

Membership churn keeps the store honest: when a member leaves or is
evicted, ``drop_source`` removes its entries NOW — before ISSUE 13 a
dead replica's open report lingered for
``max(retry_after, H2O3_FLEET_CIRCUIT_TTL)`` and kept shedding load
toward a model only the dead replica served.

Local state always wins over stale peer gossip:

- reports about THIS process (the launcher's shared-peer-list / test
  self-peer spelling) never enter the rejection store — the local
  breaker already owns that verdict;
- a device success observed LOCALLY after a peer report was ingested
  overrides it (``local_healthy_since``): this replica has fresher
  first-hand evidence that the deployment serves;
- entries expire after ``max(retry_after_s, H2O3_FLEET_CIRCUIT_TTL)``
  seconds (default 15s), and a peer reporting its circuit closed clears
  its own earlier open report on the next scrape.

``h2o3_fleet_circuit_open{model=...}`` gauges the number of live peer
open reports; ``/3/Serve/stats`` carries the merged view as
``fleet_circuit``.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

# (model, source) -> entry; source is the reporting peer's pid@host (or
# jax process index) from its snapshot identity
_STORE: Dict[Tuple[str, str], Dict[str, object]] = {}
_MU = threading.Lock()
# lock-free hot-path hint: the submit path must cost ~nothing while the
# fleet is healthy (the common case)
_HAS_OPEN = False


def _ttl() -> float:
    """Gossip time-to-live beyond an entry's own retry window
    (``H2O3_FLEET_CIRCUIT_TTL`` seconds, default 15): a peer that died
    while open must not shed this replica's load forever. Malformed
    values fall back — serve must not break on a typo'd knob."""
    try:
        v = float(os.environ.get("H2O3_FLEET_CIRCUIT_TTL", "15") or 15)
        return v if v > 0 else 15.0
    except ValueError:
        return 15.0


def _gauge(model: str):
    from h2o3_tpu.telemetry import registry
    return registry().gauge(
        "h2o3_fleet_circuit_open", {"model": model},
        help="live peer-reported open circuits for this deployment")


def _expire_locked(now: float) -> set:
    """Drop aged entries; returns the models that lost one (their
    gauge needs re-publishing — a model whose LAST entry expires would
    otherwise read 1 on dashboards forever)."""
    ttl = _ttl()
    expired = set()
    for k in list(_STORE):
        e = _STORE[k]
        if now - float(e["observed"]) > max(float(e["retry_after_s"]),
                                            ttl):
            del _STORE[k]
            expired.add(k[0])
    return expired


def _publish_gauges(models) -> None:
    counts = {m: 0 for m in models}
    with _MU:
        for (m, _s) in _STORE:
            if m in counts:
                counts[m] += 1
    for m, c in counts.items():
        try:
            _gauge(m).set(c)
        except Exception:   # noqa: BLE001 — telemetry must not break serve
            pass


def _set_has_open_locked() -> None:
    global _HAS_OPEN
    _HAS_OPEN = bool(_STORE)


def observe_peer_states(states: Optional[List[dict]], source: str,
                        self_process: bool = False) -> None:
    """Ingest one peer snapshot's circuit payload. ``self_process=True``
    (the snapshot came from THIS process — a self-peer spelling) clears
    any earlier entries under this source but never creates rejection
    state: the local breaker is the authority on local health."""
    now = time.monotonic()
    touched = set()
    fresh_opens = []
    with _MU:
        for st in states or []:
            model = st.get("model")
            if not model:
                continue
            key = (str(model), source)
            touched.add(str(model))
            if st.get("state") == "open" and not self_process:
                if key not in _STORE:
                    fresh_opens.append(str(model))
                try:
                    ra = float(st.get("retry_after_s", 1.0) or 1.0)
                except (TypeError, ValueError):
                    ra = 1.0
                # age the entry by the REPORT's wall time (publish()'s
                # 'time' field), not the scrape's ingest time: a local
                # device success between publish and scrape is fresher
                # first-hand evidence and must win. Clamped to now so a
                # peer with a skewed clock cannot mint gossip from the
                # future that local evidence could never override.
                try:
                    t_rep = float(st.get("time") or 0.0)
                except (TypeError, ValueError):
                    t_rep = 0.0
                wall = time.time()
                t_rep = min(t_rep, wall) if t_rep > 0 else wall
                _STORE[key] = {"model": str(model), "source": source,
                               "state": "open",
                               "retry_after_s": max(ra, 0.05),
                               "open_count": st.get("open_count"),
                               "observed": now,
                               "time": t_rep}
            else:
                # closed/half_open (or a self report): a peer's fresher
                # word about ITSELF clears its stale open gossip
                _STORE.pop(key, None)
        expired = _expire_locked(now)
        _set_has_open_locked()
    for model in fresh_opens:
        # flight recorder (ISSUE 19): a gossiped open circuit arriving
        # here is a control-plane decision — this replica starts
        # shedding load toward `model` on a PEER's word
        try:
            from h2o3_tpu.telemetry import blackbox
            blackbox.record("circuit_gossip", member=model,
                            payload=f"open from={source}")
        except Exception:   # noqa: BLE001 — flight recorder is advisory
            pass
    _publish_gauges(touched | expired)


def drop_source(source: str) -> None:
    """Membership-churn expiry (ISSUE 13): the member behind ``source``
    left or was evicted, so every circuit entry it gossiped drops NOW —
    a dead replica must not keep shedding this replica's load toward a
    model only IT was failing on, and a departed-but-alive replica's
    stale open report must not outlive its membership."""
    touched = set()
    with _MU:
        for k in [k for k in _STORE if k[1] == source]:
            touched.add(k[0])
            del _STORE[k]
        _set_has_open_locked()
    if touched:
        _publish_gauges(touched)


def reject_for(model: str,
               local_healthy_since: float = 0.0
               ) -> Optional[Tuple[float, str]]:
    """Admission verdict for one deployment: ``None`` admits; a
    ``(retry_after_s, source)`` tuple sheds with a 503 + Retry-After.
    ``local_healthy_since`` is the local breaker's last device-success
    wall time — first-hand evidence newer than the gossip wins, so a
    replica actively serving this deployment successfully never sheds
    on old news."""
    if not _HAS_OPEN:
        return None
    now = time.monotonic()
    best: Optional[Tuple[float, str]] = None
    with _MU:
        expired = _expire_locked(now)
        _set_has_open_locked()
        for (m, src), e in _STORE.items():
            if m != model:
                continue
            if local_healthy_since and \
                    local_healthy_since > float(e["time"]):
                continue
            remaining = max(float(e["retry_after_s"])
                            - (now - float(e["observed"])), 0.05)
            if best is None or remaining > best[0]:
                best = (remaining, src)
    if expired:
        _publish_gauges(expired)
    return best


def fleet_snapshot(local: Optional[List[dict]] = None) -> Dict[str, object]:
    """The ``fleet_circuit`` block of ``/3/Serve/stats``: this process's
    own circuit states plus every live peer report."""
    now = time.monotonic()
    with _MU:
        expired = _expire_locked(now)
        _set_has_open_locked()
        peers = [{"model": e["model"], "source": e["source"],
                  "state": e["state"],
                  "retry_after_s": round(max(
                      float(e["retry_after_s"])
                      - (now - float(e["observed"])), 0.0), 3),
                  "age_s": round(now - float(e["observed"]), 3),
                  "open_count": e.get("open_count")}
                 for e in _STORE.values()]
    if expired:
        _publish_gauges(expired)
    return {"local": list(local or []), "peers": peers,
            "shedding": sorted({p["model"] for p in peers})}


def reset() -> None:
    """Drop every peer entry (tests / undeploy-all teardown)."""
    global _HAS_OPEN, _FLEET_EPOCH
    with _MU:
        models = {m for (m, _s) in _STORE}
        _STORE.clear()
        _HAS_OPEN = False
        _FLEET_EPOCH = None
    _publish_gauges(models)


# ---------------- fleet-epoch echo (ISSUE 20) ---------------------------
#
# The membership epoch this replica last heard from a router (join /
# heartbeat response). Scoring responses echo it as the
# ``X-H2O3-Fleet-Epoch`` header so an affinity client that dispatched
# straight to this replica learns its pinned ring went stale WITHOUT a
# round trip to a router — the zero-hop fast path stays self-correcting.

_FLEET_EPOCH: Optional[int] = None


def note_fleet_epoch(epoch: int) -> None:
    """Record the fleet epoch from a router response (monotonic —
    a stale note from a slow beat never rolls it back)."""
    global _FLEET_EPOCH
    with _MU:
        if _FLEET_EPOCH is None or int(epoch) > _FLEET_EPOCH:
            _FLEET_EPOCH = int(epoch)


def fleet_epoch() -> Optional[int]:
    """The last-heard membership epoch, or None outside a fleet."""
    with _MU:
        return _FLEET_EPOCH


# ---------------- telemetry-plane wiring --------------------------------
#
# The cluster scrape (telemetry/snapshot.py cluster_samples) hands every
# fetched peer snapshot to registered consumers; circuit gossip is one.
# Registration happens at serve-package import — a process that never
# imports serve has no deployments and nothing to shed.

def _consume_peer_snapshot(snap: dict, self_process: bool) -> None:
    proc = snap.get("process") or {}
    source = f"{proc.get('pid', '?')}@{proc.get('host', '?')}"
    observe_peer_states(snap.get("circuit"), source,
                        self_process=self_process)


def _register() -> None:
    from h2o3_tpu.telemetry import snapshot as telesnap
    if _consume_peer_snapshot not in telesnap.PEER_SNAPSHOT_CONSUMERS:
        telesnap.PEER_SNAPSHOT_CONSUMERS.append(_consume_peer_snapshot)


_register()
