"""Deadline-class admission lanes for the serving path (ISSUE 20).

The training scheduler (sched/core.py) runs three priority classes —
``interactive`` > ``bulk`` > ``background`` — so a grid's bulk children
can never starve a user's direct train. Serving had no mirror: one
saturating bulk scoring flood filled the micro-batcher's row queue and
interactive p99 rode the whole backlog. These lanes are that mirror,
enforced at BOTH admission points:

- **batcher** (serve/batcher.py): each request carries a lane; the
  pending queue keeps per-lane row budgets — ``interactive`` may fill
  the whole queue, ``bulk`` and ``background`` only their configured
  fraction of it — and the batch pickup drains lanes in priority
  order, so an interactive row admitted behind a bulk backlog still
  boards the next tick's batch.
- **router** (fleet/router.py): a replica whose reported load exceeds
  a lane's budget fraction is not eligible for that lane, so bulk
  traffic sheds at the front door (503 + Retry-After, a ``lane_shed``
  flight-recorder event) while interactive still routes.

A lane arrives as an explicit ``X-H2O3-Lane`` header (or ``lane``
body/query param) and otherwise defaults from the request path:
row-scoring endpoints are interactive, frame/batch exports are bulk.

The class names and their order are the scheduler's
(``sched.PRIORITY_LEVELS``) — asserted in tests — but defined here
standalone so the serve admission path never imports the training
scheduler.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

__all__ = ["LANES", "LANE_LEVELS", "DEFAULT_LANE", "budget_fraction",
           "default_for_path", "normalize"]

# priority order mirrors sched/core.py: lower level = drained first
LANES: Tuple[str, ...] = ("interactive", "bulk", "background")
LANE_LEVELS = {"interactive": 0, "bulk": 1, "background": 2}
DEFAULT_LANE = "interactive"

# fraction of the queue (batcher: queue_limit rows; router: a member's
# load capacity) a lane may occupy. Interactive owns the whole queue —
# its isolation comes from the lower lanes' caps, not its own.
_DEFAULT_BUDGETS = {"interactive": 1.0, "bulk": 0.5, "background": 0.25}


def normalize(lane: Optional[str]) -> str:
    """Validated lane name; ``None``/empty defaults to interactive.
    Unknown names raise — a typo'd lane must not silently ride the
    highest class."""
    if not lane:
        return DEFAULT_LANE
    name = str(lane).strip().lower()
    if name not in LANE_LEVELS:
        raise ValueError(f"unknown lane '{lane}' (one of {list(LANES)})")
    return name


def budget_fraction(lane: str) -> float:
    """The lane's queue-budget fraction (``H2O3_SERVE_LANE_<LANE>``
    overrides, clamped to (0, 1]; malformed values fall back — serving
    must not break on a typo'd knob)."""
    base = _DEFAULT_BUDGETS.get(lane, 1.0)
    raw = os.environ.get(f"H2O3_SERVE_LANE_{lane.upper()}", "")
    if raw:
        try:
            v = float(raw)
            if 0.0 < v <= 1.0:
                return v
        except ValueError:
            pass
    return base


def default_for_path(path: str) -> str:
    """Lane when the client did not say: row scoring is interactive
    (a human or online system is waiting on the response); frame-batch
    scoring and bulk exports are bulk."""
    p = str(path or "").lower()
    if "/frames/" in p or p.endswith("/predict") or "downloaddataset" in p:
        return "bulk"
    return DEFAULT_LANE
