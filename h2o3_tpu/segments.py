"""Segment model building — one model per data segment.

Reference: hex/segments/SegmentModelsBuilder.java (+ WorkAllocator):
`train_segments` in h2o-py trains the same builder config once per
distinct combination of segment-column values and collects per-segment
models/errors into a SegmentModels listing.

TPU re-design: segments are host-side row masks over the shared frame;
each segment trains through the normal builder path (optionally in a
thread pool — the WorkAllocator analog), models land in the keyed
store."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame


class SegmentModels:
    """Result listing (ai/h2o's SegmentModels keyed object)."""

    def __init__(self, rows: List[Dict]):
        self._rows = rows

    def as_frame(self) -> List[Dict]:
        return self._rows

    def models(self) -> List:
        return [r["model"] for r in self._rows if r["model"] is not None]

    def __len__(self):
        return len(self._rows)

    def __iter__(self):
        return iter(self._rows)


def train_segments(builder_factory, segment_columns: Sequence[str],
                   y: str, training_frame: Frame,
                   x: Optional[Sequence[str]] = None,
                   parallelism: int = 1,
                   max_segments: int = 1000) -> SegmentModels:
    """Train one model per segment. `builder_factory()` returns a fresh
    estimator per call (params pre-bound)."""
    cols = []
    for c in segment_columns:
        v = training_frame.vec(c)
        if v.is_categorical:
            cols.append(np.asarray(v.to_strings(), dtype=object))
        else:
            cols.append(v.to_numpy())

    def seg_key(vals):
        # NaN != NaN would make every NA row its own segment; collapse
        # all NAs (float NaN or enum/string None) to one None segment
        return tuple(None if (x is None or (isinstance(x, float)
                                            and np.isnan(x)))
                     else x for x in vals)

    keys = [seg_key(k) for k in zip(*cols)]
    uniq = []
    seen = set()
    for k in keys:
        if k not in seen:
            seen.add(k)
            uniq.append(k)
    if len(uniq) > max_segments:
        raise ValueError(f"{len(uniq)} segments exceed max_segments="
                         f"{max_segments}")
    feat_x = x
    if feat_x is not None:
        feat_x = [c for c in feat_x if c not in segment_columns]

    def one(seg):
        mask = np.ones(training_frame.nrow, bool)
        for c_arr, v in zip(cols, seg):
            if v is None:
                if c_arr.dtype == object:   # enum/string NA = None
                    mask &= np.asarray([x is None for x in c_arr])
                else:
                    mask &= np.isnan(c_arr.astype(float))
            else:
                mask &= (c_arr == v)
        sub = training_frame.rows(mask).drop(list(segment_columns))
        row = {"segment": dict(zip(segment_columns, seg)),
               "nrow": int(mask.sum()), "model": None,
               "status": "PENDING", "error": None}
        try:
            est = builder_factory()
            est.train(x=feat_x, y=y, training_frame=sub)
            row["model"] = est.model
            row["status"] = "SUCCEEDED"
        except Exception as e:  # per-segment failure is recorded, not fatal
            row["status"] = "FAILED"
            row["error"] = str(e)
        return row

    from h2o3_tpu.models.model_base import build_parallelism
    parallelism = build_parallelism(parallelism)
    if parallelism > 1:
        import concurrent.futures as cf
        with cf.ThreadPoolExecutor(max_workers=parallelism) as ex:
            rows = list(ex.map(one, uniq))
    else:
        rows = [one(s) for s in uniq]
    return SegmentModels(rows)
