"""MOJO export/import — h2o-genmodel–compatible scoring artifacts.

Writer side of the reference's MOJO v1.40 tree format so `h2o-genmodel`
jars can score models trained here (the SURVEY §7.1.11 parity
checkpoint), plus an independent reader/scorer used both for round-trip
tests and to import H2O-written MOJOs as first-class models.

Format contracts implemented (all reverse-engineered from the READER,
which defines the wire format):
- zip layout + model.ini [info]/[columns]/[domains] sections:
  hex/genmodel/ModelMojoReader.java:286-364 (parseModelInfo,
  parseModelDomains; domains line = "<col>: <n> <file>")
- compressed tree bytes (little-endian, ByteOrder.nativeOrder on x86):
  hex/genmodel/algos/tree/SharedTreeMojoModel.java:134-249 (scoreTree):
  node = [u8 nodeType][u16 colId][u8 naSplitDir][f32 splitVal]
  [left: u8/u16/u24/u32 size + subtree | f32 leaf][right: subtree | f32];
  nodeType bits: 0,1=left-size-field width-1, 4,5(=48)=left leaf,
  2,3=split kind (0=float), 6,7(=0xC0)=right leaf; colId 65535 = root
  leaf marker (writer: hex/tree/DTree.java:845-935 compress/size)
- aux tree info (pre-order, internal nodes only, 40 bytes each):
  SharedTreeMojoModel.java:709-766 AuxInfo — [i32 nid][i32 numNodes of
  left subtree][f32 wL][f32 wR][f32 predL][f32 predR][f32 seL][f32 seR]
  [i32 nidL][i32 nidR]
- per-algo keys: GbmMojoReader.java (distribution/init_f/link_function),
  DrfMojoReader.java (binomial_double_trees),
  SharedTreeMojoReader.java:13-60 (n_trees, n_trees_per_class,
  trees/tCC_GGG.bin naming, _genmodel_encoding for v>=1.40)
"""
from __future__ import annotations

import io
import json
import struct
import uuid as _uuid
import zipfile
from typing import Dict, List, Optional, Tuple

import numpy as np

NA_LEFT = 2    # NaSplitDir.NALeft
NA_RIGHT = 3   # NaSplitDir.NARight


# ------------------------------------------------------------------ writer

def _compress_tree(feat, thr, na_left, is_split, value) -> Tuple[bytes,
                                                                 bytes]:
    """Complete-binary-array tree → (tree_bytes, aux_bytes)."""
    ids = {}
    counter = [0]

    def assign(m):
        ids[m] = counter[0]
        counter[0] += 1
        if m < len(is_split) and is_split[m]:
            assign(2 * m + 1)
            assign(2 * m + 2)

    assign(0)

    def n_internal(m):
        if m >= len(is_split) or not is_split[m]:
            return 0
        return 1 + n_internal(2 * m + 1) + n_internal(2 * m + 2)

    def emit(m) -> bytes:
        if m >= len(is_split) or not is_split[m]:
            return struct.pack("<f", float(value[m]))
        left = emit(2 * m + 1)
        right = emit(2 * m + 2)
        left_leaf = not (2 * m + 1 < len(is_split) and is_split[2 * m + 1])
        right_leaf = not (2 * m + 2 < len(is_split) and is_split[2 * m + 2])
        node_type = 0
        if left_leaf:
            node_type |= 48
        else:
            lsz = len(left)
            slen = 0 if lsz < 256 else (1 if lsz < 65535 else
                                        (2 if lsz < (1 << 24) else 3))
            node_type |= slen
        if right_leaf:
            node_type |= 0xC0
        out = io.BytesIO()
        out.write(struct.pack("<BHB", node_type, int(feat[m]),
                              NA_LEFT if na_left[m] else NA_RIGHT))
        out.write(struct.pack("<f", float(thr[m])))
        if not left_leaf:
            lsz = len(left)
            if lsz < 256:
                out.write(struct.pack("<B", lsz))
            elif lsz < 65535:
                out.write(struct.pack("<H", lsz))
            elif lsz < (1 << 24):
                out.write(struct.pack("<I", lsz)[:3])
            else:
                out.write(struct.pack("<i", lsz))
        out.write(left)
        out.write(right)
        return out.getvalue()

    if not is_split[0]:
        # root is a leaf: special 65535 marker then the value
        return (struct.pack("<BHf", 0, 65535, float(value[0])), b"")
    body = emit(0)
    # aux records: strict pre-order over INTERNAL nodes, 40 bytes each
    aux = io.BytesIO()

    def emit_aux(m):
        if m >= len(is_split) or not is_split[m]:
            return
        lv = value[2 * m + 1] if not (
            2 * m + 1 < len(is_split) and is_split[2 * m + 1]) else 0.0
        rv = value[2 * m + 2] if not (
            2 * m + 2 < len(is_split) and is_split[2 * m + 2]) else 0.0
        aux.write(struct.pack(
            "<iiffffffii", ids[m], n_internal(2 * m + 1), 0.0, 0.0,
            float(lv), float(rv), 0.0, 0.0,
            ids[2 * m + 1], ids[2 * m + 2]))
        emit_aux(2 * m + 1)
        emit_aux(2 * m + 2)

    emit_aux(0)
    return body, aux.getvalue()


_LINK = {"bernoulli": "logit", "quasibinomial": "logit",
         "multinomial": "log", "poisson": "log", "gamma": "log",
         "tweedie": "log"}

_CATEGORY = {1: "Regression", 2: "Binomial"}


def export_mojo(model, path: str) -> str:
    """Write a model as an h2o-genmodel-readable MOJO zip. Trees carry
    the v1.40 wire format; GLM/KMeans/DeepLearning write their readers'
    kv formats (h2o3_tpu/genmodel.py)."""
    algo = model.algo
    if algo == "glm":
        from h2o3_tpu.genmodel import export_mojo_glm
        return export_mojo_glm(model, path)
    if algo == "kmeans":
        from h2o3_tpu.genmodel import export_mojo_kmeans
        return export_mojo_kmeans(model, path)
    if algo == "deeplearning":
        from h2o3_tpu.genmodel import export_mojo_deeplearning
        return export_mojo_deeplearning(model, path)
    if algo == "coxph":
        from h2o3_tpu.genmodel import export_mojo_coxph
        return export_mojo_coxph(model, path)
    if algo == "word2vec":
        from h2o3_tpu.genmodel import export_mojo_word2vec
        return export_mojo_word2vec(model, path)
    if algo == "glrm":
        from h2o3_tpu.genmodel import export_mojo_glrm
        return export_mojo_glrm(model, path)
    if algo == "pca":
        from h2o3_tpu.genmodel import export_mojo_pca
        return export_mojo_pca(model, path)
    if algo in ("isotonic", "isotonicregression"):
        from h2o3_tpu.genmodel import export_mojo_isotonic
        return export_mojo_isotonic(model, path)
    if algo == "psvm":
        from h2o3_tpu.genmodel import export_mojo_psvm
        return export_mojo_psvm(model, path)
    if algo == "targetencoder":
        from h2o3_tpu.genmodel import export_mojo_targetencoder
        return export_mojo_targetencoder(model, path)
    if algo in ("isolationforest", "isolation_forest"):
        from h2o3_tpu.genmodel import export_mojo_isofor
        return export_mojo_isofor(model, path)
    if algo == "gam":
        from h2o3_tpu.genmodel import export_mojo_gam
        return export_mojo_gam(model, path)
    if algo == "stackedensemble":
        from h2o3_tpu.genmodel import export_mojo_ensemble
        return export_mojo_ensemble(model, path)
    if algo not in ("gbm", "drf"):
        raise ValueError(f"MOJO export supports gbm/drf/glm/kmeans/"
                         f"deeplearning/coxph/word2vec/glrm/isofor/gam/"
                         f"stackedensemble (got '{algo}')")
    # ONE counted pytree fetch (telemetry.device_get) instead of five
    # raw jax.device_get calls: the bytes show up in the d2h counters
    # (they were invisible to the transfer budgets before) and the five
    # per-array syncs collapse into a single transfer
    from h2o3_tpu import telemetry
    feat, thr, nal, spl, val = telemetry.device_get(
        (model._feat, model._thr, model._na_left, model._is_split,
         model._value))
    feat = np.asarray(feat)
    thr = np.asarray(thr)
    nal = np.asarray(nal)
    spl = np.asarray(spl)
    val = np.array(val)
    K = model.nclasses if model.nclasses > 2 else 1
    T = model.ntrees_built
    f0 = np.asarray(model.f0, dtype=np.float64).reshape(-1) \
        if algo == "gbm" else None
    dist = model.dist_name if algo == "gbm" else None
    if algo == "gbm" and model.nclasses > 2:
        # MOJO carries ONE scalar init_f: fold the per-class prior into
        # every leaf of each class's first tree group
        for k in range(K):
            row = 0 * K + k
            leaf_mask = ~spl[row]
            val[row] = np.where(leaf_mask, val[row] + f0[k], val[row])
        init_f = 0.0
    elif algo == "gbm":
        init_f = float(f0[0])
    if algo == "drf" and model.nclasses == 2:
        # genmodel DRF binomial: preds[1] = avg(tree) = P(class 0)
        # (DrfMojoModel.java:46-48); our leaves store P(class 1)
        val = np.where(~spl, 1.0 - val, val)
    columns = list(model.feature_names) + (
        [model.response] if model.response else [])
    n_columns = len(columns)
    category = _CATEGORY.get(model.nclasses, "Multinomial")
    ini = ["[info]",
           "h2o_version = 3.46.0.1",
           "mojo_version = 1.40",
           "license = Apache License Version 2.0",
           f"algo = {algo}",
           "algorithm = %s" % ("Gradient Boosting Machine" if algo == "gbm"
                               else "Distributed Random Forest"),
           f"category = {category}",
           f"uuid = {int(_uuid.uuid4()) % (1 << 63)}",
           "supervised = true",
           f"n_features = {len(model.feature_names)}",
           f"n_classes = {max(model.nclasses, 1)}",
           f"n_columns = {n_columns}",
           "balance_classes = false",
           "default_threshold = 0.5",
           "prior_class_distrib = null",
           "model_class_distrib = null",
           "timestamp = 2026-01-01 00:00:00",
           "escape_domain_values = false",
           f"n_trees = {T}",
           f"n_trees_per_class = {K}",
           "_genmodel_encoding = AUTO",
           ]
    if algo == "gbm":
        ini += [f"distribution = {dist}",
                f"init_f = {init_f}",
                f"link_function = {_LINK.get(dist, 'identity')}"]
    else:
        ini += ["binomial_double_trees = false"]
    # domains
    dom_lines = ["", "[columns]"] + columns + ["", "[domains]"]
    dom_files: List[Tuple[str, List[str]]] = []
    di = 0
    for ci, name in enumerate(columns):
        dom = None
        if name == model.response and model.response_domain:
            dom = list(model.response_domain)
        elif name in model.cat_domains:
            dom = list(model.cat_domains[name])
        if dom:
            fn = f"d{di:03d}.txt"
            dom_lines.append(f"{ci}: {len(dom)} {fn}")
            dom_files.append((fn, dom))
            di += 1
    ini_text = "\n".join(ini + dom_lines) + "\n"
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("model.ini", ini_text)
        for fn, dom in dom_files:
            zf.writestr(f"domains/{fn}", "\n".join(str(d) for d in dom)
                        + "\n")
        for t in range(T):
            for k in range(K):
                row = t * K + k
                tree, aux = _compress_tree(feat[row], thr[row], nal[row],
                                           spl[row], val[row])
                zf.writestr(f"trees/t{k:02d}_{t:03d}.bin", tree)
                zf.writestr(f"trees/t{k:02d}_{t:03d}_aux.bin", aux)
    return path


# ------------------------------------------------------------------ reader

def _score_tree(tree: bytes, row: np.ndarray, domains) -> float:
    """Python port of SharedTreeMojoModel.scoreTree (the independent
    verification path for the writer above)."""
    pos = 0

    def u8():
        nonlocal pos
        v = tree[pos]; pos += 1
        return v

    def u16():
        nonlocal pos
        v = struct.unpack_from("<H", tree, pos)[0]; pos += 2
        return v

    def f32():
        nonlocal pos
        v = struct.unpack_from("<f", tree, pos)[0]; pos += 4
        return v

    while True:
        node_type = u8()
        col_id = u16()
        if col_id == 65535:
            return f32()
        na_dir = u8()
        na_vs_rest = na_dir == 1
        leftward = na_dir in (2, 4)
        lmask = node_type & 51
        equal = node_type & 12
        split_val = None
        bs_offset = bs_nbits = bs_bytes = None
        if not na_vs_rest:
            if equal == 0:
                split_val = f32()
            elif equal == 8:  # bitset fill2: u16 offset? (GenmodelBitSet)
                bs_offset = 0
                nb = u16()
                bs_bytes = tree[pos:pos + nb]
                pos += nb
            else:             # fill3: i32 offset + i32 nbits
                bs_offset = struct.unpack_from("<i", tree, pos)[0]; pos += 4
                nbits = struct.unpack_from("<i", tree, pos)[0]; pos += 4
                nb = (nbits + 7) // 8
                bs_bytes = tree[pos:pos + nb]
                pos += nb
        d = row[col_id]
        dom = domains[col_id] if domains else None
        is_na = (np.isnan(d) or
                 (dom is not None and int(d) >= len(dom)))
        if equal != 0 and not is_na and bs_bytes is not None:
            idx = int(d) - (bs_offset or 0)
            in_range = 0 <= idx < len(bs_bytes) * 8
            if not in_range:
                is_na = True
        if is_na:
            go_right = not leftward
        elif na_vs_rest:
            go_right = False
        elif equal == 0:
            go_right = d >= split_val
        else:
            idx = int(d) - (bs_offset or 0)
            go_right = bool(bs_bytes[idx >> 3] & (1 << (idx & 7)))
        if go_right:
            # NB: read the length FIRST (the reader functions advance
            # pos); `pos += u8()` would add to the pre-call pos
            if lmask == 0:
                sz = u8()
                pos += sz
            elif lmask == 1:
                sz = u16()
                pos += sz
            elif lmask == 2:
                v = tree[pos] | (tree[pos + 1] << 8) | (tree[pos + 2] << 16)
                pos += 3 + v
            elif lmask == 3:
                v = struct.unpack_from("<i", tree, pos)[0]
                pos += 4 + v
            elif lmask == 48:
                pos += 4
            lmask = (node_type & 0xC0) >> 2
        else:
            if lmask <= 3:
                pos += lmask + 1
        if lmask & 16:
            return f32()


class MojoModel:
    """Parsed MOJO: scores rows exactly like h2o-genmodel."""

    def __init__(self, info: Dict, columns: List[str], domains,
                 trees: Dict[Tuple[int, int], bytes]):
        self.info = info
        self.columns = columns
        self.domains = domains
        self.trees = trees
        self.algo = info.get("algo")
        self.n_classes = int(info.get("n_classes", 1))
        self.n_trees = int(info.get("n_trees", 0))
        self.tpc = int(info.get("n_trees_per_class",
                                1 if self.n_classes <= 2 else
                                self.n_classes))

    def score(self, row: np.ndarray) -> np.ndarray:
        """row: feature values (codes for enums, NaN for NA). Returns
        probabilities [K] or [1] margin-space prediction."""
        sums = np.zeros(max(self.tpc, 1))
        for t in range(self.n_trees):
            for k in range(self.tpc):
                b = self.trees.get((k, t))
                if b is not None:
                    sums[k] += _score_tree(b, row, self.domains)
        if self.algo == "gbm":
            init_f = float(self.info.get("init_f", 0.0))
            dist = self.info.get("distribution", "gaussian")
            if dist in ("bernoulli", "quasibinomial"):
                p1 = 1.0 / (1.0 + np.exp(-(sums[0] + init_f)))
                return np.array([1.0 - p1, p1])
            if dist == "multinomial":
                e = np.exp(sums - sums.max())
                return e / e.sum()
            return np.array([sums[0] + init_f])
        if self.algo == "drf":
            if self.n_classes == 2:
                p0 = sums[0] / max(self.n_trees, 1)
                return np.array([p0, 1.0 - p0])
            if self.n_classes > 2:
                s = sums.sum()
                return sums / s if s > 0 else sums
            return np.array([sums[0] / max(self.n_trees, 1)])
        if self.algo == "isofor":
            # leaf values carry node depth: preds[0] = mean path length
            # over trees (hex/genmodel/algos/isofor scoring contract;
            # callers normalize with min/max_path_length from the ini)
            return np.array([sums[0] / max(self.n_trees, 1)])
        raise ValueError(f"unsupported mojo algo '{self.algo}'")


def read_mojo(path: str) -> MojoModel:
    with zipfile.ZipFile(path) as zf:
        names = set(zf.namelist())
        ini = zf.read("model.ini").decode().splitlines()
        info: Dict[str, str] = {}
        columns: List[str] = []
        dom_map: Dict[int, str] = {}
        section = 0
        for line in ini:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if line == "[info]":
                section = 1
            elif line == "[columns]":
                section = 2
            elif line == "[domains]":
                section = 3
            elif section == 1:
                k, _, v = line.partition("=")
                info[k.strip()] = v.strip()
            elif section == 2:
                columns.append(line)
            elif section == 3:
                ci, _, rest = line.partition(":")
                dom_map[int(ci)] = rest.strip()
        domains: List[Optional[List[str]]] = [None] * len(columns)
        for ci, spec in dom_map.items():
            n, _, fn = spec.partition(" ")
            lines = zf.read(f"domains/{fn.strip()}").decode().splitlines()
            domains[ci] = lines[: int(n)]
        trees = {}
        T = int(info.get("n_trees", 0))
        K = int(info.get("n_trees_per_class", 1))
        for t in range(T):
            for k in range(K):
                nm = f"trees/t{k:02d}_{t:03d}.bin"
                if nm in names:
                    trees[(k, t)] = zf.read(nm)
    algo = info.get("algo", "")
    if algo in ("glm", "kmeans", "deeplearning", "coxph", "pca",
                "isotonic"):
        from h2o3_tpu.genmodel import (CoxPHMojoScorer,
                                       DeepLearningMojoScorer,
                                       GlmMojoScorer,
                                       IsotonicMojoScorer,
                                       KMeansMojoScorer, PcaMojoScorer)
        resp = columns[-1] if info.get("supervised") == "true" else None
        scorer_cls = {"glm": GlmMojoScorer, "kmeans": KMeansMojoScorer,
                      "deeplearning": DeepLearningMojoScorer,
                      "coxph": CoxPHMojoScorer, "pca": PcaMojoScorer,
                      "isotonic": IsotonicMojoScorer}[algo]
        s = scorer_cls(info, columns, domains, resp)
        s.info = info
        return s
    if algo in ("word2vec", "glrm", "psvm", "targetencoder"):
        from h2o3_tpu.genmodel import (GlrmMojoScorer, PsvmMojoScorer,
                                       TargetEncoderMojoScorer,
                                       Word2VecMojoScorer)
        with zipfile.ZipFile(path) as zf2:
            blobs = {n: zf2.read(n) for n in zf2.namelist()
                     if n.endswith((".bin", ".txt"))}
        cls2 = {"word2vec": Word2VecMojoScorer, "glrm": GlrmMojoScorer,
                "psvm": PsvmMojoScorer,
                "targetencoder": TargetEncoderMojoScorer}[algo]
        s = cls2(info, columns, domains, None, blobs=blobs)
        s.info = info
        return s
    return MojoModel(info, columns, domains, trees)


def import_mojo(path: str):
    """Load a MOJO as a first-class scoring model over Frames
    (hex/generic MOJO import analog)."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.frame.vec import T_ENUM, Vec

    mm = read_mojo(path)
    n_feat = int(mm.info.get("n_features", len(mm.columns) - 1))
    feat_names = mm.columns[:n_feat]

    class _MojoFrameModel:
        """Duck-typed Model over MOJO bytes — carries the attributes the
        REST schema layer and keyed store dereference (training_metrics,
        output, scoring_history, run_time, params)."""
        algo = f"mojo_{mm.algo}"
        key = f"mojo_{abs(hash(path)) & 0xffffff:x}"
        nclasses = mm.n_classes
        feature_names = feat_names
        feature_is_cat = [mm.domains[j] is not None
                          for j in range(n_feat)]
        cat_domains = {feat_names[j]: tuple(mm.domains[j])
                       for j in range(n_feat) if mm.domains[j]}
        response = (mm.columns[n_feat] if n_feat < len(mm.columns)
                    else None)
        response_domain = (tuple(mm.domains[n_feat])
                           if n_feat < len(mm.columns)
                           and mm.domains[n_feat] else None)
        mojo = mm

        def __init__(self):
            self.params = {"path": path}
            self.output = {"mojo_source": path,
                           "algo": mm.algo}
            self.training_metrics = None
            self.validation_metrics = None
            self.cross_validation_metrics = None
            self.scoring_history = []
            self.run_time = 0.0

        def model_performance(self, frame=None):
            return self.training_metrics

        def _save_arrays(self):
            raise NotImplementedError(
                "an imported MOJO re-exports as-is: copy the original "
                "zip instead of save_model")

        def predict(self, frame: Frame) -> Frame:
            rows = frame.nrow
            X = np.full((rows, n_feat), np.nan)
            for j, fn in enumerate(feat_names):
                if fn not in frame:
                    continue
                v = frame.vec(fn)
                col = np.asarray(v.to_numpy(), dtype=np.float64)
                if v.is_categorical and mm.domains[j]:
                    remap = {lvl: i for i, lvl in
                             enumerate(mm.domains[j])}
                    src = v.domain or ()
                    lut = np.asarray([remap.get(l, np.nan) for l in src]
                                     + [np.nan])
                    col = lut[np.where(np.isnan(col), len(src),
                                       col).astype(int)]
                X[:, j] = col
            out = np.stack([mm.score(X[i]) for i in range(rows)])
            if mm.n_classes >= 2:
                lbl = np.argmax(out, axis=1).astype(np.int32)
                dom = self.response_domain or tuple(
                    str(i) for i in range(mm.n_classes))
                names = ["predict"] + [f"p{d}" for d in dom]
                vecs = [Vec.from_numpy(lbl, vtype=T_ENUM, domain=dom)]
                vecs += [Vec.from_numpy(out[:, k].astype(np.float32))
                         for k in range(mm.n_classes)]
                return Frame(names, vecs)
            return Frame(["predict"],
                         [Vec.from_numpy(out[:, 0].astype(np.float32))])

    return _MojoFrameModel()
