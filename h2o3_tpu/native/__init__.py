"""ctypes binding + on-demand build of the native CSV tokenizer.

The shared object compiles once per machine into this package directory
(g++ -O3; ~1s). Import degrades gracefully: `lib()` returns None when no
toolchain is available and callers keep the Python path — the same
pluggable seam as the reference's ParserProvider SPI."""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "fast_csv.cpp")
_SO = os.path.join(_DIR, "libfastcsv.so")
_LOCK = threading.Lock()
_LIB = None
_TRIED = False


def _build() -> bool:
    try:
        r = subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC",
             "-o", _SO + ".tmp", _SRC],
            capture_output=True, timeout=120)
        if r.returncode != 0:
            return False
        os.replace(_SO + ".tmp", _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def lib():
    """The loaded native library, or None (Python fallback)."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            if not _build():
                return None
        try:
            L = ctypes.CDLL(_SO)
        except OSError:
            return None
        L.csv_shape.restype = ctypes.c_longlong
        L.csv_shape.argtypes = [ctypes.c_char_p, ctypes.c_longlong,
                                ctypes.c_char,
                                ctypes.POINTER(ctypes.c_longlong)]
        L.csv_parse.restype = ctypes.c_longlong
        L.csv_parse.argtypes = [ctypes.c_char_p, ctypes.c_longlong,
                                ctypes.c_char, ctypes.c_longlong,
                                ctypes.c_longlong,
                                ctypes.POINTER(ctypes.c_longlong),
                                ctypes.POINTER(ctypes.c_int),
                                ctypes.POINTER(ctypes.c_double),
                                ctypes.POINTER(ctypes.c_ubyte)]
        try:
            # absent only in a stale .so whose mtime beat the source (the
            # mtime check above rebuilds the normal stale case); callers
            # probe with hasattr and fall back to the numpy encoder
            L.csv_enum_encode.restype = ctypes.c_longlong
            L.csv_enum_encode.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_longlong),
                ctypes.POINTER(ctypes.c_int),
                ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_longlong),
                ctypes.c_longlong]
        except AttributeError:
            pass
        _LIB = L
        return _LIB


def parse_bytes(data: bytes, sep: str):
    """Tokenise a CSV byte buffer natively.

    Returns (starts[r,c], lens[r,c], vals[r,c], ok[r,c]) numpy arrays or
    None when the native path declines (no toolchain, quotes present,
    ragged rows)."""
    import numpy as np
    L = lib()
    if L is None or b'"' in data:
        return None
    ncols = ctypes.c_longlong(0)
    rows = L.csv_shape(data, len(data), sep.encode()[0:1],
                       ctypes.byref(ncols))
    if rows <= 0 or ncols.value <= 0:
        return None
    r, c = int(rows), int(ncols.value)
    starts = np.empty(r * c, np.int64)
    lens = np.empty(r * c, np.int32)
    vals = np.empty(r * c, np.float64)
    ok = np.empty(r * c, np.uint8)
    got = L.csv_parse(
        data, len(data), sep.encode()[0:1], r, c,
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ok.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)))
    if got != r:
        return None
    return (starts.reshape(r, c), lens.reshape(r, c),
            vals.reshape(r, c), ok.reshape(r, c))


def enum_encode(data: bytes, starts, lens, max_card: int):
    """Dictionary-encode one column's tokens natively.

    ``starts``/``lens`` are the column's per-cell offsets from
    ``parse_bytes``. Returns ``(codes int32, uniq_rows int64)`` where
    ``uniq_rows[k]`` is the row whose cell first used dictionary id
    ``k`` — or None when the native path declines (no toolchain, old
    .so, cardinality above ``max_card``)."""
    import numpy as np
    L = lib()
    if L is None or not hasattr(L, "csv_enum_encode"):
        return None
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    n = len(starts)
    # cardinality can never exceed n cells, so cap the dictionary buffer
    # by n — max_card is ~1M (8 MB) and 16 workers run concurrently
    max_card = min(max_card, n)
    codes = np.empty(n, np.int32)
    uniq = np.empty(max(max_card, 1), np.int64)
    card = L.csv_enum_encode(
        data, starts.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int)), n,
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        uniq.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        max_card)
    if card < 0:
        return None
    return codes, uniq[:card]
