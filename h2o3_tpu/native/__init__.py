"""ctypes binding + on-demand build of the native CSV tokenizer.

The shared object compiles once per machine into this package directory
(g++ -O3; ~1s). Import degrades gracefully: `lib()` returns None when no
toolchain is available and callers keep the Python path — the same
pluggable seam as the reference's ParserProvider SPI.

Zero-copy contract (ISSUE 14): every entry point takes any buffer numpy
can view — bytes, memoryview, an mmap slice — and hands the C scans a
raw pointer into it (``c_void_p``), so a byte-range worker tokenizes the
file's page cache directly with no per-range ``read()`` copy. The GIL is
released for the whole C call (ctypes), so a thread pool scales the scan
across cores.

``parse_bytes`` returns COLUMN-major cell arrays carved out of a
thread-local scratch arena that is REUSED across calls: callers must
finish (copy out or consume) every returned array before the same
thread calls ``parse_bytes`` again — ``encode_chunk_native`` does
exactly that within one call. Declines come back as a *reason string*
(``ragged_rows`` / ``unterminated_quote`` / ``trailing_after_quote`` /
``no_toolchain``), and the parse seam falls back per-range, not
per-import, counting each reason in ``h2o3_ingest_fallback_total``."""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
import warnings

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "fast_csv.cpp")
_SO = os.path.join(_DIR, "libfastcsv.so")
_HASH = _SO + ".srchash"  # sha256 of the source the .so was built from
_COMPILER = "g++"
_LOCK = threading.Lock()
_LIB = None
_TRIED = False

# last failed build's diagnostic (compiler name + stderr tail); callers
# that degrade to the Python path can surface WHY the toolchain bailed
BUILD_ERROR = None

# csv_parse reason codes -> the fallback-counter label (parse.py)
DECLINE_REASONS = {1: "ragged_rows", 2: "unterminated_quote",
                   3: "trailing_after_quote"}


def _src_hash() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _build() -> bool:
    """Compile the .so and stamp the source hash it was built from. A
    failed compile records a clear error NAMING the compiler (the silent
    `return False` used to leave "why is ingest slow" undiagnosable)."""
    global BUILD_ERROR
    cmd = [_COMPILER, "-O3", "-march=native", "-shared", "-fPIC",
           "-o", _SO + ".tmp", _SRC]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        BUILD_ERROR = (f"native CSV build failed: compiler '{_COMPILER}' "
                       f"could not run ({e}); falling back to the Python "
                       f"tokenizer")
        warnings.warn(BUILD_ERROR, RuntimeWarning, stacklevel=2)
        return False
    if r.returncode != 0:
        tail = (r.stderr or b"").decode("utf-8", "replace").strip()[-800:]
        BUILD_ERROR = (f"native CSV build failed: '{_COMPILER}' exited "
                       f"{r.returncode} compiling {_SRC}:\n{tail}")
        warnings.warn(BUILD_ERROR, RuntimeWarning, stacklevel=2)
        return False
    os.replace(_SO + ".tmp", _SO)
    try:
        with open(_HASH + ".tmp", "w") as f:
            f.write(_src_hash())
        os.replace(_HASH + ".tmp", _HASH)
    except OSError:
        pass  # hash sidecar is advisory; mtime still catches most edits
    BUILD_ERROR = None
    return True


def _stale() -> bool:
    """Rebuild-if-stale guard: CONTENT hash of fast_csv.cpp against the
    sidecar stamped at build time. mtime alone served stale symbols when
    a checkout/copy stamped the .so newer than an edited source (git
    checkout, rsync, build caches) — with new entry points landing per
    PR that silently pinned callers to an old ABI."""
    if not os.path.exists(_SO):
        return True
    try:
        with open(_HASH) as f:
            built_from = f.read().strip()
    except OSError:
        # pre-hash .so (or lost sidecar): fall back to the mtime check
        # once; the rebuild it triggers writes the sidecar
        try:
            return os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        except OSError:
            return True
    return built_from != _src_hash()


def lib():
    """The loaded native library, or None (Python fallback)."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if _stale():
            if not _build():
                return None
        for attempt in range(2):
            try:
                L = ctypes.CDLL(_SO)
            except OSError:
                return None
            LL, VP = ctypes.c_longlong, ctypes.c_void_p
            pLL = ctypes.POINTER(ctypes.c_longlong)
            pI = ctypes.POINTER(ctypes.c_int)
            pD = ctypes.POINTER(ctypes.c_double)
            pU8 = ctypes.POINTER(ctypes.c_ubyte)
            try:
                L.csv_parse.restype = LL
                L.csv_parse.argtypes = [VP, LL, ctypes.c_char,
                                        ctypes.c_char, LL, LL, VP, pLL,
                                        pI, pD, pU8, pLL, pLL]
                L.csv_chunk_bounds.restype = LL
                L.csv_chunk_bounds.argtypes = [VP, LL, ctypes.c_char,
                                               ctypes.c_char, pLL, LL, pLL]
                L.csv_enum_encode.restype = LL
                L.csv_enum_encode.argtypes = [VP, pLL, pI, LL, pI, pLL, LL]
                L.csv_gather_tokens.restype = None
                L.csv_gather_tokens.argtypes = [VP, pLL, pI, LL, LL, VP]
                L.csv_match_any.restype = None
                L.csv_match_any.argtypes = [VP, pLL, pI, LL,
                                            VP, pLL, pI, LL, pU8]
                L.csv_numeric_stats.restype = None
                L.csv_numeric_stats.argtypes = [pD, LL, pLL, LL, LL, LL,
                                                pD, pD, pU8]
                L.csv_count_rows.restype = LL
                L.csv_count_rows.argtypes = [VP, LL, ctypes.c_char,
                                             ctypes.c_char]
                L.csv_enum_encode_full.restype = LL
                L.csv_enum_encode_full.argtypes = [
                    VP, pLL, pI, LL, VP, VP, pLL, pI, LL, LL,
                    ctypes.c_int, pI, pLL, pU8]
            except AttributeError:
                # a stale .so that slipped BOTH the hash sidecar and the
                # mtime check: missing symbols mean the binary is from
                # another era — rebuild once, then give up (the ABI
                # check is the SYMBOL SET; a same-symbol signature
                # change must ride a new symbol or this check is blind)
                if attempt == 0 and _build():
                    continue
                return None
            _LIB = L
            return _LIB
        return None


def _as_u8(data):
    """Zero-copy uint8 view of any buffer (bytes / memoryview / mmap
    slice). The returned array BORROWS the caller's buffer — keep the
    source alive across the native call."""
    import numpy as np
    return np.frombuffer(data, dtype=np.uint8)


# thread-local scratch arena for the csv_parse output arrays, grown to
# the high-water cell count and reused across calls (the per-range
# allocation was measurable at 24-way fan-out). Each worker thread owns
# its own arena; parse_bytes hands out views into it.
_TLS = threading.local()


def _scratch(ncells: int):
    import numpy as np
    bufs = getattr(_TLS, "bufs", None)
    if bufs is None or bufs[0].size < ncells:
        n = max(ncells, 1)
        bufs = (np.empty(n, np.int64), np.empty(n, np.int32),
                np.empty(n, np.float64), np.empty(n, np.uint8))
        _TLS.bufs = bufs
    return bufs


def _infer_ncols(data, sep: str, quote: str) -> int:
    """Column count from the first row (only for callers without a
    ParseSetup — the parse pipeline passes its setup's count)."""
    import csv
    import io
    buf = _as_u8(data)
    head = bytes(buf[:buf.size if buf.size < 65536 else 65536])
    txt = head.decode("utf-8", errors="replace")
    for row in csv.reader(io.StringIO(txt), delimiter=sep,
                          quotechar=quote or '"'):
        if row:
            return len(row)
    return 0


def parse_bytes(data, sep: str, quote: str = '"', ncols=None,
                want_offsets=None):
    """Tokenise a CSV buffer natively (RFC-4180 quotes included) in ONE
    quote-aware C pass — rows are bounded by the buffer's newline count
    (a vectorized popcount, not a byte-walk), and the scan itself
    validates every row against ``ncols`` (the ParseSetup column count;
    inferred from the first row when absent).

    Returns ``(starts, lens, vals, ok, esc)`` numpy arrays of shape
    ``[ncols, nrows]`` (column-major: one contiguous slice per column),
    or a decline-reason string when the native path cannot tokenize this
    range (``no_toolchain``, ``ragged_rows``, ``unterminated_quote``,
    ``trailing_after_quote``, ``empty_range``). ``esc`` marks cells
    whose raw bytes still carry RFC-4180 ``""`` escapes (unescape before
    using the token's text). ``want_offsets`` (uint8 per column, None =
    all) suppresses the starts/lens writes for columns whose offsets the
    caller will never read back (float64 columns: their value IS
    vals[idx]) — the skipped arena regions stay unfaulted, roughly
    halving the scan's write traffic on mostly-numeric files; the
    starts/lens slices of suppressed columns hold GARBAGE. All five
    arrays are views into a reused thread-local arena — consume them
    before the next call on this thread."""
    import numpy as np
    L = lib()
    if L is None:
        return "no_toolchain"
    if ncols is None:
        ncols = _infer_ncols(data, sep, quote)
    if ncols <= 0:
        return "empty_range"
    buf = _as_u8(data)
    ptr, n = buf.ctypes.data, buf.size
    sep_b, quote_b = sep.encode()[0:1], (quote or '"').encode()[0:1]
    # upper bound: quoted embedded newlines only ever REDUCE the true
    # row count below newlines+1, so the arena never overflows
    cap = int(np.count_nonzero(buf == 0x0A)) + 1
    c = int(ncols)
    want_ptr = 0
    if want_offsets is not None:
        want_offsets = np.ascontiguousarray(want_offsets, dtype=np.uint8)
        want_ptr = want_offsets.ctypes.data
    starts, lens, vals, ok = _scratch(cap * c)
    starts, lens = starts[:cap * c], lens[:cap * c]
    vals, ok = vals[:cap * c], ok[:cap * c]
    reason = ctypes.c_longlong(0)
    esc_count = ctypes.c_longlong(0)
    got = L.csv_parse(
        ptr, n, sep_b, quote_b, cap, c, want_ptr,
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ok.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.byref(reason), ctypes.byref(esc_count))
    if got < 0:
        return DECLINE_REASONS.get(int(reason.value), "ragged_rows")
    if got == 0:
        return "empty_range"
    r = int(got)
    # column-major with cap as the stride: each column's filled prefix
    # [j, :r] is contiguous. The esc mask only materializes when the
    # scan actually saw "" escapes (esc_count) — the common quote-free
    # case skips three full passes over the ok array.
    if int(esc_count.value):
        esc_full = ok & 0x80
        np.bitwise_and(ok, 0x7F, out=ok)
        esc = esc_full.astype(bool).reshape(c, cap)[:, :r]
    else:
        esc = None
    return (starts.reshape(c, cap)[:, :r], lens.reshape(c, cap)[:, :r],
            vals.reshape(c, cap)[:, :r], ok.reshape(c, cap)[:, :r], esc)


def chunk_bounds(data, sep: str, quote: str, targets):
    """Quote-safe byte-range boundaries: for each ascending byte target,
    the offset just past the first newline at/after it that sits OUTSIDE
    any quoted field (one native state-machine pass over the buffer).
    Returns an int64 array (possibly shorter than ``targets`` when the
    tail targets fall past the last safe newline), or None without the
    toolchain."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    buf = _as_u8(data)
    t = np.ascontiguousarray(targets, dtype=np.int64)
    out = np.empty(max(len(t), 1), np.int64)
    got = L.csv_chunk_bounds(
        buf.ctypes.data, buf.size, sep.encode()[0:1],
        (quote or '"').encode()[0:1],
        t.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)), len(t),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)))
    return out[:max(int(got), 0)]


def enum_encode(data, starts, lens, max_card: int):
    """Dictionary-encode one column's tokens natively.

    ``starts``/``lens`` are the column's per-cell offsets from
    ``parse_bytes``. Returns ``(codes int32, uniq_rows int64)`` where
    ``uniq_rows[k]`` is the row whose cell first used dictionary id
    ``k`` — or None when the native path declines (no toolchain,
    cardinality above ``max_card``)."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    buf = _as_u8(data)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    n = len(starts)
    # cardinality can never exceed n cells, so cap the dictionary buffer
    # by n — max_card is ~1M (8 MB) and dozens of workers run at once
    max_card = min(max_card, n)
    codes = np.empty(n, np.int32)
    uniq = np.empty(max(max_card, 1), np.int64)
    card = L.csv_enum_encode(
        buf.ctypes.data,
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int)), n,
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        uniq.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        max_card)
    if card < 0:
        return None
    return codes, uniq[:card]


# ---- nogil encode plane (ISSUE 16) ----------------------------------

def _ptr(a, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _gather_arena(nbytes: int):
    """Thread-local gather arena (token S-arrays, match flags): reused
    across calls like the parse scratch, so a worker's per-column
    gathers stop round-tripping the allocator. Same contract: consume
    the returned view before the next gather on this thread."""
    import numpy as np
    buf = getattr(_TLS, "gather", None)
    if buf is None or buf.size < nbytes:
        buf = np.empty(max(nbytes, 1 << 16), np.uint8)
        _TLS.gather = buf
    return buf


def arena_bytes() -> int:
    """This thread's total scratch-arena footprint (parse + gather), for
    the profiler's per-worker memory attribution."""
    total = 0
    bufs = getattr(_TLS, "bufs", None)
    if bufs is not None:
        total += sum(b.nbytes for b in bufs)
    g = getattr(_TLS, "gather", None)
    if g is not None:
        total += g.nbytes
    return total


def _pack_patterns(pats):
    """Concatenate byte patterns (NA strings) into (buf, offs, lens)."""
    import numpy as np
    bs = [p if isinstance(p, bytes) else str(p).encode("utf-8")
          for p in pats]
    offs = np.zeros(max(len(bs), 1), np.int64)
    lens = np.zeros(max(len(bs), 1), np.int32)
    o = 0
    for k, b in enumerate(bs):
        offs[k] = o
        lens[k] = len(b)
        o += len(b)
    return b"".join(bs) or b"\0", offs, lens


def gather_tokens(data, starts, lens, width: int = None):
    """Fixed-width token gather into an ``S{width}`` array — the native
    spelling of the numpy slab loop (_tokens_sarr). Returns a view into
    the thread-local gather arena (consume before the next call on this
    thread), or None without the toolchain."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    buf = _as_u8(data)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    n = len(starts)
    if n == 0:
        return np.empty(0, dtype="S1")
    if width is None:
        width = max(int(lens.max()), 1)
    out = _gather_arena(n * width)[:n * width]
    L.csv_gather_tokens(buf.ctypes.data, _ptr(starts, ctypes.c_longlong),
                        _ptr(lens, ctypes.c_int), n, width,
                        out.ctypes.data)
    return out.view(f"S{width}")


def match_any(data, starts, lens, patterns):
    """Per-cell membership flags (bool array): cell bytes equal to any
    pattern — the NA-string test, without materializing tokens. None
    without the toolchain."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    buf = _as_u8(data)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    n = len(starts)
    out = np.zeros(n, np.uint8)
    if n and patterns:
        pat_buf, offs, plens = _pack_patterns(patterns)
        pat = np.frombuffer(pat_buf, np.uint8)
        L.csv_match_any(buf.ctypes.data, _ptr(starts, ctypes.c_longlong),
                        _ptr(lens, ctypes.c_int), n,
                        pat.ctypes.data, _ptr(offs, ctypes.c_longlong),
                        _ptr(plens, ctypes.c_int), len(patterns),
                        _ptr(out, ctypes.c_ubyte))
    return out.view(bool)


def numeric_stats(vals, col_stride: int, col_idx, r0: int, nrows: int):
    """Detach selected numeric columns from the column-major parse arena
    and reduce them in one nogil pass. Returns ``(block, fmax, allfin)``
    — an owned ``[k, nrows]`` float64 block, per-column finite |max|
    (-inf when none), and per-column all-finite flags — or None without
    the toolchain."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    col_idx = np.ascontiguousarray(col_idx, dtype=np.int64)
    k = len(col_idx)
    block = np.empty((k, nrows), np.float64)
    fmax = np.empty(k, np.float64)
    allfin = np.empty(k, np.uint8)
    L.csv_numeric_stats(_ptr(vals, ctypes.c_double), col_stride,
                        _ptr(col_idx, ctypes.c_longlong), k, r0, nrows,
                        _ptr(block, ctypes.c_double),
                        _ptr(fmax, ctypes.c_double),
                        _ptr(allfin, ctypes.c_ubyte))
    return block, fmax, allfin.view(bool)


def count_rows(data, sep: str, quote: str = '"'):
    """Quote-aware row count of a buffer (csv_parse's row accounting,
    no per-cell work) — the multi-host range planner's cheap pass.
    Returns the count, or None (toolchain missing / open quote)."""
    L = lib()
    if L is None:
        return None
    buf = _as_u8(data)
    got = L.csv_count_rows(buf.ctypes.data, buf.size, sep.encode()[0:1],
                           (quote or '"').encode()[0:1])
    return int(got) if got >= 0 else None


def enum_encode_full(data, starts, lens, nas, max_card: int,
                     na_code: int, esc=None):
    """Full native enum encode: dictionary build, ""-unescape, NA map,
    sorted-domain dedupe and final code remap in one released-GIL call.
    Returns ``(codes int32, dom_rows int64, dom_esc bool)`` where entry
    ``k`` of ``dom_rows``/``dom_esc`` locates a representative cell for
    the k-th SORTED domain label (the caller decodes card labels — the
    only per-label Python left). None when the native path declines
    (no toolchain, cardinality above ``max_card``, or a non-UTF-8 label
    whose sort order native bytes cannot reproduce)."""
    import numpy as np
    L = lib()
    if L is None:
        return None
    buf = _as_u8(data)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    n = len(starts)
    nas = list(nas or ())
    max_card = min(max_card, max(n, 1))
    codes = np.empty(n, np.int32)
    dom_rows = np.empty(max_card + 1, np.int64)
    dom_esc = np.empty(max_card + 1, np.uint8)
    esc_ptr = 0
    if esc is not None:
        esc = np.ascontiguousarray(esc, dtype=np.uint8)
        esc_ptr = esc.ctypes.data
    pat_buf, offs, plens = _pack_patterns(nas)
    pat = np.frombuffer(pat_buf, np.uint8)
    card = L.csv_enum_encode_full(
        buf.ctypes.data, _ptr(starts, ctypes.c_longlong),
        _ptr(lens, ctypes.c_int), n, esc_ptr,
        pat.ctypes.data, _ptr(offs, ctypes.c_longlong),
        _ptr(plens, ctypes.c_int), len(nas),
        max_card, na_code,
        _ptr(codes, ctypes.c_int), _ptr(dom_rows, ctypes.c_longlong),
        _ptr(dom_esc, ctypes.c_ubyte))
    if card < 0:
        return None
    return codes, dom_rows[:card], dom_esc[:card].view(bool)
