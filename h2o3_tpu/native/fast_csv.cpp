// Native CSV tokenizer — the hot byte-scanning loop of ingest.
//
// Reference role: water/parser/CsvParser.java streams raw-byte chunks
// into NewChunks inside MultiFileParseTask (ParseDataset.java:623); the
// tokenizer is the CPU-bound inner loop of every import. Here the same
// loop is C++ behind a C ABI (ctypes binding in h2o3_tpu/native/
// __init__.py), emitting per-cell byte offsets plus eagerly-parsed
// doubles; Python only touches the (rare) non-numeric cells.
//
// Scope (ISSUE 14 widened it): separator-delimited rows, '\n'/'\r\n'
// terminators, RFC-4180 quoted fields (embedded separators, embedded
// newlines, "" escapes), numeric tokens of any length (in-place strtod
// — no copy, no 63-char cap), and unicode-whitespace trimming that
// byte-matches Python's str.strip() on UTF-8 input. The caller scans a
// borrowed buffer (an mmap view — zero copy), and cell values land
// COLUMN-major (idx = col*rows + row) so each finished column is one
// contiguous slice.
//
// Equivalence contract: a cell the Python tokenizer (csv.reader +
// str.strip + float) would produce must come out bit-identical here —
// the range-scoped fallback in ingest/parse.py mixes tokenizers across
// byte ranges of the SAME column, so any divergence corrupts frames
// silently. Numeric acceptance therefore mirrors Python float(): a
// strict [0-9+-.eE] / inf / nan character filter runs before strtod so
// C-isms Python rejects (hex floats "0x1A", "NAN(tag)") stay
// non-numeric, and PEP-515 digit-group underscores ("1_000") parse via
// their stripped form exactly as float() would. Known residual
// divergence (documented, exotic): Python float() also accepts
// non-ASCII unicode digits; those parse as NA here.
//
// Declines are *reasons*, not booleans: ragged rows, a quote left open
// at the end of the range, or non-whitespace trailing a closing quote
// return a reason code and ONLY that byte range re-parses through the
// Python tokenizer (parse.py fallback seam).
#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <vector>

namespace {

inline unsigned long long fnv1a(const char* p, int n) {
    unsigned long long h = 1469598103934665603ULL;
    for (int i = 0; i < n; ++i) {
        h ^= (unsigned char)p[i];
        h *= 1099511628211ULL;
    }
    return h;
}

// ---- unicode-whitespace trim (byte-level mirror of str.strip()) ------
//
// Python's str.strip() removes every codepoint where str.isspace() is
// true. On UTF-8 bytes that is: the ASCII set below, plus the exact
// multi-byte sequences for U+0085 U+00A0 U+1680 U+2000..200A U+2028
// U+2029 U+202F U+205F U+3000. (U+200B ZWSP is NOT whitespace.)

inline bool ascii_ws(unsigned char c) {
    return c == ' ' || c == '\t' || c == '\r' || c == '\n' || c == '\v'
        || c == '\f' || (c >= 0x1c && c <= 0x1f);
}

// byte length of one whitespace char starting at p (0 = not whitespace)
inline int ws_fwd(const unsigned char* p, long long n) {
    unsigned char c = p[0];
    if (ascii_ws(c)) return 1;
    if (c == 0xC2 && n >= 2 && (p[1] == 0x85 || p[1] == 0xA0)) return 2;
    if (n >= 3) {
        if (c == 0xE1 && p[1] == 0x9A && p[2] == 0x80) return 3;
        if (c == 0xE2) {
            if (p[1] == 0x80 && ((p[2] >= 0x80 && p[2] <= 0x8A)
                                 || p[2] == 0xA8 || p[2] == 0xA9
                                 || p[2] == 0xAF)) return 3;
            if (p[1] == 0x81 && p[2] == 0x9F) return 3;
        }
        if (c == 0xE3 && p[1] == 0x80 && p[2] == 0x80) return 3;
    }
    return 0;
}

// byte length of one whitespace char ENDING at e (exclusive); s bounds
// the lookback. Exact-pattern matches are unambiguous across lengths.
inline int ws_back(const unsigned char* s, const unsigned char* e) {
    long long n = e - s;
    unsigned char c = e[-1];
    if (ascii_ws(c)) return 1;
    if (n >= 3) {
        unsigned char a = e[-3], b = e[-2];
        if (a == 0xE1 && b == 0x9A && c == 0x80) return 3;
        if (a == 0xE2 && b == 0x80 && ((c >= 0x80 && c <= 0x8A)
                                       || c == 0xA8 || c == 0xA9
                                       || c == 0xAF)) return 3;
        if (a == 0xE2 && b == 0x81 && c == 0x9F) return 3;
        if (a == 0xE3 && b == 0x80 && c == 0x80) return 3;
    }
    if (n >= 2 && e[-2] == 0xC2 && (c == 0x85 || c == 0xA0)) return 2;
    return 0;
}

// ---- numeric acceptance: the Python float() shape -------------------

// every byte in [0-9 + - . e E] — the only tokens handed to strtod
// besides the inf/nan words, so strtod's wider grammar (hex, NAN(tag))
// can never diverge from what float() would accept
inline bool numeric_chars(const char* p, long long n) {
    for (long long i = 0; i < n; ++i) {
        char c = p[i];
        if (!((c >= '0' && c <= '9') || c == '+' || c == '-' || c == '.'
              || c == 'e' || c == 'E')) return false;
    }
    return true;
}

// PEP-515 underscore grouping: Python float("1_000.5") == 1000.5, with
// every '_' strictly BETWEEN two digits. Tokens passing this check are
// re-parsed with the underscores stripped, so a numeric column mixing
// tokenizers across byte ranges (range-scoped fallback) cannot read
// '1_000' as NA natively and 1000.0 in Python. Returns the stripped
// length, or -1 when the token is not a valid grouped numeric.
inline long long strip_underscores(const char* p, long long n,
                                   char* out, long long cap) {
    if (n >= cap) return -1;
    long long m = 0;
    bool saw = false;
    for (long long i = 0; i < n; ++i) {
        char c = p[i];
        if (c == '_') {
            saw = true;
            if (i == 0 || i + 1 >= n) return -1;
            char a = p[i - 1], b = p[i + 1];
            if (!(a >= '0' && a <= '9') || !(b >= '0' && b <= '9'))
                return -1;
            continue;
        }
        if (!((c >= '0' && c <= '9') || c == '+' || c == '-' || c == '.'
              || c == 'e' || c == 'E')) return -1;
        out[m++] = c;
    }
    if (!saw) return -1;   // no underscores: take the normal path
    out[m] = 0;
    return m;
}

inline bool ieq(char a, char b) { return (a | 0x20) == b; }

// [+-]? (inf | infinity | nan), case-insensitive — strtod and float()
// agree on these
inline bool inf_nan_form(const char* p, long long n) {
    if (n > 0 && (p[0] == '+' || p[0] == '-')) { ++p; --n; }
    if (n == 3) {
        if (ieq(p[0], 'i') && ieq(p[1], 'n') && ieq(p[2], 'f')) return true;
        if (ieq(p[0], 'n') && ieq(p[1], 'a') && ieq(p[2], 'n')) return true;
    }
    if (n == 8) {
        const char* w = "infinity";
        for (int i = 0; i < 8; ++i) if (!ieq(p[i], w[i])) return false;
        return true;
    }
    return false;
}

// Clinger fast path: when the token is [+-]?digits[.digits][eE[+-]digits]
// with <= 19 digits, mantissa < 2^53 and |decimal exponent| <= 22, both
// the mantissa and the power of ten are EXACT doubles, so one multiply
// (or divide) performs the single correctly-rounded step — bit-identical
// to strtod, ~15x faster (strtod was the tokenize bottleneck: ~50 MB/s
// per core on an all-numeric CSV). Returns false to fall back.
const double P10[] = {1e0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9,
                      1e10, 1e11, 1e12, 1e13, 1e14, 1e15, 1e16, 1e17,
                      1e18, 1e19, 1e20, 1e21, 1e22};

inline bool fast_atod(const char* p, long long n, double* out) {
    bool neg = false;
    long long i = 0;
    if (i < n && (p[i] == '+' || p[i] == '-')) { neg = p[i] == '-'; ++i; }
    unsigned long long mant = 0;
    int digits = 0, frac = 0;
    bool seen_dot = false, any = false;
    for (; i < n; ++i) {
        char c = p[i];
        if (c >= '0' && c <= '9') {
            if (digits >= 19) return false;
            ++digits;
            mant = mant * 10 + (unsigned long long)(c - '0');
            if (seen_dot) ++frac;
            any = true;
        } else if (c == '.') {
            if (seen_dot) return false;
            seen_dot = true;
        } else {
            break;
        }
    }
    if (!any) return false;
    long long e = 0;
    if (i < n && (p[i] == 'e' || p[i] == 'E')) {
        ++i;
        bool eneg = false;
        if (i < n && (p[i] == '+' || p[i] == '-')) { eneg = p[i] == '-'; ++i; }
        if (i >= n) return false;
        long long ev = 0;
        for (; i < n; ++i) {
            char c = p[i];
            if (c < '0' || c > '9') return false;
            ev = ev * 10 + (c - '0');
            if (ev > 9999) return false;
        }
        e = eneg ? -ev : ev;
    }
    if (i != n) return false;
    e -= frac;
    if (mant >= (1ULL << 53)) return false;
    double d;
    if (e >= 0) {
        if (e > 22) return false;
        d = (double)mant * P10[e];
    } else {
        if (e < -22) return false;
        d = (double)mant / P10[-e];
    }
    *out = neg ? -d : d;
    return true;
}

// decline reasons shared by csv_parse / the binding
enum { DECLINE_OK = 0, DECLINE_RAGGED = 1, DECLINE_OPEN_QUOTE = 2,
       DECLINE_TRAILING_QUOTE = 3 };

// Strict UTF-8 validation (rejects overlongs, surrogates, > U+10FFFF).
// Used by csv_enum_encode_full: byte-lexicographic order over VALID
// UTF-8 equals Python's code-point order over the decoded strings, so
// the native sort can stand in for sorted() on the domain — any invalid
// label instead declines the whole column back to the Python path
// (whose errors='replace' decode has no byte-order guarantee).
inline bool valid_utf8(const unsigned char* p, long long n) {
    long long i = 0;
    while (i < n) {
        unsigned char c = p[i];
        if (c < 0x80) { ++i; continue; }
        int k;
        if ((c & 0xE0) == 0xC0) k = 1;
        else if ((c & 0xF0) == 0xE0) k = 2;
        else if ((c & 0xF8) == 0xF0) k = 3;
        else return false;
        if (i + k >= n) return false;
        for (int j = 1; j <= k; ++j)
            if ((p[i + j] & 0xC0) != 0x80) return false;
        if (k == 1 && c < 0xC2) return false;                   // overlong
        if (k == 2) {
            if (c == 0xE0 && p[i + 1] < 0xA0) return false;     // overlong
            if (c == 0xED && p[i + 1] >= 0xA0) return false;    // surrogate
        }
        if (k == 3) {
            if (c == 0xF0 && p[i + 1] < 0x90) return false;     // overlong
            if (c > 0xF4 || (c == 0xF4 && p[i + 1] >= 0x90))
                return false;                                   // > U+10FFFF
        }
        i += k + 1;
    }
    return true;
}

}  // namespace

extern "C" {

// The single scan pass: per-cell start offsets + lengths (content
// between quotes for quoted cells; unicode-whitespace-trimmed both
// ways) and an eager in-place numeric parse, through the full
// quote-aware state machine. The caller supplies the expected column
// count (from ParseSetup) and a row-count UPPER BOUND (its newline
// count + 1 — embedded quoted newlines only ever make the true row
// count smaller); the scan itself validates widths, so no separate
// shape pass walks the bytes twice. Returns the rows actually filled,
// or -1 with *reason_out set: inconsistent row widths, a quote still
// open at the end of the range, or non-whitespace text after a closing
// quote (csv.reader glues it into the field — offsets can't express
// that). Quotes open ONLY as a cell's first byte, exactly like
// csv.reader with skipinitialspace off.
//
// Output arrays are caller-allocated with rows_cap*ncols entries, laid
// out COLUMN-major with rows_cap as the stride: idx = col*rows_cap +
// row, so each column's filled prefix is one contiguous slice.
//
// ok[idx] low bits: 1 = numeric (vals[idx] holds the value), 0 =
// non-numeric text, 2 = empty cell; bit 0x80 = the (quoted) cell
// contains "" escape sequences — its raw bytes need one
// replace("\"\"" -> "\"") before use as a token.
//
// The numeric parse runs IN PLACE on the borrowed buffer (fast_atod,
// strtod fallback): tokens handed to strtod are pre-filtered to numeric
// characters and the byte at p+n is always a delimiter/whitespace/
// quote, so the parse cannot run past the token — except when the
// token touches the very end of the buffer (an mmap of a file ending
// without a newline may not be readable one byte past EOF), where it
// copies through a small stack buffer instead.
// ``want_offsets`` (len ncols, NULL = all) suppresses the starts/lens
// writes per column: a float64 column's offsets are never read back
// (its value IS vals[idx]), and skipping them skips ~12B/cell of write
// traffic AND the page faults of the untouched arena region — on a
// mostly-numeric file that halves the scan's memory traffic.
long long csv_parse(const char* buf, long long len, char sep, char quote,
                    long long rows_cap, long long ncols,
                    const unsigned char* want_offsets,
                    long long* starts, int* lens, double* vals,
                    unsigned char* ok, long long* reason_out,
                    long long* esc_count_out) {
    const long long rows = rows_cap;  // column stride
    long long r = 0, cidx = 0;
    long long esc_cells = 0;
    long long cell_start = 0;
    long long qs = -1, qe = -1;   // quoted-content span of the current cell
    bool esc = false;             // current quoted cell has "" escapes
    bool any = false, at_start = true;
    const unsigned char* ub = (const unsigned char*)buf;
    *reason_out = DECLINE_OK;
    *esc_count_out = 0;
    auto close_cell = [&](long long end) {
        if (r >= rows || cidx >= ncols) return;
        long long s, e;
        bool escaped = false;
        if (qs >= 0) { s = qs; e = qe; escaped = esc; qs = qe = -1; esc = false; }
        else { s = cell_start; e = end; }
        while (s < e) {
            int k = ws_fwd(ub + s, e - s);
            if (!k) break;
            s += k;
        }
        while (e > s) {
            int k = ws_back(ub + s, ub + e);
            if (!k) break;
            e -= k;
        }
        long long idx = cidx * rows + r;         // column-major
        long long n = e - s;
        if (!want_offsets || want_offsets[cidx]) {
            starts[idx] = s;
            lens[idx] = (int)n;
        }
        if (n > 0) {
            const char* p = buf + s;
            bool cand = numeric_chars(p, n) || inf_nan_form(p, n);
            double v = NAN;
            bool is_num = false;
            if (!cand && n < 511) {
                // PEP-515 grouped numerics ("1_000"): float() accepts
                // them, so the stripped form must parse here too
                char tmp[512];
                long long m = strip_underscores(p, n, tmp, 512);
                if (m > 0) {
                    if (fast_atod(tmp, m, &v)) {
                        is_num = true;
                    } else {
                        char* endp = nullptr;
                        v = strtod(tmp, &endp);
                        is_num = (endp == tmp + m);
                        if (!is_num) v = NAN;
                    }
                }
            }
            if (is_num) {
                // grouped-numeric path above already parsed the value
            } else if (cand && fast_atod(p, n, &v)) {
                is_num = true;
            } else if (cand) {
                char* endp = nullptr;
                if (e < len) {                    // delimiter byte stops strtod
                    v = strtod(p, &endp);
                    is_num = (endp == p + n);
                } else {                          // token touches buffer end
                    char tmp[512];
                    if (n < 511) {
                        memcpy(tmp, p, n);
                        tmp[n] = 0;
                        v = strtod(tmp, &endp);
                        is_num = (endp == tmp + n);
                    } else {
                        std::vector<char> big(p, p + n);
                        big.push_back(0);
                        v = strtod(big.data(), &endp);
                        is_num = (endp == big.data() + n);
                    }
                }
            }
            vals[idx] = is_num ? v : NAN;
            ok[idx] = is_num ? 1 : 0;
        } else {
            vals[idx] = NAN;
            ok[idx] = 2;                          // empty cell
        }
        if (escaped) { ok[idx] |= 0x80; ++esc_cells; }
    };
    long long i = 0;
    while (i < len && r < rows) {
        char c = buf[i];
        if (c == quote && at_start) {
            qs = i + 1; esc = false;
            ++i;
            for (;;) {
                if (i >= len) {
                    *reason_out = DECLINE_OPEN_QUOTE;
                    return -1;
                }
                if (buf[i] == quote) {
                    if (i + 1 < len && buf[i + 1] == quote) { esc = true; i += 2; continue; }
                    qe = i; ++i; break;
                }
                ++i;
            }
            any = true; at_start = false;
            while (i < len && buf[i] != sep && buf[i] != '\n') {
                char t = buf[i];
                if (t != ' ' && t != '\t' && t != '\r') {
                    *reason_out = DECLINE_TRAILING_QUOTE;
                    return -1;
                }
                ++i;
            }
            continue;                            // i sits on sep/'\n'/EOF
        }
        if (c == '\n') {
            if (any || cidx > 0) {
                if (cidx + 1 != ncols) { *reason_out = DECLINE_RAGGED; return -1; }
                close_cell(i);
                ++r;
            }
            cidx = 0; cell_start = i + 1; any = false; at_start = true;
        } else if (c == sep) {
            close_cell(i);
            ++cidx;
            if (cidx >= ncols) { *reason_out = DECLINE_RAGGED; return -1; }
            cell_start = i + 1; at_start = true;
        } else {
            if (c != '\r') any = true;
            at_start = false;
        }
        ++i;
    }
    if ((any || cidx > 0 || qs >= 0) && r < rows) {
        if (cidx + 1 != ncols) { *reason_out = DECLINE_RAGGED; return -1; }
        close_cell(len);
        ++r;
    }
    *esc_count_out = esc_cells;
    return r;
}

// Range-boundary discovery: one pass of the SAME quote state machine,
// writing the first safe row boundary (offset just past a newline that
// is outside any quoted field) at or after each ascending target.
// parse.py splits files on these so a quoted field with embedded
// newlines can never straddle two byte ranges. Returns the number of
// bounds written (may be < n_targets when targets fall past the last
// outside-quote newline; bounds_out entries are ascending, deduped by
// the caller).
long long csv_chunk_bounds(const char* buf, long long len, char sep,
                           char quote, const long long* targets,
                           long long n_targets, long long* bounds_out) {
    long long t = 0, filled = 0;
    bool at_start = true;
    long long i = 0;
    while (i < len && t < n_targets) {
        char c = buf[i];
        if (c == quote && at_start) {
            ++i;
            for (;;) {
                if (i >= len) return filled;     // open quote: no more bounds
                if (buf[i] == quote) {
                    if (i + 1 < len && buf[i + 1] == quote) { i += 2; continue; }
                    ++i; break;
                }
                ++i;
            }
            at_start = false;
            continue;
        }
        if (c == '\n') {
            at_start = true;
            while (t < n_targets && i >= targets[t]) {
                bounds_out[filled++] = i + 1;
                ++t;
            }
        } else if (c == sep) {
            at_start = true;
        } else {
            at_start = false;
        }
        ++i;
    }
    return filled;
}

// Chunk-local enum dictionary encode (the NewChunk categorical path of
// water/parser/CsvParser.java, where each chunk builds its own domain
// before ParseDataset unions them). One column's cells arrive as
// (starts, lens) pairs from csv_parse; tokens dictionary-encode against
// an open-addressing hash table in first-appearance order. Outputs:
// codes[i] = dictionary id of cell i, uniq_rows[k] = row index of the
// first cell holding dictionary entry k (the caller decodes labels from
// those). Returns the cardinality, or -1 when it would exceed max_card
// (caller falls back to a string column). NA-string, empty-cell and
// ""-escape handling stay in Python: they become ordinary dictionary
// entries the caller remaps/dedupes on the decoded label.
long long csv_enum_encode(const char* buf,
                          const long long* starts, const int* lens,
                          long long n,
                          int* codes, long long* uniq_rows,
                          long long max_card) {
    long long cap = 1024;
    std::vector<long long> table(cap, -1);
    long long card = 0;
    for (long long i = 0; i < n; ++i) {
        if (card * 10 >= cap * 7) {          // load > 0.7: rehash
            cap <<= 1;
            table.assign(cap, -1);
            for (long long k = 0; k < card; ++k) {
                long long r = uniq_rows[k];
                long long j = fnv1a(buf + starts[r], lens[r]) & (cap - 1);
                while (table[j] >= 0) j = (j + 1) & (cap - 1);
                table[j] = k;
            }
        }
        const char* p = buf + starts[i];
        int len = lens[i];
        long long j = fnv1a(p, len) & (cap - 1);
        for (;;) {
            long long e = table[j];
            if (e < 0) {
                if (card >= max_card) return -1;
                uniq_rows[card] = i;
                table[j] = card;
                codes[i] = (int)card;
                ++card;
                break;
            }
            long long r = uniq_rows[e];
            if (lens[r] == len && memcmp(buf + starts[r], p, len) == 0) {
                codes[i] = (int)e;
                break;
            }
            j = (j + 1) & (cap - 1);
        }
    }
    return card;
}

// ---- nogil encode plane (ISSUE 16) ----------------------------------
//
// These entry points move the last GIL-held numpy glue of
// ingest/chunk.py (S-array gathers, NA membership, per-column
// reductions, the enum sort/remap) into released-GIL native passes so
// N parse workers scale to N cores — the chunk worker keeps only
// bookkeeping.

// Fixed-width token gather: out[i*width .. ] = the cell's bytes,
// zero-padded (the numpy S-array layout _tokens_sarr built through a
// slab of fancy-index passes). One memcpy per cell, no index matrix.
void csv_gather_tokens(const char* buf, const long long* starts,
                       const int* lens, long long n, long long width,
                       char* out) {
    memset(out, 0, (size_t)(n * width));
    for (long long i = 0; i < n; ++i) {
        int m = lens[i];
        if (m > 0) {
            if (m > width) m = (int)width;
            memcpy(out + i * width, buf + starts[i], (size_t)m);
        }
    }
}

// Membership flags: out[i] = 1 when cell i's bytes equal any of the
// n_pat patterns (concatenated in pat_buf at pat_offs/pat_lens) — the
// NA-string test np.isin ran over the gathered S array.
void csv_match_any(const char* buf, const long long* starts,
                   const int* lens, long long n,
                   const char* pat_buf, const long long* pat_offs,
                   const int* pat_lens, long long n_pat,
                   unsigned char* out) {
    for (long long i = 0; i < n; ++i) {
        unsigned char hit = 0;
        const char* p = buf + starts[i];
        int m = lens[i];
        for (long long k = 0; k < n_pat && !hit; ++k)
            if (pat_lens[k] == m
                    && memcmp(pat_buf + pat_offs[k], p, (size_t)m) == 0)
                hit = 1;
        out[i] = hit;
    }
}

// Numeric column detach + reductions in ONE pass: gather the selected
// columns' row slices [r0, r0+nrows) out of the column-major scratch
// arena (stride col_stride) into an owned [ncols_sel, nrows] block, and
// compute per column the finite |max| (fmax_out, -inf when no finite
// cell) and an all-finite flag — the isfinite/all/abs-max numpy passes
// that each re-walked the block under the GIL.
void csv_numeric_stats(const double* vals, long long col_stride,
                       const long long* col_idx, long long ncols_sel,
                       long long r0, long long nrows,
                       double* out_block, double* fmax_out,
                       unsigned char* allfin_out) {
    for (long long t = 0; t < ncols_sel; ++t) {
        const double* src = vals + col_idx[t] * col_stride + r0;
        double* dst = out_block + t * nrows;
        memcpy(dst, src, (size_t)nrows * sizeof(double));
        double fmax = -INFINITY;
        unsigned char allfin = 1;
        for (long long i = 0; i < nrows; ++i) {
            double v = dst[i];
            if (std::isfinite(v)) {
                double a = v < 0 ? -v : v;
                if (a > fmax) fmax = a;
            } else {
                allfin = 0;
            }
        }
        fmax_out[t] = fmax;
        allfin_out[t] = allfin;
    }
}

// Quote-aware row count: the SAME row-accounting as csv_parse (a row
// closes at an outside-quote newline when it saw any content; a
// content-bearing tail without a newline counts) with no per-cell
// work — the multi-host range planner's one cheap pass. Returns the
// row count, or -1 when a quote is left open (the caller cannot trust
// a count over a range it would decline).
long long csv_count_rows(const char* buf, long long len, char sep,
                         char quote) {
    long long r = 0, cidx = 0;
    bool any = false, at_start = true, in_row = false;
    long long i = 0;
    while (i < len) {
        char c = buf[i];
        if (c == quote && at_start) {
            ++i;
            for (;;) {
                if (i >= len) return -1;          // open quote
                if (buf[i] == quote) {
                    if (i + 1 < len && buf[i + 1] == quote) { i += 2; continue; }
                    ++i; break;
                }
                ++i;
            }
            any = true; at_start = false; in_row = true;
            continue;
        }
        if (c == '\n') {
            if (any || cidx > 0) ++r;
            cidx = 0; any = false; at_start = true; in_row = false;
        } else if (c == sep) {
            ++cidx; at_start = true; in_row = true;
        } else {
            if (c != '\r') { any = true; in_row = true; }
            at_start = false;
        }
        ++i;
    }
    if ((any || cidx > 0) && in_row) ++r;
    return r;
}

// Full enum encode: hash-dictionary build, ""-unescape, NA-string
// mapping, byte-lexicographic domain sort + dedupe, and the final
// code remap — ONE native pass chain replacing the per-label
// bytes.decode loop, sorted(set()), rank-LUT build and lut[codes] take
// that _codes_from_labels ran under the GIL. Outputs: codes[i] = rank
// of cell i's label in the SORTED deduped domain (NA cells = na_code);
// dom_rows[k] / dom_esc[k] = a representative cell row (+ its escape
// flag) for domain entry k, from which the caller decodes the label
// text (O(card), the only Python left). Returns the domain cardinality,
// -1 when it would exceed max_card (string fallback), or -2 when a
// label is not valid UTF-8 (byte order no longer matches Python's
// sorted(); caller takes the Python path).
long long csv_enum_encode_full(const char* buf, const long long* starts,
                               const int* lens, long long n,
                               const unsigned char* esc,
                               const char* na_buf, const long long* na_offs,
                               const int* na_lens, long long n_na,
                               long long max_card, int na_code,
                               int* codes, long long* dom_rows,
                               unsigned char* dom_esc) {
    // phase 1: raw-byte dictionary (first-appearance ids), same
    // open-addressing scheme as csv_enum_encode. Raw cardinality is
    // allowed a small overhead above max_card: NA labels and ""-escape
    // aliases collapse before the final count.
    const long long raw_cap_card = max_card + n_na + 1;
    std::vector<long long> uniq;                 // first row per raw id
    uniq.reserve(raw_cap_card < 4096 ? raw_cap_card : 4096);
    long long cap = 1024;
    std::vector<long long> table(cap, -1);
    for (long long i = 0; i < n; ++i) {
        long long card = (long long)uniq.size();
        if (card * 10 >= cap * 7) {
            cap <<= 1;
            table.assign(cap, -1);
            for (long long k = 0; k < card; ++k) {
                long long r = uniq[k];
                long long j = fnv1a(buf + starts[r], lens[r]) & (cap - 1);
                while (table[j] >= 0) j = (j + 1) & (cap - 1);
                table[j] = k;
            }
        }
        const char* p = buf + starts[i];
        int len = lens[i];
        long long j = fnv1a(p, len) & (cap - 1);
        for (;;) {
            long long e = table[j];
            if (e < 0) {
                if (card >= raw_cap_card) return -1;
                uniq.push_back(i);
                table[j] = card;
                codes[i] = (int)card;
                break;
            }
            long long r = uniq[e];
            if (lens[r] == len && memcmp(buf + starts[r], p, len) == 0) {
                codes[i] = (int)e;
                break;
            }
            j = (j + 1) & (cap - 1);
        }
    }
    const long long raw_card = (long long)uniq.size();
    // phase 2: per-unique label view — unescaped into a side arena when
    // the representative cell carries "" escapes — then UTF-8 validate.
    std::vector<char> arena;
    std::vector<long long> l_off(raw_card), l_len(raw_card);
    std::vector<unsigned char> l_in_arena(raw_card, 0);
    for (long long k = 0; k < raw_card; ++k) {
        long long r = uniq[k];
        const char* p = buf + starts[r];
        int m = lens[r];
        if (esc && esc[r] && m >= 2) {
            long long o = (long long)arena.size();
            for (int t = 0; t < m; ++t) {
                arena.push_back(p[t]);
                if (p[t] == '"' && t + 1 < m && p[t + 1] == '"') ++t;
            }
            l_off[k] = o;
            l_len[k] = (long long)arena.size() - o;
            l_in_arena[k] = 1;
        } else {
            l_off[k] = starts[r];
            l_len[k] = m;
        }
    }
    auto label = [&](long long k) -> const char* {
        return (l_in_arena[k] ? arena.data() + l_off[k] : buf + l_off[k]);
    };
    for (long long k = 0; k < raw_card; ++k)
        if (!valid_utf8((const unsigned char*)label(k), l_len[k]))
            return -2;
    // phase 3: NA membership on the unescaped label bytes (the decoded
    // string equality test `lab in nas`, moved to bytes — exact for
    // valid UTF-8 since the NA strings arrive UTF-8 encoded).
    std::vector<unsigned char> is_na(raw_card, 0);
    for (long long k = 0; k < raw_card; ++k) {
        const char* p = label(k);
        long long m = l_len[k];
        for (long long t = 0; t < n_na; ++t)
            if (na_lens[t] == m
                    && memcmp(na_buf + na_offs[t], p, (size_t)m) == 0) {
                is_na[k] = 1;
                break;
            }
    }
    // phase 4: byte-lexicographic sort of the non-NA raw ids (== code
    // point order == Python sorted() on the decoded labels), deduping
    // escape aliases that unescaped to the same bytes.
    std::vector<long long> order;
    order.reserve(raw_card);
    for (long long k = 0; k < raw_card; ++k)
        if (!is_na[k]) order.push_back(k);
    std::sort(order.begin(), order.end(),
              [&](long long a, long long b) {
                  long long la = l_len[a], lb = l_len[b];
                  int c = memcmp(label(a), label(b),
                                 (size_t)(la < lb ? la : lb));
                  if (c != 0) return c < 0;
                  return la < lb;
              });
    std::vector<int> lut(raw_card, na_code);
    long long dom = 0;
    for (size_t t = 0; t < order.size(); ++t) {
        long long k = order[t];
        if (t > 0) {
            long long pk = order[t - 1];
            if (l_len[pk] == l_len[k]
                    && memcmp(label(pk), label(k), (size_t)l_len[k]) == 0) {
                lut[k] = lut[pk];                // escape alias: same label
                continue;
            }
        }
        if (dom >= max_card) return -1;
        dom_rows[dom] = uniq[k];
        dom_esc[dom] = (esc && esc[uniq[k]]) ? 1 : 0;
        lut[k] = (int)dom;
        ++dom;
    }
    // phase 5: final remap — one pass, no Python.
    for (long long i = 0; i < n; ++i) codes[i] = lut[codes[i]];
    return dom;
}

}  // extern "C"
