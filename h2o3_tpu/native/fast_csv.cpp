// Native CSV tokenizer — the hot byte-scanning loop of ingest.
//
// Reference role: water/parser/CsvParser.java streams raw-byte chunks
// into NewChunks inside MultiFileParseTask (ParseDataset.java:623); the
// tokenizer is the CPU-bound inner loop of every import. Here the same
// loop is C++ behind a C ABI (ctypes binding in h2o3_tpu/native/
// __init__.py), emitting per-cell byte offsets plus eagerly-parsed
// doubles; Python only touches the (rare) non-numeric cells.
//
// Scope: separator-delimited rows, '\n' / '\r\n' terminators, no
// embedded quotes (the binding routes quoted files to the Python
// fallback — RFC 4180 escapes stay in one place).
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <vector>

namespace {

inline unsigned long long fnv1a(const char* p, int n) {
    unsigned long long h = 1469598103934665603ULL;
    for (int i = 0; i < n; ++i) {
        h ^= (unsigned char)p[i];
        h *= 1099511628211ULL;
    }
    return h;
}

}  // namespace

extern "C" {

// First pass: count rows and columns. Returns row count (data rows,
// including a header row if present — the caller decides), sets *ncols
// from the first row. Returns -1 if rows have inconsistent widths
// (caller falls back to the tolerant Python parser).
long long csv_shape(const char* buf, long long len, char sep,
                    long long* ncols_out) {
    long long rows = 0, ncols = 0, cols = 1;
    bool any = false;
    for (long long i = 0; i < len; ++i) {
        char c = buf[i];
        if (c == '\n') {
            if (any || cols > 1) {
                if (ncols == 0) ncols = cols;
                else if (cols != ncols) return -1;
                ++rows;
            }
            cols = 1; any = false;
        } else if (c == sep) {
            ++cols;
        } else if (c != '\r') {
            any = true;
        }
    }
    if (any || cols > 1) {              // last line without newline
        if (ncols == 0) ncols = cols;
        else if (cols != ncols) return -1;
        ++rows;
    }
    *ncols_out = ncols;
    return rows;
}

// Second pass: per-cell start offsets + lengths (whitespace-trimmed)
// and an eager strtod parse (NaN when the cell is not fully numeric;
// ok[i]=0 marks those cells so the caller can distinguish NA strings
// from genuine text). Arrays are caller-allocated with rows*ncols
// entries. Returns rows actually filled.
long long csv_parse(const char* buf, long long len, char sep,
                    long long rows, long long ncols,
                    long long* starts, int* lens, double* vals,
                    unsigned char* ok) {
    long long r = 0, cidx = 0;
    long long cell_start = 0;
    bool any = false;
    auto close_cell = [&](long long end) {
        long long s = cell_start, e = end;
        while (s < e && (buf[s] == ' ' || buf[s] == '\t')) ++s;
        while (e > s && (buf[e - 1] == ' ' || buf[e - 1] == '\t'
                         || buf[e - 1] == '\r')) --e;
        long long idx = r * ncols + cidx;
        if (idx >= rows * ncols) return;
        starts[idx] = s;
        lens[idx] = (int)(e - s);
        if (e > s) {
            char tmp[64];
            long long n = e - s;
            if (n < 63) {
                memcpy(tmp, buf + s, n);
                tmp[n] = 0;
                char* endp = nullptr;
                double v = strtod(tmp, &endp);
                if (endp == tmp + n) { vals[idx] = v; ok[idx] = 1; }
                else { vals[idx] = NAN; ok[idx] = 0; }
            } else { vals[idx] = NAN; ok[idx] = 0; }
        } else { vals[idx] = NAN; ok[idx] = 2; }   // empty cell
    };
    for (long long i = 0; i < len && r < rows; ++i) {
        char c = buf[i];
        if (c == '\n') {
            if (any || cidx > 0) {
                close_cell(i);
                ++r;
            }
            cidx = 0; cell_start = i + 1; any = false;
        } else if (c == sep) {
            close_cell(i);
            ++cidx; cell_start = i + 1;
        } else if (c != '\r') {
            any = true;
        }
    }
    if ((any || cidx > 0) && r < rows) {
        close_cell(len);
        ++r;
    }
    return r;
}

// Chunk-local enum dictionary encode (the NewChunk categorical path of
// water/parser/CsvParser.java, where each chunk builds its own domain
// before ParseDataset unions them). One column's cells arrive as
// (starts, lens) pairs from csv_parse; tokens dictionary-encode against
// an open-addressing hash table in first-appearance order. Outputs:
// codes[i] = dictionary id of cell i, uniq_rows[k] = row index of the
// first cell holding dictionary entry k (the caller decodes labels from
// those). Returns the cardinality, or -1 when it would exceed max_card
// (caller falls back to a string column). NA-string and empty-cell
// handling stay in Python: they become ordinary dictionary entries the
// caller remaps to the NA code.
long long csv_enum_encode(const char* buf,
                          const long long* starts, const int* lens,
                          long long n,
                          int* codes, long long* uniq_rows,
                          long long max_card) {
    long long cap = 1024;
    std::vector<long long> table(cap, -1);
    long long card = 0;
    for (long long i = 0; i < n; ++i) {
        if (card * 10 >= cap * 7) {          // load > 0.7: rehash
            cap <<= 1;
            table.assign(cap, -1);
            for (long long k = 0; k < card; ++k) {
                long long r = uniq_rows[k];
                long long j = fnv1a(buf + starts[r], lens[r]) & (cap - 1);
                while (table[j] >= 0) j = (j + 1) & (cap - 1);
                table[j] = k;
            }
        }
        const char* p = buf + starts[i];
        int len = lens[i];
        long long j = fnv1a(p, len) & (cap - 1);
        for (;;) {
            long long e = table[j];
            if (e < 0) {
                if (card >= max_card) return -1;
                uniq_rows[card] = i;
                table[j] = card;
                codes[i] = (int)card;
                ++card;
                break;
            }
            long long r = uniq_rows[e];
            if (lens[r] == len && memcmp(buf + starts[r], p, len) == 0) {
                codes[i] = (int)e;
                break;
            }
            j = (j + 1) & (cap - 1);
        }
    }
    return card;
}

}  // extern "C"
