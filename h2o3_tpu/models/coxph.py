"""CoxPH — proportional-hazards regression by partial-likelihood Newton.

Reference: hex/coxph/CoxPH.java:28 — Efron/Breslow partial likelihood;
per-iteration MRTasks accumulate the gradient and Hessian over the risk
sets; driver Newton step.

TPU re-design: rows sort once by stop time (descending, so risk sets are
prefix sums); each Newton iteration computes risk-set aggregates
S0 = Σe^η, S1 = Σe^η·x, S2 = Σe^η·xxᵀ with cumulative sums — S0/S1 via
jnp.cumsum (one fused pass), the S2 event-sum via an event-weighted
matmul identity: Σ_events S2(t_i)/S0(t_i) = Σ_rows e^η_j·x_jx_jᵀ·C_j
where C_j = Σ_{events i ≤ j} 1/S0(t_i) is itself a cumsum — so the
Hessian is ONE MXU matmul (Xᵀ·diag(e^η·C)·X), no per-event F×F loop.
Ties use the Breslow approximation (Efron's correction is noted per
tie group; ties are exact when absent)."""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import expand_design, expand_scoring_matrix
from h2o3_tpu.models.model_base import (Model, ModelBuilder, TrainingSpec,
                                        pack_impute_means,
                                        unpack_impute_means)
from h2o3_tpu.persist import register_model_class

COXPH_DEFAULTS: Dict = dict(
    stop_column=None, event_column=None, ties="breslow",
    max_iterations=20, init=0.0,
)


def _tie_spans(ts):
    """For rows sorted by time descending: (firstpos, lastpos) index of
    each row's equal-time run — risk sets must treat ties atomically."""
    n = ts.shape[0]
    idx = jnp.arange(n)
    is_last = jnp.concatenate([ts[1:] != ts[:-1], jnp.array([True])])
    is_first = jnp.concatenate([jnp.array([True]), ts[1:] != ts[:-1]])
    lastpos = jax.lax.cummin(jnp.where(is_last, idx, n)[::-1])[::-1]
    firstpos = jax.lax.cummax(jnp.where(is_first, idx, -1))
    return firstpos, lastpos


@jax.jit
def _cox_pass(Xs, ts, event, w, beta, off):
    """One Newton iteration's (loglik, gradient, Hessian) on rows sorted
    by stop time DESCENDING. Risk set of an event at time t = all rows
    with t_j >= t, i.e. the prefix through the END of t's tie run."""
    firstpos, lastpos = _tie_spans(ts)
    eta = Xs @ beta + off
    r = w * jnp.exp(eta)                       # [n]
    S0 = jnp.cumsum(r)[lastpos]                # tie-closed prefix Σe^η
    S1 = jnp.cumsum(r[:, None] * Xs, axis=0)[lastpos]
    d = w * event                              # event weight per row
    S0s = jnp.maximum(S0, 1e-30)
    loglik = (d * (eta - jnp.log(S0s))).sum()
    grad = (d[:, None] * (Xs - S1 / S0s[:, None])).sum(axis=0)
    # Hessian: Σ_i d_i·S2(t_i)/S0_i − Σ_i d_i·(S1/S0)(S1/S0)ᵀ; row j sits
    # in the risk set of every event with time ≤ t_j, i.e. events from
    # the START of j's tie run onward — so the S2 event-sum reorders to
    # Σ_j r_j·x_jx_jᵀ·C_j with C_j a tie-opened SUFFIX sum — one matmul
    C = jnp.cumsum((d / S0s)[::-1])[::-1][firstpos]
    H1 = (Xs * (r * C)[:, None]).T @ Xs        # Σ_j e^η_j x_j x_jᵀ C_j
    U = S1 / S0s[:, None]
    H2 = (U * d[:, None]).T @ U
    H = H1 - H2
    return loglik, grad, H


class CoxPHModel(Model):
    algo = "coxph"

    def __init__(self, key, params, spec, beta, exp_names, impute_means,
                 loglik, nevents, baseline):
        super().__init__(key, params, spec)
        self.beta = np.asarray(beta)
        self.exp_names = list(exp_names)
        self.impute_means = {k: float(v) for k, v in impute_means.items()}
        self.loglik = float(loglik)
        self.nevents = int(nevents)
        self.baseline = baseline          # (times, cumhaz) arrays or None

    def coef(self) -> Dict[str, float]:
        return {n: float(b) for n, b in zip(self.exp_names, self.beta)}

    def _predict_matrix(self, X, offset=None):
        """Linear predictor (log relative hazard), centered like the
        reference (coefficients apply to mean-centered covariates)."""
        Xe = expand_scoring_matrix(self, X)
        eta = Xe @ jnp.asarray(self.beta)
        if offset is not None:
            eta = eta + offset
        return eta - float(self.output.get("eta_mean", 0.0))

    def concordance(self):
        return self.output.get("concordance")

    def _save_arrays(self):
        d = {"beta": self.beta, **pack_impute_means(self.impute_means)}
        if self.baseline is not None:
            d["bl_times"], d["bl_cumhaz"] = self.baseline
        return d

    def _save_extra_meta(self):
        return {"exp_names": self.exp_names, "loglik": self.loglik,
                "nevents": self.nevents}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        m.beta = arrays["beta"]
        m.exp_names = list(ex["exp_names"])
        m.impute_means = unpack_impute_means(arrays)
        m.loglik = ex["loglik"]
        m.nevents = ex["nevents"]
        m.baseline = ((arrays["bl_times"], arrays["bl_cumhaz"])
                      if "bl_times" in arrays else None)
        return m


class H2OCoxProportionalHazardsEstimator(ModelBuilder):
    algo = "coxph"

    def __init__(self, **params):
        merged = dict(COXPH_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        # h2o-py: train(x=covariates, event_column=..., stop_column=...);
        # y aliases the event column PER CALL (no params mutation — a
        # later train(y=...) must not silently reuse an old column)
        ev = y if y is not None else self.params.get("event_column")
        if ev is None:
            raise ValueError("CoxPH needs event_column (or y)")
        stop_col = self.params.get("stop_column")
        if x is not None and stop_col and stop_col not in x:
            x = list(x) + [stop_col]
        return super().train(x=x, y=ev, training_frame=training_frame,
                             validation_frame=validation_frame, **kw)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        p = self.params
        stop_col = p.get("stop_column")
        if not stop_col:
            raise ValueError("CoxPH needs stop_column")
        # the stop column rides along in spec features; pull it out
        if stop_col not in spec.names:
            raise ValueError(f"stop_column '{stop_col}' not among columns")
        si = spec.names.index(stop_col)
        times = spec.X[:, si]
        keep = [i for i in range(len(spec.names)) if i != si]
        sub_names = [spec.names[i] for i in keep]
        sub_spec = TrainingSpec(
            X=spec.X[:, jnp.asarray(keep)], y=spec.y, w=spec.w,
            offset=spec.offset, names=sub_names,
            is_cat=[spec.is_cat[i] for i in keep],
            cat_domains={k: v for k, v in spec.cat_domains.items()
                         if k in sub_names},
            response=spec.response, response_domain=spec.response_domain,
            nclasses=1, nrow=spec.nrow)
        Xe, exp_names, means = expand_design(sub_spec)
        Fe = Xe.shape[1]
        # event ∈ {0,1}; response may arrive as enum codes
        event = jnp.where(spec.y > 0, 1.0, 0.0).astype(jnp.float32)
        w = spec.w
        live = (w > 0) & ~jnp.isnan(times)
        wl = jnp.where(live, w, 0.0)
        # sort by stop DESCENDING so risk sets are prefixes; dead rows sink
        order = jnp.argsort(jnp.where(live, -times, jnp.inf))
        Xs = Xe[order]
        evs = (event * (live.astype(jnp.float32)))[order]
        ws = wl[order]
        ts = times[order]
        # center covariates (reference: coefficients on centered scale)
        wsum = jnp.maximum(ws.sum(), 1e-30)
        xm = (Xs * ws[:, None]).sum(0) / wsum
        Xc = (Xs - xm[None, :]) * (ws > 0)[:, None]
        beta = jnp.full(Fe, float(p.get("init", 0.0)), jnp.float32)
        max_iter = int(p.get("max_iterations", 20))
        off = (jnp.zeros_like(ws) if spec.offset is None
               else jnp.nan_to_num(spec.offset, nan=0.0)[order])
        loglik = None
        for it in range(max_iter):
            ll, g, H = _cox_pass(Xc, ts, evs, ws, beta, off)
            ridge = 1e-6 * jnp.eye(Fe)
            step = jnp.linalg.solve(H + ridge, g)
            nb = beta + step
            delta = float(jax.device_get(jnp.max(jnp.abs(nb - beta))))
            beta = nb
            loglik = float(jax.device_get(ll))
            job.set_progress((it + 1) / max_iter)
            if delta < 1e-6:
                break
        nevents = float(jax.device_get(evs.sum()))
        # Breslow baseline cumulative hazard at event times
        firstpos, lastpos = _tie_spans(ts)
        eta = Xc @ beta + off
        r = ws * jnp.exp(eta)
        S0 = jnp.maximum(jnp.cumsum(r)[lastpos], 1e-30)
        dl = evs / S0
        cum = jnp.cumsum(dl[::-1])[::-1][firstpos]  # H0(t_j), ties closed
        t_host = np.asarray(jax.device_get(ts))
        c_host = np.asarray(jax.device_get(cum))
        e_host = np.asarray(jax.device_get(evs)) > 0
        bl_t = t_host[e_host][::-1]        # ascending time
        bl_c = c_host[e_host][::-1]
        model = CoxPHModel(
            f"coxph_{id(self) & 0xffffff:x}", self.params, sub_spec,
            jax.device_get(beta), exp_names,
            {k: float(jax.device_get(v)) for k, v in means.items()},
            loglik, nevents, (bl_t.copy(), bl_c.copy()))
        # un-center: scoring expands raw X, so stash the mean offset
        model.output["eta_mean"] = float(jax.device_get(
            (xm * beta).sum()))
        model.output["coefficients"] = model.coef()
        model.output["loglik"] = loglik
        model.output["n_event"] = nevents
        # concordance (Harrell's C) on the training data, O(n log n)-ish
        # via pairwise count on host for moderate n, sampled above 20k
        eta_h = np.asarray(jax.device_get(eta))
        live_h = np.asarray(jax.device_get(ws)) > 0
        model.output["concordance"] = _concordance(
            t_host[live_h], np.asarray(jax.device_get(evs))[live_h] > 0,
            eta_h[live_h])
        return model


def _concordance(time, event, eta, cap: int = 20000) -> float:
    """Harrell's C: P(eta_i > eta_j | t_i < t_j, event_i)."""
    n = len(time)
    if n > cap:
        idx = np.random.default_rng(0).choice(n, cap, replace=False)
        time, event, eta = time[idx], event[idx], eta[idx]
    conc = ties = disc = 0
    order = np.argsort(time)
    t, e, s = time[order], event[order], eta[order]
    for i in range(len(t)):
        if not e[i]:
            continue
        later = t > t[i]
        if not later.any():
            continue
        d = s[later]
        conc += (s[i] > d).sum()
        ties += (s[i] == d).sum()
        disc += (s[i] < d).sum()
    tot = conc + ties + disc
    return float((conc + 0.5 * ties) / tot) if tot else float("nan")


register_model_class("coxph", CoxPHModel)
