"""Word2Vec — skip-gram word embeddings.

Reference: hex/word2vec/Word2Vec.java:15 — skip-gram with hierarchical
softmax; per-node Hogwild training with cross-node weight averaging
(WordVectorTrainer). Input is one string column, sentences separated by
NA rows; API: find_synonyms, transform(words, aggregate_method).

TPU re-design: skip-gram with NEGATIVE SAMPLING instead of hierarchical
softmax — HS walks a per-word Huffman path (sequential, scalar); negative
sampling turns each step into dense [batch, k+1, D] contractions that
batch onto the MXU, and is the standard accuracy-equivalent choice. The
update is synchronous minibatch SGD (replaces Hogwild+averaging): grads
of gathered rows scatter-add into the embedding tables inside one jit."""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder
from h2o3_tpu.persist import register_model_class

W2V_DEFAULTS: Dict = dict(
    vec_size=100, window_size=5, epochs=5, min_word_freq=5,
    init_learning_rate=0.025, sent_sample_rate=1e-3, negative=5, seed=-1,
)


@jax.jit
def _sgd_step(Win, Wout, center, context, negs, lr):
    """One skip-gram negative-sampling minibatch: returns updated tables.

    center [B], context [B], negs [B, K]; loss = -log σ(u·v)
    - Σ log σ(-u_n·v). Grad of the gathers scatter-adds back (JAX turns
    take-grad into segment-sum)."""
    def loss_fn(Win, Wout):
        v = Win[center]                        # [B, D]
        u = Wout[context]                      # [B, D]
        un = Wout[negs]                        # [B, K, D]
        pos = jax.nn.log_sigmoid((v * u).sum(-1))
        neg = jax.nn.log_sigmoid(-(un * v[:, None, :]).sum(-1)).sum(-1)
        return -(pos + neg).mean()

    g_in, g_out = jax.grad(loss_fn, argnums=(0, 1))(Win, Wout)
    return Win - lr * g_in, Wout - lr * g_out


class Word2VecModel(Model):
    algo = "word2vec"
    supervised = False

    def __init__(self, key, params, spec, vocab: List[str], vectors):
        super().__init__(key, params, spec)
        self.vocab = list(vocab)
        self.vectors = np.asarray(vectors)          # [V, D]
        self._index = {w: i for i, w in enumerate(self.vocab)}

    def find_synonyms(self, word: str, count: int = 20) -> Dict[str, float]:
        if word not in self._index:
            return {}
        V = self.vectors
        q = V[self._index[word]]
        norms = np.linalg.norm(V, axis=1) * max(np.linalg.norm(q), 1e-30)
        sims = (V @ q) / np.maximum(norms, 1e-30)
        order = np.argsort(-sims)
        out = {}
        for i in order:
            w = self.vocab[i]
            if w == word:
                continue
            out[w] = float(sims[i])
            if len(out) >= count:
                break
        return out

    def transform(self, words_frame: Frame,
                  aggregate_method: str = "none") -> Frame:
        """Map a words column to embeddings; 'average' pools rows per
        NA-separated sentence (h2o.transform_word2vec semantics)."""
        v = words_frame.vecs[0]
        words = v.to_strings()
        D = self.vectors.shape[1]
        E = np.zeros((len(words), D), np.float32)
        hit = np.zeros(len(words), bool)
        for i, w in enumerate(words):
            j = self._index.get(w)
            if j is not None:
                E[i] = self.vectors[j]
                hit[i] = True
        if aggregate_method == "average":
            rows = []
            acc = np.zeros(D, np.float32)
            cnt = 0
            pending = False        # tokens seen since the last separator
            for i, w in enumerate(words):
                if w is None or w == "":
                    rows.append(acc / cnt if cnt else np.full(D, np.nan))
                    acc = np.zeros(D, np.float32); cnt = 0
                    pending = False
                else:
                    pending = True
                    if hit[i]:
                        acc += E[i]; cnt += 1
            if pending:            # no trailing separator: close last sent
                rows.append(acc / cnt if cnt else np.full(D, np.nan))
            E = np.stack(rows)
        else:
            E[~hit] = np.nan
        names = [f"C{i + 1}" for i in range(D)]
        return Frame(names, [Vec.from_numpy(E[:, i]) for i in range(D)])

    def _predict_matrix(self, X, offset=None):
        raise NotImplementedError("Word2Vec scores via transform()")

    def _save_arrays(self):
        return {"vectors": self.vectors}

    def _save_extra_meta(self):
        return {"vocab": self.vocab}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        m.vocab = list(meta["extra"]["vocab"])
        m.vectors = arrays["vectors"]
        m._index = {w: i for i, w in enumerate(m.vocab)}
        return m


class H2OWord2vecEstimator(ModelBuilder):
    algo = "word2vec"
    supervised = False

    def __init__(self, **params):
        merged = dict(W2V_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        p = self.params
        if training_frame is None:
            raise ValueError("Word2Vec needs a training_frame (one words "
                             "column, sentences separated by NA)")
        words = training_frame.vecs[0].to_strings()
        job = Job("word2vec", work=float(max(int(p.get("epochs", 5)), 1)))

        def body(job):
            return self._fit(words, job)

        job.run(body)
        self.model = job.join()
        self.job = job
        from h2o3_tpu import dkv
        dkv.put(self.model.key, "model", self.model)
        return self

    def _fit(self, words: List[Optional[str]], job: Job) -> Word2VecModel:
        p = self.params
        D = int(p.get("vec_size", 100))
        win = int(p.get("window_size", 5))
        epochs = int(p.get("epochs", 5))
        min_freq = int(p.get("min_word_freq", 5))
        K = int(p.get("negative", 5))
        lr0 = float(p.get("init_learning_rate", 0.025))
        seed = int(p.get("seed", -1) or -1)
        rng = np.random.default_rng(None if seed == -1 else seed)
        # vocab
        freq: Dict[str, int] = {}
        for w in words:
            if w:
                freq[w] = freq.get(w, 0) + 1
        vocab = sorted([w for w, c in freq.items() if c >= min_freq],
                       key=lambda w: -freq[w])
        if not vocab:
            raise ValueError(f"no words reach min_word_freq={min_freq}")
        index = {w: i for i, w in enumerate(vocab)}
        V = len(vocab)
        # sentences → id sequences (NA separates)
        sents: List[List[int]] = [[]]
        for w in words:
            if not w:
                if sents[-1]:
                    sents.append([])
            elif w in index:
                sents[-1].append(index[w])
        sents = [s for s in sents if len(s) >= 2]
        counts = np.asarray([freq[w] for w in vocab], np.float64)
        # negative-sampling table: unigram^0.75 (word2vec standard)
        neg_p = counts ** 0.75
        neg_p /= neg_p.sum()
        # frequent-word subsampling threshold (sent_sample_rate)
        samp = float(p.get("sent_sample_rate", 1e-3))
        total = counts.sum()
        keep_p = np.minimum(
            1.0, np.sqrt(samp * total / np.maximum(counts, 1)) +
            samp * total / np.maximum(counts, 1)) if samp > 0 else \
            np.ones(V)
        key = jax.random.PRNGKey(rng.integers(2 ** 31))
        k1, _ = jax.random.split(key)
        scale = 0.5 / D
        Win = jax.random.uniform(k1, (V, D), jnp.float32, -scale, scale)
        Wout = jnp.zeros((V, D), jnp.float32)
        batch = 8192
        for ep in range(epochs):
            centers, contexts = [], []
            for s in sents:
                ids = np.asarray(s)
                if samp > 0:
                    ids = ids[rng.random(len(ids)) < keep_p[ids]]
                for i in range(len(ids)):
                    b = rng.integers(1, win + 1)
                    lo, hi = max(0, i - b), min(len(ids), i + b + 1)
                    for j in range(lo, hi):
                        if j != i:
                            centers.append(ids[i])
                            contexts.append(ids[j])
            if not centers:
                continue
            c = np.asarray(centers, np.int32)
            t = np.asarray(contexts, np.int32)
            perm = rng.permutation(len(c))
            c, t = c[perm], t[perm]
            lr = lr0 * max(1.0 - ep / max(epochs, 1), 0.1)
            # pad the tail batch so one compiled step shape serves all
            n = len(c)
            pad = (-n) % batch
            if pad:
                c = np.concatenate([c, c[:pad]])
                t = np.concatenate([t, t[:pad]])
            negs = rng.choice(V, size=(len(c), K), p=neg_p).astype(np.int32)
            for s0 in range(0, len(c), batch):
                Win, Wout = _sgd_step(
                    Win, Wout, jnp.asarray(c[s0:s0 + batch]),
                    jnp.asarray(t[s0:s0 + batch]),
                    jnp.asarray(negs[s0:s0 + batch]), jnp.float32(lr))
            job.update(1.0)
        model = Word2VecModel(f"w2v_{id(self) & 0xffffff:x}", self.params,
                              _W2VSpec(), vocab,
                              np.asarray(jax.device_get(Win)))
        model.output["vocab_size"] = V
        model.output["vec_size"] = D
        return model

    def _train_impl(self, spec, valid_spec, job: Job):
        raise RuntimeError("Word2Vec overrides train() directly")


class _W2VSpec:
    names: List[str] = []
    is_cat: List[bool] = []
    cat_domains: Dict[str, tuple] = {}
    response = None
    response_domain = None
    nclasses = 1


register_model_class("word2vec", Word2VecModel)
