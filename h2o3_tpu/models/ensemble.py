"""StackedEnsemble — a metalearner over base models' CV holdout preds.

Reference: hex/ensemble/StackedEnsemble.java:38 — collects the base
models' cross-validation holdout predictions into a level-one frame and
trains a metalearner (default GLM) on it; scoring runs the base models
then the metalearner.

TPU re-design: the level-one matrix is assembled from the holdout
predictions each builder already keeps on device (ModelBuilder CV stores
``cross_validation_holdout_predictions``, model_base.py), and the
metalearner is the existing GLM (MXU Gram IRLS) or any registered
builder. Scoring is a batched chain: base `_predict_matrix`s →
metalearner `_predict_matrix` — no per-row dispatch."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu import dkv
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import T_ENUM, Vec
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.model_base import (Model, ModelBuilder, TrainingSpec,
                                        adapt_test_matrix, compute_metrics)
from h2o3_tpu.persist import register_model_class

SE_DEFAULTS: Dict = dict(
    base_models=None, metalearner_algorithm="auto",
    metalearner_params=None, seed=-1,
)


def _base_level_one_cols(model, X_or_holdout, is_holdout: bool):
    """Level-one features from one base model: p(class1) for binomial,
    K probability columns for multinomial, the prediction for
    regression (StackedEnsemble.java levelOneFrame assembly)."""
    if is_holdout:
        out = np.asarray(X_or_holdout)
    else:
        out = np.asarray(jax.device_get(model._predict_matrix(X_or_holdout)))
    if model.nclasses == 2:
        return [out[:, 1]]
    if model.nclasses > 2:
        return [out[:, k] for k in range(model.nclasses)]
    return [out]


class StackedEnsembleModel(Model):
    algo = "stackedensemble"

    def __init__(self, key, params, spec, base_models, meta_model):
        super().__init__(key, params, spec)
        self.base_models = list(base_models)
        self.meta_model = meta_model
        self.ntrees_built = 0

    def _predict_matrix(self, X, offset=None):
        cols = []
        for bm in self.base_models:
            # base models may have trained on a column subset/order —
            # remap by name from the ensemble's feature order
            idx = [self.feature_names.index(n) for n in bm.feature_names]
            Xb = X[:, jnp.asarray(idx)] if idx != list(
                range(len(self.feature_names))) else X
            cols.extend(_base_level_one_cols(bm, Xb, is_holdout=False))
        Z = np.stack(cols, axis=1).astype(np.float32)
        return self.meta_model._predict_matrix(jnp.asarray(Z))

    # persistence: the whole ensemble bundles into ONE artifact — each
    # base model and the metalearner nest via model_to_meta/
    # model_from_meta (the reference exports SE MOJOs the same way:
    # base models embedded)
    def _save_arrays(self):
        from h2o3_tpu.persist import model_to_meta  # noqa: F401
        d = {}
        for i, bm in enumerate(self.base_models):
            for k, v in bm._save_arrays().items():
                d[f"base{i}__{k}"] = v
        for k, v in self.meta_model._save_arrays().items():
            d[f"meta__{k}"] = v
        return d

    def _save_extra_meta(self):
        from h2o3_tpu.persist import model_to_meta
        return {"n_base": len(self.base_models),
                "base_metas": [model_to_meta(bm)
                               for bm in self.base_models],
                "meta_meta": model_to_meta(self.meta_model)}

    @classmethod
    def _restore(cls, meta, arrays):
        from h2o3_tpu.persist import model_from_meta
        m = cls._restore_base(meta)
        ex = meta["extra"]
        m.base_models = []
        for i, bm_meta in enumerate(ex["base_metas"]):
            pre = f"base{i}__"
            sub = {k[len(pre):]: v for k, v in arrays.items()
                   if k.startswith(pre)}
            m.base_models.append(model_from_meta(bm_meta, sub))
        sub = {k[len("meta__"):]: v for k, v in arrays.items()
               if k.startswith("meta__")}
        m.meta_model = model_from_meta(ex["meta_meta"], sub)
        m.ntrees_built = 0
        return m


def _level_one_frame(base_models, y_codes, w, nrow, response_domain):
    cols: List[np.ndarray] = []
    names: List[str] = []
    for bi, bm in enumerate(base_models):
        hold = bm.output.get("cross_validation_holdout_predictions")
        if hold is None:
            raise ValueError(
                f"base model {bm.key} has no cross-validation holdout "
                f"predictions — train base models with nfolds >= 2 "
                f"(StackedEnsemble requires CV holdouts)")
        parts = _base_level_one_cols(bm, hold, is_holdout=True)
        for k, c in enumerate(parts):
            cols.append(np.asarray(c, dtype=np.float32)[:nrow])
            names.append(f"m{bi}_p{k}")
    data = {n: c for n, c in zip(names, cols)}
    if response_domain:
        data["__response"] = np.asarray(
            [response_domain[int(c)] for c in y_codes[:nrow]], dtype=object)
    else:
        data["__response"] = np.asarray(y_codes[:nrow], dtype=np.float32)
    fr = Frame(list(data.keys()),
               [Vec.from_numpy(v) for v in data.values()])
    return fr, names


class H2OStackedEnsembleEstimator(ModelBuilder):
    algo = "stackedensemble"

    def __init__(self, **params):
        merged = dict(SE_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _resolve_base_models(self):
        out = []
        for b in self.params.get("base_models") or []:
            if isinstance(b, str):
                out.append(dkv.get(b, "model"))
            elif isinstance(b, Model):
                out.append(b)
            elif hasattr(b, "model") and b.model is not None:
                out.append(b.model)
            else:
                raise ValueError(f"bad base model reference: {b!r}")
        if len(out) < 2:
            raise ValueError("StackedEnsemble needs >= 2 base models")
        return out

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        base = self._resolve_base_models()
        nrow = spec.nrow
        y_host = np.asarray(jax.device_get(spec.y))
        w_host = np.asarray(jax.device_get(spec.w))
        l1fr, znames = _level_one_frame(base, y_host, w_host, nrow,
                                        spec.response_domain)
        algo = (self.params.get("metalearner_algorithm") or "auto").lower()
        mp = dict(self.params.get("metalearner_params") or {})
        if algo in ("auto", "glm"):
            from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
            mp.setdefault("family",
                          "binomial" if spec.nclasses == 2 else
                          "multinomial" if spec.nclasses > 2 else "gaussian")
            mp.setdefault("alpha", 0.0)
            mp.setdefault("Lambda", 1e-5)
            if spec.nclasses <= 2:
                mp.setdefault("non_negative", True)  # reference AUTO metalearner
            meta_est = H2OGeneralizedLinearEstimator(**mp)
        elif algo == "gbm":
            from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
            meta_est = H2OGradientBoostingEstimator(**mp)
        elif algo == "drf":
            from h2o3_tpu.models.drf import H2ORandomForestEstimator
            meta_est = H2ORandomForestEstimator(**mp)
        elif algo == "deeplearning":
            from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
            meta_est = H2ODeepLearningEstimator(**mp)
        else:
            raise ValueError(f"unsupported metalearner '{algo}'")
        meta_est.train(x=znames, y="__response", training_frame=l1fr)
        if meta_est.job.status == "FAILED":
            raise RuntimeError(meta_est.job.exception)
        meta = meta_est.model
        model = StackedEnsembleModel(
            f"se_{id(self) & 0xffffff:x}", self.params, spec, base, meta)
        # training metrics: metalearner predictions over the level-one frame
        out = model._predict_matrix(spec.X)
        model.training_metrics = compute_metrics(
            out, spec.y, spec.w, spec.nclasses, spec.response_domain)
        if valid_spec is not None:
            vout = model._predict_matrix(valid_spec.X)
            model.validation_metrics = compute_metrics(
                vout, valid_spec.y, valid_spec.w, spec.nclasses,
                spec.response_domain)
        return model


register_model_class("stackedensemble", StackedEnsembleModel)
