"""Uplift DRF — treatment-effect random forest.

Reference: hex/tree/uplift/UpliftDRF.java:25 — random forest whose
splits maximize a divergence (KL / euclidean / chi_squared) between the
treatment and control response distributions (DHistogram._valsUplift,
hex/tree/DHistogram.java:79-86); a leaf predicts
uplift = P(y=1|treat) − P(y=1|control).

TPU re-design: level-synchronous growth like the GBM stack, but the
histogram carries FOUR accumulators (w_treat, wy_treat, w_ctrl, wy_ctrl)
scattered into a [nodes·F·(B+1), 4] table in one .at[].add pass per
level; divergence gains evaluate on the prefix-summed table entirely on
device. Row subsampling per tree, random feature subset per level (the
reference draws mtries per split; per-level is the SPMD-friendly
equivalent and is noted as a deviation)."""
from __future__ import annotations

import time
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.model_base import (Model, ModelBuilder, TrainingSpec,
                                        adapt_test_matrix)
from h2o3_tpu.ops.binning import bin_matrix, split_threshold
from h2o3_tpu.persist import register_model_class

UPLIFT_DEFAULTS: Dict = dict(
    ntrees=50, max_depth=8, sample_rate=0.632, mtries=-1,
    uplift_metric="kl", treatment_column=None, nbins=255, seed=-1,
    min_rows=10,
)


def _divergence(pt, pc, metric: str):
    eps = 1e-6
    pt = jnp.clip(pt, eps, 1 - eps)
    pc = jnp.clip(pc, eps, 1 - eps)
    if metric == "kl":
        return (pt * jnp.log(pt / pc)
                + (1 - pt) * jnp.log((1 - pt) / (1 - pc)))
    if metric == "euclidean":
        return 2.0 * (pt - pc) ** 2
    if metric == "chi_squared":
        return (pt - pc) ** 2 / pc + (pc - pt) ** 2 / (1 - pc)
    raise ValueError(f"unknown uplift_metric '{metric}'")


def _level_step(codes, y, treat, w, nid, level_mask, feat_mask, base, N,
                B, metric, min_rows):
    """One level: histogram → divergence gains → best split per node.
    Returns (split_feat[N], split_bin[N], can_split[N], node stats)."""
    rows, F = codes.shape
    local = nid - base
    in_lvl = (local >= 0) & (local < N) & level_mask
    lid = jnp.clip(local, 0, N - 1)
    wt = w * treat
    wc = w * (1.0 - treat)
    vals = jnp.stack([wt, wt * y, wc, wc * y], axis=1)  # [rows, 4]
    vals = jnp.where(in_lvl[:, None], vals, 0.0)
    flat = (lid[:, None] * F + jnp.arange(F)[None, :]) * (B + 1) + codes
    hist = jnp.zeros((N * F * (B + 1), 4), jnp.float32)
    hist = hist.at[flat.reshape(-1)].add(
        jnp.repeat(vals, F, axis=0).reshape(rows * F, 4))
    hist = hist.reshape(N, F, B + 1, 4)
    cum = jnp.cumsum(hist, axis=2)                     # prefix over bins
    tot = cum[:, :, -1, :]                             # [N, F, 4]
    # candidate split t = 1..B-1: left = bins < t PLUS the NA bin — the
    # router and the scorer both send NA left, so the gain must be
    # evaluated on the same partition
    na = hist[:, :, -1, :]                             # [N, F, 4]
    left = cum[:, :, :-1, :] + na[:, :, None, :]       # [N, F, B, 4]
    right = tot[:, :, None, :] - left
    def p(v):
        return v[..., 1] / jnp.maximum(v[..., 0], 1e-12), \
               v[..., 3] / jnp.maximum(v[..., 2], 1e-12)
    n_l = left[..., 0] + left[..., 2]
    n_r = right[..., 0] + right[..., 2]
    n_tot = jnp.maximum(n_l + n_r, 1e-12)
    pt_l, pc_l = p(left)
    pt_r, pc_r = p(right)
    pt_n, pc_n = p(tot)
    d_node = _divergence(pt_n, pc_n, metric)[:, :, None]
    d_split = (n_l / n_tot) * _divergence(pt_l, pc_l, metric) + \
              (n_r / n_tot) * _divergence(pt_r, pc_r, metric)
    ok = ((left[..., 0] > 0) & (left[..., 2] > 0)
          & (right[..., 0] > 0) & (right[..., 2] > 0)
          & (n_l >= min_rows) & (n_r >= min_rows))
    gain = jnp.where(ok & feat_mask[None, :, None],
                     d_split - d_node, -jnp.inf)       # [N, F, B]
    gflat = gain.reshape(N, -1)
    best = jnp.argmax(gflat, axis=1)
    bgain = jnp.take_along_axis(gflat, best[:, None], axis=1)[:, 0]
    bf = best // gain.shape[2]
    bb = best % gain.shape[2] + 1                      # split bin ≥ 1
    can = jnp.isfinite(bgain) & (bgain > 1e-9)
    return bf.astype(jnp.int32), bb.astype(jnp.int32), can, tot


class UpliftRandomForestModel(Model):
    algo = "upliftdrf"

    def __init__(self, key, params, spec, trees, depth):
        super().__init__(key, params, spec)
        self._feat = jnp.asarray(trees["feat"])        # [T, M]
        self._thr = jnp.asarray(trees["thr"])
        self._is_split = jnp.asarray(trees["is_split"])
        self._pt = jnp.asarray(trees["pt"])            # leaf P(y|treat)
        self._pc = jnp.asarray(trees["pc"])
        self.max_depth = depth

    def _walk(self, X):
        rows = X.shape[0]
        T = self._feat.shape[0]

        def one(carry, t):
            nid = jnp.zeros(rows, jnp.int32)
            for _ in range(self.max_depth):
                f = self._feat[t][nid]
                s = self._is_split[t][nid]
                th = self._thr[t][nid]
                x = jnp.take_along_axis(X, jnp.maximum(f, 0)[:, None],
                                        axis=1)[:, 0]
                go_right = jnp.where(jnp.isnan(x), False, x >= th)
                nid = jnp.where(s, 2 * nid + 1 + go_right.astype(jnp.int32),
                                nid)
            return carry, (self._pt[t][nid], self._pc[t][nid])

        _, (pt, pc) = jax.lax.scan(one, None, jnp.arange(T))
        return pt.mean(axis=0), pc.mean(axis=0)

    def _predict_matrix(self, X, offset=None):
        pt, pc = self._walk(X)
        return pt - pc

    def predict(self, frame: Frame) -> Frame:
        X = adapt_test_matrix(self, frame)
        pt, pc = self._walk(X)
        nrow = frame.nrow
        u = np.asarray(jax.device_get(pt - pc))[:nrow]
        pt = np.asarray(jax.device_get(pt))[:nrow]
        pc = np.asarray(jax.device_get(pc))[:nrow]
        return Frame(["uplift_predict", "p_y1_ct1", "p_y1_ct0"],
                     [Vec.from_numpy(u.astype(np.float32)),
                      Vec.from_numpy(pt.astype(np.float32)),
                      Vec.from_numpy(pc.astype(np.float32))])

    def _save_arrays(self):
        return {k: np.asarray(jax.device_get(getattr(self, f"_{k}")))
                for k in ("feat", "thr", "is_split", "pt", "pc")}

    def _save_extra_meta(self):
        return {"max_depth": self.max_depth}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        m.max_depth = meta["extra"]["max_depth"]
        for k in ("feat", "thr", "is_split", "pt", "pc"):
            setattr(m, f"_{k}", jnp.asarray(arrays[k]))
        return m


class H2OUpliftRandomForestEstimator(ModelBuilder):
    algo = "upliftdrf"

    def __init__(self, **params):
        merged = dict(UPLIFT_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        tc = self.params.get("treatment_column")
        if not tc:
            raise ValueError("UpliftDRF needs treatment_column")
        if x is not None and tc not in x:
            x = list(x) + [tc]
        return super().train(x=x, y=y, training_frame=training_frame,
                             validation_frame=validation_frame, **kw)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        p = self.params
        if spec.nclasses != 2:
            raise ValueError("UpliftDRF needs a binary response")
        tc = p["treatment_column"]
        if tc not in spec.names:
            raise ValueError(f"treatment_column '{tc}' not in columns")
        ti = spec.names.index(tc)
        treat = jnp.where(jnp.isnan(spec.X[:, ti]), 0.0,
                          spec.X[:, ti]).astype(jnp.float32)
        treat = (treat > 0).astype(jnp.float32)
        keep = [i for i in range(len(spec.names)) if i != ti]
        names = [spec.names[i] for i in keep]
        is_cat = [spec.is_cat[i] for i in keep]
        Xf = spec.X[:, jnp.asarray(keep)]
        F = len(keep)
        depth = int(p.get("max_depth", 8))
        ntrees = int(p.get("ntrees", 50))
        metric = (p.get("uplift_metric") or "kl").lower()
        min_rows = float(p.get("min_rows", 10))
        nbins = int(p.get("nbins", 255))
        bm = bin_matrix(Xf, names, is_cat, spec.nrow, nbins=nbins)
        codes = jnp.asarray(bm.codes.rm).astype(jnp.int32)
        B = bm.n_bins
        y = spec.y.astype(jnp.float32)
        w = spec.w
        seed = int(p.get("seed", -1) or -1)
        rng = np.random.default_rng(None if seed == -1 else seed)
        mtries = int(p.get("mtries", -1))
        if mtries <= 0:
            mtries = max(1, int(np.sqrt(F)))
        sample_rate = float(p.get("sample_rate", 0.632))
        M = 2 ** (depth + 1) - 1
        all_trees = {k: np.zeros((ntrees, M), dt) for k, dt in
                     (("feat", np.int32), ("thr", np.float32),
                      ("is_split", bool), ("pt", np.float32),
                      ("pc", np.float32))}
        built = 0
        step = jax.jit(_level_step,
                       static_argnames=("base", "N", "B", "metric",
                                        "min_rows"))
        for t in range(ntrees):
            mask = jnp.asarray(
                (rng.random(codes.shape[0]) < sample_rate))
            level_mask = mask & (w > 0)
            nid = jnp.zeros(codes.shape[0], jnp.int32)
            feat = np.zeros(M, np.int32)
            thr = np.zeros(M, np.float32)
            is_split = np.zeros(M, bool)
            for d in range(depth):
                N = 2 ** d
                base = N - 1
                fm = np.zeros(F, bool)
                fm[rng.choice(F, size=min(mtries, F), replace=False)] = True
                bf, bb, can, tot = step(codes, y, treat, w, nid, level_mask,
                                        jnp.asarray(fm), base, N, B, metric,
                                        min_rows)
                bf_h = np.asarray(jax.device_get(bf))
                bb_h = np.asarray(jax.device_get(bb))
                can_h = np.asarray(jax.device_get(can))
                idx = base + np.arange(N)
                feat[idx] = bf_h
                is_split[idx] = can_h
                for i in range(N):
                    thr[idx[i]] = split_threshold(bm, int(bf_h[i]),
                                                  int(bb_h[i]))
                # route rows (codes-space: right ⇔ code >= split_bin;
                # NA bin B always ≥ any split bin ⇒ NA routes RIGHT in
                # code space, so scoring must send NaN right too — but
                # the walk sends NaN left; keep them consistent by
                # sending the NA bin LEFT here:
                node_f = jnp.asarray(bf_h)[jnp.clip(nid - base, 0, N - 1)]
                node_b = jnp.asarray(bb_h)[jnp.clip(nid - base, 0, N - 1)]
                node_can = jnp.asarray(can_h)[jnp.clip(nid - base, 0, N - 1)]
                c = jnp.take_along_axis(codes, node_f[:, None], axis=1)[:, 0]
                is_na = c >= B
                go_right = jnp.where(is_na, False, c >= node_b)
                local = nid - base
                route = (local >= 0) & (local < N) & node_can
                nid = jnp.where(route,
                                2 * nid + 1 + go_right.astype(jnp.int32),
                                nid)
            # leaf stats: one final histogram at the deepest level grid
            wt = w * treat * level_mask
            wc = w * (1.0 - treat) * level_mask
            cnt_t = jnp.zeros(M, jnp.float32).at[nid].add(wt)
            sum_t = jnp.zeros(M, jnp.float32).at[nid].add(wt * y)
            cnt_c = jnp.zeros(M, jnp.float32).at[nid].add(wc)
            sum_c = jnp.zeros(M, jnp.float32).at[nid].add(wc * y)
            pt_leaf = np.array(jax.device_get(
                sum_t / jnp.maximum(cnt_t, 1e-12)))   # writable copy
            pc_leaf = np.array(jax.device_get(
                sum_c / jnp.maximum(cnt_c, 1e-12)))
            ct_h = np.asarray(jax.device_get(cnt_t))
            cc_h = np.asarray(jax.device_get(cnt_c))
            # empty root (it split, so no rows stopped there) falls back
            # to the global rates; children then inherit down the chain
            if ct_h[0] == 0:
                pt_leaf[0] = float(jax.device_get(
                    (wt * y).sum() / jnp.maximum(wt.sum(), 1e-12)))
            if cc_h[0] == 0:
                pc_leaf[0] = float(jax.device_get(
                    (wc * y).sum() / jnp.maximum(wc.sum(), 1e-12)))
            # propagate parent stats into empty nodes so the walk always
            # lands on a populated value
            for m in range(1, M):
                parent = (m - 1) // 2
                if ct_h[m] == 0:
                    pt_leaf[m] = pt_leaf[parent]
                if cc_h[m] == 0:
                    pc_leaf[m] = pc_leaf[parent]
            all_trees["feat"][t] = feat
            all_trees["thr"][t] = thr
            all_trees["is_split"][t] = is_split
            all_trees["pt"][t] = pt_leaf
            all_trees["pc"][t] = pc_leaf
            built = t + 1
            job.set_progress(built / ntrees)
            if job.cancel_requested:
                break
        # keep only the trees actually built (cancel mid-run must not
        # average in preallocated zero trees)
        all_trees = {k: v[:built] for k, v in all_trees.items()}
        sub_spec = TrainingSpec(
            X=Xf, y=spec.y, w=w, offset=None, names=names, is_cat=is_cat,
            cat_domains={k: v for k, v in spec.cat_domains.items()
                         if k in names},
            nrow=spec.nrow, response=spec.response,
            response_domain=spec.response_domain, nclasses=2)
        model = UpliftRandomForestModel(
            f"uplift_{id(self) & 0xffffff:x}", self.params, sub_spec,
            all_trees, depth)
        # Qini-flavoured training summary: mean uplift by predicted sign
        u = np.asarray(jax.device_get(model._predict_matrix(Xf)))
        live = np.asarray(jax.device_get(w)) > 0
        model.output["mean_uplift_prediction"] = float(u[live].mean())
        # full metrics OBJECT (hex/ModelMetricsBinomialUplift + AUUC.java
        # flavors/thresholds); the scalar output rides the same pass
        from h2o3_tpu.models.metrics import make_uplift_metrics
        model.training_metrics = make_uplift_metrics(
            u, np.asarray(jax.device_get(y)),
            np.asarray(jax.device_get(treat)),
            weights=np.asarray(jax.device_get(w)))
        model.output["auuc"] = model.training_metrics.auuc
        return model


def _auuc(uplift, y, treat, bins: int = 1000) -> float:
    """Qini-flavor AUUC — delegates to the maintained implementation
    (h2o3_tpu/models/metrics.py make_uplift_metrics)."""
    from h2o3_tpu.models.metrics import make_uplift_metrics
    return make_uplift_metrics(uplift, y, treat, nbins=bins).auuc


register_model_class("upliftdrf", UpliftRandomForestModel)
