"""DRF — distributed random forest on the shared histogram tree machinery.

Reference: hex/tree/drf/DRF.java:30 over hex/tree/SharedTree.java — per-node
mtries feature subsets, row sampling per tree (default 0.632), OOB
("out-of-bag") scoring reported as the training metrics, class-probability
leaves (each tree's leaf stores the weighted class fraction / mean
response, not a boosting step).

TPU re-design: trees are independent, so a whole chunk builds inside one
shard_mapped lax.scan (like GBM's chunk step, models/gbm.py) with the
histogram psum over the 'data' mesh axis; mtries is a per-node random
feature mask drawn inside grow_tree (models/tree.py). Leaf values come
from the same Newton formula with (g, h) = (-y·w, w) ⇒ leaf = weighted
mean of the (indicator) response — the variance-reduction criterion.
Static-shape note: trees are complete binary arrays, so max_depth is
capped at 16 (the reference default is 20, practically limited by
min_rows; histograms at depth d need 2^(d-1)·F·(B+1)·3 floats).
"""
from __future__ import annotations

import time
from functools import lru_cache, partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from h2o3_tpu import telemetry
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.model_base import (Model, ModelBuilder, ScoreKeeper,
                                        TrainingSpec, compute_metrics)
from h2o3_tpu.models.tree import (ADAPTIVE_HIST_TYPES,
                                  TreeConfig, adaptive_feasible,
                                  adaptive_setup, binned_feasible,
                                  packed_bins_upper_bound,
                                  chunk_bucket,
                                  collect_chunk_trees, grow_tree,
                                  grow_tree_adaptive, grow_tree_binned,
                                  packed_codes_requested,
                                  predict_raw_stacked)
from h2o3_tpu.ops.binning import (CodesView, bin_matrix_device,
                                  make_codes_view, pack_codes,
                                  packed_codes_record)
from h2o3_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS, current_mesh,
                                    n_data_shards, n_model_shards,
                                    spmd_enabled)
from h2o3_tpu.persist import register_model_class
from h2o3_tpu.resilience import resilient_device_put, retry_transient

MAX_DEPTH_CAP = 16

DRF_DEFAULTS: Dict = dict(
    # default depth 10, not the reference's 20: trees are complete binary
    # arrays (static shapes for XLA), so depth-d histograms/compile cost
    # scale with 2^d; the reference's deep default relies on dynamic node
    # allocation (hex/tree/DTree.java) and min_rows pruning
    ntrees=50, max_depth=10, min_rows=1.0, nbins=20, nbins_cats=1024,
    mtries=-1, sample_rate=0.632, sample_rate_per_class=None,
    col_sample_rate_per_tree=1.0, col_sample_rate_change_per_level=1.0,
    min_split_improvement=1e-5, seed=-1, histogram_type="uniform_adaptive",
    score_tree_interval=0, stopping_rounds=0, stopping_metric="auto",
    stopping_tolerance=1e-3, hist_kernel="auto", reg_lambda=0.0,
    # continue-training + in-training checkpoints (formerly a
    # compat_params warn entry): forest trees are independent, so a
    # resumed train with the same seed rebuilds the remaining trees
    # bit-identically; OOB accumulators ride the checkpoint as resume
    # state so training metrics match the uninterrupted run
    checkpoint=None, in_training_checkpoints_dir=None,
    in_training_checkpoints_tree_interval=1,
    # MXU histogram precision + packed binned-code hot path — same
    # semantics as the GBM params (models/gbm.py GBM_DEFAULTS)
    histogram_precision="auto", packed_codes="auto",
)


from h2o3_tpu.models.treeshap import TreeScoringOptionsMixin  # noqa: E402


class DRFModel(TreeScoringOptionsMixin, Model):
    algo = "drf"

    def __init__(self, key, params, spec, trees_host, edges, n_bins,
                 max_depth, ntrees_built, nclasses):
        super().__init__(key, params, spec)
        self.edges = edges
        self.n_bins = n_bins
        self.max_depth = max_depth
        self.ntrees_built = ntrees_built
        self._K = max(nclasses, 1) if nclasses > 2 else 1
        self._feat = jnp.asarray(trees_host["feat"])
        self._thr = jnp.asarray(trees_host["thr"])
        self._na_left = jnp.asarray(trees_host["na_left"])
        self._is_split = jnp.asarray(trees_host["is_split"])
        self._value = jnp.asarray(trees_host["value"])
        nw = trees_host.get("node_w")
        self._node_w = jnp.asarray(nw) if nw is not None else None

    def _contrib_scale(self):
        # forest prediction = MEAN over trees, so each tree's SHAP values
        # scale by 1/T (contributions live in probability/response space)
        return 1.0 / max(self.ntrees_built, 1)

    def staged_predict_proba(self, frame):
        # cumulative margins are a boosting concept; DRF trees are
        # independent probability votes (reference restricts this to GBM)
        raise ValueError("staged_predict_proba is not supported for DRF "
                         "(GBM/XGBoost only, hex/Model.java)")

    def _predict_matrix(self, X, offset=None):
        contribs = predict_raw_stacked(X, self._feat, self._thr, self._na_left,
                                       self._is_split, self._value,
                                       self.max_depth)
        T = self.ntrees_built
        if self.nclasses <= 1:
            return contribs.mean(axis=1)
        if self.nclasses == 2:
            p1 = jnp.clip(contribs.mean(axis=1), 0.0, 1.0)
            return jnp.stack([1.0 - p1, p1], axis=1)
        per_class = jnp.clip(
            contribs.reshape(X.shape[0], T, self._K).mean(axis=1), 0.0, 1.0)
        return per_class / jnp.maximum(per_class.sum(axis=1, keepdims=True),
                                       1e-12)

    def varimp(self, use_pandas=False):
        return self.output.get("variable_importances")

    # -- persistence ----------------------------------------------------

    def _save_arrays(self):
        # ONE counted pytree fetch (the raw per-array device_gets were
        # invisible to d2h budgets — PR-11 transfer-seam burn-down)
        host = telemetry.device_get(
            {"feat": self._feat, "thr": self._thr,
             "na_left": self._na_left, "is_split": self._is_split,
             "value": self._value})
        d = {k: np.asarray(v) for k, v in host.items()}
        if self._node_w is not None:
            d["node_w"] = np.asarray(telemetry.device_get(self._node_w))
        # in-training checkpoint resume state: the OOB accumulators at
        # the committed tree count, so resumed training metrics equal
        # the uninterrupted run's
        for attr, name in (("_resume_oob_num", "resume_oob_num"),
                           ("_resume_oob_cnt", "resume_oob_cnt"),
                           ("_resume_sig", "resume_sig")):
            v = getattr(self, attr, None)
            if v is not None:
                d[name] = np.asarray(v)
        for i, e in enumerate(self.edges):
            d[f"edge_{i}"] = np.asarray(e)
        return d

    def _save_extra_meta(self):
        return {"n_bins": self.n_bins, "max_depth": self.max_depth,
                "ntrees_built": self.ntrees_built,
                "n_edges": len(self.edges)}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        m.n_bins = ex["n_bins"]
        m.max_depth = ex["max_depth"]
        m.ntrees_built = ex["ntrees_built"]
        m.edges = [arrays[f"edge_{i}"] for i in range(ex["n_edges"])]
        m._K = max(m.nclasses, 1) if m.nclasses > 2 else 1
        m._feat = jnp.asarray(arrays["feat"])
        m._thr = jnp.asarray(arrays["thr"])
        m._na_left = jnp.asarray(arrays["na_left"])
        m._is_split = jnp.asarray(arrays["is_split"])
        m._value = jnp.asarray(arrays["value"])
        m._node_w = (jnp.asarray(arrays["node_w"])
                     if "node_w" in arrays else None)
        m._resume_oob_num = (np.asarray(arrays["resume_oob_num"])
                             if "resume_oob_num" in arrays else None)
        m._resume_oob_cnt = (np.asarray(arrays["resume_oob_cnt"])
                             if "resume_oob_cnt" in arrays else None)
        m._resume_sig = (np.asarray(arrays["resume_sig"])
                         if "resume_sig" in arrays else None)
        return m


def _drf_chunk_body(codes_rm, codes_t, y, w, oob_num, oob_cnt, base_key,
                    root_lo, root_hi, nb_f, start_idx, n_active, sample_rate,
                    col_rate, *, cfg, K,
                    sample_rate_per_class, chunk, has_t, adaptive, binned,
                    axis_name, model_axis=None):
    """A chunk of independent forest trees per data shard; OOB sums ride
    the scan carry (reference: DRF's OOB rows are scored by the trees that
    did not sample them — hex/tree/drf/DRF.java OOB machinery).

    ``chunk`` is a padding bucket (see gbm._gbm_chunk_body): the traced
    ``n_active`` masks trailing trees out of the OOB sums and the driver
    drops them at finalize; sample/col rates ride as traced scalars so
    grid variants share one executable."""
    codes = CodesView(rm=codes_rm, t=codes_t if has_t else None)
    F = codes_rm.shape[1]
    shard = jax.lax.axis_index(axis_name) if axis_name else 0

    def build(gv, hv, wt, col_mask, key_m):
        if adaptive:
            return grow_tree_adaptive(codes_rm, gv, hv, wt, cfg, col_mask,
                                      root_lo, root_hi, axis_name=axis_name,
                                      key=key_m, nb_f=nb_f,
                                      model_axis=model_axis)
        if binned:
            return grow_tree_binned(codes_rm, gv, hv, wt, cfg, col_mask,
                                    axis_name=axis_name, key=key_m,
                                    model_axis=model_axis, ct=codes.t)
        return grow_tree(codes, gv, hv, wt, cfg, col_mask,
                         axis_name=axis_name, key=key_m,
                         model_axis=model_axis)

    def one_tree(carry, i):
        oob_num, oob_cnt = carry
        key = jax.random.fold_in(base_key, start_idx + i)
        key_r, key_c, key_m = jax.random.split(key, 3)
        key_r = jax.random.fold_in(key_r, shard)
        if sample_rate_per_class is not None:
            # per-class bootstrap rates (hex/tree/SharedTree.java:210)
            srpc = jnp.asarray(sample_rate_per_class, jnp.float32)
            thr = srpc[jnp.clip(y.astype(jnp.int32), 0,
                                len(sample_rate_per_class) - 1)]
            sampled = jax.random.uniform(key_r, w.shape) < thr
        else:
            sampled = jax.random.uniform(key_r, w.shape) < sample_rate
        wt = w * sampled
        col_mask = jax.random.uniform(key_c, (F,)) < col_rate
        live_oob = (w > 0) & ~sampled & (i < n_active)
        trees = []
        if K == 1:
            yf = y.astype(jnp.float32)
            tree, nid = build(-(yf * wt), wt, wt, col_mask, key_m)
            pred = tree["value"][nid]
            oob_num = oob_num + jnp.where(live_oob, pred, 0.0)
            oob_cnt = oob_cnt + live_oob.astype(jnp.float32)
            trees.append(tree)
        else:
            preds = []
            for k in range(K):
                yk = (y == k).astype(jnp.float32)
                tree, nid = build(-(yk * wt), wt, wt, col_mask,
                                  jax.random.fold_in(key_m, k))
                preds.append(tree["value"][nid])
                trees.append(tree)
            pk = jnp.stack(preds, axis=1)
            oob_num = oob_num + jnp.where(live_oob[:, None], pk, 0.0)
            oob_cnt = oob_cnt + live_oob.astype(jnp.float32)
        stacked = {kk: jnp.stack([t[kk] for t in trees]) for kk in trees[0]}
        return (oob_num, oob_cnt), stacked

    (oob_num, oob_cnt), chunk_trees = jax.lax.scan(
        one_tree, (oob_num, oob_cnt), jnp.arange(chunk))
    return oob_num, oob_cnt, chunk_trees


@lru_cache(maxsize=128)
def _compiled_drf_chunk(mesh, cfg, K, sample_rate_per_class, chunk, has_t,
                        adaptive, binned=False, donate=False):
    model_axis = (MODEL_AXIS
                  if mesh.shape[MODEL_AXIS] > 1 and spmd_enabled()
                  else None)
    body = partial(_drf_chunk_body, cfg=cfg, K=K,
                   sample_rate_per_class=sample_rate_per_class,
                   chunk=chunk, has_t=has_t,
                   adaptive=adaptive, binned=binned, axis_name=DATA_AXIS,
                   model_axis=model_axis)
    in_specs = (P(DATA_AXIS),
                P(None, DATA_AXIS) if has_t else P(DATA_AXIS),
                P(DATA_AXIS), P(DATA_AXIS),
                P(DATA_AXIS), P(DATA_AXIS),
                P(), P(), P(), P(), P(), P(), P(), P())
    out_specs = (P(DATA_AXIS), P(DATA_AXIS), P())
    f = jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
    # the OOB accumulators are write-once-per-chunk carries: donate them
    # so the device updates in place instead of double-buffering
    return jax.jit(f, donate_argnums=(4, 5) if donate else ())


class H2ORandomForestEstimator(ModelBuilder):
    algo = "drf"

    def __init__(self, **params):
        merged = dict(DRF_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job) -> DRFModel:
        p = self.params
        if spec.offset is not None:
            raise NotImplementedError("DRF does not support offset_column "
                                      "(matching hex/tree/drf/DRF.java)")
        K = spec.nclasses if spec.nclasses > 2 else 1
        depth = int(p["max_depth"])
        if depth > MAX_DEPTH_CAP:
            raise ValueError(
                f"max_depth {depth} exceeds the static-tree cap "
                f"{MAX_DEPTH_CAP} (complete-binary-array trees; the "
                f"reference's default 20 relies on dynamic node allocation)")
        nbins = int(p["nbins"])
        hist_type = (p.get("histogram_type") or "uniform_adaptive").lower()
        # packed binned-code hot path (ISSUE 12) — same gating as GBM:
        # default wherever compiled pallas runs; 'random' keeps the
        # adaptive kernel (per-tree grid phase needs per-level rebinning)
        packed_req = packed_codes_requested(p) and hist_type != "random"
        if (packed_req
                and not binned_feasible(
                    packed_bins_upper_bound(spec, p), spec.n_features,
                    depth)
                and hist_type in ADAPTIVE_HIST_TYPES
                and adaptive_feasible(spec, p, depth)):
            # cheap pre-gate from the cat domains (see models/gbm.py)
            packed_req = False
        adaptive = (hist_type in ADAPTIVE_HIST_TYPES
                    and not packed_req
                    and adaptive_feasible(spec, p, depth))
        packed = False
        pc = None
        mtries = int(p.get("mtries", -1) or -1)
        F = spec.n_features
        if mtries <= 0:
            # reference defaults: sqrt(p) classification, p/3 regression
            mtries = (max(1, int(np.sqrt(F))) if spec.nclasses > 1
                      else max(1, F // 3))
        if adaptive:
            bm = None
            cfg, root_lo, root_hi, nb_f = adaptive_setup(
                spec, p, depth, mtries=min(mtries, F))
        else:
            # device-side sketch (ops/binning.bin_matrix_device): no
            # device_get of the full X
            # packed mode skips the int32 transposed operand — the
            # packed layouts supersede it (see models/gbm.py)
            bm = bin_matrix_device(spec.X, spec.names,
                                   spec.is_cat, spec.nrow, nbins=max(nbins, 2),
                                   nbins_cats=int(p["nbins_cats"]),
                                   histogram_type=hist_type,
                                   with_t=not packed_req)
            packed = (packed_req
                      and binned_feasible(bm.n_bins, bm.n_features, depth))
            if (not packed and packed_req
                    and hist_type in ADAPTIVE_HIST_TYPES
                    and adaptive_feasible(spec, p, depth)):
                # packing infeasible (sketch bin count past the 254-lane
                # cap / VMEM): fall back to the fused adaptive kernel,
                # not the slow matmul path (see models/gbm.py)
                adaptive = True
                bm = None
                cfg, root_lo, root_hi, nb_f = adaptive_setup(
                    spec, p, depth, mtries=min(mtries, F))
            if packed:
                pc = pack_codes(bm)
                # free the int32 code view (see models/gbm.py)
                bm.codes = CodesView(rm=pc.rm, t=None)
            if not adaptive:
                cfg = TreeConfig(max_depth=depth, n_bins=bm.n_bins,
                                 n_features=bm.n_features,
                                 min_rows=float(p["min_rows"]),
                                 min_split_improvement=float(p["min_split_improvement"]),
                                 reg_lambda=float(p.get("reg_lambda", 0.0)),
                                 mtries=min(mtries, bm.n_features),
                                 col_rate_change=float(
                                     p.get("col_sample_rate_change_per_level",
                                           1.0) or 1.0),
                                 hist_method=p.get("hist_kernel", "auto"),
                                 histogram_precision=str(
                                     p.get("histogram_precision",
                                           "auto")).lower())
                root_lo = jnp.zeros(cfg.n_features, jnp.float32)
                root_hi = jnp.zeros(cfg.n_features, jnp.float32)
                nb_f = jnp.zeros(cfg.n_features, jnp.float32)
        mesh = current_mesh()
        nd = n_data_shards(mesh)
        padded = spec.X.shape[0]
        if padded % nd != 0:
            raise ValueError(f"padded rows {padded} not divisible by the "
                             f"{nd}-shard data axis")
        seed = int(p.get("seed", -1) or -1)
        key = jax.random.PRNGKey(seed if seed != -1
                                 else int(time.time() * 1e3) % (2 ** 31))
        srpc = self.validate_sample_rate_per_class(spec)
        ntrees = int(p["ntrees"])
        prior = self._resolve_checkpoint(spec)
        start_trees = prior.ntrees_built if prior is not None else 0
        ntrees_new = ntrees - start_trees
        sample_rate = float(p["sample_rate"])
        col_rate = float(p.get("col_sample_rate_per_tree", 1.0))
        Xtr = spec.X if adaptive else (pc.rm if packed else bm.codes.rm)
        if packed:
            has_t = pc.t is not None
            codes_t_arg = pc.t if has_t else Xtr
        else:
            has_t = (not adaptive) and bm.codes.t is not None
            codes_t_arg = bm.codes.t if has_t else Xtr
        # data-sharded from the start so every chunk (not just the 2nd+)
        # sees identically-sharded carry operands — one executable per
        # bucket (see the margin pinning note in models/gbm.py)
        from jax.sharding import NamedSharding
        rows_sh = NamedSharding(mesh, P(DATA_AXIS))
        # checkpoint continuation resumes the OOB accumulators saved
        # with the prior (else new trees' OOB would be averaged from a
        # zeroed state and training metrics would drift from the
        # uninterrupted run)
        from h2o3_tpu.models.gbm import _spec_signature
        rn = getattr(prior, "_resume_oob_num", None) \
            if prior is not None else None
        rc = getattr(prior, "_resume_oob_cnt", None) \
            if prior is not None else None
        psig = getattr(prior, "_resume_sig", None) \
            if prior is not None else None
        # the saved OOB state belongs to a specific training frame —
        # applying it to different data would silently skew metrics
        sig_ok = psig is None or np.array_equal(np.asarray(psig),
                                                _spec_signature(spec))
        want = (padded,) if K == 1 else (padded, K)
        if rn is not None and rc is not None and sig_ok \
                and np.asarray(rn).shape == tuple(want):
            oob_num = resilient_device_put(jnp.asarray(rn, jnp.float32),
                                           rows_sh, pipeline="train")
            oob_cnt = resilient_device_put(jnp.asarray(rc, jnp.float32),
                                           rows_sh, pipeline="train")
        else:
            if prior is not None:
                from h2o3_tpu.log import warn
                warn("drf checkpoint carries no OOB resume state — "
                     "training metrics will reflect only the new trees")
            oob_num = resilient_device_put(
                jnp.zeros(padded if K == 1 else (padded, K), jnp.float32),
                rows_sh, pipeline="train")
            oob_cnt = resilient_device_put(
                jnp.zeros(padded, jnp.float32), rows_sh,
                pipeline="train")
        y = spec.y
        all_trees = []          # [(device chunk trees, n_active)]
        built = 0
        chunk = min(ntrees_new, 25)
        ckpt_dir = p.get("in_training_checkpoints_dir")
        ckpt_interval = max(int(
            p.get("in_training_checkpoints_tree_interval", 1) or 1), 1)
        ckpt_on = bool(ckpt_dir)
        if ckpt_on:
            chunk = max(min(chunk, ckpt_interval), 1)
        trees_since_ckpt = 0
        # donation is unsafe with checkpoints on: commit_ckpt
        # device_gets the OOB accumulators, which a donated dispatch
        # would already have consumed
        donate = jax.default_backend() == "tpu" and not ckpt_on
        rate_t = jnp.float32(sample_rate)
        col_rate_t = jnp.float32(col_rate)

        def commit_ckpt():
            # advisory end to end: a transient fetch failure in the
            # finalize/OOB device_gets must neither kill a healthy
            # train nor mask the original error on the failure path
            try:
                m = self._finalize(spec, bm, cfg, K, built, all_trees,
                                   prior=prior, tree_offset=start_trees)
                on, oc = telemetry.device_get((oob_num, oob_cnt),
                                              pipeline="train")
                m._resume_oob_num = np.asarray(on, np.float32)
                m._resume_oob_cnt = np.asarray(oc, np.float32)
                m._resume_sig = _spec_signature(spec)
                from h2o3_tpu.models.model_base import \
                    persist_in_training_ckpt
                persist_in_training_ckpt(m, self.algo, ckpt_dir)
            except Exception as e:  # noqa: BLE001 — advisory only
                from h2o3_tpu.log import warn
                warn("drf: in-training checkpoint commit failed: %s", e)

        # per-shard collective/straggler observation (ISSUE 8): chunk
        # k's output shards are watched AFTER chunk k+1 is dispatched,
        # so the host block lands where the device is already busy
        from h2o3_tpu.parallel.mesh import partitioner
        from h2o3_tpu.parallel.shardstats import merge_observations
        partn = partitioner(mesh)
        shard_obs = []
        pending_obs = None            # (prev chunk_trees, t_disp)
        # performance accounting (ISSUE 11): executable cost capture at
        # this jit seam + loop wall -> roofline point (None = no-op)
        perf_acc = telemetry.costmodel.accumulator(
            "train.loop", n_devices=mesh.size)
        t0 = time.monotonic()
        while built < ntrees_new:
            # bucket-rounded chunk lengths (models/gbm.py): ntrees
            # variants landing in one bucket reuse the executable
            c = min(chunk, ntrees_new - built)
            # ONE spelling of the executable cache key, shared by the
            # dispatch and the cost capture below (see models/gbm.py)
            bucket = chunk_bucket(c)
            lru_key = (mesh, cfg, K, srpc, bucket, has_t,
                       adaptive, packed, donate)

            def _dispatch(lru_key=lru_key, c=c):
                from h2o3_tpu import faults
                if faults.ACTIVE:
                    faults.check("compile", pipeline="train")
                step = _compiled_drf_chunk(*lru_key)
                if faults.ACTIVE:
                    faults.check("execute", pipeline="train")
                    if nd > 1:
                        # ICI collective seam (see models/gbm.py)
                        faults.check("collective", pipeline="train")
                return step(
                    Xtr, codes_t_arg, y, spec.w, oob_num, oob_cnt, key,
                    root_lo, root_hi, nb_f,
                    jnp.int32(start_trees + built), jnp.int32(c),
                    rate_t, col_rate_t)
            try:
                # transient failures retry with backoff; donated OOB
                # accumulators cannot be replayed (TPU path), so
                # donation disables retry
                oob_num, oob_cnt, chunk_trees = retry_transient(
                    _dispatch, site="train.execute",
                    attempts=1 if donate else 3)
                t_disp = time.perf_counter()
            except BaseException:
                if ckpt_on and built > 0:
                    # leave a resumable checkpoint at the committed
                    # prefix before the failure propagates
                    commit_ckpt()
                raise
            if perf_acc is not None:
                # one trace+lower per (config, bucket); scale=bucket —
                # the HLO analysis counts the tree-scan body once and
                # the executable runs it `bucket` times (see gbm.py)
                t_cap0 = time.perf_counter()
                step = _compiled_drf_chunk(*lru_key)   # lru cache hit
                perf_acc.add(telemetry.costmodel.executable_cost(
                    ("drf.chunk",) + lru_key,
                    lambda s=step, b=built, cc=c: s.lower(
                        Xtr, codes_t_arg, y, spec.w, oob_num, oob_cnt,
                        key, root_lo, root_hi, nb_f,
                        jnp.int32(start_trees + b), jnp.int32(cc),
                        rate_t, col_rate_t),
                    scale=bucket))
                perf_acc.note_capture_seconds(
                    time.perf_counter() - t_cap0)
            if pending_obs is not None:
                shard_obs.append(partn.observe_step(
                    pending_obs[0], pending_obs[1], algo=self.algo))
                pending_obs = None
            if nd > 1 and telemetry.enabled():
                pending_obs = (chunk_trees, t_disp)
            all_trees.append((chunk_trees, c))
            built += c
            trees_since_ckpt += c
            if ckpt_on and trees_since_ckpt >= ckpt_interval \
                    and built < ntrees_new:
                commit_ckpt()
                trees_since_ckpt = 0
            job.set_progress(built / ntrees_new)
            if job.cancel_requested or job.preempt_requested:
                break
        # checkpoint-based preemption (ISSUE 15): commit the built
        # prefix (DKV-only when no checkpoint dir is set — commit_ckpt
        # handles ckpt_dir=None) and unwind so the scheduler requeues
        # and resumes bit-identically from the saved OOB accumulators.
        # User cancel wins; a preempt racing the final chunk is moot.
        if (job.preempt_requested and not job.cancel_requested
                and built < ntrees_new):
            if built > 0:
                commit_ckpt()
            from h2o3_tpu.jobs import JobPreempted
            raise JobPreempted(
                f"drf train preempted at {built} committed trees"
                + (f": {job.preempt_reason}" if job.preempt_reason
                   else ""))
        if pending_obs is not None:
            # the final chunk: the loop has nothing left to overlap, so
            # this is the block_until_ready below, observed per shard
            shard_obs.append(partn.observe_step(
                pending_obs[0], pending_obs[1], algo=self.algo))
        jax.block_until_ready(oob_cnt)  # h2o3-lint: allow[transfer-seam] tree-loop timing fence + final-chunk shard observation point
        t_loop = time.monotonic() - t0

        model = self._finalize(spec, bm, cfg, K, built, all_trees,
                               prior=prior, tree_offset=start_trees)
        if ckpt_on:
            try:
                on, oc = telemetry.device_get((oob_num, oob_cnt),
                                              pipeline="train")
                model._resume_oob_num = np.asarray(on, np.float32)
                model._resume_oob_cnt = np.asarray(oc, np.float32)
                model._resume_sig = _spec_signature(spec)
                from h2o3_tpu.models.model_base import \
                    persist_in_training_ckpt
                # final=True: the durable artifact is written but the
                # DKV '<key>_ckpt' entry is dropped — the finished
                # model supersedes it
                persist_in_training_ckpt(model, self.algo, ckpt_dir,
                                         final=True)
            except Exception as e:  # noqa: BLE001 — advisory only
                from h2o3_tpu.log import warn
                warn("drf: final in-training checkpoint failed: %s", e)
        model.output["training_loop_seconds"] = t_loop
        model.output["packed_codes"] = packed_codes_record(
            packed, dtype=pc.rm.dtype if packed else None,
            W=pc.W if packed else None,
            bytes_per_value=pc.itemsize if packed else None,
            n_bins=bm.n_bins if packed else None)
        # the DRF chunk body (like GBM dense) traces its whole level
        # loop into one executable — all levels per dispatch
        model.output["levels_per_dispatch"] = int(cfg.max_depth)
        if perf_acc is not None:
            perf_acc.add_device_seconds(t_loop)
            rp = perf_acc.finish()
            if rp is not None:
                model.output["perf"] = {"train": rp,
                                        "phases": {"loop": rp}}
        model.output["spmd"] = {
            "n_data": nd, "n_model": n_model_shards(mesh),
            "model_axis_split_search": bool(
                n_model_shards(mesh) > 1 and spmd_enabled())}
        collective = merge_observations(shard_obs)
        if collective is not None:
            model.output["spmd"]["collective"] = collective
        # OOB metrics as training metrics (reference DRF semantics:
        # "training" numbers are out-of-bag when sample_rate < 1)
        self._oob_metrics(model, spec, K, oob_num, oob_cnt)
        if valid_spec is not None:
            # valid_spec is already adapted to the training domains
            # (build_validation_spec in ModelBuilder.train)
            out = model._predict_matrix(valid_spec.X)
            model.validation_metrics = compute_metrics(
                out, valid_spec.y, valid_spec.w, spec.nclasses,
                spec.response_domain)
        return model

    def _oob_metrics(self, model, spec, K, oob_num, oob_cnt):
        # ONE counted fetch for the OOB finalize (transfer-seam
        # burn-down: these were four raw uncounted device_gets)
        host = telemetry.device_get((oob_cnt, oob_num, spec.w, spec.y),
                                    pipeline="train")
        cnt, num, w, y = (np.asarray(v) for v in host)
        live = (cnt > 0) & (w > 0)
        if not live.any():
            # no OOB rows (sample_rate == 1.0): fall back to in-bag scoring
            # so training_metrics is never silently None (the reference
            # still reports training metrics when OOB is unavailable)
            out = model._predict_matrix(spec.X)
            model.training_metrics = compute_metrics(
                out, spec.y, spec.w, spec.nclasses, spec.response_domain)
            model.output["oob_metrics"] = False
            return
        if K == 1:
            pred = num[live] / cnt[live]
            if spec.nclasses == 2:
                p1 = np.clip(pred, 0.0, 1.0)
                probs = np.stack([1 - p1, p1], axis=1)
                model.training_metrics = compute_metrics(
                    probs, y[live], w[live], 2, spec.response_domain)
            else:
                model.training_metrics = compute_metrics(
                    pred, y[live], w[live], 1)
        else:
            pk = np.clip(num[live] / cnt[live][:, None], 0.0, 1.0)
            pk = pk / np.maximum(pk.sum(axis=1, keepdims=True), 1e-12)
            model.training_metrics = compute_metrics(
                pk, y[live], w[live], K, spec.response_domain)
        model.output["oob_metrics"] = True

    def _resolve_checkpoint(self, spec):
        """Continue-training support (hex/Model.java:487 _checkpoint):
        same compatibility contract as GBM's — the prior trees' feature
        indices and enum-code thresholds must address the same columns
        and domains."""
        ckpt = self.params.get("checkpoint")
        if not ckpt:
            return None
        from h2o3_tpu.models.gbm import _resolve_checkpoint_source
        prior = _resolve_checkpoint_source(ckpt, DRFModel, "DRF")
        if prior.max_depth != int(self.params["max_depth"]):
            raise ValueError("checkpoint max_depth differs")
        if int(self.params["ntrees"]) <= prior.ntrees_built:
            raise ValueError(
                f"ntrees ({self.params['ntrees']}) must exceed the "
                f"checkpoint's ntrees_built ({prior.ntrees_built})")
        if list(prior.feature_names) != list(spec.names):
            raise ValueError(
                f"checkpoint feature set {prior.feature_names} differs "
                f"from the training spec's {spec.names}")
        if prior.nclasses != spec.nclasses:
            raise ValueError(
                f"checkpoint has {prior.nclasses} response classes but "
                f"the training frame has {spec.nclasses}")
        prd = tuple(prior.response_domain) if prior.response_domain else None
        srd = tuple(spec.response_domain) if spec.response_domain else None
        if prd != srd:
            raise ValueError(
                f"checkpoint response domain {prior.response_domain} "
                f"differs from the training frame's "
                f"{spec.response_domain}")
        pcd = {k: tuple(v) for k, v in prior.cat_domains.items()}
        scd = {k: tuple(v) for k, v in spec.cat_domains.items()}
        if pcd != scd:
            raise ValueError(
                "checkpoint categorical domains differ from the "
                "training frame's")
        return prior

    def _finalize(self, spec, bm, cfg, K, built, all_trees, prior=None,
                  tree_offset=0) -> DRFModel:
        M = cfg.n_nodes
        # one pytree device_get; padding-bucket tails sliced off in the
        # shared helper (models/tree.py collect_chunk_trees)
        th = collect_chunk_trees(all_trees, M,
                                 bm.edges if bm is not None else [])
        feat = th["feat"]
        gains = th["gain"]
        trees_host = {"feat": feat, "thr": th["thr"],
                      "na_left": th["na_left"], "is_split": th["is_split"],
                      "value": th["value"], "node_w": th["node_w"]}
        if prior is not None:
            # checkpoint continuation: prepend the prior model's trees
            trees_host = {
                "feat": np.concatenate([np.asarray(prior._feat), feat]),
                "thr": np.concatenate([np.asarray(prior._thr),
                                       th["thr"]]),
                "na_left": np.concatenate([np.asarray(prior._na_left),
                                           th["na_left"]]),
                "is_split": np.concatenate([np.asarray(prior._is_split),
                                            th["is_split"]]),
                "value": np.concatenate([np.asarray(prior._value),
                                         th["value"]]),
                "node_w": (np.concatenate([np.asarray(prior._node_w),
                                           th["node_w"]])
                           if getattr(prior, "_node_w", None) is not None
                           else None),
            }
        model = DRFModel(self._model_key(), self.params,
                         spec, trees_host,
                         bm.edges if bm is not None else [],
                         bm.n_bins if bm is not None else cfg.n_bins,
                         cfg.max_depth, tree_offset + built, spec.nclasses)
        vi = np.zeros(len(spec.names))
        live = feat >= 0
        np.add.at(vi, feat[live], gains[live])
        if prior is not None:
            pv = prior.output.get("variable_importances")
            if pv:
                lut = {n: i for i, n in enumerate(spec.names)}
                for n, g in zip(pv["variable"], pv["relative_importance"]):
                    if n in lut:
                        vi[lut[n]] += g
        order = np.argsort(-vi)
        rel = vi / vi.max() if vi.max() > 0 else vi
        model.output["variable_importances"] = {
            "variable": [spec.names[i] for i in order],
            "relative_importance": vi[order].tolist(),
            "scaled_importance": rel[order].tolist(),
            "percentage": (vi[order] / vi.sum() if vi.sum() > 0
                           else vi[order]).tolist(),
        }
        return model


register_model_class("drf", DRFModel)
