"""Extended Isolation Forest — oblique random-hyperplane isolation trees.

Reference: hex/tree/isoforextended/ExtendedIsolationForest.java:27 — each
split draws a random normal vector n (extension_level+1 non-zero
components) and an intercept point p uniform inside the node's data
bounding box; a row goes left when (x - p)·n < 0. Anomaly score is the
isolation-forest 2^(-E[h]/c(n)) normalization.

TPU re-design: level-synchronous growth like isoforest.py, but the
per-node data bounding boxes are EXACT, computed per level with one
scatter-min/max over the sampled rows (segment reduce → the MRTask
reduction), and routing is a batched (rows × F)·(F) contraction per
level — all inside one jitted lax.scan over trees."""
from __future__ import annotations

import time
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.jobs import Job
from h2o3_tpu.models.isoforest import _avg_path
from h2o3_tpu.models.model_base import Model, ModelBuilder, TrainingSpec
from h2o3_tpu.persist import register_model_class

EIF_DEFAULTS: Dict = dict(
    ntrees=100, sample_size=256, extension_level=0, seed=-1,
)

_BIG = 3.0e38


def _grow_ext_tree(X, in_sample, depth, extension_level, key):
    """One extended isolation tree: per-node hyperplane (normal[M,F],
    point[M,F]) with M = 2^(depth+1)-1 slots."""
    rows, F = X.shape
    M = 2 ** (depth + 1) - 1
    normal = jnp.zeros((M, F), jnp.float32)
    point = jnp.zeros((M, F), jnp.float32)
    is_split = jnp.zeros(M, bool)
    nid = jnp.zeros(rows, jnp.int32)
    Xs = jnp.nan_to_num(X, nan=0.0)
    for d in range(depth):
        N = 2 ** d
        base = N - 1
        local = nid - base
        in_lvl = (local >= 0) & (local < N) & in_sample
        lid = jnp.clip(local, 0, N - 1)
        # exact per-node bounding box: scatter-min/max of sampled rows
        xin = jnp.where(in_lvl[:, None], Xs, _BIG)
        node_min = jnp.full((N, F), _BIG, jnp.float32).at[lid].min(xin)
        xax = jnp.where(in_lvl[:, None], Xs, -_BIG)
        node_max = jnp.full((N, F), -_BIG, jnp.float32).at[lid].max(xax)
        cnt = jnp.zeros(N, jnp.float32).at[lid].add(
            jnp.where(in_lvl, 1.0, 0.0))
        key, kn, kp, kz = jax.random.split(key, 4)
        nvec = jax.random.normal(kn, (N, F))
        # extension_level e: keep e+1 random coordinates per node
        # (e = F-1 → fully oblique; e = 0 → axis-parallel = classic IF)
        keep = min(extension_level + 1, F)
        if keep < F:
            z = jax.random.uniform(kz, (N, F))
            kth = jnp.sort(z, axis=1)[:, keep - 1][:, None]
            nvec = jnp.where(z <= kth, nvec, 0.0)
        u = jax.random.uniform(kp, (N, F))
        p = node_min + u * jnp.maximum(node_max - node_min, 0.0)
        can = (cnt >= 2) & (node_max > node_min).any(axis=1)
        idx = base + jnp.arange(N)
        normal = normal.at[idx].set(nvec)
        point = point.at[idx].set(p)
        is_split = is_split.at[idx].set(can)
        proj = ((Xs - p[lid]) * nvec[lid]).sum(axis=1)
        go_right = proj >= 0.0
        child = 2 * nid + 1 + go_right.astype(jnp.int32)
        route = (local >= 0) & (local < N) & can[lid]
        nid = jnp.where(route, child, nid)
    return {"normal": normal, "point": point, "is_split": is_split}


def _ext_path_lengths(X, normal, point, is_split, depth):
    rows = X.shape[0]
    Xs = jnp.nan_to_num(X, nan=0.0)
    nid = jnp.zeros(rows, jnp.int32)
    length = jnp.zeros(rows, jnp.float32)
    for _ in range(depth):
        s = is_split[nid]
        proj = ((Xs - point[nid]) * normal[nid]).sum(axis=1)
        go_right = proj >= 0.0
        nid = jnp.where(s, 2 * nid + 1 + go_right.astype(jnp.int32), nid)
        length = length + s.astype(jnp.float32)
    return length


class ExtendedIsolationForestModel(Model):
    algo = "extendedisolationforest"
    supervised = False

    def __init__(self, key, params, spec, trees, depth, sample_size):
        super().__init__(key, params, spec)
        self._normal = jnp.asarray(trees["normal"])     # [T, M, F]
        self._point = jnp.asarray(trees["point"])
        self._is_split = jnp.asarray(trees["is_split"])
        self.max_depth = depth
        self.sample_size = sample_size

    def _mean_length(self, X):
        T = self._normal.shape[0]

        def one(carry, t):
            return carry, _ext_path_lengths(
                X, self._normal[t], self._point[t], self._is_split[t],
                self.max_depth)

        _, L = jax.lax.scan(one, None, jnp.arange(T))
        return L.mean(axis=0)

    def _predict_matrix(self, X, offset=None):
        ml = self._mean_length(X)
        c = _avg_path(jnp.float32(self.sample_size))
        return jnp.exp2(-ml / c)

    def predict(self, frame):
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.models.model_base import adapt_test_matrix
        X = adapt_test_matrix(self, frame)
        # one forest traversal: score derives from the same mean lengths
        ml = np.asarray(jax.device_get(self._mean_length(X)))[: frame.nrow]
        c = float(np.asarray(_avg_path(jnp.float32(self.sample_size))))
        score = np.exp2(-ml / c)
        return Frame(["anomaly_score", "mean_length"],
                     [Vec.from_numpy(score.astype(np.float32)),
                      Vec.from_numpy(ml.astype(np.float32))])

    def _save_arrays(self):
        return {"normal": np.asarray(jax.device_get(self._normal)),
                "point": np.asarray(jax.device_get(self._point)),
                "is_split": np.asarray(jax.device_get(self._is_split))}

    def _save_extra_meta(self):
        return {"max_depth": self.max_depth,
                "sample_size": self.sample_size}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        m.max_depth = ex["max_depth"]
        m.sample_size = ex["sample_size"]
        m._normal = jnp.asarray(arrays["normal"])
        m._point = jnp.asarray(arrays["point"])
        m._is_split = jnp.asarray(arrays["is_split"])
        return m


class H2OExtendedIsolationForestEstimator(ModelBuilder):
    algo = "extendedisolationforest"
    supervised = False

    def __init__(self, **params):
        merged = dict(EIF_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        p = self.params
        ntrees = int(p.get("ntrees", 100))
        sample_size = int(p.get("sample_size", 256))
        ext = int(p.get("extension_level", 0))
        # reference grows to ceil(log2(sample_size)) (iTree height limit)
        depth = max(1, int(np.ceil(np.log2(max(sample_size, 2)))))
        X = spec.X
        w = spec.w
        rows, F = X.shape
        if not 0 <= ext <= F - 1:
            raise ValueError(
                f"extension_level must be in [0, {F - 1}], got {ext}")
        seed = int(p.get("seed", -1) or -1)
        key = jax.random.PRNGKey(seed if seed != -1
                                 else int(time.time() * 1e3) % (2 ** 31))

        @jax.jit
        def build_forest(key, X, w):
            def one_tree(carry, i):
                k = jax.random.fold_in(key, i)
                k1, k2 = jax.random.split(k)
                u = jax.random.uniform(k1, (rows,))
                u = jnp.where(w > 0, u, 2.0)
                kth = jnp.sort(u)[jnp.minimum(sample_size, rows) - 1]
                in_sample = (u <= kth) & (w > 0)
                tree = _grow_ext_tree(X, in_sample, depth, ext, k2)
                return carry, tree

            _, trees = jax.lax.scan(one_tree, None, jnp.arange(ntrees))
            return trees

        trees = build_forest(key, X, w)
        trees_host = {k: np.asarray(jax.device_get(v))
                      for k, v in trees.items()}
        model = ExtendedIsolationForestModel(
            f"eif_{id(self) & 0xffffff:x}", self.params, spec, trees_host,
            depth, sample_size)
        from h2o3_tpu.models.metrics import make_anomaly_metrics
        ml = np.asarray(jax.device_get(model._mean_length(X)))
        c = float(np.asarray(_avg_path(jnp.float32(sample_size))))
        live = np.asarray(jax.device_get(w)) > 0
        model.training_metrics = make_anomaly_metrics(
            np.exp2(-ml[live] / c), ml[live] / max(depth, 1))
        return model


register_model_class("extendedisolationforest", ExtendedIsolationForestModel)
