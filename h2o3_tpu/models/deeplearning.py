"""DeepLearning — feed-forward MLP (the reference's deepest NN).

Reference: hex/deeplearning/DeepLearning.java:35, Neurons.java (Rectifier/
Tanh/Maxout layers + dropout variants), DeepLearningModelInfo (flat weight
arrays), DeepLearningTask.java:17 — per-row fprop/bprop on thread-shared
weights (Hogwild!) with per-iteration model averaging across nodes
(:101,:180) and optional elastic averaging.

TPU re-design (SURVEY §2.5): Hogwild + averaging is an artifact of JVM
threads — synchronous data-parallel minibatch SGD is strictly better on
TPU: one jitted train step computes batched fwd/bwd on the MXU; under a
mesh the batch shards over 'data' and gradients psum over ICI. A whole
epoch runs as one lax.scan over contiguous batches of a device-resident,
per-epoch-permuted design matrix — zero host round-trips inside an epoch.

Optimizers match the reference's: ADADELTA (adaptive_rate=true default,
rho/epsilon) or momentum SGD with rate annealing + ramp-up
(rate/momentum_start/ramp/stable). Dropout (input + per-layer hidden),
L1/L2, UniformAdaptive init.
"""
from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu import telemetry
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import expand_design
from h2o3_tpu.models.model_base import (Model, ModelBuilder, ScoreKeeper,
                                        TrainingSpec, compute_metrics,
                                        pack_impute_means,
                                        unpack_impute_means)
from h2o3_tpu.persist import register_model_class

DL_DEFAULTS: Dict = dict(
    hidden=(200, 200), epochs=10.0, activation="rectifier",
    checkpoint=None, initial_weights=None, initial_biases=None,
    adaptive_rate=True, rho=0.99, epsilon=1e-8,
    rate=0.005, rate_annealing=1e-6, rate_decay=1.0,
    momentum_start=0.0, momentum_ramp=1e6, momentum_stable=0.0,
    input_dropout_ratio=0.0, hidden_dropout_ratios=None,
    l1=0.0, l2=0.0, max_w2=1e30,
    loss="auto", distribution="auto", standardize=True,
    # per-epoch reshuffling costs a full gather of the design matrix each
    # epoch; the reference's Hogwild pass doesn't shuffle at all
    # (DeepLearningTask streams rows in storage order), so default to one
    # up-front permutation
    shuffle_training_data=False,
    # TPU batch size: the reference's mini_batch_size default 1 feeds the
    # per-row Hogwild loop; a batched MXU step wants hundreds of rows
    mini_batch_size=256,
    autoencoder=False,
    seed=-1, stopping_rounds=0, stopping_metric="auto",
    stopping_tolerance=1e-3, score_interval=1,
)

_ACTS = {
    "rectifier": jax.nn.relu,
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "rectifier_with_dropout": jax.nn.relu,
    "tanh_with_dropout": jnp.tanh,
}


def _init_params(key, sizes):
    """UniformAdaptive init (hex/deeplearning Neurons: ±√(6/(fan_in+out)))."""
    params = []
    for i in range(len(sizes) - 1):
        key, k = jax.random.split(key)
        lim = float(np.sqrt(6.0 / (sizes[i] + sizes[i + 1])))
        Wm = jax.random.uniform(k, (sizes[i], sizes[i + 1]), jnp.float32,
                                -lim, lim)
        params.append({"W": Wm, "b": jnp.zeros(sizes[i + 1], jnp.float32)})
    return params


def _forward(params, x, act, drop_key=None, in_drop=0.0, hid_drops=None):
    """Batched fprop; dropout only when drop_key is given (training)."""
    h = x
    if drop_key is not None and in_drop > 0:
        drop_key, k = jax.random.split(drop_key)
        h = h * (jax.random.uniform(k, h.shape) >= in_drop) / (1 - in_drop)
    n = len(params)
    for i, layer in enumerate(params):
        h = h @ layer["W"] + layer["b"]
        if i < n - 1:
            h = act(h)
            if drop_key is not None and hid_drops and hid_drops[i] > 0:
                drop_key, k = jax.random.split(drop_key)
                keep = 1.0 - hid_drops[i]
                h = h * (jax.random.uniform(k, h.shape) < keep) / keep
    return h


def _loss_fn(out, y, w, task, dist_name):
    if task == "autoencoder":
        # reconstruction MSE over the standardized inputs (y = Xs batch)
        per = 0.5 * ((out - y) ** 2).sum(axis=1)
        return (w * per).sum() / jnp.maximum(w.sum(), 1e-12)
    if task == "classification":
        logp = jax.nn.log_softmax(out, axis=1)
        ll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
        return (w * ll).sum() / jnp.maximum(w.sum(), 1e-12)
    mu = out[:, 0]
    if dist_name == "laplace":
        per = jnp.abs(mu - y)
    elif dist_name == "poisson":
        per = jnp.exp(mu) - y * mu
    else:  # gaussian
        per = 0.5 * (mu - y) ** 2
    return (w * per).sum() / jnp.maximum(w.sum(), 1e-12)


def _init_opt(net, adaptive: bool):
    def zeros_like_params(params):
        return [{k: jnp.zeros_like(v) for k, v in layer.items()}
                for layer in params]
    return ((zeros_like_params(net), zeros_like_params(net)) if adaptive
            else (zeros_like_params(net),))


from functools import lru_cache  # noqa: E402


@lru_cache(maxsize=64)
def _compiled_epoch(sizes, act_name, task, dist_name, l1, l2, in_drop,
                    hid_drops, use_dropout, adaptive, rho, eps, rate0,
                    annealing, mom_start, mom_ramp, mom_stable, batch,
                    n_batches, use_rows, padded, shuffle):
    """Build + cache the jitted epoch for a static config. Data rides as
    ARGUMENTS: a closure over the design matrix bakes it into the program
    as a constant (~90s XLA compile at MNIST shape), and a fresh closure
    per estimator re-pays the compile every train."""
    act = _ACTS[act_name]

    def loss(params, xb, yb, wb, dkey):
        out = _forward(params, xb, act,
                       drop_key=dkey if use_dropout else None,
                       in_drop=in_drop, hid_drops=list(hid_drops))
        l = _loss_fn(out, yb, wb, task, dist_name)
        if l2 > 0:
            l = l + l2 * sum((layer["W"] ** 2).sum() for layer in params)
        if l1 > 0:
            l = l + l1 * sum(jnp.abs(layer["W"]).sum() for layer in params)
        return l

    grad_fn = jax.value_and_grad(loss)

    def sgd_update(params, opt, grads, samples):
        if adaptive:
            # ADADELTA (hex/deeplearning adaptive_rate default)
            Eg, Ed = opt
            new_p, nEg, nEd = [], [], []
            for layer, g, eg, ed in zip(params, grads, Eg, Ed):
                upd, neg, ned = {}, {}, {}
                for k in ("W", "b"):
                    eg2 = rho * eg[k] + (1 - rho) * g[k] ** 2
                    delta = (-jnp.sqrt(ed[k] + eps)
                             / jnp.sqrt(eg2 + eps) * g[k])
                    ned[k] = rho * ed[k] + (1 - rho) * delta ** 2
                    neg[k] = eg2
                    upd[k] = layer[k] + delta
                new_p.append(upd)
                nEg.append(neg)
                nEd.append(ned)
            return new_p, (nEg, nEd)
        # momentum SGD with annealing + ramp
        vel, = opt
        lr = rate0 / (1.0 + annealing * samples)
        mom = jnp.where(samples < mom_ramp,
                        mom_start + (mom_stable - mom_start)
                        * samples / mom_ramp, mom_stable)
        new_p, nv = [], []
        for layer, g, v in zip(params, grads, vel):
            upd, uv = {}, {}
            for k in ("W", "b"):
                uv[k] = mom * v[k] - lr * g[k]
                upd[k] = layer[k] + uv[k]
            new_p.append(upd)
            nv.append(uv)
        return new_p, (nv,)

    @jax.jit
    def run_epoch(params, opt, samples, ekey, Xs, y, w, shift):
        pkey, dkey = jax.random.split(ekey)
        if shuffle:
            perm = jax.random.permutation(pkey, padded)
            Xp = Xs[perm][:use_rows]
            yp = y[perm][:use_rows]
            wp = w[perm][:use_rows]
        else:
            # rotate the start offset per epoch so the dropped tail
            # (padded - use_rows rows) cycles instead of permanently
            # excluding the same rows
            Xp = jnp.roll(Xs, shift, axis=0)[:use_rows]
            yp = jnp.roll(y, shift, axis=0)[:use_rows]
            wp = jnp.roll(w, shift)[:use_rows]

        def one_batch(carry, i):
            params, opt, samples = carry
            xb = jax.lax.dynamic_slice_in_dim(Xp, i * batch, batch)
            yb = jax.lax.dynamic_slice_in_dim(yp, i * batch, batch)
            wb = jax.lax.dynamic_slice_in_dim(wp, i * batch, batch)
            bkey = jax.random.fold_in(dkey, i)
            l, grads = grad_fn(params, xb, yb, wb, bkey)
            params, opt = sgd_update(params, opt, grads, samples)
            return (params, opt, samples + batch), l

        (params, opt, samples), losses = jax.lax.scan(
            one_batch, (params, opt, samples), jnp.arange(n_batches))
        return params, opt, samples, losses.mean()

    return run_epoch


class DeepLearningModel(Model):
    algo = "deeplearning"

    def __init__(self, key, params, spec, net_params, exp_names, impute_means,
                 xm, xs, task, dist_name, hidden, activation):
        super().__init__(key, params, spec)
        self.net = net_params
        self.exp_names = exp_names
        self.impute_means = {k: float(v) for k, v in impute_means.items()}
        self.xm = np.asarray(xm)
        self.xs = np.asarray(xs)
        self.task = task
        self.dist_name = dist_name
        self.hidden = list(hidden)
        self.activation = activation

    def _predict_matrix(self, X, offset=None):
        from h2o3_tpu.models.glm import expand_scoring_matrix
        Xe = expand_scoring_matrix(self, X)
        Xs = (Xe - jnp.asarray(self.xm)[None, :]) / jnp.asarray(self.xs)[None, :]
        act = _ACTS[self.activation]
        out = _forward(self.net, Xs, act)
        if self.task == "autoencoder":
            return out                    # standardized reconstruction
        if self.task == "classification":
            probs = jax.nn.softmax(out, axis=1)
            return probs
        mu = out[:, 0]
        if self.dist_name == "poisson":
            mu = jnp.exp(mu)
        if offset is not None:
            mu = mu + offset
        return mu

    def predict(self, frame):
        if self.task != "autoencoder":
            return super().predict(frame)
        # autoencoder: reconstruction in ORIGINAL units, one column per
        # expanded feature (h2o predict on an autoencoder model)
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.models.model_base import adapt_test_matrix
        X = adapt_test_matrix(self, frame)
        out = self._predict_matrix(X)
        recon = out * jnp.asarray(self.xs)[None, :] + \
            jnp.asarray(self.xm)[None, :]
        R = np.asarray(telemetry.device_get(
            recon, pipeline="score"))[: frame.nrow]
        names = [f"reconstr_{n}" for n in self.exp_names]
        return Frame(names, [Vec.from_numpy(R[:, i].astype(np.float32))
                             for i in range(R.shape[1])])

    def anomaly(self, frame, per_feature: bool = False):
        """Per-row reconstruction MSE in standardized space
        (h2o.anomaly / ModelMetricsAutoEncoder scoring)."""
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.models.glm import expand_scoring_matrix
        from h2o3_tpu.models.model_base import adapt_test_matrix
        X = adapt_test_matrix(self, frame)
        Xe = expand_scoring_matrix(self, X)
        Xs = (Xe - jnp.asarray(self.xm)[None, :]) / \
            jnp.asarray(self.xs)[None, :]
        out = _forward(self.net, Xs, _ACTS[self.activation])
        err = (out - Xs) ** 2
        if per_feature:
            E = np.asarray(telemetry.device_get(
                err, pipeline="score"))[: frame.nrow]
            names = [f"reconstr_{n}.SE" for n in self.exp_names]
            return Frame(names,
                         [Vec.from_numpy(E[:, i].astype(np.float32))
                          for i in range(E.shape[1])])
        mse = np.asarray(telemetry.device_get(
            err.mean(axis=1), pipeline="score"))[: frame.nrow]
        return Frame(["Reconstruction.MSE"],
                     [Vec.from_numpy(mse.astype(np.float32))])

    # -- persistence ----------------------------------------------------

    def _save_arrays(self):
        d = {"xm": self.xm, "xs": self.xs,
             **pack_impute_means(self.impute_means)}
        for i, layer in enumerate(self.net):
            d[f"W{i}"] = np.asarray(
                telemetry.device_get(layer["W"], pipeline="score"))
            d[f"b{i}"] = np.asarray(
                telemetry.device_get(layer["b"], pipeline="score"))
        return d

    def _save_extra_meta(self):
        return {"exp_names": self.exp_names, "task": self.task,
                "dist_name": self.dist_name, "hidden": self.hidden,
                "activation": self.activation, "n_layers": len(self.net)}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        m.exp_names = list(ex["exp_names"])
        m.task = ex["task"]
        m.dist_name = ex["dist_name"]
        m.hidden = list(ex["hidden"])
        m.activation = ex["activation"]
        m.xm = arrays["xm"]
        m.xs = arrays["xs"]
        m.impute_means = unpack_impute_means(arrays)
        m.net = [{"W": jnp.asarray(arrays[f"W{i}"]),
                  "b": jnp.asarray(arrays[f"b{i}"])}
                 for i in range(ex["n_layers"])]
        return m


class H2ODeepLearningEstimator(ModelBuilder):
    algo = "deeplearning"

    def __init__(self, **params):
        merged = dict(DL_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)
        # autoencoder mode is unsupervised: train() must not demand y
        self.supervised = not bool(merged.get("autoencoder"))

    def _resolve_checkpoint(self, spec: TrainingSpec, task: str,
                            act_name: str):
        """checkpoint continue-training (hex/Model.java:487 _checkpoint,
        DeepLearning restart semantics): the prior model's weights seed
        the network and epochs continue from its state. Accepts a model
        object, a DKV model key, or an artifact path."""
        ckpt = self.params.get("checkpoint")
        if not ckpt:
            return None
        if isinstance(ckpt, DeepLearningModel):
            prior = ckpt
        else:
            from h2o3_tpu import dkv
            got = dkv.get_opt(str(ckpt))
            if got is not None and got[0] == "model":
                prior = got[1]
            else:
                from h2o3_tpu.persist import load_model
                prior = load_model(str(ckpt))
        if not isinstance(prior, DeepLearningModel):
            raise ValueError(
                f"checkpoint '{ckpt}' is not a DeepLearning model")
        if prior.task != task:
            raise ValueError(f"checkpoint task '{prior.task}' != '{task}'")
        if prior.activation != act_name:
            raise ValueError(
                f"checkpoint activation '{prior.activation}' != "
                f"'{act_name}' (checkpoint topology must match)")
        hidden = [int(h) for h in (self.params.get("hidden") or (200, 200))]
        if list(prior.hidden) != hidden:
            raise ValueError(
                f"checkpoint hidden layers {prior.hidden} != {hidden}")
        if prior.nclasses != spec.nclasses:
            raise ValueError(
                f"checkpoint has {prior.nclasses} response classes but "
                f"the training frame has {spec.nclasses}")
        prd = (tuple(prior.response_domain) if prior.response_domain
               else None)
        srd = tuple(spec.response_domain) if spec.response_domain else None
        if prd != srd:
            raise ValueError(
                f"checkpoint response domain {prd} differs from the "
                f"training frame's {srd} — the prior output layer's "
                f"class columns would address swapped labels")
        return prior

    def _apply_initial_weights(self, net, sizes):
        """initial_weights / initial_biases (hex/deeplearning
        DeepLearningParameters): user-specified per-layer [in, out]
        weight matrices / [out] bias vectors; None entries keep the
        random init. Accepts numpy arrays or Frames."""
        p = self.params

        def _mat(v):
            if hasattr(v, "as_matrix"):     # Frame
                return np.asarray(telemetry.device_get(
                    v.as_matrix(v.names), pipeline="train"))[:v.nrow]
            return np.asarray(v, np.float32)

        for kind, idx in (("initial_weights", "W"),
                          ("initial_biases", "b")):
            vals = p.get(kind)
            if not vals:
                continue
            if len(vals) != len(net):
                raise ValueError(
                    f"{kind} needs one entry per layer "
                    f"({len(net)}), got {len(vals)}")
            for li, v in enumerate(vals):
                if v is None:
                    continue
                a = _mat(v).astype(np.float32)
                want = ((sizes[li], sizes[li + 1]) if idx == "W"
                        else (sizes[li + 1],))
                if idx == "b" and a.ndim == 2 and 1 in a.shape:
                    a = a.reshape(-1)    # single-column bias frame
                if (idx == "W" and a.ndim == 2 and a.shape != want
                        and a.shape == (sizes[li + 1], sizes[li])):
                    # the reference supplies weight matrices in [out, in]
                    # orientation (hex/deeplearning Neurons rows=units of
                    # THIS layer, cols=previous layer); the native layout
                    # here is [in, out] — accept the reference
                    # orientation by transposing. Square layers are
                    # shape-ambiguous and taken as [in, out] as-is.
                    a = a.T
                if a.shape != want:
                    # exact match required beyond the two orientations: a
                    # reshaped matrix would scramble the connections
                    hint = ((f" ([in, out] native orientation; the "
                             f"reference's [out, in] = "
                             f"{(sizes[li + 1], sizes[li])} is accepted "
                             f"and transposed)") if idx == "W" else "")
                    raise ValueError(
                        f"{kind}[{li}] has shape {a.shape}, layer "
                        f"expects {want}{hint}")
                net[li] = dict(net[li])
                net[li][idx] = jnp.asarray(a)
        return net

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        p = self.params
        autoenc = bool(p.get("autoencoder"))
        task = ("autoencoder" if autoenc else
                "classification" if spec.nclasses > 1 else "regression")
        dist_name = (p.get("distribution") or "auto").lower()
        if dist_name in ("auto", ""):
            dist_name = ("multinomial" if spec.nclasses > 2 else
                         "bernoulli" if spec.nclasses == 2 else "gaussian")
        act_name = (p.get("activation") or "rectifier").lower()
        if act_name not in _ACTS:
            raise ValueError(f"unsupported activation '{act_name}'; have "
                             f"{sorted(_ACTS)} (maxout not implemented)")
        act = _ACTS[act_name]
        prior = self._resolve_checkpoint(spec, task, act_name)
        Xe, exp_names, means = expand_design(
            spec, impute_means=(dict(prior.impute_means)
                                if prior is not None else None))
        if prior is not None and list(prior.exp_names) != list(exp_names):
            raise ValueError(
                f"checkpoint expanded design {prior.exp_names} differs "
                f"from the training frame's {exp_names} — the prior "
                f"weights would address the wrong inputs")
        Fe = Xe.shape[1]
        w = spec.w
        # weighted standardization
        if prior is not None:
            # continue in the PRIOR model's input space — its weights
            # are only valid under its own standardization (and the
            # fresh reduction would be discarded anyway)
            xm = jnp.asarray(prior.xm, jnp.float32)
            xs = jnp.asarray(prior.xs, jnp.float32)
        else:
            wsum = w.sum()
            xm = (Xe * w[:, None]).sum(0) / wsum
            xv = (w[:, None] * (Xe - xm[None, :]) ** 2).sum(0) / wsum
            xs = jnp.sqrt(jnp.maximum(xv, 1e-12))
            if not bool(p.get("standardize", True)):
                xm = jnp.zeros_like(xm)
                xs = jnp.ones_like(xs)
        Xs = (Xe - xm[None, :]) / xs[None, :]
        if task == "autoencoder":
            # the network reconstructs its own standardized inputs
            # (hex/deeplearning autoencoder mode)
            y = Xs
            n_out = Fe
        else:
            y = (spec.y.astype(jnp.int32) if task == "classification"
                 else spec.y.astype(jnp.float32))
            n_out = spec.nclasses if task == "classification" else 1
        hidden = [int(h) for h in (p.get("hidden") or (200, 200))]
        sizes = [Fe] + hidden + [n_out]
        seed = int(p.get("seed", -1) or -1)
        key = jax.random.PRNGKey(seed if seed != -1
                                 else int(time.time() * 1e3) % (2 ** 31))
        key, ik = jax.random.split(key)
        if prior is not None:
            net = [{"W": jnp.asarray(ly["W"], jnp.float32),
                    "b": jnp.asarray(ly["b"], jnp.float32)}
                   for ly in prior.net]
        else:
            net = _init_params(ik, sizes)
        net = self._apply_initial_weights(net, sizes)

        padded = Xs.shape[0]
        nrow = spec.nrow
        # cap the batch so an epoch always makes >=8 optimizer updates
        # (and never exceeds the frame): the reference's per-row Hogwild
        # loop gets nrow updates per epoch; one giant batch would starve
        # small frames of updates entirely
        batch = max(min(int(p.get("mini_batch_size", 256)),
                        max(padded // 8, 1)), 1)
        n_batches = padded // batch
        use_rows = n_batches * batch
        epochs = float(p.get("epochs", 10.0))
        prior_epochs = 0.0
        if prior is not None:
            # epochs is the TOTAL (hex/Model checkpoint semantics, same
            # contract as the GBM resolver's ntrees): continue for the
            # remainder, and reject a target the prior already met
            prior_epochs = float(prior.output.get("epochs_trained", 0.0))
            if epochs <= prior_epochs:
                raise ValueError(
                    f"epochs ({epochs}) must exceed the checkpoint's "
                    f"epochs_trained ({prior_epochs})")
            epochs = epochs - prior_epochs
        adaptive = bool(p.get("adaptive_rate", True))
        rho = float(p.get("rho", 0.99))
        eps = float(p.get("epsilon", 1e-8))
        rate0 = float(p.get("rate", 0.005))
        annealing = float(p.get("rate_annealing", 1e-6))
        mom_start = float(p.get("momentum_start", 0.0))
        mom_ramp = max(float(p.get("momentum_ramp", 1e6)), 1.0)
        mom_stable = float(p.get("momentum_stable", 0.0))
        l1 = float(p.get("l1", 0.0))
        l2 = float(p.get("l2", 0.0))
        in_drop = float(p.get("input_dropout_ratio", 0.0))
        hid_drops = p.get("hidden_dropout_ratios")
        if hid_drops is None:
            hid_drops = ([0.5] * len(hidden) if act_name.endswith("_dropout")
                         else [0.0] * len(hidden))
        hid_drops = [float(d) for d in hid_drops]
        use_dropout = in_drop > 0 or any(d > 0 for d in hid_drops)

        opt0 = _init_opt(net, adaptive)
        shuffle = bool(p.get("shuffle_training_data", False))
        run_epoch = _compiled_epoch(
            tuple(sizes), act_name, task, dist_name, l1, l2, in_drop,
            tuple(hid_drops), use_dropout, adaptive, rho, eps, rate0,
            annealing, mom_start, mom_ramp, mom_stable, batch, n_batches,
            use_rows, padded, shuffle)

        if not shuffle:
            key, pk = jax.random.split(key)
            perm0 = jax.random.permutation(pk, padded)
            Xs = Xs[perm0]
            y = y[perm0]
            w = w[perm0]
        keeper = ScoreKeeper(p.get("stopping_rounds", 0),
                             p.get("stopping_metric"),
                             p.get("stopping_tolerance", 1e-3),
                             "binomial" if spec.nclasses == 2 else
                             "multinomial" if spec.nclasses > 2 else
                             "regression")
        n_epochs = max(int(np.ceil(epochs)), 1)
        # annealing/momentum ramp continue from the prior sample count
        samples = jnp.float32(prior.output.get("training_samples", 0.0)
                              if prior is not None else 0.0)
        t0 = time.monotonic()
        history = []
        # cancel/max_runtime polling (the last ROADMAP-listed algo
        # without it — GLM/KMeans landed in PR 7): run_epoch dispatches
        # ASYNCHRONOUSLY, so an unbounded loop would enqueue every
        # remaining epoch before a watchdog cancel could land — the
        # cooperative poll would see nothing left to skip. Poll BEFORE
        # each dispatch and keep at most two epochs in flight by
        # blocking on epoch e-1's loss scalar before dispatching e+1:
        # compute still overlaps host work, but a cancel takes effect
        # within ~one epoch instead of at the end of the train.
        prev_loss = None
        e = 0
        for e in range(n_epochs):
            if job.cancel_requested:
                e -= 1      # this epoch never dispatched
                break
            key, ekey = jax.random.split(key)
            if prev_loss is not None:
                jax.block_until_ready(prev_loss)  # h2o3-lint: allow[transfer-seam] deliberate depth bound: at most 2 epochs in flight (cancel-polling contract)
            net, opt0, samples, mloss = run_epoch(
                net, opt0, samples, ekey, Xs, y, w,
                jnp.int32((e * batch) % max(padded, 1)))
            prev_loss = mloss
            job.set_progress((e + 1) / n_epochs)
            if keeper.rounds > 0 or e == n_epochs - 1:
                entry = self._score(net, act, Xs, y, w, valid_spec, task,
                                    dist_name, xm, xs, means, exp_names, spec,
                                    e + 1)
                keeper.record(entry)
                history.append(entry)
                if keeper.should_stop():
                    break
            if job.cancel_requested:
                break
        jax.block_until_ready(net[0]["W"])  # h2o3-lint: allow[transfer-seam] epoch-loop timing fence: the loop clock must cover device completion
        t_loop = time.monotonic() - t0

        model = DeepLearningModel(
            f"dl_{id(self) & 0xffffff:x}", self.params, spec, net, exp_names,
            {k: float(telemetry.device_get(v, pipeline="train"))
             for k, v in means.items()},
            telemetry.device_get(xm, pipeline="train"),
            telemetry.device_get(xs, pipeline="train"), task, dist_name,
            hidden,
            act_name)
        model.scoring_history = history
        model.output["training_loop_seconds"] = t_loop
        model.output["epochs_trained"] = prior_epochs + e + 1
        model.output["training_samples"] = float(
            telemetry.device_get(samples, pipeline="train"))
        if task == "autoencoder":
            # reconstruction error metrics (hex/ModelMetricsAutoEncoder:
            # MSE over all reconstructed cells)
            from h2o3_tpu.models.metrics import ModelMetricsRegression

            def recon_metrics(Xs_in, w_in):
                out_ = _forward(net, Xs_in, act)
                per_row, wh = (np.asarray(v) for v in
                               telemetry.device_get(
                                   (((out_ - Xs_in) ** 2).mean(axis=1),
                                    w_in), pipeline="train"))
                live = wh > 0
                mse = float((per_row[live] * wh[live]).sum()
                            / max(wh[live].sum(), 1e-30))
                # MSE IS the reconstruction error — do not route per-row
                # MSEs through the regression maker (that would square
                # them again); ModelMetricsAutoEncoder reports the mean
                mm = ModelMetricsRegression(
                    mse=mse, rmse=float(np.sqrt(mse)),
                    mae=float("nan"), rmsle=float("nan"),
                    r2=float("nan"), mean_residual_deviance=mse,
                    nobs=int(live.sum()))
                return mm, mse

            model.training_metrics, mse = recon_metrics(Xs, w)
            model.output["reconstruction_mse"] = mse
            if valid_spec is not None:
                vXe, _, _ = expand_design(valid_spec, impute_means=means)
                vXs = (vXe - xm[None, :]) / xs[None, :]
                model.validation_metrics, vmse = recon_metrics(
                    vXs, valid_spec.w)
                model.output["validation_reconstruction_mse"] = vmse
            return model
        out = model._predict_matrix(spec.X)
        model.training_metrics = compute_metrics(out, spec.y, w,
                                                 spec.nclasses,
                                                 spec.response_domain)
        if valid_spec is not None:
            vout = model._predict_matrix(valid_spec.X)
            model.validation_metrics = compute_metrics(
                vout, valid_spec.y, valid_spec.w, spec.nclasses,
                spec.response_domain)
        return model

    def _score(self, net, act, Xs, y, w, valid_spec, task, dist_name, xm,
               xs, means, exp_names, spec, epoch):
        out = _forward(net, Xs, act)
        if task == "autoencoder":
            mse = float(telemetry.device_get(
                (w * ((out - y) ** 2).mean(axis=1)).sum() / w.sum(),
                pipeline="train"))
            return {"epoch": epoch, "mse": mse,
                    "rmse": float(np.sqrt(mse)), "deviance": mse}
        if task == "classification":
            logp = jax.nn.log_softmax(out, axis=1)
            ll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
            tl = float(telemetry.device_get(
                (w * ll).sum() / w.sum(), pipeline="train"))
            return {"epoch": epoch, "logloss": tl, "deviance": tl}
        mse = float(telemetry.device_get(
            (w * (out[:, 0] - y) ** 2).sum() / w.sum(),
            pipeline="train"))
        return {"epoch": epoch, "mse": mse, "rmse": float(np.sqrt(mse)),
                "deviance": mse}


register_model_class("deeplearning", DeepLearningModel)
