"""GBM — gradient boosting on the JAX histogram tree builder.

Reference: hex/tree/gbm/GBM.java:32 over the shared machinery in
hex/tree/SharedTree.java:229 (scoreAndBuildTrees :481, per-level
ScoreBuildHistogram2 MRTask, DTree split finding, CompressedTree storage).

The TPU training loop is one jitted per-tree step: compute (g, h) from the
distribution at the current margin, row/column-sample, grow a static-depth
tree from MXU histograms, and fold the tree's leaf values back into the
margin — no host round-trips inside a tree. Multinomial builds K trees per
iteration (one per class), as the reference does per-class DTrees.
"""
from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.jobs import Job
from h2o3_tpu.models.distributions import get_distribution
from h2o3_tpu.models.model_base import (Model, ModelBuilder, ScoreKeeper,
                                        TrainingSpec, compute_metrics)
from h2o3_tpu.models.tree import (TreeConfig, bins_to_thresholds, grow_tree,
                                  predict_binned, predict_raw_stacked)
from h2o3_tpu.ops.binning import bin_matrix, digitize_with_edges, make_codes_view

GBM_DEFAULTS: Dict = dict(
    ntrees=50, max_depth=5, min_rows=10.0, learn_rate=0.1,
    learn_rate_annealing=1.0, sample_rate=1.0, col_sample_rate=1.0,
    col_sample_rate_per_tree=1.0, nbins=20, nbins_cats=1024,
    distribution="auto", tweedie_power=1.5, min_split_improvement=1e-5,
    seed=-1, stopping_rounds=0, stopping_metric="auto",
    stopping_tolerance=1e-3, score_tree_interval=5, reg_lambda=0.0,
    max_abs_leafnode_pred=1e30, histogram_type="quantiles_global",
    # TPU-specific: which histogram kernel ('auto' = matmul on TPU,
    # scatter on CPU); see ops/histogram.py
    hist_kernel="auto",
)


class GBMModel(Model):
    algo = "gbm"

    def __init__(self, key, params, spec, dist_name, f0, trees_host, edges,
                 n_bins, max_depth, ntrees_built, nclasses):
        super().__init__(key, params, spec)
        self.dist_name = dist_name
        self.f0 = f0                      # scalar or [K]
        self.edges = edges
        self.n_bins = n_bins
        self.max_depth = max_depth
        self.ntrees_built = ntrees_built
        self._K = max(nclasses, 1) if nclasses > 2 else 1
        # stacked device arrays [T*K, M] in (tree, class) order
        self._feat = jnp.asarray(trees_host["feat"])
        self._thr = jnp.asarray(trees_host["thr"])
        self._na_left = jnp.asarray(trees_host["na_left"])
        self._is_split = jnp.asarray(trees_host["is_split"])
        self._value = jnp.asarray(trees_host["value"])

    def _margin_matrix(self, X, offset=None):
        contribs = predict_raw_stacked(X, self._feat, self._thr, self._na_left,
                                       self._is_split, self._value,
                                       self.max_depth)
        K = self._K
        if K == 1:
            margin = jnp.asarray(self.f0) + contribs.sum(axis=1)
            if offset is not None:
                margin = margin + offset
            return margin
        T = self.ntrees_built
        per_class = contribs.reshape(X.shape[0], T, K).sum(axis=1)
        return jnp.asarray(self.f0)[None, :] + per_class

    def _predict_matrix(self, X, offset=None):
        margin = self._margin_matrix(X, offset=offset)
        if self.nclasses <= 1:
            return get_distribution(self.dist_name,
                                    self.params.get("tweedie_power", 1.5)
                                    ).predict(margin)
        if self.nclasses == 2:
            p1 = 1.0 / (1.0 + jnp.exp(-margin))
            return jnp.stack([1.0 - p1, p1], axis=1)
        return jax.nn.softmax(margin, axis=1)

    def varimp(self, use_pandas=False):
        """Relative importance = summed split gain per feature
        (hex/tree/SharedTreeModel varimp semantics)."""
        return self.output.get("variable_importances")


class H2OGradientBoostingEstimator(ModelBuilder):
    algo = "gbm"

    def __init__(self, **params):
        merged = dict(GBM_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    # -- the chunked jitted training step ------------------------------
    #
    # ``chunk`` trees are built inside ONE jit via lax.scan: per-call
    # dispatch overhead (which dominates through remote relays) amortises,
    # and margins/trees stay on device between trees. The reference
    # dispatches one MRTask per level per tree (SharedTree.java:566-635) —
    # here a whole chunk of trees is a single XLA program.

    @staticmethod
    @partial(jax.jit, static_argnames=("cfg", "K", "dist_name", "tweedie_power",
                                       "sample_rate", "col_rate", "na_bin",
                                       "chunk", "anneal", "has_valid"))
    def _train_chunk(codes, margin, y, w, vcodes, vmargin, base_key, lr0,
                     start_idx, cfg, K, dist_name, tweedie_power,
                     sample_rate, col_rate, na_bin, chunk, anneal, has_valid):
        F = codes.shape[1]

        def one_tree(carry, i):
            margin, vmargin, lr = carry
            key = jax.random.fold_in(base_key, start_idx + i)
            key_r, key_c = jax.random.split(key)
            wt = w
            if sample_rate < 1.0:
                wt = w * (jax.random.uniform(key_r, w.shape) < sample_rate)
            col_mask = jnp.ones(F, bool)
            if col_rate < 1.0:
                col_mask = jax.random.uniform(key_c, (F,)) < col_rate
            trees = []
            if K == 1:
                dist = get_distribution(dist_name, tweedie_power)
                g, h = dist.grad_hess(margin, y)
                tree, nid = grow_tree(codes, g * wt, h * wt, wt, cfg, col_mask)
                # grow_tree already routed every row to its leaf — reuse
                # nid instead of re-walking the tree (saves ~250ms/tree@1M)
                margin = margin + lr * tree["value"][nid]
                if has_valid:
                    vc, _ = predict_binned(vcodes, tree, cfg.max_depth, na_bin)
                    vmargin = vmargin + lr * vc
                trees.append(tree)
            else:
                p = jax.nn.softmax(margin, axis=1)
                for k in range(K):
                    yk = (y == k).astype(jnp.float32)
                    gk = (p[:, k] - yk)
                    hk = jnp.maximum(p[:, k] * (1.0 - p[:, k]), 1e-9)
                    tree, nid = grow_tree(codes, gk * wt, hk * wt, wt, cfg,
                                          col_mask)
                    margin = margin.at[:, k].add(lr * tree["value"][nid])
                    if has_valid:
                        vc, _ = predict_binned(vcodes, tree, cfg.max_depth,
                                               na_bin)
                        vmargin = vmargin.at[:, k].add(lr * vc)
                    trees.append(tree)
            stacked = {kk: jnp.stack([t[kk] for t in trees])
                       for kk in trees[0]}
            return (margin, vmargin, lr * anneal), stacked

        (margin, vmargin, _), chunk_trees = jax.lax.scan(
            one_tree, (margin, vmargin, lr0), jnp.arange(chunk))
        return margin, vmargin, chunk_trees

    # -- driver ---------------------------------------------------------

    def _resolve_distribution(self, spec: TrainingSpec) -> str:
        d = (self.params.get("distribution") or "auto").lower()
        if d in ("auto", ""):
            if spec.nclasses == 2:
                return "bernoulli"
            if spec.nclasses > 2:
                return "multinomial"
            return "gaussian"
        return d

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job) -> GBMModel:
        p = self.params
        dist_name = self._resolve_distribution(spec)
        K = spec.nclasses if spec.nclasses > 2 else 1
        task = ("binomial" if spec.nclasses == 2
                else "multinomial" if K > 1 else "regression")
        nbins = int(p["nbins"])
        bm = bin_matrix(np.asarray(jax.device_get(spec.X)), spec.names,
                        spec.is_cat, spec.nrow, nbins=max(nbins, 2),
                        nbins_cats=int(p["nbins_cats"]),
                        histogram_type=p.get("histogram_type", "quantiles_global"))
        cfg = TreeConfig(max_depth=int(p["max_depth"]), n_bins=bm.n_bins,
                         n_features=bm.n_features, min_rows=float(p["min_rows"]),
                         min_split_improvement=float(p["min_split_improvement"]),
                         reg_lambda=float(p.get("reg_lambda", 0.0)),
                         hist_method=p.get("hist_kernel", "auto"))
        y, w = spec.y, spec.w
        padded = spec.X.shape[0]
        dist = get_distribution(dist_name, p["tweedie_power"]) if K == 1 else None
        if spec.offset is not None and K > 1:
            raise NotImplementedError(
                "offset_column is not supported for multinomial GBM "
                "(matching hex/tree/gbm/GBM.java offset restrictions)")
        if K == 1:
            yf = y.astype(jnp.float32)
            f0 = dist.init_f0(yf, w)
            margin = jnp.full(padded, f0, jnp.float32)
            if spec.offset is not None:
                # offset enters the margin, not the trees: f = f0 + offset + Σ lr·tree
                # (reference GBM honors offsets in every distribution's margin)
                margin = margin + spec.offset
        else:
            pri = jnp.maximum(
                jnp.zeros(K, jnp.float32).at[y].add(w) / w.sum(), 1e-9)
            f0 = jnp.log(pri)
            margin = jnp.broadcast_to(f0, (padded, K)).astype(jnp.float32)
            yf = y
        seed = int(p.get("seed", -1) or -1)
        key = jax.random.PRNGKey(seed if seed != -1 else int(time.time() * 1e3) % (2**31))
        ntrees = int(p["ntrees"])
        lr = float(p["learn_rate"])
        anneal = float(p["learn_rate_annealing"])
        col_rate = float(p["col_sample_rate"]) * float(p["col_sample_rate_per_tree"])
        keeper = ScoreKeeper(p.get("stopping_rounds", 0), p.get("stopping_metric"),
                             p.get("stopping_tolerance", 1e-3), task)
        interval = max(int(p.get("score_tree_interval", 5) or 5), 1)
        # validation margin tracked with train edges
        has_valid = valid_spec is not None
        if has_valid:
            vcodes = make_codes_view(
                digitize_with_edges(valid_spec.X, bm.edges, bm.n_bins))
            vmargin = (jnp.full(valid_spec.X.shape[0], f0, jnp.float32) if K == 1
                       else jnp.broadcast_to(f0, (valid_spec.X.shape[0], K)).astype(jnp.float32))
            if K == 1 and valid_spec.offset is not None:
                vmargin = vmargin + valid_spec.offset
        else:  # small dummies (untraced branches, but args need shapes)
            vcodes = make_codes_view(jnp.zeros((8, bm.n_features),
                                               bm.codes.dtype))
            vmargin = (jnp.zeros(8, jnp.float32) if K == 1
                       else jnp.zeros((8, K), jnp.float32))

        chunk = interval if keeper.rounds > 0 else min(ntrees, 50)
        all_trees = []
        built = 0
        jax.block_until_ready(margin)
        t_loop0 = time.time()
        while built < ntrees:
            c = min(chunk, ntrees - built)
            margin, vmargin, chunk_trees = self._train_chunk(
                bm.codes, margin, yf, w, vcodes, vmargin, key,
                jnp.float32(lr), built, cfg, K, dist_name,
                float(p["tweedie_power"]), float(p["sample_rate"]), col_rate,
                bm.na_bin, c, anneal, has_valid)
            all_trees.append(chunk_trees)  # stays on device until finalize
            built += c
            lr *= anneal ** c
            job.set_progress(0.5 * built / ntrees)
            if job.cancel_requested:
                break
            if keeper.rounds > 0:
                sc_spec = valid_spec if has_valid else spec
                sc_margin = vmargin if has_valid else margin
                entry = self._score_entry(sc_margin, sc_spec, dist, K, built,
                                          want_auc=keeper.metric == "auc")
                keeper.record(entry)
                if keeper.should_stop():
                    break

        jax.block_until_ready(margin)
        t_loop = time.time() - t_loop0
        model = self._finalize(spec, valid_spec, dist_name, f0, all_trees, bm,
                               cfg, K, built, margin,
                               vmargin if has_valid else None, keeper)
        model.output["training_loop_seconds"] = t_loop
        return model

    def _score_entry(self, margin, sc_spec, dist, K, built,
                     want_auc: bool = False) -> Dict:
        w = sc_spec.w
        y = sc_spec.y
        if K == 1:
            mu = dist.predict(margin)
            yf = y.astype(jnp.float32)
            dev = float(jax.device_get(dist.deviance(w, yf, mu)))
            entry = {"ntrees": built, "deviance": dev}
            if dist.name == "gaussian":
                entry["mse"] = dev
                entry["rmse"] = float(np.sqrt(max(dev, 0)))
            if dist.name == "bernoulli":
                entry["logloss"] = dev / 2.0
                if want_auc:
                    from h2o3_tpu.models.metrics import _binary_curve_kernel
                    auc = _binary_curve_kernel(mu, yf, w)[4]
                    entry["auc"] = float(jax.device_get(auc))
            return entry
        probs = jax.nn.softmax(margin, axis=1)
        eps = 1e-15
        py = jnp.clip(probs[jnp.arange(probs.shape[0]), y], eps, 1.0)
        ll = float(jax.device_get(-(w * jnp.log(py)).sum() / w.sum()))
        return {"ntrees": built, "logloss": ll, "deviance": ll}

    def _finalize(self, spec, valid_spec, dist_name, f0, all_trees, bm, cfg,
                  K, built, margin, vmargin, keeper) -> GBMModel:
        M = cfg.n_nodes
        T = built * max(K, 1)
        host = [{k: np.asarray(jax.device_get(v)) for k, v in t.items()}
                for t in all_trees]
        feat = np.concatenate([t["feat"].reshape(-1, M) for t in host])
        sbin = np.concatenate([t["split_bin"].reshape(-1, M) for t in host])
        nal = np.concatenate([t["na_left"].reshape(-1, M) for t in host])
        spl = np.concatenate([t["is_split"].reshape(-1, M) for t in host])
        val = np.concatenate([t["value"].reshape(-1, M) for t in host])
        gains = np.concatenate([t["gain"].reshape(-1, M) for t in host])
        lr0 = float(self.params["learn_rate"])
        anneal = float(self.params["learn_rate_annealing"])
        lrs = lr0 * anneal ** np.repeat(np.arange(built), max(K, 1))
        val_scaled = val * lrs[:, None]
        thr = np.stack([bins_to_thresholds(sbin[i], feat[i], bm.edges)
                        for i in range(T)])
        trees_host = {"feat": feat, "thr": thr, "na_left": nal,
                      "is_split": spl, "value": val_scaled}
        f0_host = np.asarray(jax.device_get(f0))
        model = GBMModel(f"{self.algo}_{id(self) & 0xffffff:x}", self.params,
                         spec, dist_name, f0_host, trees_host, bm.edges,
                         bm.n_bins, cfg.max_depth, built, spec.nclasses)
        # variable importances from split gains
        vi = np.zeros(len(spec.names))
        live = feat >= 0
        np.add.at(vi, feat[live], gains[live])
        order = np.argsort(-vi)
        rel = vi / vi.max() if vi.max() > 0 else vi
        model.output["variable_importances"] = {
            "variable": [spec.names[i] for i in order],
            "relative_importance": vi[order].tolist(),
            "scaled_importance": rel[order].tolist(),
            "percentage": (vi[order] / vi.sum() if vi.sum() > 0 else vi[order]).tolist(),
        }
        model.scoring_history = keeper.history
        # final metrics from the training margin (exact, no re-predict)
        model.training_metrics = self._metrics_from_margin(margin, spec, dist_name, K)
        if vmargin is not None:
            model.validation_metrics = self._metrics_from_margin(
                vmargin, valid_spec, dist_name, K)
        return model

    def _metrics_from_margin(self, margin, spec, dist_name, K):
        if spec.nclasses == 2:
            p1 = 1.0 / (1.0 + jnp.exp(-margin))
            probs = jnp.stack([1.0 - p1, p1], axis=1)
            return compute_metrics(probs, spec.y, spec.w, 2, spec.response_domain)
        if K > 1:
            probs = jax.nn.softmax(margin, axis=1)
            return compute_metrics(probs, spec.y, spec.w, K, spec.response_domain)
        dist = get_distribution(dist_name, self.params.get("tweedie_power", 1.5))
        mu = dist.predict(margin)
        dev = float(jax.device_get(dist.deviance(spec.w, spec.y.astype(jnp.float32), mu)))
        return compute_metrics(mu, spec.y, spec.w, 1, deviance=dev)
