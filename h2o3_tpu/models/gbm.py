"""GBM — gradient boosting on the JAX histogram tree builder.

Reference: hex/tree/gbm/GBM.java:32 over the shared machinery in
hex/tree/SharedTree.java:229 (scoreAndBuildTrees :481, per-level
ScoreBuildHistogram2 MRTask, DTree split finding, CompressedTree storage).

The TPU training loop is one jitted per-tree step: compute (g, h) from the
distribution at the current margin, row/column-sample, grow a static-depth
tree from MXU histograms, and fold the tree's leaf values back into the
margin — no host round-trips inside a tree. Multinomial builds K trees per
iteration (one per class), as the reference does per-class DTrees.
"""
from __future__ import annotations

import time
from functools import lru_cache, partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from h2o3_tpu import telemetry
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.distributions import get_distribution
from h2o3_tpu.models.model_base import (Model, ModelBuilder, ScoreKeeper,
                                        TrainingSpec, compute_metrics)
from h2o3_tpu.models.tree import (ADAPTIVE_HIST_TYPES,
                                  TreeConfig, adaptive_feasible,
                                  adaptive_setup, binned_feasible,
                                  packed_bins_upper_bound,
                                  chunk_bucket,
                                  collect_chunk_trees, grow_tree,
                                  grow_tree_adaptive, grow_tree_binned,
                                  levels_per_pass,
                                  packed_codes_requested, predict_binned,
                                  predict_raw_stacked, predict_raw_tree)
from h2o3_tpu.ops.binning import (CodesView, bin_matrix_device,
                                  digitize_with_edges, make_codes_view,
                                  pack_codes, pack_codes_for,
                                  packed_codes_record)
from h2o3_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS, current_mesh,
                                    n_data_shards, n_model_shards,
                                    partitioner, spmd_enabled)
from h2o3_tpu.resilience import resilient_device_put, retry_transient

GBM_DEFAULTS: Dict = dict(
    ntrees=50, max_depth=5, min_rows=10.0, learn_rate=0.1,
    learn_rate_annealing=1.0, sample_rate=1.0, sample_rate_per_class=None,
    col_sample_rate=1.0, col_sample_rate_per_tree=1.0,
    col_sample_rate_change_per_level=1.0, nbins=20, nbins_cats=1024,
    distribution="auto", tweedie_power=1.5, quantile_alpha=0.5,
    huber_alpha=0.9, min_split_improvement=1e-5,
    seed=-1, stopping_rounds=0, stopping_metric="auto",
    stopping_tolerance=1e-3, score_tree_interval=0, reg_lambda=0.0,
    # continue-training + in-training checkpoints (hex/Model.java:487
    # _checkpoint, hex/tree/SharedTree in_training_checkpoints_*):
    # REAL params now (formerly compat_params warn entries) — resumed
    # trains are bit-identical to uninterrupted ones via the saved
    # resume margin (tests/test_resilience.py)
    checkpoint=None, in_training_checkpoints_dir=None,
    in_training_checkpoints_tree_interval=1,
    # uniform_adaptive = the reference's default (hex/tree/DHistogram.java
    # UniformAdaptive): per-node re-binned uniform histograms via the fused
    # adaptive kernel; quantiles_global = global-sketch binned codes
    # (XGBoost tree_method=hist semantics)
    max_abs_leafnode_pred=1e30, histogram_type="uniform_adaptive",
    # monotone_constraints: {col: +1/-1} (hex/tree/DTree Constraints);
    # interaction_constraints: [[col,...],...] feature groups allowed to
    # interact on a branch (GlobalInteractionConstraints)
    monotone_constraints=None, interaction_constraints=None,
    # TPU-specific: which histogram kernel ('auto' = matmul on TPU,
    # scatter on CPU); see ops/histogram.py
    hist_kernel="auto",
    # MXU histogram precision: 'auto' (= bfloat16 1-pass; deviation bound
    # in ops/hist_adaptive.py) or 'float32' (exact, ~6x hist cost)
    histogram_precision="auto",
    # packed binned-code hot path (ISSUE 12): 'auto' bins features once
    # into int8/int16 codes and runs the fused binned level kernel
    # wherever compiled pallas runs (TPU / interpret escape) — the
    # XGBoost tree_method=hist shape with 1-2 byte/value hot-loop
    # traffic; True forces it everywhere (scatter reference), False
    # keeps the per-node adaptive f32 kernel. histogram_type='random'
    # always uses the adaptive kernel (its per-tree grid phase needs
    # per-level rebinning, which packing removes by design)
    packed_codes="auto",
)


from h2o3_tpu.models.treeshap import TreeScoringOptionsMixin  # noqa: E402


def _spec_signature(spec) -> np.ndarray:
    """Cheap fingerprint of the training data a resume state belongs
    to: (nrow, Σy, Σw) as f32 device reductions — identical data gives
    bit-equal sums, different data virtually never does. Guards
    against applying a checkpoint's saved margin/OOB state to a
    different frame that merely has the same shape."""
    sy, sw = telemetry.device_get(
        (spec.y.astype(jnp.float32).sum(),
         spec.w.astype(jnp.float32).sum()), pipeline="train")
    return np.array([float(spec.nrow), float(sy), float(sw)],
                    np.float64)


def _resolve_checkpoint_source(ckpt, model_cls, algo_label):
    """``checkpoint=`` accepts a live model, a DKV key (the in-training
    checkpoints land there as ``<key>_ckpt``) or an artifact path
    (hex/Model.java _checkpoint takes a Key; h2o-py also passes model
    objects)."""
    if isinstance(ckpt, model_cls):
        return ckpt
    if isinstance(ckpt, str):
        from h2o3_tpu import dkv
        ent = dkv.get_opt(ckpt)
        if ent is not None and ent[0] == "model":
            prior = ent[1]
        else:
            from h2o3_tpu.persist import load_model
            prior = load_model(ckpt)
    else:
        raise ValueError(
            f"checkpoint must be a {algo_label} model, DKV key or "
            f"artifact path, got {type(ckpt).__name__}")
    if not isinstance(prior, model_cls):
        raise ValueError(
            f"checkpoint resolves to a {getattr(prior, 'algo', '?')} "
            f"model — {algo_label} can only continue from its own kind")
    return prior


class GBMModel(TreeScoringOptionsMixin, Model):
    algo = "gbm"

    def __init__(self, key, params, spec, dist_name, f0, trees_host, edges,
                 n_bins, max_depth, ntrees_built, nclasses):
        super().__init__(key, params, spec)
        self.dist_name = dist_name
        self.f0 = f0                      # scalar or [K]
        self.edges = edges
        self.n_bins = n_bins
        self.max_depth = max_depth
        self.ntrees_built = ntrees_built
        self._K = max(nclasses, 1) if nclasses > 2 else 1
        # stacked device arrays [T*K, M] in (tree, class) order
        self._feat = jnp.asarray(trees_host["feat"])
        self._thr = jnp.asarray(trees_host["thr"])
        self._na_left = jnp.asarray(trees_host["na_left"])
        self._is_split = jnp.asarray(trees_host["is_split"])
        self._value = jnp.asarray(trees_host["value"])
        nw = trees_host.get("node_w")
        self._node_w = jnp.asarray(nw) if nw is not None else None

    def _contrib_f0(self) -> float:
        return float(np.asarray(self.f0).reshape(-1)[0])

    def _margin_matrix(self, X, offset=None):
        contribs = predict_raw_stacked(X, self._feat, self._thr, self._na_left,
                                       self._is_split, self._value,
                                       self.max_depth)
        K = self._K
        if K == 1:
            margin = jnp.asarray(self.f0) + contribs.sum(axis=1)
            if offset is not None:
                margin = margin + offset
            return margin
        T = self.ntrees_built
        per_class = contribs.reshape(X.shape[0], T, K).sum(axis=1)
        return jnp.asarray(self.f0)[None, :] + per_class

    def _predict_matrix(self, X, offset=None):
        margin = self._margin_matrix(X, offset=offset)
        if self.nclasses <= 1:
            return get_distribution(self.dist_name,
                                    self.params.get("tweedie_power", 1.5)
                                    ).predict(margin)
        if self.nclasses == 2:
            p1 = 1.0 / (1.0 + jnp.exp(-margin))
            return jnp.stack([1.0 - p1, p1], axis=1)
        return jax.nn.softmax(margin, axis=1)

    def varimp(self, use_pandas=False):
        """Relative importance = summed split gain per feature
        (hex/tree/SharedTreeModel varimp semantics)."""
        return self.output.get("variable_importances")

    # -- persistence (persist.save_model/load_model) -------------------

    def _save_arrays(self):
        # ONE counted pytree fetch for the stacked tree arrays (the
        # five raw per-array device_gets were invisible to d2h budgets)
        host = telemetry.device_get(
            {"feat": self._feat, "thr": self._thr,
             "na_left": self._na_left, "is_split": self._is_split,
             "value": self._value})
        d = {k: np.asarray(v) for k, v in host.items()}
        d["f0"] = np.asarray(self.f0)
        if self._node_w is not None:
            d["node_w"] = np.asarray(telemetry.device_get(self._node_w))
        rm = getattr(self, "_resume_margin", None)
        if rm is not None:
            # in-training checkpoint state: the exact f32 training
            # margin at the committed tree count — resuming from it
            # (instead of re-summing tree contributions) is what makes
            # a resumed train BIT-identical to an uninterrupted one
            d["resume_margin"] = np.asarray(rm)
        sig = getattr(self, "_resume_sig", None)
        if sig is not None:
            d["resume_sig"] = np.asarray(sig)
        for i, e in enumerate(self.edges):
            d[f"edge_{i}"] = np.asarray(e)
        return d

    def _save_extra_meta(self):
        return {"dist_name": self.dist_name, "n_bins": self.n_bins,
                "max_depth": self.max_depth,
                "ntrees_built": self.ntrees_built,
                "n_edges": len(self.edges)}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        m.dist_name = ex["dist_name"]
        m.n_bins = ex["n_bins"]
        m.max_depth = ex["max_depth"]
        m.ntrees_built = ex["ntrees_built"]
        m.f0 = arrays["f0"]
        m.edges = [arrays[f"edge_{i}"] for i in range(ex["n_edges"])]
        m._K = max(m.nclasses, 1) if m.nclasses > 2 else 1
        m._feat = jnp.asarray(arrays["feat"])
        m._thr = jnp.asarray(arrays["thr"])
        m._na_left = jnp.asarray(arrays["na_left"])
        m._is_split = jnp.asarray(arrays["is_split"])
        m._value = jnp.asarray(arrays["value"])
        m._node_w = (jnp.asarray(arrays["node_w"])
                     if "node_w" in arrays else None)
        m._resume_margin = (np.asarray(arrays["resume_margin"])
                            if "resume_margin" in arrays else None)
        m._resume_sig = (np.asarray(arrays["resume_sig"])
                         if "resume_sig" in arrays else None)
        return m


def _gbm_chunk_body(codes_rm, codes_t, margin, y, w, vrm, vmargin, base_key,
                    lr0, hdelta, root_lo, root_hi, nb_f, mono, sets,
                    start_idx, n_active, sample_rate, col_rate, anneal,
                    *, cfg, K,
                    dist_name, tweedie_power, quantile_alpha,
                    sample_rate_per_class, na_bin, chunk,
                    has_valid, has_t, adaptive, binned, has_mono, has_sets,
                    axis_name, model_axis=None):
    """One chunk of the boosting loop, per data shard (runs under
    shard_map). ``chunk`` trees are built inside ONE program via lax.scan:
    per-call dispatch overhead amortises and margins/trees stay on device
    between trees. The reference dispatches one MRTask per level per tree
    (SharedTree.java:566-635) — here a whole chunk of trees is a single
    XLA program, and the cross-shard histogram reduction is the psum
    inside the tree grower (the Rabit-allreduce / MRTask-reduce-tree
    analog, hex/tree/xgboost/rabit/RabitTrackerH2O.java,
    water/MRTask.java:871).

    ``chunk`` is a PADDING BUCKET, not the exact tree count: the traced
    ``n_active`` scalar masks trailing trees (their margin contribution
    is zeroed; the driver drops them at finalize), so one compiled
    executable serves every remaining-tree count in the bucket —
    grid/AutoML variants with different ntrees reuse it. Sampling rates
    and learn-rate annealing ride as TRACED scalars for the same reason.

    ``adaptive`` selects the fused per-node-adaptive-bins kernel over raw
    features (codes_rm then carries raw X); ``binned`` the PACKED
    global-sketch path (codes_rm/codes_t carry int8/int16 codes with
    NA = W-1 through the fused binned kernel, split thresholds as bin
    indices); otherwise the matmul/scatter global-sketch path."""
    codes = CodesView(rm=codes_rm, t=codes_t if has_t else None)
    vcodes = vrm
    F = codes_rm.shape[1]
    shard = jax.lax.axis_index(axis_name) if axis_name else 0

    mono_a = mono if has_mono else None
    sets_a = sets if has_sets else None

    def build(gv, hv, wt, col_mask, key=None):
        if adaptive:
            return grow_tree_adaptive(codes_rm, gv, hv, wt, cfg, col_mask,
                                      root_lo, root_hi, axis_name=axis_name,
                                      nb_f=nb_f, mono=mono_a, sets=sets_a,
                                      key=key, model_axis=model_axis)
        if binned:
            return grow_tree_binned(codes_rm, gv, hv, wt, cfg, col_mask,
                                    axis_name=axis_name, mono=mono_a,
                                    sets=sets_a, key=key,
                                    model_axis=model_axis, ct=codes.t)
        return grow_tree(codes, gv, hv, wt, cfg, col_mask,
                         axis_name=axis_name, mono=mono_a, sets=sets_a,
                         key=key, model_axis=model_axis)

    def valid_contrib(tree):
        if adaptive:
            return predict_raw_tree(vrm, tree, cfg.max_depth)[0]
        # binned + global-sketch: bin-space walk (na_bin = W-1 packed)
        return predict_binned(vcodes, tree, cfg.max_depth, na_bin)[0]

    def one_tree(carry, i):
        margin, vmargin, lr = carry
        # padding-bucket mask: trees at i >= n_active are built but their
        # margin contribution is zeroed (finalize drops them host-side)
        lr_t = jnp.where(i < n_active, lr, 0.0)
        key = jax.random.fold_in(base_key, start_idx + i)
        key_r, key_c = jax.random.split(key)
        if axis_name is not None:
            # decorrelate row sampling across shards (same base key would
            # repeat the identical draw pattern on every shard); the column
            # key stays common so col_mask is identical everywhere
            key_r = jax.random.fold_in(key_r, shard)
        if sample_rate_per_class is not None:
            # hex/tree/SharedTree.java:210: per-class rates override
            # sample_rate (one rate per RESPONSE class — binomial runs
            # with internal K=1, so index by the tuple length)
            srpc = jnp.asarray(sample_rate_per_class, jnp.float32)
            thr = srpc[jnp.clip(y.astype(jnp.int32), 0,
                                len(sample_rate_per_class) - 1)]
            wt = w * (jax.random.uniform(key_r, w.shape) < thr)
        else:
            # always draw against the TRACED rate: uniform() < 1.0 is
            # identically True (draws live in [0, 1)), so rate=1.0 keeps
            # the exact unsampled weights while the executable is shared
            # across every sample_rate value
            wt = w * (jax.random.uniform(key_r, w.shape) < sample_rate)
        col_mask = jax.random.uniform(key_c, (F,)) < col_rate
        trees = []
        if K == 1:
            # hdelta rides as a traced scalar so data-derived huber deltas
            # don't fragment the compile cache
            dist = get_distribution(dist_name, tweedie_power, quantile_alpha,
                                    hdelta)
            g, h = dist.grad_hess(margin, y)
            tree, nid = build(g * wt, h * wt, wt, col_mask, key=key)
            # the grower already routed every row to its leaf — reuse
            # nid instead of re-walking the tree (saves ~250ms/tree@1M)
            margin = margin + lr_t * tree["value"][nid]
            if has_valid:
                vmargin = vmargin + lr_t * valid_contrib(tree)
            trees.append(tree)
        else:
            p = jax.nn.softmax(margin, axis=1)
            for k in range(K):
                yk = (y == k).astype(jnp.float32)
                gk = (p[:, k] - yk)
                hk = jnp.maximum(p[:, k] * (1.0 - p[:, k]), 1e-9)
                tree, nid = build(gk * wt, hk * wt, wt, col_mask, key=key)
                margin = margin.at[:, k].add(lr_t * tree["value"][nid])
                if has_valid:
                    vmargin = vmargin.at[:, k].add(lr_t * valid_contrib(tree))
                trees.append(tree)
        stacked = {kk: jnp.stack([t[kk] for t in trees])
                   for kk in trees[0]}
        return (margin, vmargin, lr * anneal), stacked

    (margin, vmargin, _), chunk_trees = jax.lax.scan(
        one_tree, (margin, vmargin, lr0), jnp.arange(chunk))
    return margin, vmargin, chunk_trees


@lru_cache(maxsize=128)
def _compiled_chunk(mesh, cfg, K, dist_name, tweedie_power, quantile_alpha,
                    sample_rate_per_class, na_bin, chunk, has_valid, has_t,
                    adaptive, binned=False, has_mono=False, has_sets=False,
                    donate=False):
    """Build + cache the sharded jitted chunk step for a given mesh/config.

    Rows ride the mesh 'data' axis; tree arrays come back replicated (every
    shard computes identical splits from the psum'd histograms — the same
    redundancy the reference's per-node DTree split scan has).

    ``donate=True`` donates the margin/vmargin operands: each chunk's
    margins are dead the moment the next chunk's outputs exist, so XLA
    reuses their HBM instead of holding two generations live. The driver
    only donates when early stopping is off (a stop rollback needs the
    committed chunk's buffers intact)."""
    # split search shards over the model axis whenever the mesh HAS one
    # (feature blocks per shard, all_gather'd winners — tree.py
    # _find_splits_sharded); H2O3_SPMD=0 keeps it off everywhere
    model_axis = (MODEL_AXIS
                  if mesh.shape[MODEL_AXIS] > 1 and spmd_enabled()
                  else None)
    body = partial(_gbm_chunk_body, cfg=cfg, K=K, dist_name=dist_name,
                   tweedie_power=tweedie_power, quantile_alpha=quantile_alpha,
                   sample_rate_per_class=sample_rate_per_class,
                   na_bin=na_bin, chunk=chunk,
                   has_valid=has_valid, has_t=has_t,
                   adaptive=adaptive, binned=binned, has_mono=has_mono,
                   has_sets=has_sets,
                   axis_name=DATA_AXIS, model_axis=model_axis)
    in_specs = (P(DATA_AXIS),                              # codes_rm / raw X
                P(None, DATA_AXIS) if has_t else P(DATA_AXIS),  # codes_t/dummy
                P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),  # margin, y, w
                P(DATA_AXIS), P(DATA_AXIS),                # vrm, vmargin
                P(), P(), P(), P(), P(), P(),       # key, lr0, hdelta, lo/hi, nb_f
                P(), P(), P(),                      # mono, sets, start
                P(), P(), P(), P())                 # n_active, rates, anneal
    out_specs = (P(DATA_AXIS), P(DATA_AXIS), P())
    f = jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
    return jax.jit(f, donate_argnums=(2, 6) if donate else ())


class H2OGradientBoostingEstimator(ModelBuilder):
    algo = "gbm"
    supports_streaming = True

    def __init__(self, **params):
        merged = dict(GBM_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    # -- driver ---------------------------------------------------------

    def _resolve_distribution(self, spec: TrainingSpec) -> str:
        d = (self.params.get("distribution") or "auto").lower()
        if d in ("auto", ""):
            if spec.nclasses == 2:
                return "bernoulli"
            if spec.nclasses > 2:
                return "multinomial"
            return "gaussian"
        return d

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job) -> GBMModel:
        dist_name = self._resolve_distribution(spec)
        if spec.stream:
            return self._train_streaming(spec, valid_spec, dist_name, job)
        try:
            return self._train_dense(spec, valid_spec, dist_name, job)
        except Exception as e:   # noqa: BLE001 — classified below
            from h2o3_tpu.resilience import is_oom
            if not is_oom(e):
                raise
            return self._degrade_to_streaming(spec, valid_spec, dist_name,
                                              job, e)

    def _degrade_to_streaming(self, spec: TrainingSpec, valid_spec,
                              dist_name, job: Job,
                              cause: BaseException) -> GBMModel:
        """Device OOM mid-train: degrade from the dense grower to the
        resident-window streamed path (water/Cleaner.java graceful
        degradation) instead of crashing the job — slower, but the
        train COMPLETES. The design matrix is pulled back to host and
        the streamed pipeline re-uploads only what its memman window
        allows resident."""
        from h2o3_tpu.log import warn
        warn("%s: device OOM during dense training (%s: %s) — degrading "
             "to the streamed resident-window path", self.algo,
             type(cause).__name__, cause)
        telemetry.counter(
            "h2o3_degrade_total", {"algo": self.algo},
            help="dense→streamed graceful degradations on device OOM"
        ).inc()
        from dataclasses import replace as dc_replace
        X_host = np.asarray(telemetry.device_get(spec.X,
                                                 pipeline="train"),
                            np.float32)
        host_spec = dc_replace(spec, X=None, X_host=X_host, stream=True)
        try:
            return self._train_streaming(host_spec, valid_spec, dist_name,
                                         job)
        except NotImplementedError as e2:
            # this configuration has no streamed fallback (multinomial,
            # huber, constraints, …): surface the ORIGINAL OOM — it is
            # the actionable failure — with the degrade refusal chained
            warn("%s: streamed fallback unavailable (%s) — re-raising "
                 "the device OOM", self.algo, e2)
            raise cause from e2

    def _train_dense(self, spec: TrainingSpec, valid_spec, dist_name,
                     job: Job) -> GBMModel:
        p = self.params
        K = spec.nclasses if spec.nclasses > 2 else 1
        task = ("binomial" if spec.nclasses == 2
                else "multinomial" if K > 1 else "regression")
        nbins = int(p["nbins"])
        hist_type = (p.get("histogram_type") or "uniform_adaptive").lower()
        t_bin0 = time.time()           # span wall anchor
        t_bin0_m = time.monotonic()    # duration clock (NTP-immune)
        # packed binned-code hot path (ISSUE 12): bin ONCE per train
        # into int8/int16 codes and run the fused binned level kernel —
        # the default wherever compiled pallas runs. histogram_type=
        # 'random' keeps the adaptive kernel (per-tree grid phase needs
        # per-level rebinning, which packing removes by design).
        packed_req = packed_codes_requested(p) and hist_type != "random"
        if (packed_req
                and not binned_feasible(
                    packed_bins_upper_bound(spec, p), spec.n_features,
                    int(p["max_depth"]))
                and hist_type in ADAPTIVE_HIST_TYPES
                and adaptive_feasible(spec, p, int(p["max_depth"]))):
            # cheap pre-gate from the cat domains alone: packing CANNOT
            # come in under its lane/VMEM caps, so take the adaptive
            # kernel without paying the O(rows*F) sketch + digitise
            packed_req = False
        # uniform_adaptive (reference default) runs the fused per-node
        # adaptive kernel on raw features; the global-sketch path handles
        # quantiles_global and nbins beyond the adaptive kernel's 254 cap
        adaptive = (hist_type in ADAPTIVE_HIST_TYPES + ("random",)
                    and not packed_req
                    and adaptive_feasible(spec, p, int(p["max_depth"])))
        packed = False
        pc = None
        if adaptive:
            bm = None
            cfg, root_lo, root_hi, nb_f = adaptive_setup(
                spec, p, int(p["max_depth"]))
        else:
            # device-side sketch: X never leaves HBM (the old path
            # device_get the whole matrix just to run np.quantile on it)
            # packed mode skips the int32 transposed pallas operand
            # (with_t): pack_codes supersedes it with the int8/int16
            # layouts, and building a rows*F*4 copy just to drop it
            # would cost the HBM the packing saves
            bm = bin_matrix_device(spec.X, spec.names,
                                   spec.is_cat, spec.nrow, nbins=max(nbins, 2),
                                   nbins_cats=int(p["nbins_cats"]),
                                   histogram_type=hist_type,
                                   with_t=not packed_req)
            packed = (packed_req
                      and binned_feasible(bm.n_bins, bm.n_features,
                                          int(p["max_depth"])))
            if (not packed and packed_req
                    and hist_type in ADAPTIVE_HIST_TYPES
                    and adaptive_feasible(spec, p, int(p["max_depth"]))):
                # packing infeasible (sketch bin count past the 254-lane
                # cap / VMEM): fall back to the fused ADAPTIVE kernel,
                # not the slow matmul path the sketch would otherwise
                # route to
                adaptive = True
                bm = None
                cfg, root_lo, root_hi, nb_f = adaptive_setup(
                    spec, p, int(p["max_depth"]))
            if packed:
                pc = pack_codes(bm)
                # free the int32 code view: the packed layouts replace
                # it (1-2 bytes/value x2 <= half the f32 X footprint);
                # only bm.edges / n_bins are read from here on
                bm.codes = CodesView(rm=pc.rm, t=None)
            if not adaptive:
                cfg = TreeConfig(max_depth=int(p["max_depth"]),
                                 n_bins=bm.n_bins,
                                 n_features=bm.n_features,
                                 min_rows=float(p["min_rows"]),
                                 min_split_improvement=float(p["min_split_improvement"]),
                                 reg_lambda=float(p.get("reg_lambda", 0.0)),
                                 reg_alpha=float(p.get("reg_alpha", 0.0)),
                                 col_rate_change=float(
                                     p.get("col_sample_rate_change_per_level",
                                           1.0) or 1.0),
                                 hist_method=p.get("hist_kernel", "auto"),
                                 histogram_precision=str(
                                     p.get("histogram_precision",
                                           "auto")).lower())
                root_lo = jnp.zeros(cfg.n_features, jnp.float32)
                root_hi = jnp.zeros(cfg.n_features, jnp.float32)
                nb_f = jnp.zeros(cfg.n_features, jnp.float32)
        t_bin = time.monotonic() - t_bin0_m
        # same clocks feed train_profile AND the spans (parented under
        # the Profile's train phase span via the thread-local stack)
        telemetry.record_span("train.bin", t_bin0, t_bin)
        y, w = spec.y, spec.w
        padded = spec.X.shape[0]
        if spec.offset is not None and K > 1:
            raise NotImplementedError(
                "offset_column is not supported for multinomial GBM "
                "(matching hex/tree/gbm/GBM.java offset restrictions)")
        prior = self._resolve_checkpoint(dist_name, spec)
        huber_delta = jnp.float32(1.0)
        if K == 1 and dist_name == "huber":
            # transition point = huber_alpha w-quantile of |resid - init|
            # on the OFFSET-ADJUSTED scale (the reference re-estimates per
            # scoring round; computed once here; w-weighted so pad/NA/
            # zero-weight rows can't skew it). The quantile STAYS a device
            # scalar: it feeds the chunk step as a traced operand and the
            # distribution's jnp ops, so the old mid-train device_get was
            # a pure pipeline stall
            from h2o3_tpu.models.distributions import (weighted_median,
                                                       weighted_quantile)
            yf0 = y.astype(jnp.float32)
            if spec.offset is not None:
                yf0 = yf0 - spec.offset
            med = weighted_median(yf0, w)
            huber_delta = jnp.maximum(weighted_quantile(
                jnp.abs(yf0 - med), w,
                float(p.get("huber_alpha", 0.9))).astype(jnp.float32),
                jnp.float32(1e-10))
        dist = (self._dist(dist_name, huber_delta) if K == 1 else None)
        if K == 1:
            yf = y.astype(jnp.float32)
            if prior is not None:
                f0 = jnp.asarray(prior.f0)
                margin, prior_has_offset = self._prior_margin(
                    prior, spec, padded, K)
            else:
                if spec.offset is not None:
                    # initial value on the offset-adjusted scale, not the
                    # marginal init — early trees shouldn't spend capacity
                    # correcting a biased intercept
                    from h2o3_tpu.models.distributions import offset_adjusted_f0
                    f0 = offset_adjusted_f0(dist, yf, w, spec.offset)
                else:
                    f0 = dist.init_f0(yf, w)
                margin = jnp.full(padded, f0, jnp.float32)
                prior_has_offset = False
            if spec.offset is not None and not prior_has_offset:
                # offset enters the margin, not the trees: f = f0 + offset + Σ lr·tree
                # (reference GBM honors offsets in every distribution's
                # margin); a resumed margin already carries it
                margin = margin + spec.offset
        else:
            if prior is not None:
                f0 = jnp.asarray(prior.f0)
                margin, _ = self._prior_margin(prior, spec, padded, K)
            else:
                pri = jnp.maximum(
                    jnp.zeros(K, jnp.float32).at[y].add(w) / w.sum(), 1e-9)
                f0 = jnp.log(pri)
                margin = jnp.broadcast_to(f0, (padded, K)).astype(jnp.float32)
            yf = y
        seed = int(p.get("seed", -1) or -1)
        key = jax.random.PRNGKey(seed if seed != -1 else int(time.time() * 1e3) % (2**31))
        ntrees = int(p["ntrees"])
        start_trees = prior.ntrees_built if prior is not None else 0
        ntrees_new = ntrees - start_trees
        lr = float(p["learn_rate"])
        anneal = float(p["learn_rate_annealing"])
        lr *= anneal ** start_trees
        col_rate = float(p["col_sample_rate"]) * float(p["col_sample_rate_per_tree"])
        srpc = self.validate_sample_rate_per_class(spec)
        if srpc is not None and float(p.get("sample_rate", 1.0)) < 1.0:
            from h2o3_tpu.log import warn as _warn
            _warn("sample_rate is ignored when sample_rate_per_class "
                  "is specified (hex/tree/SharedTree.java:210)")
        keeper = ScoreKeeper(p.get("stopping_rounds", 0), p.get("stopping_metric"),
                             p.get("stopping_tolerance", 1e-3), task)
        interval = max(int(p.get("score_tree_interval", 5) or 5), 1)
        # validation margin tracked with train edges
        mesh = current_mesh()
        nd = n_data_shards(mesh)
        Xtr = spec.X if adaptive else (pc.rm if packed else bm.codes.rm)
        if Xtr.shape[0] % nd != 0:
            raise ValueError(
                f"padded row count {Xtr.shape[0]} is not divisible by "
                f"the {nd}-shard data axis — the training frame was built "
                f"under a different mesh; rebuild it after h2o3_tpu.init()")
        has_valid = valid_spec is not None
        if has_valid:
            if valid_spec.X.shape[0] % nd != 0:
                raise ValueError(
                    f"validation frame padded rows {valid_spec.X.shape[0]} "
                    f"not divisible by the {nd}-shard data axis — rebuild it "
                    f"after h2o3_tpu.init()")
            if adaptive:
                vtrain = valid_spec.X
            elif packed:
                # validation codes share the training sketch AND the
                # packed NA = W-1 convention (predict_binned walk)
                vtrain = pack_codes_for(valid_spec.X, bm, pc.W)
            else:
                vtrain = make_codes_view(digitize_with_edges(
                    valid_spec.X, bm.edges, bm.n_bins)).rm
            if prior is not None:
                vmargin = prior._margin_matrix(valid_spec.X).astype(jnp.float32)
            else:
                vmargin = (jnp.full(valid_spec.X.shape[0], f0, jnp.float32) if K == 1
                           else jnp.broadcast_to(f0, (valid_spec.X.shape[0], K)).astype(jnp.float32))
            if K == 1 and valid_spec.offset is not None:
                vmargin = vmargin + valid_spec.offset
        else:  # small dummies (untraced branches, but args need shapes)
            vtrain = jnp.zeros((8 * nd, cfg.n_features), Xtr.dtype)
            vmargin = (jnp.zeros(8 * nd, jnp.float32) if K == 1
                       else jnp.zeros((8 * nd, K), jnp.float32))

        # scoring cadence: early stopping OR an explicit
        # score_tree_interval both record ScoreKeeper history (the
        # reference scores every interval regardless of stopping —
        # learning_curve_plot reads this)
        # reference default score_tree_interval=0 (score only at the
        # stopping cadence); ANY positive value is an explicit request
        sti = int(p.get("score_tree_interval", 0) or 0)
        score_each = keeper.rounds > 0 or sti > 0
        chunk = interval if score_each else min(ntrees_new, 50)
        # in-training checkpoints: align chunk commits to the checkpoint
        # cadence so every `tree_interval` committed trees persist a
        # resumable state (hex/tree/SharedTree in_training_checkpoints_*)
        ckpt_dir = p.get("in_training_checkpoints_dir")
        ckpt_interval = max(int(
            p.get("in_training_checkpoints_tree_interval", 1) or 1), 1)
        ckpt_on = bool(ckpt_dir)
        if ckpt_on and not score_each:
            # align chunk commits to the checkpoint cadence — but NEVER
            # when interval scoring is on: shrinking the chunk there
            # would change the early-stopping score cadence (a silent
            # model change); checkpoints then land at the scoring
            # chunk's commit boundaries instead
            chunk = max(min(chunk, ckpt_interval), 1)
        if ckpt_on and ntrees_new / ckpt_interval > 50:
            # each commit re-fetches every committed tree + writes a
            # full artifact (O(T²) across the train) — loud, not silent
            from h2o3_tpu.log import warn as _warn
            _warn("gbm: in_training_checkpoints_tree_interval=%d means "
                  "~%d checkpoint commits, each fetching all committed "
                  "trees and writing a full artifact — consider a "
                  "larger interval", ckpt_interval,
                  int(ntrees_new / ckpt_interval))
        trees_since_ckpt = 0
        if packed:
            has_t = pc.t is not None
            codes_t_arg = pc.t if has_t else Xtr
            na_bin = pc.na_bin                   # reserved lane W-1
        else:
            has_t = (not adaptive) and bm.codes.t is not None
            codes_t_arg = bm.codes.t if has_t else Xtr  # dummy otherwise
            na_bin = 0 if adaptive else bm.na_bin
        # monotone constraints ({col: ±1}, hex/tree/DTree Constraints) and
        # interaction constraints ([[col,...],...], per-branch feature
        # allowance) ride as traced arrays through the chunk step
        mc = p.get("monotone_constraints") or {}
        has_mono = bool(mc)
        mono_arr = jnp.zeros(cfg.n_features, jnp.int32)
        if has_mono:
            mono_host = np.zeros(cfg.n_features, np.int32)
            for cname, direction in dict(mc).items():
                if cname not in spec.names:
                    raise ValueError(
                        f"monotone_constraints column '{cname}' is not a "
                        f"training feature {list(spec.names)}")
                if spec.is_cat[spec.names.index(cname)]:
                    raise ValueError(
                        f"monotone constraint on categorical column "
                        f"'{cname}' is not supported (reference restricts "
                        f"constraints to numeric columns)")
                mono_host[spec.names.index(cname)] = int(direction)
            mono_arr = jnp.asarray(mono_host)
        ic = p.get("interaction_constraints") or None
        has_sets = bool(ic)
        sets_arr = jnp.ones((1, cfg.n_features), bool)
        if has_sets:
            sets_host = np.zeros((len(ic), cfg.n_features), bool)
            for si, group in enumerate(ic):
                for cname in group:
                    if cname not in spec.names:
                        raise ValueError(
                            f"interaction_constraints column '{cname}' is "
                            f"not a training feature")
                    sets_host[si, spec.names.index(cname)] = True
            sets_arr = jnp.asarray(sets_host)
        # pin the margins to the data sharding BEFORE the first dispatch:
        # freshly-built margins (jnp.full of a traced f0) are replicated,
        # while every chunk OUTPUT is data-sharded — without this the
        # first call of each bucket compiles a second, replicated-operand
        # executable (visible as one stray recompile per new ntrees)
        from jax.sharding import NamedSharding
        rows_sh = NamedSharding(mesh, P(DATA_AXIS))
        margin = resilient_device_put(margin, rows_sh, pipeline="train")
        vmargin = resilient_device_put(vmargin, rows_sh,
                                       pipeline="train")
        # buffer donation is only safe when (a) an early stop can never
        # force a rollback to the previous chunk's margins and (b) no
        # in-training checkpoint will device_get a margin after it has
        # been donated to the next dispatch
        donate = (keeper.rounds == 0 and not ckpt_on
                  and jax.default_backend() == "tpu")
        sc_spec = valid_spec if has_valid else spec
        want_auc = keeper.metric == "auc"
        rate_t = jnp.float32(float(p["sample_rate"]))
        col_rate_t = jnp.float32(col_rate)
        anneal_t = jnp.float32(anneal)
        all_trees = []          # [(device chunk trees, n_active)]
        built = 0               # committed trees
        disp = 0                # dispatched trees (committed + in flight)
        inflight = None         # last dispatched, not yet committed chunk
        stopped = False
        # per-shard collective/straggler observations (ISSUE 8): the
        # commit point sits one chunk behind the dispatch frontier, so
        # watching the committed chunk's output shards there costs the
        # pipeline nothing the score fetch wasn't already paying
        shard_obs = []
        partn = partitioner(mesh)
        # performance accounting (ISSUE 11): per-executable cost capture
        # at this jit seam + the measured loop wall -> the train's
        # roofline point (None when telemetry is off — checked no-op)
        perf_acc = telemetry.costmodel.accumulator(
            "train.loop", n_devices=mesh.size)
        jax.block_until_ready(margin)  # h2o3-lint: allow[transfer-seam] loop-entry fence: resume-margin upload must land before the tree-loop clock starts

        def commit_ckpt(cur_margin):
            """Write an in-training checkpoint at the COMMITTED tree
            count (``built`` trees; ``cur_margin`` is their margin).
            The WHOLE commit — finalize's tree device_get included — is
            advisory: a transient fetch failure here must neither kill
            a healthy train nor mask the original error on the
            failure-path commit."""
            try:
                m = self._finalize(spec, None, dist_name, f0, all_trees,
                                   bm, cfg, K, built, cur_margin, None,
                                   keeper, tree_offset=start_trees,
                                   prior=prior, dist=dist,
                                   with_metrics=False)
                self._write_in_training_checkpoint(m, cur_margin,
                                                   ckpt_dir, spec=spec)
                from h2o3_tpu.telemetry import blackbox
                blackbox.record("ckpt_commit",
                                member=str(self.params.get("model_id")
                                           or self.algo),
                                payload=f"trees={built} algo={self.algo}")
            except Exception as e:  # noqa: BLE001 — advisory only
                from h2o3_tpu.log import warn
                warn("%s: in-training checkpoint commit failed: %s",
                     self.algo, e)

        t_loop0 = time.time()          # span wall anchor
        t_loop0_m = time.monotonic()
        score_s = 0.0
        # pipelined boosting: dispatch chunk k+1 BEFORE blocking on chunk
        # k's score scalars, so the metric fetch overlaps device compute.
        # With early stopping on, chunk k+1 is SPECULATIVE: a stop verdict
        # discards it (margins roll back to chunk k's outputs), keeping
        # the built-tree count identical to the serial loop.
        while disp < ntrees_new and not stopped:
            c = min(chunk, ntrees_new - disp)
            if score_each and c == chunk:
                # full score intervals compile at their EXACT length: an
                # off-bucket interval (say 6) repeats every chunk, and
                # rounding it up would pay masked trees on EVERY chunk —
                # one compile per interval value instead
                bucket = c
            else:
                # single-shot lengths (the non-scoring whole-train chunk,
                # any final partial interval) round up to a shared bucket
                # so grid/AutoML ntrees variants reuse the executable;
                # masked waste is bounded by ONE chunk per train
                bucket = chunk_bucket(c)
            # ONE spelling of the executable cache key, shared by the
            # dispatch and the cost capture below — the two must
            # describe the SAME executable or the accounting drifts
            lru_key = (mesh, cfg, K, dist_name,
                       float(p["tweedie_power"]),
                       float(p.get("quantile_alpha", 0.5)),
                       srpc, na_bin, bucket, has_valid, has_t,
                       adaptive, packed, has_mono, has_sets, donate)
            def _dispatch(lru_key=lru_key, c=c):
                # compile + execute behind the fault seam: both the
                # executable build and the chunk dispatch may fail
                # transiently (the injected faults reproduce that)
                from h2o3_tpu import faults
                if faults.ACTIVE:
                    faults.check("compile", pipeline="train")
                step = _compiled_chunk(*lru_key)
                if faults.ACTIVE:
                    faults.check("execute", pipeline="train")
                    if nd > 1:
                        # ICI collective seam: the per-level histogram
                        # psum rides inside this dispatch on a multi-
                        # shard mesh — a transient interconnect failure
                        # surfaces here and retries like any other
                        # transient execute error
                        faults.check("collective", pipeline="train")
                return step(
                    Xtr, codes_t_arg, margin, yf, w, vtrain, vmargin,
                    key, jnp.float32(lr), huber_delta,
                    root_lo, root_hi, nb_f, mono_arr, sets_arr,
                    jnp.int32(start_trees + disp), jnp.int32(c),
                    rate_t, col_rate_t, anneal_t)
            try:
                # transient device failures retry with backoff; donated
                # operand buffers cannot be replayed, so donation (TPU,
                # no early stopping) disables the retry path
                nm, nv, chunk_trees = retry_transient(
                    _dispatch, site="train.execute",
                    attempts=1 if donate else 3)
                # dispatch is async — this clock starts when the chunk
                # is enqueued, not when it completes, so THIS chunk's
                # cold-bucket compile stays out of its own step numbers;
                # a later chunk's compile delaying the observation is
                # caught by shardstats' staleness check instead
                t_disp = time.perf_counter()
            except BaseException:
                # commit the already-computed in-flight chunk and leave
                # a resumable checkpoint before the error propagates —
                # a mid-train kill then resumes from the committed
                # prefix instead of tree 0 (`margin` still holds that
                # chunk's outputs; it is only rebound after dispatch)
                if inflight is not None:
                    all_trees.append((inflight["trees"], inflight["c"]))
                    built += inflight["c"]
                    inflight = None
                    if ckpt_on:
                        commit_ckpt(margin)
                raise
            if perf_acc is not None:
                # per-executable FLOP/byte attribution: ONE trace+lower
                # per (config, bucket) key for the process lifetime (NO
                # backend compile — the zero-recompile guards never see
                # it); warm dispatches pay a dict lookup. scale=bucket:
                # HLO cost analysis counts the tree-scan body once, and
                # the executable runs it `bucket` times (masked trees
                # included — they compute). The capture wall is noted
                # so a cold key's trace+lower (host work inside the
                # measured loop) is excluded from device seconds.
                t_cap0 = time.perf_counter()
                step = _compiled_chunk(*lru_key)    # lru cache hit
                perf_acc.add(telemetry.costmodel.executable_cost(
                    ("gbm.chunk",) + lru_key,
                    lambda s=step, d=disp, cc=c: s.lower(
                        Xtr, codes_t_arg, margin, yf, w, vtrain,
                        vmargin, key, jnp.float32(lr), huber_delta,
                        root_lo, root_hi, nb_f, mono_arr, sets_arr,
                        jnp.int32(start_trees + d), jnp.int32(cc),
                        rate_t, col_rate_t, anneal_t),
                    scale=bucket))
                perf_acc.note_capture_seconds(
                    time.perf_counter() - t_cap0)
            pend = None
            if score_each:
                pend = self._score_entry_dev(nv if has_valid else nm,
                                             sc_spec, dist, K,
                                             start_trees + disp + c,
                                             want_auc=want_auc)
            if inflight is not None:
                # commit the previous chunk; its metric scalars land
                # while the device crunches the chunk just dispatched
                all_trees.append((inflight["trees"], inflight["c"]))
                built += inflight["c"]
                trees_since_ckpt += inflight["c"]
                if nd > 1 and telemetry.enabled():
                    shard_obs.append(partn.observe_step(
                        inflight["trees"], inflight["t_disp"],
                        algo=self.algo))
                if score_each:
                    t_s0 = time.monotonic()
                    keeper.record(self._score_entry_fetch(inflight["pend"]))
                    score_s += time.monotonic() - t_s0
                    if keeper.rounds > 0 and keeper.should_stop():
                        # discard the speculative dispatch: the margin/
                        # vmargin locals still hold the COMMITTED chunk's
                        # outputs (they are only rebound to the new
                        # dispatch below), so breaking here is the
                        # rollback — nm/nv are simply never used
                        stopped = True
                        break
                if ckpt_on and trees_since_ckpt >= ckpt_interval:
                    commit_ckpt(margin)   # margin = committed chunk's
                    trees_since_ckpt = 0
            inflight = {"trees": chunk_trees, "c": c, "pend": pend,
                        "t_disp": t_disp}
            margin, vmargin = nm, nv
            disp += c
            lr *= anneal ** c
            # progress by DISPATCHED trees: the committed count lags one
            # chunk behind and would sit at 0 through a one-chunk train
            job.set_progress(0.5 * disp / ntrees_new)
            if job.cancel_requested or job.preempt_requested:
                break
        # checkpoint-based preemption (ISSUE 15): the scheduler asked
        # this train to yield — commit the prefix as a DKV checkpoint
        # (below) and unwind; user cancel wins and keeps its semantics.
        # A preempt that raced the last chunk (every tree dispatched) is
        # moot: the train just finishes.
        preempting = (job.preempt_requested and not job.cancel_requested
                      and not stopped and disp < ntrees_new)
        if not stopped and inflight is not None:
            all_trees.append((inflight["trees"], inflight["c"]))
            built += inflight["c"]
            trees_since_ckpt += inflight["c"]
            if nd > 1 and telemetry.enabled():
                shard_obs.append(partn.observe_step(
                    inflight["trees"], inflight["t_disp"],
                    algo=self.algo))
            if score_each:
                t_s0 = time.monotonic()
                keeper.record(self._score_entry_fetch(inflight["pend"]))
                score_s += time.monotonic() - t_s0
            if (ckpt_on and trees_since_ckpt > 0) \
                    or (preempting and built > 0):
                # final commit covers cancellation too: a cancelled job
                # leaves a checkpoint at its committed tree count. A
                # PREEMPTED train commits even without a checkpoint dir
                # (DKV-only artifact) — that checkpoint's exact f32
                # margin is what makes the scheduler's resume
                # bit-identical
                commit_ckpt(margin)
        if preempting:
            from h2o3_tpu.jobs import JobPreempted
            raise JobPreempted(
                f"gbm train preempted at {built} committed trees"
                + (f": {job.preempt_reason}" if job.preempt_reason
                   else ""))

        jax.block_until_ready(margin)  # h2o3-lint: allow[transfer-seam] train-loop timing fence: the loop span must cover device completion, not dispatch
        t_loop = time.monotonic() - t_loop0_m
        telemetry.record_span("train.loop", t_loop0, t_loop,
                              trees=built)
        if score_s:
            telemetry.record_span("train.score", t_loop0, score_s)
        t_fin0 = time.time()           # span wall anchor
        t_fin0_m = time.monotonic()
        model = self._finalize(spec, valid_spec, dist_name, f0, all_trees, bm,
                               cfg, K, built, margin,
                               vmargin if has_valid else None, keeper,
                               tree_offset=start_trees, prior=prior,
                               dist=dist)
        if ckpt_on:
            # the finished model supersedes the in-training DKV entry —
            # leaving it would accumulate partial-model copies (with
            # dataset-sized resume margins) across trains and surface
            # phantom models on GET /3/Models; disk artifacts remain
            from h2o3_tpu import dkv
            dkv.remove(f"{model.key}_ckpt")
        t_fin = time.monotonic() - t_fin0_m
        telemetry.record_span("train.finalize", t_fin0, t_fin)
        model.output["training_loop_seconds"] = t_loop
        model.output["train_profile"] = {
            "bin_s": round(t_bin, 4), "loop_s": round(t_loop, 4),
            "score_s": round(score_s, 4),
            "finalize_s": round(t_fin, 4)}
        if perf_acc is not None:
            # measured device time = the loop wall (dispatches pipeline;
            # the block_until_ready fence above makes it device-
            # saturated) paired with the dispatched executables' cost
            perf_acc.add_device_seconds(t_loop)
            rp = perf_acc.finish()
            if rp is not None:
                model.output["perf"] = {"train": rp,
                                        "phases": {"loop": rp}}
        # hot-loop representation record (ISSUE 12): what the level
        # kernel actually streamed — bench.py and profile_train.py read
        # this for the bytes/row attribution
        model.output["packed_codes"] = packed_codes_record(
            packed, dtype=pc.rm.dtype if packed else None,
            W=pc.W if packed else None,
            bytes_per_value=pc.itemsize if packed else None,
            n_bins=bm.n_bins if packed else None)
        # the dense chunk body traces its whole level loop into ONE
        # executable — every level rides a single dispatch (the fused
        # shape the streamed driver's L-level windows approximate)
        model.output["levels_per_dispatch"] = int(cfg.max_depth)
        # mesh layout this train actually ran under — the bench scaling
        # round and the SPMD parity tests assert against it instead of
        # inferring from env
        model.output["spmd"] = {
            "n_data": nd, "n_model": n_model_shards(mesh),
            "model_axis_split_search": bool(
                n_model_shards(mesh) > 1 and spmd_enabled())}
        # collective/straggler attribution for the scaling verdict
        # (tools/multichip_bench.py reads this per point)
        from h2o3_tpu.parallel.shardstats import merge_observations
        collective = merge_observations(shard_obs)
        if collective is not None:
            model.output["spmd"]["collective"] = collective
        return model

    def _train_streaming(self, spec: TrainingSpec, valid_spec, dist_name,
                         job: Job) -> GBMModel:
        """Memory-pressure path: the frame exceeded the device budget, so
        X stays on host and every tree streams row chunks through the
        adaptive level kernels (models/tree.py
        grow_tree_adaptive_streamed over a models/streaming.py
        StreamedChunks pipeline: budget-sized resident window uploaded
        once per train, overflow chunks double-buffered per level;
        water/Cleaner.java graceful degradation — slower, but any frame
        that fits host RAM trains)."""
        from h2o3_tpu import memman
        from h2o3_tpu.models.streaming import StreamedChunks
        from h2o3_tpu.models.tree import grow_tree_adaptive_streamed
        p = self.params
        if spec.nclasses > 2:
            raise NotImplementedError(
                "multinomial GBM is not supported in streaming "
                "(memory-pressure) mode; raise H2O3_DEVICE_BUDGET_BYTES "
                "or reduce the frame")
        if valid_spec is not None:
            raise NotImplementedError(
                "validation_frame is not supported in streaming mode")
        # options the dense path honors but this path does not: fail
        # fast rather than silently train a different model
        if spec.offset is not None:
            raise NotImplementedError(
                "offset_column is not supported in streaming mode")
        if p.get("sample_rate_per_class"):
            raise NotImplementedError(
                "sample_rate_per_class is not supported in streaming "
                "mode")
        if float(p.get("col_sample_rate_change_per_level", 1.0)
                 or 1.0) != 1.0:
            raise NotImplementedError(
                "col_sample_rate_change_per_level is not supported in "
                "streaming mode")
        if dist_name == "huber":
            raise NotImplementedError(
                "huber distribution is not supported in streaming mode "
                "(its delta re-estimation needs the dense path)")
        if p.get("monotone_constraints") or p.get("interaction_constraints"):
            raise NotImplementedError(
                "monotone/interaction constraints are not supported in "
                "streaming mode")
        K = 1
        dist = self._dist(dist_name)
        X_host = spec.X_host
        rows = spec.nrow
        X_host = X_host[:rows]
        yw_host = telemetry.device_get((spec.y, spec.w),
                                       pipeline="train")
        y_host = np.asarray(yw_host[0])[:rows].astype(np.float32)
        w_host = np.asarray(yw_host[1])[:rows].astype(np.float32)
        budget = memman.manager().budget
        # packed binned-code streaming (ISSUE 12): bin once on host,
        # stream 1-2 byte codes — the compressed resident window fits
        # ~4x more rows under the same budget and overflow H2D moves
        # codes, not f32. histogram_type='random' keeps the adaptive
        # kernel (per-tree grid phase needs per-level rebinning).
        from h2o3_tpu.ops.binning import _edges_host, digitize_codes_host
        hist_type = (p.get("histogram_type") or "uniform_adaptive").lower()
        packed = packed_codes_requested(p) and hist_type != "random"
        bin_edges = None
        W = None
        if packed:
            # feasibility from the (cheap) edge sketch BEFORE paying
            # the O(rows·F) host digitise — an infeasible bin count
            # must not build a throwaway code matrix on the
            # memory-pressure path
            try:
                bin_edges, n_bins_eff = _edges_host(
                    X_host, rows, spec.is_cat, max(int(p["nbins"]), 2),
                    int(p.get("nbins_cats", 1024)), hist_type)
                packed = binned_feasible(n_bins_eff, spec.n_features,
                                         int(p["max_depth"]))
            except ValueError:
                packed = False      # bin count past the routing cap
            if packed:
                codes_host, W = digitize_codes_host(X_host, bin_edges,
                                                    n_bins_eff)
        if packed:
            cfg = TreeConfig(
                max_depth=int(p["max_depth"]), n_bins=n_bins_eff,
                n_features=spec.n_features,
                min_rows=float(p["min_rows"]),
                min_split_improvement=float(p["min_split_improvement"]),
                reg_lambda=float(p.get("reg_lambda", 0.0)),
                reg_alpha=float(p.get("reg_alpha", 0.0)),
                hist_method=p.get("hist_kernel", "auto"),
                histogram_precision=str(
                    p.get("histogram_precision", "auto")).lower())
            root_lo = root_hi = nb_f = None
            x_stream = codes_host
            x_itemsize = int(codes_host.dtype.itemsize)
        else:
            cfg, root_lo, root_hi, nb_f = adaptive_setup(
                spec, p, int(p["max_depth"]))
            x_stream = X_host
            x_itemsize = 4
        chunk_rows = int(max(min(
            budget // max(spec.n_features * x_itemsize * 4, 1), rows),
            16384))
        padded = int(spec.y.shape[0])
        # checkpoint continuation (formerly a streamed-path fail-fast,
        # ISSUE 9 satellite): the dense resolver's full compatibility
        # contract applies; the resume state is the saved f32 margin
        # plus the tree cursor (start_trees), so a resumed streamed
        # train is bit-identical to an uninterrupted one — and to the
        # DENSE resume on fully-resident data
        prior = self._resolve_checkpoint(dist_name, spec)
        start_trees = prior.ntrees_built if prior is not None else 0
        margin0 = None
        if prior is not None:
            f0 = float(np.asarray(prior.f0).reshape(-1)[0])
            rm = getattr(prior, "_resume_margin", None)
            sig = getattr(prior, "_resume_sig", None)
            sig_ok = (sig is None
                      or np.array_equal(np.asarray(sig),
                                        _spec_signature(spec)))
            if rm is not None and sig_ok \
                    and np.asarray(rm).shape == (padded,):
                margin0 = np.asarray(rm, np.float32)
            else:
                from h2o3_tpu.log import warn as _warn
                if rm is not None and not sig_ok:
                    _warn("checkpoint resume margin belongs to "
                          "different training data — recomputing from "
                          "trees")
                # recompute chunk-wise: the whole host matrix must
                # never upload at once on this memory-pressure path
                margin0 = np.empty(rows, np.float32)
                for s in range(0, rows, chunk_rows):
                    e = min(s + chunk_rows, rows)
                    margin0[s:e] = np.asarray(jax.device_get(  # h2o3-lint: allow[transfer-seam,host-sync-hot-loop] once-per-RESUME chunked recompute on the memory-pressure path, not the tree loop
                        prior._margin_matrix(jnp.asarray(X_host[s:e]))
                        .astype(jnp.float32)))
        else:
            f0 = float(telemetry.device_get(
                dist.init_f0(jnp.asarray(y_host), jnp.asarray(w_host)),
                pipeline="train"))
        ntrees = int(p["ntrees"])
        ntrees_new = ntrees - start_trees
        anneal = float(p.get("learn_rate_annealing", 1.0) or 1.0)
        lr = float(p["learn_rate"]) * anneal ** start_trees
        col_rate = (float(p.get("col_sample_rate", 1.0))
                    * float(p.get("col_sample_rate_per_tree", 1.0)))
        seed = int(p.get("seed", -1) or -1)
        key = jax.random.PRNGKey(seed if seed != -1 else 0)
        chunks = StreamedChunks(x_stream, y_host, w_host, f0, chunk_rows,
                                padded_rows=padded, margin0=margin0,
                                packed_W=W if packed else None)
        # cancel propagation into the streamed pipeline: the level
        # passes poll this BETWEEN levels (never mid leaf-apply), so a
        # REST cancel / watchdog max_runtime kill lands promptly even
        # inside a deep tree's chunk uploads
        chunks.cancel_check = lambda: job.cancel_requested
        # fused-window clamp (ISSUE 17): a pending preempt OR cancel
        # shrinks the next L-level window to one level so the
        # cooperative yield lands at the next boundary — the PR-15
        # chunk-commit contract survives multi-level fusion
        chunks.interrupt_check = lambda: job.preempt_requested
        # performance accounting (ISSUE 11): the streamed level passes
        # feed this through chunks.perf_acc (tree.py captures each level
        # kernel's cost once per shape); coverage noted — the routing/
        # leaf-apply passes are not costed
        perf_acc = telemetry.costmodel.accumulator(
            "train.stream", note="level-histogram kernels only")
        chunks.perf_acc = perf_acc
        from h2o3_tpu.jobs import JobCancelled
        trees = []

        def build_model(trees_list):
            """Partial/final GBMModel from the committed streamed trees
            (prior trees prepended, dense-_finalize shape) — shared by
            the in-training checkpoint commits and the train tail."""
            T = len(trees_list)
            th = {k: np.stack([tr[k] for tr in trees_list]) for k in
                  ("feat", "thr", "na_left", "is_split", "value",
                   "node_w")}
            if prior is not None:
                th = {
                    "feat": np.concatenate(
                        [np.asarray(prior._feat), th["feat"]]),
                    "thr": np.concatenate(
                        [np.asarray(prior._thr), th["thr"]]),
                    "na_left": np.concatenate(
                        [np.asarray(prior._na_left), th["na_left"]]),
                    "is_split": np.concatenate(
                        [np.asarray(prior._is_split), th["is_split"]]),
                    "value": np.concatenate(
                        [np.asarray(prior._value), th["value"]]),
                    "node_w": (np.concatenate(
                        [np.asarray(prior._node_w), th["node_w"]])
                        if getattr(prior, "_node_w", None) is not None
                        else None),
                }
            m = GBMModel(self._model_key(), p, spec,
                         dist_name, np.float32(f0), th, [],
                         cfg.n_bins, cfg.max_depth, start_trees + T,
                         spec.nclasses)
            gains = np.stack([tr["gain"] for tr in trees_list])
            feat = np.stack([tr["feat"] for tr in trees_list])
            vi = np.zeros(len(spec.names))
            live = feat >= 0
            np.add.at(vi, feat[live], gains[live])
            if prior is not None:
                pv = prior.output.get("variable_importances")
                if pv:
                    lut = {nn: i for i, nn in enumerate(spec.names)}
                    for nn, g in zip(pv["variable"],
                                     pv["relative_importance"]):
                        if nn in lut:
                            vi[lut[nn]] += g
            order = np.argsort(-vi)
            rel = vi / vi.max() if vi.max() > 0 else vi
            m.output["variable_importances"] = {
                "variable": [spec.names[i] for i in order],
                "relative_importance": vi[order].tolist(),
                "scaled_importance": rel[order].tolist(),
                "percentage": (vi[order] / vi.sum() if vi.sum() > 0
                               else vi[order]).tolist()}
            return m

        def attach_resume_state(m):
            """The streamed resume state: the exact f32 margin at the
            committed tree count (window-cursor = ntrees_built) + the
            PR-6 data signature, so resumes are bit-identical and
            never applied to a different frame."""
            mfull = chunks.gather_margin()
            mpad = np.full(padded, np.float32(f0), np.float32)
            mpad[:rows] = mfull      # pad rows carry w=0 everywhere
            m._resume_margin = mpad
            m._resume_sig = _spec_signature(spec)

        # in-training checkpoints on the resident-window path (formerly
        # a warn-and-drop): every tree_interval committed trees persist
        # a resumable artifact, same contract as the dense path
        ckpt_dir = p.get("in_training_checkpoints_dir")
        ckpt_interval = max(int(
            p.get("in_training_checkpoints_tree_interval", 1) or 1), 1)
        ckpt_on = bool(ckpt_dir)
        trees_since_ckpt = 0

        def commit_ckpt():
            # advisory end to end (dense commit_ckpt contract): a
            # checkpoint write must neither kill a healthy train nor
            # mask the original error on the failure-path commit
            try:
                from h2o3_tpu.models.model_base import \
                    persist_in_training_ckpt
                m = build_model(trees)
                attach_resume_state(m)
                persist_in_training_ckpt(m, self.algo, ckpt_dir)
                from h2o3_tpu.telemetry import blackbox
                blackbox.record("ckpt_commit",
                                member=str(p.get("model_id")
                                           or self.algo),
                                payload=f"trees={len(trees)} "
                                        f"algo={self.algo} streamed=1")
            except Exception as ce:  # noqa: BLE001 — advisory only
                from h2o3_tpu.log import warn as _warn
                _warn("%s: streamed in-training checkpoint commit "
                      "failed: %s", self.algo, ce)

        t0 = time.monotonic()
        for t in range(ntrees_new):
            # global tree index keys the RNG (dense start_idx contract)
            # so a resumed train draws the same samples the
            # uninterrupted one would have
            tkey = jax.random.fold_in(key, start_trees + t)
            col_mask = None
            if col_rate < 1.0:
                col_mask = (jax.random.uniform(
                    jax.random.fold_in(tkey, 1), (spec.n_features,))
                    < col_rate)
            try:
                if packed:
                    from h2o3_tpu.models.tree import \
                        grow_tree_binned_streamed
                    tree = grow_tree_binned_streamed(
                        chunks, dist, lr, cfg, bin_edges, key=tkey,
                        sample_rate=float(p.get("sample_rate", 1.0)),
                        col_mask=col_mask)
                else:
                    tree = grow_tree_adaptive_streamed(
                        chunks, dist, lr, cfg, root_lo, root_hi, nb_f,
                        key=tkey,
                        sample_rate=float(p.get("sample_rate", 1.0)),
                        col_mask=col_mask)
            except JobCancelled:
                # the partial tree applied no margin updates (cancel
                # only fires between level passes, before leaf apply) —
                # drop it and finalize the committed trees
                break
            except BaseException:
                # NO failure-path commit here (unlike the dense path,
                # whose per-chunk margin is an immutable device array
                # rebound only at commit points): the streamed grower
                # mutates margin_host chunk-by-chunk DURING leaf apply,
                # so a mid-tree error leaves margins that partially
                # include the failed tree — committing them would
                # silently break resume bit-identity. The last interval
                # commit is the resumable prefix.
                raise
            # lr-scale values like the dense finalize does (float64
            # product rounded once at model construction — bit-matching
            # `val * lrs[:, None]` in _finalize)
            tree = dict(tree)
            tree["value"] = tree["value"].astype(np.float64) * lr
            trees.append(tree)
            trees_since_ckpt += 1
            lr *= anneal
            if ckpt_on and trees_since_ckpt >= ckpt_interval \
                    and len(trees) < ntrees_new:
                commit_ckpt()
                trees_since_ckpt = 0
            job.set_progress((t + 1) / ntrees_new)
            if job.cancel_requested or job.preempt_requested:
                break
        preempting = (job.preempt_requested and not job.cancel_requested
                      and len(trees) < ntrees_new)
        if preempting:
            # checkpoint-based preemption (ISSUE 15): commit the built
            # prefix (DKV-only when no checkpoint dir is set) and unwind
            # so the scheduler can requeue + resume bit-identically —
            # margin_host holds exactly the committed trees' updates.
            # Zero trees built → no checkpoint; the requeue reruns clean.
            if trees:
                commit_ckpt()
            from h2o3_tpu.jobs import JobPreempted
            raise JobPreempted(
                f"gbm streamed train preempted at {len(trees)} trees"
                + (f": {job.preempt_reason}" if job.preempt_reason
                   else ""))
        if not trees:
            raise JobCancelled(
                "cancelled before the first streamed tree completed")
        margin_host = chunks.gather_margin()
        t_loop = time.monotonic() - t0
        T = len(trees)
        model = build_model(trees)
        if ckpt_on:
            # final commit: durable artifact kept, DKV `<key>_ckpt`
            # dropped — the finished model supersedes it (dense/DRF
            # final=True contract); resume state rides the artifact so
            # continue-training stays bit-identical. The state is
            # attached to a COPY (the dense commit_ckpt contract): the
            # RETURNED model must not pin a dataset-sized margin in the
            # DKV or serialize it into every later save_model
            try:
                import copy as _copy

                from h2o3_tpu.models.model_base import \
                    persist_in_training_ckpt
                mfinal = _copy.copy(model)   # shares the tree arrays
                attach_resume_state(mfinal)
                persist_in_training_ckpt(mfinal, self.algo, ckpt_dir,
                                         final=True)
            except Exception as ce:  # noqa: BLE001 — advisory only
                from h2o3_tpu.log import warn as _warn
                _warn("%s: final streamed checkpoint failed: %s",
                      self.algo, ce)
        model.output["training_loop_seconds"] = t_loop
        model.output["streamed"] = True
        model.output["packed_codes"] = packed_codes_record(
            packed, dtype=x_stream.dtype, W=W,
            bytes_per_value=x_itemsize, n_bins=cfg.n_bins)
        # multi-level fusion record (ISSUE 17): the resolved
        # H2O3_LEVELS_PER_PASS window, and how many levels each device
        # dispatch actually covered — fused only on the packed
        # single-chunk path (a multi-chunk window still batches its
        # host syncs but keeps per-level dispatches for the cross-chunk
        # histogram reduction)
        lpp = (levels_per_pass(cfg.max_depth, cfg.n_features, W)
               if packed else 1)
        model.output["levels_per_dispatch"] = int(
            lpp if (packed and chunks.C == 1) else 1)
        if perf_acc is not None:
            perf_acc.add_device_seconds(t_loop)
            rp = perf_acc.finish()
            if rp is not None:
                model.output["perf"] = {"train": rp,
                                        "phases": {"levels": rp}}
        # transfer accounting for the bench guard: h2d bytes per tree vs
        # the dataset's device footprint (once-per-tree contract). The
        # count is the pipeline's OWN tally (chunks.h2d_bytes), not a
        # process-global counter delta — concurrent serve/parse traffic
        # must not be attributed to this train
        sp = chunks.profile()
        sp["trees"] = T
        sp["levels_per_pass"] = int(lpp)
        # steady-state per-tree traffic: the once-per-train resident
        # window upload is reported separately, not amortized — at
        # ntrees=1 amortization would read ~1.6x footprint and false-
        # fail the once-per-tree guard even though each chunk crossed
        # the bus exactly once
        sp["h2d_bytes_per_tree"] = (
            (sp["h2d_bytes"] - sp["h2d_resident_bytes"]) / T) if T else 0
        model.output["stream_profile"] = sp
        mpad = np.full(padded, f0, np.float32)
        mpad[:rows] = margin_host       # pad rows carry w=0 in metrics
        model.training_metrics = self._metrics_from_margin(
            jnp.asarray(mpad), spec, dist_name, K, dist=dist)
        return model

    def _dist(self, dist_name: str, huber_delta: float = 1.0):
        if str(dist_name).lower().startswith("custom"):
            # UDF family (water/udf CDistributionFunc): an instance on
            # custom_distribution_func wins over the registry lookup
            cdf = self.params.get("custom_distribution_func")
            if cdf is not None and not isinstance(cdf, str):
                return get_distribution(cdf)
        return get_distribution(dist_name,
                                float(self.params.get("tweedie_power", 1.5)),
                                float(self.params.get("quantile_alpha", 0.5)),
                                huber_delta)

    def _resolve_checkpoint(self, dist_name: str, spec: TrainingSpec):
        """Continue-training support (hex/Model.java:487 _checkpoint): the
        checkpoint model's trees seed the margin; ntrees is the TOTAL tree
        count, so training builds ntrees - prior.ntrees_built new trees."""
        ckpt = self.params.get("checkpoint")
        if not ckpt:
            return None
        prior = _resolve_checkpoint_source(ckpt, GBMModel, "GBM")
        if prior.dist_name != dist_name:
            raise ValueError(
                f"checkpoint distribution '{prior.dist_name}' != "
                f"'{dist_name}' (checkpoint params must match — "
                f"hex/ModelBuilder checkpoint contract)")
        if prior.max_depth != int(self.params["max_depth"]):
            raise ValueError("checkpoint max_depth differs")
        if int(self.params["ntrees"]) <= prior.ntrees_built:
            raise ValueError(
                f"ntrees ({self.params['ntrees']}) must exceed the "
                f"checkpoint's ntrees_built ({prior.ntrees_built})")
        if list(prior.feature_names) != list(spec.names):
            raise ValueError(
                f"checkpoint feature set {prior.feature_names} differs from "
                f"the training spec's {spec.names} — the prior trees' feature "
                f"indices would address the wrong columns")
        # response/domain compatibility (SharedTree/ModelBuilder checkpoint
        # contract): a different class count would silently corrupt the
        # margin columns under jit's clamped indexing; different categorical
        # domains would misroute the prior trees' enum-code thresholds
        if prior.nclasses != spec.nclasses:
            raise ValueError(
                f"checkpoint has {prior.nclasses} response classes but the "
                f"training frame has {spec.nclasses}")
        prd = tuple(prior.response_domain) if prior.response_domain else None
        srd = tuple(spec.response_domain) if spec.response_domain else None
        if prd != srd:
            raise ValueError(
                f"checkpoint response domain {prior.response_domain} differs "
                f"from the training frame's {spec.response_domain}")
        # normalize to tuples: domains loaded from disk round-trip as lists
        pcd = {k: tuple(v) for k, v in prior.cat_domains.items()}
        scd = {k: tuple(v) for k, v in spec.cat_domains.items()}
        if pcd != scd:
            raise ValueError(
                "checkpoint categorical domains differ from the training "
                "frame's — prior trees' enum-code splits would misroute")
        return prior

    def _prior_margin(self, prior, spec, padded, K):
        """Training margin to resume from. An in-training checkpoint
        carries the EXACT f32 margin at its committed tree count
        (``resume_margin``) — resuming from it reproduces the
        uninterrupted train bit-for-bit. A plain saved model recomputes
        the margin from its trees (correct to f32 summation order, not
        bit-guaranteed). Returns (margin, includes_offset)."""
        rm = getattr(prior, "_resume_margin", None)
        if rm is not None:
            rm = np.asarray(rm)
            want = (padded,) if K == 1 else (padded, K)
            sig = getattr(prior, "_resume_sig", None)
            sig_ok = (sig is None
                      or np.array_equal(np.asarray(sig),
                                        _spec_signature(spec)))
            if rm.shape == tuple(want) and sig_ok:
                # a checkpointed margin already includes any offset the
                # train carried — the caller must not add it again
                return jnp.asarray(rm, jnp.float32), True
            from h2o3_tpu.log import warn
            if not sig_ok:
                # continue-on-new-data: the saved margin belongs to a
                # DIFFERENT frame — applying it would silently train
                # against stale state; recompute from trees instead
                warn("checkpoint resume margin belongs to different "
                     "training data — recomputing from trees")
            else:
                warn("checkpoint resume margin shape %s != expected %s "
                     "— recomputing from trees", rm.shape, want)
        # recomputed from trees WITHOUT the offset — the caller must
        # still add spec.offset (f = f0 + offset + Σ lr·tree)
        return prior._margin_matrix(spec.X).astype(jnp.float32), False

    def _write_in_training_checkpoint(self, model, margin, ckpt_dir,
                                      spec=None):
        """Persist an in-training checkpoint: the partial model + its
        exact f32 training margin (the resume state that makes a
        resumed train bit-identical) + a cheap data fingerprint so the
        margin is never applied to a DIFFERENT training frame."""
        from h2o3_tpu.models.model_base import persist_in_training_ckpt
        model._resume_margin = np.asarray(
            telemetry.device_get(margin, pipeline="train"), np.float32)
        if spec is not None:
            model._resume_sig = _spec_signature(spec)
        return persist_in_training_ckpt(model, self.algo, ckpt_dir)

    def _score_entry_dev(self, margin, sc_spec, dist, K, built,
                         want_auc: bool = False):
        """Dispatch the interval-score reduction ON DEVICE and return a
        pending entry of device scalars — the driver fetches them with
        ``_score_entry_fetch`` only after the next chunk is in flight, so
        the metric transfer never stalls the boosting pipeline."""
        w = sc_spec.w
        y = sc_spec.y
        if K == 1:
            mu = dist.predict(margin)
            yf = y.astype(jnp.float32)
            vals = {"deviance": dist.deviance(w, yf, mu)}
            if dist.name == "bernoulli" and want_auc:
                from h2o3_tpu.models.metrics import auc_device
                vals["auc"] = auc_device(mu, yf, w)
            return ("k1", dist.name, built, vals)
        probs = jax.nn.softmax(margin, axis=1)
        eps = 1e-7  # f32-safe: 1-1e-15 rounds to 1.0f -> log1p(-1) = -inf
        py = jnp.clip(probs[jnp.arange(probs.shape[0]), y], eps, 1.0)
        return ("multi", None, built,
                {"logloss": -(w * jnp.log(py)).sum() / w.sum()})

    def _score_entry_fetch(self, pend) -> Dict:
        """Materialize a pending score entry: ONE device_get for all of
        the interval's scalars."""
        kind, dname, built, vals = pend
        h = telemetry.device_get(vals, pipeline="train")
        if kind != "k1":
            ll = float(h["logloss"])
            return {"ntrees": built, "logloss": ll, "deviance": ll}
        dev = float(h["deviance"])
        entry = {"ntrees": built, "deviance": dev}
        if dname == "gaussian":
            entry["mse"] = dev
            entry["rmse"] = float(np.sqrt(max(dev, 0)))
        if dname == "bernoulli":
            entry["logloss"] = dev / 2.0
            if "auc" in h:
                entry["auc"] = float(h["auc"])
        return entry

    def _finalize(self, spec, valid_spec, dist_name, f0, all_trees, bm, cfg,
                  K, built, margin, vmargin, keeper, tree_offset=0,
                  prior=None, dist=None, with_metrics=True) -> GBMModel:
        M = cfg.n_nodes
        # ONE pytree device_get for every chunk's trees, deferred to here
        # — nothing tree-shaped crosses to the host inside the boosting
        # loop (collect_chunk_trees slices off the padding-bucket tails)
        th = collect_chunk_trees(all_trees, M,
                                 bm.edges if bm is not None else [])
        feat = th["feat"]
        nal = th["na_left"]
        spl = th["is_split"]
        val = th["value"]
        gains = th["gain"]
        node_w = th["node_w"]
        thr = th["thr"]
        lr0 = float(self.params["learn_rate"])
        anneal = float(self.params["learn_rate_annealing"])
        lrs = lr0 * anneal ** np.repeat(
            np.arange(tree_offset, tree_offset + built), max(K, 1))
        val_scaled = val * lrs[:, None]
        trees_host = {"feat": feat, "thr": thr, "na_left": nal,
                      "is_split": spl, "value": val_scaled, "node_w": node_w}
        if prior is not None:
            # checkpoint continuation: prepend the prior model's trees
            # (already lr-scaled) in (tree, class) order
            trees_host = {
                "feat": np.concatenate([np.asarray(prior._feat), feat]),
                "thr": np.concatenate([np.asarray(prior._thr), thr]),
                "na_left": np.concatenate([np.asarray(prior._na_left), nal]),
                "is_split": np.concatenate([np.asarray(prior._is_split), spl]),
                "value": np.concatenate([np.asarray(prior._value), val_scaled]),
                "node_w": (np.concatenate([np.asarray(prior._node_w), node_w])
                           if getattr(prior, "_node_w", None) is not None
                           else None),
            }
        f0_host = np.asarray(telemetry.device_get(f0, pipeline="train"))
        model = GBMModel(self._model_key(), self.params,
                         spec, dist_name, f0_host, trees_host,
                         bm.edges if bm is not None else [],
                         bm.n_bins if bm is not None else cfg.n_bins,
                         cfg.max_depth, tree_offset + built,
                         spec.nclasses)
        # variable importances from split gains (merged with the prior's on
        # checkpoint continuation)
        vi = np.zeros(len(spec.names))
        live = feat >= 0
        np.add.at(vi, feat[live], gains[live])
        if prior is not None:
            pv = prior.output.get("variable_importances")
            if pv:
                lut = {n: i for i, n in enumerate(spec.names)}
                for n, g in zip(pv["variable"], pv["relative_importance"]):
                    if n in lut:
                        vi[lut[n]] += g
        order = np.argsort(-vi)
        rel = vi / vi.max() if vi.max() > 0 else vi
        model.output["variable_importances"] = {
            "variable": [spec.names[i] for i in order],
            "relative_importance": vi[order].tolist(),
            "scaled_importance": rel[order].tolist(),
            "percentage": (vi[order] / vi.sum() if vi.sum() > 0 else vi[order]).tolist(),
        }
        model.scoring_history = keeper.history
        if with_metrics:
            # final metrics from the training margin (exact, no
            # re-predict); in-training checkpoints skip this — they are
            # resume state, not reporting artifacts
            model.training_metrics = self._metrics_from_margin(
                margin, spec, dist_name, K, dist=dist)
            if vmargin is not None:
                model.validation_metrics = self._metrics_from_margin(
                    vmargin, valid_spec, dist_name, K, dist=dist)
        return model

    def _metrics_from_margin(self, margin, spec, dist_name, K, dist=None):
        if spec.nclasses == 2:
            p1 = 1.0 / (1.0 + jnp.exp(-margin))
            probs = jnp.stack([1.0 - p1, p1], axis=1)
            return compute_metrics(probs, spec.y, spec.w, 2, spec.response_domain)
        if K > 1:
            probs = jax.nn.softmax(margin, axis=1)
            return compute_metrics(probs, spec.y, spec.w, K, spec.response_domain)
        dist = dist if dist is not None else self._dist(dist_name)
        mu = dist.predict(margin)
        dev = float(telemetry.device_get(
            dist.deviance(spec.w, spec.y.astype(jnp.float32), mu),
            pipeline="train"))
        return compute_metrics(mu, spec.y, spec.w, 1, deviance=dev)


from h2o3_tpu.persist import register_model_class  # noqa: E402

register_model_class("gbm", GBMModel)
