"""Grid search — hyperparameter space walkers over any ModelBuilder.

Reference: hex/grid/GridSearch.java (job orchestration, parallelism),
hex/grid/HyperSpaceWalker.java:409 (CartesianWalker), :511
(RandomDiscreteValueWalker: seeded sampling, max_models /
max_runtime_secs budgets), hex/leaderboard/Leaderboard.java (metric
ranking).

TPU re-design: grid points build sequentially on the controller (each
model already saturates the chip — the reference's `parallelism` knob
multiplexes JVM threads over CPU cores, which has no analog when one
model owns the MXU); the walker/budget/leaderboard logic is pure
orchestration, kept shape-compatible with h2o-py's H2OGridSearch."""
from __future__ import annotations

import itertools
import json
import os
import random
import time
from typing import Any, Dict, List, Optional, Sequence

from h2o3_tpu import dkv

_LESS_IS_BETTER = {"logloss", "mse", "rmse", "mae", "rmsle",
                   "mean_residual_deviance", "deviance", "error",
                   "mean_per_class_error"}


def _metric_of(model, name: str):
    m = model.training_metrics
    if model.cross_validation_metrics is not None:
        m = model.cross_validation_metrics
    elif model.validation_metrics is not None:
        m = model.validation_metrics
    return getattr(m, name, None)


def sort_models(models, metric: str, decreasing: bool):
    """None-metric models LAST regardless of direction (a reversed sort
    would otherwise float them to the top for more-is-better metrics)."""
    def key(m):
        v = _metric_of(m, metric)
        if v is None:
            return (1, 0.0)
        return (0, -v if decreasing else v)
    models.sort(key=key)


def _default_metric(model) -> str:
    if model.nclasses == 2:
        return "auc"
    if model.nclasses > 2:
        return "logloss"
    return "mse"


class H2OGridSearch:
    """h2o-py H2OGridSearch shape: walk hyper_params over a builder."""

    def __init__(self, model, hyper_params: Dict[str, Sequence],
                 grid_id: Optional[str] = None,
                 search_criteria: Optional[Dict] = None,
                 recovery_dir: Optional[str] = None,
                 parallelism: int = 1):
        self.model_template = model
        self.hyper_params = {k: list(v) for k, v in hyper_params.items()}
        self.grid_id = grid_id or dkv.unique_key("grid")
        self.search_criteria = dict(search_criteria or {})
        self.recovery_dir = recovery_dir
        # hex/grid/GridSearch.java `parallelism`: >1 overlaps host
        # orchestration + XLA compile of point N+1 with device train of
        # point N (one model rarely saturates host+device together for
        # the small models grids sweep)
        par = parallelism if parallelism is not None else 1
        if int(par) == 1:  # explicit arg wins; else consult the criteria
            par = self.search_criteria.get("parallelism", 1)
        par = int(par if par is not None else 1)
        if par == 0:
            # reference semantics: 0 = adaptive parallelism
            par = max(2, min((os.cpu_count() or 4) // 2, 8))
        self.parallelism = max(par, 1)
        self.models: List = []
        self.failures: List[Dict] = []

    # -- walkers (HyperSpaceWalker.java:409 / :511) ---------------------

    def _combos(self):
        keys = list(self.hyper_params)
        spaces = [self.hyper_params[k] for k in keys]
        strategy = (self.search_criteria.get("strategy")
                    or "Cartesian").lower()
        all_pts = [dict(zip(keys, vals))
                   for vals in itertools.product(*spaces)]
        if strategy in ("cartesian",):
            return all_pts
        if strategy in ("randomdiscrete", "random_discrete"):
            seed = self.search_criteria.get("seed", -1)
            rng = random.Random(None if seed in (-1, None) else seed)
            rng.shuffle(all_pts)
            return all_pts
        raise ValueError(f"unknown search strategy '{strategy}'")

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **train_kw):
        max_models = int(self.search_criteria.get("max_models", 0) or 0)
        max_secs = float(self.search_criteria.get("max_runtime_secs", 0)
                         or 0)
        t0 = time.monotonic()   # duration budget anchor
        base_params = dict(self.model_template.params)
        cls = type(self.model_template)
        # auto-recovery (hex/faulttolerance/Recovery.java + the
        # -auto_recovery_dir flag): completed grid points persist as
        # artifacts + a manifest; a restarted grid resumes from it
        done: Dict[str, str] = {}
        # fingerprint of the non-hyper base config: a resume against a
        # CHANGED base estimator must retrain, not load stale artifacts
        base_fp = json.dumps(
            {k: v for k, v in sorted(base_params.items())
             if not callable(v)},
            sort_keys=True, default=str)
        if self.recovery_dir:
            os.makedirs(self.recovery_dir, exist_ok=True)
            manifest = os.path.join(self.recovery_dir,
                                    f"{self.grid_id}.json")
            if os.path.exists(manifest):
                try:
                    with open(manifest) as f:
                        m = json.load(f)
                    if m.get("base") == base_fp:
                        done = m.get("completed", {})
                except (json.JSONDecodeError, OSError):
                    done = {}  # crashed mid-write — retrain everything
        built_count = [0]

        def reload_done_point(ckey):
            """Reload a completed point's artifact from the recovery
            manifest; None = not recorded or stale (retrain). ONE
            implementation for the sequential/pool walkers and the
            scheduler branch — the reload contract must not drift."""
            if ckey not in done:
                return None
            from h2o3_tpu.persist import load_model
            try:
                return load_model(done[ckey])
            except Exception:   # noqa: BLE001
                return None     # stale artifact — retrain the point

        def one_point(i, combo):
            """Train (or reload) one grid point; returns (i, model|None,
            failure|None)."""
            ckey = json.dumps(combo, sort_keys=True, default=str)
            model = reload_done_point(ckey)
            if model is not None:
                return i, model, None, ckey, False
            params = dict(base_params)
            params.update(combo)
            est = cls(**params)
            try:
                est.train(x=x, y=y, training_frame=training_frame,
                          validation_frame=validation_frame, **train_kw)
                return i, est.model, None, ckey, True
            except Exception as e:  # noqa: BLE001 — grid keeps walking
                return i, None, {"params": combo, "error": str(e)}, ckey, \
                    False

        def record(i, combo, model, failure, ckey, fresh):
            if failure is not None:
                self.failures.append(failure)
                return
            model.key = f"{self.grid_id}_model_{i}"
            model.output["grid_hyper_params"] = combo
            dkv.put(model.key, "model", model)
            self.models.append(model)
            if self.recovery_dir and fresh:
                from h2o3_tpu.persist import save_model
                art = save_model(model, self.recovery_dir,
                                 force=True, filename=model.key)
                done[ckey] = art
                # atomic manifest write: a crash mid-dump must not
                # leave a truncated file that blocks the resume
                mpath = os.path.join(self.recovery_dir,
                                     f"{self.grid_id}.json")
                tmp = mpath + ".part"
                with open(tmp, "w") as f:
                    json.dump({"base": base_fp, "completed": done}, f)
                os.replace(tmp, mpath)

        combos = list(enumerate(self._combos()))
        from h2o3_tpu import sched
        from h2o3_tpu.models.model_base import build_parallelism
        par = build_parallelism(self.parallelism)
        use_sched = sched.enabled() and not sched.in_scheduled_run()
        if use_sched and par > 1:
            # children route through the training scheduler (ISSUE 15):
            # `parallelism` is a CAP on the in-flight submission wave;
            # device-memory ADMISSION decides how many actually run, and
            # the grid id is the fair-share group so one grid cannot
            # starve another tenant's children in the bulk class
            from h2o3_tpu import jobs as jobs_mod
            pending = {}        # sched Entry -> (i, combo, est, ckey)
            ci = 0
            with sched.submit_context(priority="bulk",
                                      share=self.grid_id):
                while ci < len(combos) or pending:
                    while ci < len(combos) and len(pending) < par:
                        if ((max_models and built_count[0]
                             + len(pending) >= max_models)
                                or (max_secs
                                    and time.monotonic() - t0
                                    > max_secs)):
                            ci = len(combos)
                            break
                        i, combo = combos[ci]
                        ci += 1
                        ckey = json.dumps(combo, sort_keys=True,
                                          default=str)
                        reloaded = reload_done_point(ckey)
                        if reloaded is not None:
                            record(i, combo, reloaded, None, ckey,
                                   False)
                            built_count[0] += 1
                            continue
                        params = dict(base_params)
                        params.update(combo)
                        est = cls(**params)
                        try:
                            est.train(x=x, y=y,
                                      training_frame=training_frame,
                                      validation_frame=validation_frame,
                                      background=True, **train_kw)
                        except Exception as e:  # noqa: BLE001
                            record(i, combo, None,
                                   {"params": combo, "error": str(e)},
                                   ckey, False)
                            continue
                        entry = est.__dict__.get("_sched_entry")
                        if entry is None:
                            # wrapper builders (CoxPH, ANOVA-GLM,
                            # Word2Vec…) override train() and swallow
                            # background= in **kw — they completed
                            # SYNCHRONOUSLY above
                            record(i, combo, est.model, None, ckey,
                                   True)
                            built_count[0] += 1
                            continue
                        pending[entry] = (i, combo, est, ckey)
                    if not pending:
                        break
                    if max_secs and time.monotonic() - t0 > max_secs:
                        # wall budget expired: children already RUNNING
                        # finish (the reference's in-flight slack), but
                        # still-QUEUED ones must not start minutes past
                        # the deadline once the queue drains — cancel
                        # them (the scheduler finalizes cancelled queued
                        # entries within one dispatch tick)
                        for _, (_, _, qest, _) in pending.items():
                            if qest.job.status == jobs_mod.QUEUED:
                                qest.job.cancel(
                                    "grid max_runtime_secs exceeded "
                                    "while queued")
                    # drain any finished child; the timeout re-checks
                    # the wall budget while everything queues
                    sched.scheduler().wait_any(list(pending),
                                               timeout=1.0)
                    for entry in [e for e in pending
                                  if e.done.is_set()]:
                        i, combo, est, ckey = pending.pop(entry)
                        job = est.job
                        if job.status == jobs_mod.DONE \
                                and job.result is not None:
                            record(i, combo, job.result, None, ckey,
                                   True)
                            built_count[0] += 1
                        elif (job.status == jobs_mod.CANCELLED
                              and (job.cancel_reason or "").startswith(
                                  "grid max_runtime_secs")):
                            # budget-cancelled while QUEUED: the point
                            # never trained — same outcome as never
                            # having been submitted, not a failure
                            pass
                        else:
                            record(i, combo, None,
                                   {"params": combo,
                                    "error": job.exception_msg
                                    or job.cancel_reason
                                    or f"job ended {job.status}"},
                                   ckey, False)
            self.models.sort(
                key=lambda m: int(m.key.rsplit("_", 1)[1]))
        elif par > 1:
            # hex/grid/GridSearch parallelism: a worker pool walks the
            # space; budgets are enforced at SUBMIT time per wave so
            # max_models overshoots by at most parallelism-1 in-flight
            # points (the reference has the same in-flight slack).
            # This branch only runs NESTED (inside an admitted build)
            # or with the scheduler disabled — the pool threads must
            # re-enter the inline flag (it is thread-local) so children
            # ride the parent's admission instead of enqueueing while
            # the parent blocks on them
            import concurrent.futures as cf

            def one_point_inline(i, combo):
                with sched.inline_run():
                    return one_point(i, combo)

            with cf.ThreadPoolExecutor(max_workers=par) as ex:
                pending = {}
                ci = 0
                while ci < len(combos) or pending:
                    while (ci < len(combos)
                           and len(pending) < par):
                        if ((max_models and built_count[0]
                             + len(pending) >= max_models)
                                or (max_secs
                                    and time.monotonic() - t0 > max_secs)):
                            ci = len(combos)
                            break
                        i, combo = combos[ci]
                        pending[ex.submit(one_point_inline, i,
                                          combo)] = combo
                        ci += 1
                    if not pending:
                        break
                    done_futs, _ = cf.wait(
                        list(pending), return_when=cf.FIRST_COMPLETED)
                    for fu in done_futs:
                        combo = pending.pop(fu)
                        i, model, failure, ckey, fresh = fu.result()
                        record(i, combo, model, failure, ckey, fresh)
                        if model is not None:
                            built_count[0] += 1
            self.models.sort(
                key=lambda m: int(m.key.rsplit("_", 1)[1]))
        else:
            # sequential walk: children still submit one at a time under
            # the bulk class + this grid's fair-share group, so a serial
            # grid queues behind interactive trains exactly like a
            # parallel one
            with sched.submit_context(priority="bulk",
                                      share=self.grid_id):
                for i, combo in combos:
                    if max_models and len(self.models) >= max_models:
                        break
                    if max_secs and time.monotonic() - t0 > max_secs:
                        break
                    i2, model, failure, ckey, fresh = one_point(i, combo)
                    record(i, combo, model, failure, ckey, fresh)
        dkv.put(self.grid_id, "grid", self)
        return self

    # -- leaderboard (hex/leaderboard/Leaderboard.java) ------------------

    def get_grid(self, sort_by: Optional[str] = None,
                 decreasing: Optional[bool] = None) -> "H2OGridSearch":
        if not self.models:
            return self
        metric = sort_by or _default_metric(self.models[0])
        if decreasing is None:
            decreasing = metric not in _LESS_IS_BETTER
        sort_models(self.models, metric, decreasing)
        return self

    @property
    def model_ids(self) -> List[str]:
        return [m.key for m in self.models]

    def leaderboard(self, sort_by: Optional[str] = None) -> List[Dict]:
        self.get_grid(sort_by)
        metric = sort_by or _default_metric(self.models[0])
        return [{"model_id": m.key, metric: _metric_of(m, metric),
                 **m.output.get("grid_hyper_params", {})}
                for m in self.models]

    def __getitem__(self, i):
        return self.models[i]

    def __len__(self):
        return len(self.models)


def save_grid_artifact(grid: "H2OGridSearch", gid: str, directory: str) -> str:
    """h2o.save_grid analog (water/api/GridImportExportHandler +
    Grid.exportBinary): persist the grid manifest + every model artifact
    into ``directory``; reloadable by ``load_grid_artifact``."""
    from h2o3_tpu.persist import save_model
    os.makedirs(directory, exist_ok=True)
    model_files = []
    for m in grid.models:
        p = save_model(m, directory, force=True, filename=f"{m.key}.zip")
        model_files.append(os.path.basename(p))
    est = grid.model_template
    manifest = {
        "grid_id": gid,
        "algo": getattr(est, "algo", type(est).__name__),
        "estimator_params": {k: (list(v) if isinstance(v, tuple) else v)
                             for k, v in est.params.items()
                             if not callable(v)
                             and isinstance(v, (int, float, str, bool,
                                                list, tuple, dict,
                                                type(None)))},
        "hyper_params": grid.hyper_params,
        "search_criteria": grid.search_criteria,
        "models": model_files,
    }
    path = os.path.join(directory, f"{gid}.grid.json")
    with open(path, "w") as f:
        json.dump(manifest, f, default=str)
    return path


def load_grid_artifact(path: str):
    """Load a grid saved by ``save_grid_artifact``. ``path`` is either
    the ``<gid>.grid.json`` manifest or ``<dir>/<gid>`` (h2o.load_grid
    passes dir + grid id joined). Returns (gid, grid, models)."""
    from h2o3_tpu.persist import load_model
    if os.path.isdir(path):
        cands = [f for f in os.listdir(path) if f.endswith(".grid.json")]
        if len(cands) != 1:
            raise ValueError(f"expected one .grid.json in {path}")
        path = os.path.join(path, cands[0])
    elif not path.endswith(".grid.json"):
        d, gid = os.path.dirname(path), os.path.basename(path)
        path = os.path.join(d, f"{gid}.grid.json")
    with open(path) as f:
        man = json.load(f)
    directory = os.path.dirname(path)
    models = [load_model(os.path.join(directory, mf))
              for mf in man["models"]]
    try:
        from h2o3_tpu.api.server import _builders
        est = _builders()[man["algo"]](**man["estimator_params"])
    except Exception as e:
        # keep the grid loadable for inspection, but surface why the
        # template is unusable instead of a far-away NoneType crash
        from h2o3_tpu.log import warn
        warn(f"load_grid_artifact: could not rebuild the template "
             f"estimator for algo '{man.get('algo')}': {e!r}; the grid "
             f"can be inspected but not extended via train()")
        est = None
    grid = H2OGridSearch(est, man["hyper_params"],
                         grid_id=man["grid_id"],
                         search_criteria=man["search_criteria"])
    grid.models = models
    return man["grid_id"], grid, models
