"""Grep + Generic — the two utility model builders.

Reference: hex/grep/Grep.java:19 (regex scan over a ByteVec — the
reference's demo of a raw-bytes MRTask) and hex/generic/Generic.java
(import a saved model artifact as a first-class Model)."""
from __future__ import annotations

import re
from typing import Dict, List

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder
from h2o3_tpu.persist import load_model, register_model_class

GREP_DEFAULTS: Dict = dict(regex=None)


class GrepModel(Model):
    algo = "grep"
    supervised = False

    def __init__(self, key, params, spec, matches):
        super().__init__(key, params, spec)
        self.matches = matches        # list of (row, offset, text)

    def _predict_matrix(self, X, offset=None):
        raise NotImplementedError("Grep reports matches at train time")

    def matches_frame(self) -> Frame:
        rows = np.asarray([m[0] for m in self.matches], np.float64)
        offs = np.asarray([m[1] for m in self.matches], np.float64)
        txt = np.asarray([m[2] for m in self.matches], dtype=object)
        return Frame(["row", "offset", "match"],
                     [Vec.from_numpy(rows), Vec.from_numpy(offs),
                      Vec.from_numpy(txt)])

    def _save_extra_meta(self):
        return {"matches": [[int(r), int(o), t]
                            for r, o, t in self.matches]}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        m.matches = [tuple(x) for x in meta["extra"]["matches"]]
        return m


class H2OGrepEstimator(ModelBuilder):
    algo = "grep"
    supervised = False

    def __init__(self, **params):
        merged = dict(GREP_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        rx = self.params.get("regex")
        if not rx:
            raise ValueError("Grep needs a regex")
        if training_frame is None:
            raise ValueError("Grep needs a training_frame")
        pat = re.compile(rx)
        job = Job("grep", work=float(training_frame.ncol))

        def body(job):
            matches: List = []
            for v in training_frame.vecs:
                if v.type not in ("string", "enum"):
                    job.update(1.0)
                    continue
                for i, s in enumerate(v.to_strings()):
                    if not s:
                        continue
                    for mt in pat.finditer(s):
                        matches.append((i, mt.start(), mt.group()))
                job.update(1.0)
            model = GrepModel(f"grep_{id(self) & 0xffffff:x}", self.params,
                              _GrepSpec(), matches)
            model.output["matches"] = [m[2] for m in matches]
            model.output["n_matches"] = len(matches)
            return model

        job.run(body)
        self.model = job.join()
        self.job = job
        return self

    def _train_impl(self, spec, valid_spec, job: Job):
        raise RuntimeError("Grep overrides train() directly")


class _GrepSpec:
    names: List[str] = []
    is_cat: List[bool] = []
    cat_domains: Dict[str, tuple] = {}
    response = None
    response_domain = None
    nclasses = 1


class H2OGenericEstimator(ModelBuilder):
    """Import a saved artifact as a first-class model
    (hex/generic/Generic.java — MOJO import; here: our zip artifact)."""
    algo = "generic"
    supervised = False

    def __init__(self, **params):
        super().__init__(**params)

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        path = self.params.get("path") or self.params.get("model_key")
        if not path:
            raise ValueError("Generic needs path= to a saved model "
                             "artifact")
        job = Job("generic import", work=1.0)

        def body(job):
            import zipfile
            with zipfile.ZipFile(path) as zf:
                is_mojo = "model.ini" in zf.namelist()
            if is_mojo:
                from h2o3_tpu.mojo import import_mojo
                model = import_mojo(path)
            else:
                model = load_model(path)
                model.output["generic_source"] = path
            return model

        job.run(body)
        self.model = job.join()
        self.job = job
        from h2o3_tpu import dkv
        dkv.put(self.model.key, "model", self.model)
        return self

    def _train_impl(self, spec, valid_spec, job: Job):
        raise RuntimeError("Generic overrides train() directly")


register_model_class("grep", GrepModel)
