"""KMeans — Lloyd iterations as distance matmuls on the MXU.

Reference: hex/kmeans/KMeans.java:26 — k-means|| initialization
(Sampler), Lloyd iterations as one MRTask pass per iteration
(LloydsIterationTask :731, one pass per iteration :343), standardization,
categorical one-hot expansion.

TPU re-design: the per-row nearest-center search is a single
[rows, F] x [F, K] matmul per iteration (||x-c||² = ||x||² - 2x·c +
||c||²) + argmin; per-cluster sums are a one-hot matmul (segment-sum on
the MXU). Under a mesh rows shard over 'data' and the cluster sums psum
— the MRTask reduce analog. k-means|| init is replaced by k-means++ on a
device-sampled subset (same spirit: spread the seeds, O(K) passes)."""
from __future__ import annotations

import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu import telemetry
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import expand_design, expand_scoring_matrix
from h2o3_tpu.models.model_base import (Model, ModelBuilder, TrainingSpec,
                                        pack_impute_means,
                                        unpack_impute_means)
from h2o3_tpu.persist import register_model_class

KMEANS_DEFAULTS: Dict = dict(
    k=3, max_iterations=10, standardize=True, init="plus_plus", seed=-1,
)


def _dists2(X, C):
    """Squared distances [rows, K] via the MXU (no [rows, K, F] blowup)."""
    xn = (X * X).sum(1, keepdims=True)
    cn = (C * C).sum(1)[None, :]
    return jnp.maximum(xn - 2.0 * (X @ C.T) + cn, 0.0)


@jax.jit
def _lloyd_step(X, w, C):
    d2 = _dists2(X, C)
    assign = jnp.argmin(d2, axis=1)
    K = C.shape[0]
    oh = (assign[:, None] == jnp.arange(K)[None, :]).astype(jnp.float32)
    oh = oh * w[:, None]
    sums = jax.lax.dot_general(oh, X, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)  # [K, F]
    cnt = oh.sum(0)
    newC = jnp.where(cnt[:, None] > 0, sums / jnp.maximum(cnt[:, None], 1e-12),
                     C)
    wcss = (w * jnp.take_along_axis(d2, assign[:, None], axis=1)[:, 0]).sum()
    return newC, assign, cnt, wcss


def _kmeans_pp_init(X, w, k, key, sample=8192):
    """k-means++ on a device sample (replaces k-means|| — same goal of
    spread seeds without K full passes over all rows)."""
    rows = X.shape[0]
    key, ks = jax.random.split(key)
    probs = w / jnp.maximum(w.sum(), 1e-12)
    idx = jax.random.choice(ks, rows, (min(sample, rows),), p=probs)
    S = X[idx]
    key, k0 = jax.random.split(key)
    first = jax.random.randint(k0, (), 0, S.shape[0])
    C = jnp.zeros((k, X.shape[1]), jnp.float32).at[0].set(S[first])

    def add_center(i, state):
        C, key = state
        d2 = _dists2(S, C)
        # distance to the nearest chosen center (unchosen rows are zeros
        # at C[0]... mask by taking min over the first i centers)
        mask = jnp.arange(C.shape[0])[None, :] < i
        d2m = jnp.where(mask, d2, jnp.inf).min(axis=1)
        key, kc = jax.random.split(key)
        p = d2m / jnp.maximum(d2m.sum(), 1e-12)
        nxt = jax.random.choice(kc, S.shape[0], (), p=p)
        return C.at[i].set(S[nxt]), key

    C, _ = jax.lax.fori_loop(1, k, add_center, (C, key))
    return C


class KMeansModel(Model):
    algo = "kmeans"
    supervised = False

    def __init__(self, key, params, spec, centers_std, centers_raw, xm, xs,
                 exp_names, impute_means, wcss, sizes, iters):
        super().__init__(key, params, spec)
        self.centers_std = np.asarray(centers_std)
        self.centers_raw = np.asarray(centers_raw)
        self.xm = np.asarray(xm)
        self.xs = np.asarray(xs)
        self.exp_names = list(exp_names)
        self.impute_means = {k: float(v) for k, v in impute_means.items()}
        self.tot_withinss = wcss
        self.cluster_sizes = list(sizes)
        self.iterations = iters

    def centers(self):
        """Raw-space cluster centers (h2o .centers())."""
        return self.centers_raw

    def _predict_matrix(self, X, offset=None):
        Xe = expand_scoring_matrix(self, X)
        Xs = (Xe - jnp.asarray(self.xm)[None, :]) / jnp.asarray(self.xs)[None, :]
        d2 = _dists2(Xs, jnp.asarray(self.centers_std))
        return jnp.argmin(d2, axis=1).astype(jnp.float32)

    def predict(self, frame):
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.models.model_base import adapt_test_matrix
        X = adapt_test_matrix(self, frame)
        out = np.asarray(telemetry.device_get(self._predict_matrix(X)))[: frame.nrow]
        return Frame(["predict"], [Vec.from_numpy(out.astype(np.int32))])

    # -- persistence ----------------------------------------------------

    def _save_arrays(self):
        return {"centers_std": self.centers_std,
                "centers_raw": self.centers_raw, "xm": self.xm,
                "xs": self.xs,
                **pack_impute_means(self.impute_means),
                "sizes": np.asarray(self.cluster_sizes)}

    def _save_extra_meta(self):
        return {"exp_names": self.exp_names,
                "tot_withinss": self.tot_withinss,
                "iterations": self.iterations}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        m.exp_names = list(ex["exp_names"])
        m.tot_withinss = ex["tot_withinss"]
        m.iterations = ex["iterations"]
        m.centers_std = arrays["centers_std"]
        m.centers_raw = arrays["centers_raw"]
        m.xm = arrays["xm"]
        m.xs = arrays["xs"]
        m.cluster_sizes = list(arrays["sizes"])
        m.impute_means = unpack_impute_means(arrays)
        return m


class H2OKMeansEstimator(ModelBuilder):
    algo = "kmeans"
    supervised = False

    def __init__(self, **params):
        merged = dict(KMEANS_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        p = self.params
        if p.get("estimate_k"):
            raise NotImplementedError(
                "estimate_k is not implemented (hex/kmeans estimate_k)")
        k = int(p.get("k", 3))
        Xe, exp_names, means = expand_design(spec)
        w = spec.w
        if bool(p.get("standardize", True)):
            wsum = w.sum()
            xm = (Xe * w[:, None]).sum(0) / wsum
            xv = (w[:, None] * (Xe - xm[None, :]) ** 2).sum(0) / wsum
            xs = jnp.sqrt(jnp.maximum(xv, 1e-12))
        else:
            xm = jnp.zeros(Xe.shape[1], jnp.float32)
            xs = jnp.ones(Xe.shape[1], jnp.float32)
        Xs = ((Xe - xm[None, :]) / xs[None, :]) * (w > 0)[:, None]
        seed = int(p.get("seed", -1) or -1)
        key = jax.random.PRNGKey(seed if seed != -1
                                 else int(time.time() * 1e3) % (2 ** 31))
        if p.get("init", "plus_plus") in ("random",):
            idx = jax.random.choice(key, Xs.shape[0], (k,), replace=False,
                                    p=w / jnp.maximum(w.sum(), 1e-12))
            C = Xs[idx]
        else:
            C = _kmeans_pp_init(Xs, w, k, key)
        max_iter = max(int(p.get("max_iterations", 10)), 1)
        wcss = np.inf
        it = 0
        for it in range(max_iter):
            if it and job.cancel_requested:
                # poll BEFORE dispatching the next Lloyd step (watchdog
                # max_runtime_secs cancels land here without paying one
                # extra full iteration); the current centers are the
                # partial model
                break
            C, assign, cnt, new_wcss = _lloyd_step(Xs, w, C)
            new_wcss = float(telemetry.device_get(new_wcss))
            job.set_progress((it + 1) / max_iter)
            if abs(wcss - new_wcss) < 1e-6 * max(abs(wcss), 1.0):
                wcss = new_wcss
                break
            wcss = new_wcss
        cnt_h = np.asarray(telemetry.device_get(cnt))
        C_h = np.asarray(telemetry.device_get(C))
        C_raw = C_h * np.asarray(telemetry.device_get(xs))[None, :] \
            + np.asarray(telemetry.device_get(xm))[None, :]
        model = KMeansModel(f"kmeans_{id(self) & 0xffffff:x}", self.params,
                            spec, C_h, C_raw, telemetry.device_get(xm),
                            telemetry.device_get(xs), exp_names,
                            {k_: float(telemetry.device_get(v))
                             for k_, v in means.items()},
                            wcss, cnt_h.tolist(), it + 1)
        model.output["tot_withinss"] = wcss
        model.output["cluster_sizes"] = cnt_h.tolist()
        return model


register_model_class("kmeans", KMeansModel)
