"""Distribution families — gradient/hessian/link providers for boosting
and deep learning.

Reference: hex/Distribution.java + hex/DistributionFactory.java (gaussian,
bernoulli, multinomial, poisson, gamma, tweedie, laplace, quantile, huber,
custom) with per-family link/deviance/gradient. Here each family exposes
the Newton quantities the tree builder needs (g = dL/df, h = d²L/df²) plus
init margin and inverse link — all jnp, usable inside jit.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class Distribution:
    name = "base"
    def init_f0(self, y, w):
        raise NotImplementedError
    def grad_hess(self, f, y):
        """g, h with respect to margin f."""
        raise NotImplementedError
    def predict(self, f):
        """inverse link"""
        raise NotImplementedError
    def deviance(self, w, y, mu):
        raise NotImplementedError


class Gaussian(Distribution):
    name = "gaussian"
    def init_f0(self, y, w):
        return (w * y).sum() / w.sum()
    def grad_hess(self, f, y):
        return f - y, jnp.ones_like(f)
    def predict(self, f):
        return f
    def deviance(self, w, y, mu):
        return (w * (y - mu) ** 2).sum() / w.sum()


class Bernoulli(Distribution):
    name = "bernoulli"
    def init_f0(self, y, w):
        p = jnp.clip((w * y).sum() / w.sum(), 1e-9, 1 - 1e-9)
        return jnp.log(p / (1 - p))
    def grad_hess(self, f, y):
        p = jax_sigmoid(f)
        return p - y, jnp.maximum(p * (1 - p), 1e-9)
    def predict(self, f):
        return jax_sigmoid(f)
    def deviance(self, w, y, mu):
        eps = 1e-7  # f32-safe: 1-1e-15 rounds to 1.0f -> log1p(-1) = -inf
        mu = jnp.clip(mu, eps, 1 - eps)
        return -2.0 * (w * (y * jnp.log(mu) + (1 - y) * jnp.log1p(-mu))).sum() / w.sum()


class Poisson(Distribution):
    name = "poisson"
    def init_f0(self, y, w):
        return jnp.log(jnp.maximum((w * y).sum() / w.sum(), 1e-9))
    def grad_hess(self, f, y):
        mu = jnp.exp(f)
        return mu - y, jnp.maximum(mu, 1e-9)
    def predict(self, f):
        return jnp.exp(f)
    def deviance(self, w, y, mu):
        yl = jnp.where(y > 0, y * jnp.log(y / jnp.maximum(mu, 1e-30)), 0.0)
        return 2.0 * (w * (yl - (y - mu))).sum() / w.sum()


class Gamma(Distribution):
    name = "gamma"
    def init_f0(self, y, w):
        return jnp.log(jnp.maximum((w * y).sum() / w.sum(), 1e-9))
    def grad_hess(self, f, y):
        mu = jnp.exp(f)
        # -L = y/mu + log(mu); d/df with mu=e^f: 1 - y*e^-f ; h = y*e^-f
        return 1.0 - y * jnp.exp(-f), jnp.maximum(y * jnp.exp(-f), 1e-9)
    def predict(self, f):
        return jnp.exp(f)
    def deviance(self, w, y, mu):
        r = y / jnp.maximum(mu, 1e-30)
        return 2.0 * (w * (-jnp.log(jnp.maximum(r, 1e-30)) + r - 1.0)).sum() / w.sum()


class Tweedie(Distribution):
    name = "tweedie"
    def __init__(self, power=1.5):
        self.p = power
    def init_f0(self, y, w):
        return jnp.log(jnp.maximum((w * y).sum() / w.sum(), 1e-9))
    def grad_hess(self, f, y):
        p = self.p
        g = jnp.exp(f * (2 - p)) - y * jnp.exp(f * (1 - p))
        h = (2 - p) * jnp.exp(f * (2 - p)) - (1 - p) * y * jnp.exp(f * (1 - p))
        return g, jnp.maximum(h, 1e-9)
    def predict(self, f):
        return jnp.exp(f)
    def deviance(self, w, y, mu):
        p = self.p
        mu = jnp.maximum(mu, 1e-30)
        a = jnp.where(y > 0, y ** (2 - p) / ((1 - p) * (2 - p)), 0.0)
        b = y * mu ** (1 - p) / (1 - p)
        c = mu ** (2 - p) / (2 - p)
        return 2.0 * (w * (a - b + c)).sum() / w.sum()


def weighted_quantile(y, w, q):
    """Smallest y with cumulative weight >= q × total weight. Zero-weight
    rows (padding, NA responses) never influence the result."""
    order = jnp.argsort(y)
    ys = y[order]
    cw = jnp.cumsum(w[order])
    idx = jnp.searchsorted(cw, q * cw[-1])
    return ys[jnp.minimum(idx, ys.shape[0] - 1)]


def weighted_median(y, w):
    """Weighted median (matching the reference's weighted-median leaf
    updates for Laplace, hex/Distribution.java laplace family)."""
    return weighted_quantile(y, w, 0.5)


class Laplace(Distribution):
    name = "laplace"
    def init_f0(self, y, w):
        return weighted_median(y, w)
    def grad_hess(self, f, y):
        return jnp.sign(f - y), jnp.ones_like(f)
    def predict(self, f):
        return f
    def deviance(self, w, y, mu):
        return (w * jnp.abs(y - mu)).sum() / w.sum()


class Quantile(Distribution):
    """Pinball / quantile loss (hex/Distribution.java quantile family,
    GBM quantile_alpha parameter)."""
    name = "quantile"
    def __init__(self, alpha=0.5):
        self.alpha = alpha
    def init_f0(self, y, w):
        return weighted_quantile(y, w, self.alpha)
    def grad_hess(self, f, y):
        # dL/df of alpha*(y-f)+ + (1-alpha)*(f-y)+
        g = jnp.where(y > f, -self.alpha, 1.0 - self.alpha)
        return g, jnp.ones_like(f)
    def predict(self, f):
        return f
    def deviance(self, w, y, mu):
        r = y - mu
        loss = jnp.where(r > 0, self.alpha * r, (self.alpha - 1.0) * r)
        return (w * loss).sum() / w.sum()


class Huber(Distribution):
    """Huber loss with a fixed transition point ``delta`` (the reference
    re-estimates delta each scoring iteration as the huber_alpha quantile
    of absolute residuals, hex/Distribution.java huber; here the GBM
    driver computes delta once from the initial residuals — a documented
    static-shape simplification)."""
    name = "huber"
    def __init__(self, delta=1.0):
        self.delta = delta
    def init_f0(self, y, w):
        return weighted_median(y, w)
    def grad_hess(self, f, y):
        r = f - y
        return jnp.clip(r, -self.delta, self.delta), jnp.ones_like(f)
    def predict(self, f):
        return f
    def deviance(self, w, y, mu):
        r = jnp.abs(y - mu)
        d = self.delta
        loss = jnp.where(r <= d, 0.5 * r ** 2, d * (r - 0.5 * d))
        return (w * loss).sum() / w.sum()


def jax_sigmoid(x):
    return 1.0 / (1.0 + jnp.exp(-x))


_FAMILIES = {
    "gaussian": Gaussian,
    "bernoulli": Bernoulli,
    "binomial": Bernoulli,
    "poisson": Poisson,
    "gamma": Gamma,
    "laplace": Laplace,
}


# user-defined families (water/udf CDistributionFunc analog): register a
# Distribution subclass/instance under a name, then train with
# distribution="custom:<name>" (or pass the instance via the builder's
# custom_distribution_func param)
_CUSTOM: dict = {}


def register_custom_distribution(name: str, dist) -> None:
    """Register a UDF distribution. `dist` implements the Distribution
    contract (init_f0/grad_hess/predict/deviance) with jnp math — it is
    traced into the jitted training step like the built-ins."""
    _CUSTOM[name.lower()] = dist


def get_distribution(name: str, tweedie_power: float = 1.5,
                     quantile_alpha: float = 0.5,
                     huber_delta: float = 1.0) -> Distribution:
    if isinstance(name, Distribution):
        return name
    if isinstance(name, type) and issubclass(name, Distribution):
        return name()
    name = (name or "gaussian").lower()
    if name.startswith("custom"):
        key = name.split(":", 1)[1] if ":" in name else name
        if key in _CUSTOM:
            d = _CUSTOM[key]
            return d() if isinstance(d, type) else d
        raise ValueError(
            f"custom distribution '{key}' is not registered "
            f"(register_custom_distribution); have {sorted(_CUSTOM)}")
    if name == "tweedie":
        return Tweedie(tweedie_power)
    if name == "quantile":
        return Quantile(quantile_alpha)
    if name == "huber":
        return Huber(huber_delta)
    if name in _FAMILIES:
        return _FAMILIES[name]()
    raise ValueError(
        f"unknown distribution '{name}'; have "
        f"{sorted(_FAMILIES) + ['tweedie', 'quantile', 'huber', 'multinomial']}")


# identity-link families where the offset-adjusted init is exactly the
# family init of (y - offset); Newton on these is bounded by max|g| per
# step (unit hessian) and cannot converge for large shifts
SHIFT_INIT = {"gaussian", "laplace", "quantile", "huber"}


def offset_adjusted_f0(dist: Distribution, y, w, offset, n_iter: int = 8):
    """Initial margin on the offset-adjusted scale (the reference GBM
    computes the initial value against the offset, hex/tree/gbm/GBM.java
    init). Identity-link families shift exactly; log/logit families solve
    Σ w·g(offset + f0, y) = 0 by Newton."""
    import jax

    if dist.name in SHIFT_INIT:
        return dist.init_f0(y - offset, w)

    def step(f0, _):
        g, h = dist.grad_hess(offset + f0, y)
        return f0 - (w * g).sum() / jnp.maximum((w * h).sum(), 1e-12), None

    f0, _ = jax.lax.scan(step, jnp.float32(0.0), None, length=n_iter)
    return f0
