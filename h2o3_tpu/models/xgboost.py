"""H2OXGBoostEstimator — the XGBoost parameter surface over the shared
tree machinery.

Reference: h2o-extensions/xgboost — XGBoostModel.java:124 (parameter
definitions), :253-293 (tree_method/backend selection), BoosterWrapper
JNI into libxgboost's hist/gpu_hist + Rabit allreduce.

TPU re-design: there is no JNI and no Rabit — the booster IS the JAX
histogram tree builder (ops/hist_adaptive.py fused kernel or the
global-sketch path), with the cross-shard psum standing in for the Rabit
ring (SURVEY §2.4). This class maps the XGBoost parameter names onto the
shared TreeConfig/GBM knobs:

  eta                  -> learn_rate          (default 0.3, XGBoost's)
  subsample            -> sample_rate
  colsample_bytree     -> col_sample_rate_per_tree
  colsample_bylevel    -> col_sample_rate
  max_bins             -> nbins
  min_split_improvement<- gamma
  reg_lambda (1.0)     -> L2 on leaf values  (XGBoost default, not 0)
  reg_alpha            -> L1 soft-threshold on leaf values
  min_child_weight     -> min_rows (hessian-weight bound approximated by
                          the row-weight bound, exact for unit hessians)
  tree_method auto/hist-> uniform_adaptive / quantiles_global histograms
  booster              -> gbtree only (dart/gblinear raise)
"""
from __future__ import annotations

from typing import Dict

from h2o3_tpu.models.gbm import GBM_DEFAULTS, H2OGradientBoostingEstimator

XGB_DEFAULTS: Dict = dict(
    ntrees=50, max_depth=6, eta=0.3, subsample=1.0, colsample_bytree=1.0,
    colsample_bylevel=1.0, max_bins=256, min_child_weight=1.0,
    gamma=0.0, reg_lambda=1.0, reg_alpha=0.0, tree_method="auto",
    booster="gbtree", distribution="auto", seed=-1, stopping_rounds=0,
    stopping_metric="auto", stopping_tolerance=1e-3, score_tree_interval=0,
)

_ALIAS = {
    "learn_rate": "eta",
    "sample_rate": "subsample",
    "col_sample_rate_per_tree": "colsample_bytree",
    "col_sample_rate": "colsample_bylevel",
}


class H2OXGBoostEstimator(H2OGradientBoostingEstimator):
    algo = "xgboost"

    def __init__(self, **params):
        booster = (params.get("booster",
                              XGB_DEFAULTS["booster"]) or "gbtree").lower()
        if booster not in ("gbtree",):
            raise NotImplementedError(
                f"booster='{booster}' is not implemented (gbtree only; "
                f"the reference's dart/gblinear come from libxgboost)")
        tm = (params.get("tree_method",
                         XGB_DEFAULTS["tree_method"]) or "auto").lower()
        hist = ("uniform_adaptive" if tm in ("auto", "exact")
                else "quantiles_global")

        def pick(*names, default):
            # user-supplied value wins under EITHER spelling; the XGBoost
            # default applies only when neither was given
            for nm in names:
                if nm in params:
                    return params[nm]
            return default

        max_bins = int(pick("max_bins", "nbins", default=256))
        gbm_params = dict(GBM_DEFAULTS)
        gbm_params.update(dict(
            ntrees=int(pick("ntrees", "n_estimators", default=50)),
            max_depth=int(pick("max_depth", default=6)),
            learn_rate=float(pick("eta", "learn_rate", default=0.3)),
            sample_rate=float(pick("subsample", "sample_rate", default=1.0)),
            col_sample_rate_per_tree=float(
                pick("colsample_bytree", "col_sample_rate_per_tree",
                     default=1.0)),
            col_sample_rate=float(
                pick("colsample_bylevel", "col_sample_rate", default=1.0)),
            # adaptive histograms recover resolution with depth, so
            # tree_method=auto uses 62 bins (W=64); explicit hist keeps
            # the full global-sketch bin budget
            nbins=(min(max_bins - 2, 62) if hist == "uniform_adaptive"
                   else min(max_bins - 2, 1022)),
            min_rows=float(pick("min_child_weight", "min_rows", default=1.0)),
            min_split_improvement=float(
                pick("gamma", "min_split_improvement", default=0.0)),
            reg_lambda=float(pick("reg_lambda", default=1.0)),
            reg_alpha=float(pick("reg_alpha", default=0.0)),
            histogram_type=hist,
            distribution=params.get("distribution", "auto"),
            seed=params.get("seed", -1),
            stopping_rounds=params.get("stopping_rounds", 0),
            stopping_metric=params.get("stopping_metric", "auto"),
            stopping_tolerance=params.get("stopping_tolerance", 1e-3),
            score_tree_interval=params.get("score_tree_interval", 0),
        ))
        handled = (set(_ALIAS) | set(_ALIAS.values()) | set(XGB_DEFAULTS)
                   | {"n_estimators", "nbins", "min_rows",
                      "min_split_improvement"})
        for k, v in params.items():
            if k in gbm_params and k not in handled:
                gbm_params[k] = v
        super(H2OGradientBoostingEstimator, self).__init__(**gbm_params)
