"""ANOVA GLM — type-III deviance decomposition via GLM refits.

Reference: hex/anovaglm/AnovaGLM.java — trains a full GLM plus one
reduced GLM per term (the frame-transformation wrapper over GLM), then
reports per-term degrees of freedom, sum-of-squares (deviance
difference), and F / likelihood-ratio χ² p-values.

TPU re-design: each (re)fit is the existing MXU Gram IRLS solve — the
whole ANOVA is a handful of F×F Cholesky solves over one shared design,
so the deviance table costs a few device solves, not passes over data.
Main effects always enter; numeric×numeric pairwise interactions join
when highest_interaction_term >= 2."""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
from scipy import stats

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import GLM_DEFAULTS, H2OGeneralizedLinearEstimator
from h2o3_tpu.models.model_base import Model, ModelBuilder
from h2o3_tpu.persist import (model_from_meta, model_to_meta,
                              register_model_class)

ANOVA_DEFAULTS: Dict = dict(
    highest_interaction_term=2, type=3,
    # reference ANOVAGLM computes p-values by default — its ANOVA
    # tables depend on them (h2o-py h2o/estimators/anovaglm.py:49)
    compute_p_values=True, tweedie_link_power=1.0,
)


class AnovaGLMModel(Model):
    algo = "anovaglm"

    def __init__(self, key, params, spec, full_model, table):
        super().__init__(key, params, spec)
        self.full_model = full_model
        self.anova_table = table

    def predict(self, frame: Frame) -> Frame:
        return self.full_model.predict(frame)

    def _predict_matrix(self, X, offset=None):
        return self.full_model._predict_matrix(X, offset=offset)

    def summary(self):
        return self.anova_table

    def _save_arrays(self):
        return {f"inner__{k}": v
                for k, v in self.full_model._save_arrays().items()}

    def _save_extra_meta(self):
        return {"inner_meta": model_to_meta(self.full_model),
                "anova_table": self.anova_table}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        inner_arrays = {k[len("inner__"):]: v for k, v in arrays.items()
                        if k.startswith("inner__")}
        m.full_model = model_from_meta(ex["inner_meta"], inner_arrays)
        m.anova_table = ex["anova_table"]
        return m


class H2OANOVAGLMEstimator(ModelBuilder):
    algo = "anovaglm"

    def __init__(self, **params):
        merged = dict(GLM_DEFAULTS)
        merged.update(ANOVA_DEFAULTS)
        merged.update(params)
        for alias in ("lambda_", "lambda"):
            if alias in merged:
                merged["Lambda"] = merged.pop(alias)
        super().__init__(**merged)

    def _glm(self, terms: List[str], y, frame, base_frame_cols) -> "Model":
        p = {k: v for k, v in self.params.items()
             if k not in ANOVA_DEFAULTS}
        p["Lambda"] = [0.0]          # ANOVA is unpenalized by definition
        p.pop("lambda_search", None)
        est = H2OGeneralizedLinearEstimator(**p)
        est.train(x=terms, y=y, training_frame=frame)
        return est.model

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        p = self.params
        y = y or p.get("response_column")
        if training_frame is None or y is None:
            raise ValueError("ANOVA GLM needs training_frame and y")
        special = {y, p.get("weights_column"), p.get("offset_column")}
        preds = list(x) if x else [n for n in training_frame.names
                                   if n not in special]
        # term → columns in the working frame; interactions get product cols
        frame = training_frame
        terms: Dict[str, List[str]] = {n: [n] for n in preds}
        if int(p.get("highest_interaction_term", 2)) >= 2:
            numeric = [n for n in preds
                       if not training_frame.vec(n).is_categorical]
            extra_names: List[str] = []
            extra_vecs: List[Vec] = []
            for i in range(len(numeric)):
                for j in range(i + 1, len(numeric)):
                    a, b = numeric[i], numeric[j]
                    nm = f"{a}:{b}"
                    prod = (training_frame.vec(a).to_numpy()
                            * training_frame.vec(b).to_numpy())
                    extra_names.append(nm)
                    extra_vecs.append(Vec.from_numpy(
                        prod.astype(np.float32)))
                    terms[nm] = [nm]
            if extra_names:
                frame = frame.cbind(Frame(extra_names, extra_vecs))
        all_cols = [c for t in terms.values() for c in t]
        job = Job("anovaglm", work=float(len(terms) + 1))

        def body(job):
            full = self._glm(all_cols, y, frame, preds)
            job.update(1.0)
            family = full.family
            dev_full = full.residual_deviance
            df_resid = full.nobs - full.rank
            rows = []
            for ti, (tname, tcols) in enumerate(terms.items()):
                reduced_cols = [c for c in all_cols if c not in tcols]
                if reduced_cols:
                    red = self._glm(reduced_cols, y, frame, preds)
                    red_dev, red_rank = red.residual_deviance, red.rank
                else:
                    # single-term model: the reduced fit is the null
                    # (intercept-only) model — x=[] would mean "all cols"
                    red_dev, red_rank = full.null_deviance, 1
                df_t = max(full.rank - red_rank, 1)
                ss = max(red_dev - dev_full, 0.0)
                if family == "gaussian":
                    msr = ss / df_t
                    mse = dev_full / max(df_resid, 1)
                    f = msr / max(mse, 1e-30)
                    pval = float(stats.f.sf(f, df_t, max(df_resid, 1)))
                    rows.append({"term": tname, "df": df_t, "ss": ss,
                                 "msr": msr, "f_value": f, "p_value": pval})
                else:
                    pval = float(stats.chi2.sf(ss, df_t))
                    rows.append({"term": tname, "df": df_t, "deviance": ss,
                                 "p_value": pval})
                job.update(1.0)
            model = AnovaGLMModel(
                f"anova_{id(self) & 0xffffff:x}", self.params,
                _spec_of(full), full, rows)
            model.training_metrics = full.training_metrics
            model.output["anova_table"] = rows
            model.output["coefficients"] = full.coef()
            return model

        job.run(body)
        self.model = job.join()
        self.job = job
        from h2o3_tpu import dkv
        dkv.put(self.model.key, "model", self.model)
        return self

    def _train_impl(self, spec, valid_spec, job: Job):
        raise RuntimeError("ANOVA GLM overrides train() directly")


def _spec_of(model: Model):
    """Adapter: reuse an inner model's schema as the wrapper's spec."""
    class _S:
        names = model.feature_names
        is_cat = model.feature_is_cat
        cat_domains = model.cat_domains
        response = model.response
        response_domain = model.response_domain
        nclasses = model.nclasses
    return _S()


register_model_class("anovaglm", AnovaGLMModel)
