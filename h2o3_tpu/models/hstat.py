"""Friedman-Popescu H statistic for tree ensembles.

Reference: h2o-algos/src/main/java/hex/tree/FriedmanPopescusH.java —
H (Friedman & Popescu 2008, Ann. Appl. Stat. 2:916-954 s.8.1) tests for
an interaction among a set of variables in a tree ensemble:

  H^2 = sum_u c_u [ sum_{S subseteq V, S != {}} (-1)^{|V|-|S|} F_S(u) ]^2
        / sum_u c_u F_V(u)^2

evaluated over the unique rows u (with counts c_u) of the training
frame's V-columns, where F_S is the CENTERED partial dependence of the
ensemble on the variable subset S (FriedmanPopescusH.computeFValues:
count-weighted mean subtracted). For |V|=2 the inner sum is
F_{12} - F_1 - F_2: zero when the model is additive in the two
variables. H = sqrt(H^2) when numerator < denominator, else NaN (weak
main effects + rounding spoil the ratio — same rule as computeHValue).

Partial dependence is computed directly on the tree structure
(FriedmanPopescusH.partialDependenceTree, Friedman's weighted-traversal
algorithm): splits on a variable in S route the whole weight by the
grid value; splits on complement variables send cover-proportional
weight (node_w children ratio) down BOTH branches. Vectorized here over
all grid rows at once per tree: a [n_u, M] weight matrix walked in heap
order — no per-row stack, one numpy pass per tree.
"""
from itertools import combinations
from typing import List, Sequence

import numpy as np

__all__ = ["friedman_popescu_h"]


def _pd_tree(Vs: np.ndarray, pos_of_feat: dict, feat, thr, na_left,
             is_split, node_w, value, max_depth: int) -> np.ndarray:
    """Partial dependence of ONE tree on the features in `pos_of_feat`
    (model feature id -> column of Vs), evaluated at grid rows Vs."""
    n_u = Vs.shape[0]
    M = feat.shape[0]
    first_bottom = 2 ** max_depth - 1       # depth-D nodes cannot split
    Wt = np.zeros((n_u, M), np.float64)
    Wt[:, 0] = 1.0
    out = np.zeros(n_u, np.float64)
    for m in range(M):
        w = Wt[:, m]
        if not np.any(w):
            continue
        if m >= first_bottom or not is_split[m]:
            out += w * float(value[m])
            continue
        l, r = 2 * m + 1, 2 * m + 2
        f = int(feat[m])
        if f in pos_of_feat:
            x = Vs[:, pos_of_feat[f]]
            # same routing as predict_raw_stacked (models/tree.py):
            # NaN goes by na_left, else right iff x >= thr
            go_right = np.where(np.isnan(x), not bool(na_left[m]),
                                x >= float(thr[m])).astype(np.float64)
            Wt[:, r] += w * go_right
            Wt[:, l] += w * (1.0 - go_right)
        else:
            wl, wr = float(node_w[l]), float(node_w[r])
            tot = wl + wr
            frac = wl / tot if tot > 0 else 1.0
            Wt[:, l] += w * frac
            Wt[:, r] += w * (1.0 - frac)
    return out


def _pd_ensemble(Vs, pos_of_feat, feat, thr, na_left, is_split, node_w,
                 value, max_depth: int, tree_scale) -> np.ndarray:
    T = feat.shape[0]
    out = np.zeros(Vs.shape[0], np.float64)
    for t in range(T):
        out += _pd_tree(Vs, pos_of_feat, feat[t], thr[t], na_left[t],
                        is_split[t], node_w[t], value[t], max_depth)
    if tree_scale is not None:
        out *= float(tree_scale)
    return out


def friedman_popescu_h(model, frame, variables: Sequence[str]) -> float:
    """H statistic of `variables` for a stacked-tree model (GBM/DRF/
    XGBoost-compat). 0 = no interaction; NaN when numer >= denom."""
    from h2o3_tpu.models.model_base import adapt_test_matrix

    names: List[str] = list(model.feature_names)
    variables = list(variables)
    if len(variables) < 2:
        raise ValueError("H statistic needs at least 2 variables")
    missing = [v for v in variables if v not in names]
    if missing:
        raise ValueError(f"variables not in model features: {missing}")
    if getattr(model, "nclasses", 1) > 2:
        raise ValueError("H statistic supports regression and binomial "
                         "models only")
    if getattr(model, "_node_w", None) is None:
        raise ValueError("this model artifact predates contributions "
                         "support (no per-node cover weights); retrain")
    fids = [names.index(v) for v in variables]
    X = np.asarray(adapt_test_matrix(model, frame), np.float64)
    X = X[: frame.nrow]
    V = X[:, fids]                                       # [n, k]
    uniq, counts = np.unique(V, axis=0, return_counts=True)
    n = float(V.shape[0])
    k = len(fids)

    feat = np.asarray(model._feat)
    thr = np.asarray(model._thr)
    na_left = np.asarray(model._na_left)
    is_split = np.asarray(model._is_split)
    node_w = np.asarray(model._node_w)
    value = np.asarray(model._value)
    scale = model._contrib_scale() if hasattr(model, "_contrib_scale") \
        else None

    inner = np.zeros(uniq.shape[0], np.float64)
    f_full = None
    for size in range(k, 0, -1):
        sign = (-1.0) ** (k - size)
        for sub in combinations(range(k), size):
            pos = {fids[j]: j for j in sub}              # feature id -> V col
            f_s = _pd_ensemble(uniq, pos, feat, thr, na_left, is_split,
                               node_w, value, int(model.max_depth), scale)
            f_s = f_s - float(counts @ f_s) / n          # centered
            inner += sign * f_s
            if size == k:
                f_full = f_s
    numer = float(counts @ (inner ** 2))
    denom = float(counts @ (f_full ** 2))
    return float(np.sqrt(numer / denom)) if numer < denom else float("nan")
