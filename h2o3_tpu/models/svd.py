"""SVD — singular value decomposition of the (expanded) design matrix.

Reference: hex/svd/SVD.java:46 — GramSVD (distributed Gram then driver
eigensolver), Power iteration, Randomized subspace; outputs v (right
singular vectors), d (singular values), optional u frame.

TPU re-design: the Gram is one sharded MXU matmul (the GramTask reduce)
and eigh runs on device — power/randomized methods collapse into the
same path (an F×F eigh is cheap at any dense F we support). U = X·V/d is
one more matmul, computed lazily by predict()/u()."""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import expand_design, expand_scoring_matrix
from h2o3_tpu.models.model_base import (Model, ModelBuilder, TrainingSpec,
                                        pack_impute_means,
                                        unpack_impute_means)
from h2o3_tpu.persist import register_model_class

SVD_DEFAULTS: Dict = dict(
    nv=1, transform="none", svd_method="gram_s_v_d", seed=-1,
    use_all_factor_levels=True, keep_u=True, max_iterations=1000,
)


class SVDModel(Model):
    algo = "svd"
    supervised = False

    def __init__(self, key, params, spec, v, d, xm, xs, exp_names,
                 impute_means):
        super().__init__(key, params, spec)
        self.v = np.asarray(v)            # [Fe, nv] right singular vectors
        self.d = np.asarray(d)            # [nv] singular values
        self._xm = np.asarray(xm)
        self._xs = np.asarray(xs)
        self.expanded_names = list(exp_names)
        self.impute_means = dict(impute_means)
        self.use_all_levels = bool(params.get("use_all_factor_levels", True))

    def _predict_matrix(self, X, offset=None):
        Xe = expand_scoring_matrix(self, X)
        Xs = (Xe - jnp.asarray(self._xm)[None, :]) / \
            jnp.asarray(self._xs)[None, :]
        # u rows: X·V / d
        return (Xs @ jnp.asarray(self.v)) / jnp.maximum(
            jnp.asarray(self.d)[None, :], 1e-30)

    def predict(self, frame):
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.models.model_base import adapt_test_matrix
        X = adapt_test_matrix(self, frame)
        U = np.asarray(jax.device_get(self._predict_matrix(X)))[: frame.nrow]
        names = [f"u{i}" for i in range(U.shape[1])]
        return Frame(names, [Vec.from_numpy(U[:, i].astype(np.float32))
                             for i in range(U.shape[1])])

    def _save_arrays(self):
        d = {"v": self.v, "d": self.d, "xm": self._xm, "xs": self._xs}
        d.update(pack_impute_means(self.impute_means))
        return d

    def _save_extra_meta(self):
        return {"expanded_names": self.expanded_names}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        m.v = arrays["v"]
        m.d = arrays["d"]
        m._xm = arrays["xm"]
        m._xs = arrays["xs"]
        m.expanded_names = meta["extra"]["expanded_names"]
        m.impute_means = unpack_impute_means(arrays)
        m.use_all_levels = bool(m.params.get("use_all_factor_levels", True))
        return m


class H2OSingularValueDecompositionEstimator(ModelBuilder):
    algo = "svd"
    supervised = False

    def __init__(self, **params):
        merged = dict(SVD_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        p = self.params
        use_all = bool(p.get("use_all_factor_levels", True))
        Xe, exp_names, means = expand_design(spec, use_all_levels=use_all)
        Fe = Xe.shape[1]
        nv = min(int(p.get("nv", 1)), Fe)
        w = spec.w
        wsum = w.sum()
        transform = (p.get("transform") or "none").lower()
        xm = (Xe * w[:, None]).sum(0) / wsum
        if transform == "standardize":
            xv = (w[:, None] * (Xe - xm[None, :]) ** 2).sum(0) / wsum
            xs = jnp.sqrt(jnp.maximum(xv, 1e-12))
        elif transform in ("demean", "center"):
            xs = jnp.ones(Fe, jnp.float32)
        elif transform in ("descale", "scale"):
            xv = (w[:, None] * (Xe - xm[None, :]) ** 2).sum(0) / wsum
            xs = jnp.sqrt(jnp.maximum(xv, 1e-12))
            xm = jnp.zeros(Fe, jnp.float32)
        else:  # none
            xm = jnp.zeros(Fe, jnp.float32)
            xs = jnp.ones(Fe, jnp.float32)
        Xs = ((Xe - xm[None, :]) / xs[None, :]) * (w > 0)[:, None]
        # Gram of the weighted design (unnormalized — hex/svd semantics:
        # d are singular values of X itself, not of X/sqrt(n))
        G = jax.lax.dot_general(Xs, Xs * w[:, None], (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        vals, vecs = jnp.linalg.eigh(G)
        order = jnp.argsort(-vals)
        vals = jnp.maximum(vals[order][:nv], 0.0)
        vecs = vecs[:, order][:, :nv]
        d = jnp.sqrt(vals)
        job.set_progress(1.0)
        model = SVDModel(f"svd_{id(self) & 0xffffff:x}", self.params, spec,
                         jax.device_get(vecs), jax.device_get(d),
                         jax.device_get(xm), jax.device_get(xs), exp_names,
                         {k_: float(jax.device_get(v))
                          for k_, v in means.items()})
        model.output["v"] = model.v.tolist()
        model.output["d"] = model.d.tolist()
        model.output["names_expanded"] = exp_names
        return model


register_model_class("svd", SVDModel)
