"""Infogram — admissible-ML feature screening.

Reference: h2o-admissibleml (hex/Infogram/Infogram.java) — plots each
feature's RELEVANCE (variable importance in a model on all predictors)
against its (conditional) INFORMATION (normalized CMI estimated with
per-feature GBMs); features above both thresholds are "admissible".
Core infogram: x = total information of the single feature; fair
infogram (protected_columns set): x = conditional information given the
protected set.

TPU re-design: relevance reuses the GBM path's gain-based variable
importances; each per-feature CMI estimate is one small histogram-GBM
fit (the reference does exactly this, one GBM per feature) — these run
back-to-back on device with shared binning machinery."""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.jobs import Job
from h2o3_tpu.models.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.model_base import Model, ModelBuilder
from h2o3_tpu.persist import register_model_class

INFOGRAM_DEFAULTS: Dict = dict(
    protected_columns=None, net_information_threshold=0.1,
    total_information_threshold=0.1, relevance_index_threshold=0.1,
    safety_index_threshold=0.1, ntop=50, cmi_ntrees=10, cmi_max_depth=3,
    seed=-1,
)


def _model_score(est, nclasses: int) -> float:
    """Scalar predictive strength of a fitted model: AUC-gini for
    binomial, 1-rel.error for multinomial, R2 for regression — all in
    [0, 1]-ish so CMI ratios normalize cleanly."""
    mm = est.model.training_metrics
    if nclasses == 2:
        return max(2.0 * mm.auc - 1.0, 0.0)
    if nclasses > 2:
        return max(1.0 - mm.error, 0.0)
    return max(mm.r2, 0.0)


class InfogramModel(Model):
    algo = "infogram"

    def __init__(self, key, params, spec, table):
        super().__init__(key, params, spec)
        self.infogram_table = table

    def get_admissible_features(self) -> List[str]:
        return [r["column"] for r in self.infogram_table
                if r["admissible"]]

    def _predict_matrix(self, X, offset=None):
        raise NotImplementedError(
            "Infogram is a screening tool — train on "
            "get_admissible_features() instead of predicting")

    def _save_extra_meta(self):
        return {"table": self.infogram_table}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        m.infogram_table = meta["extra"]["table"]
        return m


class H2OInfogram(ModelBuilder):
    algo = "infogram"

    def __init__(self, **params):
        merged = dict(INFOGRAM_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        p = self.params
        y = y or p.get("response_column")
        if training_frame is None or y is None:
            raise ValueError("Infogram needs training_frame and y")
        protected = list(p.get("protected_columns") or [])
        special = {y, p.get("weights_column"), p.get("offset_column")}
        preds = [c for c in (x or training_frame.names)
                 if c not in special and c not in protected]
        ntrees = int(p.get("cmi_ntrees", 10))
        depth = int(p.get("cmi_max_depth", 3))
        seed = int(p.get("seed", -1) or -1)
        job = Job("infogram", work=float(len(preds) + 2))

        def gbm(cols):
            est = H2OGradientBoostingEstimator(
                ntrees=ntrees, max_depth=depth, seed=seed,
                weights_column=p.get("weights_column"))
            est.train(x=cols, y=y, training_frame=training_frame)
            return est

        def body(job):
            # relevance: gain varimp of the all-predictor model
            full = gbm(preds + protected)
            job.update(1.0)
            nclasses = full.model.nclasses
            vi = full.model.output.get("variable_importances") or {}
            rel = dict(zip(vi.get("variable", []),
                           vi.get("scaled_importance", [])))
            # information: per-feature CMI estimates
            base = 0.0
            if protected:
                base = _model_score(gbm(protected), nclasses)
                job.update(1.0)
            rows = []
            for c in preds:
                cols = [c] + protected
                sc = _model_score(gbm(cols), nclasses)
                cmi = max(sc - base, 0.0)
                rows.append({"column": c, "cmi_raw": cmi,
                             "relevance": float(rel.get(c, 0.0))})
                job.update(1.0)
            max_cmi = max((r["cmi_raw"] for r in rows), default=0.0)
            # thresholds per the reference: fair infogram (protected set)
            # gates on safety_index (x) + relevance_index (y); core
            # infogram on total_information (x) + net_information (y)
            if protected:
                info_thr = float(p.get("safety_index_threshold", 0.1))
                rel_thr = float(p.get("relevance_index_threshold", 0.1))
            else:
                info_thr = float(p.get("total_information_threshold", 0.1))
                rel_thr = float(p.get("net_information_threshold", 0.1))
            for r in rows:
                r["cmi"] = (r["cmi_raw"] / max_cmi) if max_cmi > 0 else 0.0
                r["admissible"] = (r["cmi"] >= info_thr
                                   and r["relevance"] >= rel_thr)
            rows.sort(key=lambda r: -(r["cmi"] + r["relevance"]))
            rows = rows[: int(p.get("ntop", 50))]
            model = InfogramModel(
                f"ig_{id(self) & 0xffffff:x}", self.params,
                _spec_of(full.model), rows)
            model.output["infogram_table"] = rows
            model.output["admissible_features"] = [
                r["column"] for r in rows if r["admissible"]]
            model.output["protected_columns"] = protected
            return model

        job.run(body)
        self.model = job.join()
        self.job = job
        from h2o3_tpu import dkv
        dkv.put(self.model.key, "model", self.model)
        return self

    def _train_impl(self, spec, valid_spec, job: Job):
        raise RuntimeError("Infogram overrides train() directly")


def _spec_of(model: Model):
    class _S:
        names = model.feature_names
        is_cat = model.feature_is_cat
        cat_domains = model.cat_domains
        response = model.response
        response_domain = model.response_domain
        nclasses = model.nclasses
    return _S()


register_model_class("infogram", InfogramModel)
