"""Double-buffered device chunk pipeline for memory-pressure GBM training.

The PR-2 streamed path (``grow_tree_adaptive_streamed``) re-uploaded
every chunk's X once per TREE LEVEL — throughput degraded by levels ×
(transfer/compute ratio), the exact failure mode XGBoost's out-of-core
mode (Chen & Guestrin 2016) attacks with block streaming + prefetch.
This manager restructures the transfer schedule:

- **Resident window**: as many chunks as the memman budget allows keep
  their X (plus y/w/margin/nid working vectors) DEVICE-resident for the
  whole train — uploaded once per train, not once per level. When the
  window covers the dataset, per-tree H2D traffic collapses to the tiny
  split tables (the bench guard asserts ≤ 1.1× the dataset footprint
  per tree).
- **Double-buffered overflow**: chunks beyond the window stream per
  level as before, but chunk k+1's ``device_put`` is issued BEFORE
  chunk k's level kernel result is consumed — JAX's async dispatch
  overlaps the transfer with compute (upload k+1 while k computes).
- **Device-side margins**: resident chunks update margins on device
  with the same f32 arithmetic as the dense path's jitted chunk body,
  so a fully-resident streamed train is BIT-IDENTICAL to the dense
  grower on the same single chunk (tests/test_transfer_budget.py).
- **Packed (compressed) resident windows** (ISSUE 12, ``packed_W``):
  the window representation is the int8/int16 BIN-CODE matrix instead
  of f32 features — the same memman budget keeps ~4x more rows
  resident, overflow-chunk H2D moves codes, and on the pallas path
  each upload is relaid out ONCE into the kernel's transposed
  tile-padded operand (no per-level transpose).

Every upload/fetch goes through the telemetry byte counters with
``pipeline="train"``, so the once-per-tree contract is asserted by a
counter test instead of eyeballed.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu import telemetry

# stream-buffer depth for non-resident chunks: the upload of chunk k+1
# rides under chunk k's level kernel (double buffer)
_PREFETCH_DEPTH = 1

# fraction of the memman budget the resident window may claim (leaves
# headroom for histograms, split tables and XLA scratch)
_RESIDENT_SHARE = 0.8


def _record_h2d(nbytes: int) -> None:
    telemetry.record_h2d(int(nbytes), pipeline="train")


@jax.jit
def _apply_leaf(margin, lr, value, nid):
    """margin += lr · value[nid], jitted as ONE expression so XLA makes
    the same gather+FMA fusion decision as the dense chunk body's
    in-scan `margin + lr_t * tree["value"][nid]` — the eager two-op
    form rounds twice and breaks dense/streamed bit parity."""
    return margin + lr * value[nid]


class _ChunkHandle:
    """One chunk's view for a level pass: device X/nid plus the (g,h,w)
    triple computed on device from the chunk's margin."""
    __slots__ = ("mgr", "k", "s", "e", "X", "_nid", "_margin", "_y", "_wt")

    def __init__(self, mgr: "StreamedChunks", k: int, X, nid, margin, y, wt):
        self.mgr = mgr
        self.k = k
        self.s, self.e = mgr.spans[k]
        self.X = X
        self._nid = nid
        self._margin = margin
        self._y = y
        self._wt = wt

    @property
    def nid(self):
        return self._nid

    def ghw(self, dist):
        """[3, rows_c] f32 — same expression the dense chunk body feeds
        the grower: (g·wt, h·wt, wt) from the CURRENT margin."""
        g, h = dist.grad_hess(self._margin, self._y)
        return jnp.stack([g * self._wt, h * self._wt,
                          self._wt]).astype(jnp.float32)

    def put_nid(self, nid2) -> None:
        if self.mgr.is_resident(self.k):
            self.mgr._res[self.k]["nid"] = nid2
        else:
            host = np.asarray(telemetry.device_get(nid2, pipeline="train"))
            self.mgr.nid_host[self.s:self.e] = host

    def apply_leaf(self, lr, value, nid) -> None:
        """margin += lr·value[nid] via the fused jitted update (see
        ``_apply_leaf``) — on device for resident chunks, computed on
        device then fetched back for overflow chunks."""
        new_margin = _apply_leaf(self._margin, lr, value, nid)
        if self.mgr.is_resident(self.k):
            self.mgr._res[self.k]["margin"] = new_margin
        else:
            host = np.asarray(telemetry.device_get(new_margin,
                                                   pipeline="train"))
            self.mgr.margin_host[self.s:self.e] = host


class StreamedChunks:
    """Per-train chunk manager: resident window + double-buffered
    overflow streaming (see module docstring)."""

    def __init__(self, X_host: np.ndarray, y_host: np.ndarray,
                 w_host: np.ndarray, f0: float, chunk_rows: int,
                 padded_rows: Optional[int] = None,
                 margin0: Optional[np.ndarray] = None,
                 packed_W: Optional[int] = None):
        from h2o3_tpu import memman
        rows, F = X_host.shape
        # the dense grower sizes its histogram-precision auto rule by the
        # frame's PADDED row count — carry it so a fully-resident
        # streamed train makes the identical choice at the boundary
        self.padded_rows = int(padded_rows) if padded_rows else rows
        self.X_host = X_host
        self.y_host = np.asarray(y_host, np.float32)
        self.w_host = np.asarray(w_host, np.float32)
        self.rows, self.F = rows, F
        # packed mode (ISSUE 12): X_host carries int8/int16 BIN CODES
        # (NA = packed_W - 1) instead of f32 features — the compressed
        # resident window. The smaller per-row footprint below is what
        # lets the same memman budget keep ~4x more rows resident, and
        # every overflow upload moves codes, not floats.
        self.packed_W = packed_W
        self._x_itemsize = int(X_host.dtype.itemsize)
        if packed_W is not None:
            from h2o3_tpu.ops.hist_adaptive import pallas_interpret
            import jax as _jax
            self.kernel_layout = ("t" if (_jax.default_backend() == "tpu"
                                          or pallas_interpret()) else "rm")
        else:
            self.kernel_layout = "rm"
        self.spans: List[Tuple[int, int]] = [
            (s, min(s + chunk_rows, rows))
            for s in range(0, rows, chunk_rows)]
        self.C = len(self.spans)
        budget = memman.manager().budget
        # X (codes or f32) + y/w/margin/nid/wt f32 working vectors
        per_row = F * self._x_itemsize + 5 * 4
        window = int(budget * _RESIDENT_SHARE)
        if rows * per_row <= window:
            R = self.C
        else:
            # reserve the two stream buffers the overflow pipeline needs
            window -= 2 * chunk_rows * F * self._x_itemsize
            R = max(0, window // max(chunk_rows * per_row, 1))
        self.R = int(min(R, self.C))
        ro = os.environ.get("H2O3_STREAM_RESIDENT")
        if ro is not None and ro != "":
            self.R = max(0, min(int(ro), self.C))   # test/bench override
        self._res: Dict[int, Dict[str, object]] = {}
        # host mirrors serve the overflow chunks (and the final gather).
        # ``margin0`` is checkpoint-resume state (the saved f32 training
        # margin at the committed tree count): starting from it instead
        # of the constant f0 is what makes a resumed streamed train
        # bit-identical to an uninterrupted one (the dense path's
        # _prior_margin contract)
        if margin0 is not None:
            self.margin_host = np.asarray(margin0,
                                          np.float32)[:rows].copy()
        else:
            self.margin_host = np.full(rows, np.float32(f0), np.float32)
        self.nid_host = np.zeros(rows, np.int32)
        self._wt_host: Optional[np.ndarray] = None
        self._wt_dev = None            # full-rows device draw (resident slices)
        self.h2d_bytes = 0
        self.h2d_resident_bytes = 0    # the once-per-train window upload
        # cooperative cancellation (jobs.py watchdog / REST cancel): the
        # training driver points this at job.cancel_requested so a
        # cancel lands BETWEEN level passes — never inside the leaf-apply
        # pass, where a partial update would corrupt chunk margins
        self.cancel_check: Optional[callable] = None
        # preemption probe (scheduler checkpoint-preempt, PR 15): the
        # driver points this at job.preempt_requested. The fused
        # multi-level driver polls interrupt_pending() at each window
        # START and clamps the window to ONE level when a cancel or
        # preempt is pending, so the cooperative yield still lands at
        # the next level boundary instead of L levels later — the
        # chunk-commit contract is unchanged by fusion
        self.interrupt_check: Optional[callable] = None
        # performance accounting (ISSUE 11): the training driver parks
        # its costmodel.PerfAccumulator here so the level passes in
        # tree.py can attribute each level kernel's cost without
        # threading a parameter through the grower signature
        self.perf_acc = None

    # -- residency -------------------------------------------------------

    def is_resident(self, k: int) -> bool:
        return k < self.R

    def _put(self, arr: np.ndarray, resident: bool = False):
        from h2o3_tpu import memman
        from h2o3_tpu.resilience import resilient_device_put
        memman.manager().request(arr.nbytes)
        # transient chunk-upload failures retry with backoff — a flaky
        # DMA must not kill a train that has resident state to protect
        dev = resilient_device_put(arr, pipeline="train")
        _record_h2d(arr.nbytes)
        self.h2d_bytes += arr.nbytes
        if resident:
            self.h2d_resident_bytes += arr.nbytes
        return dev

    def _kernel_operand(self, dev):
        """Device-side relayout of an uploaded X chunk into the level
        kernel's operand. Packed + pallas: transposed tile-padded codes
        [F, rows_p] (pad = NA bin W-1), built ONCE per upload so
        resident chunks never pay a per-level transpose. Otherwise the
        chunk passes through unchanged."""
        if self.packed_W is not None and self.kernel_layout == "t":
            from h2o3_tpu import memman
            from h2o3_tpu.ops.binning import _pack_t_single
            from h2o3_tpu.ops.hist_adaptive import TILE
            rows_c = dev.shape[0]
            pad_r = (-rows_c) % TILE
            # the relayout is a SECOND device allocation (row-major
            # upload + padded transpose briefly coexist): admit the
            # padded buffer against the budget too, or a window sized
            # to exactly R chunks can OOM on the memory-pressure path
            memman.manager().request(
                (rows_c + pad_r) * self.F * self._x_itemsize)
            return _pack_t_single(dev, W=self.packed_W, tile=TILE)
        return dev

    def _ensure_resident(self, k: int, need_x: bool = True
                         ) -> Dict[str, object]:
        st = self._res.get(k)
        if st is None:
            s, e = self.spans[k]
            st = {"X": None,
                  "y": self._put(self.y_host[s:e], resident=True),
                  "w": self._put(self.w_host[s:e], resident=True),
                  "margin": self._put(self.margin_host[s:e], resident=True),
                  "nid": jnp.zeros(e - s, jnp.int32)}
            self._res[k] = st
        if need_x and st["X"] is None:
            # X deferred until a pass actually reads features — a
            # depth-0 stump train never uploads it at all
            s, e = self.spans[k]
            st["X"] = self._kernel_operand(
                self._put(self.X_host[s:e], resident=True))
        return st

    # -- per-tree state --------------------------------------------------

    def begin_tree(self, key, sample_rate: float) -> None:
        """Draw the per-tree row-sample weights (one full-rows device
        draw, sliced per chunk — same draw the PR-2 path made) and reset
        per-chunk node ids."""
        self._wt_dev = None
        self._wt_host = None
        if sample_rate < 1.0 and key is not None:
            u = jax.random.uniform(key, (self.rows,))
            self._wt_dev = u
            if self.R < self.C:
                host = np.asarray(telemetry.device_get(u, pipeline="train"))
                self._wt_host = self.w_host * (host < sample_rate)
        self._sample_rate = float(sample_rate)
        for k in range(self.R):
            st = self._res.get(k)
            if st is not None:
                s, e = self.spans[k]
                st["nid"] = jnp.zeros(e - s, jnp.int32)
        self.nid_host[:] = 0

    def _wt_for(self, k: int, st: Optional[dict]):
        s, e = self.spans[k]
        if st is not None:
            w = st["w"]
            if self._wt_dev is None:
                return w
            return w * (self._wt_dev[s:e] < self._sample_rate)
        if self._wt_host is not None:
            return jnp.asarray(self._wt_host[s:e])
        return jnp.asarray(self.w_host[s:e])

    # -- level iteration -------------------------------------------------

    def interrupt_pending(self) -> bool:
        """True when a cooperative cancel or preempt is pending — read
        by the fused L-level driver at window start (see
        ``interrupt_check``). Never raises; the actual cancel still
        lands via ``cancel_check`` at the next ``level_pass`` start."""
        for check in (self.cancel_check, self.interrupt_check):
            if check is not None and check():
                return True
        return False

    def level_pass(self, need_x: bool = True):
        """Yield a `_ChunkHandle` per chunk. Overflow chunks' X uploads
        are issued ``_PREFETCH_DEPTH`` chunks ahead so the DMA drains
        under the previous chunk's level kernel. ``need_x=False`` (the
        depth-0 stump's (g,h,w)-only passes) skips the X staging
        entirely — those passes never read features."""
        from h2o3_tpu import memman
        if self.cancel_check is not None and self.cancel_check():
            # raised at pass START only: an in-progress pass (including
            # the leaf-apply pass) always completes, keeping margins
            # consistent across chunks
            from h2o3_tpu.jobs import JobCancelled
            raise JobCancelled("training cancelled between tree levels")
        pending: Dict[int, object] = {}

        def stage(k: int) -> None:
            if (not need_x or self.is_resident(k) or k in pending
                    or k >= self.C):
                return
            s, e = self.spans[k]
            # relayout rides the async dispatch queue right behind the
            # DMA, so it too drains under the previous chunk's kernel
            pending[k] = self._kernel_operand(self._put(self.X_host[s:e]))

        for k in range(min(_PREFETCH_DEPTH, self.C)):
            stage(k)
        for k in range(self.C):
            stage(k + _PREFETCH_DEPTH)
            s, e = self.spans[k]
            if self.is_resident(k):
                st = self._ensure_resident(k, need_x=need_x)
                yield _ChunkHandle(self, k, st["X"], st["nid"],
                                   st["margin"], st["y"],
                                   self._wt_for(k, st))
            else:
                X = pending.pop(k, None)
                # the small per-level vectors ride along with the
                # prefetched X: margin/y for ghw, nid for routing, plus
                # the (sampled) weight slice _wt_for uploads — 16 B/row
                # total, all of it on the byte counters
                mg = jnp.asarray(self.margin_host[s:e])
                yv = jnp.asarray(self.y_host[s:e])
                nid = jnp.asarray(self.nid_host[s:e])
                self.h2d_bytes += (e - s) * 16
                _record_h2d((e - s) * 16)
                yield _ChunkHandle(self, k, X, nid, mg, yv,
                                   self._wt_for(k, None))

    # -- finalize --------------------------------------------------------

    def gather_margin(self) -> np.ndarray:
        """Full-rows host margin (resident chunks fetched once, at the
        end of training — not per tree)."""
        for k, st in self._res.items():
            s, e = self.spans[k]
            host = np.asarray(telemetry.device_get(st["margin"],
                                                   pipeline="train"))
            self.margin_host[s:e] = host
        return self.margin_host

    def profile(self) -> Dict[str, object]:
        return {"chunks": self.C, "resident_chunks": self.R,
                "chunk_rows": (self.spans[0][1] - self.spans[0][0]
                               if self.spans else 0),
                "h2d_bytes": int(self.h2d_bytes),
                # once-per-train window upload, reported separately so
                # the per-tree steady-state number isn't distorted by
                # amortizing it over a small ntrees
                "h2d_resident_bytes": int(self.h2d_resident_bytes),
                # footprint of the representation ACTUALLY resident:
                # 1-2 byte codes in packed mode, f32 otherwise — the
                # bench guard's once-per-tree ratio stays honest
                "device_footprint_bytes": int(
                    self.rows * self.F * self._x_itemsize),
                "packed_codes": self.packed_W is not None,
                "x_itemsize": self._x_itemsize}
