"""PSVM — kernel support vector machine.

Reference: hex/psvm/PSVM.java:24 — Gaussian-kernel SVM solved by ICF
(incomplete Cholesky low-rank factorization of the kernel matrix, MRTask
per column) + interior-point method on the factor; support vectors are
the rows with dual alpha above _sv_threshold (PSVM.java:152
RegulateAlphaTask, sv/bsv counts in PSVMModel.java:169-170).

TPU re-design, two regimes:

- EXACT DUAL (default when the exact Gram fits — nrow <=
  H2O3_PSVM_EXACT_MAX, 8192 by default): the dual box-QP
  max Σα − ½(αy)ᵀK(αy), 0 ≤ α_i ≤ C_i, Σα_i y_i = 0 is solved by
  FISTA-accelerated projected gradient — each iteration is ONE [n, n]
  MXU matvec, and the {box ∩ hyperplane} projection is a 60-step
  bisection on the dual shift (all inside one lax.scan; the IPM's
  sequential Cholesky back-solves have no MXU shape). This produces
  true dual alphas → real support vectors, matching the reference's
  model semantics (svs_count/bsv_count, kernel scoring against SVs).

- RFF PRIMAL (large n): RANDOM FOURIER FEATURES (Rahimi-Recht):
  z(x) = √(2/R)·cos(xW + b), W ~ N(0, 2γI) gives E[z(x)·z(y)] =
  exp(−γ‖x−y‖²) — the same "factorize the kernel, solve a linear
  problem" structure as ICF, but the factor is one MXU matmul instead
  of a sequential column pivot. The primal squared-hinge objective is
  minimized with a jitted full-batch Nesterov loop."""
from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import expand_design, expand_scoring_matrix
from h2o3_tpu.models.model_base import (Model, ModelBuilder, TrainingSpec,
                                        compute_metrics, pack_impute_means,
                                        unpack_impute_means)
from h2o3_tpu.persist import register_model_class

PSVM_DEFAULTS: Dict = dict(
    kernel_type="gaussian", gamma=-1.0, hyper_param=1.0,
    rank_ratio=-1.0, max_iterations=200, seed=-1,
    positive_weight=1.0, negative_weight=1.0, sv_threshold=1e-4,
)


@partial(jax.jit, static_argnames=("steps",))
def _svm_dual_fit(K, yy, Cvec, steps):
    """Exact dual box-QP by FISTA projected gradient.

    max Σα − ½ (α∘y)ᵀ K (α∘y)  s.t.  0 ≤ α ≤ C, Σ α y = 0.
    Step size 1/λmax(K) (16-step power iteration); the joint
    {box ∩ Σαy=0} projection solves for the hyperplane multiplier δ in
    clip(α − δy, 0, C) by monotone bisection (s(δ) = Σ y·clip(α − δy)
    is non-increasing). Returns alphas."""
    n = K.shape[0]

    def pow_step(v, _):
        v = K @ v
        return v / jnp.maximum(jnp.linalg.norm(v), 1e-30), None
    v, _ = jax.lax.scan(pow_step, jnp.ones(n) / jnp.sqrt(n), None,
                        length=16)
    lam_max = jnp.maximum(v @ (K @ v), 1e-6)
    eta = 1.0 / lam_max

    def project(a):
        b0 = jnp.max(Cvec) + jnp.max(jnp.abs(a)) + 1.0

        def body(lohi, _):
            lo, hi = lohi
            mid = 0.5 * (lo + hi)
            s = (yy * jnp.clip(a - mid * yy, 0.0, Cvec)).sum()
            return (jnp.where(s > 0, mid, lo),
                    jnp.where(s > 0, hi, mid)), None
        (lo, hi), _ = jax.lax.scan(body, (-b0, b0), None, length=60)
        return jnp.clip(a - 0.5 * (lo + hi) * yy, 0.0, Cvec)

    def step(carry, _):
        a, z, t = carry
        g = 1.0 - yy * (K @ (z * yy))
        a_new = project(z + eta * g)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        z = a_new + ((t - 1.0) / t_new) * (a_new - a)
        return (a_new, z, t_new), None

    a0 = jnp.zeros(n)
    (a, _z, _t), _ = jax.lax.scan(step, (a0, a0, jnp.float32(1.0)), None,
                                  length=steps)
    return project(a)


def _gauss_gram(Xa, Xb, gamma):
    """exp(−γ‖xa−xb‖²) via one MXU matmul + row norms."""
    na = (Xa * Xa).sum(1)
    nb = (Xb * Xb).sum(1)
    d2 = jnp.maximum(na[:, None] - 2.0 * (Xa @ Xb.T) + nb[None, :], 0.0)
    return jnp.exp(-gamma * d2)


@partial(jax.jit, static_argnames=("steps",))
def _svm_fit(Z, yy, w, C, steps, lr):
    """Squared-hinge primal, mean-normalized:
    min λ/2·‖β‖² + (1/Σw)·Σ w·max(0, 1−y·(Zβ+b))², λ = 1/(C·Σw).
    Nesterov-accelerated full-batch gradient; returns (beta, b)."""
    R = Z.shape[1]
    wsum = jnp.maximum(w.sum(), 1e-30)
    lam = 1.0 / (C * wsum)

    def grad(params):
        beta, b = params
        m = yy * (Z @ beta + b)
        viol = jnp.maximum(0.0, 1.0 - m)
        g_common = (-2.0 / wsum) * w * viol * yy
        gb = (Z * g_common[:, None]).sum(0) + lam * beta
        g0 = g_common.sum()
        return gb, g0

    def step(carry, _):
        (beta, b), (vb, v0) = carry
        gb, g0 = grad((beta + 0.9 * vb, b + 0.9 * v0))
        vb = 0.9 * vb - lr * gb
        v0 = 0.9 * v0 - lr * g0
        return ((beta + vb, b + v0), (vb, v0)), None

    init = ((jnp.zeros(R), jnp.array(0.0)),
            (jnp.zeros(R), jnp.array(0.0)))
    (params, _), _ = jax.lax.scan(step, init, None, length=steps)
    return params


class PSVMModel(Model):
    algo = "psvm"

    def __init__(self, key, params, spec, beta, b, W, phase, xm, xs,
                 exp_names, impute_means, sv_X=None, alpha_y=None,
                 gamma=None):
        super().__init__(key, params, spec)
        self.beta = np.asarray(beta) if beta is not None else None
        self.b = float(b)
        self.W = np.asarray(W) if W is not None else None  # RFF projection
        self.phase = np.asarray(phase) if phase is not None else None
        self._xm = np.asarray(xm)
        self._xs = np.asarray(xs)
        self.exp_names = list(exp_names)
        self.impute_means = dict(impute_means)
        # exact-dual artifacts: standardized support vectors + alpha_i*y_i
        self.sv_X = np.asarray(sv_X) if sv_X is not None else None
        self.alpha_y = np.asarray(alpha_y) if alpha_y is not None else None
        self.gamma = float(gamma) if gamma is not None else None

    def _standardized(self, X):
        Xe = expand_scoring_matrix(self, X)
        return (Xe - jnp.asarray(self._xm)[None]) / jnp.asarray(self._xs)[None]

    def _features(self, X):
        Xs = self._standardized(X)
        if self.W is None:
            return Xs
        R = self.W.shape[1]
        return jnp.sqrt(2.0 / R) * jnp.cos(
            Xs @ jnp.asarray(self.W) + jnp.asarray(self.phase)[None])

    def decision_function(self, X):
        if self.alpha_y is not None:
            # exact kernel scoring against the support vectors
            # (PSVMModel.score0 ScorerTask analog)
            K = _gauss_gram(self._standardized(X),
                            jnp.asarray(self.sv_X), self.gamma)
            return K @ jnp.asarray(self.alpha_y) + self.b
        return self._features(X) @ jnp.asarray(self.beta) + self.b

    def _predict_matrix(self, X, offset=None):
        d = self.decision_function(X)
        # probability-shaped output via the decision margin (Platt-less
        # sigmoid; the reference reports raw decision + label)
        p1 = jax.nn.sigmoid(2.0 * d)
        return jnp.stack([1.0 - p1, p1], axis=1)

    def _save_arrays(self):
        d = {"xm": self._xm, "xs": self._xs,
             **pack_impute_means(self.impute_means)}
        if self.beta is not None:
            d["beta"] = self.beta
        if self.W is not None:
            d["W"] = self.W
            d["phase"] = self.phase
        if self.alpha_y is not None:
            d["sv_X"] = self.sv_X
            d["alpha_y"] = self.alpha_y
        return d

    def _save_extra_meta(self):
        return {"b": self.b, "exp_names": self.exp_names,
                "gamma": self.gamma}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        m.beta = arrays.get("beta")
        m.b = meta["extra"]["b"]
        m.gamma = meta["extra"].get("gamma")
        m.exp_names = list(meta["extra"]["exp_names"])
        m.W = arrays.get("W")
        m.phase = arrays.get("phase")
        m.sv_X = arrays.get("sv_X")
        m.alpha_y = arrays.get("alpha_y")
        m._xm = arrays["xm"]
        m._xs = arrays["xs"]
        m.impute_means = unpack_impute_means(arrays)
        return m


class H2OSupportVectorMachineEstimator(ModelBuilder):
    algo = "psvm"

    def __init__(self, **params):
        merged = dict(PSVM_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        p = self.params
        if spec.nclasses != 2:
            raise ValueError("PSVM is a binary classifier "
                             f"(got nclasses={spec.nclasses})")
        Xe, exp_names, means = expand_design(spec)
        Fe = Xe.shape[1]
        w = spec.w
        wsum = jnp.maximum(w.sum(), 1e-30)
        xm = (Xe * w[:, None]).sum(0) / wsum
        xv = (w[:, None] * (Xe - xm[None]) ** 2).sum(0) / wsum
        xs = jnp.sqrt(jnp.maximum(xv, 1e-12))
        Xs = ((Xe - xm[None]) / xs[None]) * (w > 0)[:, None]
        yy = jnp.where(spec.y > 0, 1.0, -1.0) * (w > 0)
        kernel = (p.get("kernel_type") or "gaussian").lower()
        seed = int(p.get("seed", -1) or -1)
        key = jax.random.PRNGKey(seed if seed != -1 else 0)
        gamma = float(p.get("gamma", -1.0))
        if gamma <= 0:
            gamma = 1.0 / max(Fe, 1)          # reference default 1/#cols
        C = float(p.get("hyper_param", 1.0))
        sv_thr = float(p.get("sv_threshold", 1e-4))
        import os as _os
        exact_max = int(_os.environ.get("H2O3_PSVM_EXACT_MAX", "8192"))
        # exact dual when the Gram fits AND the user didn't explicitly
        # ask for a low-rank factorization (rank_ratio > 0 selects the
        # RFF regime the way it selects ICF rank in the reference)
        if (kernel == "gaussian" and spec.nrow <= exact_max
                and float(p.get("rank_ratio", -1.0)) <= 0):
            return self._train_exact_dual(spec, job, Xs, yy, w, gamma, C,
                                          sv_thr, xm, xs, exp_names, means)
        W = phase = None
        if kernel == "gaussian":
            rr = float(p.get("rank_ratio", -1.0))
            nrow = spec.nrow
            R = int(rr * nrow) if rr > 0 else min(
                512, max(64, 4 * Fe))
            k1, k2 = jax.random.split(key)
            W = jax.random.normal(k1, (Fe, R)) * jnp.sqrt(2.0 * gamma)
            phase = jax.random.uniform(k2, (R,), minval=0.0,
                                       maxval=2.0 * jnp.pi)
            Z = jnp.sqrt(2.0 / R) * jnp.cos(Xs @ W + phase[None])
            Z = Z * (w > 0)[:, None]
        elif kernel == "linear":
            Z = Xs
        else:
            raise ValueError(f"unsupported kernel_type '{kernel}'")
        steps = int(p.get("max_iterations", 200))
        # lr from the mean-loss Lipschitz bound: L ≈ λ + 2·mean‖z‖²
        # (λmax of the mean Gram is bounded by its trace = mean ‖z‖²)
        wtot = float(jax.device_get(w.sum()))
        zz = float(jax.device_get((Z * Z * w[:, None]).sum()))
        mean_znorm = zz / max(wtot, 1e-30)
        lr = 1.0 / (1.0 / (C * max(wtot, 1e-30)) + 2.0 * mean_znorm + 1.0)
        beta, b = _svm_fit(Z, yy, w, jnp.float32(C),
                           steps, jnp.float32(lr))
        job.set_progress(1.0)
        model = PSVMModel(
            f"svm_{id(self) & 0xffffff:x}", self.params, spec,
            jax.device_get(beta), float(jax.device_get(b)),
            None if W is None else jax.device_get(W),
            None if phase is None else jax.device_get(phase),
            jax.device_get(xm), jax.device_get(xs), exp_names,
            {k_: float(jax.device_get(v)) for k_, v in means.items()})
        scores = model._predict_matrix(spec.X)
        model.training_metrics = compute_metrics(
            scores, spec.y, w, 2, spec.response_domain)
        nsv = int(jax.device_get(
            ((yy * (Z @ beta + b) < 1.0) & (w > 0)).sum()))
        model.output["svs_count"] = nsv   # margin violators ≈ SVs
        return model

    def _train_exact_dual(self, spec, job, Xs, yy, w, gamma, C, sv_thr,
                          xm, xs, exp_names, means):
        """Exact Gaussian dual with real support vectors (the regime the
        reference's ICF+IPM targets; hex/psvm/PSVM.java:139-170)."""
        p = self.params
        c_pos = float(p.get("positive_weight", 1.0))
        c_neg = float(p.get("negative_weight", 1.0))
        # per-row box: class weight x observation weight; w=0 rows get
        # C=0 so their alpha is pinned at 0 (excluded from the fit)
        Cvec = jnp.where(yy > 0, C * c_pos, C * c_neg) * w
        K = _gauss_gram(Xs, Xs, jnp.float32(gamma))
        # one PG step != one IPM iteration: scale the exposed
        # max_iterations (IPM default 200) into the first-order budget
        steps = 10 * max(int(p.get("max_iterations", 200)), 1)
        alphas = _svm_dual_fit(K, yy, Cvec.astype(jnp.float32), steps)
        ay = alphas * yy
        Kay = K @ ay
        free = (alphas > sv_thr) & (Cvec - alphas > sv_thr)
        nfree = jnp.maximum(free.sum(), 1)
        b_free = ((yy - Kay) * free).sum() / nfree
        sv = alphas > sv_thr
        b_any = ((yy - Kay) * sv).sum() / jnp.maximum(sv.sum(), 1)
        b = jnp.where(free.any(), b_free, b_any)
        job.set_progress(1.0)
        sv_np = np.asarray(jax.device_get(sv))
        model = PSVMModel(
            f"svm_{id(self) & 0xffffff:x}", self.params, spec,
            None, float(jax.device_get(b)), None, None,
            jax.device_get(xm), jax.device_get(xs), exp_names,
            {k_: float(jax.device_get(v)) for k_, v in means.items()},
            sv_X=np.asarray(jax.device_get(Xs))[sv_np],
            alpha_y=np.asarray(jax.device_get(ay))[sv_np], gamma=gamma)
        scores = model._predict_matrix(spec.X)
        model.training_metrics = compute_metrics(
            scores, spec.y, w, 2, spec.response_domain)
        bsv = (Cvec - alphas <= sv_thr) & sv
        model.output["svs_count"] = int(jax.device_get(sv.sum()))
        model.output["bsv_count"] = int(jax.device_get(bsv.sum()))
        return model


register_model_class("psvm", PSVMModel)
