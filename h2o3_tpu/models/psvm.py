"""PSVM — kernel support vector machine, primal formulation.

Reference: hex/psvm/PSVM.java:24 — Gaussian-kernel SVM solved by ICF
(incomplete Cholesky low-rank factorization of the kernel matrix, MRTask
per column) + interior-point method on the factor.

TPU re-design: the low-rank kernel factorization becomes RANDOM FOURIER
FEATURES (Rahimi-Recht): z(x) = √(2/R)·cos(xW + b) with W ~ N(0, 2γI)
gives E[z(x)·z(y)] = exp(−γ‖x−y‖²) — the same "factorize the kernel,
solve a linear problem" structure as ICF, but the factor is one MXU
matmul instead of a sequential column pivot. The primal squared-hinge
objective is then minimized with a jitted full-batch Nesterov loop
(every iteration: one [rows, R] matmul + reduction)."""
from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import expand_design, expand_scoring_matrix
from h2o3_tpu.models.model_base import (Model, ModelBuilder, TrainingSpec,
                                        compute_metrics, pack_impute_means,
                                        unpack_impute_means)
from h2o3_tpu.persist import register_model_class

PSVM_DEFAULTS: Dict = dict(
    kernel_type="gaussian", gamma=-1.0, hyper_param=1.0,
    rank_ratio=-1.0, max_iterations=200, seed=-1,
)


@partial(jax.jit, static_argnames=("steps",))
def _svm_fit(Z, yy, w, C, steps, lr):
    """Squared-hinge primal, mean-normalized:
    min λ/2·‖β‖² + (1/Σw)·Σ w·max(0, 1−y·(Zβ+b))², λ = 1/(C·Σw).
    Nesterov-accelerated full-batch gradient; returns (beta, b)."""
    R = Z.shape[1]
    wsum = jnp.maximum(w.sum(), 1e-30)
    lam = 1.0 / (C * wsum)

    def grad(params):
        beta, b = params
        m = yy * (Z @ beta + b)
        viol = jnp.maximum(0.0, 1.0 - m)
        g_common = (-2.0 / wsum) * w * viol * yy
        gb = (Z * g_common[:, None]).sum(0) + lam * beta
        g0 = g_common.sum()
        return gb, g0

    def step(carry, _):
        (beta, b), (vb, v0) = carry
        gb, g0 = grad((beta + 0.9 * vb, b + 0.9 * v0))
        vb = 0.9 * vb - lr * gb
        v0 = 0.9 * v0 - lr * g0
        return ((beta + vb, b + v0), (vb, v0)), None

    init = ((jnp.zeros(R), jnp.array(0.0)),
            (jnp.zeros(R), jnp.array(0.0)))
    (params, _), _ = jax.lax.scan(step, init, None, length=steps)
    return params


class PSVMModel(Model):
    algo = "psvm"

    def __init__(self, key, params, spec, beta, b, W, phase, xm, xs,
                 exp_names, impute_means):
        super().__init__(key, params, spec)
        self.beta = np.asarray(beta)
        self.b = float(b)
        self.W = np.asarray(W) if W is not None else None  # RFF projection
        self.phase = np.asarray(phase) if phase is not None else None
        self._xm = np.asarray(xm)
        self._xs = np.asarray(xs)
        self.exp_names = list(exp_names)
        self.impute_means = dict(impute_means)

    def _features(self, X):
        Xe = expand_scoring_matrix(self, X)
        Xs = (Xe - jnp.asarray(self._xm)[None]) / jnp.asarray(self._xs)[None]
        if self.W is None:
            return Xs
        R = self.W.shape[1]
        return jnp.sqrt(2.0 / R) * jnp.cos(
            Xs @ jnp.asarray(self.W) + jnp.asarray(self.phase)[None])

    def decision_function(self, X):
        return self._features(X) @ jnp.asarray(self.beta) + self.b

    def _predict_matrix(self, X, offset=None):
        d = self.decision_function(X)
        # probability-shaped output via the decision margin (Platt-less
        # sigmoid; the reference reports raw decision + label)
        p1 = jax.nn.sigmoid(2.0 * d)
        return jnp.stack([1.0 - p1, p1], axis=1)

    def _save_arrays(self):
        d = {"beta": self.beta, "xm": self._xm, "xs": self._xs,
             **pack_impute_means(self.impute_means)}
        if self.W is not None:
            d["W"] = self.W
            d["phase"] = self.phase
        return d

    def _save_extra_meta(self):
        return {"b": self.b, "exp_names": self.exp_names}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        m.beta = arrays["beta"]
        m.b = meta["extra"]["b"]
        m.exp_names = list(meta["extra"]["exp_names"])
        m.W = arrays.get("W")
        m.phase = arrays.get("phase")
        m._xm = arrays["xm"]
        m._xs = arrays["xs"]
        m.impute_means = unpack_impute_means(arrays)
        return m


class H2OSupportVectorMachineEstimator(ModelBuilder):
    algo = "psvm"

    def __init__(self, **params):
        merged = dict(PSVM_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        p = self.params
        if spec.nclasses != 2:
            raise ValueError("PSVM is a binary classifier "
                             f"(got nclasses={spec.nclasses})")
        Xe, exp_names, means = expand_design(spec)
        Fe = Xe.shape[1]
        w = spec.w
        wsum = jnp.maximum(w.sum(), 1e-30)
        xm = (Xe * w[:, None]).sum(0) / wsum
        xv = (w[:, None] * (Xe - xm[None]) ** 2).sum(0) / wsum
        xs = jnp.sqrt(jnp.maximum(xv, 1e-12))
        Xs = ((Xe - xm[None]) / xs[None]) * (w > 0)[:, None]
        yy = jnp.where(spec.y > 0, 1.0, -1.0) * (w > 0)
        kernel = (p.get("kernel_type") or "gaussian").lower()
        seed = int(p.get("seed", -1) or -1)
        key = jax.random.PRNGKey(seed if seed != -1 else 0)
        gamma = float(p.get("gamma", -1.0))
        if gamma <= 0:
            gamma = 1.0 / max(Fe, 1)          # reference default 1/#cols
        W = phase = None
        if kernel == "gaussian":
            rr = float(p.get("rank_ratio", -1.0))
            nrow = spec.nrow
            R = int(rr * nrow) if rr > 0 else min(
                512, max(64, 4 * Fe))
            k1, k2 = jax.random.split(key)
            W = jax.random.normal(k1, (Fe, R)) * jnp.sqrt(2.0 * gamma)
            phase = jax.random.uniform(k2, (R,), minval=0.0,
                                       maxval=2.0 * jnp.pi)
            Z = jnp.sqrt(2.0 / R) * jnp.cos(Xs @ W + phase[None])
            Z = Z * (w > 0)[:, None]
        elif kernel == "linear":
            Z = Xs
        else:
            raise ValueError(f"unsupported kernel_type '{kernel}'")
        C = float(p.get("hyper_param", 1.0))
        steps = int(p.get("max_iterations", 200))
        # lr from the mean-loss Lipschitz bound: L ≈ λ + 2·mean‖z‖²
        # (λmax of the mean Gram is bounded by its trace = mean ‖z‖²)
        wtot = float(jax.device_get(w.sum()))
        zz = float(jax.device_get((Z * Z * w[:, None]).sum()))
        mean_znorm = zz / max(wtot, 1e-30)
        lr = 1.0 / (1.0 / (C * max(wtot, 1e-30)) + 2.0 * mean_znorm + 1.0)
        beta, b = _svm_fit(Z, yy, w, jnp.float32(C),
                           steps, jnp.float32(lr))
        job.set_progress(1.0)
        model = PSVMModel(
            f"svm_{id(self) & 0xffffff:x}", self.params, spec,
            jax.device_get(beta), float(jax.device_get(b)),
            None if W is None else jax.device_get(W),
            None if phase is None else jax.device_get(phase),
            jax.device_get(xm), jax.device_get(xs), exp_names,
            {k_: float(jax.device_get(v)) for k_, v in means.items()})
        scores = model._predict_matrix(spec.X)
        model.training_metrics = compute_metrics(
            scores, spec.y, w, 2, spec.response_domain)
        nsv = int(jax.device_get(
            ((yy * (Z @ beta + b) < 1.0) & (w > 0)).sum()))
        model.output["svs_count"] = nsv   # margin violators ≈ SVs
        return model


register_model_class("psvm", PSVMModel)
