"""Model / ModelBuilder — the ML abstraction layer.

Reference: hex/ModelBuilder.java:25 (param validation, trainModel driver,
N-fold CV orchestration at :535-957) and hex/Model.java (Parameters/
Output, adaptTestForTrain categorical remap, BigScore bulk scoring
:1919-2176, per-row score0 contract :2304).

TPU re-design: the Driver/H2OCountedCompleter machinery collapses into a
plain call (optionally wrapped in a Job thread for REST); BigScore's
per-row score0 becomes one jitted batched predict over the sharded
feature matrix; adaptTestForTrain becomes domain remapping host-side when
building the test matrix.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.vec import T_ENUM, T_STR, Vec
from h2o3_tpu.jobs import Job
from h2o3_tpu import telemetry as _tel
from h2o3_tpu.models import metrics as metrics_mod


@dataclass
class TrainingSpec:
    """Resolved training inputs: dense device matrix + response/weights.

    The DataInfo analog (h2o-algos/.../hex/DataInfo.java:16) — but trees
    take enum codes directly (no one-hot); GLM/DL expand downstream."""
    X: Any                       # [padded, F] float32, NaN=NA (enum codes as floats)
    y: Any                       # [padded] float32 (reg) / int32 codes (classif)
    w: Any                       # [padded] float32 weights; 0 on pad/NA-response rows
    names: List[str]
    is_cat: List[bool]
    cat_domains: Dict[str, tuple]
    nrow: int
    response: str
    response_domain: Optional[tuple]
    nclasses: int                # 1 = regression
    offset: Any = None
    # memory-pressure mode (memman.fits_device said no): X stays on HOST
    # as float32 numpy and algorithms stream row chunks through training
    # (water/Cleaner.java graceful-degradation analog); X above is None
    X_host: Any = None
    stream: bool = False

    @property
    def n_features(self) -> int:
        return len(self.names)


def build_training_spec(frame: Frame, y: str, x: Optional[Sequence[str]] = None,
                        ignored_columns: Optional[Sequence[str]] = None,
                        weights_column: Optional[str] = None,
                        offset_column: Optional[str] = None,
                        classification: Optional[bool] = None) -> TrainingSpec:
    if y not in frame:
        raise ValueError(f"response column '{y}' not in frame {frame.names}")
    excluded = {y} | set(ignored_columns or ())
    if weights_column:
        excluded.add(weights_column)
    if offset_column:
        excluded.add(offset_column)
    names = list(x) if x else [n for n in frame.names if n not in excluded]
    names = [n for n in names if n != y and frame.vec(n).type != T_STR]
    rvec = frame.vec(y)
    if classification is None:
        classification = rvec.type == T_ENUM
    if classification and rvec.type != T_ENUM:
        # numeric response used as classification → derive domain
        # (Vec.asfactor: unique finite values → sorted domain, NaN → NA)
        rvec = rvec.asfactor()
    # memory pressure gate (water/MemoryManager.java allocation gate):
    # a design matrix beyond the device budget stays on HOST and the
    # algorithms stream row chunks (X_host/stream mode)
    from h2o3_tpu import memman
    mm = memman.manager()
    est_bytes = (frame.nrow + 256) * max(len(names), 1) * 4
    # account for what's already resident (the frame's own Vec payloads
    # count): as_matrix is a fresh copy ON TOP of them
    stream = not mm.fits_device(est_bytes + mm.stats()
                                ["device_resident_bytes"])
    if not stream:
        mm.request(est_bytes)    # spill LRU peers to make room
    if stream:
        X = None
        X_host = _host_matrix(frame, names)
        # y/w stay device vectors at the VEC padded length
        padded = int(rvec.data.shape[0])
    else:
        X = frame.as_matrix(names)
        X_host = None
        padded = X.shape[0]
    is_cat = [frame.vec(n).type == T_ENUM for n in names]
    cat_domains = {n: frame.vec(n).domain for n in names
                   if frame.vec(n).type == T_ENUM}
    nrow = frame.nrow
    row_ok = jnp.arange(padded) < nrow
    if classification:
        yd = rvec.data.astype(jnp.int32)
        resp_ok = yd >= 0
        y_dev = jnp.maximum(yd, 0)
        nclasses = rvec.cardinality
        response_domain = rvec.domain
    else:
        yf = rvec.as_float()
        resp_ok = ~jnp.isnan(yf)
        y_dev = jnp.where(resp_ok, yf, 0.0)
        nclasses = 1
        response_domain = None
    w = jnp.where(row_ok & resp_ok, 1.0, 0.0).astype(jnp.float32)
    if weights_column:
        wv = frame.vec(weights_column).as_float()
        w = w * jnp.where(jnp.isnan(wv), 0.0, wv)
    offset = None
    if offset_column:
        ov = frame.vec(offset_column).as_float()
        offset = jnp.where(jnp.isnan(ov), 0.0, ov)
    return TrainingSpec(X=X, y=y_dev, w=w, names=names, is_cat=is_cat,
                        cat_domains=cat_domains, nrow=nrow, response=y,
                        response_domain=response_domain, nclasses=nclasses,
                        offset=offset, X_host=X_host, stream=stream)


def build_parallelism(par: int) -> int:
    """Effective build-thread count for parallel CV/grid building.

    H2O3_MAX_BUILD_THREADS caps every build thread pool: on the
    virtual-device CPU test backend, many threads dispatching jitted
    train steps concurrently across oversubscribed xdist processes can
    abort() inside XLA — the suite pins the cap to 1 (conftest.py) and
    the dedicated concurrency tests raise it back. Unset/0 = no cap
    (TPU path: the device serializes dispatch, threads only overlap
    host orchestration + compiles)."""
    cap = int(os.environ.get("H2O3_MAX_BUILD_THREADS", "0") or 0)
    return min(par, cap) if cap > 0 else par


def _host_matrix(frame: Frame, names) -> np.ndarray:
    """Host-resident float32 design (as_matrix semantics: enum codes as
    floats, NA→NaN, string cols all-NaN) for streaming training."""
    nrow = frame.nrow
    out = np.empty((nrow, len(names)), np.float32)
    for j, n in enumerate(names):
        v = frame.vec(n)
        if v.type == T_STR:
            out[:, j] = np.nan
            continue
        a = v.to_numpy()
        if v.type == T_ENUM:
            a = np.where(np.asarray(a) < 0, np.nan,
                         np.asarray(a, np.float64))
        out[:, j] = np.asarray(a, np.float32)[:nrow]
    return out


def build_unsupervised_spec(frame: Frame, x: Optional[Sequence[str]] = None,
                            ignored_columns: Optional[Sequence[str]] = None,
                            weights_column: Optional[str] = None) -> TrainingSpec:
    """Spec for unsupervised builders (IsolationForest, KMeans, PCA…):
    no response column, y is a dummy zero vector."""
    excluded = set(ignored_columns or ())
    if weights_column:
        excluded.add(weights_column)
    names = list(x) if x else [n for n in frame.names if n not in excluded]
    names = [n for n in names if frame.vec(n).type != T_STR]
    X = frame.as_matrix(names)
    padded = X.shape[0]
    row_ok = jnp.arange(padded) < frame.nrow
    w = jnp.where(row_ok, 1.0, 0.0).astype(jnp.float32)
    if weights_column:
        wv = frame.vec(weights_column).as_float()
        w = w * jnp.where(jnp.isnan(wv), 0.0, wv)
    return TrainingSpec(
        X=X, y=jnp.zeros(padded, jnp.float32), w=w, names=names,
        is_cat=[frame.vec(n).type == T_ENUM for n in names],
        cat_domains={n: frame.vec(n).domain for n in names
                     if frame.vec(n).type == T_ENUM},
        nrow=frame.nrow, response=None, response_domain=None, nclasses=1)


def adapt_test_matrix(model: "Model", frame: Frame):
    """adaptTestForTrain (hex/Model.java): reorder columns to training
    order, remap enum codes through the training domain (unseen → NA),
    missing columns → all-NA."""
    return _adapt_matrix(frame, model.feature_names, model.feature_is_cat,
                         model.cat_domains)


def build_validation_spec(frame: Frame, train_spec: TrainingSpec,
                          weights_column=None, offset_column=None) -> TrainingSpec:
    """Validation/test spec ADAPTED to a training spec: columns in training
    order, enum codes remapped through the TRAINING domains (unseen → NA),
    response codes mapped through the training response domain. Building a
    fresh spec from the validation frame's own domains silently misroutes
    enum splits and class indices (adaptTestForTrain, hex/Model.java)."""
    X = _adapt_matrix(frame, train_spec.names, train_spec.is_cat,
                      train_spec.cat_domains)
    padded = X.shape[0]
    nrow = frame.nrow
    row_ok = np.arange(padded) < nrow
    if train_spec.response is None:
        return TrainingSpec(
            X=X, y=jnp.zeros(padded, jnp.float32),
            w=jnp.asarray(row_ok.astype(np.float32)),
            names=train_spec.names, is_cat=train_spec.is_cat,
            cat_domains=train_spec.cat_domains, nrow=nrow, response=None,
            response_domain=None, nclasses=1)
    if train_spec.nclasses > 1:
        codes, wr = response_codes_in_domain(frame, train_spec.response,
                                             train_spec.response_domain)
        y_dev = jnp.asarray(np.pad(codes, (0, padded - len(codes))))
        w = np.zeros(padded, np.float32)
        w[:nrow] = wr
    else:
        yf = np.asarray(_tel.device_get(
            frame.vec(train_spec.response).as_float(), pipeline="train"))
        resp_ok = np.isfinite(yf) & row_ok
        y_dev = jnp.asarray(np.where(resp_ok, yf, 0.0).astype(np.float32))
        w = resp_ok.astype(np.float32)
    if weights_column:
        if weights_column not in frame:
            raise ValueError(
                f"validation frame lacks weights_column '{weights_column}'")
        wv = np.asarray(_tel.device_get(
            frame.vec(weights_column).as_float(), pipeline="train"))
        w = w * np.where(np.isnan(wv), 0.0, wv)
    w = jnp.asarray(w)
    offset = None
    if offset_column:
        # an offset-trained model requires the offset at validation time —
        # silently dropping it would shift every margin (hex/Model.java
        # adaptTestForTrain raises)
        if offset_column not in frame:
            raise ValueError(
                f"validation frame lacks offset_column '{offset_column}'")
        ov = frame.vec(offset_column).as_float()
        offset = jnp.where(jnp.isnan(ov), 0.0, ov)
    return TrainingSpec(X=X, y=y_dev, w=w, names=train_spec.names,
                        is_cat=train_spec.is_cat,
                        cat_domains=train_spec.cat_domains, nrow=nrow,
                        response=train_spec.response,
                        response_domain=train_spec.response_domain,
                        nclasses=train_spec.nclasses, offset=offset)


def _adapt_matrix(frame: Frame, feature_names, feature_is_cat, cat_domains):
    cols = []
    padded = None
    for n, is_cat in zip(feature_names, feature_is_cat):
        if n not in frame:
            cols.append(None)
            continue
        v = frame.vec(n)
        if is_cat and v.type == T_ENUM:
            train_dom = cat_domains.get(n)
            if train_dom and v.domain != train_dom:
                lut = {lab: i for i, lab in enumerate(train_dom)}
                remap = np.array([lut.get(lab, -1) for lab in v.domain] + [-1],
                                 dtype=np.int32)
                codes = np.asarray(_tel.device_get(v.data, pipeline="score"))
                codes = remap[np.where(codes < 0, len(v.domain), codes)]
                v = Vec.from_numpy(codes[: v.nrow], vtype=T_ENUM, domain=train_dom)
        cols.append(v.as_float())
        padded = cols[-1].shape[0]
    if padded is None:
        raise ValueError("test frame shares no columns with the model")
    cols = [jnp.full(padded, jnp.nan, dtype=jnp.float32) if c is None else c
            for c in cols]
    return jnp.stack(cols, axis=1)


class ScoreKeeper:
    """Scoring history + convergence-based early stopping
    (hex/ScoreKeeper.java stopping_rounds/metric/tolerance semantics:
    stop when the moving average of the last k scores is no better than
    the previous k's by rel. tolerance)."""

    LESS_IS_BETTER = {"logloss", "mse", "rmse", "mae", "deviance",
                      "mean_per_class_error", "rmsle", "anomaly_score"}

    def __init__(self, stopping_rounds=0, stopping_metric="auto",
                 stopping_tolerance=1e-3, task="regression"):
        self.rounds = int(stopping_rounds or 0)
        metric = (stopping_metric or "auto").lower()
        if metric == "auto":
            metric = "logloss" if task in ("binomial", "multinomial") else "deviance"
        self.metric = metric
        self.tol = stopping_tolerance
        self.history: List[Dict] = []

    def record(self, entry: Dict):
        self.history.append(entry)

    def should_stop(self) -> bool:
        if self.rounds <= 0:
            return False
        k = self.rounds
        metric = self.metric
        if self.history and all(e.get(metric) is None for e in self.history):
            metric = "deviance"  # requested metric unavailable for this family
        scores = [e.get(metric) for e in self.history
                  if e.get(metric) is not None]
        if metric == "deviance" and self.metric != "deviance":
            return self._stop_on(scores, k, less_is_better=True)
        return self._stop_on(scores, k,
                             less_is_better=metric in self.LESS_IS_BETTER)

    def _stop_on(self, scores, k, less_is_better):
        if len(scores) < 2 * k:
            return False
        recent = np.mean(scores[-k:])
        prev = np.mean(scores[-2 * k:-k])
        # relative-improvement test with |prev| scaling — robust to metrics
        # that cross zero (the old sign trick inverted the band there)
        margin = self.tol * abs(prev)
        if less_is_better:
            return recent >= prev - margin
        return recent <= prev + margin


# reference param surfaces carrying the class-balancing trio and the
# calibration trio (h2o-py generated estimators; enforced by the
# bindings diff in tests/test_bindings.py) — merged as REAL defaults in
# ModelBuilder.__init__ since both features are implemented generically
_BALANCE_DEFAULTS = dict(balance_classes=False,
                         class_sampling_factors=None,
                         max_after_balance_size=5.0)
_CALIBRATION_DEFAULTS = dict(calibrate_model=False,
                             calibration_frame=None,
                             calibration_method="auto")
_BALANCE_ALGOS = {"gbm", "drf", "deeplearning", "glm", "gam", "anovaglm",
                  "infogram", "modelselection", "naivebayes", "upliftdrf"}
_CALIBRATION_ALGOS = {"gbm", "drf", "xgboost"}


class Model:
    """Trained artifact. Subclasses implement _predict_matrix(X)."""

    algo = "base"

    def __init__(self, key: str, params: Dict, spec: TrainingSpec):
        self.key = key
        self.params = dict(params)
        self.feature_names = list(spec.names)
        self.feature_is_cat = list(spec.is_cat)
        self.cat_domains = dict(spec.cat_domains)
        self.response = spec.response
        self.response_domain = spec.response_domain
        self.nclasses = spec.nclasses
        self.output: Dict[str, Any] = {}
        self.training_metrics = None
        self.validation_metrics = None
        self.cross_validation_metrics = None
        self.scoring_history: List[Dict] = []
        self.run_time: float = 0.0

    # -- scoring --------------------------------------------------------

    def _predict_matrix(self, X, offset=None):
        """Return margin/score array: [padded] for regression,
        [padded, K] class probabilities for classification."""
        raise NotImplementedError

    def _frame_offset(self, frame: Frame):
        """Offset vector for scoring. An offset-trained model requires the
        offset column at scoring time (adaptTestForTrain raises in the
        reference, hex/Model.java) — silently dropping it would shift every
        prediction."""
        oc = self.params.get("offset_column")
        if not oc:
            return None
        if oc not in frame:
            raise ValueError(
                f"model was trained with offset_column='{oc}' but the "
                f"scoring frame does not contain it")
        ov = frame.vec(oc).as_float()
        return jnp.where(jnp.isnan(ov), 0.0, ov)

    def _correct_probabilities(self, probs: np.ndarray) -> np.ndarray:
        """balance_classes probability un-correction (hex/Model
        correctProbabilities): p_k ∝ p̂_k · prior_k / model_dist_k, so
        a model trained on a rebalanced distribution reports
        probabilities calibrated to the ORIGINAL class priors."""
        prior_d = self.output.get("prior_class_dist")
        model_d = self.output.get("model_class_dist")
        if not prior_d or not model_d or probs.ndim != 2 \
                or probs.shape[1] != len(prior_d):
            return probs
        ratio = (np.asarray(prior_d, np.float64)
                 / np.maximum(np.asarray(model_d, np.float64), 1e-12))
        p = probs.astype(np.float64) * ratio[None, :]
        return (p / np.maximum(p.sum(axis=1, keepdims=True),
                               1e-12)).astype(probs.dtype)

    def predict(self, frame: Frame) -> Frame:
        """Bulk scoring → prediction Frame (BigScore analog). Output
        schema mirrors the reference: regression → 'predict'; classif →
        'predict' + one prob column per class."""
        X = adapt_test_matrix(self, frame)
        out = self._predict_matrix(X, offset=self._frame_offset(frame))
        nrow = frame.nrow
        if self.nclasses <= 1:
            pv = np.asarray(_tel.device_get(out, pipeline="score"))[:nrow]
            return Frame(["predict"], [Vec.from_numpy(pv)])
        probs = self._correct_probabilities(
            np.asarray(_tel.device_get(out, pipeline="score"))[:nrow])
        lbl = np.argmax(probs, axis=1).astype(np.int32)
        names = ["predict"] + [f"p{d}" for d in self.response_domain]
        vecs = [Vec.from_numpy(lbl, vtype=T_ENUM, domain=self.response_domain)]
        vecs += [Vec.from_numpy(probs[:, k]) for k in range(self.nclasses)]
        cal = self.output.get("calibration")
        if cal and self.nclasses == 2:
            # calibrated probability columns (CalibrationHelper
            # postProcessPredictions appends cal_p0/cal_p1)
            p1 = np.clip(probs[:, 1].astype(np.float64), 1e-12, 1 - 1e-12)
            if cal["method"] == "platt":
                q1 = 1.0 / (1.0 + np.exp(-(cal["a"] * np.log(
                    p1 / (1 - p1)) + cal["b"])))
            else:
                q1 = np.interp(p1, np.asarray(cal["tx"]),
                               np.asarray(cal["ty"]))
            names += [f"cal_p{self.response_domain[0]}",
                      f"cal_p{self.response_domain[1]}"]
            vecs += [Vec.from_numpy((1.0 - q1).astype(np.float32)),
                     Vec.from_numpy(q1.astype(np.float32))]
        return Frame(names, vecs)

    def deploy(self, **serve_config):
        """Register this model with the serving subsystem
        (h2o3_tpu.serve): pre-encodes the column/domain spec and warms
        compiled predict executables at the batch-size buckets, then
        rows score through the micro-batcher — see
        POST /3/Predictions/models/{key}/rows. Returns the Deployment."""
        from h2o3_tpu import serve
        return serve.deploy(self.key, model=self, **serve_config)

    def predict_rows(self, rows, timeout_ms=None):
        """Score a list of {column: value} dicts through the deployed
        micro-batching path. Deploys with defaults on first use; an
        EXISTING deployment under this key is reused as-is — replacing
        a live (possibly pinned, custom-configured) deployment
        mid-traffic is deploy()'s explicit job, not a scoring
        side-effect."""
        from h2o3_tpu import serve
        dep = serve.deployment(self.key) or self.deploy()
        return dep.predict_rows(rows, timeout_ms=timeout_ms)

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        X = adapt_test_matrix(self, frame)
        out = self._predict_matrix(X, offset=self._frame_offset(frame))
        nrow = frame.nrow
        if self.nclasses > 1:
            # remap the test response through the TRAINING domain — a fresh
            # spec would re-derive codes from the test frame's own label set
            # (adaptTestForTrain semantics, hex/Model.java)
            y, w = response_codes_in_domain(frame, self.response,
                                            self.response_domain)
            out_h = self._correct_probabilities(
                np.asarray(_tel.device_get(out, pipeline="score"))[:nrow])
            return compute_metrics(out_h, y, w, self.nclasses, self.response_domain)
        spec_like = build_training_spec(frame, self.response, classification=False)
        return compute_metrics(out, spec_like.y, spec_like.w, 1)

    # -- persistence hooks (persist.save_model/load_model) -------------

    def _save_arrays(self) -> Dict[str, np.ndarray]:
        """Per-algo tensors to persist (trees, coefficients, weights…)."""
        return {}

    def _save_extra_meta(self) -> Dict[str, Any]:
        """Per-algo JSON metadata to persist."""
        return {}

    @classmethod
    def _restore_base(cls, meta) -> "Model":
        """Rebuild the base Model state from artifact metadata (subclass
        _restore() fills algo-specific fields)."""
        m = cls.__new__(cls)
        m.key = meta["key"]
        m.params = dict(meta["params"] or {})
        m.feature_names = list(meta["feature_names"])
        m.feature_is_cat = list(meta["feature_is_cat"])
        m.cat_domains = {k: tuple(v) for k, v in
                         (meta.get("cat_domains") or {}).items()}
        m.response = meta["response"]
        rd = meta.get("response_domain")
        m.response_domain = tuple(rd) if rd else None
        m.nclasses = meta["nclasses"]
        m.output = dict(meta.get("output") or {})
        m.training_metrics = None
        m.validation_metrics = None
        m.cross_validation_metrics = None
        m.scoring_history = []
        m.run_time = 0.0
        return m

    @classmethod
    def _restore(cls, meta, arrays) -> "Model":
        raise NotImplementedError(f"{cls.__name__} does not support load yet")

    # -- convenience accessors (h2o-py parity) -------------------------

    def _metric(self, name, valid=False):
        m = self.validation_metrics if valid else self.training_metrics
        return getattr(m, name, None)

    def download_mojo(self, path: str = ".", get_genmodel_jar: bool = False):
        """Export as an h2o-genmodel-readable MOJO zip (tree models)."""
        import os
        from h2o3_tpu.mojo import export_mojo
        if os.path.isdir(path):
            path = os.path.join(path, f"{self.key}.zip")
        return export_mojo(self, path)

    def auc(self, valid=False):
        return self._metric("auc", valid)

    def logloss(self, valid=False):
        return self._metric("logloss", valid)

    def rmse(self, valid=False):
        return self._metric("rmse", valid)

    def mse(self, valid=False):
        return self._metric("mse", valid)

    def mae(self, valid=False):
        return self._metric("mae", valid)

    def r2(self, valid=False):
        return self._metric("r2", valid)

    def __repr__(self):
        return f"<{type(self).__name__} {self.key} {self.params.get('model_id', '')}>"


def persist_in_training_ckpt(model, algo: str, ckpt_dir,
                             final: bool = False) -> Optional[str]:
    """Persist an in-training checkpoint model to the DKV
    (``<key>_ckpt``) and to ``in_training_checkpoints_dir`` (one
    artifact per committed tree count — hex/tree/SharedTree's
    in_training_checkpoints_* contract). The caller attaches the
    algo-specific resume state (GBM: the f32 training margin; DRF: the
    OOB accumulators) before calling. ``final=True`` (a train that
    COMPLETED) keeps the durable disk artifact but drops the DKV entry
    — the finished model supersedes it, and leaving partial-model
    copies (with dataset-sized resume margins) to accumulate in the
    store would both leak memory and surface phantom models on
    GET /3/Models. Failures are logged, never fatal: a checkpoint
    write must not kill the train it protects."""
    import os as _os

    from h2o3_tpu import dkv, telemetry
    from h2o3_tpu.persist import save_model
    try:
        if final:
            dkv.remove(f"{model.key}_ckpt")
        else:
            dkv.put(f"{model.key}_ckpt", "model", model)
        path = None
        if ckpt_dir:
            _os.makedirs(ckpt_dir, exist_ok=True)
            path = save_model(
                model, ckpt_dir, force=True,
                filename=f"{model.key}_t{model.ntrees_built}.zip")
        telemetry.counter(
            "h2o3_ckpt_written_total", {"algo": algo},
            help="in-training checkpoints written").inc()
        return path
    except Exception as e:   # noqa: BLE001 — advisory only
        from h2o3_tpu.log import warn
        warn("%s: in-training checkpoint write failed: %s", algo, e)
        return None


def pack_impute_means(means) -> Dict[str, np.ndarray]:
    """npz-safe encoding of the {column: imputation mean} dict shared by
    the expanded-design models (GLM/DL/KMeans/PCA)."""
    return {"impute_keys": np.array(list(means.keys())),
            "impute_vals": np.array(list(means.values()), dtype=np.float64)}


def unpack_impute_means(arrays) -> Dict[str, float]:
    return {str(k): float(v) for k, v in
            zip(arrays["impute_keys"], arrays["impute_vals"])}


def response_codes_in_domain(frame: Frame, response: str, domain):
    """Test-frame response codes mapped through a training domain
    (labels unseen in training → NA/zero-weight)."""
    v = frame.vec(response)
    if v.type == T_ENUM:
        labels = v.to_strings()
    else:
        raw = v.to_numpy()
        labels = np.array([None if not np.isfinite(x)
                           else (str(int(x)) if x == int(x) else str(x))
                           for x in raw], dtype=object)
    lut = {lab: i for i, lab in enumerate(domain)}
    codes = np.array([lut.get(l, -1) if l is not None else -1 for l in labels],
                     dtype=np.int32)
    w = (codes >= 0).astype(np.float32)
    return np.maximum(codes, 0), w


def compute_metrics(scores, y, w, nclasses, response_domain=None,
                    deviance=None):
    """Dispatch to the right ModelMetrics maker, masking pad rows by w>0.

    The mask stays ON DEVICE: the old path device_get the full score
    matrix (80MB at 10M×2) just to drop pad rows before re-uploading it
    into the metric kernels — at bench scale that fetch dominated warm
    train time. When every row is live (the common padded==nrow case)
    the arrays pass through untouched; otherwise one device gather
    compacts them. Only kernel outputs (scalars / 2^17-bin curve
    summaries) ever cross to the host."""
    w_d = jnp.asarray(w)
    live = w_d > 0
    all_live = bool(live.all())
    scores_d = jnp.asarray(scores)
    y_d = jnp.asarray(y)
    if not all_live:
        idx = jnp.nonzero(live)[0]
        scores_d = jnp.take(scores_d, idx, axis=0)
        y_d = jnp.take(y_d, idx, axis=0)
        w_d = jnp.take(w_d, idx, axis=0)
    if nclasses <= 1:
        return metrics_mod.make_regression_metrics(
            scores_d, y_d, w_d, deviance=deviance)
    if nclasses == 2:
        return metrics_mod.make_binomial_metrics(scores_d[:, 1], y_d, w_d)
    return metrics_mod.make_multinomial_metrics(scores_d, y_d, w_d)


class ModelBuilder:
    """Base trainer with the reference's train/CV orchestration shape."""

    algo = "base"
    supervised = True
    model_count = 0
    # algos with a host-chunked memory-pressure path (spec.stream);
    # others fail fast with guidance instead of crashing on spec.X=None
    supports_streaming = False

    def __init__(self, **params):
        # reference-parity parameters this backend accepts but does not
        # act on (generated by tools/gen_python.py --wire): they keep the
        # generated-bindings/clients' full signatures working; train()
        # warns whenever one is set away from its reference default so
        # nothing is silently ignored
        try:
            from h2o3_tpu.models.compat_params import COMPAT_PARAMS
            compat = COMPAT_PARAMS.get(self.algo, {})
        except ImportError:
            compat = {}
        self._compat_defaults = compat
        merged = {k: v for k, v in compat.items() if k not in params}
        if self.algo in _BALANCE_ALGOS:
            for k, v in _BALANCE_DEFAULTS.items():
                merged.setdefault(k, v)
        if self.algo in _CALIBRATION_ALGOS:
            for k, v in _CALIBRATION_DEFAULTS.items():
                merged.setdefault(k, v)
        merged.update(params)
        self.params = merged
        self.model: Optional[Model] = None

    def _model_key(self) -> str:
        """Key the trained model will carry. ``model_id`` wins when set
        (the reference's Model key naming; the restart-recovery resume
        passes the interrupted train's original key through it so the
        resumed checkpoints land under the same artifact names);
        otherwise the per-builder default."""
        mid = self.params.get("model_id")
        return str(mid) if mid else f"{self.algo}_{id(self) & 0xffffff:x}"

    def _warn_compat_params(self):
        from h2o3_tpu.log import warn
        for k, dflt in self._compat_defaults.items():
            if self.params.get(k) != dflt:
                warn(f"{self.algo}: parameter '{k}' is accepted for "
                     f"reference API compatibility but NOT implemented — "
                     f"value {self.params[k]!r} has no effect")

    # per-algo: build a model from a spec
    def _train_impl(self, spec: TrainingSpec, valid_spec: Optional[TrainingSpec],
                    job: Job) -> Model:
        raise NotImplementedError

    def _validate_calibration(self, spec: TrainingSpec) -> None:
        """Pre-train parameter validation for calibrate_model — all
        checks depend only on params + spec, so a bad combination must
        not cost a full training run (the reference validates in
        ModelBuilder init)."""
        p = self.params
        if self.algo not in _CALIBRATION_ALGOS:
            raise ValueError(
                f"calibrate_model is not supported for {self.algo} "
                f"(hex/tree/CalibrationHelper covers GBM/DRF/XGBoost)")
        if p.get("calibration_frame") is None:
            raise ValueError(
                "calibrate_model requires a calibration_frame")
        if spec.nclasses != 2:
            raise ValueError("model calibration is only supported for "
                             "binomial classification")
        method = str(p.get("calibration_method") or "auto").lower()
        method = method.replace("_scaling", "").replace("scaling", "") \
                       .replace("_regression", "").replace("regression",
                                                           "")
        if method not in ("auto", "", "platt", "isotonic"):
            raise ValueError(
                f"unknown calibration_method "
                f"'{p.get('calibration_method')}' (one of AUTO, "
                f"PlattScaling, IsotonicRegression)")

    def validate_sample_rate_per_class(self, spec: TrainingSpec):
        """Shared GBM/DRF sample_rate_per_class validation
        (hex/tree/SharedTree.java:210-213): one rate per RESPONSE
        class. Returns the normalized tuple or None."""
        srpc = self.params.get("sample_rate_per_class")
        if srpc is None or not len(srpc):
            return None
        if spec.nclasses < 2:
            raise ValueError("sample_rate_per_class requires a "
                             "classification response")
        if len(srpc) != spec.nclasses:
            raise ValueError(
                f"sample_rate_per_class must have {spec.nclasses} "
                f"values (one per class), got {len(srpc)}")
        return tuple(float(v) for v in srpc)

    def _fit_calibration(self, model: "Model") -> None:
        """calibrate_model / calibration_frame / calibration_method
        (hex/tree/CalibrationHelper, used by GBM/DRF): fit Platt scaling
        (Platt 1999, 1-D logistic a·logit(p)+b by Newton) or isotonic
        regression (PAV) of the true labels on the model's predicted
        positive-class probability over the calibration frame; scoring
        then appends cal_p0/cal_p1 columns."""
        p = self.params
        if self.algo not in _CALIBRATION_ALGOS:
            raise ValueError(
                f"calibrate_model is not supported for {self.algo} "
                f"(hex/tree/CalibrationHelper covers GBM/DRF/XGBoost)")
        cf = p.get("calibration_frame")
        if cf is None:
            raise ValueError(
                "calibrate_model requires a calibration_frame")
        if isinstance(cf, str):
            from h2o3_tpu import dkv
            cf = dkv.get(cf, "frame")
        if model.nclasses != 2:
            raise ValueError("model calibration is only supported for "
                             "binomial classification")
        method = str(p.get("calibration_method") or "auto").lower()
        method = method.replace("_scaling", "").replace("scaling", "") \
                       .replace("_regression", "").replace("regression", "")
        if method in ("auto", ""):
            method = "platt"
        X = adapt_test_matrix(model, cf)
        out = model._predict_matrix(X, offset=model._frame_offset(cf))
        probs = model._correct_probabilities(
            np.asarray(_tel.device_get(out, pipeline="train"))[:cf.nrow])
        p1 = np.clip(probs[:, 1].astype(np.float64), 1e-12, 1 - 1e-12)
        yc, w = response_codes_in_domain(cf, model.response,
                                         model.response_domain)
        yv = np.asarray(yc, np.float64)
        wv = np.asarray(w, np.float64)
        if method == "platt":
            z = np.log(p1 / (1.0 - p1))
            a, b = 1.0, 0.0
            for _ in range(50):
                mu = 1.0 / (1.0 + np.exp(-(a * z + b)))
                s = np.maximum(mu * (1 - mu), 1e-12) * wv
                g = np.array([(wv * (yv - mu) * z).sum(),
                              (wv * (yv - mu)).sum()])
                H = np.array([[(s * z * z).sum(), (s * z).sum()],
                              [(s * z).sum(), s.sum()]])
                d = np.linalg.solve(H + 1e-9 * np.eye(2), g)
                a += d[0]
                b += d[1]
                if np.abs(d).max() < 1e-10:
                    break
            model.output["calibration"] = {"method": "platt",
                                           "a": float(a), "b": float(b)}
        elif method == "isotonic":
            from h2o3_tpu.models.isotonic import _pav
            ux, inv = np.unique(p1, return_inverse=True)
            awy = np.bincount(inv, weights=wv * yv)
            aw = np.bincount(inv, weights=wv)
            tx, ty = _pav(ux, awy, aw)
            model.output["calibration"] = {
                "method": "isotonic",
                "tx": [float(v) for v in tx],
                "ty": [float(v) for v in ty]}
        else:
            raise ValueError(
                f"unknown calibration_method "
                f"'{p.get('calibration_method')}' (one of AUTO, "
                f"PlattScaling, IsotonicRegression)")

    def _apply_balance_classes(self, spec: TrainingSpec) -> TrainingSpec:
        """balance_classes / class_sampling_factors /
        max_after_balance_size (hex/ModelBuilder ClassSamplingMethod +
        water/util/MRUtils.sampleFrameStratified): the reference
        physically re-samples rows; the TPU redesign multiplies class
        factors into the row WEIGHTS — identical in expectation for
        every weighted learner here (tree histograms, GLM IRLS, DL
        loss) with no data movement. The prior/model class
        distributions are recorded so scoring can correct predicted
        probabilities back to the prior (hex/Model correctProbabilities
        / _priorClassDist vs _modelClassDist)."""
        from dataclasses import replace as dc_replace
        self._class_dists = None
        if not self.params.get("balance_classes"):
            return spec
        if spec.nclasses < 2:
            return spec
        if self.algo == "upliftdrf":
            raise ValueError(
                "balance_classes is not supported for Uplift DRF "
                "(hex/tree/uplift/UpliftDRF.java rejects it)")
        if spec.stream:
            raise NotImplementedError(
                "balance_classes is not supported in streaming "
                "(memory-pressure) mode")
        K = spec.nclasses
        yc = jnp.clip(spec.y.astype(jnp.int32), 0, K - 1)
        w_eff = spec.w
        mvh = str(self.params.get("missing_values_handling")
                  or "").lower().replace("_", "")
        if mvh == "skip" and spec.X is not None:
            # Skip drops NA rows downstream (GLM _apply_mvh) — class
            # distributions must reflect the data actually trained on
            w_eff = spec.w * (~jnp.isnan(spec.X).any(axis=1))
        counts = jnp.zeros(K, jnp.float32).at[yc].add(w_eff)
        ch = np.asarray(_tel.device_get(counts, pipeline="train"),
                        np.float64)
        total = float(ch.sum())
        if total <= 0:
            return spec
        csf = self.params.get("class_sampling_factors")
        if csf is not None and len(csf):
            fac = np.asarray(csf, np.float64)
            if fac.shape[0] != K:
                raise ValueError(
                    f"class_sampling_factors needs {K} values (one per "
                    f"response class), got {fac.shape[0]}")
        else:
            # auto: uniform target — factor_k = total/(K·n_k)
            fac = total / (K * np.maximum(ch, 1.0))
        mabs = float(self.params.get("max_after_balance_size", 5.0)
                     or 5.0)
        new_total = float((ch * fac).sum())
        if new_total > mabs * total:
            fac *= mabs * total / new_total
            new_total = mabs * total
        w2 = spec.w * jnp.asarray(fac, jnp.float32)[yc]
        self._class_dists = (
            (ch / total).tolist(),
            ((ch * fac) / max(new_total, 1e-12)).tolist())
        return dc_replace(spec, w=w2)

    def train(self, x: Optional[Sequence[str]] = None, y: Optional[str] = None,
              training_frame: Optional[Frame] = None,
              validation_frame: Optional[Frame] = None,
              background: bool = False) -> "ModelBuilder":
        """Train via the cluster scheduler (h2o3_tpu.sched): the
        submission ENQUEUES (surfacing as QUEUED on /3/Jobs) and the
        whole build — spec construction and its device allocations
        included — runs only once admission releases it. Nested builds
        (CV folds, metalearners, calibration trains inside an admitted
        run) and the H2O3_SCHED=0 escape run the pre-scheduler inline/
        daemon-thread path: queueing a child while the parent blocks on
        it would deadlock the parent against its own admission."""
        y = y or self.params.get("response_column")
        training_frame = training_frame if training_frame is not None else \
            self.params.get("training_frame")
        if training_frame is None or (y is None and self.supervised):
            raise ValueError("train() needs training_frame"
                             + (" and y" if self.supervised else ""))
        from h2o3_tpu import sched
        # max_runtime_secs rides on the job so the supervision watchdog
        # (jobs.py) enforces it by cancellation — the chunk loops poll
        # cancel_requested and exit cooperatively. Queue wait does NOT
        # count: mark_dispatched restarts the clock.
        job = Job(f"{self.algo} training", work=1.0,
                  max_runtime_secs=float(
                      self.params.get("max_runtime_secs", 0) or 0))
        self.job = job
        # restart recovery (ISSUE 9): is_resuming() is thread-local to
        # the SUBMITTING thread — capture it before the body hops to a
        # scheduler worker
        self._resuming = False
        if os.environ.get("H2O3_RECOVERY_DIR"):
            from h2o3_tpu import recovery
            self._resuming = recovery.is_resuming()
        kwargs = dict(x=x, y=y, training_frame=training_frame,
                      validation_frame=validation_frame)
        if sched.enabled() and not sched.in_scheduled_run():
            try:
                # foreground submissions execute on THIS thread once
                # admission grants them (caller_runs): the caller blocks
                # anyway, and XLA compiles run measurably slower on
                # freshly-spawned worker threads
                entry = sched.scheduler().submit(
                    self, job, kwargs, caller_runs=not background)
            except (sched.SchedulerSaturatedError, ValueError) as e:
                # any submit rejection (queue cap, unknown priority):
                # the job never enters the queue — terminal-fail it so
                # /3/Jobs pollers and join()ers see the rejection
                # instead of a RUNNING zombie that is never evicted
                # (end clocks stamped — a terminal job's msec must not
                # keep growing)
                from h2o3_tpu.jobs import FAILED
                job.status = FAILED
                job._record_failure(e)
                job.end_time = time.time()
                job._end_mono = time.monotonic()
                job._done_evt.set()
                raise
            self._sched_entry = entry
            if not background:
                sched.scheduler().run_to_completion(entry)
                self.model = self._join_typed(job)
            return self
        # inline path (nested build or scheduler disabled)
        if self._resuming:
            from h2o3_tpu import jobs as jobs_mod
            job.status = jobs_mod.RECOVERING
        job.run(lambda j: self._run_build(j, **kwargs),
                background=background)
        if not background:
            self.model = self._join_typed(job)
        return self

    def _join_typed(self, job: Job):
        """Foreground-train result: parameter-validation failures (the
        spec phase — bad columns, unsupported modes) re-raise TYPED
        exactly as they did when the spec was built on the calling
        thread; training-phase failures keep join()'s RuntimeError
        wrapping."""
        from h2o3_tpu.jobs import FAILED
        if (job.status == FAILED and job.exception_obj is not None
                and getattr(job.exception_obj, "_h2o3_param_error",
                            False)):
            raise job.exception_obj
        return job.join()

    def _run_build(self, job: Job, x=None, y=None, training_frame=None,
                   validation_frame=None):
        """The whole build — spec (device allocation), train, CV,
        calibration — executed on the dispatching thread (a scheduler
        worker, the caller for inline foreground builds, or a daemon
        thread for inline background ones)."""
        from h2o3_tpu import telemetry
        from h2o3_tpu.log import Profile, info, timeline_record
        t0 = time.monotonic()
        if self._resuming:
            from h2o3_tpu import jobs as jobs_mod
            job.status = jobs_mod.RECOVERING
        # root span for the whole build; handed EXPLICITLY to the Profile
        # because this body may run on a worker thread (thread-local
        # nesting does not carry across threads)
        sp_root = telemetry.open_span(f"train.{self.algo}")
        prof = Profile(parent_span=sp_root)
        timeline_record("train_start", f"{self.algo}")
        self._warn_compat_params()
        try:
            with prof.phase("spec"):
                spec = self._make_spec(training_frame, y, x)
                spec = self._apply_balance_classes(spec)
                if self.params.get("calibrate_model"):
                    self._validate_calibration(spec)
                if getattr(spec, "stream", False) \
                        and not self.supports_streaming:
                    raise NotImplementedError(
                        f"{self.algo}: the training frame exceeds the "
                        f"device memory budget and this algorithm has no "
                        f"streaming (memory-pressure) path — raise "
                        f"H2O3_DEVICE_BUDGET_BYTES, reduce the frame, or "
                        f"use GBM/XGBoost/GLM which stream")
                valid_spec = None
                if validation_frame is not None:
                    # ADAPT the validation frame to the training spec
                    # (domain remap), not a fresh spec from its own
                    # domains
                    valid_spec = build_validation_spec(
                        validation_frame, spec,
                        weights_column=self.params.get("weights_column"),
                        offset_column=self.params.get("offset_column"))
        except Exception as e:
            # parameter/spec validation failed: tag so a foreground
            # train() re-raises it TYPED (pre-scheduler, this phase ran
            # on the calling thread and its ValueErrors were never
            # RuntimeError-wrapped)
            e._h2o3_param_error = True
            if sp_root is not None and sp_root.duration_s is None:
                sp_root.finish()
            raise
        # restart recovery (ISSUE 9): a checkpointing train records a
        # durable manifest so a killed PROCESS can rediscover and resume
        # it at the next boot; the env gate keeps the common path one
        # dict lookup (H2O3_TELEMETRY=0 idiom). A train resumed BY the
        # recovery scan surfaces as RECOVERING on /3/Jobs.
        rec_key = None
        if os.environ.get("H2O3_RECOVERY_DIR"):
            from h2o3_tpu import recovery
            if self.params.get("in_training_checkpoints_dir"):
                rec_key = recovery.record_training(self, job,
                                                   training_frame, y, spec)
        info("%s train start: %d rows, %d features", self.algo, spec.nrow,
             spec.n_features)

        def body(job):
            nfolds = int(self.params.get("nfolds", 0) or 0)
            fold_column = self.params.get("fold_column")
            par = build_parallelism(
                int(self.params.get("parallelism", 1) or 1))
            cv_fut = None
            # builders that override _cross_validate opt OUT of the
            # generic fold machinery (TargetEncoder: fold_column selects
            # ENCODING folds, not CV folds) — route through the override,
            # never _cv_fold_pass directly
            custom_cv = (type(self)._cross_validate
                         is not ModelBuilder._cross_validate)
            if (nfolds > 1 or fold_column) and par > 1 and not spec.stream \
                    and not custom_cv:
                # concurrent CV-main (hex/ModelBuilder.java:884
                # cv_buildModels + main build overlap): fold models start
                # on a worker pool while the main model trains here
                import concurrent.futures as cf
                cv_pool = cf.ThreadPoolExecutor(max_workers=1)
                cv_fut = cv_pool.submit(
                    self._cv_fold_pass, training_frame, y, x, spec, job,
                    nfolds, fold_column)
            try:
                with prof.phase("train"):
                    model = self._train_impl(spec, valid_spec, job)
                # PlugValues substitutions must follow the model to
                # scoring time: enum plugs via cat_plugs, numeric plugs
                # MERGED over the computed means so columns the user did
                # not plug keep real mean imputation
                if getattr(self, "_cat_plugs", None):
                    model.cat_plugs = dict(self._cat_plugs)
                if (getattr(self, "_plug_num", None)
                        and hasattr(model, "impute_means")):
                    model.impute_means = {**model.impute_means,
                                          **self._plug_num}
                if getattr(self, "_class_dists", None):
                    prior_d, model_d = self._class_dists
                    model.output["prior_class_dist"] = prior_d
                    model.output["model_class_dist"] = model_d
                if self.params.get("calibrate_model"):
                    self._fit_calibration(model)
            except BaseException:
                if cv_fut is not None:    # don't orphan the fold pass
                    cv_fut.cancel()
                    cv_pool.shutdown(wait=False, cancel_futures=True)
                raise
            model.run_time = time.monotonic() - t0
            # UDF metric (water/udf CMetricFunc analog): a callable
            # (pred, y, w) -> float evaluated on the training data
            cmf = self.params.get("custom_metric_func")
            # unsupervised specs carry a dummy zero y — a metric on it
            # would be meaningless (and wrappers may not even score)
            if callable(cmf) and spec.response is not None:
                pred, yh, wh = (np.asarray(v) for v in _tel.device_get(
                    (model._predict_matrix(spec.X), spec.y, spec.w),
                    pipeline="train"))
                live = wh > 0
                model.output["custom_metric"] = {
                    "name": getattr(cmf, "__name__", "custom"),
                    "value": float(cmf(pred[live], yh[live], wh[live]))}
            if nfolds > 1 or fold_column:
                with prof.phase("cv"):
                    if custom_cv:
                        self._cross_validate(model, training_frame, y, x,
                                             spec, job, nfolds,
                                             fold_column)
                    elif cv_fut is not None:
                        fold_pass = cv_fut.result()
                        cv_pool.shutdown()
                        self._attach_cv(model, training_frame, y, x,
                                        *fold_pass)
                    else:
                        fold_pass = self._cv_fold_pass(
                            training_frame, y, x, spec, job, nfolds,
                            fold_column)
                        self._attach_cv(model, training_frame, y, x,
                                        *fold_pass)
            model.output["profile"] = prof.to_dict()
            if rec_key is not None:
                # DELIBERATE completion (DONE or a cooperative cancel
                # that finalized a partial model): the manifest's job is
                # over — only a crash/kill leaves it for boot recovery
                from h2o3_tpu import recovery
                recovery.complete_training(rec_key)
            info("%s train done: %s", self.algo, prof.summary())
            timeline_record("train_done",
                            f"{self.algo} {prof.summary()}")
            if sp_root is not None:
                sp_root.attrs.update(rows=spec.nrow,
                                     features=spec.n_features)
                sp_root.finish()
            return model

        def body_spanned(j):
            try:
                return body(j)
            except BaseException as e:
                # a cooperative cancel that unwound before finalize is
                # still a DELIBERATE end — drop the recovery manifest
                # so the cancelled train does not auto-resume at the
                # next boot (crash/kill paths never reach this handler).
                # A PREEMPTION unwind is NOT terminal: the scheduler
                # requeues the entry, and a crash while it waits must
                # still find the manifest at the next boot
                if rec_key is not None:
                    from h2o3_tpu.jobs import JobCancelled, JobPreempted
                    if isinstance(e, JobCancelled) \
                            and not isinstance(e, JobPreempted):
                        from h2o3_tpu import recovery
                        recovery.complete_training(rec_key)
                raise
            finally:
                # failed/cancelled builds still close their root span
                if sp_root is not None and sp_root.duration_s is None:
                    sp_root.finish()

        return body_spanned(job)

    def _make_spec(self, frame, y, x):
        if not self.supervised:
            return build_unsupervised_spec(
                frame, x,
                ignored_columns=self.params.get("ignored_columns"),
                weights_column=self.params.get("weights_column"))
        classification = None
        dist = (self.params.get("distribution") or "").lower()
        if dist in ("bernoulli", "binomial", "multinomial"):
            classification = True
        elif dist and dist != "auto":
            classification = False
        return build_training_spec(
            frame, y, x,
            ignored_columns=self.params.get("ignored_columns"),
            weights_column=self.params.get("weights_column"),
            offset_column=self.params.get("offset_column"),
            classification=classification)

    def _cross_validate(self, model: Model, frame: Frame, y: str, x, spec,
                        job: Job, nfolds: int, fold_column: Optional[str]):
        """N-fold CV (hex/ModelBuilder.java:535-957): assign folds, train a
        model per fold on the complement, score the holdout, aggregate.
        Holdout predictions are kept for StackedEnsemble."""
        self._attach_cv(model, frame, y, x,
                        *self._cv_fold_pass(frame, y, x, spec, job, nfolds,
                                            fold_column))

    def _cv_fold_pass(self, frame: Frame, y: str, x, spec, job: Job,
                      nfolds: int, fold_column: Optional[str]):
        """Fold assignment + per-fold training/holdout scoring — the part
        that can overlap the MAIN model's build (concurrent CV-main).
        Returns (holdout, fold_models, fold, K)."""
        nrow = frame.nrow
        if fold_column:
            fold = frame.vec(fold_column).to_numpy().astype(int)
            fold_ids = np.unique(fold)
        else:
            assignment = (self.params.get("fold_assignment") or "auto").lower()
            seed = int(self.params.get("seed", -1) or -1)
            rng = np.random.default_rng(None if seed == -1 else seed)
            if assignment == "modulo":
                fold = np.arange(nrow) % nfolds
            else:
                fold = rng.integers(0, nfolds, size=nrow)
            fold_ids = np.arange(nfolds)
        K = spec.nclasses if spec.nclasses > 1 else 1
        holdout = np.full((nrow, K) if K > 1 else (nrow,), np.nan, dtype=np.float32)

        def one_fold(fid):
            # fold builds are NESTED: they ride the parent's scheduler
            # admission. The inline flag is thread-local, so a fold
            # running on a pool thread (parallel CV / concurrent
            # CV-main) must re-enter it explicitly — without this the
            # fold would ENQUEUE while the parent blocks holding its
            # grant, deadlocking under a tight budget
            from h2o3_tpu import sched
            with sched.inline_run():
                mask = fold == fid
                tr = frame.rows(~mask)
                te = frame.rows(mask)
                sub = type(self)(**{k: v for k, v in self.params.items()
                                    if k not in ("nfolds", "fold_column",
                                                 "parallelism")})
                sub.train(x=x, y=y, training_frame=tr)
                fm = sub.model
                X_te = adapt_test_matrix(fm, te)
                out = np.asarray(_tel.device_get(
                    fm._predict_matrix(X_te,
                                       offset=fm._frame_offset(te)),
                    pipeline="train"))[: te.nrow]
                return mask, out, fm

        par = build_parallelism(
            int(self.params.get("parallelism", 1) or 1))
        fold_models = []
        if par > 1:
            # CVModelBuilder parallel fold building (hex/CVModelBuilder,
            # ModelBuilderHelper.trainModelsParallel): threads overlap
            # host orchestration and XLA compiles (GIL released)
            import concurrent.futures as cf
            with cf.ThreadPoolExecutor(max_workers=par) as ex:
                futs = [ex.submit(one_fold, fid) for fid in fold_ids]
                for i, fu in enumerate(futs):
                    mask, out, fm = fu.result()
                    holdout[mask] = out
                    fold_models.append(fm)
                    job.set_progress(0.5 + 0.5 * (i + 1) / len(fold_ids))
        else:
            for i, fid in enumerate(fold_ids):
                mask, out, fm = one_fold(fid)
                holdout[mask] = out
                fold_models.append(fm)
                job.set_progress(0.5 + 0.5 * (i + 1) / len(fold_ids))
        return holdout, fold_models, fold, K

    def _attach_cv(self, model: Model, frame: Frame, y: str, x, holdout,
                   fold_models, fold, K):
        """Aggregate pooled-holdout CV metrics onto the main model."""
        nrow = frame.nrow
        cv_spec = build_training_spec(frame, y, x,
                                      classification=model.nclasses > 1)
        yh, wh = (np.asarray(v)[:nrow] for v in _tel.device_get(
            (cv_spec.y, cv_spec.w), pipeline="train"))
        ok = wh > 0
        if K > 1:
            model.cross_validation_metrics = (
                metrics_mod.make_binomial_metrics(holdout[ok, 1], yh[ok], wh[ok])
                if K == 2 else
                metrics_mod.make_multinomial_metrics(holdout[ok], yh[ok], wh[ok]))
        else:
            model.cross_validation_metrics = metrics_mod.make_regression_metrics(
                holdout[ok], yh[ok], wh[ok])
        model.output["cross_validation_holdout_predictions"] = holdout
        model.output["cross_validation_models"] = fold_models
        model.output["cv_fold_assignment"] = fold

    @staticmethod
    def nclasses_of(model: Model) -> int:
        return model.nclasses

    def __getattr__(self, item):
        # delegate metric accessors to the trained model (h2o-py style)
        if item.startswith("_") or self.__dict__.get("model") is None:
            raise AttributeError(item)
        return getattr(self.model, item)
