"""Aggregator — exemplar-based dataset reduction.

Reference: hex/aggregator/Aggregator.java:16 — single pass keeping a set
of exemplars: a row within sqrt(delta) of an existing exemplar is counted
into it, otherwise becomes a new exemplar; delta grows (and exemplars
re-merge) until the exemplar count approaches target_num_exemplars.

TPU re-design: the O(rows × exemplars) distance work is batched matmul
(|a-b|² = |a|²+|b|²-2a·b on the MXU) over row blocks; only the rare
"new exemplar" admissions run on host (bounded by target count, not rows).
The final counts pass is one full distance matmul + argmin."""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import expand_design
from h2o3_tpu.models.model_base import Model, ModelBuilder, TrainingSpec
from h2o3_tpu.persist import register_model_class

AGG_DEFAULTS: Dict = dict(
    target_num_exemplars=5000, rel_tol_num_exemplars=0.5,
    transform="normalize", seed=-1,
)


@jax.jit
def _block_dists(B, E):
    """Pairwise squared distances block[rows,F] × exemplars[M,F]."""
    bb = (B * B).sum(axis=1)[:, None]
    ee = (E * E).sum(axis=1)[None, :]
    return bb + ee - 2.0 * jax.lax.dot_general(
        B, E, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)


_PAD_COORD = 1.0e6  # standardized data is O(10); dist² to a pad row ≈ F·1e12


def _padded_dists(B, E_np):
    """_block_dists with the exemplar matrix padded to the next power of
    two so XLA compiles O(log target) kernels instead of one per admitted
    exemplar count. Pad rows sit at a far-away finite point; callers
    slice the result back to the real count."""
    k = E_np.shape[0]
    cap = 1 << max(0, (k - 1).bit_length())
    if cap > k:
        pad = np.full((cap - k, E_np.shape[1]), _PAD_COORD, E_np.dtype)
        E_np = np.concatenate([E_np, pad], axis=0)
    D = _block_dists(B, jnp.asarray(E_np))
    return D[:, :k]


class AggregatorModel(Model):
    algo = "aggregator"
    supervised = False

    def __init__(self, key, params, spec, exemplar_idx, counts):
        super().__init__(key, params, spec)
        self.exemplar_idx = np.asarray(exemplar_idx)   # row ids of exemplars
        self.counts = np.asarray(counts)

    def aggregated_frame(self, frame):
        """Exemplar rows of `frame` plus a counts column."""
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        sub = frame.rows(self.exemplar_idx)
        names = list(sub.names) + ["counts"]
        vecs = [sub.vec(n) for n in sub.names]
        vecs.append(Vec.from_numpy(self.counts.astype(np.float64)))
        return Frame(names, vecs)

    def _predict_matrix(self, X, offset=None):
        raise NotImplementedError("Aggregator does not score rows")

    def _save_arrays(self):
        return {"exemplar_idx": self.exemplar_idx, "counts": self.counts}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        m.exemplar_idx = arrays["exemplar_idx"]
        m.counts = arrays["counts"]
        return m


class H2OAggregatorEstimator(ModelBuilder):
    algo = "aggregator"
    supervised = False

    def __init__(self, **params):
        merged = dict(AGG_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        p = self.params
        target = int(p.get("target_num_exemplars", 5000))
        rel_tol = float(p.get("rel_tol_num_exemplars", 0.5))
        Xe, _, _ = expand_design(spec, use_all_levels=False)
        w = np.asarray(jax.device_get(spec.w))
        live = np.flatnonzero(w > 0)
        Xh = np.asarray(jax.device_get(Xe))[live].astype(np.float32)
        n, F = Xh.shape
        transform = (p.get("transform") or "normalize").lower()
        if transform != "none":
            mu = Xh.mean(axis=0)
            sd = Xh.std(axis=0)
            Xh = (Xh - mu) / np.maximum(sd, 1e-12)
        rng = np.random.default_rng(
            None if int(p.get("seed", -1) or -1) == -1
            else int(p["seed"]))
        order = rng.permutation(n)
        # delta: start from the radius that would tile the data's bounding
        # box into ~target cells (the reference seeds delta from dimension)
        span = float(np.maximum(Xh.max(0) - Xh.min(0), 1e-12).mean())
        delta = (span / max(target, 1) ** (1.0 / max(F, 1))) ** 2 * F
        block = 8192
        for _ in range(20):
            ex = []          # exemplar row positions (into order)
            Ed = None
            for s in range(0, n, block):
                idx = order[s: s + block]
                B = Xh[idx]
                if Ed is None:
                    mind = np.full(len(idx), np.inf, np.float32)
                else:
                    D = np.asarray(jax.device_get(_padded_dists(
                        jnp.asarray(B), Ed)))
                    mind = D.min(axis=1)
                far = np.flatnonzero(mind > delta)
                # greedy within-block admission among far rows: the matmul
                # pass vetted them against pre-block exemplars; check each
                # candidate only against this block's own admissions
                new_rows = []
                for j in far:
                    xb = B[j]
                    if new_rows:
                        d2 = ((B[new_rows] - xb) ** 2).sum(axis=1)
                        if d2.min() <= delta:
                            continue
                    new_rows.append(j)
                    ex.append(int(idx[j]))
                Ed = Xh[np.asarray(ex, int)] if ex else None
                if ex and len(ex) > target * (1 + rel_tol):
                    break  # too many exemplars at this delta — grow it
            count = len(ex)
            if count <= target * (1 + rel_tol) and (
                    count >= target * (1 - rel_tol) or delta <= 1e-12
                    or count == n):
                break
            if count > target * (1 + rel_tol):
                delta *= 2.0
            else:
                delta *= 0.5
        ex_arr = np.asarray(ex, int)
        # final assignment pass: every row to its nearest exemplar
        E = Xh[ex_arr]
        counts = np.zeros(len(ex_arr), np.int64)
        for s in range(0, n, block):
            D = np.asarray(jax.device_get(_padded_dists(
                jnp.asarray(Xh[s: s + block]), E)))
            a = D.argmin(axis=1)
            np.add.at(counts, a, 1)
        job.set_progress(1.0)
        model = AggregatorModel(
            f"agg_{id(self) & 0xffffff:x}", self.params, spec,
            live[ex_arr], counts)
        model.output["num_exemplars"] = int(len(ex_arr))
        model.output["delta"] = float(delta)
        return model


register_model_class("aggregator", AggregatorModel)
