"""GLRM — generalized low-rank model A ≈ X·Y.

Reference: hex/glrm/GLRM.java:52 — alternating minimization with a
loss/regularizer zoo: the X update runs as an MRTask over rows, Y
updates on the driver; missing cells are simply excluded from the loss
(GLRM's headline use: imputation / compression of mixed frames).

TPU re-design: X [rows, k] is row-sharded with the frame, Y [k, Fe]
replicated; each alternating step is a masked dense matmul pair
(residual = mask·(XY − A); grad_X = r·Yᵀ, grad_Y = Xᵀ·r — both MXU
contractions with GSPMD psums over the row shards), followed by an
elementwise proximal map (quadratic / L1-shrink / non-negative
projection). The whole alternation runs inside one jitted lax.scan."""
from __future__ import annotations

import time
from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import expand_design, expand_scoring_matrix
from h2o3_tpu.models.model_base import (Model, ModelBuilder, TrainingSpec,
                                        pack_impute_means,
                                        unpack_impute_means)
from h2o3_tpu.persist import register_model_class

GLRM_DEFAULTS: Dict = dict(
    k=1, loss="quadratic", regularization_x="none",
    regularization_y="none", gamma_x=0.0, gamma_y=0.0,
    max_iterations=100, init="svd", transform="none", seed=-1,
)


def _prox(M, reg: str, step_gamma):
    """Elementwise/rowwise proximal maps for the reference's regularizer
    zoo (hex/glrm/GlrmRegularizer.java: None, Quadratic, L2, L1,
    NonNegative, OneSparse, UnitOneSparse, Simplex)."""
    if reg in ("quadratic", "l2"):
        return M / (1.0 + 2.0 * step_gamma)
    if reg == "l1":
        return jnp.sign(M) * jnp.maximum(jnp.abs(M) - step_gamma, 0.0)
    if reg in ("non_negative", "nonnegative"):
        return jnp.maximum(M, 0.0)
    if reg == "one_sparse":
        # projection onto 1-sparse vectors per row: keep the largest-
        # magnitude entry (GlrmRegularizer.OneSparse.project)
        amax = jnp.max(jnp.abs(M), axis=-1, keepdims=True)
        return jnp.where(jnp.abs(M) >= amax, M, 0.0)
    if reg == "unit_one_sparse":
        # 1-sparse with the surviving entry snapped to 1 (archetype
        # membership indicator — UnitOneSparse)
        amax = jnp.max(jnp.abs(M), axis=-1, keepdims=True)
        return jnp.where(jnp.abs(M) >= amax, 1.0, 0.0)
    if reg == "simplex":
        # Euclidean projection onto the probability simplex per row
        # (GlrmRegularizer.Simplex; Duchi et al. algorithm, vectorized)
        k = M.shape[-1]
        u = jnp.sort(M, axis=-1)[..., ::-1]
        css = jnp.cumsum(u, axis=-1) - 1.0
        idx = jnp.arange(1, k + 1)
        cond = u - css / idx > 0
        rho = jnp.sum(cond, axis=-1, keepdims=True)
        theta = jnp.take_along_axis(css, rho - 1, axis=-1) / rho
        return jnp.maximum(M - theta, 0.0)
    return M


@partial(jax.jit, static_argnames=("iters", "reg_x", "reg_y"))
def _alternate(A, mask, X0, Y0, gamma_x, gamma_y, iters: int,
               reg_x: str, reg_y: str):
    """Masked alternating proximal gradient; returns (X, Y, objective)."""

    def step(carry, _):
        X, Y = carry
        # X update: prox gradient with the EXACT per-row Lipschitz
        # constant λmax(YYᵀ) — a k×k eigh, cheap at any rank
        Ly = jnp.maximum(jnp.linalg.eigvalsh(Y @ Y.T)[-1], 1e-8)
        R = mask * (X @ Y - A)
        X = _prox(X - (R @ Y.T) / Ly, reg_x, gamma_x / Ly)
        # Y update: λmax(XᵀX)
        Lx = jnp.maximum(jnp.linalg.eigvalsh(X.T @ X)[-1], 1e-8)
        R = mask * (X @ Y - A)
        Y = _prox(Y - (X.T @ R) / Lx, reg_y, gamma_y / Lx)
        return (X, Y), None

    (X, Y), _ = jax.lax.scan(step, (X0, Y0), None, length=iters)
    R = mask * (X @ Y - A)
    obj = (R * R).sum()
    return X, Y, obj


class GLRMModel(Model):
    algo = "glrm"
    supervised = False

    def __init__(self, key, params, spec, Y, xm, xs, exp_names,
                 impute_means, objective):
        super().__init__(key, params, spec)
        self.archetypes_y = np.asarray(Y)        # [k, Fe]
        self._xm = np.asarray(xm)
        self._xs = np.asarray(xs)
        self.exp_names = list(exp_names)
        self.impute_means = dict(impute_means)
        self.objective = float(objective)
        self.use_all_levels = False

    def _solve_x(self, Xe, mask, iters: int = 30):
        """Project new rows onto the fixed archetypes (the reference's
        scoring-side X solve)."""
        k = self.archetypes_y.shape[0]
        Y = jnp.asarray(self.archetypes_y)
        X = jnp.zeros((Xe.shape[0], k), jnp.float32)
        p = self.params
        gx = jnp.float32(p.get("gamma_x", 0.0))
        reg_x = (p.get("regularization_x") or "none").lower()
        Ly = jnp.maximum(jnp.linalg.eigvalsh(Y @ Y.T)[-1], 1e-8)
        for _ in range(iters):
            R = mask * (X @ Y - Xe)
            X = _prox(X - (R @ Y.T) / Ly, reg_x, gx / Ly)
        return X

    def _scale(self, Xe):
        return (Xe - jnp.asarray(self._xm)[None]) / \
            jnp.asarray(self._xs)[None]

    def _expanded_mask(self, Xraw):
        """Observed-cell mask in expanded-column space, from the RAW
        feature matrix (expand_scoring_matrix mean-imputes NAs, so the
        mask must be derived before expansion or every hole would score
        as an observed mean)."""
        cols = []
        for i, (n, is_cat) in enumerate(zip(self.feature_names,
                                            self.feature_is_cat)):
            isna = jnp.isnan(Xraw[:, i])
            # EXACTLY expand_design's column count: card-1 indicators per
            # enum (0 for a single-level enum), 1 per numeric
            reps = (len(self.cat_domains.get(n, ())) - 1 if is_cat else 1)
            if reps > 0:
                cols.extend([~isna] * reps)
        return jnp.stack(cols, axis=1).astype(jnp.float32)

    def predict(self, frame):
        """Reconstruction of the input columns (reconstructed frame —
        'reconstruct_train' semantics)."""
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.models.model_base import adapt_test_matrix
        Xraw = adapt_test_matrix(self, frame)
        Xe = expand_scoring_matrix(self, Xraw)
        mask = self._expanded_mask(Xraw)
        Xs = jnp.nan_to_num(self._scale(Xe), nan=0.0) * mask
        X = self._solve_x(Xs, mask)
        recon = X @ jnp.asarray(self.archetypes_y)
        recon = recon * jnp.asarray(self._xs)[None] + \
            jnp.asarray(self._xm)[None]
        R = np.asarray(jax.device_get(recon))[: frame.nrow]
        names = [f"reconstr_{n}" for n in self.exp_names]
        return Frame(names, [Vec.from_numpy(R[:, i].astype(np.float32))
                             for i in range(R.shape[1])])

    def transform_frame(self, frame):
        """Row archetype weights X for new rows (x() factor output)."""
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.models.model_base import adapt_test_matrix
        Xraw = adapt_test_matrix(self, frame)
        Xe = expand_scoring_matrix(self, Xraw)
        mask = self._expanded_mask(Xraw)
        Xs = jnp.nan_to_num(self._scale(Xe), nan=0.0) * mask
        X = self._solve_x(Xs, mask)
        Xh = np.asarray(jax.device_get(X))[: frame.nrow]
        return Frame([f"Arch{i + 1}" for i in range(Xh.shape[1])],
                     [Vec.from_numpy(Xh[:, i].astype(np.float32))
                      for i in range(Xh.shape[1])])

    def _predict_matrix(self, X, offset=None):
        raise NotImplementedError("GLRM scores via predict(frame)")

    def _save_arrays(self):
        return {"Y": self.archetypes_y, "xm": self._xm, "xs": self._xs,
                **pack_impute_means(self.impute_means)}

    def _save_extra_meta(self):
        return {"exp_names": self.exp_names, "objective": self.objective}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        m.archetypes_y = arrays["Y"]
        m._xm = arrays["xm"]
        m._xs = arrays["xs"]
        m.exp_names = list(meta["extra"]["exp_names"])
        m.objective = meta["extra"]["objective"]
        m.impute_means = unpack_impute_means(arrays)
        m.use_all_levels = False
        return m


class H2OGeneralizedLowRankEstimator(ModelBuilder):
    algo = "glrm"
    supervised = False

    def __init__(self, **params):
        merged = dict(GLRM_DEFAULTS)
        merged.update(params)
        super().__init__(**merged)

    def _train_impl(self, spec: TrainingSpec, valid_spec, job: Job):
        p = self.params
        k = int(p.get("k", 1))
        # NA-preserving expansion: expand_design mean-imputes numerics,
        # but GLRM must EXCLUDE missing cells from the loss — rebuild
        # the NA mask from the raw spec
        Xe, exp_names, means = expand_design(spec)
        Fe = Xe.shape[1]
        k = min(k, Fe)
        w = spec.w
        live = (w > 0)
        # mask: per expanded column, NA where the source column was NA
        na_cols = []
        for i, (n, is_cat) in enumerate(zip(spec.names, spec.is_cat)):
            x = spec.X[:, i]
            if is_cat:
                card = len(spec.cat_domains.get(n, ())) or int(
                    jax.device_get(jnp.nanmax(jnp.where(
                        jnp.isnan(x), 0.0, x)))) + 1
                reps = card - 1   # expand_design emits card-1 indicators
            else:
                reps = 1
            if reps > 0:
                na_cols.extend([jnp.isnan(x)] * reps)
        na = jnp.stack(na_cols, axis=1)
        mask = ((~na) & live[:, None]).astype(jnp.float32)
        transform = (p.get("transform") or "none").lower()
        wsum = jnp.maximum((mask.sum(0)), 1e-12)
        if transform in ("standardize", "demean", "center"):
            xm = (Xe * mask).sum(0) / wsum
        else:
            xm = jnp.zeros(Fe, jnp.float32)
        if transform == "standardize":
            xv = (mask * (Xe - xm[None]) ** 2).sum(0) / wsum
            xs = jnp.sqrt(jnp.maximum(xv, 1e-12))
        else:
            xs = jnp.ones(Fe, jnp.float32)
        A = ((Xe - xm[None]) / xs[None]) * mask
        seed = int(p.get("seed", -1) or -1)
        key = jax.random.PRNGKey(seed if seed != -1
                                 else int(time.time() * 1e3) % (2 ** 31))
        init = (p.get("init") or "svd").lower()
        if init in ("svd", "power"):
            G = jax.lax.dot_general(A, A, (((0,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            vals, vecs = jnp.linalg.eigh(G)
            order = jnp.argsort(-vals)
            Y0 = vecs[:, order][:, :k].T * jnp.sqrt(
                jnp.maximum(vals[order][:k], 0.0))[:, None]
            X0 = jnp.zeros((A.shape[0], k), jnp.float32)
        else:
            k1, k2 = jax.random.split(key)
            Y0 = jax.random.normal(k1, (k, Fe)) * 0.1
            X0 = jax.random.normal(k2, (A.shape[0], k)) * 0.1
        iters = int(p.get("max_iterations", 100))
        X, Y, obj = _alternate(
            A, mask, X0, Y0, jnp.float32(p.get("gamma_x", 0.0)),
            jnp.float32(p.get("gamma_y", 0.0)), iters,
            (p.get("regularization_x") or "none").lower(),
            (p.get("regularization_y") or "none").lower())
        job.set_progress(1.0)
        model = GLRMModel(
            f"glrm_{id(self) & 0xffffff:x}", self.params, spec,
            jax.device_get(Y), jax.device_get(xm), jax.device_get(xs),
            exp_names, {k_: float(jax.device_get(v))
                        for k_, v in means.items()},
            float(jax.device_get(obj)))
        model.output["objective"] = model.objective
        model.output["archetypes"] = model.archetypes_y.tolist()
        model.output["iterations"] = iters
        return model


register_model_class("glrm", GLRMModel)
