"""ModelMetrics family — device-computed, host-materialised.

Reference: hex/ModelMetrics.java and subclasses (~30 classes), AUC via
hex/AUC2.java (400-bin threshold sketch), confusion matrices, gains/lift.
TPU design: metrics are one jitted pass over the (sharded) prediction and
actual arrays; AUC uses an exact full device sort instead of AUC2's
histogram approximation (a 10M-row sort is cheap on-chip, and exactness
makes golden tests tighter than the reference's).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------- regression

@jax.jit
def _regression_kernel(pred, actual, w):
    tot = w.sum()
    err = actual - pred
    mse = (w * err * err).sum() / tot
    mae = (w * jnp.abs(err)).sum() / tot
    both_pos = (actual >= 0) & (pred >= 0)
    sle = jnp.where(both_pos, (jnp.log1p(pred) - jnp.log1p(actual)) ** 2, 0.0)
    rmsle_ok = both_pos.all()
    rmsle = jnp.sqrt((w * sle).sum() / tot)
    mean_a = (w * actual).sum() / tot
    ss_tot = (w * (actual - mean_a) ** 2).sum()
    r2 = 1.0 - (w * err * err).sum() / jnp.maximum(ss_tot, 1e-30)
    return mse, mae, rmsle, rmsle_ok, r2, mean_a


@dataclass
class ModelMetricsRegression:
    mse: float
    rmse: float
    mae: float
    rmsle: float
    r2: float
    mean_residual_deviance: float
    nobs: int

    def to_dict(self) -> Dict:
        return {"MSE": self.mse, "RMSE": self.rmse, "mae": self.mae,
                "rmsle": self.rmsle, "r2": self.r2,
                "mean_residual_deviance": self.mean_residual_deviance,
                "nobs": self.nobs}


def make_regression_metrics(pred, actual, weights=None, deviance=None) -> ModelMetricsRegression:
    pred = jnp.asarray(pred, dtype=jnp.float32)
    actual = jnp.asarray(actual, dtype=jnp.float32)
    w = jnp.ones_like(actual) if weights is None else jnp.asarray(weights, jnp.float32)
    mse, mae, rmsle, rmsle_ok, r2, _ = [np.asarray(v) for v in
                                        _regression_kernel(pred, actual, w)]
    mse = float(mse)
    return ModelMetricsRegression(
        mse=mse, rmse=float(np.sqrt(mse)), mae=float(mae),
        rmsle=float(rmsle) if bool(rmsle_ok) else float("nan"), r2=float(r2),
        mean_residual_deviance=float(deviance) if deviance is not None else mse,
        nobs=int(pred.shape[0]))


# ------------------------------------------------------------------ binomial

@jax.jit
def _binary_curve_kernel(score, y, w):
    """Sorted threshold sweep → cumulative TP/FP at unique-score boundaries.

    Exact AUC semantics under ties: per-score-group aggregation (the chord
    rule), matching sklearn's roc_auc and the reference's intent (AUC2
    approximates with 400 bins; we are exact)."""
    order = jnp.argsort(-score)
    s = score[order]
    yw = (w * y)[order]
    nw = (w * (1.0 - y))[order]
    tp = jnp.cumsum(yw)
    fp = jnp.cumsum(nw)
    # group boundary = last element of a run of equal scores
    is_boundary = jnp.concatenate([s[1:] != s[:-1], jnp.array([True])])
    P = tp[-1]
    N = fp[-1]
    # trapezoid between consecutive boundaries (chord rule over tied runs):
    # for each boundary, find the previous boundary via a prefix-max scan
    idx = jnp.arange(s.shape[0])
    idxf = jnp.where(is_boundary, idx, -1)
    # prefix max via the cummax primitive: associative_scan traces an
    # unrolled log-depth slice tree whose XLA compile takes minutes at
    # 10M elements (the r3 "hung bench" root cause)
    prevb = jax.lax.cummax(idxf)                                  # last boundary ≤ i
    prevb = jnp.concatenate([jnp.array([-1]), prevb[:-1]])        # last boundary < i
    has_prev = prevb >= 0
    tp_prev = jnp.where(has_prev, tp[prevb], 0.0)
    fp_prev = jnp.where(has_prev, fp[prevb], 0.0)
    seg = jnp.where(is_boundary, (fp - fp_prev) * (tp + tp_prev) * 0.5, 0.0)
    auc = seg.sum() / jnp.maximum(P * N, 1e-30)
    # PR curve: step-wise interpolation on the recall axis at boundaries
    prec = tp / jnp.maximum(tp + fp, 1e-30)
    rec = tp / jnp.maximum(P, 1e-30)
    rec_prev = tp_prev / jnp.maximum(P, 1e-30)
    aucpr = jnp.where(is_boundary, (rec - rec_prev) * prec, 0.0).sum()
    return order, tp, fp, is_boundary, auc, aucpr, P, N


@jax.jit
def _logloss_kernel(p, y, w):
    eps = 1e-7  # f32-safe: 1-1e-15 rounds to 1.0f -> log1p(-1) = -inf
    p = jnp.clip(p, eps, 1.0 - eps)
    ll = -(w * (y * jnp.log(p) + (1.0 - y) * jnp.log1p(-p))).sum() / w.sum()
    return ll


@dataclass
class ModelMetricsBinomial:
    auc: float
    aucpr: float
    logloss: float
    mse: float
    rmse: float
    gini: float
    mean_per_class_error: float
    r2: float
    f1_threshold: float
    max_f1: float
    confusion_matrix: np.ndarray  # [[tn, fp], [fn, tp]] at max-F1 threshold
    accuracy: float
    nobs: int
    thresholds_and_metric_scores: Optional[dict] = None

    def to_dict(self) -> Dict:
        return {"AUC": self.auc, "pr_auc": self.aucpr, "logloss": self.logloss,
                "MSE": self.mse, "RMSE": self.rmse, "Gini": self.gini,
                "mean_per_class_error": self.mean_per_class_error, "r2": self.r2,
                "max_f1": self.max_f1, "f1_threshold": self.f1_threshold,
                "cm": self.confusion_matrix.tolist(), "accuracy": self.accuracy,
                "nobs": self.nobs}


def make_binomial_metrics(prob, actual, weights=None) -> ModelMetricsBinomial:
    """prob = P(class 1); actual ∈ {0,1}."""
    prob = jnp.asarray(prob, dtype=jnp.float32)
    y = jnp.asarray(actual, dtype=jnp.float32)
    w = jnp.ones_like(y) if weights is None else jnp.asarray(weights, jnp.float32)
    order, tp, fp, is_b, auc, aucpr, P, N = _binary_curve_kernel(prob, y, w)
    auc = float(np.asarray(auc))
    aucpr = float(np.asarray(aucpr))
    ll = float(np.asarray(_logloss_kernel(prob, y, w)))
    reg = _regression_kernel(prob, y, w)
    mse = float(np.asarray(reg[0]))
    r2 = float(np.asarray(reg[4]))
    # host: max-F1 threshold + confusion matrix there
    tp_h = np.asarray(tp); fp_h = np.asarray(fp); isb_h = np.asarray(is_b)
    s_h = np.asarray(prob)[np.asarray(order)]
    Pf = float(np.asarray(P)); Nf = float(np.asarray(N))
    tpb = tp_h[isb_h]; fpb = fp_h[isb_h]; sb = s_h[isb_h]
    fnb = Pf - tpb; tnb = Nf - fpb
    prec = tpb / np.maximum(tpb + fpb, 1e-30)
    rec = tpb / max(Pf, 1e-30)
    f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-30)
    bi = int(np.argmax(f1))
    cm = np.array([[tnb[bi], fpb[bi]], [fnb[bi], tpb[bi]]])
    per_class_err = 0.5 * (fpb[bi] / max(Nf, 1e-30) + fnb[bi] / max(Pf, 1e-30))
    acc = (tpb[bi] + tnb[bi]) / max(Pf + Nf, 1e-30)
    return ModelMetricsBinomial(
        auc=auc, aucpr=aucpr, logloss=ll, mse=mse, rmse=float(np.sqrt(mse)),
        gini=2 * auc - 1, mean_per_class_error=float(per_class_err), r2=r2,
        f1_threshold=float(sb[bi]), max_f1=float(f1[bi]), confusion_matrix=cm,
        accuracy=float(acc), nobs=int(prob.shape[0]))


# --------------------------------------------------------------- multinomial

@jax.jit
def _multinomial_kernel(probs, y, w):
    eps = 1e-7  # f32-safe: 1-1e-15 rounds to 1.0f -> log1p(-1) = -inf
    rows = probs.shape[0]
    py = probs[jnp.arange(rows), y]
    ll = -(w * jnp.log(jnp.clip(py, eps, 1.0))).sum() / w.sum()
    pred = jnp.argmax(probs, axis=1)
    err = (w * (pred != y)).sum() / w.sum()
    K = probs.shape[1]
    cm = jnp.zeros((K, K), dtype=jnp.float32).at[y, pred].add(w)
    return ll, err, cm, pred


@dataclass
class ModelMetricsMultinomial:
    logloss: float
    mse: float
    rmse: float
    mean_per_class_error: float
    error: float
    confusion_matrix: np.ndarray
    hit_ratios: np.ndarray
    nobs: int

    def to_dict(self) -> Dict:
        return {"logloss": self.logloss, "MSE": self.mse, "RMSE": self.rmse,
                "mean_per_class_error": self.mean_per_class_error,
                "error": self.error, "cm": self.confusion_matrix.tolist(),
                "hit_ratios": self.hit_ratios.tolist(), "nobs": self.nobs}


def make_multinomial_metrics(probs, actual, weights=None) -> ModelMetricsMultinomial:
    probs = jnp.asarray(probs, dtype=jnp.float32)
    y = jnp.asarray(actual, dtype=jnp.int32)
    w = (jnp.ones(probs.shape[0], jnp.float32) if weights is None
         else jnp.asarray(weights, jnp.float32))
    ll, err, cm, _ = _multinomial_kernel(probs, y, w)
    cm = np.asarray(cm)
    K = cm.shape[0]
    row_tot = cm.sum(axis=1)
    per_class = np.where(row_tot > 0, 1.0 - np.diag(cm) / np.maximum(row_tot, 1e-30), 0.0)
    present = row_tot > 0
    mpce = float(per_class[present].mean()) if present.any() else 0.0
    # MSE on 1-vs-all probabilities (reference semantics: 1 - p_actual)
    rows = probs.shape[0]
    py = np.asarray(probs)[np.arange(rows), np.asarray(y)]
    wh = np.asarray(w)
    mse = float((wh * (1.0 - py) ** 2).sum() / wh.sum())
    # hit ratio @k
    ranks = np.asarray(jnp.argsort(-probs, axis=1))
    hits = ranks == np.asarray(y)[:, None]
    hr = np.cumsum(hits.mean(axis=0))[: min(K, 10)]
    return ModelMetricsMultinomial(
        logloss=float(np.asarray(ll)), mse=mse, rmse=float(np.sqrt(mse)),
        mean_per_class_error=mpce, error=float(np.asarray(err)),
        confusion_matrix=cm, hit_ratios=hr, nobs=int(probs.shape[0]))
