"""ModelMetrics family — device-computed, host-materialised.

Reference: hex/ModelMetrics.java and subclasses (~30 classes), AUC via
hex/AUC2.java (400-bin threshold sketch), confusion matrices, gains/lift.
TPU design: metrics are one jitted pass over the (sharded) prediction and
actual arrays. The AUC curve is EXACT (device sort + host chord rule)
up to _EXACT_SWEEP_ROWS rows; above that it switches to an
order-preserving 2^17-bucket histogram sketch — 300x finer than AUC2's
400 bins but no longer bit-exact (golden tests at large n should allow
~1e-4 AUC tolerance).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------- regression

@jax.jit
def _regression_kernel(pred, actual, w):
    tot = w.sum()
    err = actual - pred
    mse = (w * err * err).sum() / tot
    mae = (w * jnp.abs(err)).sum() / tot
    both_pos = (actual >= 0) & (pred >= 0)
    sle = jnp.where(both_pos, (jnp.log1p(pred) - jnp.log1p(actual)) ** 2, 0.0)
    rmsle_ok = both_pos.all()
    rmsle = jnp.sqrt((w * sle).sum() / tot)
    mean_a = (w * actual).sum() / tot
    ss_tot = (w * (actual - mean_a) ** 2).sum()
    r2 = 1.0 - (w * err * err).sum() / jnp.maximum(ss_tot, 1e-30)
    return mse, mae, rmsle, rmsle_ok, r2, mean_a


@dataclass
class ModelMetricsHGLMGaussianGaussian:
    """HGLM gaussian/gaussian metrics — field-for-field analog of
    hex/ModelMetricsHGLMGaussianGaussian.java (sefe/sere per-coefficient
    standard errors, varfix/varranef dispersion components, the
    h-likelihood family hlik/pvh/pbvh and conditional AIC, plus the
    Σ(ηᵢ−η₀)²/Σηᵢ² convergence ratio of GLM.java:569)."""
    fixef: list
    ranef: list
    sefe: list
    sere: list
    varfix: float
    varranef: list
    hlik: float
    pvh: float
    pbvh: float
    caic: float
    dfrefe: float
    converge: bool
    convergence: float
    iterations: int
    mse: float
    nobs: int

    def to_dict(self) -> Dict:
        return {"fixef": self.fixef, "ranef": self.ranef,
                "sefe": self.sefe, "sere": self.sere,
                "varfix": self.varfix, "varranef": self.varranef,
                "hlik": self.hlik, "pvh": self.pvh, "pbvh": self.pbvh,
                "caic": self.caic, "dfrefe": self.dfrefe,
                "converge": self.converge,
                "convergence": self.convergence,
                "iterations": self.iterations,
                "MSE": self.mse, "nobs": self.nobs}


@dataclass
class ModelMetricsRegression:
    mse: float
    rmse: float
    mae: float
    rmsle: float
    r2: float
    mean_residual_deviance: float
    nobs: int

    def to_dict(self) -> Dict:
        return {"MSE": self.mse, "RMSE": self.rmse, "mae": self.mae,
                "rmsle": self.rmsle, "r2": self.r2,
                "mean_residual_deviance": self.mean_residual_deviance,
                "nobs": self.nobs}


def make_regression_metrics(pred, actual, weights=None, deviance=None) -> ModelMetricsRegression:
    pred = jnp.asarray(pred, dtype=jnp.float32)
    actual = jnp.asarray(actual, dtype=jnp.float32)
    w = jnp.ones_like(actual) if weights is None else jnp.asarray(weights, jnp.float32)
    mse, mae, rmsle, rmsle_ok, r2, _ = [np.asarray(v) for v in
                                        _regression_kernel(pred, actual, w)]
    mse = float(mse)
    return ModelMetricsRegression(
        mse=mse, rmse=float(np.sqrt(mse)), mae=float(mae),
        rmsle=float(rmsle) if bool(rmsle_ok) else float("nan"), r2=float(r2),
        mean_residual_deviance=float(deviance) if deviance is not None else mse,
        nobs=int(pred.shape[0]))


# ------------------------------------------------------------------ binomial

@jax.jit
def _sorted_sweep_kernel(score, y, w):
    """Device sort + cumulative TP/FP (small-n exact path). Boundary and
    chord-rule logic runs host-side in numpy: every scan-flavoured XLA
    primitive tried here (associative_scan, cummax, searchsorted) costs
    minutes of COMPILE time at 10M elements, while argsort+cumsum
    compile in ~2s — so the device does only those two."""
    order = jnp.argsort(-score)
    s = score[order]
    tp = jnp.cumsum((w * y)[order])
    fp = jnp.cumsum((w * (1.0 - y))[order])
    return s, tp, fp


_AUC_BIN_BITS = 17
_AUC_BINS = 1 << _AUC_BIN_BITS

# above this row count the curve switches from the exact sorted sweep to
# the 2^17-bucket histogram sketch (no O(n) host transfer either way)
_EXACT_SWEEP_ROWS = 200_000


@jax.jit
def _binned_curve_kernel(score, y, w):
    """Large-n curve summary: order-preserving float32-bit bucketisation
    into 2^17 bins + scatter-add histograms (the AUC2 sketch idea,
    hex/AUC2.java's 400 bins, at 300× finer resolution). Only scatter,
    elementwise bit math, and a 2^17 cumsum — everything compiles fast
    and nothing O(n) ever reaches the host."""
    s32 = score.astype(jnp.float32)
    bits = jax.lax.bitcast_convert_type(s32, jnp.uint32)
    # standard float radix trick: flip all bits for negatives, set the
    # sign bit for positives → unsigned keys in score order
    key = jnp.where((bits >> 31) == 1, ~bits,
                    bits | jnp.uint32(0x80000000))
    b = (key >> (32 - _AUC_BIN_BITS)).astype(jnp.int32)
    hp = jnp.zeros(_AUC_BINS, jnp.float32).at[b].add(w * y)
    hn = jnp.zeros(_AUC_BINS, jnp.float32).at[b].add(w * (1.0 - y))
    smax = jnp.full(_AUC_BINS, -jnp.inf, jnp.float32).at[b].max(s32)
    return hp, hn, smax


@jax.jit
def auc_device(score, y, w):
    """Scalar AUC entirely on device (the 2^17-bucket sketch + chord
    rule; empty buckets contribute zero-width chords so no occupancy
    filtering is needed). Used by the training loop's per-interval
    scoring so only ONE scalar crosses to the host — the previous
    interval-AUC path imported a kernel that no longer existed."""
    hp, hn, _ = _binned_curve_kernel(score, y, w)
    tp = jnp.cumsum(hp[::-1])
    fp = jnp.cumsum(hn[::-1])
    P, N = tp[-1], fp[-1]
    tp_prev = jnp.concatenate([jnp.zeros(1, tp.dtype), tp[:-1]])
    fp_prev = jnp.concatenate([jnp.zeros(1, fp.dtype), fp[:-1]])
    return ((fp - fp_prev) * (tp + tp_prev)).sum() * 0.5 \
        / jnp.maximum(P * N, 1e-30)


def _binary_curve(prob, y, w):
    """(sb, tpb, fpb, P, N, auc, aucpr): score thresholds (descending)
    with cumulative weighted TP/FP at tie-run boundaries, plus the
    chord-rule AUC and step-interpolated PR AUC. Exact for small n;
    quantised to 2^17 order-preserving buckets above _EXACT_SWEEP_ROWS."""
    n = int(prob.shape[0])
    if n <= _EXACT_SWEEP_ROWS:
        s, tp, fp = (np.asarray(v) for v in
                     _sorted_sweep_kernel(prob, y, w))
        is_b = np.concatenate([s[1:] != s[:-1], [True]])
        sb, tpb, fpb = s[is_b], tp[is_b], fp[is_b]
    else:
        hp, hn, smax = (np.asarray(v) for v in
                        _binned_curve_kernel(prob, y, w))
        occ = np.isfinite(smax) & ((hp > 0) | (hn > 0))
        # descending score order
        sb = smax[occ][::-1]
        tpb = np.cumsum(hp[occ][::-1])
        fpb = np.cumsum(hn[occ][::-1])
    P = float(tpb[-1]) if len(tpb) else 0.0
    N = float(fpb[-1]) if len(fpb) else 0.0
    tp_prev = np.concatenate([[0.0], tpb[:-1]])
    fp_prev = np.concatenate([[0.0], fpb[:-1]])
    auc = float(((fpb - fp_prev) * (tpb + tp_prev)).sum()
                * 0.5 / max(P * N, 1e-30))
    prec = tpb / np.maximum(tpb + fpb, 1e-30)
    rec = tpb / max(P, 1e-30)
    rec_prev = tp_prev / max(P, 1e-30)
    aucpr = float(((rec - rec_prev) * prec).sum())
    return sb, tpb, fpb, P, N, auc, aucpr


@jax.jit
def _logloss_kernel(p, y, w):
    eps = 1e-7  # f32-safe: 1-1e-15 rounds to 1.0f -> log1p(-1) = -inf
    p = jnp.clip(p, eps, 1.0 - eps)
    ll = -(w * (y * jnp.log(p) + (1.0 - y) * jnp.log1p(-p))).sum() / w.sum()
    return ll


@dataclass
class ModelMetricsBinomial:
    auc: float
    aucpr: float
    logloss: float
    mse: float
    rmse: float
    gini: float
    mean_per_class_error: float
    r2: float
    f1_threshold: float
    max_f1: float
    confusion_matrix: np.ndarray  # [[tn, fp], [fn, tp]] at max-F1 threshold
    accuracy: float
    nobs: int
    thresholds_and_metric_scores: Optional[dict] = None

    def to_dict(self) -> Dict:
        return {"AUC": self.auc, "pr_auc": self.aucpr, "logloss": self.logloss,
                "MSE": self.mse, "RMSE": self.rmse, "Gini": self.gini,
                "mean_per_class_error": self.mean_per_class_error, "r2": self.r2,
                "max_f1": self.max_f1, "f1_threshold": self.f1_threshold,
                "cm": self.confusion_matrix.tolist(), "accuracy": self.accuracy,
                "nobs": self.nobs}


def _threshold_columns(thr, tp, fp, P, N):
    """Per-threshold metric columns (hex/AUC2.java ThresholdCriterion set).

    tp/fp are cumulative weighted counts predicting positive at score >= thr."""
    fn = P - tp
    tn = N - fp
    tot = max(P + N, 1e-30)
    precision = tp / np.maximum(tp + fp, 1e-30)
    recall = tp / max(P, 1e-30)                       # tpr
    specificity = tn / max(N, 1e-30)                  # tnr
    fpr = fp / max(N, 1e-30)
    fnr = fn / max(P, 1e-30)
    accuracy = (tp + tn) / tot
    f1 = 2 * precision * recall / np.maximum(precision + recall, 1e-30)
    f2 = 5 * precision * recall / np.maximum(4 * precision + recall, 1e-30)
    f0point5 = (1.25 * precision * recall
                / np.maximum(0.25 * precision + recall, 1e-30))
    mcc_den = np.sqrt(np.maximum(
        (tp + fp) * (tp + fn) * (tn + fp) * (tn + fn), 1e-30))
    mcc = (tp * tn - fp * fn) / mcc_den
    min_pca = np.minimum(recall, specificity)
    mean_pca = 0.5 * (recall + specificity)
    return {
        "threshold": thr, "f1": f1, "f2": f2, "f0point5": f0point5,
        "accuracy": accuracy, "precision": precision, "recall": recall,
        "specificity": specificity, "absolute_mcc": np.abs(mcc),
        "min_per_class_accuracy": min_pca,
        "mean_per_class_accuracy": mean_pca,
        "tns": tn, "fns": fn, "fps": fp, "tps": tp,
        "tnr": specificity, "fnr": fnr, "fpr": fpr, "tpr": recall,
    }


_MAX_CRITERIA = ["f1", "f2", "f0point5", "accuracy", "precision", "recall",
                 "specificity", "absolute_mcc", "min_per_class_accuracy",
                 "mean_per_class_accuracy"]


def make_gains_lift(prob, actual, weights=None, groups=16) -> Optional[dict]:
    """Gains/lift table — hex/GainsLift.java semantics: sort by score desc,
    split into `groups` weight-quantile bins, report response rate / lift /
    cumulative capture & gain per bin, plus the Kolmogorov-Smirnov stat."""
    s = np.asarray(prob, dtype=np.float64)
    y = np.asarray(actual, dtype=np.float64)
    w = np.ones_like(y) if weights is None else np.asarray(weights, np.float64)
    order = np.argsort(-s, kind="stable")
    yw = (y * w)[order]
    wo = w[order]
    W = wo.sum()
    P = yw.sum()
    if P <= 0 or P >= W:
        return None  # single-class: table undefined (reference skips it too)
    cw = np.cumsum(wo)
    cy = np.cumsum(yw)
    # bin edges at weight quantiles (last row index with cw <= k*W/groups)
    edges = np.searchsorted(cw, W * np.arange(1, groups + 1) / groups,
                            side="left")
    edges = np.minimum(edges, len(cw) - 1)
    edges = np.unique(edges)
    cum_w = cw[edges]
    cum_y = cy[edges]
    lo_w = np.concatenate([[0.0], cum_w[:-1]])
    lo_y = np.concatenate([[0.0], cum_y[:-1]])
    grp_w = cum_w - lo_w
    grp_y = cum_y - lo_y
    overall_rate = P / W
    response_rate = grp_y / np.maximum(grp_w, 1e-30)
    lift = response_rate / overall_rate
    cum_rate = cum_y / np.maximum(cum_w, 1e-30)
    cum_lift = cum_rate / overall_rate
    capture = grp_y / P
    cum_capture = cum_y / P
    gain = 100.0 * (lift - 1.0)
    cum_gain = 100.0 * (cum_lift - 1.0)
    ks = np.max(np.abs(cy / P - (cw - cy) / (W - P)))
    return {
        "cumulative_data_fraction": (cum_w / W).tolist(),
        "lower_threshold": np.asarray(s[order][edges]).tolist(),
        "lift": lift.tolist(), "cumulative_lift": cum_lift.tolist(),
        "response_rate": response_rate.tolist(),
        "cumulative_response_rate": cum_rate.tolist(),
        "capture_rate": capture.tolist(),
        "cumulative_capture_rate": cum_capture.tolist(),
        "gain": gain.tolist(), "cumulative_gain": cum_gain.tolist(),
        "kolmogorov_smirnov": float(ks),
    }


def make_binomial_metrics(prob, actual, weights=None) -> ModelMetricsBinomial:
    """prob = P(class 1); actual ∈ {0,1}."""
    prob = jnp.asarray(prob, dtype=jnp.float32)
    y = jnp.asarray(actual, dtype=jnp.float32)
    w = jnp.ones_like(y) if weights is None else jnp.asarray(weights, jnp.float32)
    n = int(prob.shape[0])
    sb, tpb, fpb, Pf, Nf, auc, aucpr = _binary_curve(prob, y, w)
    ll = float(np.asarray(_logloss_kernel(prob, y, w)))
    reg = _regression_kernel(prob, y, w)
    mse = float(np.asarray(reg[0]))
    r2 = float(np.asarray(reg[4]))
    fnb = Pf - tpb; tnb = Nf - fpb
    prec = tpb / np.maximum(tpb + fpb, 1e-30)
    rec = tpb / max(Pf, 1e-30)
    f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-30)
    bi = int(np.argmax(f1))
    cm = np.array([[tnb[bi], fpb[bi]], [fnb[bi], tpb[bi]]])
    per_class_err = 0.5 * (fpb[bi] / max(Nf, 1e-30) + fnb[bi] / max(Pf, 1e-30))
    acc = (tpb[bi] + tnb[bi]) / max(Pf + Nf, 1e-30)
    # thresholds_and_metric_scores: AUC2 caps the sweep at ~400 thresholds;
    # subsample boundaries evenly on the sorted-score axis to match.
    n_b = len(sb)
    if n_b > 400:
        keep = np.unique(np.round(np.linspace(0, n_b - 1, 400)).astype(int))
    else:
        keep = np.arange(n_b)
    table = _threshold_columns(sb[keep], tpb[keep], fpb[keep], Pf, Nf)
    table = {k: np.asarray(v).tolist() for k, v in table.items()}
    # max_criteria over the FULL boundary sweep (exact below
    # _EXACT_SWEEP_ROWS, 2^17-bucket resolution above — either way far
    # tighter than AUC2's 400 bins); idx points at the nearest KEPT table
    # row, matching the reference contract that idx indexes the table
    full = _threshold_columns(sb, tpb, fpb, Pf, Nf)
    max_crit = {}
    for c in _MAX_CRITERIA:
        i = int(np.argmax(full[c]))
        ti = int(np.searchsorted(keep, i))
        ti = min(ti, len(keep) - 1)
        max_crit[c] = {"threshold": float(sb[i]), "value": float(full[c][i]),
                       "idx": ti}
    table["max_criteria_and_metric_scores"] = max_crit
    table["gains_lift"] = _gains_lift_from_curve(sb, tpb, fpb, Pf, Nf)
    return ModelMetricsBinomial(
        auc=auc, aucpr=aucpr, logloss=ll, mse=mse, rmse=float(np.sqrt(mse)),
        gini=2 * auc - 1, mean_per_class_error=float(per_class_err), r2=r2,
        f1_threshold=float(sb[bi]), max_f1=float(f1[bi]), confusion_matrix=cm,
        accuracy=float(acc), nobs=n,
        thresholds_and_metric_scores=table)


def _gains_lift_from_curve(sb, tpb, fpb, Pf, Nf, groups: int = 16):
    """Gains/lift from the boundary curve (cum weight = tp+fp): same
    semantics as make_gains_lift without re-sorting the raw scores."""
    W = Pf + Nf
    if not (0.0 < Pf < W) or len(sb) == 0:
        return None
    cum_w = tpb + fpb
    edges = np.searchsorted(cum_w, W * np.arange(1, groups + 1) / groups,
                            side="left")
    edges = np.unique(np.minimum(edges, len(cum_w) - 1))
    cw = cum_w[edges]
    cy = tpb[edges]
    lo_w = np.concatenate([[0.0], cw[:-1]])
    lo_y = np.concatenate([[0.0], cy[:-1]])
    grp_w = np.maximum(cw - lo_w, 1e-30)
    grp_y = cy - lo_y
    rate = Pf / W
    return {
        "cumulative_data_fraction": (cw / W).tolist(),
        "lower_threshold": np.asarray(sb)[edges].tolist(),
        "lift": (grp_y / grp_w / rate).tolist(),
        "cumulative_lift": (cy / np.maximum(cw, 1e-30) / rate).tolist(),
        "response_rate": (grp_y / grp_w).tolist(),
        "cumulative_response_rate": (cy / np.maximum(cw, 1e-30)).tolist(),
        "capture_rate": (grp_y / Pf).tolist(),
        "cumulative_capture_rate": (cy / Pf).tolist(),
        "gain": (100.0 * (grp_y / grp_w / rate - 1.0)).tolist(),
        "cumulative_gain": (100.0 * (cy / np.maximum(cw, 1e-30)
                                     / rate - 1.0)).tolist(),
        "kolmogorov_smirnov": float(np.max(np.abs(
            tpb / max(Pf, 1e-30) - fpb / max(Nf, 1e-30)))),
    }


# --------------------------------------------------------------- multinomial

@jax.jit
def _multinomial_kernel(probs, y, w):
    """Full multinomial aggregate pass ON DEVICE: logloss, argmax error,
    confusion matrix, 1-vs-all MSE and the hit-position histogram all
    reduce to O(K²) outputs here, so finalize does ONE small device_get
    of aggregates and the O(n·K) probability matrix never crosses to the
    host (the old path fetched it three times: py gather, argsort ranks,
    and the OVR AUC table)."""
    eps = 1e-7  # f32-safe: 1-1e-15 rounds to 1.0f -> log1p(-1) = -inf
    rows = probs.shape[0]
    py = probs[jnp.arange(rows), y]
    ll = -(w * jnp.log(jnp.clip(py, eps, 1.0))).sum() / w.sum()
    pred = jnp.argmax(probs, axis=1)
    err = (w * (pred != y)).sum() / w.sum()
    K = probs.shape[1]
    cm = jnp.zeros((K, K), dtype=jnp.float32).at[y, pred].add(w)
    # 1-vs-all MSE (reference semantics: 1 - p_actual)
    mse = (w * (1.0 - py) ** 2).sum() / w.sum()
    # hit ratio @k: position of the true class in the per-row descending
    # sort (same jnp.argsort tie-breaking the host path used), histogram
    # over positions — the cumulative sum happens host-side on [K] floats
    ranks = jnp.argsort(-probs, axis=1)
    pos = jnp.argmax(ranks == y[:, None], axis=1)
    hitpos = jnp.zeros(K, jnp.float32).at[pos].add(1.0) / rows
    return ll, err, cm, mse, hitpos


@jax.jit
def _ovr_auc_kernel(probs, y, w):
    """One-vs-rest AUC/PR-AUC per class, entirely on device: each class
    column runs the 2^17-bucket order-preserving sketch (`auc_device`'s
    curve) and reduces to scalars — the fetch is 3·[K] floats however
    large n is. Empty buckets contribute zero-width chords (AUC) and
    zero-recall steps (PR), so no occupancy filtering is needed."""
    wtot = w.sum()

    def one_class(k):
        yk = (y == k).astype(jnp.float32)
        hp, hn, _ = _binned_curve_kernel(probs[:, k], yk, w)
        tp = jnp.cumsum(hp[::-1])
        fp = jnp.cumsum(hn[::-1])
        P, N = tp[-1], fp[-1]
        tp_prev = jnp.concatenate([jnp.zeros(1, tp.dtype), tp[:-1]])
        fp_prev = jnp.concatenate([jnp.zeros(1, fp.dtype), fp[:-1]])
        auc = ((fp - fp_prev) * (tp + tp_prev)).sum() * 0.5 \
            / jnp.maximum(P * N, 1e-30)
        prec = tp / jnp.maximum(tp + fp, 1e-30)
        rec = tp / jnp.maximum(P, 1e-30)
        rec_prev = tp_prev / jnp.maximum(P, 1e-30)
        aucpr = ((rec - rec_prev) * prec).sum()
        # degenerate-class weight directly, NOT the bucket cumsum: for a
        # single-class input w·yk == w elementwise, so this sum is
        # bit-equal to wtot and the >= guard below cannot be defeated by
        # the scatter-add's different accumulation order
        wk = (w * yk).sum()
        return auc, aucpr, wk

    K = probs.shape[1]
    per_auc, per_pr, prevalence = jax.vmap(one_class)(jnp.arange(K))
    # degenerate classes (no positives / no negatives under the weights)
    # have an undefined OVR AUC — mask to NaN on device like the host
    # path's wk<=0 / wk>=wtot guard
    bad = (prevalence <= 0) | (prevalence >= wtot)
    nan = jnp.float32(jnp.nan)
    return (jnp.where(bad, nan, per_auc), jnp.where(bad, nan, per_pr),
            prevalence)


@dataclass
class ModelMetricsMultinomial:
    logloss: float
    mse: float
    rmse: float
    mean_per_class_error: float
    error: float
    confusion_matrix: np.ndarray
    hit_ratios: np.ndarray
    nobs: int
    auc: Optional[float] = None          # macro one-vs-rest (MultinomialAUC)
    aucpr: Optional[float] = None
    auc_table: Optional[dict] = None     # per-class OVR auc/aucpr + averages

    def to_dict(self) -> Dict:
        return {"logloss": self.logloss, "MSE": self.mse, "RMSE": self.rmse,
                "mean_per_class_error": self.mean_per_class_error,
                "error": self.error, "cm": self.confusion_matrix.tolist(),
                "hit_ratios": self.hit_ratios.tolist(), "nobs": self.nobs,
                "AUC": self.auc, "pr_auc": self.aucpr}


def multinomial_auc_table(probs, y, w, max_classes=20) -> Optional[dict]:
    """One-vs-rest AUC per class + macro/weighted averages.

    Reference: hex/MultinomialAUC.java (default OVR). Skipped above
    `max_classes` (the reference gates this behind auc_type for memory).
    Computed on device via the 2^17-bucket sketch (``_ovr_auc_kernel``)
    so the fetch is 3·[K] scalars regardless of n — the old path pulled
    the whole probability matrix host-side and sorted each class column;
    sketch-vs-exact AUC deviation is bounded by the bucket quantisation
    (~1e-4, same contract as the binomial large-n path)."""
    probs = jnp.asarray(probs, jnp.float32)
    K = int(probs.shape[1])
    if K > max_classes:
        return None
    per_auc_d, per_pr_d, prev_d = _ovr_auc_kernel(
        probs, jnp.asarray(y, jnp.int32), jnp.asarray(w, jnp.float32))
    from h2o3_tpu import telemetry
    pa, pp, pv = telemetry.device_get((per_auc_d, per_pr_d, prev_d),
                                      pipeline="train")
    pa = np.asarray(pa, np.float64)
    pp = np.asarray(pp, np.float64)
    pv = np.asarray(pv, np.float64)
    pv = pv / max(pv.sum(), 1e-30)
    ok = ~np.isnan(pa)
    macro = float(pa[ok].mean()) if ok.any() else float("nan")
    weighted = float((pa[ok] * pv[ok]).sum() / max(pv[ok].sum(), 1e-30)) \
        if ok.any() else float("nan")
    macro_pr = float(pp[ok].mean()) if ok.any() else float("nan")
    weighted_pr = float((pp[ok] * pv[ok]).sum() / max(pv[ok].sum(), 1e-30)) \
        if ok.any() else float("nan")
    return {"per_class_auc": [float(v) for v in pa],
            "per_class_aucpr": [float(v) for v in pp],
            "macro_auc": macro, "weighted_auc": weighted,
            "macro_aucpr": macro_pr, "weighted_aucpr": weighted_pr}


def make_multinomial_metrics(probs, actual, weights=None) -> ModelMetricsMultinomial:
    """All aggregates computed on device; the host sees O(K²) numbers
    (confusion matrix, hit histogram, OVR AUC scalars) in two counted
    fetches — never the [n, K] probability matrix (transfer-budget
    guarded in tests/test_transfer_budget.py)."""
    probs = jnp.asarray(probs, dtype=jnp.float32)
    y = jnp.asarray(actual, dtype=jnp.int32)
    w = (jnp.ones(probs.shape[0], jnp.float32) if weights is None
         else jnp.asarray(weights, jnp.float32))
    from h2o3_tpu import telemetry
    ll, err, cm, mse, hitpos = telemetry.device_get(
        _multinomial_kernel(probs, y, w), pipeline="train")
    cm = np.asarray(cm)
    K = cm.shape[0]
    row_tot = cm.sum(axis=1)
    per_class = np.where(row_tot > 0, 1.0 - np.diag(cm) / np.maximum(row_tot, 1e-30), 0.0)
    present = row_tot > 0
    mpce = float(per_class[present].mean()) if present.any() else 0.0
    mse = float(mse)
    # hit ratio @k: cumulative share of rows whose true class ranks in
    # the top k (host cumsum over the [K] device histogram)
    hr = np.cumsum(np.asarray(hitpos, np.float64))[: min(K, 10)]
    auct = multinomial_auc_table(probs, y, w)
    return ModelMetricsMultinomial(
        logloss=float(ll), mse=mse, rmse=float(np.sqrt(mse)),
        mean_per_class_error=mpce, error=float(err),
        confusion_matrix=cm, hit_ratios=hr, nobs=int(probs.shape[0]),
        auc=None if auct is None else auct["macro_auc"],
        aucpr=None if auct is None else auct["macro_aucpr"],
        auc_table=auct)


# ------------------------------------------------------------------- anomaly

@dataclass
class ModelMetricsAnomaly:
    """hex/ModelMetricsAnomaly.java — score summary for IsolationForest."""
    mean_score: float
    mean_normalized_score: float
    nobs: int

    def to_dict(self) -> Dict:
        return {"mean_score": self.mean_score,
                "mean_normalized_score": self.mean_normalized_score,
                "nobs": self.nobs}


def make_anomaly_metrics(score, normalized_score) -> ModelMetricsAnomaly:
    s = np.asarray(score, np.float64)
    ns = np.asarray(normalized_score, np.float64)
    return ModelMetricsAnomaly(mean_score=float(s.mean()),
                               mean_normalized_score=float(ns.mean()),
                               nobs=int(s.shape[0]))


# ---------------- uplift (hex/AUUC.java + ModelMetricsBinomialUplift) ---

@dataclass
class ModelMetricsBinomialUplift:
    """hex/ModelMetricsBinomialUplift: the AUUC object with its
    threshold table and the qini/lift/gain flavors
    (hex/AUUC.java AUUCType)."""
    auuc: float                         # default-flavor AUUC (qini)
    auuc_normalized: float
    qini: float                         # Qini coefficient (area - random)
    ate: float                          # average treatment effect
    att: float                          # ATE on the treated
    atc: float                          # ATE on control
    auuc_table: Optional[dict] = None   # per-bin AUUC per flavor
    thresholds_and_metric_scores: Optional[dict] = None
    nobs: int = 0

    @property
    def auuc_normalized_(self):
        return self.auuc_normalized

    def to_dict(self):
        return {"AUUC": self.auuc, "auuc": self.auuc,
                "auuc_normalized": self.auuc_normalized,
                "qini": self.qini, "ate": self.ate, "att": self.att,
                "atc": self.atc, "nobs": self.nobs}


def make_uplift_metrics(uplift, y, treat, weights=None,
                        nbins: int = 1000) -> ModelMetricsBinomialUplift:
    """Full AUUC computation (hex/AUUC.java): rows ranked by predicted
    uplift, cumulative uplift at ``nbins`` thresholds, three flavors:
      qini:  cum_treat_y − cum_ctrl_y · n_t/n_c
      lift:  cum_treat_y/n_t − cum_ctrl_y/n_c
      gain:  lift · (n_t + n_c)
    AUUC = mean over bins of the chosen flavor's curve; normalized
    divides by the curve's final value (AUUC.java normalizedAUUC)."""
    uplift = np.asarray(uplift, np.float64)
    y = np.asarray(y, np.float64)
    treat = np.asarray(treat, np.float64)
    w = (np.ones_like(y) if weights is None
         else np.asarray(weights, np.float64))
    live = w > 0
    uplift, y, treat, w = uplift[live], y[live], treat[live], w[live]
    n = len(y)
    order = np.argsort(-uplift)
    u_s = uplift[order]
    wt = (w * treat)[order]
    wc = (w * (1 - treat))[order]
    wyt = (w * y * treat)[order]
    wyc = (w * y * (1 - treat))[order]
    nt = np.cumsum(wt)
    nc = np.cumsum(wc)
    cyt = np.cumsum(wyt)
    cyc = np.cumsum(wyc)
    qini_c = cyt - cyc * nt / np.maximum(nc, 1e-12)
    lift_c = cyt / np.maximum(nt, 1e-12) - cyc / np.maximum(nc, 1e-12)
    gain_c = lift_c * (nt + nc)
    idx = np.linspace(0, n - 1, min(nbins, n)).astype(int)
    flavors = {"qini": qini_c, "lift": lift_c, "gain": gain_c}
    aucs = {k: float(v[idx].mean()) for k, v in flavors.items()}
    finals = {k: float(v[-1]) if n else 0.0 for k, v in flavors.items()}
    norm = {k: (aucs[k] / finals[k] if abs(finals[k]) > 1e-12 else 0.0)
            for k in flavors}
    # random-targeting baseline for the Qini coefficient
    rand_area = 0.5 * finals["qini"]
    ate = (float(cyt[-1] / max(nt[-1], 1e-12)
                 - cyc[-1] / max(nc[-1], 1e-12)) if n else 0.0)
    # ATT/ATC: the model's PREDICTED uplift averaged over the treated /
    # control subpopulations (distinct estimands from the outcome-based
    # ATE above — hex/ModelMetricsBinomialUplift)
    wt_sum = float((w * treat).sum())
    wc_sum = float((w * (1 - treat)).sum())
    att = (float((w * treat * uplift).sum() / max(wt_sum, 1e-12))
           if n else 0.0)
    atc = (float((w * (1 - treat) * uplift).sum() / max(wc_sum, 1e-12))
           if n else 0.0)
    tbl = {
        "thresholds": [float(u_s[i]) for i in idx],
        "qini": [float(qini_c[i]) for i in idx],
        "lift": [float(lift_c[i]) for i in idx],
        "gain": [float(gain_c[i]) for i in idx],
        "n": [int(i + 1) for i in idx],
    }
    return ModelMetricsBinomialUplift(
        auuc=aucs["qini"], auuc_normalized=norm["qini"],
        qini=aucs["qini"] - rand_area, ate=ate, att=att, atc=atc,
        auuc_table={"flavors": aucs, "normalized": norm},
        thresholds_and_metric_scores=tbl, nobs=n)
