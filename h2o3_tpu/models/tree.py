"""Shared histogram-tree machinery (GBM / DRF / IF / XGBoost-compat).

Reference: hex/tree/ — SharedTree.java:229 driver, per-level histogram
MRTask (ScoreBuildHistogram2.java:121-301 two-stage private-then-merge
accumulate), DHistogram (w,wY,wYY) bins merged up the reduce tree
(DHistogram.java:432), split finding on the reduced histograms
(DTree.java), CompressedTree storage.

TPU re-design (SURVEY.md §7.3):
- trees are complete binary arrays of static depth (XLA needs static
  shapes): node k's children are 2k+1 / 2k+2; rows carry an int32 node id
  and are re-routed by vectorized gathers each level — no mutable 'nids'
  column;
- per-level histograms come from the one-hot-matmul / scatter kernels in
  ops/histogram.py, all-reduced over ICI ('data' axis psum) instead of the
  MRTask tree / Rabit ring;
- split finding = masked cumsum + argmax over [nodes, features, bins, 2
  NA-directions] entirely on device (the reference scans bins per leaf on
  the driver);
- Newton (g, h) gains; NA gets a dedicated bin with learned direction
  (DHistogram.wNA semantics).
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial
from typing import List, Optional

import os as _os

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30

# packed routing word layout: feat[0:14) | bin[14:28) | na_left[28] | split[29]
# (14 bits each caps features and bins at 16383 — asserted in TreeConfig and
# binning.bin_matrix; bins can exceed 10 bits when nbins_cats grows the
# shared bin count for high-cardinality categoricals)
FEAT_BITS = 14
FEAT_MASK = (1 << FEAT_BITS) - 1
BIN_SHIFT = FEAT_BITS
BIN_MASK = (1 << 14) - 1
NA_SHIFT = 28
SPLIT_SHIFT = 29


@dataclass(frozen=True)
class TreeConfig:
    max_depth: int
    n_bins: int            # real bins B; NA bin index = B
    n_features: int
    min_rows: float = 10.0
    min_split_improvement: float = 1e-5
    reg_lambda: float = 0.0
    reg_alpha: float = 0.0   # L1 on leaf values (xgboost semantics)
    mtries: int = 0          # >0: random feature subset PER NODE per level
                             # (DRF mtries, hex/tree/drf/DRF.java)
    # col_sample_rate_change_per_level (hex/tree/DTree.java:57):
    # effective per-level subset size = (mtries or F)·factor^depth,
    # clamped to [1, F]
    col_rate_change: float = 1.0
    hist_method: str = "auto"
    # histogram_type=random (hex/tree/DHistogram.java HistogramType.Random):
    # randomize the adaptive grid's phase per tree/feature so split points
    # land at random offsets within a bin width
    random_grid: bool = False
    # histogram contraction precision on the MXU: 'bfloat16' (1-pass,
    # default — deviation bound quantified in ops/hist_adaptive.py) or
    # 'float32' (6-pass HIGHEST, exact); 'auto' = bfloat16
    histogram_precision: str = "auto"

    @property
    def n_nodes(self) -> int:
        return 2 ** (self.max_depth + 1) - 1

    def __post_init__(self):
        assert self.n_features <= FEAT_MASK, self.n_features
        assert self.n_bins < BIN_MASK, self.n_bins


def _leaf_score2(g, h, cfg: TreeConfig):
    """Squared score T(g)²/(h+λ) with the xgboost L1 soft-threshold T."""
    lam = cfg.reg_lambda
    if cfg.reg_alpha:
        g = jnp.sign(g) * jnp.maximum(jnp.abs(g) - cfg.reg_alpha, 0.0)
    return g ** 2 / (h + lam + 1e-12)


def _leaf_value(g, h, cfg: TreeConfig):
    lam = cfg.reg_lambda
    if cfg.reg_alpha:
        g = jnp.sign(g) * jnp.maximum(jnp.abs(g) - cfg.reg_alpha, 0.0)
    return -g / (h + lam + 1e-12)


def _find_splits(trip, cfg: TreeConfig, col_mask, mono=None,
                 max_bin=None):
    """Best split per node from a (g, h, w) histogram triple, each
    [N, F', B'] with F' >= n_features and B' >= n_bins+1 (the pallas
    kernel's padded layout; trailing features/bins are zero).

    ``col_mask`` is [F] (per-tree column sampling) or [N, F] (per-node
    mtries subsets). ``mono`` ([F] int, -1/0/+1) enforces monotone
    constraints: a candidate split on feature f with mono[f]=c is invalid
    unless c·(left child value) <= c·(right child value) — the same
    pruning hex/tree/DTree.java applies via Constraints.

    ``max_bin`` restricts candidates to t in 1..max_bin-1 when the
    histogram's lane width exceeds the REAL bin count (the packed path:
    B = W-1 lanes, codes occupy max_bin real bins). Without the mask
    the empty lanes admit an 'all non-NA left vs NA right' candidate
    the unpacked global-sketch scan cannot express — masking keeps
    packed and unpacked candidate grids IDENTICAL, so f32 trees stay
    bit-identical on NA-heavy frames too.

    Returns (gain, feat, bin, na_left, g_tot, h_tot, w_tot, vl, vr) per
    node, where vl/vr are the SELECTED split's unclipped child values
    (used by callers to propagate monotone bounds)."""
    B = cfg.n_bins
    F = cfg.n_features
    g = trip[0][:, :F, :]
    h = trip[1][:, :F, :]
    w = trip[2][:, :F, :]
    g_na, h_na, w_na = g[..., B], h[..., B], w[..., B]
    cg = jnp.cumsum(g[..., :B], axis=-1)
    ch = jnp.cumsum(h[..., :B], axis=-1)
    cw = jnp.cumsum(w[..., :B], axis=-1)
    g_tot = cg[..., -1] + g_na
    h_tot = ch[..., -1] + h_na
    w_tot = cw[..., -1] + w_na
    # candidate split t in 1..B-1: left = bins < t (+ NA if na_left)
    gl0, hl0, wl0 = cg[..., :-1], ch[..., :-1], cw[..., :-1]

    def gains(gl, hl, wl):
        gr = g_tot[..., None] - gl
        hr = h_tot[..., None] - hl
        wr = w_tot[..., None] - wl
        parent = _leaf_score2(g_tot, h_tot, cfg)
        gain = (_leaf_score2(gl, hl, cfg) + _leaf_score2(gr, hr, cfg)
                - parent[..., None])
        ok = (wl >= cfg.min_rows) & (wr >= cfg.min_rows)
        if mono is not None:
            c = mono.astype(jnp.float32)[None, :, None]      # [1,F,1]
            vl = _leaf_value(gl, hl, cfg)
            vr = _leaf_value(gr, hr, cfg)
            ok = ok & ((c == 0) | (c * (vr - vl) >= 0))
        return jnp.where(ok, gain, NEG_INF)

    gains_nr = gains(gl0, hl0, wl0)                                  # NA right
    gains_nl = gains(gl0 + g_na[..., None], hl0 + h_na[..., None],
                     wl0 + w_na[..., None])                          # NA left
    all_gains = jnp.stack([gains_nr, gains_nl], axis=-1)             # [N,F,B-1,2]
    cm = col_mask if col_mask.ndim == 2 else col_mask[None, :]
    all_gains = jnp.where(cm[:, :, None, None], all_gains, NEG_INF)
    if max_bin is not None and max_bin - 1 < B - 1:
        tmask = jnp.arange(B - 1) < (max_bin - 1)
        all_gains = jnp.where(tmask[None, None, :, None], all_gains,
                              NEG_INF)
    N, F = all_gains.shape[0], all_gains.shape[1]
    flat = all_gains.reshape(N, -1)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    per_f = (B - 1) * 2
    feat = best // per_f
    rem = best % per_f
    bin_idx = rem // 2 + 1          # split t in 1..B-1
    na_left = (rem % 2) == 1
    # selected split's child (g, h, w) for bound propagation and
    # deepest-level leaf values (children of the last split level)
    nidx = jnp.arange(N)
    t_sel = bin_idx - 1
    gl_s = gl0[nidx, feat, t_sel]
    hl_s = hl0[nidx, feat, t_sel]
    wl_s = wl0[nidx, feat, t_sel]
    gl_s = gl_s + jnp.where(na_left, g_na[nidx, feat], 0.0)
    hl_s = hl_s + jnp.where(na_left, h_na[nidx, feat], 0.0)
    wl_s = wl_s + jnp.where(na_left, w_na[nidx, feat], 0.0)
    gt_s = g_tot[nidx, 0]
    ht_s = h_tot[nidx, 0]
    vl_sel = _leaf_value(gl_s, hl_s, cfg)
    vr_sel = _leaf_value(gt_s - gl_s, ht_s - hl_s, cfg)
    wr_sel = w_tot[nidx, 0] - wl_s
    # f=0 slice of per-feature totals == node totals
    return (best_gain, feat.astype(jnp.int32), bin_idx.astype(jnp.int32),
            na_left, g_tot[:, 0], h_tot[:, 0], w_tot[:, 0], vl_sel, vr_sel,
            wl_s, wr_sel)


def _axis_size(axis_name) -> int:
    """Static mesh-axis size inside shard_map, across jax versions
    (jax.lax.axis_size is missing on 0.4.x; jax.core.axis_frame returns
    the bare size there and a frame object on newer builds)."""
    if hasattr(jax.lax, "axis_size"):
        return int(jax.lax.axis_size(axis_name))
    frame = jax.core.axis_frame(axis_name)
    return int(frame if isinstance(frame, int) else frame.size)


def _find_splits_sharded(trip, cfg: TreeConfig, col_mask, mono=None,
                         model_axis=None, max_bin=None):
    """Split search sharded over the mesh 'model' axis: each model shard
    scans a contiguous FEATURE BLOCK of the (already data-psum'd)
    histograms with the ordinary :func:`_find_splits`, and the global
    best split per node is reconstructed with one small all_gather +
    argmax over shards. Features never move — only [N, 8] candidate
    scalars cross the ICI (the reference has no wide-axis sharding at
    all, SURVEY.md §5; this divides the N·F·B split scan by n_model).

    Tie-breaking matches the single-shard argmax EXACTLY: the local
    flattened candidate order is feature-major and shard blocks are
    contiguous feature ranges, so "first max wins" picks the same split
    — sharded and unsharded trees stay bit-identical."""
    if model_axis is None:
        return _find_splits(trip, cfg, col_mask, mono=mono,
                            max_bin=max_bin)
    n_model = _axis_size(model_axis)
    if n_model == 1:
        return _find_splits(trip, cfg, col_mask, mono=mono,
                            max_bin=max_bin)
    from dataclasses import replace as dc_replace
    B = cfg.n_bins
    F = cfg.n_features
    F_loc = -(-F // n_model)
    Fp = F_loc * n_model
    midx = jax.lax.axis_index(model_axis)
    start = midx * F_loc
    # node totals from the full histograms (a shard whose block is pure
    # zero-padding has no real feature to read them from): any real
    # feature's bins sum to the node totals — use feature 0
    g_tot = trip[0][:, 0, : B + 1].sum(-1)
    h_tot = trip[1][:, 0, : B + 1].sum(-1)
    w_tot = trip[2][:, 0, : B + 1].sum(-1)

    def block(x):
        xp = jnp.pad(x[:, :F, :], ((0, 0), (0, Fp - F), (0, 0)))
        return jax.lax.dynamic_slice_in_dim(xp, start, F_loc, axis=1)

    trip_l = tuple(block(t) for t in trip)
    cm = col_mask if col_mask.ndim == 2 else col_mask[None, :]
    cm = jnp.pad(cm, ((0, 0), (0, Fp - F)))          # padding: never split
    cm_l = jax.lax.dynamic_slice_in_dim(cm, start, F_loc, axis=1)
    mono_l = None
    if mono is not None:
        mono_l = jax.lax.dynamic_slice_in_dim(
            jnp.pad(mono, (0, Fp - F)), start, F_loc)
    cfg_l = dc_replace(cfg, n_features=F_loc)
    (bg, bf, bb, bnl, _gt, _ht, _wt, vl, vr, wl, wr) = _find_splits(
        trip_l, cfg_l, cm_l, mono=mono_l, max_bin=max_bin)
    cand = jnp.stack([bg, (start + bf).astype(jnp.float32),
                      bb.astype(jnp.float32), bnl.astype(jnp.float32),
                      vl, vr, wl, wr], axis=-1)      # [N, 8]
    allc = jax.lax.all_gather(cand, model_axis)      # [n_model, N, 8]
    winner = jnp.argmax(allc[:, :, 0], axis=0)       # first max = low shard
    sel = jnp.take_along_axis(allc, winner[None, :, None], axis=0)[0]
    # feature/bin indices survive the f32 ride exactly (both < 2^14)
    return (sel[:, 0], sel[:, 1].astype(jnp.int32),
            sel[:, 2].astype(jnp.int32), sel[:, 3] > 0.5,
            g_tot, h_tot, w_tot, sel[:, 4], sel[:, 5], sel[:, 6],
            sel[:, 7])


BIGV = jnp.float32(1e30)


def _child_bounds(lo_b, hi_b, vl, vr, mono_dir, can):
    """Monotone bound propagation (hex/tree/DTree Constraints): a split
    on a constrained feature bounds both subtrees at the midpoint of the
    (clipped) child values; unconstrained splits inherit the parent's
    bounds. Returns interleaved [2N] (lo, hi) for the children level."""
    vl_c = jnp.clip(vl, lo_b, hi_b)
    vr_c = jnp.clip(vr, lo_b, hi_b)
    mid = 0.5 * (vl_c + vr_c)
    up = can & (mono_dir > 0)      # left <= right
    dn = can & (mono_dir < 0)
    lo_left = jnp.where(dn, mid, lo_b)
    hi_left = jnp.where(up, mid, hi_b)
    lo_right = jnp.where(up, mid, lo_b)
    hi_right = jnp.where(dn, mid, hi_b)
    lo2 = jnp.stack([lo_left, lo_right], 1).reshape(-1)
    hi2 = jnp.stack([hi_left, hi_right], 1).reshape(-1)
    return lo2, hi2


def _next_allowed(allowed, sets, bf, can):
    """Interaction-constraint propagation: children may only split on
    features sharing an interaction set with the parent's split feature
    (intersected with the parent's own allowance — path semantics).
    ``allowed`` [N, F] bool, ``sets`` [S, F] bool. (hex/tree
    interaction_constraints / GlobalInteractionConstraints)."""
    contains = sets[:, bf].T                     # [N, S]: sets with feat
    union = (contains.astype(jnp.float32) @ sets.astype(jnp.float32)) > 0
    child = jnp.where(can[:, None], allowed & union, allowed)
    return jnp.repeat(child, 2, axis=0)          # both children alike


def _level_mtries(cfg: TreeConfig, d: int, F: int) -> int:
    """Per-level column-subset size: mtries scaled by
    col_sample_rate_change_per_level^depth (hex/tree/DTree.java:57),
    clamped to [1, F]. 0 = use the full column set."""
    mt_d = cfg.mtries
    if cfg.col_rate_change != 1.0:
        base_m = cfg.mtries if cfg.mtries > 0 else F
        mt_d = int(min(max(1, round(base_m * cfg.col_rate_change ** d)), F))
        if mt_d >= F and cfg.mtries <= 0:
            mt_d = 0               # full set — no subset draw
    return mt_d


def grow_tree(codes, g, h, w, cfg: TreeConfig, col_mask, axis_name=None,
              key=None, mono=None, sets=None, model_axis=None):
    """Build one tree. All args are device arrays (codes [rows,F] int,
    g/h/w [rows] float32, already weight-multiplied); returns tree arrays
    of length M = 2^(D+1)-1 plus per-row final node ids.

    Runs under jit; the level loop is unrolled (static depth). Under plain
    jit on sharded inputs GSPMD inserts the histogram all-reduce; under
    shard_map pass ``axis_name='data'`` for explicit psums (this is the
    Rabit-allreduce replacement point).

    ``cfg.mtries > 0`` draws a fresh random feature subset per NODE per
    level from ``key`` (DRF mtries semantics, hex/tree/drf/DRF.java —
    the key must be identical across shards so splits agree).

    ``model_axis`` shards the per-level split SEARCH over the mesh
    'model' axis (histograms stay data-psum'd and replicated across
    model shards; see _find_splits_sharded)."""
    from h2o3_tpu.ops.binning import CodesView
    from h2o3_tpu.ops.histogram import build_histograms

    rm = codes.rm if isinstance(codes, CodesView) else codes
    D = cfg.max_depth
    M = cfg.n_nodes
    B1 = cfg.n_bins + 1
    rows, F = rm.shape

    feat = jnp.full(M, -1, jnp.int32)
    split_bin = jnp.zeros(M, jnp.int32)
    na_left = jnp.zeros(M, bool)
    is_split = jnp.zeros(M, bool)
    value = jnp.zeros(M, jnp.float32)
    gain_arr = jnp.zeros(M, jnp.float32)
    node_w = jnp.zeros(M, jnp.float32)

    # (g, h, w) stacked ONCE — constant across levels: dead/off-level rows
    # are excluded by OOB seg ids instead of per-level weight masking
    # (saves 3 × rows multiplies per level and keeps one operand cached)
    ghw = jnp.stack([g, h, w]).astype(jnp.float32)

    nid = jnp.zeros(rows, jnp.int32)
    prev_hist = None
    lo_b = jnp.full(1, -BIGV)
    hi_b = jnp.full(1, BIGV)
    allowed = (jnp.ones((1, F), bool) if sets is not None else None)
    for d in range(D):
        base = 2 ** d - 1
        N = 2 ** d
        local = nid - base
        in_level = (local >= 0) & (local < N)
        lid = jnp.clip(local, 0, N - 1)
        if prev_hist is None:
            seg = jnp.where(in_level, local, -1)
            hist = build_histograms(codes, seg, ghw, N, B1, cfg.hist_method)
            if axis_name is not None:
                hist = jax.lax.psum(hist, axis_name)
        else:
            # sibling subtraction: build only LEFT children (even local
            # ids), right = parent − left (halves the histogram FLOPs —
            # the reference plays the same trick per DHistogram pair).
            # Children of non-split parents get phantom mass but are
            # unreachable by routing, so never read.
            is_left = in_level & (local % 2 == 0)
            seg = jnp.where(is_left, local // 2, -1)
            hist_l = build_histograms(codes, seg, ghw, N // 2, B1,
                                      cfg.hist_method)
            if axis_name is not None:
                hist_l = jax.lax.psum(hist_l, axis_name)
            # interleave (left, parent−left) → [N, F', B'] per component
            hist = tuple(
                jnp.stack([hl, hp - hl], axis=1).reshape(
                    N, hl.shape[1], hl.shape[2])
                for hl, hp in zip(hist_l, prev_hist))
        prev_hist = hist
        level_mask = col_mask
        mt_d = _level_mtries(cfg, d, F)
        if mt_d > 0 and key is not None:
            u = jax.random.uniform(jax.random.fold_in(key, d), (N, F))
            u = jnp.where(col_mask[None, :], u, 2.0)  # excluded cols last
            kth = jnp.sort(u, axis=1)[:, min(mt_d, F) - 1]
            level_mask = (u <= kth[:, None]) & col_mask[None, :]
        if allowed is not None:
            lm2 = level_mask if level_mask.ndim == 2 else level_mask[None, :]
            level_mask = lm2 & allowed
        bg, bf, bb, bnl, gt, ht, wt, vl_s, vr_s, _wl, _wr = \
            _find_splits_sharded(hist, cfg, level_mask, mono=mono,
                                 model_axis=model_axis)
        can = (bg > jnp.maximum(cfg.min_split_improvement, 0.0)) & (wt > 0)
        idx = base + jnp.arange(N)
        feat = feat.at[idx].set(jnp.where(can, bf, -1))
        split_bin = split_bin.at[idx].set(bb)
        na_left = na_left.at[idx].set(bnl)
        is_split = is_split.at[idx].set(can)
        value = value.at[idx].set(
            jnp.clip(_leaf_value(gt, ht, cfg), lo_b, hi_b))
        gain_arr = gain_arr.at[idx].set(jnp.where(can, bg, 0.0))
        node_w = node_w.at[idx].set(wt)
        if mono is not None:
            lo_b, hi_b = _child_bounds(lo_b, hi_b, vl_s, vr_s, mono[bf], can)
        else:
            lo_b = jnp.repeat(lo_b, 2)
            hi_b = jnp.repeat(hi_b, 2)
        if allowed is not None:
            allowed = _next_allowed(allowed, sets, bf, can)
        # route rows: only rows whose current node is at this level AND
        # split. Per-node routing data is packed into ONE word so each row
        # does a single small-table gather (4 separate gathers cost ~8ms
        # per level at 1M rows on TPU)
        word = (bf | (bb << BIN_SHIFT) | (bnl.astype(jnp.int32) << NA_SHIFT)
                | (can.astype(jnp.int32) << SPLIT_SHIFT))
        rw = word[lid]
        node_feat = rw & FEAT_MASK
        node_bin = (rw >> BIN_SHIFT) & BIN_MASK
        node_nal = ((rw >> NA_SHIFT) & 1).astype(bool)
        node_can = ((rw >> SPLIT_SHIFT) & 1).astype(bool)
        c = jnp.take_along_axis(rm, node_feat[:, None].astype(jnp.int32),
                                axis=1)[:, 0].astype(jnp.int32)
        is_na = c == cfg.n_bins
        go_right = jnp.where(is_na, ~node_nal, c >= node_bin)
        child = 2 * nid + 1 + go_right.astype(jnp.int32)
        nid = jnp.where(in_level & node_can, child, nid)

    # deepest level: leaf values from segment totals
    baseD = 2 ** D - 1
    localD = nid - baseD
    inD = (localD >= 0) & (localD < 2 ** D)
    lidD = jnp.clip(localD, 0, 2 ** D - 1)
    gD, hD, wD = _segment_totals(lidD, inD, g, h, w, 2 ** D)
    if axis_name is not None:
        gD = jax.lax.psum(gD, axis_name)
        hD = jax.lax.psum(hD, axis_name)
        wD = jax.lax.psum(wD, axis_name)
    idxD = baseD + jnp.arange(2 ** D)
    value = value.at[idxD].set(
        jnp.clip(_leaf_value(gD, hD, cfg), lo_b, hi_b))
    node_w = node_w.at[idxD].set(wD)

    tree = {"feat": feat, "split_bin": split_bin, "na_left": na_left,
            "is_split": is_split, "value": value, "gain": gain_arr,
            "node_w": node_w}
    return tree, nid


# histogram_type values the fused ADAPTIVE kernel serves — ONE spelling
# for the GBM/DRF packed-path gating and its infeasible-fallback rule
# (GBM additionally allows 'random', which only the adaptive kernel's
# per-tree grid phase can honor)
ADAPTIVE_HIST_TYPES = ("uniform_adaptive", "uniform", "auto", "round_robin")


def packed_codes_requested(params) -> bool:
    """Packed binned-code hot-path gate (GBM/DRF ``packed_codes``
    param). 'auto' (default) packs wherever the binned pallas kernel
    runs — TPU, or the H2O3_PALLAS_INTERPRET escape — making int8/int16
    codes the default TPU hot loop; True forces the packed path
    everywhere (the scatter reference carries it on CPU — parity
    tests); False keeps the per-node adaptive f32 kernel."""
    v = params.get("packed_codes", "auto")
    if isinstance(v, str):
        v = v.lower()
    if v in ("auto", None):
        from h2o3_tpu.ops.hist_adaptive import pallas_interpret
        return jax.default_backend() == "tpu" or pallas_interpret()
    return v in (True, "true", "1")


def packed_bins_upper_bound(spec, params) -> int:
    """Upper bound on the global sketch's effective bin count, from the
    cat domains alone (numeric features never exceed nbins; identity
    cats need their cardinality, grouped cats at most nbins_cats+1).
    Lets the packed gating reject infeasible configs BEFORE paying the
    O(rows·F) sketch+digitise — binned_feasible is monotone in n_bins,
    so 'upper bound feasible' implies 'actual feasible'."""
    nbins = int(params["nbins"])
    nc = int(params.get("nbins_cats", 1024))
    cards = [len(spec.cat_domains.get(n, ())) for n, c in
             zip(spec.names, spec.is_cat) if c]
    mc = max(cards, default=0)
    return max(nbins, min(mc, nc + 1), 2)


def binned_feasible(n_bins: int, n_features: int, max_depth: int) -> bool:
    """Whether the packed binned kernel's deepest level fits VMEM —
    the adaptive_feasible bound applied to W = pick_W(n_bins) (scratch
    + output block both hold [3·2^(D-1), F·W] f32). Past the 254-bin
    lane cap or the VMEM bound, the matmul/scatter global-sketch path
    takes over."""
    from h2o3_tpu.ops.hist_adaptive import pick_W
    if n_bins > 254:
        return False
    W = pick_W(n_bins)
    n_deep = 2 ** max(max_depth - 1, 0)
    return 2 * 3 * n_deep * n_features * W * 4 <= 96 * 2 ** 20


def _adaptive_n_bins_eff(spec, params) -> int:
    """Effective bin count sizing the kernel's lane width W: enums want
    identity bins (card-1), capped by nbins_cats and the 254-lane max."""
    nbins = int(params["nbins"])
    cards = [len(spec.cat_domains.get(n, ())) for n, c in
             zip(spec.names, spec.is_cat) if c]
    max_card = max(cards, default=0)
    return max(nbins, min(max(max_card - 1, 0),
                          int(params.get("nbins_cats", 1024)), 254), 2)


def adaptive_feasible(spec, params, max_depth: int) -> bool:
    """Whether the fused adaptive kernel's deepest level fits VMEM
    (scratch + output block both hold [3·2^(D-1), F·W] f32; ~128MB/core
    on v5e, gated conservatively at 96MB). Beyond this the global-sketch
    path takes over (it tiles features and uses sibling subtraction)."""
    from h2o3_tpu.ops.hist_adaptive import pick_W
    if int(params["nbins"]) > 254:
        return False
    W = pick_W(_adaptive_n_bins_eff(spec, params))
    n_deep = 2 ** max(max_depth - 1, 0)
    level_bytes = 2 * 3 * n_deep * spec.n_features * W * 4
    return level_bytes <= 96 * 2 ** 20


def adaptive_setup(spec, params, max_depth: int, mtries: int = 0):
    """Shared GBM/DRF setup for the adaptive path: TreeConfig sized so
    enums get identity bins (card-1 real bins, capped by nbins_cats and
    the 254-lane max), per-feature finite root ranges (±inf masked BEFORE
    the min/max so one infinite cell can't zero a feature's range) and
    per-feature bin counts nb_f (the nbins_cats analog,
    hex/tree/DHistogram nbins_cats)."""
    p = params
    nbins = int(p["nbins"])
    nbins_cats = int(p.get("nbins_cats", 1024))
    cfg = TreeConfig(max_depth=max_depth,
                     n_bins=_adaptive_n_bins_eff(spec, p),
                     n_features=spec.n_features,
                     min_rows=float(p["min_rows"]),
                     min_split_improvement=float(p["min_split_improvement"]),
                     reg_lambda=float(p.get("reg_lambda", 0.0)),
                     reg_alpha=float(p.get("reg_alpha", 0.0)),
                     mtries=mtries,
                     col_rate_change=float(
                         p.get("col_sample_rate_change_per_level", 1.0)
                         or 1.0),
                     hist_method=p.get("hist_kernel", "auto"),
                     random_grid=(str(p.get("histogram_type", "")).lower()
                                  == "random"),
                     histogram_precision=str(
                         p.get("histogram_precision", "auto")).lower())
    if spec.X is None:           # streaming mode: ranges from host X
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")     # all-NaN cols → 0 below
            Xh = np.where(np.isfinite(spec.X_host), spec.X_host, np.nan)
            root_lo = jnp.asarray(np.nan_to_num(
                np.nanmin(Xh, axis=0), nan=0.0).astype(np.float32))
            root_hi = jnp.asarray(np.nan_to_num(
                np.nanmax(Xh, axis=0), nan=0.0).astype(np.float32))
    else:
        Xf = jnp.where(jnp.isfinite(spec.X), spec.X, jnp.nan)
        root_lo = jnp.nan_to_num(jnp.nanmin(Xf, axis=0), nan=0.0)
        root_hi = jnp.nan_to_num(jnp.nanmax(Xf, axis=0), nan=0.0)
    cat = jnp.asarray(np.asarray(spec.is_cat, dtype=bool))
    span = jnp.maximum(root_hi - root_lo, 1.0)
    nb_f = jnp.where(cat, jnp.minimum(span, float(nbins_cats)),
                     float(nbins)).astype(jnp.float32)
    return cfg, root_lo, root_hi, nb_f


def grow_tree_adaptive(X, g, h, w, cfg: TreeConfig, col_mask, root_lo,
                       root_hi, axis_name=None, key=None, nb_f=None,
                       mono=None, sets=None, model_axis=None):
    """Build one tree with PER-NODE ADAPTIVE uniform bins on raw features
    (H2O's default histogram_type=UniformAdaptive, hex/tree/DHistogram.java
    _min/_maxEx per-node re-binning) via the fused route+bin+histogram
    kernel (ops/hist_adaptive.py).

    X is [rows, F] float32 with NaN=NA (enum codes as floats — identity
    uniform bins reproduce ordinal enum splits). root_lo/root_hi are [F]
    global finite min/max (computed once per training run). Returns a
    tree dict with RAW split thresholds (``thr``) — no bin→threshold
    conversion at finalize, and training-time routing (x >= thr inside
    the kernel) is bit-identical to scoring-time walks.

    Child ranges narrow by the parent's split point on the split feature
    (exact) and by the parent's occupied-bin span elsewhere (within one
    bin width) — the static-shape analog of DHistogram's per-child
    min/max re-measurement.

    ``nb_f`` ([F] float, optional) gives PER-FEATURE bin counts: enums get
    nb = their root span so identity binning reproduces exact per-level
    splits up to W-1 categories (beyond that, ordinal grouping refined by
    narrowing — the nbins_cats analog)."""
    from h2o3_tpu.ops.hist_adaptive import (adaptive_level, pick_W,
                                            route_only)
    from dataclasses import replace as dc_replace

    D = cfg.max_depth
    M = cfg.n_nodes
    rows, F = X.shape
    W = pick_W(cfg.n_bins)
    # hist_kernel param: pallas/scatter honored; 'matmul' (a global-path
    # kernel name) degrades to scatter here
    method = (cfg.hist_method if cfg.hist_method in ("pallas", "scatter")
              else "scatter" if cfg.hist_method == "matmul" else "auto")
    # histogram_precision='auto': exact f32 when the frame is small
    # enough that the 1.4x hist cost is negligible, bf16 at scale.
    # Measured bound (tools/bf16_deviation.py, 2M rows, depth 8,
    # adversarial near-duplicate features): bf16 flips ~30% of split
    # choices BETWEEN statistically equivalent candidates; AUC delta
    # 2.8e-5. Deepest-level leaf values come from the same histograms,
    # so they carry the same precision choice (exact under 'float32').
    mxu_dtype = _hist_mxu_dtype(cfg, X.shape[0])
    if nb_f is None:
        nb_f = jnp.full(F, float(min(cfg.n_bins, W - 2)), jnp.float32)
    else:
        nb_f = jnp.minimum(nb_f.astype(jnp.float32), float(W - 2))
    find_cfg = dc_replace(cfg, n_bins=W - 1)  # NA lane at W-1 for _find_splits

    feat = jnp.full(M, -1, jnp.int32)
    thr_arr = jnp.zeros(M, jnp.float32)
    na_left = jnp.zeros(M, bool)
    is_split = jnp.zeros(M, bool)
    value = jnp.zeros(M, jnp.float32)
    gain_arr = jnp.zeros(M, jnp.float32)
    node_w = jnp.zeros(M, jnp.float32)

    ghw = jnp.stack([g, h, w]).astype(jnp.float32)
    nid = jnp.zeros(rows, jnp.int32)
    # per-(node, feature) ranges for the current level
    lo_d = jnp.broadcast_to(root_lo[None, :], (1, F)).astype(jnp.float32)
    hi_d = jnp.broadcast_to(root_hi[None, :], (1, F)).astype(jnp.float32)
    # previous level's split tables (root has none)
    zeros1 = jnp.zeros(1, jnp.float32)
    tables = (zeros1, zeros1, zeros1, zeros1)
    lo_b = jnp.full(1, -BIGV)          # monotone value bounds per node
    hi_b = jnp.full(1, BIGV)
    allowed = (jnp.ones((1, F), bool) if sets is not None else None)

    # histogram_type=random: per-(tree, feature) grid phase offset in
    # [0, 1) bin widths (key differs per tree → split points randomized
    # the way DHistogram.Random randomizes its bin boundaries)
    phase = None
    if cfg.random_grid and key is not None:
        phase = jax.random.uniform(jax.random.fold_in(key, 7919), (F,))

    # bandwidth-packed transpose for the pallas path: [rows, F] device
    # layout pads F to 128 lanes (~4.6x wasted HBM reads at F=28 —
    # measured in ops/hist_adaptive.py header); [F, rows] puts rows in
    # lanes. XLA hoists this loop-invariant transpose out of the per-tree
    # scan, so it costs one pass per chunk, not per level.
    on_tpu = (method == "pallas"
              or (method == "auto" and jax.default_backend() == "tpu"))
    Xt = X.T if on_tpu else None

    # OPT-IN (H2O3_HIST_I8=1/2=terms): int8 fixed-point histogram path.
    # The bare int8 MXU contraction measures 1.33x faster than bf16
    # (tools/kern_mxu_probe.py) and single-term quantization matches the
    # bf16 AUC on the bench (0.8357 vs 0.8358) — but in the FUSED kernel
    # the int8 operand build (i32 masking + i8 narrowing; Mosaic won't
    # legalize i8 muli or i1->i8-tiling selects) costs more than the MXU
    # saves: 65.7M rows/s vs 68.6M bf16 on the 10M-row bench. Kept as an
    # opt-in for future Mosaic versions with native i8 select.
    qs = None
    i8_terms = int(_os.environ.get("H2O3_HIST_I8", "0") or 0)
    if (i8_terms and on_tpu and mxu_dtype == jnp.bfloat16
            and rows <= 16_000_000):
        from h2o3_tpu.ops.hist_adaptive import quantize_ghw_i8
        qs = quantize_ghw_i8(ghw, terms=i8_terms)

    for d in range(D):
        N = 2 ** d
        base = N - 1
        if phase is not None:
            width0 = jnp.maximum(hi_d - lo_d, 0.0) / jnp.maximum(
                nb_f[None, :], 1.0)
            lo_d = lo_d - phase[None, :] * width0
        span = jnp.maximum(hi_d - lo_d, 0.0)
        inv_d = jnp.where(span > 0,
                          nb_f[None, :] / jnp.where(span > 0, span, 1.0), 0.0)
        nid, hist = adaptive_level(X, nid, ghw, tables, lo_d, inv_d,
                                   N // 2 if d else 0, N, base, W, method,
                                   mxu_dtype=mxu_dtype, xt=Xt, qs=qs)
        if axis_name is not None:
            hist = jax.lax.psum(hist, axis_name)
        trip = (hist[0], hist[1], hist[2])
        level_mask = col_mask
        mt_d = _level_mtries(cfg, d, F)
        if mt_d > 0 and key is not None:
            u = jax.random.uniform(jax.random.fold_in(key, d), (N, F))
            u = jnp.where(col_mask[None, :], u, 2.0)
            kth = jnp.sort(u, axis=1)[:, min(mt_d, F) - 1]
            level_mask = (u <= kth[:, None]) & col_mask[None, :]
        if allowed is not None:
            lm2 = level_mask if level_mask.ndim == 2 else level_mask[None, :]
            level_mask = lm2 & allowed
        bg, bf, bb, bnl, gt, ht, wt, vl_s, vr_s, wl_s, wr_s = \
            _find_splits_sharded(trip, find_cfg, level_mask, mono=mono,
                                 model_axis=model_axis)
        can = (bg > jnp.maximum(cfg.min_split_improvement, 0.0)) & (wt > 0)
        nidx = jnp.arange(N)
        lo_sel = lo_d[nidx, bf]
        inv_sel = inv_d[nidx, bf]
        # raw threshold: left ⇔ bin < t ⇔ x < lo + t/inv. Never store inf
        # (the kernel's one-hot LUT matmul turns inf·0 into NaN and
        # poisons every row's threshold at that level): a zero-span split
        # (NA-vs-finite on a constant feature) uses a huge FINITE value so
        # all finite rows still route left; non-split nodes get 0.0.
        BIG = jnp.float32(3.0e38)
        thr = jnp.where(can,
                        jnp.where(inv_sel > 0,
                                  lo_sel + bb.astype(jnp.float32)
                                  / jnp.maximum(inv_sel, 1e-30), BIG),
                        0.0)
        idx = base + nidx
        feat = feat.at[idx].set(jnp.where(can, bf, -1))
        thr_arr = thr_arr.at[idx].set(thr)
        na_left = na_left.at[idx].set(bnl)
        is_split = is_split.at[idx].set(can)
        value = value.at[idx].set(
            jnp.clip(_leaf_value(gt, ht, cfg), lo_b, hi_b))
        gain_arr = gain_arr.at[idx].set(jnp.where(can, bg, 0.0))
        node_w = node_w.at[idx].set(wt)
        if mono is not None:
            lo_b, hi_b = _child_bounds(lo_b, hi_b, vl_s, vr_s, mono[bf], can)
        else:
            lo_b = jnp.repeat(lo_b, 2)
            hi_b = jnp.repeat(hi_b, 2)
        if allowed is not None:
            allowed = _next_allowed(allowed, sets, bf, can)
        # next level's routing tables
        tables = (jnp.maximum(bf, 0).astype(jnp.float32), thr,
                  bnl.astype(jnp.float32), can.astype(jnp.float32))
        # next level's ranges: occupied-span narrowing + split-point cut
        whist = hist[2][..., :W - 1]                  # [N, F, W-1] real bins
        occ = whist > 0
        first = jnp.argmax(occ, axis=-1)              # [N, F]
        last = (W - 2) - jnp.argmax(occ[..., ::-1], axis=-1)
        width = jnp.where(inv_d > 0, 1.0 / jnp.maximum(inv_d, 1e-30), 0.0)
        lo_n = lo_d + first.astype(jnp.float32) * width
        hi_n = jnp.minimum(lo_d + (last + 1).astype(jnp.float32) * width, hi_d)
        any_occ = occ.any(axis=-1)
        lo_n = jnp.where(any_occ, lo_n, lo_d)
        hi_n = jnp.where(any_occ, hi_n, hi_d)
        fsel = (jnp.arange(F)[None, :] == bf[:, None]) & can[:, None]
        lo_left, hi_left = lo_n, jnp.where(fsel, jnp.minimum(thr[:, None], hi_n), hi_n)
        lo_right, hi_right = jnp.where(fsel, jnp.maximum(thr[:, None], lo_n), lo_n), hi_n
        lo_d = jnp.stack([lo_left, lo_right], axis=1).reshape(2 * N, F)
        hi_d = jnp.stack([hi_left, hi_right], axis=1).reshape(2 * N, F)

    # deepest level: leaf values are the LAST split level's selected
    # left/right child stats — already in the (psum'd) histograms, so the
    # final pass only needs to ROUTE rows for the margin update (a ~3x
    # cheaper kernel than a full level; with histogram_precision=float32
    # these stats are exact, with bf16 they carry the documented bound)
    if D == 0:
        # degenerate stump: one root leaf from exact totals
        g0 = g * (w > 0)
        h0 = h * (w > 0)
        gs, hs, ws = g0.sum(), h0.sum(), w.sum()
        if axis_name is not None:
            gs = jax.lax.psum(gs, axis_name)
            hs = jax.lax.psum(hs, axis_name)
            ws = jax.lax.psum(ws, axis_name)
        value = value.at[0].set(_leaf_value(gs, hs, cfg))
        node_w = node_w.at[0].set(ws)
        tree = {"feat": feat, "thr": thr_arr, "na_left": na_left,
                "is_split": is_split, "value": value, "gain": gain_arr,
                "node_w": node_w}
        return tree, nid
    ND = 2 ** D
    baseD = ND - 1
    nid = route_only(X, nid, tables, ND // 2, baseD, method, xt=Xt)
    vD = jnp.stack([vl_s, vr_s], axis=1).reshape(ND)
    wD = jnp.stack([wl_s, wr_s], axis=1).reshape(ND)
    idxD = baseD + jnp.arange(ND)
    value = value.at[idxD].set(jnp.clip(vD, lo_b, hi_b))
    node_w = node_w.at[idxD].set(wD)

    tree = {"feat": feat, "thr": thr_arr, "na_left": na_left,
            "is_split": is_split, "value": value, "gain": gain_arr,
            "node_w": node_w}
    return tree, nid


def _hist_mxu_dtype(cfg: TreeConfig, rows: int):
    """Histogram contraction precision shared by every grower:
    ``histogram_precision`` forces f32 (exact 6-pass HIGHEST) or bf16;
    'auto' picks exact f32 below 2^18 rows where the ~1.4x hist cost
    is negligible, bf16 at scale (deviation bound in
    ops/hist_adaptive.py and README)."""
    if cfg.histogram_precision in ("float32", "f32"):
        return jnp.float32
    if cfg.histogram_precision in ("bfloat16", "bf16"):
        return jnp.bfloat16
    return jnp.float32 if rows < (1 << 18) else jnp.bfloat16


def levels_per_pass(max_depth: int, n_features: int, W: int) -> int:
    """Resolve ``H2O3_LEVELS_PER_PASS`` — how many consecutive tree
    levels one fused dispatch covers in the streamed binned driver.

    - integer: clamped to [1, max_depth]; 1 is the exact old per-level
      path (one dispatch + one host sync per level);
    - unset / 'auto': VMEM-budgeted — the largest L <= 4 whose DEEPEST
      possible window keeps the sum of its live level histograms
      (3 · 2^d · F · W · 4 bytes over the window) inside half the
      kernel VMEM limit, the same ceiling the per-level accumulator
      scratch is provisioned against. L=4 everywhere practical; the
      bound only bites at extreme depth × features × W products where
      the fused executable's histogram working set would thrash.
    """
    from h2o3_tpu.ops.hist_adaptive import _VMEM_LIMIT
    D = max(1, int(max_depth))
    raw = _os.environ.get("H2O3_LEVELS_PER_PASS", "").strip().lower()
    if raw and raw != "auto":
        return max(1, min(int(raw), D))
    budget = _VMEM_LIMIT // 2
    L = 1
    while L < min(4, D):
        cand = L + 1
        top = sum(3 * (1 << d) * n_features * W * 4
                  for d in range(max(0, D - cand), D))
        if top > budget:
            break
        L = cand
    return L


def _binned_split_level(trip, find_cfg: TreeConfig, level_mask,
                        cfg: TreeConfig, mono=None, model_axis=None):
    """ONE level's split selection + the derived next-level routing
    tables, shared by every binned driver: the dense trace-time loop,
    the streamed per-level pass and the fused L-level window all run
    THIS function, so the multi-level path traces exactly the
    per-level ops and f32 bit-parity holds by construction. Returns
    (the _find_splits 11-tuple, can, tables)."""
    sel = _find_splits_sharded(trip, find_cfg, level_mask, mono=mono,
                               model_axis=model_axis, max_bin=cfg.n_bins)
    bg, bf, bb, bnl = sel[0], sel[1], sel[2], sel[3]
    wt_ = sel[6]
    can = (bg > jnp.maximum(cfg.min_split_improvement, 0.0)) & (wt_ > 0)
    # next level's routing tables: the split BIN rides where the
    # adaptive path carries a raw threshold — an exact integer-valued
    # float through the kernel's bf16-split LUT
    tables = (jnp.maximum(bf, 0).astype(jnp.float32),
              bb.astype(jnp.float32),
              bnl.astype(jnp.float32), can.astype(jnp.float32))
    return sel, can, tables


def _level_record(sel, can, cfg: TreeConfig):
    """The per-level split record the streamed drivers fetch to host —
    built on device, batched into ONE counted pytree fetch per L-level
    window (transfer-seam contract)."""
    bg, bf, bb, bnl = sel[0], sel[1], sel[2], sel[3]
    gt, ht, wt_ = sel[4], sel[5], sel[6]
    return {"feat": jnp.where(can, bf, -1), "bin": bb, "nal": bnl,
            "can": can, "val": _leaf_value(gt, ht, cfg),
            "gain": jnp.where(can, bg, 0.0), "w": wt_}


@lru_cache(maxsize=64)
def _fused_binned_window(cfg: TreeConfig, d0: int, Lw: int, W: int,
                         trans: bool, mxu_name: str):
    """ONE jitted executable running ``Lw`` consecutive binned levels:
    route + histogram + split selection + next-level tables, unrolled
    Lw times at trace time exactly like the dense grower's loop. The
    packed codes operand is read once per window, ``nid`` and the
    routing tables carry on-device between levels, and the host syncs
    only on the window-boundary record fetch — eliminating per-level
    dispatch overhead and per-level nid round-trips. Each level's body
    is the streamed per-level pass verbatim (binned_level +
    _binned_split_level + _level_record), so f32 multi-level trees are
    bit-identical to the per-level path. lru-cached per (cfg, window,
    layout): a warm retrain reuses the executable (zero-recompile
    guard)."""
    from dataclasses import replace as dc_replace

    from h2o3_tpu.ops.hist_adaptive import binned_level
    find_cfg = dc_replace(cfg, n_bins=W - 1)
    mxu_dtype = jnp.float32 if mxu_name == "float32" else jnp.bfloat16

    def window(x, nid, ghw, tables, col_mask):
        recs = []
        for j in range(Lw):
            d = d0 + j
            N = 1 << d
            nid, hist = binned_level(
                None if trans else x, nid, ghw, tables,
                N // 2 if d else 0, N, N - 1, W,
                mxu_dtype=mxu_dtype, ct=x if trans else None)
            sel, can, tables = _binned_split_level(
                (hist[0], hist[1], hist[2]), find_cfg, col_mask, cfg)
            recs.append(_level_record(sel, can, cfg))
        return nid, recs, tables

    return jax.jit(window)


def grow_tree_binned(codes_rm, g, h, w, cfg: TreeConfig, col_mask,
                     axis_name=None, key=None, mono=None, sets=None,
                     model_axis=None, ct=None):
    """Build one tree on PACKED global-sketch bin codes — the
    XGBoost ``tree_method=hist`` shape made TPU-native: features are
    binned ONCE per train (ops/binning.pack_codes), the int8/int16
    code matrix is the representation the hot loop computes on, split
    thresholds thread through the levels as BIN INDICES, and finalize
    unbins to raw thresholds (bins_to_thresholds_stacked reads
    ``tree["split_bin"]``).

    ``codes_rm`` is [rows, F] int8/int16 with NA = the reserved bin
    W-1; ``ct`` is the pre-transposed [F, rows_p] pallas operand
    (pad = W-1). cfg.n_bins is the REAL bin count (codes in
    [0, n_bins-1]); the kernel lane width is W = pick_W(n_bins) and
    the split search scans W-1 real lanes with the NA lane at W-1
    (lanes beyond n_bins are empty; a selected split bin past the
    edge list unbins to +inf = all non-NA left).

    Per level the fused binned kernel routes rows by integer
    code-vs-bin compare and builds the histogram one-hot straight off
    the codes — no lo/inv rebinning anywhere, so the hot loop moves
    1-2 bytes/value instead of 4."""
    from h2o3_tpu.ops.hist_adaptive import (binned_level,
                                            binned_route_only,
                                            pallas_interpret, pick_W)
    from dataclasses import replace as dc_replace

    D = cfg.max_depth
    M = cfg.n_nodes
    rows, F = codes_rm.shape
    W = pick_W(cfg.n_bins)
    method = (cfg.hist_method if cfg.hist_method in ("pallas", "scatter")
              else "scatter" if cfg.hist_method == "matmul" else "auto")
    mxu_dtype = _hist_mxu_dtype(cfg, rows)
    find_cfg = dc_replace(cfg, n_bins=W - 1)   # NA lane at W-1

    feat = jnp.full(M, -1, jnp.int32)
    split_bin = jnp.zeros(M, jnp.int32)
    na_left = jnp.zeros(M, bool)
    is_split = jnp.zeros(M, bool)
    value = jnp.zeros(M, jnp.float32)
    gain_arr = jnp.zeros(M, jnp.float32)
    node_w = jnp.zeros(M, jnp.float32)

    ghw = jnp.stack([g, h, w]).astype(jnp.float32)
    nid = jnp.zeros(rows, jnp.int32)
    zeros1 = jnp.zeros(1, jnp.float32)
    tables = (zeros1, zeros1, zeros1, zeros1)
    lo_b = jnp.full(1, -BIGV)
    hi_b = jnp.full(1, BIGV)
    allowed = (jnp.ones((1, F), bool) if sets is not None else None)

    on_tpu = (method == "pallas"
              or (method == "auto" and (jax.default_backend() == "tpu"
                                        or pallas_interpret())))
    # opt-in int8-ghw fixed-point contraction — same contract as the
    # adaptive path (H2O3_HIST_I8=1/2=terms, ops/hist_adaptive.py)
    qs = None
    i8_terms = int(_os.environ.get("H2O3_HIST_I8", "0") or 0)
    if (i8_terms and on_tpu and mxu_dtype == jnp.bfloat16
            and rows <= 16_000_000):
        from h2o3_tpu.ops.hist_adaptive import quantize_ghw_i8
        qs = quantize_ghw_i8(ghw, terms=i8_terms)

    if D == 0:
        g0 = g * (w > 0)
        h0 = h * (w > 0)
        gs, hs, ws = g0.sum(), h0.sum(), w.sum()
        if axis_name is not None:
            gs = jax.lax.psum(gs, axis_name)
            hs = jax.lax.psum(hs, axis_name)
            ws = jax.lax.psum(ws, axis_name)
        value = value.at[0].set(_leaf_value(gs, hs, cfg))
        node_w = node_w.at[0].set(ws)
        tree = {"feat": feat, "split_bin": split_bin, "na_left": na_left,
                "is_split": is_split, "value": value, "gain": gain_arr,
                "node_w": node_w}
        return tree, nid

    vl_s = vr_s = wl_s = wr_s = None
    for d in range(D):
        N = 2 ** d
        base = N - 1
        nid, hist = binned_level(codes_rm, nid, ghw, tables,
                                 N // 2 if d else 0, N, base, W, method,
                                 mxu_dtype=mxu_dtype, ct=ct, qs=qs)
        if axis_name is not None:
            hist = jax.lax.psum(hist, axis_name)
        trip = (hist[0], hist[1], hist[2])
        level_mask = col_mask
        mt_d = _level_mtries(cfg, d, F)
        if mt_d > 0 and key is not None:
            u = jax.random.uniform(jax.random.fold_in(key, d), (N, F))
            u = jnp.where(col_mask[None, :], u, 2.0)
            kth = jnp.sort(u, axis=1)[:, min(mt_d, F) - 1]
            level_mask = (u <= kth[:, None]) & col_mask[None, :]
        if allowed is not None:
            lm2 = level_mask if level_mask.ndim == 2 else level_mask[None, :]
            level_mask = lm2 & allowed
        sel, can, tables = _binned_split_level(trip, find_cfg, level_mask,
                                               cfg, mono=mono,
                                               model_axis=model_axis)
        bg, bf, bb, bnl, gt, ht, wt, vl_s, vr_s, wl_s, wr_s = sel
        nidx = jnp.arange(N)
        idx = base + nidx
        feat = feat.at[idx].set(jnp.where(can, bf, -1))
        split_bin = split_bin.at[idx].set(bb)
        na_left = na_left.at[idx].set(bnl)
        is_split = is_split.at[idx].set(can)
        value = value.at[idx].set(
            jnp.clip(_leaf_value(gt, ht, cfg), lo_b, hi_b))
        gain_arr = gain_arr.at[idx].set(jnp.where(can, bg, 0.0))
        node_w = node_w.at[idx].set(wt)
        if mono is not None:
            lo_b, hi_b = _child_bounds(lo_b, hi_b, vl_s, vr_s, mono[bf], can)
        else:
            lo_b = jnp.repeat(lo_b, 2)
            hi_b = jnp.repeat(hi_b, 2)
        if allowed is not None:
            allowed = _next_allowed(allowed, sets, bf, can)

    # deepest level: route, then EXACT per-leaf (g,h,w) segment totals —
    # the same tail as grow_tree, so packed and unpacked f32 trees are
    # bit-identical INCLUDING leaf values (and under bf16 the leaves
    # stay exact, like the reference's driver-side leaf stats; the
    # totals matmul is tiny next to a level kernel)
    ND = 2 ** D
    baseD = ND - 1
    nid = binned_route_only(codes_rm, nid, tables, ND // 2, baseD, W,
                            method, ct=ct)
    localD = nid - baseD
    inD = (localD >= 0) & (localD < ND)
    lidD = jnp.clip(localD, 0, ND - 1)
    gD, hD, wD = _segment_totals(lidD, inD, g, h, w, ND)
    if axis_name is not None:
        gD = jax.lax.psum(gD, axis_name)
        hD = jax.lax.psum(hD, axis_name)
        wD = jax.lax.psum(wD, axis_name)
    idxD = baseD + jnp.arange(ND)
    value = value.at[idxD].set(
        jnp.clip(_leaf_value(gD, hD, cfg), lo_b, hi_b))
    node_w = node_w.at[idxD].set(wD)

    tree = {"feat": feat, "split_bin": split_bin, "na_left": na_left,
            "is_split": is_split, "value": value, "gain": gain_arr,
            "node_w": node_w}
    return tree, nid


def predict_raw_tree(X, tree, max_depth: int):
    """Walk ONE tree (dict of [M] arrays with raw ``thr``) over raw
    features; used for validation-margin updates inside the training
    chunk. Returns (leaf values [rows], nid)."""
    rows = X.shape[0]
    nid = jnp.zeros(rows, jnp.int32)
    for _ in range(max_depth):
        f = jnp.maximum(tree["feat"], 0)[nid]
        s = tree["is_split"][nid]
        th = tree["thr"][nid]
        nl = tree["na_left"][nid]
        xv = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
        go_right = jnp.where(jnp.isnan(xv), ~nl, xv >= th)
        nid = jnp.where(s, 2 * nid + 1 + go_right.astype(jnp.int32), nid)
    return tree["value"][nid], nid


def grow_tree_spmd(codes, g, h, w, cfg: TreeConfig, col_mask,
                   data_axis: str = "data", model_axis: str = "model"):
    """Fully-sharded tree build for multi-chip meshes: rows over the
    'data' axis AND features over the 'model' axis.

    Runs inside shard_map with in_specs codes=P(data, model), g/h/w/
    col_mask sharded accordingly. Per level:
      1. each shard builds histograms for its (row-block × feature-block);
      2. psum over the data axis → complete histograms for local features
         (the ICI all-reduce replacing Rabit / the MRTask reduce tree);
      3. local split finding, then an all_gather + argmax over the model
         axis picks the global best split per node (features never move);
      4. row routing: the model-shard owning the winning feature computes
         the children for its nodes; a psum over the model axis broadcasts
         the routing to all feature shards (rows are replicated across the
         model axis, so this is a small [rows] exchange).

    The reference has no feature-axis sharding at all (SURVEY.md §5) —
    every JVM node holds all columns of its rows; this is where the TPU
    design scales wider data than the reference can.
    """
    from h2o3_tpu.ops.histogram import build_histograms

    D = cfg.max_depth
    M = cfg.n_nodes
    B1 = cfg.n_bins + 1
    rows, F_loc = codes.shape
    midx = jax.lax.axis_index(model_axis)
    n_model = _axis_size(model_axis)

    feat = jnp.full(M, -1, jnp.int32)
    split_bin = jnp.zeros(M, jnp.int32)
    na_left = jnp.zeros(M, bool)
    is_split = jnp.zeros(M, bool)
    value = jnp.zeros(M, jnp.float32)

    ghw = jnp.stack([g, h, w]).astype(jnp.float32)
    nid = jnp.zeros(rows, jnp.int32)
    for d in range(D):
        base = 2 ** d - 1
        N = 2 ** d
        local = nid - base
        in_level = (local >= 0) & (local < N)
        lid = jnp.clip(local, 0, N - 1)
        seg = jnp.where(in_level, local, -1)
        hist = build_histograms(codes, seg, ghw, N, B1, cfg.hist_method)
        hist = jax.lax.psum(hist, data_axis)
        (bg, bf, bb, bnl, gt, ht, wt,
         _vl, _vr, _wl, _wr) = _find_splits(hist, cfg, col_mask)
        # global best over the model axis
        cand = jnp.stack([bg, (midx * F_loc + bf).astype(jnp.float32),
                          bb.astype(jnp.float32), bnl.astype(jnp.float32)], 1)
        allc = jax.lax.all_gather(cand, model_axis)          # [n_model, N, 4]
        winner = jnp.argmax(allc[:, :, 0], axis=0)           # [N]
        sel = jnp.take_along_axis(allc, winner[None, :, None], axis=0)[0]
        gbg, gbf, gbb, gbnl = sel[:, 0], sel[:, 1].astype(jnp.int32), \
            sel[:, 2].astype(jnp.int32), sel[:, 3] > 0.5
        can = (gbg > jnp.maximum(cfg.min_split_improvement, 0.0)) & (wt > 0)
        idx = base + jnp.arange(N)
        feat = feat.at[idx].set(jnp.where(can, gbf, -1))
        split_bin = split_bin.at[idx].set(gbb)
        na_left = na_left.at[idx].set(gbnl)
        is_split = is_split.at[idx].set(can)
        value = value.at[idx].set(_leaf_value(gt, ht, cfg))
        # routing: owner shard of each node's feature computes children
        node_feat_g = gbf[lid]
        owner = node_feat_g // F_loc
        node_feat_l = node_feat_g % F_loc
        node_bin = gbb[lid]
        node_nal = gbnl[lid]
        node_can = can[lid]
        c = jnp.take_along_axis(codes, node_feat_l[:, None], axis=1)[:, 0]
        c = c.astype(jnp.int32)
        is_na = c == cfg.n_bins
        go_right = jnp.where(is_na, ~node_nal, c >= node_bin)
        child = 2 * nid + 1 + go_right.astype(jnp.int32)
        mine = (owner == midx) & in_level & node_can
        routed = jnp.where(mine, child, 0)
        routed = jax.lax.psum(routed, model_axis)
        nid = jnp.where(in_level & node_can, routed, nid)

    baseD = 2 ** D - 1
    localD = nid - baseD
    inD = (localD >= 0) & (localD < 2 ** D)
    lidD = jnp.clip(localD, 0, 2 ** D - 1)
    gD = jnp.zeros(2 ** D, jnp.float32).at[lidD].add(jnp.where(inD, g, 0.0))
    hD = jnp.zeros(2 ** D, jnp.float32).at[lidD].add(jnp.where(inD, h, 0.0))
    gD = jax.lax.psum(gD, data_axis)
    hD = jax.lax.psum(hD, data_axis)
    idxD = baseD + jnp.arange(2 ** D)
    value = value.at[idxD].set(_leaf_value(gD, hD, cfg))

    tree = {"feat": feat, "split_bin": split_bin, "na_left": na_left,
            "is_split": is_split, "value": value}
    return tree, nid


def _segment_totals(lid, valid, g, h, w, n_seg: int):
    """Per-node (g,h,w) sums. One-hot matmul for small node counts (TPU
    scatter-add costs ~20ms/1M rows; the matmul is <1ms), scatter beyond."""
    if n_seg <= 256:
        oh = (lid[:, None] == jnp.arange(n_seg)[None, :]).astype(jnp.float32)
        ghw = jnp.stack([jnp.where(valid, g, 0.0), jnp.where(valid, h, 0.0),
                         jnp.where(valid, w, 0.0)], axis=1)
        tot = jax.lax.dot_general(oh, ghw, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return tot[:, 0], tot[:, 1], tot[:, 2]
    gD = jnp.zeros(n_seg, jnp.float32).at[lid].add(jnp.where(valid, g, 0.0))
    hD = jnp.zeros(n_seg, jnp.float32).at[lid].add(jnp.where(valid, h, 0.0))
    wD = jnp.zeros(n_seg, jnp.float32).at[lid].add(jnp.where(valid, w, 0.0))
    return gD, hD, wD


def predict_binned(codes, tree, max_depth: int, na_bin: int):
    """Prediction on a binned matrix (leaf lookup); one packed-word gather
    per level (see grow_tree routing)."""
    from h2o3_tpu.ops.binning import CodesView
    rm = codes.rm if isinstance(codes, CodesView) else codes
    rows = rm.shape[0]
    word = (jnp.maximum(tree["feat"], 0)
            | (tree["split_bin"] << BIN_SHIFT)
            | (tree["na_left"].astype(jnp.int32) << NA_SHIFT)
            | (tree["is_split"].astype(jnp.int32) << SPLIT_SHIFT))
    nid = jnp.zeros(rows, jnp.int32)
    for _ in range(max_depth):
        rw = word[nid]
        f = rw & FEAT_MASK
        b = (rw >> BIN_SHIFT) & BIN_MASK
        nl = ((rw >> NA_SHIFT) & 1).astype(bool)
        s = ((rw >> SPLIT_SHIFT) & 1).astype(bool)
        c = jnp.take_along_axis(rm, f[:, None], axis=1)[:, 0]
        c = c.astype(jnp.int32)
        is_na = c == na_bin
        go_right = jnp.where(is_na, ~nl, c >= b)
        nid = jnp.where(s, 2 * nid + 1 + go_right.astype(jnp.int32), nid)
    return tree["value"][nid], nid


def predict_raw_stacked(X, feat, thr, na_left, is_split, value, max_depth: int):
    """Scoring-time prediction on raw features for a stack of T trees.

    feat/thr/... are [T, M]; X is [rows, F] float32 with NaN=NA.
    Returns [rows, T] per-tree contributions; caller sums/weights.
    The descent is T*D gathers — the score0 analog (hex/Model.java:2304,
    GBM: walk CompressedTrees) vectorized over rows and trees."""
    rows = X.shape[0]

    def one_tree(carry, t):
        nid = jnp.zeros(rows, jnp.int32)
        for _ in range(max_depth):
            f = feat[t][nid]
            s = is_split[t][nid]
            th = thr[t][nid]
            nl = na_left[t][nid]
            x = jnp.take_along_axis(X, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
            go_right = jnp.where(jnp.isnan(x), ~nl, x >= th)
            nid = jnp.where(s, 2 * nid + 1 + go_right.astype(jnp.int32), nid)
        return carry, value[t][nid]

    _, contribs = jax.lax.scan(one_tree, None, jnp.arange(feat.shape[0]))
    return contribs.T  # [rows, T]


def bins_to_thresholds(tree_split_bin: np.ndarray, tree_feat: np.ndarray,
                       edges: List[np.ndarray]) -> np.ndarray:
    """Convert bin-space splits to raw-value thresholds for scoring:
    left ⇔ code < t ⇔ raw < edges[feat][t-1]."""
    M = tree_split_bin.shape[0]
    thr = np.zeros(M, dtype=np.float32)
    for m in range(M):
        f = tree_feat[m]
        if f < 0:
            continue
        e = edges[f]
        t = tree_split_bin[m]
        if len(e) == 0 or t - 1 >= len(e):
            # t > E is reachable when a feature has fewer unique edges than
            # nbins: all non-NA rows go left, only NA can go right. Clamping
            # to e[-1] (the old behaviour) misrouted rows >= e[-1] into the
            # NA branch at scoring time.
            thr[m] = np.inf
        else:
            thr[m] = e[t - 1]
    return thr


def bins_to_thresholds_stacked(split_bin: np.ndarray, feat: np.ndarray,
                               edges: List[np.ndarray]) -> np.ndarray:
    """Vectorized bin→raw-threshold conversion for a whole [T, M] tree
    stack at once (the per-node Python loop in :func:`bins_to_thresholds`
    costs ~T·M dict/branch steps at finalize; this is three numpy
    gathers). Semantics identical: non-split nodes → 0, split bins past
    a feature's edge list → +inf (all non-NA left)."""
    if not edges:
        return np.zeros_like(split_bin, dtype=np.float32)
    emax = max((len(e) for e in edges), default=0)
    emat = np.full((len(edges), max(emax, 1)), np.inf, dtype=np.float32)
    elen = np.zeros(len(edges), dtype=np.int64)
    for f, e in enumerate(edges):
        emat[f, : len(e)] = e
        elen[f] = len(e)
    fidx = np.maximum(feat, 0)
    t = split_bin.astype(np.int64)
    over = (t - 1) >= elen[fidx]
    thr = emat[fidx, np.clip(t - 1, 0, max(emax - 1, 0))]
    thr = np.where(over, np.float32(np.inf), thr)
    return np.where(feat < 0, np.float32(0.0), thr).astype(np.float32)


# chunk-length buckets (shared GBM/DRF): single-shot chunk lengths (the
# whole-train chunk, a final partial interval) round UP to the next
# bucket with the tail trees masked via the traced n_active (their
# compute is wasted and finalize drops them — bounded to ONE chunk per
# train, ≤ ~25% of that chunk's scan; REPEATED lengths like a full
# score interval compile exact instead, see the GBM loop). Grid/AutoML
# ntrees variants landing in the same bucket reuse the executable (and
# its persistent-compile-cache entry) instead of compiling one scan per
# distinct remainder.
CHUNK_BUCKETS = (1, 2, 3, 4, 5, 8, 10, 13, 16, 20, 25, 32, 40, 50)


def chunk_bucket(c: int) -> int:
    """Smallest bucket >= c."""
    for b in CHUNK_BUCKETS:
        if b >= c:
            return b
    # beyond 50 (an over-50 score_tree_interval): next multiple of 10
    # keeps the masked-tail waste under ~20% of a chunk
    return -(-c // 10) * 10


def collect_chunk_trees(all_trees, M: int, edges) -> dict:
    """Shared GBM/DRF finalize front half: ONE pytree ``device_get`` of
    the ``[(stacked chunk trees, n_active), ...]`` list, padding-bucket
    tail slicing, and the bin→raw-threshold conversion. Returns host
    arrays [T_active·K, M] keyed feat/na_left/is_split/value/gain/
    node_w/thr."""
    from h2o3_tpu import telemetry
    host = telemetry.device_get([t for t, _ in all_trees],
                                pipeline="train")
    acts = [n for _, n in all_trees]

    def cat(kk):
        return np.concatenate(
            [np.asarray(t[kk])[:n].reshape(-1, M)
             for t, n in zip(host, acts)])

    out = {k: cat(k) for k in ("feat", "na_left", "is_split", "value",
                               "gain", "node_w")}
    if "thr" in host[0]:
        # adaptive path: raw thresholds straight from the grower
        out["thr"] = cat("thr")
    else:
        out["thr"] = bins_to_thresholds_stacked(cat("split_bin"),
                                                out["feat"], edges)
    return out


def _streamed_stump(chunks, dist, lr, cfg: TreeConfig):
    """Depth-0 streamed tree shared by the adaptive and binned streamed
    growers: exact (g,h,w) totals over chunks -> one root leaf, applied
    without ever uploading X (need_x=False passes)."""
    from h2o3_tpu import telemetry
    gs = hs = ws = 0.0
    for ch in chunks.level_pass(need_x=False):
        ghw = ch.ghw(dist)
        # ONE counted fetch of the three chunk scalars
        s3 = telemetry.device_get(
            (ghw[0].sum(), ghw[1].sum(), ghw[2].sum()),
            pipeline="train")
        gs += float(s3[0])
        hs += float(s3[1])
        ws += float(s3[2])
    v0 = float(telemetry.device_get(
        _leaf_value(jnp.float32(gs), jnp.float32(hs), cfg),
        pipeline="train"))
    tree = {"feat": np.full(1, -1, np.int32),
            "thr": np.zeros(1, np.float32),
            "na_left": np.zeros(1, bool),
            "is_split": np.zeros(1, bool),
            "value": np.array([v0], np.float32),
            "gain": np.zeros(1, np.float32),
            "node_w": np.array([ws], np.float32)}
    v0_dev = jnp.asarray(np.array([v0], np.float32))
    for ch in chunks.level_pass(need_x=False):
        ch.apply_leaf(jnp.float32(lr), v0_dev,
                      jnp.zeros(ch.e - ch.s, jnp.int32))
    return tree


def grow_tree_adaptive_streamed(chunks, dist, lr, cfg: TreeConfig,
                                root_lo, root_hi, nb_f, key=None,
                                sample_rate: float = 1.0,
                                col_mask=None):
    """Host-chunked adaptive tree build for frames beyond the device
    budget (the memman streaming mode; water/Cleaner.java graceful
    degradation). Semantics match grow_tree_adaptive with per-node
    adaptive bins; rows stream through the SAME level kernels via the
    ``chunks`` manager (models/streaming.py StreamedChunks):

    - chunks inside the budget's RESIDENT window keep X on device for
      the whole train — uploaded once per train, not once per level
      (the old path re-uploaded every chunk every level);
    - overflow chunks double-buffer: chunk k+1's upload is issued while
      chunk k's level kernel runs;
    - per-level histograms accumulate across chunks (the psum analog is
      a device '+'), and resident chunks' margins update ON DEVICE with
      the dense chunk body's f32 arithmetic — a fully-resident streamed
      train is bit-identical to the dense grower on one chunk.

    Returns the tree dict of [M] numpy arrays with raw thresholds; the
    updated margins live in ``chunks`` (``gather_margin()`` at the end
    of training)."""
    from h2o3_tpu.ops.hist_adaptive import adaptive_level, pick_W, route_only

    rows, F = chunks.rows, chunks.F
    D = cfg.max_depth
    M = cfg.n_nodes
    W = pick_W(cfg.n_bins)
    if nb_f is None:
        nb_f = jnp.full(F, float(min(cfg.n_bins, W - 2)), jnp.float32)
    else:
        nb_f = jnp.minimum(jnp.asarray(nb_f, jnp.float32), float(W - 2))
    from dataclasses import replace as dc_replace
    find_cfg = dc_replace(cfg, n_bins=W - 1)
    if col_mask is None:
        col_mask = jnp.ones(F, bool)
    # histogram contraction precision: same rule as the dense grower,
    # sized by the frame's PADDED row count like the dense path's
    # X.shape[0] so the choice agrees at the 2^18 boundary
    mxu_dtype = _hist_mxu_dtype(cfg, chunks.padded_rows)

    chunks.begin_tree(key, sample_rate)

    if D == 0:
        # degenerate stump (the dense grower's D==0 branch)
        return _streamed_stump(chunks, dist, lr, cfg)

    feat = np.full(M, -1, np.int32)
    thr_arr = np.zeros(M, np.float32)
    na_left = np.zeros(M, bool)
    is_split = np.zeros(M, bool)
    value = np.zeros(M, np.float32)
    gain_arr = np.zeros(M, np.float32)
    node_w = np.zeros(M, np.float32)

    lo_d = jnp.broadcast_to(jnp.asarray(root_lo)[None, :], (1, F)
                            ).astype(jnp.float32)
    hi_d = jnp.broadcast_to(jnp.asarray(root_hi)[None, :], (1, F)
                            ).astype(jnp.float32)
    zeros1 = jnp.zeros(1, jnp.float32)
    tables = (zeros1, zeros1, zeros1, zeros1)
    vl_s = vr_s = wl_s = wr_s = None

    for d in range(D):
        N = 2 ** d
        base = N - 1
        span = jnp.maximum(hi_d - lo_d, 0.0)
        inv_d = jnp.where(span > 0,
                          nb_f[None, :] / jnp.where(span > 0, span, 1.0),
                          0.0)
        hist = None
        perf_acc = getattr(chunks, "perf_acc", None)
        for ch in chunks.level_pass():
            ghw = ch.ghw(dist)
            nid2, h_c = adaptive_level(ch.X, ch.nid, ghw, tables, lo_d,
                                       inv_d, N // 2 if d else 0, N, base,
                                       W, mxu_dtype=mxu_dtype)
            if perf_acc is not None:
                # streamed-level jit seam (ISSUE 11): one trace+lower
                # per (chunk shape, level) key; every later chunk/tree
                # hitting the same shape pays a dict lookup. The
                # capture wall is noted on the accumulator so cold
                # windows surface it as a caveat next to their MFU.
                import time as _time
                from functools import partial as _partial

                from h2o3_tpu.telemetry import costmodel
                t_cap0 = _time.perf_counter()
                perf_acc.add(costmodel.traced_cost(
                    ("gbm.stream_level", ch.X.shape, int(N), int(W),
                     str(mxu_dtype.__name__)),
                    _partial(adaptive_level, n_prev=N // 2 if d else 0,
                             n_nodes=N, level_base=base, W=W,
                             mxu_dtype=mxu_dtype),
                    ch.X, ch.nid, ghw, tables, lo_d, inv_d))
                perf_acc.note_capture_seconds(
                    _time.perf_counter() - t_cap0)
            ch.put_nid(nid2)
            hist = h_c if hist is None else hist + h_c
        trip = (hist[0], hist[1], hist[2])
        bg, bf, bb, bnl, gt, ht, wt_, vl_s, vr_s, wl_s, wr_s = _find_splits(
            trip, find_cfg, col_mask)
        can = (bg > jnp.maximum(cfg.min_split_improvement, 0.0)) & (wt_ > 0)
        nidx = jnp.arange(N)
        lo_sel = lo_d[nidx, bf]
        inv_sel = inv_d[nidx, bf]
        BIG = jnp.float32(3.0e38)
        thr = jnp.where(can,
                        jnp.where(inv_sel > 0,
                                  lo_sel + bb.astype(jnp.float32)
                                  / jnp.maximum(inv_sel, 1e-30), BIG), 0.0)
        idx = base + np.arange(N)
        # ONE counted pytree fetch per level (these were seven raw
        # device_gets — transfer-seam burn-down)
        from h2o3_tpu import telemetry
        lvl = telemetry.device_get(
            {"feat": jnp.where(can, bf, -1), "thr": thr, "nal": bnl,
             "can": can, "val": _leaf_value(gt, ht, cfg),
             "gain": jnp.where(can, bg, 0.0), "w": wt_},
            pipeline="train")
        feat[idx] = np.asarray(lvl["feat"])
        thr_arr[idx] = np.asarray(lvl["thr"])
        na_left[idx] = np.asarray(lvl["nal"])
        is_split[idx] = np.asarray(lvl["can"])
        value[idx] = np.asarray(lvl["val"])
        gain_arr[idx] = np.asarray(lvl["gain"])
        node_w[idx] = np.asarray(lvl["w"])
        tables = (jnp.maximum(bf, 0).astype(jnp.float32), thr,
                  bnl.astype(jnp.float32), can.astype(jnp.float32))
        whist = hist[2][..., :W - 1]
        occ = whist > 0
        first = jnp.argmax(occ, axis=-1)
        last = (W - 2) - jnp.argmax(occ[..., ::-1], axis=-1)
        width = jnp.where(inv_d > 0, 1.0 / jnp.maximum(inv_d, 1e-30), 0.0)
        lo_n = lo_d + first.astype(jnp.float32) * width
        hi_n = jnp.minimum(lo_d + (last + 1).astype(jnp.float32) * width,
                           hi_d)
        any_occ = occ.any(axis=-1)
        lo_n = jnp.where(any_occ, lo_n, lo_d)
        hi_n = jnp.where(any_occ, hi_n, hi_d)
        fsel = (jnp.arange(F)[None, :] == bf[:, None]) & can[:, None]
        lo_left, hi_left = lo_n, jnp.where(
            fsel, jnp.minimum(thr[:, None], hi_n), hi_n)
        lo_right, hi_right = jnp.where(
            fsel, jnp.maximum(thr[:, None], lo_n), lo_n), hi_n
        lo_d = jnp.stack([lo_left, lo_right], axis=1).reshape(2 * N, F)
        hi_d = jnp.stack([hi_left, hi_right], axis=1).reshape(2 * N, F)

    # deepest level: route chunks, leaf values from last selected splits
    ND = 2 ** D
    baseD = ND - 1
    from h2o3_tpu import telemetry
    vD_h, wD = (np.asarray(v) for v in telemetry.device_get(
        (jnp.stack([vl_s, vr_s], axis=1).reshape(ND),
         jnp.stack([wl_s, wr_s], axis=1).reshape(ND)), pipeline="train"))
    value[baseD:] = vD_h
    node_w[baseD:] = wD
    tree = {"feat": feat, "thr": thr_arr, "na_left": na_left,
            "is_split": is_split, "value": value, "gain": gain_arr,
            "node_w": node_w}
    # final route + margin update: one fused device pass per chunk (the
    # deepest values stay on device — same f32 gather+FMA as the dense
    # chunk body's `margin + lr_t * tree["value"][nid]`)
    value_dev = jnp.asarray(value)
    lr_t = jnp.float32(lr)
    for ch in chunks.level_pass():
        nid2 = route_only(ch.X, ch.nid, tables, ND // 2, baseD)
        ch.apply_leaf(lr_t, value_dev, nid2)
    return tree


def grow_tree_binned_streamed(chunks, dist, lr, cfg: TreeConfig, edges,
                              key=None, sample_rate: float = 1.0,
                              col_mask=None):
    """Host-chunked PACKED tree build: the streamed counterpart of
    :func:`grow_tree_binned`. The resident-window representation is the
    int8/int16 CODE matrix (models/streaming.py ``packed_W`` mode), so
    the memman budget fits ~4x more rows resident than f32 X and
    overflow-chunk H2D moves codes, not floats. Split thresholds
    thread as bin indices; the returned tree carries RAW thresholds
    (unbinned from ``edges`` here, once, at tree end) so the streamed
    caller's finalize shape matches the adaptive streamed grower's."""
    from h2o3_tpu import telemetry
    from h2o3_tpu.ops.hist_adaptive import (binned_level,
                                            binned_route_only, pick_W)
    from dataclasses import replace as dc_replace

    rows, F = chunks.rows, chunks.F
    D = cfg.max_depth
    M = cfg.n_nodes
    W = pick_W(cfg.n_bins)
    assert chunks.packed_W == W, (chunks.packed_W, W)
    find_cfg = dc_replace(cfg, n_bins=W - 1)
    if col_mask is None:
        col_mask = jnp.ones(F, bool)
    mxu_dtype = _hist_mxu_dtype(cfg, chunks.padded_rows)

    chunks.begin_tree(key, sample_rate)

    if D == 0:
        return _streamed_stump(chunks, dist, lr, cfg)

    feat = np.full(M, -1, np.int32)
    sbin_arr = np.zeros(M, np.int32)
    na_left = np.zeros(M, bool)
    is_split = np.zeros(M, bool)
    value = np.zeros(M, np.float32)
    gain_arr = np.zeros(M, np.float32)
    node_w = np.zeros(M, np.float32)

    zeros1 = jnp.zeros(1, jnp.float32)
    tables = (zeros1, zeros1, zeros1, zeros1)
    trans = chunks.kernel_layout == "t"
    perf_acc = getattr(chunks, "perf_acc", None)

    # L-level fused windows (ISSUE 17): H2O3_LEVELS_PER_PASS levels per
    # host round-trip. A single-chunk window runs ONE jitted dispatch
    # covering all its levels (codes tile-resident, nid + routing
    # tables on-chip, split selection between passes in the same
    # executable); a multi-chunk window keeps the per-level chunk loop
    # (the cross-chunk histogram reduction is a real barrier) but
    # still batches every level's split-record fetch into one sync at
    # the window boundary. L=1 is the exact old path.
    L = levels_per_pass(D, F, W)
    d = 0
    while d < D:
        Lw = min(L, D - d)
        if Lw > 1 and chunks.interrupt_pending():
            # PR-15 chunk-commit contract: a pending cancel/preempt
            # clamps the window so the cooperative yield lands at the
            # NEXT level boundary, not L levels later
            Lw = 1
        if Lw > 1 and chunks.C == 1:
            win = _fused_binned_window(cfg, d, Lw, W, trans,
                                       str(mxu_dtype.__name__))
            recs = None
            for ch in chunks.level_pass():
                ghw = ch.ghw(dist)
                if perf_acc is not None:
                    # streamed-window jit seam: one trace+lower per
                    # (chunk shape, window) key — the captured bytes
                    # show the codes operand read ONCE per Lw levels
                    import time as _time

                    from h2o3_tpu.telemetry import costmodel
                    t_cap0 = _time.perf_counter()
                    perf_acc.add(costmodel.traced_cost(
                        ("gbm.stream_window_binned", ch.X.shape,
                         int(d), int(Lw), int(W),
                         str(mxu_dtype.__name__)),
                        win, ch.X, ch.nid, ghw, tables, col_mask))
                    perf_acc.note_capture_seconds(
                        _time.perf_counter() - t_cap0)
                nid2, recs, tables = win(ch.X, ch.nid, ghw, tables,
                                         col_mask)
                ch.put_nid(nid2)
        else:
            recs = []
            for j in range(Lw):
                dd = d + j
                N = 2 ** dd
                base = N - 1
                hist = None
                for ch in chunks.level_pass():
                    ghw = ch.ghw(dist)
                    rm_arg = None if trans else ch.X
                    ct_arg = ch.X if trans else None
                    nid2, h_c = binned_level(rm_arg, ch.nid, ghw, tables,
                                             N // 2 if dd else 0, N, base,
                                             W, mxu_dtype=mxu_dtype,
                                             ct=ct_arg)
                    if perf_acc is not None:
                        # streamed-level jit seam, binned flavour: one
                        # trace+lower per (chunk shape, level) key — the
                        # captured bytes carry the packed
                        # representation's 1-2 byte/value traffic
                        import time as _time
                        from functools import partial as _partial

                        from h2o3_tpu.telemetry import costmodel
                        t_cap0 = _time.perf_counter()
                        perf_acc.add(costmodel.traced_cost(
                            ("gbm.stream_level_binned", ch.X.shape,
                             int(N), int(W), str(mxu_dtype.__name__)),
                            _partial(binned_level,
                                     n_prev=N // 2 if dd else 0,
                                     n_nodes=N, level_base=base, W=W,
                                     mxu_dtype=mxu_dtype),
                            rm_arg, ch.nid, ghw, tables, ct=ct_arg))
                        perf_acc.note_capture_seconds(
                            _time.perf_counter() - t_cap0)
                    ch.put_nid(nid2)
                    hist = h_c if hist is None else hist + h_c
                sel, can, tables = _binned_split_level(
                    (hist[0], hist[1], hist[2]), find_cfg, col_mask, cfg)
                recs.append(_level_record(sel, can, cfg))
        # ONE counted pytree fetch per WINDOW (transfer-seam contract):
        # every level's split records batched into a single host sync
        # at the L-level boundary
        lvl_h = telemetry.device_get(recs, pipeline="train")
        for j, r in enumerate(lvl_h):
            N = 2 ** (d + j)
            idx = (N - 1) + np.arange(N)
            feat[idx] = np.asarray(r["feat"])
            sbin_arr[idx] = np.asarray(r["bin"])
            na_left[idx] = np.asarray(r["nal"])
            is_split[idx] = np.asarray(r["can"])
            value[idx] = np.asarray(r["val"])
            gain_arr[idx] = np.asarray(r["gain"])
            node_w[idx] = np.asarray(r["w"])
        d += Lw

    # deepest level, two passes matching the dense binned tail: (A)
    # route each chunk and accumulate EXACT per-leaf (g,h,w) segment
    # totals; (B) apply leaf values — pass B reads the stored nids and
    # never touches X, so per-tree X traffic is unchanged (D level
    # passes + one route pass)
    ND = 2 ** D
    baseD = ND - 1
    tot = None
    for ch in chunks.level_pass():
        rm_arg = None if trans else ch.X
        ct_arg = ch.X if trans else None
        nid2 = binned_route_only(rm_arg, ch.nid, tables, ND // 2, baseD,
                                 W, ct=ct_arg)
        ch.put_nid(nid2)
        ghw = ch.ghw(dist)
        localD = nid2 - baseD
        inD = (localD >= 0) & (localD < ND)
        lidD = jnp.clip(localD, 0, ND - 1)
        t3 = _segment_totals(lidD, inD, ghw[0], ghw[1], ghw[2], ND)
        tot = t3 if tot is None else tuple(a + b for a, b in zip(tot, t3))
    vD_h, wD = (np.asarray(v) for v in telemetry.device_get(
        (_leaf_value(tot[0], tot[1], cfg), tot[2]), pipeline="train"))
    value[baseD:] = vD_h
    node_w[baseD:] = wD
    # unbin ONCE at tree end: bin-space splits -> raw thresholds, the
    # same conversion the dense finalize applies (left <=> code < t
    # <=> raw < edges[t-1]; past-the-edges bins -> +inf)
    thr_arr = bins_to_thresholds_stacked(sbin_arr[None, :], feat[None, :],
                                         edges)[0]
    tree = {"feat": feat, "thr": thr_arr, "na_left": na_left,
            "is_split": is_split, "value": value, "gain": gain_arr,
            "node_w": node_w}
    value_dev = jnp.asarray(value)
    lr_t = jnp.float32(lr)
    for ch in chunks.level_pass(need_x=False):
        ch.apply_leaf(lr_t, value_dev, ch.nid)
    return tree
