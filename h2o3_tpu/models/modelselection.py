"""ModelSelection — best-subset GLM search (maxr / forward / backward).

Reference: hex/modelselection/ModelSelection.java:24 — modes maxr,
maxrsweep, forward, backward over GLM; reports the best predictor subset
per model size with R²/deviance, using sweep operators on the Gram.

TPU re-design: every candidate fit is one MXU Gram + Cholesky solve
(gaussian: exact in one IRLS step), so greedy search over subsets is a
sequence of cheap device solves on a SHARED design — the data is
expanded and standardized once per refit by the GLM path. maxrsweep
collapses into maxr (same result, the sweep is an implementation detail
of the JVM)."""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.jobs import Job
from h2o3_tpu.models.glm import GLM_DEFAULTS, H2OGeneralizedLinearEstimator
from h2o3_tpu.models.model_base import Model, ModelBuilder
from h2o3_tpu.persist import (model_from_meta, model_to_meta,
                              register_model_class)

MS_DEFAULTS: Dict = dict(
    mode="maxr", max_predictor_number=1, min_predictor_number=1,
)


class ModelSelectionModel(Model):
    algo = "modelselection"

    def __init__(self, key, params, spec, best_model, results):
        super().__init__(key, params, spec)
        self.best_model = best_model
        self.results = results          # per-size rows

    def predict(self, frame):
        return self.best_model.predict(frame)

    def _predict_matrix(self, X, offset=None):
        return self.best_model._predict_matrix(X, offset=offset)

    def result(self):
        return self.results

    def coef(self):
        return self.best_model.coef()

    def _save_arrays(self):
        return {f"inner__{k}": v
                for k, v in self.best_model._save_arrays().items()}

    def _save_extra_meta(self):
        return {"inner_meta": model_to_meta(self.best_model),
                "results": self.results}

    @classmethod
    def _restore(cls, meta, arrays):
        m = cls._restore_base(meta)
        ex = meta["extra"]
        inner_arrays = {k[len("inner__"):]: v for k, v in arrays.items()
                        if k.startswith("inner__")}
        m.best_model = model_from_meta(ex["inner_meta"], inner_arrays)
        m.results = ex["results"]
        return m


class H2OModelSelectionEstimator(ModelBuilder):
    algo = "modelselection"

    def __init__(self, **params):
        merged = dict(GLM_DEFAULTS)
        merged.update(MS_DEFAULTS)
        merged.update(params)
        for alias in ("lambda_", "lambda"):
            if alias in merged:
                merged["Lambda"] = merged.pop(alias)
        super().__init__(**merged)

    def _fit(self, cols: List[str], y, frame) -> Model:
        p = {k: v for k, v in self.params.items() if k not in MS_DEFAULTS}
        p.setdefault("Lambda", [0.0])
        est = H2OGeneralizedLinearEstimator(**p)
        est.train(x=cols, y=y, training_frame=frame)
        return est.model

    @staticmethod
    def _crit(model: Model) -> float:
        """Selection criterion: residual deviance (lower = better) —
        equals (1-R²)·TSS for gaussian, matches the reference's R² order."""
        return model.residual_deviance

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        p = self.params
        y = y or p.get("response_column")
        if training_frame is None or y is None:
            raise ValueError("ModelSelection needs training_frame and y")
        special = {y, p.get("weights_column"), p.get("offset_column")}
        preds = list(x) if x else [n for n in training_frame.names
                                   if n not in special]
        mode = (p.get("mode") or "maxr").lower()
        max_k = min(int(p.get("max_predictor_number", 1)), len(preds))
        min_k = max(1, int(p.get("min_predictor_number", 1)))
        job = Job("modelselection", work=float(max_k))

        def body(job):
            results = []
            fitted: Dict[Tuple[str, ...], Model] = {}

            def fit(cols: List[str]) -> Model:
                key = tuple(sorted(cols))
                if key not in fitted:
                    fitted[key] = self._fit(list(key), y, training_frame)
                return fitted[key]

            if mode in ("maxr", "maxrsweep", "forward"):
                chosen: List[str] = []
                for k in range(1, max_k + 1):
                    # greedy add
                    cands = [c for c in preds if c not in chosen]
                    scored = [(self._crit(fit(chosen + [c])), c)
                              for c in cands]
                    _, addc = min(scored)
                    chosen = chosen + [addc]
                    if mode in ("maxr", "maxrsweep") and len(chosen) > 1:
                        # replacement sweeps: apply the BEST single swap,
                        # restart the scan, stop when none improves (the
                        # candidate lists must rebuild after every accepted
                        # swap or trials drift to a different subset size)
                        for _ in range(10):
                            best_c = self._crit(fit(chosen))
                            best_swap = None
                            for out_c in chosen:
                                for in_c in (c for c in preds
                                             if c not in chosen):
                                    trial = [c for c in chosen
                                             if c != out_c] + [in_c]
                                    cr = self._crit(fit(trial))
                                    if cr < best_c - 1e-10:
                                        best_c = cr
                                        best_swap = trial
                            if best_swap is None:
                                break
                            chosen = best_swap
                    m = fit(chosen)
                    results.append(self._row(k, chosen, m))
                    job.update(1.0)
            elif mode == "backward":
                chosen = list(preds)
                m = fit(chosen)
                results.append(self._row(len(chosen), chosen, m))
                while len(chosen) > min_k:
                    scored = [(self._crit(fit([c for c in chosen
                                               if c != drop])), drop)
                              for drop in chosen]
                    _, dropc = min(scored)
                    chosen = [c for c in chosen if c != dropc]
                    m = fit(chosen)
                    results.append(self._row(len(chosen), chosen, m))
                    job.update(1.0)
                results.reverse()
            else:
                raise ValueError(f"unsupported mode '{mode}'")
            best = min(results, key=lambda r: r["deviance"])
            best_model = fitted[tuple(sorted(best["predictors"]))]
            model = ModelSelectionModel(
                f"ms_{id(self) & 0xffffff:x}", self.params,
                _spec_of(best_model), best_model, results)
            model.training_metrics = best_model.training_metrics
            model.output["results"] = results
            model.output["best_predictors"] = best["predictors"]
            return model

        job.run(body)
        self.model = job.join()
        self.job = job
        from h2o3_tpu import dkv
        dkv.put(self.model.key, "model", self.model)
        return self

    @staticmethod
    def _row(k: int, chosen: List[str], m: Model) -> Dict:
        r2 = getattr(m.training_metrics, "r2", None)
        return {"size": k, "predictors": list(chosen),
                "deviance": m.residual_deviance,
                "r2": r2, "coefficients": m.coef()}

    def _train_impl(self, spec, valid_spec, job: Job):
        raise RuntimeError("ModelSelection overrides train() directly")


def _spec_of(model: Model):
    class _S:
        names = model.feature_names
        is_cat = model.feature_is_cat
        cat_domains = model.cat_domains
        response = model.response
        response_domain = model.response_domain
        nclasses = model.nclasses
    return _S()


register_model_class("modelselection", ModelSelectionModel)
